"""Deterministic fault injection: named sites threaded through the hot paths.

A `FaultPlan` is a set of `(site, attempt, error-class)` triples: the k-th
arrival at site s raises an error of class e. Plans are either explicit or
seeded (`FaultPlan.seeded(seed)` derives the triples from a PRNG), and every
firing is recorded on `plan.trace`, so a replay with the same plan produces
the identical trace bit-for-bit — the property the fault-smoke CI asserts.

Sites (SITES) cover each stage a scheduling run can die in:

  live_get        one HTTP GET against the kube-apiserver (simulator/live.py)
  encode          pod-batch encoding into device tables (engine.encode_batch_raw)
  to_device       host->device table/carry transfer (engine._to_device)
  dispatch        one compiled kernel dispatch (engine/probe segment loops)
  fetch           device->host result fetch (the np.asarray sync points)
  commit          one pod commit onto host cluster state (engine._commit_pod)
  preempt_evict   preemption eviction (preemption.evict)

simonguard containment sites (resilience/guard.py) — these do not model a
crash but a CONTAINED device failure, so the run is expected to degrade and
converge, not die:

  watchdog_wedge  a supervised dispatch's watchdog expiry (guard.supervised
                  converts the injection into the quarantine + BackendWedged
                  path without blocking a thread)
  oom_to_device   device OOM during the host->device transfer (classified
                  like jaxlib RESOURCE_EXHAUSTED; engine bisects the batch)
  oom_dispatch    device OOM during a kernel dispatch (same containment)
  journal_write   a capacity-search journal append (fires BEFORE the write,
                  so the journal's valid prefix survives — the crash-resume
                  smoke's injection point)

simonha crash-consistent-serving sites (serve/ha.py) — the ingest WAL,
checkpoint, and degraded-mode paths; like journal_write they fire BEFORE
the durable write so the on-disk valid prefix survives the failure:

  wal_write       an ingest WAL record append (before the write syscall)
  wal_fsync       the fsync sealing an appended WAL record (the record is
                  written but not yet durable — the torn-tail window)
  checkpoint_write  a compaction checkpoint write (the previous checkpoint
                  stays valid: writes go tmp-file + atomic rename)
  ingest_stall    the ingest admission edge (models an apiserver/watch
                  stall: serving flips to bounded-staleness degraded mode)

simonsync watch-sync sites (live/sync.py) — the resumable watch loop and
its relist-reconciliation recovery path; injections here must leave the
resident image convergent (the chaos gate replays the same seeded plan
twice and asserts identical traces AND identical final images):

  watch_read      one chunked-watch line read (a dropped connection mid
                  stream: the sync reconnects from its bookmark)
  watch_parse     decoding one watch line (malformed JSON from the server;
                  classified ProtocolError, the stream is torn down)
  watch_gone      the server compacting away the client's resourceVersion
                  (410 Gone: forces the relist-reconciliation path)
  relist          the recovery list() call itself (relist must be retried
                  with the same seeded backoff as the watch)

Activation is process-global (`install_plan` / `clear_plan`): tests use the
context manager form, the CLI wires `simon apply --fault-plan`, and the
server exposes POST /debug/fault-plan. The no-plan fast path is a single
global None check, so production hot paths pay nothing.
"""

from __future__ import annotations

import json
import os
import random
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from ..obs import instruments as obs

SITES: Tuple[str, ...] = (
    "live_get", "encode", "to_device", "dispatch", "fetch", "commit",
    "preempt_evict",
    # simonguard containment sites (resilience/guard.py)
    "watchdog_wedge", "oom_to_device", "oom_dispatch", "journal_write",
    # simonha crash-consistent-serving sites (serve/ha.py)
    "wal_write", "wal_fsync", "checkpoint_write", "ingest_stall",
    # simonsync watch-sync sites (live/sync.py)
    "watch_read", "watch_parse", "watch_gone", "relist",
)

ERROR_CLASSES: Tuple[str, ...] = ("runtime", "transient", "auth", "protocol")


class FaultInjected(RuntimeError):
    """An injected failure with no HTTP analog (engine/device sites)."""

    def __init__(self, site: str, attempt: int) -> None:
        super().__init__(f"injected fault at {site} (attempt {attempt})")
        self.site = site
        self.attempt = attempt
        self.injected = True


def _raise_for(site: str, attempt: int, error: str) -> None:
    if error == "runtime":
        raise FaultInjected(site, attempt)
    # HTTP-shaped classes come from the live client's typed hierarchy so the
    # retry policy discriminates injected faults exactly like real ones.
    # Imported lazily: live.py itself calls into this module.
    from ..simulator.live import AuthError, ProtocolError, TransientError

    cls = {"transient": TransientError, "auth": AuthError,
           "protocol": ProtocolError}[error]
    e = cls(f"injected {error} fault at {site} (attempt {attempt})")
    e.injected = True
    raise e


@dataclass(frozen=True)
class FaultSpec:
    """Fail the `attempt`-th arrival (1-based) at `site` with `error`."""

    site: str
    attempt: int = 1
    error: str = "runtime"

    def __post_init__(self) -> None:
        if self.site not in SITES:
            raise ValueError(f"unknown fault site {self.site!r}; sites: {SITES}")
        if self.attempt < 1:
            raise ValueError(f"attempt is 1-based, got {self.attempt}")
        if self.error not in ERROR_CLASSES:
            raise ValueError(
                f"unknown error class {self.error!r}; classes: {ERROR_CLASSES}")


class FaultPlan:
    """A deterministic set of FaultSpecs plus per-site arrival counters."""

    def __init__(self, specs: Sequence[FaultSpec], seed: Optional[int] = None) -> None:
        self.specs = tuple(specs)
        self.seed = seed
        self._by_site: Dict[str, Dict[int, str]] = {}
        for s in self.specs:
            self._by_site.setdefault(s.site, {})[s.attempt] = s.error
        self._lock = threading.Lock()
        self.arrivals: Dict[str, int] = {}
        self.trace: List[Tuple[str, int, str]] = []  # fired (site, attempt, error)

    # ----------------------------------------------------------- construct ----

    @classmethod
    def seeded(cls, seed: int, n_faults: int = 1,
               sites: Sequence[str] = SITES, max_attempt: int = 3,
               error_classes: Sequence[str] = ("runtime",)) -> "FaultPlan":
        """Derive `n_faults` specs from a PRNG — the fault-soak generator.
        Pure function of its arguments: seeded(s) twice is the same plan."""
        rng = random.Random(seed)
        specs = []
        seen = set()
        for _ in range(n_faults):
            for _ in range(64):  # resample collisions, bounded
                s = FaultSpec(rng.choice(list(sites)),
                              rng.randint(1, max_attempt),
                              rng.choice(list(error_classes)))
                if (s.site, s.attempt) not in seen:
                    seen.add((s.site, s.attempt))
                    specs.append(s)
                    break
        return cls(specs, seed=seed)

    @classmethod
    def parse(cls, text: str) -> "FaultPlan":
        """CLI/server plan syntax. Accepts, in order of trial:
        a JSON file path; an inline JSON object ({"seed": ..} or
        {"faults": [{"site": ..., "attempt": ..., "error": ...}]});
        `seed=N`; or `;`-separated clauses `site=S,attempt=K,error=E`."""
        text = text.strip()
        if os.path.exists(text):
            with open(text) as f:
                return cls.from_json(json.load(f))
        if text.startswith("{"):
            return cls.from_json(json.loads(text))
        if text.startswith("seed="):
            return cls.seeded(int(text[len("seed="):]))
        specs = []
        for clause in text.split(";"):
            clause = clause.strip()
            if not clause:
                continue
            kv = {}
            for part in clause.split(","):
                k, _, v = part.partition("=")
                kv[k.strip()] = v.strip()
            unknown = set(kv) - {"site", "attempt", "error"}
            if unknown or "site" not in kv:
                raise ValueError(f"bad fault clause {clause!r} "
                                 f"(want site=S[,attempt=K][,error=E])")
            specs.append(FaultSpec(kv["site"], int(kv.get("attempt", 1)),
                                   kv.get("error", "runtime")))
        if not specs:
            raise ValueError(f"empty fault plan spec {text!r}")
        return cls(specs)

    @classmethod
    def from_json(cls, doc: dict) -> "FaultPlan":
        if not isinstance(doc, dict):
            raise ValueError("fault plan JSON must be an object")
        if "seed" in doc and not doc.get("faults"):
            return cls.seeded(int(doc["seed"]),
                              n_faults=int(doc.get("n_faults", 1)))
        specs = [FaultSpec(f["site"], int(f.get("attempt", 1)),
                           f.get("error", "runtime"))
                 for f in doc.get("faults") or []]
        if not specs:
            raise ValueError("fault plan JSON names no faults")
        return cls(specs, seed=doc.get("seed"))

    def to_json(self) -> dict:
        # snapshot under the lock: a concurrent on_arrival mutating
        # `arrivals` mid-dict() would raise or yield a torn count set
        with self._lock:
            return {
                "seed": self.seed,
                "faults": [{"site": s.site, "attempt": s.attempt,
                            "error": s.error} for s in self.specs],
                "arrivals": dict(self.arrivals),
                "trace": [list(t) for t in self.trace],
            }

    # -------------------------------------------------------------- firing ----

    def on_arrival(self, site: str) -> None:
        """Count one arrival at `site`; raise when a spec names it."""
        with self._lock:
            n = self.arrivals.get(site, 0) + 1
            self.arrivals[site] = n
            error = self._by_site.get(site, {}).get(n)
            if error is not None:
                self.trace.append((site, n, error))
        if error is not None:
            obs.FAULTS_INJECTED.labels(site=site).inc()
            _raise_for(site, n, error)

    def on_arrivals(self, site: str, count: int) -> None:
        """Count `count` arrivals at once (the engine's bulk commit: one call
        covers a whole segment's commits). Replay-equal to `count` serial
        on_arrival calls: the counter advances by `count`, and the FIRST spec
        whose attempt lands inside the advanced window fires — exactly the
        arrival the per-event loop would have died on."""
        if count <= 0:
            return
        with self._lock:
            base = self.arrivals.get(site, 0)
            fired = None
            by_attempt = self._by_site.get(site)
            if by_attempt:
                for a in sorted(by_attempt):
                    if base < a <= base + count:
                        fired = (a, by_attempt[a])
                        break
            # the serial loop dies AT the firing arrival — the remaining
            # count-a events never happen, so the counter must stop there
            # too or a failover replay's window would skip later specs
            self.arrivals[site] = fired[0] if fired else base + count
            if fired is not None:
                self.trace.append((site, fired[0], fired[1]))
        if fired is not None:
            obs.FAULTS_INJECTED.labels(site=site).inc()
            _raise_for(site, fired[0], fired[1])


# ---------------------------------------------------------------- activation ---

_PLAN: Optional[FaultPlan] = None
_PLAN_LOCK = threading.Lock()


def install_plan(plan: FaultPlan) -> FaultPlan:
    """Activate `plan` process-wide (replacing any previous one)."""
    global _PLAN
    with _PLAN_LOCK:
        _PLAN = plan
    return plan


def clear_plan() -> None:
    global _PLAN
    with _PLAN_LOCK:
        _PLAN = None


def active_plan() -> Optional[FaultPlan]:
    # simonlint: ignore[race-unguarded-attr] -- reference read is GIL-atomic;
    # install/clear happen-before worker start/join in every harness, so a
    # stale None only skips an already-cleared plan
    return _PLAN


class installed:
    """Context-manager activation for tests: `with installed(plan): ...`."""

    def __init__(self, plan: FaultPlan) -> None:
        self.plan = plan

    def __enter__(self) -> FaultPlan:
        install_plan(self.plan)
        return self.plan

    def __exit__(self, *exc) -> None:
        clear_plan()


def maybe_fail(site: str) -> None:
    """The per-site hook the hot paths call. Free when no plan is active."""
    # simonlint: ignore[race-unguarded-attr] -- GIL-atomic reference read on
    # the hot path; plan installation happens-before the run it targets
    plan = _PLAN
    if plan is not None:
        plan.on_arrival(site)


def maybe_fail_bulk(site: str, count: int) -> None:
    """`count` arrivals in one call (bulk commit); free when no plan is
    active, replay-equal to `count` maybe_fail calls otherwise."""
    # simonlint: ignore[race-unguarded-attr] -- GIL-atomic reference read on
    # the hot path; plan installation happens-before the run it targets
    plan = _PLAN
    if plan is not None:
        plan.on_arrivals(site, count)
