"""Retry, deadline, and circuit-breaker policies (host-side, jax-free).

Design constraints, in order:

- **Deterministic.** Backoff jitter comes from a seeded PRNG so a retry
  trace replays bit-for-bit: `RetryPolicy(seed=s).schedule()` is a pure
  function of the policy parameters. Fail-fast/crash-only style — a policy
  either succeeds within its bounds or raises; nothing retries forever.
- **Composable budgets.** `Deadline` is a contextvar-propagated ABSOLUTE
  deadline: entering a nested `Deadline` can only tighten the budget, and
  every callee (live GETs, capacity-search rounds) slices the remainder
  instead of owning a private timeout.
- **Instrumented.** Every retry, deadline expiry, and breaker transition
  moves a counter/gauge in obs/instruments.py, so the PR-3 metrics surface
  can verify failure behavior end to end.
"""

from __future__ import annotations

import contextvars
import random
import threading
import time
from typing import Callable, List, Optional

from ..obs import instruments as obs


class DeadlineExceeded(Exception):
    """A contextvar deadline ran out before the work finished."""


class BreakerOpen(Exception):
    """A CircuitBreaker is open: the protected dependency is presumed down."""


# ---------------------------------------------------------------- deadlines ----

# Absolute time.monotonic() deadline of the current context, or None (no
# budget). Contextvars propagate per server-handler thread and asyncio task.
_DEADLINE: contextvars.ContextVar[Optional[float]] = contextvars.ContextVar(
    "open_simulator_tpu_deadline", default=None)


class Deadline:
    """A wall-clock budget for everything under this context manager.

    Nested deadlines only tighten: `with Deadline(60): with Deadline(5): ...`
    gives the inner block min(5s, whatever remains of the 60s). Callees read
    the remainder via `deadline_remaining()` / `check_deadline(site)` and
    slice it into their own timeouts.
    """

    def __init__(self, seconds: float, clock: Callable[[], float] = time.monotonic) -> None:
        if seconds <= 0:
            raise ValueError(f"deadline must be positive, got {seconds}")
        self.seconds = float(seconds)
        self._clock = clock
        self._token: Optional[contextvars.Token] = None

    def __enter__(self) -> "Deadline":
        mine = self._clock() + self.seconds
        outer = _DEADLINE.get()
        self._token = _DEADLINE.set(mine if outer is None else min(mine, outer))
        return self

    def __exit__(self, *exc) -> None:
        if self._token is not None:
            _DEADLINE.reset(self._token)
            self._token = None

    def remaining(self) -> Optional[float]:
        return deadline_remaining(self._clock)


def deadline_remaining(clock: Callable[[], float] = time.monotonic) -> Optional[float]:
    """Seconds left on the current context's deadline, or None (unbounded).
    Can be negative once expired — callers usually want check_deadline."""
    at = _DEADLINE.get()
    return None if at is None else at - clock()


def check_deadline(site: str, clock: Callable[[], float] = time.monotonic) -> None:
    """Raise DeadlineExceeded (and count it against `site`) when the current
    context's budget is spent. No-op without an active deadline."""
    rem = deadline_remaining(clock)
    if rem is not None and rem <= 0:
        obs.DEADLINE_EXCEEDED.labels(site=site).inc()
        raise DeadlineExceeded(f"deadline exceeded at {site} ({-rem:.3f}s over)")


# ------------------------------------------------------------------ retries ----


class RetryPolicy:
    """Exponential backoff with deterministic seeded jitter and hard bounds.

    The attempt-k sleep is `min(cap, base * mult**k) * (1 + jitter * u_k)`
    where u_k ∈ [0, 1) comes from `random.Random(seed)` — the whole schedule
    is a pure function of the constructor arguments, so a failure trace
    replays identically (the fault-injection acceptance criterion). Bounds:
    at most `max_attempts` calls AND at most `max_elapsed` seconds of
    cumulative sleep; whichever trips first re-raises the last error.
    """

    def __init__(self, max_attempts: int = 4, base: float = 0.1,
                 mult: float = 2.0, cap: float = 5.0, jitter: float = 0.2,
                 max_elapsed: float = 30.0, seed: int = 0) -> None:
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = int(max_attempts)
        self.base = float(base)
        self.mult = float(mult)
        self.cap = float(cap)
        self.jitter = float(jitter)
        self.max_elapsed = float(max_elapsed)
        self.seed = int(seed)

    def schedule(self) -> List[float]:
        """The sleeps between attempts (len == max_attempts - 1),
        deterministic for a given policy."""
        rng = random.Random(self.seed)
        out: List[float] = []
        for k in range(self.max_attempts - 1):
            d = min(self.cap, self.base * self.mult ** k)
            out.append(d * (1.0 + self.jitter * rng.random()))
        return out

    def call(self, fn: Callable[[], object], *, site: str,
             retryable: Callable[[BaseException], bool],
             breaker: Optional["CircuitBreaker"] = None,
             sleep: Callable[[float], None] = time.sleep,
             clock: Callable[[], float] = time.monotonic):
        """Run `fn` under this policy. Retries only errors `retryable` says
        yes to; honors an error's `retry_after` attribute (Retry-After) as a
        sleep floor; slices the contextvar deadline (never sleeps past it);
        feeds the breaker, when given, with every outcome."""
        delays = self.schedule()
        t0 = clock()
        attempt = 0
        while True:
            check_deadline(site, clock)
            if breaker is not None:
                breaker.before_call()
            try:
                result = fn()
            except BaseException as e:
                is_retryable = not isinstance(e, BreakerOpen) and retryable(e)
                if breaker is not None:
                    # only dependency-level (retryable) failures feed the
                    # breaker: a 401 means the DEPENDENCY is alive — opening
                    # on it would mask the actionable auth error
                    if is_retryable:
                        breaker.record_failure()
                    elif not isinstance(e, BreakerOpen):
                        breaker.record_success()
                if not is_retryable or attempt >= len(delays):
                    raise
                delay = max(delays[attempt],
                            float(getattr(e, "retry_after", 0.0) or 0.0))
                if clock() - t0 + delay > self.max_elapsed:
                    raise
                rem = deadline_remaining(clock)
                if rem is not None and delay >= rem:
                    obs.DEADLINE_EXCEEDED.labels(site=site).inc()
                    raise DeadlineExceeded(
                        f"deadline at {site} leaves {rem:.3f}s, "
                        f"next retry needs {delay:.3f}s") from e
                obs.RETRIES.labels(site=site).inc()
                attempt += 1
                sleep(delay)
            else:
                if breaker is not None:
                    breaker.record_success()
                return result


# ---------------------------------------------------------- circuit breaker ----

# Gauge encoding (PARITY.md "Failure handling"): matches the conventional
# three-state numeric export so dashboards can alert on state != 0.
_CLOSED, _HALF_OPEN, _OPEN = 0, 1, 2
_STATE_NAMES = {_CLOSED: "closed", _HALF_OPEN: "half_open", _OPEN: "open"}


class CircuitBreaker:
    """Classic three-state breaker for a flaky dependency (the live-cluster
    apiserver): `failure_threshold` consecutive failures open it; after
    `reset_after` seconds one probe call is let through (half-open); a probe
    success closes it, a probe failure re-opens. Thread-safe (the server's
    handler threads share one client)."""

    def __init__(self, name: str, failure_threshold: int = 5,
                 reset_after: float = 30.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.name = name
        self.failure_threshold = int(failure_threshold)
        self.reset_after = float(reset_after)
        self._clock = clock
        self._lock = threading.Lock()
        self._failures = 0
        self._state = _CLOSED
        self._opened_at = 0.0
        self._probing = False
        self._set_gauge_locked()  # construction: not yet published, no contention

    def _set_gauge_locked(self) -> None:
        obs.BREAKER_STATE.labels(name=self.name).set(self._state)

    @property
    def state(self) -> str:
        with self._lock:
            return _STATE_NAMES[self._state]

    def before_call(self) -> None:
        """Gate a call: raises BreakerOpen while open; lets exactly one
        probe through once `reset_after` has elapsed (half-open)."""
        with self._lock:
            if self._state == _CLOSED:
                return
            if self._state == _OPEN:
                if self._clock() - self._opened_at < self.reset_after:
                    raise BreakerOpen(
                        f"circuit {self.name!r} open "
                        f"({self._failures} consecutive failures)")
                self._state = _HALF_OPEN
                self._probing = False
                self._set_gauge_locked()
            # half-open: admit one probe at a time
            if self._probing:
                raise BreakerOpen(f"circuit {self.name!r} half-open, probe in flight")
            self._probing = True

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probing = False
            if self._state != _CLOSED:
                self._state = _CLOSED
                self._set_gauge_locked()

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            self._probing = False
            if (self._state == _HALF_OPEN
                    or self._failures >= self.failure_threshold):
                self._state = _OPEN
                self._opened_at = self._clock()
                self._set_gauge_locked()
