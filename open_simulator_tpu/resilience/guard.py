"""simonguard: mid-run device-failure containment.

PR 4 (simonfault) made host state crash-consistent: any failure rolls a
scheduling call back to its pre-call state. This module is the layer ABOVE
that transactional core — it decides what happens NEXT, so a wedged
accelerator or a device OOM degrades the run instead of killing it:

- **Watchdog-supervised dispatch** (`supervised`): every device computation
  (kernel dispatch, result fetch, probe fan-out round) runs in a worker
  thread under a deadline scaled by batch size and tightened by the
  contextvar `Deadline` (resilience/policy.py). On expiry the backend is
  classified *wedged*, quarantined for the process (a REAL expiry — never an
  injected one — may later clear its name through one bounded subprocess
  re-probe per `OPEN_SIMULATOR_QUARANTINE_REPROBE_S` window, so a slow
  compile outlier doesn't degrade the process forever), and
  `BackendWedged` is raised — which the engine's failover loop catches. The
  blocked worker thread is a daemon and is abandoned (a dispatch stuck in a
  driver ioctl cannot be interrupted from Python); the quarantine is exactly
  what prevents a second thread from following it.
- **OOM classification** (`oom_site` / `containment_cause`): jaxlib
  RESOURCE_EXHAUSTED errors (and the injected `oom_to_device` /
  `oom_dispatch` faults that stand in for them in tests) are recognized so
  the engine can retry by bisecting the pod batch instead of dying.
- **Quarantine registry**: process-global backend → cause map. Once a
  backend is quarantined every later Simulator in the process starts
  directly on the CPU fallback (`fallback_scope`), so one wedge costs one
  watchdog expiry, not one per run.
- **Crash-consistent capacity-search journal** (`SearchJournal`): fsync'd
  JSONL of probe verdicts with an options-digest header, so a SIGKILLed
  capacity search resumed via `simon apply --resume-journal` skips every
  completed probe — and a journal written by a DIFFERENT search is rejected
  (`JournalMismatch`) instead of silently corrupting the answer.

Every decision is observable: `simon_guard_watchdog_expiries_total{site}`,
`simon_guard_oom_bisections_total{site}`, `simon_guard_failovers_total{cause}`,
`simon_guard_quarantined{backend}`, `simon_journal_*` (obs/instruments.py),
the `events()` trace (replay-equal across identical seeded runs — the
fault-smoke CI criterion), `state()` on the server's /debug/vars, and the
result's `backend_path` (e.g. ``["tpu", "cpu"]``). Nothing fails over
silently.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple, TypeVar

from ..obs import instruments as obs
from ..obs import pulse
from . import faults
from .policy import check_deadline, deadline_remaining

T = TypeVar("T")

# Failover cause labels (simon_guard_failovers_total{cause}).
CAUSE_WEDGE = "watchdog_wedge"
CAUSE_OOM_EXHAUSTED = "oom_exhausted"
CAUSE_OOM = "oom"


class GuardError(RuntimeError):
    """Base of the containable device-failure classifications."""


class BackendWedged(GuardError):
    """A supervised device computation blew its watchdog deadline: the
    backend is presumed hung (tunnel wedge, driver deadlock) and has been
    quarantined for the process."""

    def __init__(self, site: str, backend: str, injected: bool = False) -> None:
        super().__init__(
            f"backend {backend!r} wedged at {site} "
            f"({'injected' if injected else 'watchdog deadline expired'}); "
            f"quarantined for this process")
        self.site = site
        self.backend = backend
        self.injected = injected


class OOMBisectionExhausted(GuardError):
    """Device OOM persisted all the way down to the bisection floor: the
    batch cannot be made to fit by splitting. The engine fails the run over
    to the CPU backend; if THAT also exhausts, the error propagates."""

    def __init__(self, site: str, batch: int, floor: int) -> None:
        super().__init__(
            f"device OOM at {site} persisted at batch size {batch} "
            f"(bisection floor {floor}); batch cannot be split further")
        self.site = site
        self.batch = batch
        self.floor = floor


class JournalMismatch(ValueError):
    """A --resume-journal file was written by a different search (options
    digest mismatch) or is not a capacity-search journal at all."""


# ------------------------------------------------------------------ knobs -----


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:  # tuning knob: fall back, don't crash the run
        return default


def watchdog_enabled() -> bool:
    return os.environ.get("OPEN_SIMULATOR_WATCHDOG", "").lower() not in (
        "0", "off", "false", "no")


def watchdog_budget(pods: int) -> float:
    """Seconds a supervised computation may take before it is declared
    wedged: a base generous enough for a cold XLA compile plus a per-pod
    term so giant batches are never misclassified. Env-tunable."""
    base = _env_float("OPEN_SIMULATOR_WATCHDOG_BASE_S", 120.0)
    per_pod = _env_float("OPEN_SIMULATOR_WATCHDOG_PER_POD_S", 0.005)
    return max(1.0, base + per_pod * max(0, int(pods)))


def oom_bisect_floor() -> int:
    """Smallest pod-batch size the OOM bisection will retry at (>= 1)."""
    try:
        return max(1, int(os.environ.get("OPEN_SIMULATOR_OOM_BISECT_FLOOR",
                                         "1")))
    except ValueError:
        return 1


# ----------------------------------------------------------- event trace ------

# Guard decisions in firing order: ("wedge", site, backend),
# ("oom_bisect", site, batch), ("failover", cause, where). Bounded; the
# fault-smoke CI resets it per run and asserts two identical seeded runs
# produce identical traces (the replay-equality criterion for the new sites).
_EVENTS: List[Tuple] = []
_EVENTS_MAX = 1024
_STATE_LOCK = threading.Lock()


def record_event(*event) -> None:
    with _STATE_LOCK:
        if len(_EVENTS) < _EVENTS_MAX:
            _EVENTS.append(tuple(event))


def events() -> List[Tuple]:
    with _STATE_LOCK:
        return list(_EVENTS)


# ------------------------------------------------------------- quarantine -----

_QUARANTINED: Dict[str, str] = {}  # backend platform -> cause
# real (watchdog-observed, non-injected) wedges only: when the entry was
# created and when the next bounded re-probe may run. Injected wedges carry
# no meta and never re-probe — fault-smoke determinism.
_QUARANTINE_META: Dict[str, dict] = {}
# backends whose quarantine was lifted by a re-probe once already: a SECOND
# real wedge proves the subprocess probe cannot see this process's wedged
# state (the abandoned worker thread holds in-process locks a fresh python
# never touches), so the re-quarantine is permanent — the lift/burn cycle is
# bounded at one, not one per window.
_LIFTED: set = set()


def quarantine_reprobe_s() -> float:
    """Seconds after which a REAL (non-injected) wedge quarantine becomes
    eligible for one bounded subprocess re-probe per window
    (OPEN_SIMULATOR_QUARANTINE_REPROBE_S; 0 makes quarantines permanent).
    A slow-but-healthy outlier — a cold XLA compile past the watchdog
    budget — must not pin every later Simulator in the process to the CPU
    fallback forever; a probe that finds the backend responsive lifts the
    quarantine."""
    return _env_float("OPEN_SIMULATOR_QUARANTINE_REPROBE_S", 600.0)


def quarantine(backend: str, cause: str, *, reprobe: bool = False) -> None:
    """Quarantine `backend`. `reprobe=True` (real watchdog expiries only —
    never injected faults) marks the entry eligible for the bounded
    re-probe/expiry path in `default_quarantined`, unless a previous lift
    already failed to stick (see _LIFTED)."""
    with _STATE_LOCK:
        if backend not in _QUARANTINED:
            _QUARANTINED[backend] = cause
            if reprobe and backend not in _LIFTED:
                # monotonic like policy.py's Deadline: the window is an
                # interval, and a wall-clock step must not stretch or
                # collapse it
                _QUARANTINE_META[backend] = {"ts": time.monotonic(),
                                             "next_probe": 0.0}
    obs.GUARD_QUARANTINED.labels(backend=backend).set(1)


def quarantined() -> Dict[str, str]:
    with _STATE_LOCK:
        return dict(_QUARANTINED)


def _unquarantine(backend: str, why: str) -> None:
    with _STATE_LOCK:
        _QUARANTINED.pop(backend, None)
        _QUARANTINE_META.pop(backend, None)
        _LIFTED.add(backend)  # a second real wedge is permanent
    obs.GUARD_QUARANTINED.labels(backend=backend).set(0)
    record_event("unquarantine", backend, why)
    import logging

    logging.getLogger("open_simulator_tpu").warning(
        "backend %r responded to a re-probe; lifting its quarantine (%s)",
        backend, why)


def _maybe_lift_quarantine(backend: str) -> None:
    """Bounded re-probe of a REAL wedge quarantine: once per
    quarantine_reprobe_s window, run the existing subprocess probe
    (utils/devices.probe_default_backend — deadline-bounded, never
    in-process) in a BACKGROUND daemon thread — default_quarantined() sits
    on hot dispatch paths and under callers' Deadline budgets, so the
    state check itself must never block on a 60s probe. A responsive
    backend is un-quarantined (for later calls) so one compile outlier
    doesn't degrade the whole process permanently; a lift that fails to
    stick makes the re-quarantine permanent (_LIFTED). Injected
    quarantines (no meta) and the window==0 config never re-probe."""
    window = quarantine_reprobe_s()
    if window <= 0:
        return
    now = time.monotonic()
    with _STATE_LOCK:
        meta = _QUARANTINE_META.get(backend)
        if meta is None or now - meta["ts"] < window or now < meta["next_probe"]:
            return
        # claim this window before dropping the lock: concurrent callers
        # must not stack subprocess probes
        meta["next_probe"] = now + window
    threading.Thread(target=_reprobe_and_lift, args=(backend,),
                     name="simon-guard-reprobe", daemon=True).start()


def _reprobe_and_lift(backend: str) -> None:
    from ..utils.devices import probe_default_backend

    try:
        ok, _rec = probe_default_backend()
    except Exception:  # a failed probe just leaves the quarantine standing
        return
    if ok:
        _unquarantine(backend, "reprobe_ok")


def current_backend() -> str:
    """The default JAX backend's platform name. Safe at the points the guard
    calls it: either a dispatch already initialized the backend, or the
    process-startup probe (utils/devices.py) verified it responsive."""
    import jax

    return jax.default_backend()


def default_quarantined() -> bool:
    """True when the process's default backend is quarantined (device work
    must route to the CPU fallback). Never touches jax when nothing is
    quarantined — the common case stays import-free. A real-wedge entry past
    its re-probe window kicks off one bounded BACKGROUND subprocess probe
    here (this call never blocks on it); a responsive backend is
    un-quarantined for subsequent calls."""
    with _STATE_LOCK:
        if not _QUARANTINED:
            return False
        q = dict(_QUARANTINED)
    b = current_backend()
    if b not in q:
        return False
    _maybe_lift_quarantine(b)
    with _STATE_LOCK:
        return b in _QUARANTINED


# Carried INTO supervised worker threads via contextvars.copy_context():
# jax.default_device is thread-scoped, so the scope entered on the caller
# thread does not reach the worker — the flag does, and the worker re-enters
# the scope itself (see _call_in_scope).
_FALLBACK_SCOPE = contextvars.ContextVar("simon_guard_fallback", default=False)


def _cpu_device():
    import jax

    return jax.local_devices(backend="cpu")[0]


@contextlib.contextmanager
def fallback_scope():
    """Context manager placing all JAX work inside it on the CPU fallback
    device (the degraded-mode execution target after a wedge/OOM).

    Enters jax.default_device on the CALLING thread and raises a contextvar
    flag: JAX device/config scopes are thread-local and copy_context() does
    not carry them, so `supervised` re-establishes the scope inside its
    worker thread whenever the flag is set — otherwise a post-failover
    dispatch with uncommitted inputs would still target the quarantined
    backend and burn another watchdog timeout per attempt."""
    import jax

    token = _FALLBACK_SCOPE.set(True)
    try:
        with jax.default_device(_cpu_device()):
            yield
    finally:
        _FALLBACK_SCOPE.reset(token)


def _call_in_scope(fn: Callable[[], T]) -> T:
    """Run `fn`, re-entering the CPU fallback device scope in the CURRENT
    thread when the caller held fallback_scope() (the contextvar flag is
    copied into supervised workers; the thread-local jax scope is not)."""
    if not _FALLBACK_SCOPE.get():
        return fn()
    import jax

    with jax.default_device(_cpu_device()):
        return fn()


def reset_for_tests() -> None:
    """Clear process-global guard state (quarantine + events). Tests and the
    fault-smoke CI only — production only un-quarantines through the bounded
    re-probe path (_maybe_lift_quarantine)."""
    with _STATE_LOCK:
        for b in _QUARANTINED:
            obs.GUARD_QUARANTINED.labels(backend=b).set(0)
        _QUARANTINED.clear()
        _QUARANTINE_META.clear()
        _LIFTED.clear()
        del _EVENTS[:]


def state() -> dict:
    """The /debug/vars view of the guard: quarantine map, watchdog/bisection
    configuration, and the recent containment events."""
    return {
        "quarantined": quarantined(),
        "watchdog": {
            "enabled": watchdog_enabled(),
            "base_s": _env_float("OPEN_SIMULATOR_WATCHDOG_BASE_S", 120.0),
            "per_pod_s": _env_float("OPEN_SIMULATOR_WATCHDOG_PER_POD_S", 0.005),
        },
        "oom_bisect_floor": oom_bisect_floor(),
        "quarantine_reprobe_s": quarantine_reprobe_s(),
        "events": [list(e) for e in events()[-64:]],
    }


# ------------------------------------------------------ supervised dispatch ---


def supervised(fn: Callable[[], T], *, site: str, pods: int = 0) -> T:
    """Run one device computation under the dispatch watchdog.

    `fn` executes in a daemon worker thread (contextvars copied, so the
    Deadline and any test-installed state propagate); the caller waits at
    most `watchdog_budget(pods)` seconds, further tightened by the contextvar
    Deadline. Expiry quarantines the current backend and raises
    `BackendWedged`; if the caller's own Deadline ran out during the wait,
    `DeadlineExceeded` is raised instead (a spent budget is not a wedge).
    Exceptions from `fn` re-raise transparently. The `watchdog_wedge` fault
    site fires here, so a wedge is deterministically injectable without
    actually blocking a thread."""
    try:
        faults.maybe_fail("watchdog_wedge")
    except faults.FaultInjected as e:
        raise _declare_wedged(site, injected=True) from e
    # simonpulse ledger: the window must exist in THIS context before
    # copy_context below — the pending-list object crosses into the worker
    # by reference, so dispatch notes made inside fn (probe rounds) land in
    # the list this caller drains at commit_unit. One global read when off.
    pl = pulse.active()
    if pl is not None:
        pulse.ensure_window()
        t_pulse = time.perf_counter()
    if not watchdog_enabled():
        if pl is None:
            return fn()
        try:
            result = fn()
        except BaseException:
            pl.commit_unit(site=site, pods=pods,
                           wall_s=time.perf_counter() - t_pulse, ok=False,
                           fn=fn)
            raise
        pl.commit_unit(site=site, pods=pods,
                       wall_s=time.perf_counter() - t_pulse, fn=fn)
        return result
    budget = watchdog_budget(pods)
    if deadline_remaining() is not None:
        check_deadline(site)
        budget = min(budget, deadline_remaining())
    box: dict = {}
    done = threading.Event()
    ctx = contextvars.copy_context()

    def worker() -> None:
        try:
            # _call_in_scope: the copied context carries the fallback FLAG,
            # not the thread-local jax device scope — re-enter it here so a
            # failed-over dispatch actually lands on the CPU fallback
            box["result"] = ctx.run(_call_in_scope, fn)
        # simonlint: ignore[swallowed-exception] -- not swallowed: the boxed
        # error re-raises in the supervising caller the moment done is set
        except BaseException as we:  # noqa: BLE001
            box["error"] = we
        finally:
            done.set()

    t = threading.Thread(target=worker, name=f"simon-guard-{site}",
                         daemon=True)
    t.start()
    if not done.wait(budget):
        check_deadline(site)  # the caller's budget expired, not the device
        if pl is not None:
            pl.commit_unit(site=site, pods=pods,
                           wall_s=time.perf_counter() - t_pulse, ok=False,
                           fn=fn)
        raise _declare_wedged(site, injected=False)
    if pl is not None:
        pl.commit_unit(site=site, pods=pods,
                       wall_s=time.perf_counter() - t_pulse,
                       ok="error" not in box, fn=fn)
    if "error" in box:
        raise box["error"]
    return box["result"]


def _declare_wedged(site: str, injected: bool) -> BackendWedged:
    backend = current_backend()
    # only a REAL watchdog expiry earns the re-probe/expiry path: a slow-but-
    # healthy outlier can clear its name, while injected wedges stay pinned
    # for deterministic tests and the fault-smoke CI
    quarantine(backend, f"{CAUSE_WEDGE}@{site}", reprobe=not injected)
    obs.GUARD_WATCHDOG_EXPIRIES.labels(site=site).inc()
    record_event("wedge", site, backend)
    return BackendWedged(site, backend, injected=injected)


# -------------------------------------------------------- OOM classification --


def oom_site(e: BaseException) -> Optional[str]:
    """The dispatch stage an error OOM'd at ("to_device" / "dispatch"), or
    None when the error is not an out-of-memory condition. Injected
    `oom_to_device`/`oom_dispatch` faults classify exactly like the real
    jaxlib RESOURCE_EXHAUSTED they stand in for."""
    site = getattr(e, "site", None)
    if (isinstance(e, faults.FaultInjected) and isinstance(site, str)
            and site.startswith("oom_")):
        return site[len("oom_"):]
    if type(e).__name__ == "XlaRuntimeError":
        msg = str(e)
        if "RESOURCE_EXHAUSTED" in msg or "out of memory" in msg.lower():
            # real OOMs do not carry the phase; attribute to dispatch (the
            # stage whose retry semantics — bisection — apply either way)
            return "dispatch"
    return None


def containment_cause(e: BaseException) -> Optional[str]:
    """Failover cause label for a containable error, or None when the error
    must propagate (deadline expiries, injected non-OOM faults, real bugs)."""
    if isinstance(e, BackendWedged):
        return CAUSE_WEDGE
    if isinstance(e, OOMBisectionExhausted):
        return CAUSE_OOM_EXHAUSTED
    if oom_site(e) is not None:
        return CAUSE_OOM
    return None


def count_failover(cause: str, where: str) -> None:
    """One failover decision: counter + event trace (callers log the rest)."""
    obs.GUARD_FAILOVERS.labels(cause=cause).inc()
    record_event("failover", cause, where)


# ------------------------------------------------- capacity-search journal ----


class SearchJournal:
    """Fsync'd JSONL journal of capacity-search probe verdicts.

    Line 1 is a header carrying the search's options digest; every later line
    is one verdict ``{"n": ..., "ok": ..., "n_failed": ...}``. `record` is
    write → flush → fsync, so a SIGKILL between probes loses at most the
    probe in flight; a torn trailing line (killed mid-write) is ignored on
    load — the valid prefix IS the journal. `open` rejects a file whose
    digest does not match the current search (`JournalMismatch`): a stale
    journal can steer a DIFFERENT search to a wrong answer, which is strictly
    worse than re-probing. The `journal_write` fault site fires before the
    write, so crash-during-journaling is deterministically testable."""

    KIND = "simon-capacity-journal"
    VERSION = 1

    def __init__(self, path: str, digest: str) -> None:
        self.path = path
        self.digest = digest
        self.verdicts: Dict[int, Tuple[bool, int]] = {}
        self.replayed = 0  # lookup hits served without a device probe
        self._f = None

    @classmethod
    def open(cls, path: str, digest: str) -> "SearchJournal":
        self = cls(path, digest)
        raw = b""
        if os.path.exists(path) and os.path.getsize(path) > 0:
            with open(path, "rb") as f:
                raw = f.read()
        if raw:
            # All offsets below are BYTE offsets into the raw file — a torn
            # tail can hold invalid utf-8, and a replace-decoded round trip
            # (U+FFFD is 3 bytes where the bad byte was 1) would make a
            # char-counted truncate land in the wrong place.
            nl = raw.find(b"\n")
            if nl < 0:
                # Unterminated first line. Rewrite ONLY when it is a byte-
                # prefix of the exact header THIS search would write — i.e.
                # our own crash torn mid-header-write, after which no verdict
                # can exist. Any other newline-less file (a typo'd
                # --resume-journal path at someone's digest/VERSION file, a
                # different search's torn header) is refused untouched.
                expected = (json.dumps(
                    {"kind": cls.KIND, "v": cls.VERSION, "digest": digest},
                    sort_keys=True) + "\n").encode()
                if expected.startswith(raw):
                    self._start_fresh(path, digest)
                    return self
                raise JournalMismatch(
                    f"{path} is not a capacity-search journal "
                    f"(unparsable header)")
            try:
                head = json.loads(raw[:nl])
            except ValueError:
                raise JournalMismatch(
                    f"{path} is not a capacity-search journal "
                    f"(unparsable header)") from None
            if not isinstance(head, dict) or head.get("kind") != cls.KIND:
                raise JournalMismatch(
                    f"{path} is not a capacity-search journal")
            if head.get("digest") != digest:
                raise JournalMismatch(
                    f"journal {path} was written by a different search "
                    f"(journal digest {head.get('digest')!r} != current "
                    f"{digest!r}); refusing to resume — delete it or point "
                    f"--resume-journal elsewhere")
            valid_bytes = pos = nl + 1
            while True:
                nl = raw.find(b"\n", pos)
                if nl < 0:
                    # a record the crash left unterminated doesn't count as
                    # durable even if it happens to parse: neither served
                    # from memory nor kept on disk (the truncation drops it)
                    break
                body = raw[pos:nl].strip()
                try:
                    if body:
                        rec = json.loads(body)
                        self.verdicts[int(rec["n"])] = (
                            bool(rec["ok"]), int(rec["n_failed"]))
                except (ValueError, KeyError, TypeError):
                    break  # torn tail from a crash: the valid prefix ends here
                valid_bytes = pos = nl + 1
            self._f = open(path, "a")
            if valid_bytes < len(raw):
                # repair: drop the torn tail so the next append starts a
                # fresh line instead of extending the garbage
                self._f.truncate(valid_bytes)
                self._f.flush()
                os.fsync(self._f.fileno())
        else:
            self._start_fresh(path, digest)
        return self

    def _start_fresh(self, path: str, digest: str) -> None:
        self._f = open(path, "w")
        self._append({"kind": self.KIND, "v": self.VERSION, "digest": digest})

    def _append(self, doc: dict) -> None:
        self._f.write(json.dumps(doc, sort_keys=True) + "\n")
        self._f.flush()
        os.fsync(self._f.fileno())

    def lookup(self, n: int) -> Optional[Tuple[bool, int]]:
        hit = self.verdicts.get(int(n))
        if hit is not None:
            self.replayed += 1
            obs.JOURNAL_REPLAYS.inc()
        return hit

    def record(self, n: int, ok: bool, n_failed: int) -> None:
        faults.maybe_fail("journal_write")
        if self._f is None:
            # the planner closes the fd when a search finishes; a REUSED
            # planner's next search appends to the (cleanly closed, fully
            # valid) file rather than crashing on the closed handle
            self._f = open(self.path, "a")
        self._append({"n": int(n), "ok": bool(ok), "n_failed": int(n_failed)})
        self.verdicts[int(n)] = (bool(ok), int(n_failed))
        obs.JOURNAL_RECORDS.inc()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None
