"""simonserve: the cross-request micro-batching dispatcher.

Concurrent what-if requests arriving within a short window coalesce onto the
scenario axis of ONE serve_whatif_fanout dispatch (ops/kernels.py): the
requests' pods are union-encoded into a single padded batch, each request
becomes one lane with its own node-active overlay and valid mask, and the
results demux back to the waiting callers. Lane padding repeats lane 0 and is
sliced off, the per-lane valid masks make union rows outside a request
provable no-ops, and the shared image's device tables are read-only inputs —
so a micro-batched response is bit-identical to the same request probed
serially from a fresh encode (the determinism contract PARITY.md documents
and tests/test_serve.py asserts).

Failure semantics: a contained device failure (watchdog wedge, OOM — see
resilience/guard.py) fails the whole batch over to the fresh-simulation path
per request, which the engine routes to the CPU fallback; nothing is silent
(simon_guard_failovers_total moves, responses carry path="fresh"). Ineligible
requests (census-dependent predicates, pre-bound pods, gpu/storage) never
enter a batch — they run the fresh path directly.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence

from ..obs import instruments as obs
from ..obs import scope
from ..resilience import guard
from .ha import AdmissionController
from .image import ResidentImage, WhatIfSession

# requests larger than this ride the fresh path: big batches want the
# engine's wave segmentation, not S copies of a long serial scan
MAX_BATCHED_PODS = 512


class _Pending:
    """One enqueued request and its rendezvous. `tm` is the simonscope
    timing/trace record (None with scope off — the zero-cost contract):
    the request's TraceCtx + flow id, the phase-boundary timestamps the
    dispatcher/kernel threads stamp in, and the attempt list a failover
    replay appends to. One trace ID covers every attempt."""

    __slots__ = ("session", "done", "response", "error", "tm")

    def __init__(self, session: WhatIfSession,
                 tm: Optional[dict] = None) -> None:
        self.session = session
        self.done = threading.Event()
        self.response: Optional[dict] = None
        self.error: Optional[BaseException] = None
        self.tm = tm


class WhatIfService:
    """The serving facade: submit() blocks until the request's micro-batch
    (or fresh fallback) resolves. One daemon dispatcher thread owns batch
    formation; handler threads only enqueue and wait."""

    def __init__(self, image: ResidentImage, window_ms: float = 2.0,
                 fanout: int = 8,
                 admission: Optional[AdmissionController] = None) -> None:
        self.image = image
        self.window_s = max(0.0, float(window_ms)) / 1000.0
        self.fanout = max(1, int(fanout))
        # simonha admission control (None = the historical unbounded-admit
        # behavior; `simon serve` always wires a controller). The queue
        # list itself stays a list — the BOUND lives in admission.admit,
        # checked before any enqueue.
        self.admission = admission
        # backpressure: sustained queue growth halves the batching window
        # (drain faster, coalesce less) down to this floor; a drained queue
        # grows it back — see _take_batch
        self._window_scale = 1.0
        self._window_floor = 0.125
        self._growth_rounds = 0
        self._queue: List[_Pending] = []
        self._cv = threading.Condition()
        self._stopped = False
        self._thread = threading.Thread(
            target=self._loop, name="simon-serve-dispatch", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- client -----

    def submit(self, pods: List[dict], drains: Sequence[str] = (),
               tenant: str = "default",
               deadline_s: Optional[float] = None) -> dict:
        """Serve one what-if request: {"scheduled", "total", "unscheduled",
        "utilization", "epoch", "lanes", "path"}. May raise ha.ShedError
        BEFORE any queue/device work when admission control is wired
        (bounded queue, per-tenant-route buckets, deadline-aware shed)."""
        if not pods:
            raise ValueError("what-if request has no pods")
        # simonlint: ignore[race-unguarded-attr] -- racy fast-fail: _submit
        # re-checks under _cv before enqueueing, so a stale False here only
        # defers the rejection to that locked check
        if self._stopped:
            raise RuntimeError("serve dispatcher is stopped")
        if self.admission is not None:
            # simonlint: ignore[race-unguarded-attr] -- shed BEFORE the
            # encode: a rejected request must cost nothing downstream.
            # len() is GIL-atomic and the queue bound tolerates one
            # in-flight enqueue of slack, so the off-lock read only ever
            # shifts the shed boundary by a single request
            self.admission.admit("whatif", tenant, len(self._queue),
                                 deadline_s)
        sc = scope.active()
        if sc is None:  # the zero-cost contract: one None-check, old path
            return self._submit(pods, drains, None)
        # join the edge's trace (HTTP/gRPC handler minted one) or mint here
        # (in-process callers: loadgen, tests, embedding code)
        ctx = scope.current_ctx() or sc.mint_trace("whatif")
        tm = {"ctx": ctx, "flow": sc.mint_flow(),
              "tid": threading.get_ident(),
              "t_sub": time.perf_counter(), "attempts": []}
        token = scope._CTX.set(ctx)  # inline use_ctx: this is THE hot path
        try:
            resp = self._submit(pods, drains, tm)
        except BaseException:
            self._finish_scope(sc, tm, None, error=True)
            raise
        finally:
            scope._CTX.reset(token)
        self._finish_scope(sc, tm, resp)
        return resp

    def _submit(self, pods: List[dict], drains: Sequence[str],
                tm: Optional[dict]) -> dict:
        if len(pods) > MAX_BATCHED_PODS or guard.default_quarantined():
            return self._fresh(pods, drains, tm)
        session = self.image.session(pods, drains)
        gate = self.image.eligible(session.batch, pods)
        if gate is not None:
            if tm is not None:
                tm["gate"] = gate
            return self._fresh(pods, drains, tm)
        item = _Pending(session, tm)
        t_enq = time.monotonic()
        with self._cv:
            # re-check UNDER the lock: a stop() racing the encode above must
            # not let this item enqueue after the dispatcher exited — nothing
            # would ever set its event and the caller would hang forever
            if self._stopped:
                raise RuntimeError("serve dispatcher is stopped")
            if tm is not None:
                tm["t_enq"] = time.perf_counter()
            self._queue.append(item)
            self._cv.notify_all()
        item.done.wait()
        if self.admission is not None:
            # the observed queue+dispatch wall the deadline shed compares
            # remaining Deadlines against
            self.admission.observe_wall(time.monotonic() - t_enq)
        if item.error is not None:
            raise item.error
        obs.SERVE_REQUESTS.labels(path=item.response["path"]).inc()
        return item.response

    def _fresh(self, pods: List[dict], drains: Sequence[str],
               tm: Optional[dict] = None) -> dict:
        obs.SERVE_REQUESTS.labels(path="fresh").inc()
        if tm is None:
            return self.image.fresh_probe(pods, drains)
        # the detour expands to a 'fresh_detour' span from these marks; the
        # engine's own probe span (engine.probe_pods) nests inside it via
        # the bound trace ctx
        tm["attempts"].append("fresh")
        tm["t_fresh0"] = time.perf_counter()
        resp = self.image.fresh_probe(pods, drains)
        tm["t_fresh1"] = time.perf_counter()
        return resp

    def _finish_scope(self, sc, tm: dict, resp: Optional[dict],
                      error: bool = False) -> None:
        """Feed the SLO engine and append the request's raw trace record
        (one lock + one append — the span tree expands lazily off the
        serving path). The `total_s` float on the expanded root span is the
        SAME float observed into the histogram, so trace and SLO sums
        reconcile exactly (the acceptance criterion tests/test_scope.py
        asserts)."""
        now = time.perf_counter()
        total = now - tm["t_sub"]
        route = "error" if error else (resp or {}).get("path", "error")
        phases: Dict[str, float] = {"total": total}
        t_enq, t_batch = tm.get("t_enq"), tm.get("t_batch")
        ke, fe = tm.get("kernel_end"), tm.get("fetch_end")
        if t_enq is not None and t_batch is not None:
            phases["queue"] = t_batch - t_enq
        if t_batch is not None and ke is not None:
            phases["dispatch"] = ke - t_batch
        if ke is not None and fe is not None:
            phases["fetch"] = fe - ke
        if tm.get("t_fresh0") is not None and tm.get("t_fresh1") is not None:
            # fresh path / failover replay: the probe IS the dispatch phase
            phases.setdefault("dispatch", tm["t_fresh1"] - tm["t_fresh0"])
        sc.record_request("whatif", tm, now, total, route)
        sc.slo.record("whatif", route, phases, error=error)

    def stop(self) -> None:
        """Drain: wake the dispatcher and fail still-queued requests fast
        (an in-flight batch completes normally)."""
        with self._cv:
            self._stopped = True
            for item in self._queue:
                item.error = RuntimeError("serve dispatcher is stopped")
                item.done.set()
            self._queue.clear()
            self._cv.notify_all()

    # --------------------------------------------------------- dispatcher -----

    def _loop(self) -> None:
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            try:
                self._dispatch(batch)
            except BaseException as e:  # a dispatcher crash must never hang
                for item in batch:      # callers on .wait() forever
                    if not item.done.is_set():
                        item.error = e
                        item.done.set()

    def _take_batch(self) -> Optional[List[_Pending]]:
        """Block for the first request, then hold the window open (or until
        `fanout` lanes fill); None once stopped and drained."""
        with self._cv:
            while not self._queue:
                if self._stopped:
                    return None
                self._cv.wait()
            deadline = time.monotonic() + self.window_s * self._window_scale
            while (len(self._queue) < self.fanout and not self._stopped):
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._cv.wait(timeout=left)
            batch = self._queue[:self.fanout]
            del self._queue[:self.fanout]
            # backpressure: a full fanout leaving a full fanout still
            # waiting, twice running, means arrivals outpace dispatch —
            # shrink the batching window (drain faster, coalesce less);
            # recover once the queue fully drains
            if len(self._queue) >= self.fanout:
                self._growth_rounds += 1
                if (self._growth_rounds >= 2
                        and self._window_scale > self._window_floor):
                    self._window_scale = max(self._window_floor,
                                             self._window_scale * 0.5)
                    self._growth_rounds = 0
                    obs.SERVE_BACKPRESSURE.labels(action="shrink").inc()
            else:
                self._growth_rounds = 0
                if not self._queue and self._window_scale < 1.0:
                    self._window_scale = min(1.0, self._window_scale * 2.0)
                    obs.SERVE_BACKPRESSURE.labels(action="recover").inc()
            return batch

    def _dispatch(self, batch: List[_Pending]) -> None:
        # staleness is revalidated by dispatch_sessions UNDER the image lock
        # (a racing rebuild between here and there would invalidate any
        # check made outside it)
        sc = scope.active()
        tms = [item.tm for item in batch if item.tm is not None]
        sink: dict = {}
        if sc is not None and tms:
            t_batch = time.perf_counter()
            tid = threading.get_ident()
            for tm in tms:
                tm["t_batch"] = t_batch
                tm["batch_tid"] = tid
                tm["lanes"] = len(batch)
                tm["attempts"].append("batched")
        try:
            if sc is not None and tms:
                with scope.collect_phases(sink), sc.span(
                        "serve_batch", cat="serve", lanes=len(batch)):
                    responses = self.image.dispatch_sessions(
                        [item.session for item in batch])
                # stamp the kernel-thread phase marks (guard.supervised's
                # copied contextvars carried the sink reference into the
                # watchdog worker) into every scoped request — on SUCCESS
                # only, and before any done.set(): a failed attempt's
                # partial marks must not masquerade as the fresh replay's
                # dispatch phase, and stamping after wake-up would race
                # the submitter threads reading tm in _finish_scope
                for tm in tms:
                    for k in ("kernel_begin", "kernel_end", "fetch_end"):
                        if k in sink:
                            tm[k] = sink[k]
            else:
                responses = self.image.dispatch_sessions(
                    [item.session for item in batch])
        except BaseException as e:
            if guard.containment_cause(e) is None:
                raise
            # contained device failure: the batch fails over to per-request
            # fresh probes (the engine routes those to the CPU fallback)
            guard.count_failover(guard.containment_cause(e), "serve")
            cause = guard.containment_cause(e)
            for item in batch:
                try:
                    if sc is not None and item.tm is not None:
                        # the replay keeps the REQUEST's trace id: one trace
                        # shows the wedged batched attempt and its fresh
                        # replacement end to end
                        item.tm["attempts"].append("fresh_replay")
                        item.tm["t_fresh0"] = time.perf_counter()
                        with sc.use_ctx(item.tm["ctx"]), sc.span(
                                "fresh_replay", cat="serve", cause=cause):
                            item.response = self.image.fresh_probe(
                                item.session.pods, item.session.drains)
                        item.tm["t_fresh1"] = time.perf_counter()
                    else:
                        item.response = self.image.fresh_probe(
                            item.session.pods, item.session.drains)
                except BaseException as fe:
                    import logging

                    # surfaced to the caller via item.error AND logged: a
                    # request failing on the fallback path too is never silent
                    logging.getLogger("open_simulator_tpu").warning(
                        "serve: fresh-path fallback failed after a contained "
                        "device failure: %r", fe)
                    item.error = fe
                item.done.set()
            return
        for item, resp in zip(batch, responses):
            item.response = resp
            item.done.set()

    # -------------------------------------------------------------- stats -----

    def stats(self) -> Dict[str, object]:
        img = self.image
        return {
            "epoch": img.epoch,
            "generation": img.generation,
            "nodes": img.n_nodes,
            "drained": sorted(img.drained),
            "window_ms": self.window_s * 1000.0,
            # simonlint: ignore[race-unguarded-attr] -- monitoring snapshot
            "window_scale": self._window_scale,
            "sheds": self.admission.sheds if self.admission else 0,
            "fanout": self.fanout,
            "mesh": img._mesh is not None,
            # simonlint: ignore[race-unguarded-attr] -- monitoring snapshot:
            # len() is GIL-atomic and the gauge tolerates one-batch staleness
            "queued": len(self._queue),
        }
