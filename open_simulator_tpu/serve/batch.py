"""simonserve: the cross-request micro-batching dispatcher.

Concurrent what-if requests arriving within a short window coalesce onto the
scenario axis of ONE serve_whatif_fanout dispatch (ops/kernels.py): the
requests' pods are union-encoded into a single padded batch, each request
becomes one lane with its own node-active overlay and valid mask, and the
results demux back to the waiting callers. Lane padding repeats lane 0 and is
sliced off, the per-lane valid masks make union rows outside a request
provable no-ops, and the shared image's device tables are read-only inputs —
so a micro-batched response is bit-identical to the same request probed
serially from a fresh encode (the determinism contract PARITY.md documents
and tests/test_serve.py asserts).

Failure semantics: a contained device failure (watchdog wedge, OOM — see
resilience/guard.py) fails the whole batch over to the fresh-simulation path
per request, which the engine routes to the CPU fallback; nothing is silent
(simon_guard_failovers_total moves, responses carry path="fresh"). Ineligible
requests (census-dependent predicates, pre-bound pods, gpu/storage) never
enter a batch — they run the fresh path directly.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence

from ..obs import instruments as obs
from ..resilience import guard
from .image import ResidentImage, WhatIfSession

# requests larger than this ride the fresh path: big batches want the
# engine's wave segmentation, not S copies of a long serial scan
MAX_BATCHED_PODS = 512


class _Pending:
    """One enqueued request and its rendezvous."""

    __slots__ = ("session", "done", "response", "error")

    def __init__(self, session: WhatIfSession) -> None:
        self.session = session
        self.done = threading.Event()
        self.response: Optional[dict] = None
        self.error: Optional[BaseException] = None


class WhatIfService:
    """The serving facade: submit() blocks until the request's micro-batch
    (or fresh fallback) resolves. One daemon dispatcher thread owns batch
    formation; handler threads only enqueue and wait."""

    def __init__(self, image: ResidentImage, window_ms: float = 2.0,
                 fanout: int = 8) -> None:
        self.image = image
        self.window_s = max(0.0, float(window_ms)) / 1000.0
        self.fanout = max(1, int(fanout))
        self._queue: List[_Pending] = []
        self._cv = threading.Condition()
        self._stopped = False
        self._thread = threading.Thread(
            target=self._loop, name="simon-serve-dispatch", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- client -----

    def submit(self, pods: List[dict], drains: Sequence[str] = ()) -> dict:
        """Serve one what-if request: {"scheduled", "total", "unscheduled",
        "utilization", "epoch", "lanes", "path"}."""
        if not pods:
            raise ValueError("what-if request has no pods")
        if self._stopped:
            raise RuntimeError("serve dispatcher is stopped")
        if len(pods) > MAX_BATCHED_PODS or guard.default_quarantined():
            return self._fresh(pods, drains)
        session = self.image.session(pods, drains)
        gate = self.image.eligible(session.batch, pods)
        if gate is not None:
            return self._fresh(pods, drains)
        item = _Pending(session)
        with self._cv:
            # re-check UNDER the lock: a stop() racing the encode above must
            # not let this item enqueue after the dispatcher exited — nothing
            # would ever set its event and the caller would hang forever
            if self._stopped:
                raise RuntimeError("serve dispatcher is stopped")
            self._queue.append(item)
            self._cv.notify_all()
        item.done.wait()
        if item.error is not None:
            raise item.error
        obs.SERVE_REQUESTS.labels(path=item.response["path"]).inc()
        return item.response

    def _fresh(self, pods: List[dict], drains: Sequence[str]) -> dict:
        obs.SERVE_REQUESTS.labels(path="fresh").inc()
        return self.image.fresh_probe(pods, drains)

    def stop(self) -> None:
        """Drain: wake the dispatcher and fail still-queued requests fast
        (an in-flight batch completes normally)."""
        with self._cv:
            self._stopped = True
            for item in self._queue:
                item.error = RuntimeError("serve dispatcher is stopped")
                item.done.set()
            self._queue.clear()
            self._cv.notify_all()

    # --------------------------------------------------------- dispatcher -----

    def _loop(self) -> None:
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            try:
                self._dispatch(batch)
            except BaseException as e:  # a dispatcher crash must never hang
                for item in batch:      # callers on .wait() forever
                    if not item.done.is_set():
                        item.error = e
                        item.done.set()

    def _take_batch(self) -> Optional[List[_Pending]]:
        """Block for the first request, then hold the window open (or until
        `fanout` lanes fill); None once stopped and drained."""
        with self._cv:
            while not self._queue:
                if self._stopped:
                    return None
                self._cv.wait()
            deadline = time.monotonic() + self.window_s
            while (len(self._queue) < self.fanout and not self._stopped):
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._cv.wait(timeout=left)
            batch = self._queue[:self.fanout]
            del self._queue[:self.fanout]
            return batch

    def _dispatch(self, batch: List[_Pending]) -> None:
        # staleness is revalidated by dispatch_sessions UNDER the image lock
        # (a racing rebuild between here and there would invalidate any
        # check made outside it)
        try:
            responses = self.image.dispatch_sessions(
                [item.session for item in batch])
        except BaseException as e:
            if guard.containment_cause(e) is None:
                raise
            # contained device failure: the batch fails over to per-request
            # fresh probes (the engine routes those to the CPU fallback)
            guard.count_failover(guard.containment_cause(e), "serve")
            for item in batch:
                try:
                    item.response = self.image.fresh_probe(
                        item.session.pods, item.session.drains)
                except BaseException as fe:
                    import logging

                    # surfaced to the caller via item.error AND logged: a
                    # request failing on the fallback path too is never silent
                    logging.getLogger("open_simulator_tpu").warning(
                        "serve: fresh-path fallback failed after a contained "
                        "device failure: %r", fe)
                    item.error = fe
                item.done.set()
            return
        for item, resp in zip(batch, responses):
            item.response = resp
            item.done.set()

    # -------------------------------------------------------------- stats -----

    def stats(self) -> Dict[str, object]:
        img = self.image
        return {
            "epoch": img.epoch,
            "generation": img.generation,
            "nodes": img.n_nodes,
            "drained": sorted(img.drained),
            "window_ms": self.window_s * 1000.0,
            "fanout": self.fanout,
            "mesh": img._mesh is not None,
            "queued": len(self._queue),
        }
