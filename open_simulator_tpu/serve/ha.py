"""simonha: crash-consistent serving — ingest WAL + checkpoint/restore,
overload admission control, and bounded-staleness degraded mode.

The reference gets durability and resync for free from the apiserver: an
informer relist after a crash or a 410 Gone rebuilds the watcher's world
from server truth (PARITY.md). A first-party resident image has no such
backing store — `simon serve` owned the only copy of its ingested deltas,
so a SIGKILL lost them and a restart paid a full from-scratch rebuild.
This module makes the serve process its own apiserver:

- **Write-ahead ingest.** Every `/v1/ingest` delta batch is fsync'd to an
  epoch-numbered WAL record BEFORE it mutates the image (`IngestWAL`, the
  SearchJournal machinery from resilience/guard.py: digest header, byte-
  offset torn-tail truncate, write→flush→fsync appends). The record carries
  the `seq` the batch will produce, and `apply_events` bumps seq exactly
  once per batch — even on a mid-batch failure — so replay is idempotent
  keyed on `generation.seq`: a record at-or-below the image's seq is
  skipped, the record at seq+1 is applied, a gap is refused loudly.
- **Checkpoint/restore.** Periodic compaction snapshots the image's host
  truth (live nodes — the columnar NodeStore rides whole when it is still
  exactly the cluster — committed pods in commit order, cluster objects,
  generation.seq) to `checkpoint.bin` via tmp-file + fsync + atomic rename,
  then rotates the WAL: its sealed records now live in the checkpoint.
  Restart = load checkpoint + replay the WAL tail; the PR 10 delta-ingest
  property tests prove a from-scratch build over exactly (current_nodes,
  cluster_pods) answers bit-identically, so the restored image is
  bit-identical to the never-crashed process.
- **Admission control.** A bounded queue (`max_queue`), per-tenant-route
  token buckets, and deadline-aware shedding: a request whose remaining
  Deadline cannot cover the observed p95 queue+dispatch wall is rejected
  429 + Retry-After immediately instead of timing out downstream (the
  Clipper discipline). Shed decisions consume a seeded PRNG and an
  injectable clock, so a replayed run sheds identically.
- **Bounded-staleness degraded mode.** When ingest stalls (WAL append
  failing, apply failing, `ingest_stall` injected, backend quarantined),
  serving continues against the last consistent epoch with
  `X-Simon-Epoch` / `staleness_s` stamped on every answer; crossing the
  configured staleness ceiling flips `/healthz` to 503. Recovery is the
  next successful ingest (or an explicit `resync()` generation-bumping
  rebuild) — never a wrong answer: an answer stamped with an epoch the
  image has not reached is structurally impossible, and the
  `simon_serve_wrong_epoch_answers_total` tripwire (bench-gate
  MUST_BE_ZERO) fails the request loudly if it ever were.

Fault sites `wal_write` / `wal_fsync` / `checkpoint_write` / `ingest_stall`
thread through FaultPlan (resilience/faults.py) so every failure mode here
is injectable and replay-equal, like every other stage of the engine.

The checkpoint payload is a pickle of this process's own prior state read
back from an operator-owned --state-dir (the same trust domain as the
process itself); a sha256 over the payload bytes in the JSON header line
detects torn or doctored files and refuses them loudly.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import random
import threading
import time
from collections import OrderedDict, deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..obs import instruments as obs
from ..obs import scope
from ..resilience import faults, guard
from ..resilience.policy import deadline_remaining
from .image import ResidentImage

WAL_NAME = "ingest.wal"
CHECKPOINT_NAME = "checkpoint.bin"


class WalMismatch(RuntimeError):
    """The WAL/checkpoint lineage digest does not match, a replay record's
    seq leaves a gap, or a checkpoint payload fails its integrity hash —
    the state dir belongs to a different (or doubted) serving lineage and
    is refused loudly rather than replayed into wrong answers."""


class WrongEpochError(RuntimeError):
    """An answer was about to be stamped with an epoch AHEAD of the serving
    image — structurally impossible unless the HA layer is broken; the
    request fails loudly instead of lying (the MUST_BE_ZERO tripwire)."""


class ShedError(RuntimeError):
    """A request shed by admission control before any queue/device work.
    `reason` is the SERVE_SHEDS label; `retry_after` seeds the HTTP 429's
    Retry-After header (seconds, deterministic under a seeded controller)."""

    def __init__(self, reason: str, retry_after: float) -> None:
        super().__init__(f"request shed ({reason}); retry after "
                         f"{retry_after:.3f}s")
        self.reason = reason
        self.retry_after = retry_after


def lineage_digest(nodes: Sequence[dict], pods: Sequence[dict]) -> str:
    """Content digest of the boot cluster state — the WAL/checkpoint lineage
    id. Full canonical JSON, not just names: replaying deltas onto a
    same-named but different-shaped cluster would be silently wrong."""
    doc = json.dumps({"nodes": list(nodes), "pods": list(pods)},
                     sort_keys=True, default=str)
    return hashlib.sha256(doc.encode()).hexdigest()


# ------------------------------------------------------------- ingest WAL ----


class IngestWAL:
    """Fsync'd JSONL write-ahead log of ingest delta batches.

    Line 1 is a header carrying the serving lineage digest; every later line
    is one record ``{"seq": ..., "events": [...]}`` — the seq the batch WILL
    produce, appended write→flush→fsync BEFORE apply_events mutates the
    image. Open follows SearchJournal's recovery contract byte for byte: a
    torn trailing line (SIGKILL mid-write) is truncated away and the valid
    prefix IS the log; a digest mismatch is refused untouched. The
    `wal_write` fault site fires before the write and `wal_fsync` between
    flush and fsync — the torn-tail window, deterministically injectable."""

    KIND = "simon-ingest-wal"
    VERSION = 1

    def __init__(self, path: str, digest: str) -> None:
        self.path = path
        self.digest = digest
        self.records: List[Tuple[int, list]] = []  # valid prefix, in order
        self.truncated = False
        self._f = None

    @classmethod
    def open(cls, path: str, digest: str) -> "IngestWAL":
        self = cls(path, digest)
        raw = b""
        if os.path.exists(path) and os.path.getsize(path) > 0:
            with open(path, "rb") as f:
                raw = f.read()
        if raw:
            # BYTE offsets throughout: a torn tail can hold invalid utf-8,
            # and a replace-decoded round trip would mis-place the truncate.
            nl = raw.find(b"\n")
            if nl < 0:
                # Unterminated first line: rewrite ONLY our own torn header
                # (a byte-prefix of the exact header THIS lineage writes);
                # anything else is refused untouched.
                expected = (json.dumps(
                    {"kind": cls.KIND, "v": cls.VERSION, "digest": digest},
                    sort_keys=True) + "\n").encode()
                if expected.startswith(raw):
                    self._start_fresh(path, digest)
                    return self
                obs.SERVE_WAL_MISMATCHES.inc()
                raise WalMismatch(
                    f"{path} is not an ingest WAL (unparsable header)")
            try:
                head = json.loads(raw[:nl])
            except ValueError:
                obs.SERVE_WAL_MISMATCHES.inc()
                raise WalMismatch(
                    f"{path} is not an ingest WAL (unparsable header)"
                ) from None
            if not isinstance(head, dict) or head.get("kind") != cls.KIND:
                obs.SERVE_WAL_MISMATCHES.inc()
                raise WalMismatch(f"{path} is not an ingest WAL")
            if head.get("digest") != digest:
                obs.SERVE_WAL_MISMATCHES.inc()
                raise WalMismatch(
                    f"WAL {path} belongs to a different serving lineage "
                    f"(WAL digest {head.get('digest')!r} != current "
                    f"{digest!r}); refusing to replay — delete the state "
                    f"dir or point --state-dir elsewhere")
            valid_bytes = pos = nl + 1
            while True:
                nl = raw.find(b"\n", pos)
                if nl < 0:
                    # an unterminated record is not durable even if it
                    # happens to parse: neither replayed nor kept on disk
                    break
                body = raw[pos:nl].strip()
                try:
                    if body:
                        rec = json.loads(body)
                        self.records.append(
                            (int(rec["seq"]), list(rec["events"])))
                except (ValueError, KeyError, TypeError):
                    break  # torn tail from a crash: the valid prefix ends here
                valid_bytes = pos = nl + 1
            self._f = open(path, "a")
            if valid_bytes < len(raw):
                self._f.truncate(valid_bytes)
                self._f.flush()
                os.fsync(self._f.fileno())
                self.truncated = True
                obs.SERVE_WAL_OPS.labels(op="truncate").inc()
        else:
            self._start_fresh(path, digest)
        return self

    def _start_fresh(self, path: str, digest: str) -> None:
        self._f = open(path, "w")
        self._append({"kind": self.KIND, "v": self.VERSION, "digest": digest})

    def _append(self, doc: dict) -> None:
        self._f.write(json.dumps(doc, sort_keys=True) + "\n")
        self._f.flush()
        faults.maybe_fail("wal_fsync")
        os.fsync(self._f.fileno())

    def append(self, seq: int, events: Sequence[dict]) -> None:
        """One fsync'd record, BEFORE the image mutates. A failure here
        (injected or real) leaves the on-disk valid prefix intact and the
        image untouched — the caller degrades, never half-applies."""
        faults.maybe_fail("wal_write")
        if self._f is None:
            self._f = open(self.path, "a")
        self._append({"seq": int(seq), "events": list(events)})
        self.records.append((int(seq), list(events)))
        obs.SERVE_WAL_OPS.labels(op="append").inc()

    def rotate(self) -> None:
        """Reset to header-only after a checkpoint sealed every record at
        or below its seq. Crash between the checkpoint rename and this
        rotate is safe: the stale records replay as seq <= image.seq skips."""
        if self._f is not None:
            self._f.close()
        self._start_fresh(self.path, self.digest)
        self.records = []
        obs.SERVE_WAL_OPS.labels(op="rotate").inc()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


# ------------------------------------------------------------- checkpoint ----


def save_checkpoint(path: str, image: ResidentImage, digest: str) -> dict:
    """Snapshot the image's host truth to `path` (tmp + fsync + atomic
    rename — the previous checkpoint stays valid until the rename lands).
    Returns the captured header. The `checkpoint_write` fault site fires
    before any byte is written."""
    from ..core.types import ResourceTypes

    faults.maybe_fail("checkpoint_write")
    with image._lock:
        model = image._sim.model
        rt = ResourceTypes(
            services=list(model.services),
            replication_controllers=list(model.replication_controllers),
            replica_sets=list(model.replica_sets),
            stateful_sets=list(model.stateful_sets),
            storage_classes=list(model.storage_classes),
            config_maps=list(model.config_maps),
            pod_disruption_budgets=list(model.pdbs),
            persistent_volume_claims=list(model.pvcs),
        )
        # the columnar fast path: when the store still IS the live cluster
        # (no delta node-adds, no drains), it rides whole — template blocks,
        # not N dicts — and restore hands the engine its columns back
        # instead of re-parsing N node dicts. Materializing the dict list
        # alongside it would make both the write and the restore pay the
        # per-node cost anyway, so the dict form is saved ONLY as the slow-
        # path fallback — the restart-to-ready ≥5x the bench gate pins.
        lazy = image._sim.na.nodes
        fast = (getattr(lazy, "store", None) is not None
                and not lazy._extra and not image.drained)
        state = {
            "nodes": None if fast else image.current_nodes(),
            "pods": image.cluster_pods(),
            "objects": rt,
            "sched_config": image._sim.sched_config,
            "generation": image.generation,
            "seq": image.seq,
        }
        if fast:
            state["store"] = lazy.store
    payload = pickle.dumps(state, protocol=pickle.HIGHEST_PROTOCOL)
    head = {"kind": "simon-image-checkpoint", "v": 1, "digest": digest,
            "sha256": hashlib.sha256(payload).hexdigest(),
            "n_bytes": len(payload),
            "generation": state["generation"], "seq": state["seq"]}
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write((json.dumps(head, sort_keys=True) + "\n").encode())
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    obs.SERVE_CHECKPOINTS.labels(op="write").inc()
    return head


def load_checkpoint(path: str) -> Tuple[dict, dict]:
    """(header, state) — refuses loudly (WalMismatch + the parity-mismatch
    counter) on a torn, truncated, or doctored file: serving from doubted
    state is strictly worse than a from-scratch rebuild."""
    with open(path, "rb") as f:
        raw = f.read()
    nl = raw.find(b"\n")
    bad = None
    head = None
    if nl < 0:
        bad = "no header line"
    else:
        try:
            head = json.loads(raw[:nl])
        except ValueError:
            bad = "unparsable header"
    if bad is None and (not isinstance(head, dict)
                        or head.get("kind") != "simon-image-checkpoint"):
        bad = "not an image checkpoint"
    if bad is None:
        payload = raw[nl + 1:]
        if len(payload) != head.get("n_bytes"):
            bad = (f"payload is {len(payload)} bytes, header says "
                   f"{head.get('n_bytes')}")
        elif hashlib.sha256(payload).hexdigest() != head.get("sha256"):
            bad = "payload sha256 mismatch"
    if bad is not None:
        obs.SERVE_WAL_MISMATCHES.inc()
        raise WalMismatch(f"checkpoint {path} refused: {bad}")
    return head, pickle.loads(payload)


def restore_image(state: dict, mesh=None) -> ResidentImage:
    """Rebuild a ResidentImage from a checkpoint state dict, restoring its
    generation.seq so replayed WAL records key onto the same epochs the
    crashed process stamped."""
    store = state.get("store")
    nodes = store if store is not None else state["nodes"]
    image = ResidentImage.try_build(
        nodes, cluster_objects=state["objects"], pods=state["pods"],
        sched_config=state["sched_config"], mesh=mesh)
    if image is None:
        raise WalMismatch(
            "checkpoint restore declined by the image equivalence gates "
            "(backend quarantined at boot, or the checkpointed cluster "
            "grew state the resident path cannot serve)")
    with image._lock:
        image.generation = state["generation"]
        image.seq = state["seq"]
    obs.SERVE_CHECKPOINTS.labels(op="restore").inc()
    return image


# ------------------------------------------------------ admission control ----


class _TokenBucket:
    """One (tenant, route) bucket: `rate` tokens/s refill up to `burst`,
    advanced by the controller's injectable clock — pure state, no wall
    reads of its own, so a replayed request sequence drains identically."""

    __slots__ = ("rate", "burst", "tokens", "t")

    def __init__(self, rate: float, burst: float, now: float) -> None:
        self.rate = rate
        self.burst = burst
        self.tokens = burst
        self.t = now

    def try_take(self, now: float) -> bool:
        self.tokens = min(self.burst,
                          self.tokens + max(0.0, now - self.t) * self.rate)
        self.t = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False

    def refill_wait(self) -> float:
        return (1.0 - self.tokens) / self.rate if self.rate > 0 else 1.0


class AdmissionController:
    """Shed-before-queue admission: bounded queue, per-tenant-route token
    buckets, and deadline-aware rejection against the observed p95
    queue+dispatch wall. Every decision reads the injectable `clock` and a
    seeded PRNG (the Retry-After jitter), so a replayed request sequence
    sheds identically — the determinism contract tests/test_ha.py asserts."""

    def __init__(self, max_queue: int = 256, tenant_rate: float = 0.0,
                 tenant_burst: float = 8.0, seed: int = 0,
                 clock: Callable[[], float] = time.monotonic,
                 max_tenants: int = 1024) -> None:
        self.max_queue = max(1, int(max_queue))
        self.tenant_rate = float(tenant_rate)
        self.tenant_burst = max(1.0, float(tenant_burst))
        self.clock = clock
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._walls: deque = deque(maxlen=128)  # recent queue+dispatch walls
        # LRU-bounded: an open tenant header must not grow memory without
        # bound (the exact hazard this layer exists to close)
        self._buckets: "OrderedDict[Tuple[str, str], _TokenBucket]" = \
            OrderedDict()
        self._max_tenants = max(1, int(max_tenants))
        self.sheds = 0

    # ------------------------------------------------------------ observe ----

    def observe_wall(self, seconds: float) -> None:
        with self._lock:
            self._walls.append(float(seconds))

    def p95(self) -> float:
        """p95 of the recent queue+dispatch walls; 0.0 before any sample
        (a cold controller never deadline-sheds — it has no evidence)."""
        with self._lock:
            if not self._walls:
                return 0.0
            ordered = sorted(self._walls)
            return ordered[min(len(ordered) - 1,
                               int(0.95 * len(ordered)))]

    # -------------------------------------------------------------- admit ----

    def admit(self, route: str, tenant: str, queued: int,
              deadline_s: Optional[float] = None) -> None:
        """Admit or raise ShedError. Checked in hazard order: queue bound
        (protects this process), token bucket (protects fairness), deadline
        (protects the client from a doomed wait)."""
        p95 = self.p95()
        if queued >= self.max_queue:
            self._shed("queue_full", max(0.05, p95))
        if self.tenant_rate > 0:
            with self._lock:
                key = (str(tenant), str(route))
                bucket = self._buckets.get(key)
                if bucket is None:
                    bucket = _TokenBucket(self.tenant_rate,
                                          self.tenant_burst, self.clock())
                    self._buckets[key] = bucket
                    while len(self._buckets) > self._max_tenants:
                        self._buckets.popitem(last=False)
                else:
                    self._buckets.move_to_end(key)
                ok = bucket.try_take(self.clock())
            if not ok:
                self._shed("rate_limit", bucket.refill_wait())
        remaining = deadline_s
        if remaining is None:
            remaining = deadline_remaining(self.clock)
        if remaining is not None and p95 > 0.0 and remaining < p95:
            self._shed("deadline", max(0.05, p95 - max(0.0, remaining)))

    def _shed(self, reason: str, retry_after: float) -> None:
        # seeded jitter de-synchronizes retry herds; deterministic because
        # the PRNG is seeded and decisions are made in request order
        retry_after *= 1.0 + 0.25 * self._rng.random()
        with self._lock:
            self.sheds += 1
        obs.SERVE_SHEDS.labels(reason=reason).inc()
        raise ShedError(reason, retry_after)


# ------------------------------------------------------------ HA coordinator --


class HAState:
    """The crash-consistency coordinator: WAL-ahead ingest, periodic
    compaction checkpoints, restore-or-build boot, and the bounded-staleness
    degraded-mode contract. One instance owns one --state-dir."""

    def __init__(self, state_dir: str, image: ResidentImage, wal: IngestWAL,
                 digest: str, checkpoint_every: int = 64,
                 staleness_ceiling_s: float = 120.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.state_dir = state_dir
        self.image = image
        self.wal = wal
        self.digest = digest
        self.checkpoint_every = max(1, int(checkpoint_every))
        self.staleness_ceiling_s = float(staleness_ceiling_s)
        self.clock = clock
        # reentrant: ingest holds it across its own compaction call, and a
        # background checkpoint() takes it fresh — either way WAL append
        # order == apply order == capture order
        self._mu = threading.RLock()
        self._degraded: Optional[str] = None
        self._last_ok = clock()
        self._consistent_epoch = image.epoch
        self.replayed = 0
        self.skipped = 0

    # --------------------------------------------------------------- boot ----

    @classmethod
    def open(cls, state_dir: str,
             build_image: Callable[[], Optional[ResidentImage]],
             checkpoint_every: int = 64, staleness_ceiling_s: float = 120.0,
             mesh=None,
             clock: Callable[[], float] = time.monotonic
             ) -> Optional["HAState"]:
        """Restore-or-build: load the checkpoint if one exists (its digest
        names the lineage), else build from live truth and mint the lineage
        digest from the boot state — a crashed-before-first-checkpoint WAL
        written from the same boot state carries the same digest and
        replays; any other WAL is refused. Returns None when the image
        build itself declines (serve then runs fresh-path only, exactly as
        without --state-dir)."""
        os.makedirs(state_dir, exist_ok=True)
        ckpt_path = os.path.join(state_dir, CHECKPOINT_NAME)
        if os.path.exists(ckpt_path):
            head, state = load_checkpoint(ckpt_path)
            image = restore_image(state, mesh=mesh)
            digest = head["digest"]
        else:
            image = build_image()
            if image is None:
                return None
            with image._lock:
                digest = lineage_digest(image.current_nodes(),
                                        image.cluster_pods())
        wal = IngestWAL.open(os.path.join(state_dir, WAL_NAME), digest)
        self = cls(state_dir, image, wal, digest,
                   checkpoint_every=checkpoint_every,
                   staleness_ceiling_s=staleness_ceiling_s, clock=clock)
        self._replay()
        return self

    def _replay(self) -> None:
        """Apply the WAL tail: records at-or-below the image's seq are the
        checkpoint's (or a duplicate's) — skipped; the record at seq+1
        applies; a gap means lost records and is refused loudly."""
        with self._mu:
            for seq, events in self.wal.records:
                if seq <= self.image.seq:
                    self.skipped += 1
                    obs.SERVE_WAL_OPS.labels(op="skip").inc()
                    continue
                if seq != self.image.seq + 1:
                    obs.SERVE_WAL_MISMATCHES.inc()
                    raise WalMismatch(
                        f"WAL replay gap: next record seq {seq}, image at "
                        f"{self.image.epoch} — records are missing; "
                        f"refusing to serve from doubted state")
                self.image.apply_events(events)
                self.replayed += 1
                obs.SERVE_WAL_OPS.labels(op="replay").inc()
            self._consistent_epoch = self.image.epoch

    # ------------------------------------------------------------- ingest ----

    def ingest(self, events: Sequence[dict]) -> dict:
        """WAL-ahead apply: fsync the record, then mutate the image. Any
        failure flips degraded mode; the image is never left half-applied
        (apply_events' own exception path rebuilds to consistency, and the
        follow-up checkpoint seals that truth so a later crash cannot
        replay the batch onto it twice)."""
        with self._mu:
            sc = scope.active()
            try:
                faults.maybe_fail("ingest_stall")
            except BaseException:
                self._enter_degraded("ingest_stall")
                raise
            seq = self.image.seq + 1
            try:
                if sc is not None:
                    with sc.span("ha_wal_append", cat="serve", seq=seq):
                        self.wal.append(seq, events)
                else:
                    self.wal.append(seq, events)
            except BaseException:
                self._enter_degraded("wal")
                raise
            try:
                resp = self.image.apply_events(events)
            except BaseException:
                # seq bumped, image rebuilt to consistency: seal that truth
                # so the WAL record (whose events only partially landed)
                # can never replay on top of it
                self._enter_degraded("ingest")
                try:
                    self.checkpoint()
                except BaseException:
                    # already degraded; count it, the ceiling flips healthz
                    obs.SERVE_CHECKPOINTS.labels(op="error").inc()
                raise
            self._mark_healthy()
            if len(self.wal.records) >= self.checkpoint_every:
                try:
                    self.checkpoint()
                except BaseException:
                    # the batch IS durable (WAL) and applied — failing the
                    # request here would make the client retry a landed
                    # delta as a NEW seq (double-apply). Report success,
                    # count the failure, flip degraded: the staleness
                    # ceiling bounds how long compaction may keep failing
                    # before /healthz says so.
                    obs.SERVE_CHECKPOINTS.labels(op="error").inc()
                    self._enter_degraded("checkpoint")
            return resp

    def checkpoint(self) -> None:
        """One compaction: snapshot the image, rotate the WAL. Callable from
        a background thread — takes the same locks in the same order as
        ingest, so a checkpoint racing a concurrent ingest serializes and
        can never capture a half-applied image (tests/test_ha.py races
        them)."""
        sc = scope.active()
        path = os.path.join(self.state_dir, CHECKPOINT_NAME)
        with self._mu:
            if sc is not None:
                with sc.span("ha_checkpoint", cat="serve"):
                    save_checkpoint(path, self.image, self.digest)
            else:
                save_checkpoint(path, self.image, self.digest)
            self.wal.rotate()

    def resync(self) -> None:
        """Explicit recovery: generation-bumping rebuild from current host
        truth (the image's own consistency escape hatch), then mark
        healthy. For operators whose ingest source came back after a long
        degraded stretch."""
        with self._mu:
            with self.image._lock:
                self.image._rebuild()
            self._mark_healthy()

    # ----------------------------------------------------- degraded mode -----

    def note_stall(self, reason: str) -> None:
        """Public hook for delta-source gaps (watch-sync 410 windows, feed
        outages): start the bounded-staleness clock without touching the
        image. The next successful ingest marks healthy again."""
        self._enter_degraded(reason)

    def _enter_degraded(self, reason: str) -> None:
        with self._mu:
            if self._degraded is None:
                self._degraded = reason
                obs.SERVE_DEGRADED.set(1.0)

    def _mark_healthy(self) -> None:
        # reentrant _mu: ingest/resync already hold it; the quarantine-clear
        # path (degraded_reason via a healthz probe) takes it fresh here
        with self._mu:
            self._degraded = None
            self._last_ok = self.clock()
            self._consistent_epoch = self.image.epoch
            obs.SERVE_DEGRADED.set(0.0)
            obs.SERVE_STALENESS.set(0.0)

    def degraded_reason(self) -> Optional[str]:
        """Current reason, folding in live backend quarantine (the image is
        stranded mid-rebuild: serving continues on the fresh/CPU path at
        the last consistent epoch)."""
        with self._mu:
            if self._degraded is None and guard.default_quarantined():
                self._enter_degraded("quarantine")
            elif (self._degraded == "quarantine"
                    and not guard.default_quarantined()):
                self._mark_healthy()
            return self._degraded

    def staleness_s(self) -> float:
        """Seconds serving at the last consistent epoch; 0.0 while healthy."""
        with self._mu:
            if self.degraded_reason() is None:
                return 0.0
            s = max(0.0, self.clock() - self._last_ok)
            obs.SERVE_STALENESS.set(s)
            return s

    def healthy(self) -> bool:
        """False once degraded staleness crosses the hard ceiling — the
        /healthz 503 flip: bounded staleness, not unbounded lying."""
        return self.staleness_s() <= self.staleness_ceiling_s

    def stamp(self, resp: dict) -> Dict[str, str]:
        """Stamp one answer with the staleness contract; returns the extra
        response headers. An epoch AHEAD of the image is impossible —
        counted (the MUST_BE_ZERO tripwire) and failed loudly rather than
        returned."""
        epoch = resp.get("epoch")
        if epoch is not None and self._epoch_ahead(str(epoch)):
            obs.SERVE_WRONG_EPOCH.inc()
            raise WrongEpochError(
                f"answer stamped epoch {epoch} but the image is at "
                f"{self.image.epoch}")
        resp["staleness_s"] = round(self.staleness_s(), 6)
        return {"X-Simon-Epoch": str(epoch if epoch is not None
                                     else self.image.epoch)}

    def _epoch_ahead(self, epoch: str) -> bool:
        try:
            gen, _, seq = epoch.partition(".")
            gen_i, seq_i = int(gen), int(seq)
        except ValueError:
            return True  # unparsable stamp: fail loudly, never guess
        img_gen, img_seq = self.image.generation, self.image.seq
        return gen_i > img_gen or (gen_i == img_gen and seq_i > img_seq)

    # -------------------------------------------------------------- stats ----

    def stats(self) -> Dict[str, object]:
        with self._mu:
            return {
                "epoch": self.image.epoch,
                "consistent_epoch": self._consistent_epoch,
                "degraded": self.degraded_reason(),
                "staleness_s": round(self.staleness_s(), 6),
                "staleness_ceiling_s": self.staleness_ceiling_s,
                "wal_records": len(self.wal.records),
                "replayed": self.replayed,
                "skipped": self.skipped,
                "state_dir": self.state_dir,
            }

    def close(self) -> None:
        with self._mu:
            self.wal.close()
