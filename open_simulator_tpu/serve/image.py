"""simonserve: the persistent device-resident cluster image.

The reference's server mode rebuilds and re-simulates the whole cluster from
scratch on every request (pkg/server/server.go:166,233). This module keeps ONE
encoded image of the live cluster resident on the device and current:

- **Stage once.** One Simulator owns the cluster; bound pods commit once; the
  node-side tables encode once and device_put once (sharded over the scenario
  mesh when >1 device is visible). The host keeps the carry SEEDS (small
  [N, *] / [T, D+1] arrays) — every what-if dispatch broadcasts them over its
  request lanes, so the image itself is never an input a dispatch could
  mutate.
- **Delta ingest, not re-encode.** Live watch events apply columnar deltas:
  a `pod_add`/`pod_delete` churn event touches the placed-pod registry and
  re-aggregates the carry seeds (zero device bytes move — the [G, N] tables
  are placed-independent by construction); a `node_add` extends the columnar
  NodeArrays in place (one node dict parsed, not 10k re-parsed) and re-derives
  the node-axis tables; a `node_drain` flips one bit in the live-node mask
  and evicts the node's pods from the seeds — no table bytes move at all.
- **Epoch counter.** Every applied event batch bumps `seq`; a from-scratch
  re-encode (an event the delta path cannot express) bumps `generation`.
  Sessions capture the epoch at build: a generation move invalidates their
  encoded group ids, and the service re-encodes them instead of dispatching
  a stale view — stale sessions are detected, not wrong.
- **Structurally non-donatable.** The image's device buffers are only ever
  passed as the `tables` head of a dispatch, which no kernel declares
  donation on (parallel/mesh.py donates argnum 1 — the per-request carry —
  exclusively); the simonaudit `image_leaf_aliased` census certifies that at
  compile time for every registered kernel, and `assert_image_alive` verifies
  after every serve dispatch that no buffer was consumed at runtime (the
  PR 9 zombie-write hazard applied to long-lived shared state).

Provable-equivalence gates (mirrors simulator/probe.py): the image declines
clusters with node-advertised images (ImageLocality divides by the total node
count), open-local storage, or gpu-share state (host-mirrored ledgers the
delta path does not replay); per-request gates route census-dependent
workloads (topology spread, live SelectorSpread, gpu/storage requests,
pre-bound pods) to the fresh-simulation path instead. Within those gates, a
masked-inactive node is exactly a pad_batch_tables phantom, so resident
probes are bit-identical to a fresh encode of the final cluster state —
tests/test_serve.py asserts it property-style over seeded event traces.
"""

from __future__ import annotations

import contextlib
import copy
import functools
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs import instruments as obs
from ..obs import scope
from ..ops.resources import CPU_I, MEM_I
from ..resilience import faults
from ..resilience import guard
from ..utils.objutil import name_of, namespaced_name as pod_key
from ..simulator.encode import (
    BatchTables,
    bucket_capped,
    build_node_axis_tables,
    build_pod_axis_tables,
    pad_batch_tables,
    pad_encoder_axes,
)

_jnp = None


def _jax():
    global _jnp
    if _jnp is None:
        import jax.numpy as jnp

        _jnp = jnp
    return _jnp


class StaleImageError(RuntimeError):
    """A session encoded against an image generation that no longer exists
    (the image re-encoded from scratch underneath it)."""


class ImageDonatedError(AssertionError):
    """A dispatch consumed (donated/deleted) a shared cluster-image buffer —
    the structurally-forbidden aliasing of long-lived state."""


class WhatIfSession:
    """One copy-on-write what-if overlay on a shared ResidentImage: the
    request's pods (encoded to group ids) and request-local node drains,
    captured at an image epoch. Sessions never mutate the image — the overlay
    is an active-mask row plus a per-lane valid mask plus (for drains) a
    privately adjusted seed copy, all assembled at dispatch time."""

    def __init__(self, image: "ResidentImage", pods,
                 drains: Sequence[str]) -> None:
        self.image = image
        # a columnar PodStore rides whole (its encode is one gather per
        # template); dict batches are snapshotted as before
        from ..simulator.store import is_pod_store

        self.pods = pods if is_pod_store(pods) else list(pods)
        self.drains = tuple(drains)
        self.generation = image.generation
        self.seq = image.seq
        self.batch = image.encode_request(pods)

    def ensure_current(self) -> None:
        """Re-encode after a generation move (group ids are only meaningful
        within one generation); seq moves are fine — dispatch always reads
        the image's CURRENT staged tables, and append-only interning keeps
        group ids valid across seq bumps."""
        if self.generation != self.image.generation:
            obs.SERVE_STALE_SESSIONS.inc()
            self.generation = self.image.generation
            self.seq = self.image.seq
            self.batch = self.image.encode_request(self.pods)

    def run(self) -> dict:
        """Probe this session alone (one lane). The micro-batching service
        (serve/batch.py) is the production path; this is the direct API —
        and it REFUSES a stale generation instead of silently re-encoding,
        so programmatic callers see staleness explicitly."""
        if self.generation != self.image.generation:
            raise StaleImageError(
                f"image re-encoded (generation {self.image.generation} != "
                f"session {self.generation}); rebuild the session")
        return self.image.dispatch_sessions([self])[0]


class ResidentImage:
    """Device-resident encoded cluster state + delta ingest. Build via
    try_build; None means an equivalence gate declined (serve then runs
    every request on the fresh-simulation path)."""

    def __init__(self) -> None:  # built via try_build only
        raise TypeError("use ResidentImage.try_build")

    # ------------------------------------------------------------- build ------

    @classmethod
    def try_build(cls, nodes: List[dict], cluster_objects=None,
                  pods: Sequence[dict] = (), sched_config=None,
                  mesh=None) -> Optional["ResidentImage"]:
        from ..simulator.engine import Simulator

        if guard.default_quarantined():
            return None  # the image commits device buffers to the default
            # backend; with it wedged, serve runs fresh probes on the fallback
        from ..simulator.store import NodeStore

        t0 = time.perf_counter()
        # a columnar NodeStore passes through whole (the engine adopts its
        # columns); list() would materialize N dicts just to hand them over
        sim = Simulator(nodes if isinstance(nodes, NodeStore) else list(nodes),
                        sched_config=sched_config, use_mesh=False)
        if cluster_objects is not None:
            sim.register_cluster_objects(cluster_objects)
        if sim.local_host.enabled or sim.gpu_host.enabled:
            return None  # host-mirrored storage/gpu ledgers: the delta path
            # does not replay reserve()/seed_pod() bookkeeping
        lazy_store = getattr(sim.na.nodes, "store", None)
        if lazy_store is not None:
            has_images = lazy_store.has_images
        else:
            has_images = any((n.get("status") or {}).get("images")
                             for n in sim.na.nodes)
        if has_images:
            return None  # ImageLocality divides by the TOTAL node count

        self = object.__new__(cls)
        self._sim = sim
        self._lock = threading.RLock()
        self.generation = 1
        # simonlint: ignore[race-unguarded-attr] -- construction: the instance
        # is not published until try_build returns; no concurrent reader yet
        self.seq = 0
        self._pod_index: Dict[str, Tuple[dict, int]] = {}
        self.drained: set = set()
        self._mesh = mesh if mesh is not None else self._auto_mesh()
        for pod in pods:  # simonlint: ignore[per-pod-host-loop] -- identity-keyed pod index: delta ingest removes pods BY dict, so staging materializes by design
            node_name = (pod.get("spec") or {}).get("nodeName")
            if not node_name:
                # unbound snapshot pods are request material, not cluster
                # state: the image's baseline is the BOUND set (callers
                # probing deploy-apps semantics include pending pods in
                # their request)
                continue
            ni = sim.na.index.get(node_name)
            if ni is None:
                sim.homeless.append(pod)
            else:
                sim._commit_pod(pod, ni, scheduled=False)
                self._pod_index[pod_key(pod)] = (pod, ni)
        self._restage(cause=None)
        self.build_s = time.perf_counter() - t0
        # simonscope pool attribution: registration is a WeakSet add (cheap,
        # leak-free); the runtime sampler only reads it when scope is on
        scope.register_pools(self)
        return self

    @staticmethod
    def _auto_mesh():
        """Scenario mesh over all visible devices (same OPEN_SIMULATOR_MESH
        override and quarantine rules as the probe session's)."""
        import os

        if os.environ.get("OPEN_SIMULATOR_MESH", "") in ("0", "false", "no"):
            return None
        if guard.default_quarantined():
            return None
        import jax

        n = len(jax.devices())
        if n <= 1:
            return None
        from ..parallel.mesh import make_scenario_mesh

        return make_scenario_mesh(n)

    # ------------------------------------------------------------ staging -----

    def _stage_sig(self) -> tuple:
        enc = self._sim.encoder
        return (len(enc.group_list), len(enc.counter_list),
                len(enc.carrier_list), len(enc.ports), self._sim.na.D,
                self._sim.na.N)

    def _restage(self, cause: Optional[str]) -> None:
        """Rebuild the host mirror and re-upload the device tables. `cause`
        None = initial build (uncounted)."""
        faults.maybe_fail("to_device")
        sim = self._sim
        bt_raw = BatchTables(
            **build_pod_axis_tables(sim.encoder, [], pad_to=8),
            **build_node_axis_tables(sim.encoder, sim.placed,
                                     sim.match_cache))
        btp = pad_batch_tables(pad_encoder_axes(bt_raw),
                               bucket_capped(sim.na.N, 1024))
        self._bt = btp
        self._n_pad = btp.alloc.shape[0]
        self._staged_sig = self._stage_sig()
        self._upload_tables(btp)
        self._set_seeds(btp)
        self._carry_devcache: Dict[int, object] = {}
        self._alloc = np.array(sim.na.alloc, np.float64)  # simonlint: ignore[dtype-drift] -- host-side envelope sums, mirrors probe_utilization
        active = np.zeros(self._n_pad, bool)
        active[:sim.na.N] = True
        for name in self.drained:
            ni = sim.na.index.get(name)
            if ni is not None:
                active[ni] = False
        self.active = active
        if cause is not None:
            obs.SERVE_RESTAGES.labels(cause=cause).inc()

    def _upload_tables(self, btp: BatchTables) -> None:
        from ..simulator.engine import batch_tables_nbytes
        from ..parallel.mesh import tables_from_batch

        obs.TRANSFER_BYTES.inc(batch_tables_nbytes(btp))
        if self._mesh is not None:
            import jax

            from ..parallel.mesh import fanout_shardings

            ts, self._carry_sh, self._active_sh = fanout_shardings(self._mesh)
            self._tables = type(ts)(*(
                jax.device_put(np.asarray(v), s)
                for v, s in zip(tables_from_batch(btp), ts)))
        else:
            jnp = _jax()
            from ..ops import kernels

            self._tables = kernels.Tables(
                *(jnp.asarray(v) for v in tables_from_batch(btp)))

    def _set_seeds(self, btp: BatchTables) -> None:
        self._seeds = (btp.seed_requested, btp.seed_nonzero,
                       btp.seed_port_used, btp.seed_counter, btp.seed_carrier,
                       btp.seed_dev_used, btp.seed_vg_req,
                       btp.seed_sdev_alloc)

    def _refresh_seeds(self) -> None:
        """Pod-churn refresh: the [G, N] tables are placed-independent by
        construction (build_node_axis_tables derives them from the encoder's
        group statics alone), so only the carry seeds re-aggregate from the
        placed registry — zero device bytes move."""
        sim = self._sim
        btp = pad_batch_tables(pad_encoder_axes(self._unpadded_bt()),
                               bucket_capped(sim.na.N, 1024))
        self._bt = btp
        self._set_seeds(btp)
        self._carry_devcache = {}
        obs.SERVE_SEED_REFRESHES.inc()

    def _unpadded_bt(self) -> BatchTables:
        sim = self._sim
        return BatchTables(
            **build_pod_axis_tables(sim.encoder, [], pad_to=8),
            **build_node_axis_tables(sim.encoder, sim.placed,
                                     sim.match_cache))

    def ensure_staged(self) -> None:
        """Re-upload the device tables when the encoder axes moved since the
        stage (a request interned a new group/counter/port — the staged
        [G, N] rows lack it). Warm serving (every group already interned)
        never lands here."""
        with self._lock:
            if self._stage_sig() != self._staged_sig:
                self._restage(cause="groups")

    # ---------------------------------------------------------- telemetry -----

    def device_pool_bytes(self) -> Dict[str, int]:
        """simonscope pool attribution: live device bytes owned by this
        image, by pool — the staged cluster tables vs. the cached per-lane
        base-seed carries. Holds the image lock only long enough to snapshot
        the leaf references; nbytes reads never block on device work."""
        with self._lock:
            tables = list(self._tables)
            carries = [leaf for c in self._carry_devcache.values()
                       for leaf in c]
        return {
            "image_tables": sum(int(getattr(v, "nbytes", 0) or 0)
                                for v in tables),
            "carry_cache": sum(int(getattr(v, "nbytes", 0) or 0)
                               for v in carries),
        }

    # -------------------------------------------------------------- epoch -----

    @property
    def epoch(self) -> str:
        # simonlint: ignore[race-unguarded-attr] -- epoch stamp: GIL-atomic
        # int read; racing apply_events yields the previous epoch, which is a
        # consistent published state
        return f"{self.generation}.{self.seq}"

    @property
    def n_nodes(self) -> int:
        """Live (non-drained) node count."""
        return int(self.active[:self._sim.na.N].sum())

    # ---------------------------------------------------------- sync view -----

    def has_pod(self, key: str) -> bool:
        """True when a committed pod with this "namespace/name" key is
        resident. Watch-sync presence dedup: a re-delivered pod_add for a
        resident key is a duplicate, not a new commit."""
        with self._lock:
            return key in self._pod_index

    def node_state(self, name: str) -> str:
        """"live" | "drained" | "absent" — the store's view of one node
        name, without materializing the node object."""
        with self._lock:
            ni = self._sim.na.index.get(name)
            if ni is None or ni >= self.active.shape[0]:
                return "absent"
            return "live" if bool(self.active[ni]) else "drained"

    def sync_snapshot(self) -> Tuple[Dict[str, Optional[str]], set]:
        """Columnar view for watch-sync relist reconciliation: a
        ({pod_key: node_name}, {live node names}) pair read straight off the
        index structures — no per-object dict materialization."""
        with self._lock:
            pods = {key: (pod.get("spec") or {}).get("nodeName")
                    for key, (pod, _) in self._pod_index.items()}
            na = self._sim.na
            nodes = {name for name, i in na.index.items()
                     if i < self.active.shape[0] and bool(self.active[i])}
            return pods, nodes

    # ------------------------------------------------------------- ingest -----

    def apply_events(self, events: Sequence[dict]) -> dict:
        """Apply one batch of live watch-event deltas; bumps the epoch once.
        Event kinds (each a dict with "type"):

        - pod_add:    {"pod": {... spec.nodeName set}} — a pod was scheduled
                      on the live cluster; commits into the seeds.
        - pod_delete: {"namespace": ..., "name": ...} — a pod left.
        - node_add:   {"node": {...}} — columnar NodeArrays extension + node
                      table re-derive + device re-stage.
        - node_drain: {"name": ...} — the node leaves the schedulable set;
                      its pods are evicted from the seeds (kube drain
                      semantics: the node AND its pods leave the cluster).

        Returns {"epoch", "applied", "skipped", "restaged"}. Events the
        delta path cannot express (unknown resource axes, duplicate node
        names) force a from-scratch re-encode (generation bump) rather than
        an approximation."""
        applied = skipped = 0
        with self._lock:
            seeds_dirty = False
            restage_cause: Optional[str] = None
            rebuild = False
            try:
                for ev in events:
                    kind = ev.get("type", "")
                    ok, sd, rc, rb = self._apply_one(kind, ev)
                    applied += 1 if ok else 0
                    skipped += 0 if ok else 1
                    seeds_dirty |= sd
                    rebuild |= rb
                    if rc:
                        restage_cause = rc
                    if ok:
                        obs.SERVE_INGEST_EVENTS.labels(kind=kind or "?").inc()
                self.seq += 1
                if rebuild:
                    self._rebuild()
                elif restage_cause is not None:
                    self._restage(cause=restage_cause)
                elif seeds_dirty:
                    self._refresh_seeds()
            except BaseException:
                # a mid-batch failure must not leave a half-applied image
                # (host state mutated, staged tables stale): re-encode from
                # the current host truth before propagating, so every later
                # request sees a consistent (if partially-ingested) cluster
                self.seq += 1
                self._rebuild()
                raise
            return {"epoch": self.epoch, "applied": applied,
                    "skipped": skipped,
                    "restaged": rebuild or restage_cause is not None}

    def _apply_one(self, kind: str, ev: dict):
        """(applied, seeds_dirty, restage_cause, rebuild)"""
        sim = self._sim
        if kind == "pod_add":
            pod = ev.get("pod") or {}
            node_name = (pod.get("spec") or {}).get("nodeName")
            ni = sim.na.index.get(node_name) if node_name else None
            if ni is None or not self.active[ni]:
                sim.homeless.append(pod)
                return False, False, None, False
            sim._commit_pod(pod, ni, scheduled=False)
            self._pod_index[pod_key(pod)] = (pod, ni)
            return True, True, None, False
        if kind == "pod_delete":
            key = ev.get("key") or f"{ev.get('namespace', 'default')}/{ev.get('name', '')}"
            got = self._pod_index.pop(key, None)
            if got is None:
                return False, False, None, False
            self._remove_pod(*got)
            return True, True, None, False
        if kind == "node_add":
            node = ev.get("node") or {}
            name = name_of(node)
            if not name or name in sim.na.index:
                return True, False, None, True  # duplicate/unnamed: rebuild
            alloc = ((node.get("status") or {}).get("allocatable") or {})
            if any(k not in sim.axis.names for k in alloc):
                return True, False, None, True  # new resource axis: rebuild
            self._extend_nodes([node])
            # keep the live mask current WITHIN the batch: a later event in
            # this same batch (pod_add onto / drain of the new node) must see
            # it live — _restage rebuilds the padded mask afterwards anyway
            ni = sim.na.index[name]
            if ni < self.active.shape[0]:
                self.active[ni] = True
            else:
                self.active = np.append(self.active, True)
            return True, False, "nodes", False
        if kind in ("node_drain", "node_delete"):
            name = ev.get("name", "")
            ni = sim.na.index.get(name)
            if ni is None or not self.active[ni]:
                return False, False, None, False
            self.active[ni] = False
            self.drained.add(name)
            for pod in list(sim.pods_on_node[ni]):
                self._pod_index.pop(pod_key(pod), None)
                self._remove_pod(pod, ni)
            return True, True, None, False
        return False, False, None, False

    def _remove_pod(self, pod: dict, node_i: int) -> None:
        sim = self._sim
        got = sim._sig_of.pop(id(pod), None)
        if got is None:
            return
        sig = got[0]
        pg = sim.placed.get(sig)
        if pg is not None:
            c = pg.node_counts.get(node_i, 0)
            if c <= 1:
                pg.node_counts.pop(node_i, None)
            else:
                pg.node_counts[node_i] = c - 1
        try:
            sim.pods_on_node[node_i].remove(pod)
        except ValueError:
            pass

    def _extend_nodes(self, nodes: List[dict]) -> None:
        """Delta node-add: extend the columnar node store in place and
        re-derive every group's node-axis statics; the following _restage
        rebuilds the [*, N] tables from them (the vectorized numpy half —
        the raw-dict parsing is paid for ONE node, not the cluster)."""
        sim = self._sim
        sim.na.extend(copy.deepcopy(nodes))
        sim.encoder.rebuild_group_axes()
        sim.pods_on_node.extend([] for _ in nodes)
        # per-(counter, sig) selector matches depend on pod templates only —
        # the cache stays valid across node growth (see rebuild_group_axes)

    def _rebuild(self) -> None:
        """From-scratch re-encode (generation bump): the delta path declined
        an event. Sessions from the old generation re-encode on next use."""
        from ..core.types import ResourceTypes
        from ..simulator.engine import Simulator

        old = self._sim
        nodes = [copy.deepcopy(n) for i, n in enumerate(old.na.nodes)
                 if self.active[i]]
        sim = Simulator(nodes, sched_config=old.sched_config,
                        use_mesh=False)
        rt = ResourceTypes(
            services=list(old.model.services),
            replication_controllers=list(old.model.replication_controllers),
            replica_sets=list(old.model.replica_sets),
            stateful_sets=list(old.model.stateful_sets),
            storage_classes=list(old.model.storage_classes),
            config_maps=list(old.model.config_maps),
            pod_disruption_budgets=list(old.model.pdbs),
            persistent_volume_claims=list(old.model.pvcs),
        )
        sim.register_cluster_objects(rt)
        self._sim = sim
        index: Dict[str, Tuple[dict, int]] = {}
        for key, (pod, _) in self._pod_index.items():
            ni = sim.na.index.get((pod.get("spec") or {}).get("nodeName"))
            if ni is None:
                sim.homeless.append(pod)
                continue
            sim._commit_pod(pod, ni, scheduled=False)
            index[key] = (pod, ni)
        self._pod_index = index
        self.drained = set()
        self.generation += 1
        self._restage(cause="rebuild")

    # ----------------------------------------------------------- requests -----

    def encode_request(self, pods: List[dict]) -> List[Tuple[int, int]]:
        """Pod-axis encode of one request against the shared encoder:
        (group_id, forced_node) per pod. Warm path (every signature already
        interned) is a dict hit per pod; a fresh group triggers ensure_staged
        at the next dispatch."""
        with self._lock:
            return self._sim.encode_batch_ids(pods)

    def session(self, pods,
                drains: Sequence[str] = ()) -> WhatIfSession:
        return WhatIfSession(self, pods, drains)

    def eligible(self, batch: List[Tuple[int, int]],
                 pods: List[dict]) -> Optional[str]:
        """None when the request can ride the resident micro-batched path;
        otherwise the gate name routing it to the fresh-simulation path.
        Census-dependent inputs (topology spread eligible-domain sets, live
        SelectorSpread) are computed over the node CENSUS at encode time, so
        a masked-inactive node is not equivalent to an absent one for them;
        gpu/storage groups carry host-mirrored state the image declines."""
        from ..simulator.store import is_pod_store

        if is_pod_store(pods):
            if pods.bound_mask() is not None:
                return "pre-bound pod"
        else:
            for pod in pods:  # simonlint: ignore[per-pod-host-loop] -- dict-request gate scan; PodStore requests take the bound_mask branch above
                if (pod.get("spec") or {}).get("nodeName"):
                    return "pre-bound pod"
        with self._lock:
            enc = self._sim.encoder
            for gi, _ in batch:  # simonlint: ignore[per-pod-host-loop] -- small request batches; the rows are already encoded ids
                if gi >= len(enc.group_list):
                    # the image re-encoded from scratch under the caller:
                    # conservative fresh routing (dispatch_sessions would
                    # re-encode, but the caller's gate answer must be safe)
                    return "stale image generation"
                g = enc.group_list[gi]
                if g.spread_dns or g.spread_sa:
                    return "topology spread (census-dependent eligible domains)"
                if g.ss_counter >= 0:
                    return "live SelectorSpread (census-dependent)"
                if g.gpu_mem > 0 or g.lvm_sizes or g.sdev_sizes:
                    return "gpu/local-storage request"
        return None

    def lane_overlay(self, session: WhatIfSession,
                     activate: Sequence[str] = ()):
        """One sweep lane's copy-on-write overlay: lane_inputs' (active row,
        seed copy) plus ACTIVATION of currently-drained nodes by name — the
        nodepool-mix family pre-encodes its pool nodes into the image (built
        drained) and each scenario lane flips k of them live. Activation
        never touches the seeds: a pool node has no pods, so its seed rows
        are zero by construction and a masked-live node is exactly a fresh
        encode's extra node."""
        active, seeds = self.lane_inputs(session)
        for name in activate:
            ni = self._sim.na.index.get(name)
            if ni is not None:
                active[ni] = True
        return active, seeds

    def lane_inputs(self, session: WhatIfSession):
        """(active_row [n_pad] bool, seeds tuple) for one session's overlay:
        the image's live mask minus the request's drains, and — when drains
        are present — a privately adjusted seed copy with the drained nodes'
        pods evicted (per-node rows zeroed, their counter/carrier domain
        contributions subtracted), so the lane is bit-equivalent to a fresh
        encode of the cluster without those nodes and their pods."""
        active = self.active.copy()
        if not session.drains:
            return active, self._seeds
        sim = self._sim
        drain_idx = []
        for name in session.drains:
            ni = sim.na.index.get(name)
            if ni is not None and active[ni]:
                active[ni] = False
                drain_idx.append(ni)
        if not drain_idx:
            return active, self._seeds
        (requested, nonzero, port_used, counter, carrier,
         dev_used, vg_req, sdev_alloc) = (v.copy() for v in self._seeds)
        requested[drain_idx] = 0.0
        nonzero[drain_idx] = 0.0
        port_used[drain_idx] = False
        bt = self._bt
        for pg in sim.placed.values():
            nis = [ni for ni in drain_idx if ni in pg.node_counts]
            if not nis:
                continue
            for ni in nis:
                cnt = float(pg.node_counts[ni])
                for t, cs in enumerate(sim.encoder.counter_list):
                    m = sim.match_cache.get((t, pg.sig))
                    if m is None:
                        m = sim.match_cache[(t, pg.sig)] = cs.matches_pod(pg.pod)
                    if m:
                        d = int(bt.counter_dom[t, ni])
                        if d < counter.shape[1] - 1:
                            counter[t, d] -= cnt
                for cid in pg.carrier_ids:
                    d = int(bt.carr_dom[cid, ni])
                    if d < carrier.shape[1] - 1:
                        carrier[cid, d] -= cnt
        return active, (requested, nonzero, port_used, counter, carrier,
                        dev_used, vg_req, sdev_alloc)

    # ----------------------------------------------------------- dispatch -----

    def check_backend(self) -> None:
        """Mirror of ProbeSession._check_backend: device-resident arrays are
        committed to the default backend; once it quarantines, refuse to
        touch them again (the service then routes requests to the fresh
        path, which the engine runs on the CPU fallback)."""
        if guard.default_quarantined():
            raise guard.BackendWedged("dispatch", guard.current_backend(),
                                      injected=False)

    def assert_image_alive(self) -> None:
        """Runtime half of the non-donation contract: no dispatch may have
        consumed a shared image buffer. A deleted leaf here means a donating
        executable took the tables head — the compile-time image_leaf_aliased
        audit census exists to make this unreachable."""
        for name, leaf in zip(type(self._tables)._fields, self._tables):
            if getattr(leaf, "is_deleted", None) is not None and leaf.is_deleted():
                raise ImageDonatedError(
                    f"shared cluster-image buffer '{name}' was consumed by a "
                    f"dispatch — image tables are structurally non-donatable")

    def dispatch_sessions(self, sessions: List[WhatIfSession]) -> List[dict]:
        """Micro-batched dispatch over the sessions; returns one response
        dict per session, in order. Sessions partition into the WAVE lane
        (uniform-replica requests — one group, no pin: one fused
        feasibility/score pass + top-k commit per lane via
        serve_wave_fanout, provably identical to the serial placements) and
        the SERIAL lane (mixed-pod requests — the union-batch
        serve_whatif_fanout scan). Callers (serve/batch.py) own eligibility;
        every session must be current (ensure_current) and non-empty."""
        with self._lock:
            # re-validate UNDER the lock: a rebuild-forcing ingest may have
            # swapped the generation between the caller's eligibility check
            # and here — gen-k group ids must never index gen-k+1 tables
            for s in sessions:
                s.ensure_current()
            self.ensure_staged()
            self.check_backend()
            wave: List[Tuple[int, WhatIfSession, tuple]] = []
            serial: List[Tuple[int, WhatIfSession]] = []
            for i, s in enumerate(sessions):
                route = self._wave_route(s)
                if route is not None:
                    wave.append((i, s, route))
                else:
                    serial.append((i, s))
            out: List[Optional[dict]] = [None] * len(sessions)
            lanes = len(sessions)
            if wave:
                for (i, _, _), resp in zip(
                        wave, self._dispatch_wave(
                            [s for _, s, _ in wave],
                            [r for _, _, r in wave], lanes)):
                    out[i] = resp
            if serial:
                for (i, _), resp in zip(
                        serial, self._dispatch_serial(
                            [s for _, s in serial], lanes)):
                    out[i] = resp
            self._xray_sessions(out)
            return out

    def _wave_route(self, session: WhatIfSession):
        """(g, m, cap1) when the whole request is m unpinned replicas of ONE
        wave-eligible group (the engine's own routing decides — counter-live
        or preferred-score-live groups stay on the exact serial scan)."""
        batch = session.batch
        g0, f0 = batch[0]
        if f0 >= 0 or any(b != (g0, -1) for b in batch):
            return None
        route = self._sim._wave_eligibility(g0)
        if route.kind != "wave" or route.gpu_live:
            return None
        return (g0, len(batch), route.cap1)

    def _lane_arrays(self, sessions: List[WhatIfSession],
                     activates: Optional[Sequence[Sequence[str]]] = None):
        """(S, active_s [S, n_pad], carry_np) — lane quantization (pow2,
        then the mesh shard multiple; surplus lanes repeat lane 0 and are
        sliced off) plus each lane's active overlay and seed copy. carry_np
        is None when every lane uses the UNMODIFIED base seeds (no drains) —
        the staging path then reuses the per-(epoch, S) device-resident
        carry instead of re-stacking and re-transferring it per dispatch.
        `activates` (aligned with sessions) routes through lane_overlay —
        the sweep runner's nodepool-activation lanes share this exact
        assembly (ONE home for the quantization + base-carry-cache logic,
        the area the PR 9 donation fix patched)."""
        S = 1
        while S < len(sessions):
            S *= 2
        if self._mesh is not None:
            from ..parallel.mesh import SCENARIO_AXIS

            S += (-S) % self._mesh.shape[SCENARIO_AXIS]
        active_s = np.zeros((S, self._n_pad), bool)
        lane_seeds = []
        all_base = True
        for li, s in enumerate(sessions):
            if activates is None:
                active, seeds = self.lane_inputs(s)
            else:
                active, seeds = self.lane_overlay(s, activates[li])
            active_s[li] = active
            lane_seeds.append(seeds)
            all_base &= seeds is self._seeds
        for li in range(len(sessions), S):
            active_s[li] = active_s[0]
            lane_seeds.append(lane_seeds[0])
        if all_base and self._carry_cacheable():
            return S, active_s, None
        carry_np = tuple(
            np.ascontiguousarray(
                np.stack([lane_seeds[li][k] for li in range(S)]))
            for k in range(len(lane_seeds[0])))
        return S, active_s, carry_np

    def _carry_cacheable(self) -> bool:
        """The input carry survives a dispatch only when the executable does
        not donate it: single-device module kernels never donate, and
        multi-device CPU meshes downgrade donation (donation_runtime_safe);
        an accelerator mesh donates, so its carries are never cached."""
        if self._mesh is None:
            return True
        from ..parallel.mesh import donation_runtime_safe

        return not donation_runtime_safe(self._mesh)

    def _base_carry(self, S: int):
        """Device-resident [S]-lane broadcast of the base seeds, cached per
        lane count and invalidated by every ingest/restage (the caller holds
        the image lock)."""
        got = self._carry_devcache.get(S)
        if got is not None:
            return got
        jnp = _jax()
        from ..ops import kernels

        carry_np = tuple(
            np.ascontiguousarray(np.broadcast_to(v, (S,) + v.shape))
            for v in self._seeds)
        if self._mesh is not None:
            import jax

            carry = kernels.Carry(*(
                jax.device_put(v, sh)
                for v, sh in zip(carry_np, self._carry_sh)))
        else:
            carry = kernels.Carry(*(jnp.asarray(v) for v in carry_np))
        self._carry_devcache[S] = carry
        return carry

    def _dims(self, S: int, **extra):
        sim, btp = self._sim, self._bt
        dims = {"S": S, "N": self._n_pad,
                "G": int(btp.static_mask.shape[0]),
                "T": int(btp.counter_dom.shape[0]),
                "mesh": self._mesh is not None,
                "cfg": f"{hash((sim.score_w, sim.filter_flags)) & 0xffffffff:08x}",
                **extra}
        if self._mesh is not None:
            from ..parallel.mesh import donation_runtime_safe

            dims["donate"] = donation_runtime_safe(self._mesh)
        return dims

    def _dispatch_wave(self, sessions: List[WhatIfSession], routes: List[tuple],
                       lanes: int) -> List[dict]:
        from ..ops import kernels

        S, active_s, carry_np = self._lane_arrays(sessions)
        g_s = np.zeros(S, np.int32)
        m_s = np.zeros(S, np.int32)
        cap1_s = np.zeros(S, bool)
        for li, (g, m, cap1) in enumerate(routes):
            g_s[li], m_s[li], cap1_s[li] = g, m, cap1
        g_s[len(routes):], m_s[len(routes):], cap1_s[len(routes):] = (
            g_s[0], m_s[0], cap1_s[0])
        max_m = int(m_s.max())
        block = kernels.wave_block_for(max_m, self._sim.na.N)
        kmax = kernels.wave_kmax(max_m, self._sim.na.N, block)
        obs.SERVE_BATCHES.inc()
        obs.SERVE_LANES.observe(len(sessions))
        obs.record_dispatch("serve_wave_fanout", zones=self._bt.n_zones,
                            block=block, k=kmax, **self._dims(S))
        placed_s, requested_s = guard.supervised(
            functools.partial(self._wave_round, carry_np, active_s, g_s, m_s,
                              cap1_s, block, kmax),
            site="dispatch", pods=max_m * S)
        self.assert_image_alive()
        return self._responses(sessions, [m for _, m, _ in routes], placed_s,
                               requested_s, active_s, lanes)

    def _dispatch_serial(self, sessions: List[WhatIfSession],
                         lanes: int) -> List[dict]:
        S, active_s, carry_np = self._lane_arrays(sessions)
        # union pod batch: each session's rows stay contiguous and in order
        union: List[Tuple[int, int]] = []
        spans: List[Tuple[int, int]] = []
        for s in sessions:
            spans.append((len(union), len(s.batch)))
            union.extend(s.batch)
        P = max(1, len(union))
        P_pad = bucket_capped(P, 2048)
        pod_group = np.zeros(P_pad, np.int32)
        forced_node = np.full(P_pad, -1, np.int32)
        for i, (g, f) in enumerate(union):
            pod_group[i] = g
            forced_node[i] = f
        valid_s = np.zeros((S, P_pad), bool)
        for li, (start, length) in enumerate(spans):
            valid_s[li, start:start + length] = True
        valid_s[len(sessions):] = valid_s[0]
        obs.SERVE_BATCHES.inc()
        obs.SERVE_LANES.observe(len(sessions))
        obs.record_dispatch("serve_whatif_fanout", zones=self._bt.n_zones,
                            P=P_pad, **self._dims(S))
        placed_s, requested_s = guard.supervised(
            functools.partial(self._serial_round, carry_np, active_s,
                              pod_group, forced_node, valid_s),
            site="dispatch", pods=P * S)
        self.assert_image_alive()
        return self._responses(sessions, [n for _, n in spans], placed_s,
                               requested_s, active_s, lanes)

    def _stage_lane_inputs(self, carry_np, active_s):
        """(kns, carry_s, active, ctx) — device staging for one fan-out
        round; runs inside the watchdog's worker thread (the mesh context is
        thread-local). carry_np None = all lanes ride the cached
        device-resident base-seed carry (_base_carry)."""
        jnp = _jax()
        from ..ops import kernels

        if self._mesh is not None:
            import jax

            from ..parallel.mesh import sharded_kernels

            kns = sharded_kernels(self._mesh, donate=True)
            if carry_np is None:
                carry_s = self._base_carry(active_s.shape[0])
            else:
                carry_s = kernels.Carry(*(
                    jax.device_put(v, sh)
                    for v, sh in zip(carry_np, self._carry_sh)))
            active = jax.device_put(active_s, self._active_sh)
            return kns, carry_s, active, self._mesh
        import contextlib

        if carry_np is None:
            carry_s = self._base_carry(active_s.shape[0])
        else:
            carry_s = kernels.Carry(*(jnp.asarray(v) for v in carry_np))
        return kernels, carry_s, jnp.asarray(active_s), contextlib.nullcontext()

    def _wave_round(self, carry_np, active_s, g_s, m_s, cap1_s, block, kmax):
        jnp = _jax()
        sim = self._sim
        sc = scope.active()
        kns, carry_s, active, ctx = self._stage_lane_inputs(carry_np, active_s)
        with ctx:
            faults.maybe_fail("dispatch")
            faults.maybe_fail("oom_dispatch")
            # phase marks + spans run on the watchdog WORKER thread: the
            # copied contextvars carry both the batcher's sink and the trace
            # ctx here, so the trace shows dispatch/fetch on the thread that
            # actually blocked on them
            scope.mark("kernel_begin")
            with (sc.span("kernel:serve_wave_fanout", cat="dispatch")
                  if sc is not None else contextlib.nullcontext()):
                carry_s, placed = kns.serve_wave_fanout(
                    self._tables, carry_s, active,
                    jnp.asarray(g_s), jnp.asarray(m_s), jnp.asarray(cap1_s),
                    w=sim.score_w, filters=sim.filter_flags, block=block,
                    kmax=kmax)
            scope.mark("kernel_end")
            faults.maybe_fail("fetch")
            with (sc.span("fetch:serve_wave_fanout", cat="dispatch")
                  if sc is not None else contextlib.nullcontext()):
                out = np.asarray(placed), np.asarray(carry_s.requested)
            scope.mark("fetch_end")
            return out

    def _serial_round(self, carry_np, active_s, pod_group, forced_node,
                      valid_s):
        jnp = _jax()
        sim, btp = self._sim, self._bt
        sc = scope.active()
        kns, carry_s, active, ctx = self._stage_lane_inputs(carry_np, active_s)
        with ctx:
            faults.maybe_fail("dispatch")
            faults.maybe_fail("oom_dispatch")
            scope.mark("kernel_begin")
            # enable_gpu/enable_storage pinned False: the image gates decline
            # gpu/storage clusters AND requests, so the inert subgraphs
            # compile away and an ineligible interned group can never flip
            # the staged flags (and the compiled signature) underneath us
            with (sc.span("kernel:serve_whatif_fanout", cat="dispatch")
                  if sc is not None else contextlib.nullcontext()):
                carry_s, placed = kns.serve_whatif_fanout(
                    self._tables, carry_s, active,
                    jnp.asarray(pod_group), jnp.asarray(forced_node),
                    jnp.asarray(valid_s),
                    n_zones=btp.n_zones, enable_gpu=False,
                    enable_storage=False,
                    w=sim.score_w, filters=sim.filter_flags)
            scope.mark("kernel_end")
            faults.maybe_fail("fetch")
            with (sc.span("fetch:serve_whatif_fanout", cat="dispatch")
                  if sc is not None else contextlib.nullcontext()):
                out = np.asarray(placed), np.asarray(carry_s.requested)
            scope.mark("fetch_end")
            return out

    def _responses(self, sessions, totals, placed_s, requested_s, active_s,
                   lanes: int) -> List[dict]:
        out = []
        for li, (s, total) in enumerate(zip(sessions, totals)):
            placed = int(placed_s[li])
            out.append({
                "scheduled": placed,
                "total": total,
                "unscheduled": total - placed,
                "utilization": self._utilization(active_s[li],
                                                 requested_s[li]),
                # simonlint: ignore[race-unguarded-attr] -- epoch stamp:
                # GIL-atomic int read, same contract as the epoch property
                "epoch": f"{s.generation}.{self.seq}",
                "lanes": lanes,
                "path": "batched",
            })
        return out

    def _utilization(self, active_row: np.ndarray,
                     requested_row: np.ndarray) -> Dict[str, float]:
        """probe_utilization's aggregate totals for one lane: f64 host sums
        over the lane's live nodes — masked rows (drained nodes, phantom
        padding) are excluded, so the compacted sequence equals the fresh
        encode's node order and the sums are bit-identical."""
        N = self._sim.na.N
        mask = active_row[:N]
        used = requested_row[:N][mask].astype(np.float64)  # simonlint: ignore[dtype-drift] -- host-side accumulator, mirrors probe_utilization
        alloc = self._alloc[:N][mask]
        return {
            "cpu_used": float(used[:, CPU_I].sum()),
            "cpu_alloc": float(alloc[:, CPU_I].sum()),
            "mem_used": float(used[:, MEM_I].sum()),
            "mem_alloc": float(alloc[:, MEM_I].sum()),
        }

    def _xray_sessions(self, responses: List[dict]) -> None:
        """simonxray ride-along: one probe record per micro-batched request
        (counts only — serve never materializes placements)."""
        from ..obs import xray

        run = xray.begin_run("serve")
        if run is None:
            return
        for r in responses:
            run.add_probe(r["scheduled"], r["total"])
        xray.commit_run(run, [guard.current_backend()])

    # ---------------------------------------------------------- slow path -----

    def current_nodes(self, extra_drains: Sequence[str] = (),
                      include: Sequence[str] = ()) -> List[dict]:
        """Deep copies of the live (non-drained) nodes, order preserved.
        `include` names currently-drained nodes to treat as live (the sweep
        nodepool activation overlay)."""
        skip = set(extra_drains)
        add = set(include)
        return [copy.deepcopy(n) for i, n in enumerate(self._sim.na.nodes)
                if (self.active[i] or name_of(n) in add)
                and name_of(n) not in skip]

    def cluster_pods(self, extra_drains: Sequence[str] = ()) -> List[dict]:
        """Deep copies of the committed (bound) pods on live nodes, in commit
        order — the prebound prefix a fresh probe replays."""
        skip = set(extra_drains)
        out = []
        for pod, ni in self._pod_index.values():
            if self.active[ni] and self._sim.na.names[ni] not in skip:
                out.append(copy.deepcopy(pod))
        return out

    def fresh_simulator(self, drains: Sequence[str] = (),
                        include: Sequence[str] = ()):
        """(sim, bound_pods, epoch): a fresh Simulator over the current live
        cluster state minus `drains` (and those nodes' pods) plus the named
        currently-drained nodes in `include` (sweep nodepool activation),
        with the image's cluster objects registered. `bound_pods` are deep
        copies of the committed pods in commit order — the prebound prefix
        the from-scratch oracle replays before the request. Shared by
        fresh_probe and the sweep runner's serial oracle."""
        from ..core.types import ResourceTypes
        from ..simulator.engine import Simulator

        with self._lock:
            nodes = self.current_nodes(drains, include)
            bound = self.cluster_pods(drains)
            model = self._sim.model
            rt = ResourceTypes(
                services=list(model.services),
                replication_controllers=list(model.replication_controllers),
                replica_sets=list(model.replica_sets),
                stateful_sets=list(model.stateful_sets),
                storage_classes=list(model.storage_classes),
                config_maps=list(model.config_maps),
                pod_disruption_budgets=list(model.pdbs),
                persistent_volume_claims=list(model.pvcs),
            )
            sched_config = self._sim.sched_config
            epoch = self.epoch
        sim = Simulator(nodes, sched_config=sched_config)
        sim.register_cluster_objects(rt)
        return sim, bound, epoch

    def fresh_probe(self, pods: List[dict],
                    drains: Sequence[str] = ()) -> dict:
        """The from-scratch oracle AND the fresh-path route: build a fresh
        Simulator over the current cluster state (minus request drains and
        those nodes' pods), replay the bound pods, probe the request. This
        is byte-for-byte what the resident path must reproduce — the parity
        suite compares the two on every seeded trace."""
        sim, bound, epoch = self.fresh_simulator(drains)
        request = [copy.deepcopy(p) for p in pods]
        scheduled, total = sim.probe_pods(bound + request)
        return {
            "scheduled": scheduled - len(bound),
            "total": total - len(bound),
            "unscheduled": total - scheduled,
            "utilization": sim.probe_utilization(),
            "epoch": epoch,
            "lanes": 1,
            "path": "fresh",
        }
