"""simonserve: resident what-if serving.

The production serving subsystem (ROADMAP item 3): a persistent
device-resident cluster image kept current by live watch-event deltas
(serve/image.py), copy-on-write what-if probe sessions per request, and a
cross-request micro-batching dispatcher that coalesces concurrent requests
onto the scenario axis of one serve_whatif_fanout dispatch (serve/batch.py).
Served over HTTP/gRPC as /v1/whatif (server/http.py, server/grpcbridge.py)
and from the `simon serve` CLI; benchmarked by tools/loadgen.py.

simonha (serve/ha.py) makes it crash-consistent: a write-ahead ingest log +
checkpoint/restore (`simon serve --state-dir`), bounded-queue admission
control with deadline-aware shedding, and a bounded-staleness degraded mode.
"""

from .image import (  # noqa: F401
    ImageDonatedError,
    ResidentImage,
    StaleImageError,
    WhatIfSession,
)
from .ha import (  # noqa: F401
    AdmissionController,
    HAState,
    IngestWAL,
    ShedError,
    WalMismatch,
    WrongEpochError,
    lineage_digest,
    load_checkpoint,
    restore_image,
    save_checkpoint,
)
from .batch import MAX_BATCHED_PODS, WhatIfService  # noqa: F401
