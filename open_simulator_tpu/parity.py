"""Placement-parity tooling: dump placements, compare two dumps.

BASELINE.md's quality metric is "placement-match-rate vs serial kube-scheduler
>= 99%". Pods of one workload are interchangeable (the reference's selectHost
tie-break is uniformly random among max-score nodes, generic_scheduler.go:188),
and the simulator's fake nodes get randomized names (NewFakeNode,
utils.go:903-915) — so the comparable unit is the COUNT of pods per
(namespace, workload, node), with new nodes normalized to their sorted
per-node placement profile rather than their random names.

A dump is JSON:
  {"placements": {"<ns>/<workload>|<node>": count, ...},
   "new_nodes": <int>, "unscheduled": {"<ns>/<workload>": count}}

match_rate(a, b) = sum over keys of min(a[k], b[k]) / max(total_a, total_b).
"""

from __future__ import annotations

import json
from typing import Dict, Tuple

from .core.types import SimulateResult
from .core import constants as C
from .utils.objutil import annotations_of, labels_of, name_of, namespace_of


def _workload_key(pod: dict) -> str:
    """Stable workload identity: strip the random suffix the controller
    expansion appends to generated names (utils.go's simpleNameGenerator)."""
    anns = annotations_of(pod)
    kind = anns.get(C.AnnoWorkloadKind) or "Pod"
    name = anns.get(C.AnnoWorkloadName) or name_of(pod)
    labs = labels_of(pod)
    app = labs.get("app") or labs.get("k8s-app")
    if kind in ("ReplicaSet", "Job") and app:
        # Deployment->synthetic RS and Job pods carry generated suffixes;
        # the app label is the stable identity
        name = app
    return f"{namespace_of(pod)}/{kind}/{name}"


def placement_dump(result: SimulateResult) -> dict:
    placements: Dict[str, int] = {}
    new_nodes = 0
    for ns in result.node_status:
        node_name = name_of(ns.node)
        # membership, not truthiness: the marker label's value is "" (NewFakeNode
        # sets an empty-valued simon/new-node label, utils.go:903-915)
        if C.LabelNewNode in (labels_of(ns.node) or {}):
            new_nodes += 1
            node_name = "<new>"  # random names; profile-compared below
        for pod in ns.pods:
            key = f"{_workload_key(pod)}|{node_name}"
            placements[key] = placements.get(key, 0) + 1
    unscheduled: Dict[str, int] = {}
    for up in result.unscheduled_pods:
        k = _workload_key(up.pod)
        unscheduled[k] = unscheduled.get(k, 0) + 1
    # per-new-node profiles, order-normalized
    profiles = []
    for ns in result.node_status:
        if C.LabelNewNode not in (labels_of(ns.node) or {}):
            continue
        cnt: Dict[str, int] = {}
        for pod in ns.pods:
            k = _workload_key(pod)
            cnt[k] = cnt.get(k, 0) + 1
        # lists, not tuples: dumps must survive a JSON round-trip unchanged
        profiles.append(sorted([k, v] for k, v in cnt.items()))
    profiles.sort()
    return {
        "placements": placements,
        "new_nodes": new_nodes,
        "new_node_profiles": profiles,
        "unscheduled": unscheduled,
    }


def match_rate(a: dict, b: dict) -> Tuple[float, dict]:
    """(rate, detail). Rate over aggregated (workload, node) placement counts;
    detail lists the disagreeing keys."""
    pa, pb = a.get("placements") or {}, b.get("placements") or {}
    if not pa and not pb:
        return 1.0, {}  # two empty dumps agree vacuously, not 0%
    keys = set(pa) | set(pb)
    agree = sum(min(pa.get(k, 0), pb.get(k, 0)) for k in keys)
    total = max(sum(pa.values()), sum(pb.values())) or 1
    detail = {
        k: (pa.get(k, 0), pb.get(k, 0))
        for k in sorted(keys)
        if pa.get(k, 0) != pb.get(k, 0)
    }
    return agree / total, detail


def load_dump(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def save_dump(dump: dict, path: str) -> None:
    with open(path, "w") as f:
        json.dump(dump, f, indent=1, sort_keys=True)


def cmd_parity(args) -> int:
    a, b = load_dump(args.dump_a), load_dump(args.dump_b)
    rate, detail = match_rate(a, b)
    print(f"placement match-rate: {rate:.4f}")
    if a.get("new_nodes") != b.get("new_nodes"):
        print(f"new nodes: {a.get('new_nodes')} vs {b.get('new_nodes')}")
    if detail and args.verbose:
        for k, (va, vb) in detail.items():
            print(f"  {k}: {va} vs {vb}")
    return 0 if rate >= args.threshold else 1
