"""Host-side (string-world) accessors and matchers over k8s object dicts.

These implement the exact matching semantics the vendored scheduler applies —
label selectors (k8s.io/apimachinery labels.SelectorFromSet / LabelSelectorAsSelector),
node-affinity terms (nodeaffinity filter), tolerations (v1helper.TolerationsTolerateTaint) —
used both for host-side pre-computation of per-group static node masks (see
simulator/encode.py) and by the DaemonSet controller simulation
(/root/reference/pkg/utils/utils.go:325-366).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

from .quantity import parse_milli, parse_quantity

# ------------------------------------------------------------------ metadata ----------


def meta(obj: dict) -> dict:
    return obj.get("metadata") or {}


def name_of(obj: dict) -> str:
    return meta(obj).get("name", "")


def namespace_of(obj: dict) -> str:
    return meta(obj).get("namespace") or "default"


def namespaced_name(obj: dict) -> str:
    return f"{namespace_of(obj)}/{name_of(obj)}"


def labels_of(obj: dict) -> Dict[str, str]:
    return meta(obj).get("labels") or {}


def annotations_of(obj: dict) -> Dict[str, str]:
    return meta(obj).get("annotations") or {}


def set_label(obj: dict, key: str, value: str) -> None:
    obj.setdefault("metadata", {}).setdefault("labels", {})[key] = value


def set_annotation(obj: dict, key: str, value: str) -> None:
    obj.setdefault("metadata", {}).setdefault("annotations", {})[key] = value


def owner_references(obj: dict) -> List[dict]:
    return meta(obj).get("ownerReferences") or []


def is_owned_by_kind(pod: dict, kind: str) -> bool:
    return any(ref.get("kind") == kind for ref in owner_references(pod))


# ----------------------------------------------------------- label selectors ----------


def match_expression(labels: Dict[str, str], expr: dict) -> bool:
    """One LabelSelectorRequirement / NodeSelectorRequirement against a label map.

    Operators per k8s: In, NotIn, Exists, DoesNotExist, Gt, Lt (Gt/Lt are node-only and
    compare integers).
    """
    key = expr.get("key", "")
    op = expr.get("operator", "In")
    values = expr.get("values") or []
    present = key in labels
    if op == "In":
        return present and labels[key] in values
    if op == "NotIn":
        return not present or labels[key] not in values
    if op == "Exists":
        return present
    if op == "DoesNotExist":
        return not present
    if op in ("Gt", "Lt"):
        if not present or len(values) != 1:
            return False
        try:
            lbl, val = int(labels[key]), int(values[0])
        except ValueError:
            return False
        return lbl > val if op == "Gt" else lbl < val
    return False


def match_label_selector(selector: Optional[dict], labels: Dict[str, str]) -> bool:
    """metav1.LabelSelector {matchLabels, matchExpressions} vs a label map.

    A nil selector matches nothing in k8s scheduling contexts (affinity terms with nil
    selector match no pods); an empty selector matches everything.
    """
    if selector is None:
        return False
    for k, v in (selector.get("matchLabels") or {}).items():
        if labels.get(k) != v:
            return False
    for expr in selector.get("matchExpressions") or []:
        if not match_expression(labels, expr):
            return False
    return True


def selector_from_set(match_labels: Dict[str, str], labels: Dict[str, str]) -> bool:
    """labels.SelectorFromSet — plain equality map (used by Services / RC)."""
    return all(labels.get(k) == v for k, v in match_labels.items())


# ------------------------------------------------------------- node affinity ----------


def match_node_selector_term(node: dict, term: dict) -> bool:
    """One NodeSelectorTerm (matchExpressions AND matchFields) against a node.

    An empty/nil term matches NO node (component-helpers nodeaffinity
    isEmptyNodeSelectorTerm); matchFields supports only metadata.name, as upstream does.
    """
    if not (term.get("matchExpressions") or term.get("matchFields")):
        return False
    labels = labels_of(node)
    for expr in term.get("matchExpressions") or []:
        if not match_expression(labels, expr):
            return False
    for expr in term.get("matchFields") or []:
        if expr.get("key") != "metadata.name":
            return False
        if not match_expression({"metadata.name": name_of(node)}, expr):
            return False
    return True


def match_node_selector(node: dict, node_selector: dict) -> bool:
    """v1.NodeSelector: nodeSelectorTerms are ORed; an empty term list matches nothing."""
    terms = node_selector.get("nodeSelectorTerms") or []
    return any(match_node_selector_term(node, t) for t in terms)


def pod_matches_node_affinity(pod: dict, node: dict) -> bool:
    """The NodeAffinity filter: spec.nodeSelector AND requiredDuringScheduling affinity.

    Mirrors vendored nodeaffinity.Filter semantics (plugins/nodeaffinity/node_affinity.go).
    """
    spec = pod.get("spec") or {}
    ns = spec.get("nodeSelector")
    if ns:
        if not all(labels_of(node).get(k) == v for k, v in ns.items()):
            return False
    affinity = (spec.get("affinity") or {}).get("nodeAffinity") or {}
    required = affinity.get("requiredDuringSchedulingIgnoredDuringExecution")
    if required:
        if not match_node_selector(node, required):
            return False
    return True


def preferred_node_affinity_score(pod: dict, node: dict) -> int:
    """Sum of matching preferredDuringScheduling term weights (nodeaffinity.Score)."""
    spec = pod.get("spec") or {}
    affinity = (spec.get("affinity") or {}).get("nodeAffinity") or {}
    total = 0
    for pref in affinity.get("preferredDuringSchedulingIgnoredDuringExecution") or []:
        weight = pref.get("weight", 0)
        term = pref.get("preference") or {}
        if match_node_selector_term(node, term):
            total += weight
    return total


# --------------------------------------------------------- taints/tolerations ----------


def node_taints(node: dict) -> List[dict]:
    return (node.get("spec") or {}).get("taints") or []


def pod_tolerations(pod: dict) -> List[dict]:
    return (pod.get("spec") or {}).get("tolerations") or []


def toleration_tolerates_taint(tol: dict, taint: dict) -> bool:
    """v1helper.TolerationsTolerateTaint single-pair check."""
    if tol.get("effect") and tol.get("effect") != taint.get("effect"):
        return False
    if tol.get("key") and tol.get("key") != taint.get("key"):
        return False
    op = tol.get("operator") or "Equal"
    if op == "Exists":
        return True
    return (tol.get("value") or "") == (taint.get("value") or "")


def find_untolerated_taint(node: dict, pod: dict, effects: Iterable[str]) -> Optional[dict]:
    """First taint (with effect in `effects`) no toleration tolerates; None if all tolerated."""
    tols = pod_tolerations(pod)
    for taint in node_taints(node):
        if taint.get("effect") not in effects:
            continue
        if not any(toleration_tolerates_taint(t, taint) for t in tols):
            return taint
    return None


def untolerated_prefer_no_schedule_count(node: dict, pod: dict) -> int:
    """TaintToleration score input: count of intolerable PreferNoSchedule taints."""
    tols = pod_tolerations(pod)
    cnt = 0
    for taint in node_taints(node):
        if taint.get("effect") != "PreferNoSchedule":
            continue
        if not any(toleration_tolerates_taint(t, taint) for t in tols):
            cnt += 1
    return cnt


# ------------------------------------------------------------- pod resources ----------

# Resource axis canonical names used across the tensor layer.
CPU = "cpu"
MEMORY = "memory"
EPHEMERAL = "ephemeral-storage"
PODS = "pods"


def _requests_of_container(c: dict) -> Dict[str, float]:
    out = {}
    for k, v in ((c.get("resources") or {}).get("requests") or {}).items():
        out[k] = parse_milli(v) if k == CPU else parse_quantity(v)
    return out


def pod_resource_requests(pod: dict) -> Dict[str, float]:
    """Effective pod requests: max(sum(containers), each initContainer) + overhead.

    Matches resourcehelper.PodRequestsAndLimits / scheduler's computePodResourceRequest.
    CPU is in MILLI-cores; everything else in base units.
    """
    spec = pod.get("spec") or {}
    total: Dict[str, float] = {}
    for c in spec.get("containers") or []:
        for k, v in _requests_of_container(c).items():
            total[k] = total.get(k, 0) + v
    for c in spec.get("initContainers") or []:
        for k, v in _requests_of_container(c).items():
            if v > total.get(k, 0):
                total[k] = v
    for k, v in (spec.get("overhead") or {}).items():
        q = parse_milli(v) if k == CPU else parse_quantity(v)
        total[k] = total.get(k, 0) + q
    return total


def node_allocatable(node: dict) -> Dict[str, float]:
    """status.allocatable → base units (cpu in milli). Falls back to capacity."""
    status = node.get("status") or {}
    alloc = status.get("allocatable") or status.get("capacity") or {}
    out: Dict[str, float] = {}
    for k, v in alloc.items():
        out[k] = parse_milli(v) if k == CPU else parse_quantity(v)
    return out


def pod_host_ports(pod: dict) -> List[tuple]:
    """(protocol, hostIP, hostPort) triples the NodePorts plugin checks.

    Only spec.containers are scanned (node_ports.go getContainerPorts ignores init
    containers). hostNetwork pods expose every containerPort as a host port (k8s
    defaulting sets hostPort = containerPort for hostNetwork pods).
    """
    spec = pod.get("spec") or {}
    host_net = bool(spec.get("hostNetwork"))
    out = []
    for c in spec.get("containers") or []:
        for p in c.get("ports") or []:
            hp = p.get("hostPort")
            if hp is None and host_net:
                hp = p.get("containerPort")
            if hp:
                out.append((p.get("protocol") or "TCP", p.get("hostIP") or "0.0.0.0", int(hp)))
    return out


def pod_is_bound(pod: dict) -> bool:
    return bool((pod.get("spec") or {}).get("nodeName"))


def pod_scheduler_name(pod: dict) -> str:
    return (pod.get("spec") or {}).get("schedulerName") or "default-scheduler"
