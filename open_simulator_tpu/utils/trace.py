"""utiltrace-style phase spans with LogIfLong thresholds.

The reference instruments Simulate with k8s.io/utils/trace spans — e.g.
`utiltrace.New("Simulate")` logged when a step exceeds 1s (pkg/simulator/
core.go:67-73) and the live-cluster fetch spinner at 100ms
(pkg/simulator/simulator.go:506-512). This is the same idea without the
vendored package, upgraded past it in three ways:

- **Nestable.** A Span entered while another is active (same thread /
  context) attaches to that parent as a child instead of registering as a
  sibling, via a contextvar — `recent_spans()` and the Chrome trace export
  (obs/chrome.py) show the hierarchy the way utiltrace's nestedSteps do.
- **Exception-safe.** A body that raises still records its partial step list
  and total, flagged `failed=True`, and the active-span stack unwinds
  correctly (the reference's trace.LogIfLong runs in a defer).
- **Collectable.** `start_collection()` retains every finished ROOT span
  (children ride along) beyond the 32-entry ring, for `--trace-out`'s
  Chrome trace-event dump.

Recent root spans are kept in a small ring so the server's /debug/vars
endpoint can expose them.
"""

from __future__ import annotations

import contextvars
import logging
import threading
import time
from collections import deque
from typing import Deque, List, Optional, Tuple

log = logging.getLogger("open_simulator_tpu.trace")

_RECENT: Deque["Span"] = deque(maxlen=32)
_LOCK = threading.Lock()
_COLLECTED: Optional[List["Span"]] = None  # None = collection off

# The active parent span of the current thread/context. contextvars give
# correct nesting per server-handler thread and per asyncio task alike.
_ACTIVE: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "open_simulator_tpu_active_span", default=None)


class Span:
    """One traced phase. Use as a context manager; `step(name)` marks interior
    progress like utiltrace's trace.Step. On exit, logs when total wall time
    exceeds `log_if_longer` seconds; nested use attaches to the enclosing
    Span instead of the ring."""

    def __init__(self, name: str, log_if_longer: float = 1.0) -> None:
        self.name = name
        self.threshold = log_if_longer
        self.steps: List[Tuple[str, float]] = []
        self.children: List["Span"] = []
        self.meta: dict = {}  # annotate(): JSON-able payloads carried into
        #                       /debug/vars and the Chrome trace event args
        #                       (simonxray attaches decision summaries here)
        self.failed = False
        self.t0 = 0.0       # perf_counter at __enter__ (shared clock for export)
        self.tid = 0        # thread id at __enter__
        self._last = 0.0
        self.total = 0.0
        self._token: Optional[contextvars.Token] = None

    def __enter__(self) -> "Span":
        self.t0 = self._last = time.perf_counter()
        self.tid = threading.get_ident()
        self._token = _ACTIVE.set(self)
        return self

    def step(self, name: str) -> None:
        now = time.perf_counter()
        self.steps.append((name, now - self._last))
        self._last = now

    def annotate(self, key: str, value) -> None:
        """Attach a JSON-able payload to this span (rendered as event args by
        the Chrome export and included in /debug/vars span dumps)."""
        self.meta[key] = value

    def __exit__(self, exc_type, exc, tb) -> None:
        self.total = time.perf_counter() - self.t0
        self.failed = exc_type is not None
        parent: Optional[Span] = None
        if self._token is not None:
            _ACTIVE.reset(self._token)
            self._token = None
            parent = _ACTIVE.get()
        logged = self.total >= self.threshold
        if logged:
            detail = "; ".join(f"{n}: {dt * 1000:.0f}ms" for n, dt in self.steps)
            log.warning("Trace %r %stook %.3fs (threshold %.3fs)%s",
                        self.name, "FAILED and " if self.failed else "",
                        self.total, self.threshold,
                        f" — {detail}" if detail else "")
        self.logged = logged
        if parent is not None and parent.tid == self.tid:
            # same-context nesting: ride the parent; a span whose parent lives
            # on another thread (executor handoff) registers as a root
            parent.children.append(self)
            return
        with _LOCK:
            _RECENT.append(self)
            if _COLLECTED is not None:
                _COLLECTED.append(self)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "seconds": round(self.total, 6),
            "logged": getattr(self, "logged", False),
            "failed": self.failed,
            **({"meta": self.meta} if self.meta else {}),
            "steps": [{"name": sn, "seconds": round(st, 6)}
                      for sn, st in self.steps],
            "children": [c.to_dict() for c in self.children],
        }


def current_span() -> Optional[Span]:
    """The innermost active Span of this context, or None."""
    return _ACTIVE.get()


def recent_spans() -> List[dict]:
    """Snapshot for /debug/vars: most recent ROOT spans first, children
    nested under their parents."""
    with _LOCK:
        items = list(_RECENT)
    return [sp.to_dict() for sp in reversed(items)]


def start_collection() -> None:
    """Begin retaining every finished root span (for --trace-out). Clears any
    previous collection."""
    global _COLLECTED
    with _LOCK:
        _COLLECTED = []


def stop_collection() -> List[Span]:
    """End collection and return the retained root spans, oldest first."""
    global _COLLECTED
    with _LOCK:
        out = _COLLECTED or []
        _COLLECTED = None
    return out


class Progress:
    """The schedulePods progress line (the reference renders a pterm progress
    bar per pod, simulator.go:311-321). On a tty: carriage-return updates,
    rate-limited. On a non-tty stream (log files, pipes): whole lines at 10%
    steps, so logs never fill with control-character frames."""

    def __init__(self, title: str, total: int, enabled: bool, stream=None) -> None:
        import sys

        self.title = title
        self.total = total
        self.done = 0
        self.enabled = enabled and total > 0
        self.stream = stream if stream is not None else sys.stderr
        try:
            self._tty = bool(self.stream.isatty())
        except (AttributeError, ValueError):
            self._tty = False
        self._last_render = 0.0
        self._last_pct = -1

    def advance(self, n: int) -> None:
        if not self.enabled:
            return
        self.done += n
        pct = int(self.done / self.total * 100)
        if self._tty:
            now = time.perf_counter()
            # rate-limit renders; always render the final state
            if self.done < self.total and now - self._last_render < 0.1:
                return
            self._last_render = now
            print(f"\r{self.title} {self.done}/{self.total} ({pct}%)",
                  end="", file=self.stream, flush=True)
        else:
            # one line per 10% step (and the final state), newline-terminated
            if pct // 10 == self._last_pct // 10 and self.done < self.total:
                return
            self._last_pct = pct
            print(f"{self.title} {self.done}/{self.total} ({pct}%)",
                  file=self.stream, flush=True)

    def close(self) -> None:
        if self.enabled and self.done and self._tty:
            print(file=self.stream, flush=True)
