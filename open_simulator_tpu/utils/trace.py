"""utiltrace-style phase spans with LogIfLong thresholds.

The reference instruments Simulate with k8s.io/utils/trace spans — e.g.
`utiltrace.New("Simulate")` logged when a step exceeds 1s (pkg/simulator/
core.go:67-73) and the live-cluster fetch spinner at 100ms
(pkg/simulator/simulator.go:506-512). This is the same idea without the
vendored package: nested steps, wall-clock per step, and a single log line
(via `logging`) when the span outlives its threshold. Recent spans are kept in
a small ring so the server's /debug/vars endpoint can expose them.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Deque, List, Tuple

log = logging.getLogger("open_simulator_tpu.trace")

# (name, total_seconds, [(step_name, seconds), ...], logged)
_RECENT: Deque[tuple] = deque(maxlen=32)
_LOCK = threading.Lock()


class Span:
    """One traced phase. Use as a context manager; `step(name)` marks interior
    progress like utiltrace's trace.Step. On exit, logs when total wall time
    exceeds `log_if_longer` seconds."""

    def __init__(self, name: str, log_if_longer: float = 1.0) -> None:
        self.name = name
        self.threshold = log_if_longer
        self.steps: List[Tuple[str, float]] = []
        self._t0 = 0.0
        self._last = 0.0
        self.total = 0.0

    def __enter__(self) -> "Span":
        self._t0 = self._last = time.perf_counter()
        return self

    def step(self, name: str) -> None:
        now = time.perf_counter()
        self.steps.append((name, now - self._last))
        self._last = now

    def __exit__(self, *exc) -> None:
        self.total = time.perf_counter() - self._t0
        logged = self.total >= self.threshold
        if logged:
            detail = "; ".join(f"{n}: {dt * 1000:.0f}ms" for n, dt in self.steps)
            log.warning("Trace %r took %.3fs (threshold %.3fs)%s",
                        self.name, self.total, self.threshold,
                        f" — {detail}" if detail else "")
        with _LOCK:
            _RECENT.append((self.name, self.total, list(self.steps), logged))


def recent_spans() -> List[dict]:
    """Snapshot for /debug/vars: most recent first."""
    with _LOCK:
        items = list(_RECENT)
    return [
        {"name": n, "seconds": round(t, 6), "logged": lg,
         "steps": [{"name": sn, "seconds": round(st, 6)} for sn, st in steps]}
        for n, t, steps, lg in reversed(items)
    ]


class Progress:
    """The schedulePods progress line (the reference renders a pterm progress
    bar per pod, simulator.go:311-321). On a tty: carriage-return updates,
    rate-limited. On a non-tty stream (log files, pipes): whole lines at 10%
    steps, so logs never fill with control-character frames."""

    def __init__(self, title: str, total: int, enabled: bool, stream=None) -> None:
        import sys

        self.title = title
        self.total = total
        self.done = 0
        self.enabled = enabled and total > 0
        self.stream = stream if stream is not None else sys.stderr
        try:
            self._tty = bool(self.stream.isatty())
        except (AttributeError, ValueError):
            self._tty = False
        self._last_render = 0.0
        self._last_pct = -1

    def advance(self, n: int) -> None:
        if not self.enabled:
            return
        self.done += n
        pct = int(self.done / self.total * 100)
        if self._tty:
            now = time.perf_counter()
            # rate-limit renders; always render the final state
            if self.done < self.total and now - self._last_render < 0.1:
                return
            self._last_render = now
            print(f"\r{self.title} {self.done}/{self.total} ({pct}%)",
                  end="", file=self.stream, flush=True)
        else:
            # one line per 10% step (and the final state), newline-terminated
            if pct // 10 == self._last_pct // 10 and self.done < self.total:
                return
            self._last_pct = pct
            print(f"{self.title} {self.done}/{self.total} ({pct}%)",
                  file=self.stream, flush=True)

    def close(self) -> None:
        if self.enabled and self.done and self._tty:
            print(file=self.stream, flush=True)
