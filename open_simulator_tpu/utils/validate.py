"""Object validation for generated pods/nodes.

The reference runs full k8s apimachinery validation on every generated object
(/root/reference/pkg/utils/utils.go:495-508 ValidatePod → validation.ValidatePodCreate,
utils.go:625-645 ValidateNode). This module reimplements the checks that can
actually fire on simulator inputs: DNS-1123 names and namespaces, label
key/value syntax, required fields, non-negative resource quantities, requests
≤ limits, container port ranges + per-pod hostPort uniqueness, toleration
operator/effect combinations, volume name uniqueness, topology-spread
constraint shape, node-selector requirement operators, and known
restart/DNS policies.
"""

from __future__ import annotations

import re
from typing import List

from .quantity import InvalidQuantity, parse_decimal

_DNS1123_SUBDOMAIN = re.compile(r"^[a-z0-9]([-a-z0-9.]*[a-z0-9])?$")
_DNS1123_LABEL = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")
_QUALIFIED_NAME = re.compile(r"^[A-Za-z0-9]([-A-Za-z0-9_.]*[A-Za-z0-9])?$")
_LABEL_VALUE = re.compile(r"^([A-Za-z0-9]([-A-Za-z0-9_.]*[A-Za-z0-9])?)?$")

_TOLERATION_OPS = ("", "Exists", "Equal")
_TAINT_EFFECTS = ("", "NoSchedule", "PreferNoSchedule", "NoExecute")
_SELECTOR_OPS = ("In", "NotIn", "Exists", "DoesNotExist", "Gt", "Lt")


class ValidationError(ValueError):
    pass


def _err(errs: List[str], msg: str) -> None:
    errs.append(msg)


def validate_name(name: str, errs: List[str], what: str) -> None:
    if not name:
        _err(errs, f"{what}: name is required")
    elif len(name) > 253 or not _DNS1123_SUBDOMAIN.match(name):
        _err(errs, f"{what}: invalid DNS-1123 name {name!r}")


def _validate_resources(res: dict, errs: List[str], where: str) -> None:
    requests = (res or {}).get("requests") or {}
    limits = (res or {}).get("limits") or {}
    for bucket_name, bucket in (("requests", requests), ("limits", limits)):
        for k, v in bucket.items():
            try:
                q = parse_decimal(v)
            except InvalidQuantity as e:
                _err(errs, f"{where}.{bucket_name}[{k}]: {e}")
                continue
            if q < 0:
                _err(errs, f"{where}.{bucket_name}[{k}]: must be non-negative")
    for k, v in requests.items():
        if k in limits:
            try:
                if parse_decimal(v) > parse_decimal(limits[k]):
                    _err(errs, f"{where}: request of {k} exceeds limit")
            except InvalidQuantity:
                pass


def _validate_labels(labels: dict, errs: List[str], where: str) -> None:
    """metav1.validation.ValidateLabels: qualified-name keys (optional
    DNS-subdomain prefix), 63-char label-value syntax."""
    for k, v in (labels or {}).items():
        prefix, _, name = str(k).rpartition("/")
        if prefix and (len(prefix) > 253 or not _DNS1123_SUBDOMAIN.match(prefix)):
            _err(errs, f"{where}: invalid label key prefix {prefix!r}")
        if not name or len(name) > 63 or not _QUALIFIED_NAME.match(name):
            _err(errs, f"{where}: invalid label key {k!r}")
        if len(str(v)) > 63 or not _LABEL_VALUE.match(str(v)):
            _err(errs, f"{where}: invalid label value {v!r} for key {k!r}")


def _validate_ports(containers: List[dict], errs: List[str]) -> None:
    """validateContainerPorts + AccumulateUniqueHostPorts: port ranges and
    per-pod (hostPort, protocol, hostIP) uniqueness."""
    seen_host = set()
    for c in containers:
        cname = c.get("name", "")
        for p in c.get("ports") or []:
            cp = p.get("containerPort")
            if not isinstance(cp, int) or not 0 < cp <= 65535:
                _err(errs, f"container {cname}: invalid containerPort {cp!r}")
            hp = p.get("hostPort")
            if hp is not None:
                if not isinstance(hp, int) or not 0 < hp <= 65535:
                    _err(errs, f"container {cname}: invalid hostPort {hp!r}")
                else:
                    key = (hp, p.get("protocol") or "TCP", p.get("hostIP") or "")
                    if key in seen_host:
                        _err(errs, f"container {cname}: duplicate hostPort {key}")
                    seen_host.add(key)
            proto = p.get("protocol")
            if proto and proto not in ("TCP", "UDP", "SCTP"):
                _err(errs, f"container {cname}: invalid protocol {proto!r}")


def _validate_tolerations(tolerations: List[dict], errs: List[str]) -> None:
    """validateTolerations: operator/value combinations and known effects."""
    for t in tolerations or []:
        op = t.get("operator") or ""
        if op not in _TOLERATION_OPS:
            _err(errs, f"toleration: invalid operator {op!r}")
        if op == "Exists" and t.get("value"):
            _err(errs, "toleration: value must be empty with operator Exists")
        if not t.get("key") and op not in ("", "Exists"):
            _err(errs, "toleration: empty key requires operator Exists")
        eff = t.get("effect") or ""
        if eff not in _TAINT_EFFECTS:
            _err(errs, f"toleration: invalid effect {eff!r}")


def _validate_selector_terms(affinity: dict, errs: List[str]) -> None:
    """ValidateNodeSelectorRequirement over every node-affinity term."""
    na = (affinity or {}).get("nodeAffinity") or {}
    terms = ((na.get("requiredDuringSchedulingIgnoredDuringExecution") or {})
             .get("nodeSelectorTerms") or [])
    terms = list(terms) + [
        p.get("preference") or {}
        for p in na.get("preferredDuringSchedulingIgnoredDuringExecution") or []
    ]
    for term in terms:
        for req in (term.get("matchExpressions") or []) + (term.get("matchFields") or []):
            op = req.get("operator", "")
            vals = req.get("values") or []
            if op not in _SELECTOR_OPS:
                _err(errs, f"affinity: invalid operator {op!r}")
            elif op in ("In", "NotIn") and not vals:
                _err(errs, f"affinity: operator {op} requires values")
            elif op in ("Exists", "DoesNotExist") and vals:
                _err(errs, f"affinity: operator {op} forbids values")
            elif op in ("Gt", "Lt") and len(vals) != 1:
                _err(errs, f"affinity: operator {op} requires exactly one value")


def _validate_spread(constraints: List[dict], errs: List[str]) -> None:
    """validateTopologySpreadConstraints: positive maxSkew, topologyKey
    required, known whenUnsatisfiable."""
    for c in constraints or []:
        ms = c.get("maxSkew")
        if not isinstance(ms, int) or ms <= 0:
            _err(errs, f"topologySpreadConstraint: maxSkew must be > 0, got {ms!r}")
        if not c.get("topologyKey"):
            _err(errs, "topologySpreadConstraint: topologyKey is required")
        wu = c.get("whenUnsatisfiable", "DoNotSchedule")
        if wu not in ("DoNotSchedule", "ScheduleAnyway"):
            _err(errs, f"topologySpreadConstraint: invalid whenUnsatisfiable {wu!r}")


def validate_pod(pod: dict) -> None:
    """Raise ValidationError listing every problem found (mirrors ValidatePod)."""
    errs: List[str] = []
    md = pod.get("metadata") or {}
    validate_name(md.get("name", ""), errs, "pod")
    ns = md.get("namespace")
    if ns and (len(ns) > 63 or not _DNS1123_LABEL.match(ns)):
        _err(errs, f"pod: invalid namespace {ns!r}")
    _validate_labels(md.get("labels") or {}, errs, "pod")
    spec = pod.get("spec") or {}
    containers = spec.get("containers") or []
    if not containers:
        _err(errs, "pod: spec.containers is required")
    seen = set()
    # name uniqueness is required across containers AND initContainers (ValidatePodCreate)
    for c in containers + (spec.get("initContainers") or []):
        cname = c.get("name", "")
        if not cname or not _DNS1123_LABEL.match(cname):
            _err(errs, f"container: invalid name {cname!r}")
        if not c.get("image"):
            _err(errs, f"container {cname}: image is required")
        _validate_resources(c.get("resources") or {}, errs, f"container {cname}")
        if cname in seen:
            _err(errs, f"container: duplicate name {cname!r}")
        seen.add(cname)
    _validate_ports(containers + (spec.get("initContainers") or []), errs)
    _validate_tolerations(spec.get("tolerations") or [], errs)
    _validate_selector_terms(spec.get("affinity") or {}, errs)
    _validate_spread(spec.get("topologySpreadConstraints") or [], errs)
    seen_vols = set()
    for v in spec.get("volumes") or []:
        vn = v.get("name", "")
        if not vn or len(vn) > 63 or not _DNS1123_LABEL.match(vn):
            _err(errs, f"volume: invalid name {vn!r}")
        if vn in seen_vols:
            _err(errs, f"volume: duplicate name {vn!r}")
        seen_vols.add(vn)
    rp = spec.get("restartPolicy")
    if rp and rp not in ("Always", "OnFailure", "Never"):
        _err(errs, f"pod: invalid restartPolicy {rp!r}")
    dp = spec.get("dnsPolicy")
    if dp and dp not in ("ClusterFirst", "ClusterFirstWithHostNet", "Default", "None"):
        _err(errs, f"pod: invalid dnsPolicy {dp!r}")
    if errs:
        raise ValidationError("invalid pod: " + "; ".join(errs))


def validate_node(node: dict) -> None:
    """Mirrors ValidateNode: name + non-negative capacity/allocatable quantities."""
    errs: List[str] = []
    md = node.get("metadata") or {}
    validate_name(md.get("name", ""), errs, "node")
    _validate_labels(md.get("labels") or {}, errs, "node")
    for t in (node.get("spec") or {}).get("taints") or []:
        if not t.get("key"):
            _err(errs, "node taint: key is required")
        if t.get("effect") not in ("NoSchedule", "PreferNoSchedule", "NoExecute"):
            _err(errs, f"node taint: invalid effect {t.get('effect')!r}")
    status = node.get("status") or {}
    for bucket_name in ("capacity", "allocatable"):
        for k, v in (status.get(bucket_name) or {}).items():
            try:
                if parse_decimal(v) < 0:
                    _err(errs, f"node.{bucket_name}[{k}]: must be non-negative")
            except InvalidQuantity as e:
                _err(errs, f"node.{bucket_name}[{k}]: {e}")
    if errs:
        raise ValidationError("invalid node: " + "; ".join(errs))
