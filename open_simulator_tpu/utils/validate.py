"""Validation-lite for generated pods/nodes.

The reference runs full k8s apimachinery validation on every generated object
(/root/reference/pkg/utils/utils.go:495-508 ValidatePod → validation.ValidatePodCreate,
utils.go:625-645 ValidateNode). We reimplement the checks that can actually fire on
simulator inputs: DNS-1123 names, required fields, non-negative resource quantities,
resource requests ≤ limits, known restart/DNS policies.
"""

from __future__ import annotations

import re
from typing import List

from .quantity import InvalidQuantity, parse_decimal

_DNS1123_SUBDOMAIN = re.compile(r"^[a-z0-9]([-a-z0-9.]*[a-z0-9])?$")
_DNS1123_LABEL = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")


class ValidationError(ValueError):
    pass


def _err(errs: List[str], msg: str) -> None:
    errs.append(msg)


def validate_name(name: str, errs: List[str], what: str) -> None:
    if not name:
        _err(errs, f"{what}: name is required")
    elif len(name) > 253 or not _DNS1123_SUBDOMAIN.match(name):
        _err(errs, f"{what}: invalid DNS-1123 name {name!r}")


def _validate_resources(res: dict, errs: List[str], where: str) -> None:
    requests = (res or {}).get("requests") or {}
    limits = (res or {}).get("limits") or {}
    for bucket_name, bucket in (("requests", requests), ("limits", limits)):
        for k, v in bucket.items():
            try:
                q = parse_decimal(v)
            except InvalidQuantity as e:
                _err(errs, f"{where}.{bucket_name}[{k}]: {e}")
                continue
            if q < 0:
                _err(errs, f"{where}.{bucket_name}[{k}]: must be non-negative")
    for k, v in requests.items():
        if k in limits:
            try:
                if parse_decimal(v) > parse_decimal(limits[k]):
                    _err(errs, f"{where}: request of {k} exceeds limit")
            except InvalidQuantity:
                pass


def validate_pod(pod: dict) -> None:
    """Raise ValidationError listing every problem found (mirrors ValidatePod)."""
    errs: List[str] = []
    validate_name((pod.get("metadata") or {}).get("name", ""), errs, "pod")
    spec = pod.get("spec") or {}
    containers = spec.get("containers") or []
    if not containers:
        _err(errs, "pod: spec.containers is required")
    seen = set()
    # name uniqueness is required across containers AND initContainers (ValidatePodCreate)
    for c in containers + (spec.get("initContainers") or []):
        cname = c.get("name", "")
        if not cname or not _DNS1123_LABEL.match(cname):
            _err(errs, f"container: invalid name {cname!r}")
        if not c.get("image"):
            _err(errs, f"container {cname}: image is required")
        _validate_resources(c.get("resources") or {}, errs, f"container {cname}")
        if cname in seen:
            _err(errs, f"container: duplicate name {cname!r}")
        seen.add(cname)
    rp = spec.get("restartPolicy")
    if rp and rp not in ("Always", "OnFailure", "Never"):
        _err(errs, f"pod: invalid restartPolicy {rp!r}")
    dp = spec.get("dnsPolicy")
    if dp and dp not in ("ClusterFirst", "ClusterFirstWithHostNet", "Default", "None"):
        _err(errs, f"pod: invalid dnsPolicy {dp!r}")
    if errs:
        raise ValidationError("invalid pod: " + "; ".join(errs))


def validate_node(node: dict) -> None:
    """Mirrors ValidateNode: name + non-negative capacity/allocatable quantities."""
    errs: List[str] = []
    validate_name((node.get("metadata") or {}).get("name", ""), errs, "node")
    status = node.get("status") or {}
    for bucket_name in ("capacity", "allocatable"):
        for k, v in (status.get(bucket_name) or {}).items():
            try:
                if parse_decimal(v) < 0:
                    _err(errs, f"node.{bucket_name}[{k}]: must be non-negative")
            except InvalidQuantity as e:
                _err(errs, f"node.{bucket_name}[{k}]: {e}")
    if errs:
        raise ValidationError("invalid node: " + "; ".join(errs))
