"""String interning: the bridge between k8s's string world and the tensor world.

Every label key/value, taint triple, port, topology key, namespace etc. is interned to a
dense int32 id on the host; device-side kernels see only integer tables. Interning must be
total over any expression appearing in inputs (SURVEY.md §7 "String-world ↔ tensor-world
boundary").

Id 0 is reserved as "absent" in all tables built from a StringTable, so dense lookup
matrices can use 0-fill for missing keys.
"""

from __future__ import annotations

from typing import Dict, Hashable, List


class StringTable:
    """Monotone intern table; id 0 is reserved for ABSENT."""

    ABSENT = 0

    def __init__(self) -> None:
        self._ids: Dict[Hashable, int] = {}
        self._items: List[Hashable] = [None]  # index 0 = absent

    def intern(self, item: Hashable) -> int:
        i = self._ids.get(item)
        if i is None:
            i = len(self._items)
            self._ids[item] = i
            self._items.append(item)
        return i

    def lookup(self, item: Hashable) -> int:
        """Id of item, or ABSENT if never interned."""
        return self._ids.get(item, self.ABSENT)

    def value(self, idx: int) -> Hashable:
        return self._items[idx]

    def __len__(self) -> int:
        return len(self._items)

    def __contains__(self, item: Hashable) -> bool:
        return item in self._ids
