"""Synthetic cluster/workload generators for benchmarks and harness dry-runs.

Shapes mirror BASELINE.md's configs (1k nodes / 10k nginx replicas; hard-predicate
stress with taints + affinities) without copying any reference fixture files.
"""

from __future__ import annotations

from typing import List, Optional, Tuple


def synth_node(
    i: int,
    cpu_milli: int = 32000,
    mem_bytes: int = 128 << 30,
    pods: int = 256,
    n_zones: int = 0,
    taint_every: int = 0,
) -> dict:
    name = f"node-{i:05d}"
    labels = {"kubernetes.io/hostname": name, "node-index": str(i)}
    if n_zones:
        labels["topology.kubernetes.io/zone"] = f"zone-{i % n_zones}"
    alloc = {"cpu": f"{cpu_milli}m", "memory": str(mem_bytes), "pods": str(pods)}
    node = {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {"name": name, "labels": labels},
        "spec": {},
        "status": {"allocatable": dict(alloc), "capacity": dict(alloc)},
    }
    if taint_every and i % taint_every == 0:
        node["spec"]["taints"] = [
            {"key": "synth/dedicated", "value": "batch", "effect": "NoSchedule"}
        ]
    return node


def synth_pod(
    i: int,
    cpu_milli: int = 100,
    mem_bytes: int = 256 << 20,
    labels: Optional[dict] = None,
    tolerate: bool = False,
    anti_affinity_on: Optional[str] = None,
    spread_zone: bool = False,
) -> dict:
    spec: dict = {
        "containers": [
            {
                "name": "app",
                "image": "nginx:1.25",
                "resources": {
                    "requests": {"cpu": f"{cpu_milli}m", "memory": str(mem_bytes)}
                },
            }
        ]
    }
    lbl = {"app": "synth", **(labels or {})}
    if tolerate:
        spec["tolerations"] = [
            {"key": "synth/dedicated", "operator": "Equal", "value": "batch",
             "effect": "NoSchedule"}
        ]
    if anti_affinity_on:
        spec["affinity"] = {
            "podAntiAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [
                    {
                        "labelSelector": {"matchLabels": {"app": anti_affinity_on}},
                        "topologyKey": "kubernetes.io/hostname",
                    }
                ]
            }
        }
    if spread_zone:
        spec["topologySpreadConstraints"] = [
            {
                "maxSkew": 2,
                "topologyKey": "topology.kubernetes.io/zone",
                "whenUnsatisfiable": "DoNotSchedule",
                "labelSelector": {"matchLabels": {"app": "synth"}},
            }
        ]
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": f"pod-{i:06d}", "namespace": "default", "labels": lbl},
        "spec": spec,
    }


def synth_cluster(
    n_nodes: int,
    n_pods: int,
    hard_predicates: bool = False,
) -> Tuple[List[dict], List[dict]]:
    """(nodes, pods). With hard_predicates, adds zones, a tainted slice of nodes,
    tolerating pods, and zone topology-spread — BASELINE.md's stress shape."""
    if hard_predicates:
        nodes = [synth_node(i, n_zones=8, taint_every=10) for i in range(n_nodes)]
        pods = [
            synth_pod(i, tolerate=(i % 3 == 0), spread_zone=True)
            for i in range(n_pods)
        ]
    else:
        nodes = [synth_node(i) for i in range(n_nodes)]
        pods = [synth_pod(i) for i in range(n_pods)]
    return nodes, pods
