"""Synthetic cluster/workload generators for benchmarks and harness dry-runs.

Shapes mirror BASELINE.md's configs (1k nodes / 10k nginx replicas; hard-predicate
stress with taints + affinities) without copying any reference fixture files.
"""

from __future__ import annotations

from typing import List, Optional, Tuple


def synth_node(
    i: int,
    cpu_milli: int = 32000,
    mem_bytes: int = 128 << 30,
    pods: int = 256,
    n_zones: int = 0,
    taint_every: int = 0,
) -> dict:
    name = f"node-{i:05d}"
    labels = {"kubernetes.io/hostname": name, "node-index": str(i)}
    if n_zones:
        labels["topology.kubernetes.io/zone"] = f"zone-{i % n_zones}"
    alloc = {"cpu": f"{cpu_milli}m", "memory": str(mem_bytes), "pods": str(pods)}
    node = {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {"name": name, "labels": labels},
        "spec": {},
        "status": {"allocatable": dict(alloc), "capacity": dict(alloc)},
    }
    if taint_every and i % taint_every == 0:
        node["spec"]["taints"] = [
            {"key": "synth/dedicated", "value": "batch", "effect": "NoSchedule"}
        ]
    return node


def synth_pod(
    i: int,
    cpu_milli: int = 100,
    mem_bytes: int = 256 << 20,
    labels: Optional[dict] = None,
    tolerate: bool = False,
    anti_affinity_on: Optional[str] = None,
    spread_zone: bool = False,
) -> dict:
    spec: dict = {
        "containers": [
            {
                "name": "app",
                "image": "nginx:1.25",
                "resources": {
                    "requests": {"cpu": f"{cpu_milli}m", "memory": str(mem_bytes)}
                },
            }
        ]
    }
    lbl = {"app": "synth", **(labels or {})}
    if tolerate:
        spec["tolerations"] = [
            {"key": "synth/dedicated", "operator": "Equal", "value": "batch",
             "effect": "NoSchedule"}
        ]
    if anti_affinity_on:
        spec["affinity"] = {
            "podAntiAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [
                    {
                        "labelSelector": {"matchLabels": {"app": anti_affinity_on}},
                        "topologyKey": "kubernetes.io/hostname",
                    }
                ]
            }
        }
    if spread_zone:
        spec["topologySpreadConstraints"] = [
            {
                "maxSkew": 2,
                "topologyKey": "topology.kubernetes.io/zone",
                "whenUnsatisfiable": "DoNotSchedule",
                "labelSelector": {"matchLabels": {"app": "synth"}},
            }
        ]
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": f"pod-{i:06d}", "namespace": "default", "labels": lbl},
        "spec": spec,
    }


def synth_cluster(
    n_nodes: int,
    n_pods: int,
    hard_predicates: bool = False,
) -> Tuple[List[dict], List[dict]]:
    """(nodes, pods). With hard_predicates, adds zones, a tainted slice of nodes,
    and block-structured workloads (contiguous replica runs, the shape real apps
    produce) cycling plain / tolerating / self-anti-affinity / zone-spread pods —
    BASELINE.md's stress shape."""
    if not hard_predicates:
        nodes = [synth_node(i) for i in range(n_nodes)]
        pods = [synth_pod(i) for i in range(n_pods)]
        return nodes, pods

    nodes = [synth_node(i, n_zones=8, taint_every=10) for i in range(n_nodes)]
    pods: List[dict] = []
    block = max(1, n_pods // 50)
    k = 0
    while len(pods) < n_pods:
        n = min(block, n_pods - len(pods))
        kind = k % 5
        app = f"synth-{k}"
        for i in range(n):
            idx = len(pods)
            if kind == 1:
                pods.append(synth_pod(idx, labels={"app": app}, tolerate=True))
            elif kind == 3:
                # self anti-affinity: at most one replica per node
                cap = min(n, max(1, n_nodes // 2))
                if i < cap:
                    pods.append(
                        synth_pod(idx, labels={"app": app}, anti_affinity_on=app)
                    )
                else:
                    pods.append(synth_pod(idx, labels={"app": app}))
            elif kind == 4:
                # zone topology spread (serial path: spread state is stateful)
                pods.append(synth_pod(idx, spread_zone=True))
            else:
                pods.append(synth_pod(idx, labels={"app": app}))
        k += 1
    return nodes, pods


def synth_cluster_store(
    n_nodes: int,
    n_pods: int,
    hard_predicates: bool = False,
):
    """Columnar twin of synth_cluster: the SAME cluster and workload, emitted
    as a (NodeStore, PodStore) pair (simulator/store.py) — one node template
    block and one pod template block per synth "app" instead of n dicts. The
    double-encode parity suite (tests/test_store.py) asserts a Simulator over
    this form encodes and places bit-identically to the dict form; at 1M+
    pods this is the only form that fits in host memory at all."""
    from ..simulator.store import NodeStore, PodStore

    def node_template(taint: bool = False) -> dict:
        t = synth_node(0)
        t["metadata"] = {}
        if not taint:
            t.get("spec", {}).pop("taints", None)
        return t

    def pod_template(**kw) -> dict:
        t = synth_pod(0, **kw)
        t["metadata"].pop("name", None)
        return t

    ns = NodeStore()
    ps = PodStore()
    if not hard_predicates:
        ns.add_block(node_template(), n_nodes, name_fmt="node-{0:05d}",
                     index_labels=("node-index",))
        ps.add_block(pod_template(), n_pods, name_fmt="pod-{0:06d}")
        return ns, ps

    ns.add_block(
        node_template(), n_nodes, name_fmt="node-{0:05d}",
        index_labels=("node-index",),
        zone_cycle=("topology.kubernetes.io/zone", "zone-{0}", 8),
        taint=({"key": "synth/dedicated", "value": "batch",
                "effect": "NoSchedule"}, 10))
    block = max(1, n_pods // 50)
    made = 0
    k = 0
    while made < n_pods:
        n = min(block, n_pods - made)
        kind = k % 5
        app = f"synth-{k}"
        if kind == 1:
            ps.add_block(pod_template(labels={"app": app}, tolerate=True),
                         n, name_fmt="pod-{0:06d}")
        elif kind == 3:
            cap = min(n, max(1, n_nodes // 2))
            ps.add_block(pod_template(labels={"app": app},
                                      anti_affinity_on=app),
                         cap, name_fmt="pod-{0:06d}")
            if n > cap:
                ps.add_block(pod_template(labels={"app": app}), n - cap,
                             name_fmt="pod-{0:06d}")
        elif kind == 4:
            ps.add_block(pod_template(spread_zone=True), n,
                         name_fmt="pod-{0:06d}")
        else:
            ps.add_block(pod_template(labels={"app": app}), n,
                         name_fmt="pod-{0:06d}")
        made += n
        k += 1
    return ns, ps
