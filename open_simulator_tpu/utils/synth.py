"""Synthetic cluster/workload generators for benchmarks and harness dry-runs.

Shapes mirror BASELINE.md's configs (1k nodes / 10k nginx replicas; hard-predicate
stress with taints + affinities) without copying any reference fixture files.
"""

from __future__ import annotations

import json
import random
from typing import List, Optional, Tuple


def synth_node(
    i: int,
    cpu_milli: int = 32000,
    mem_bytes: int = 128 << 30,
    pods: int = 256,
    n_zones: int = 0,
    taint_every: int = 0,
) -> dict:
    name = f"node-{i:05d}"
    labels = {"kubernetes.io/hostname": name, "node-index": str(i)}
    if n_zones:
        labels["topology.kubernetes.io/zone"] = f"zone-{i % n_zones}"
    alloc = {"cpu": f"{cpu_milli}m", "memory": str(mem_bytes), "pods": str(pods)}
    node = {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {"name": name, "labels": labels},
        "spec": {},
        "status": {"allocatable": dict(alloc), "capacity": dict(alloc)},
    }
    if taint_every and i % taint_every == 0:
        node["spec"]["taints"] = [
            {"key": "synth/dedicated", "value": "batch", "effect": "NoSchedule"}
        ]
    return node


def synth_pod(
    i: int,
    cpu_milli: int = 100,
    mem_bytes: int = 256 << 20,
    labels: Optional[dict] = None,
    tolerate: bool = False,
    anti_affinity_on: Optional[str] = None,
    spread_zone: bool = False,
) -> dict:
    spec: dict = {
        "containers": [
            {
                "name": "app",
                "image": "nginx:1.25",
                "resources": {
                    "requests": {"cpu": f"{cpu_milli}m", "memory": str(mem_bytes)}
                },
            }
        ]
    }
    lbl = {"app": "synth", **(labels or {})}
    if tolerate:
        spec["tolerations"] = [
            {"key": "synth/dedicated", "operator": "Equal", "value": "batch",
             "effect": "NoSchedule"}
        ]
    if anti_affinity_on:
        spec["affinity"] = {
            "podAntiAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [
                    {
                        "labelSelector": {"matchLabels": {"app": anti_affinity_on}},
                        "topologyKey": "kubernetes.io/hostname",
                    }
                ]
            }
        }
    if spread_zone:
        spec["topologySpreadConstraints"] = [
            {
                "maxSkew": 2,
                "topologyKey": "topology.kubernetes.io/zone",
                "whenUnsatisfiable": "DoNotSchedule",
                "labelSelector": {"matchLabels": {"app": "synth"}},
            }
        ]
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": f"pod-{i:06d}", "namespace": "default", "labels": lbl},
        "spec": spec,
    }


def synth_cluster(
    n_nodes: int,
    n_pods: int,
    hard_predicates: bool = False,
) -> Tuple[List[dict], List[dict]]:
    """(nodes, pods). With hard_predicates, adds zones, a tainted slice of nodes,
    and block-structured workloads (contiguous replica runs, the shape real apps
    produce) cycling plain / tolerating / self-anti-affinity / zone-spread pods —
    BASELINE.md's stress shape."""
    if not hard_predicates:
        nodes = [synth_node(i) for i in range(n_nodes)]
        pods = [synth_pod(i) for i in range(n_pods)]
        return nodes, pods

    nodes = [synth_node(i, n_zones=8, taint_every=10) for i in range(n_nodes)]
    pods: List[dict] = []
    block = max(1, n_pods // 50)
    k = 0
    while len(pods) < n_pods:
        n = min(block, n_pods - len(pods))
        kind = k % 5
        app = f"synth-{k}"
        for i in range(n):
            idx = len(pods)
            if kind == 1:
                pods.append(synth_pod(idx, labels={"app": app}, tolerate=True))
            elif kind == 3:
                # self anti-affinity: at most one replica per node
                cap = min(n, max(1, n_nodes // 2))
                if i < cap:
                    pods.append(
                        synth_pod(idx, labels={"app": app}, anti_affinity_on=app)
                    )
                else:
                    pods.append(synth_pod(idx, labels={"app": app}))
            elif kind == 4:
                # zone topology spread (serial path: spread state is stateful)
                pods.append(synth_pod(idx, spread_zone=True))
            else:
                pods.append(synth_pod(idx, labels={"app": app}))
        k += 1
    return nodes, pods


def synth_watch_stream(
    n_nodes: int,
    n_events: int,
    seed: int = 0,
    bookmark_every: int = 64,
    n_bound: int = 0,
    n_templates: int = 8,
    start_rv: int = 1000,
) -> Tuple[List[dict], List[dict], List[str]]:
    """A deterministic recorded kube-watch stream over a synthetic cluster:
    (initial nodes, initially bound pods, JSONL watch lines).

    The stream is churn the resident-image delta path can express end to
    end — bound-pod ADDED/DELETED from a small template pool (so decode's
    template interning has something to intern), occasional node ADDED and
    drain (MODIFIED with spec.unschedulable) — delimited by BOOKMARK lines
    every `bookmark_every` events. resourceVersions are globally monotone;
    deletes only target pods committed before the current window so a
    window's net effect is never a wash (the chaos gate's relist windows
    stay meaningful). Drains evict their pods from the generator's own
    live-set, mirroring the image's node_drain semantics.
    """
    rng = random.Random(seed)
    nodes = [synth_node(i) for i in range(n_nodes)]
    live_nodes = [f"node-{i:05d}" for i in range(n_nodes)]

    bound: List[dict] = []
    pods_by_node: dict = {name: set() for name in live_nodes}
    live_pods: dict = {}  # key -> node name
    for i in range(n_bound):
        p = synth_pod(i, cpu_milli=100 + 50 * (i % n_templates),
                      labels={"app": f"seed-{i % n_templates}"})
        node = live_nodes[i % len(live_nodes)]
        p["spec"]["nodeName"] = node
        bound.append(p)
        key = f"default/{p['metadata']['name']}"
        live_pods[key] = node
        pods_by_node[node].add(key)

    def _line(typ: str, obj: dict) -> str:
        return json.dumps({"type": typ, "object": obj},
                          separators=(",", ":"))

    lines: List[str] = []
    rv = start_rv
    next_node_i = n_nodes
    next_pod_i = 0
    # pods eligible for deletion: committed before the current window
    deletable = sorted(live_pods)
    in_window = 0

    for _ in range(n_events):
        rv += 1
        r = rng.random()
        if r < 0.04 and len(live_nodes) > max(2, n_nodes // 2):
            # drain one node; its pods leave the cluster with it
            name = live_nodes.pop(rng.randrange(len(live_nodes)))
            for key in pods_by_node.pop(name, ()):
                live_pods.pop(key, None)
            deletable = [k for k in deletable if k in live_pods]
            obj = synth_node(int(name.split("-")[-1]))
            obj["spec"]["unschedulable"] = True
            obj["metadata"]["resourceVersion"] = str(rv)
            lines.append(_line("MODIFIED", obj))
        elif r < 0.07:
            obj = synth_node(next_node_i)
            name = obj["metadata"]["name"]
            next_node_i += 1
            live_nodes.append(name)
            pods_by_node[name] = set()
            obj["metadata"]["resourceVersion"] = str(rv)
            lines.append(_line("ADDED", obj))
        elif r < 0.27 and deletable:
            key = deletable.pop(rng.randrange(len(deletable)))
            node = live_pods.pop(key, None)
            if node is not None:
                pods_by_node.get(node, set()).discard(key)
            ns, name = key.split("/", 1)
            lines.append(_line("DELETED", {
                "kind": "Pod", "apiVersion": "v1",
                "metadata": {"name": name, "namespace": ns,
                             "resourceVersion": str(rv)}}))
        else:
            t = rng.randrange(n_templates)
            p = synth_pod(0, cpu_milli=100 + 50 * t,
                          labels={"app": f"stream-{t}"})
            name = f"wpod-{next_pod_i:06d}"
            next_pod_i += 1
            node = live_nodes[rng.randrange(len(live_nodes))]
            p["metadata"]["name"] = name
            p["metadata"]["resourceVersion"] = str(rv)
            p["kind"] = "Pod"
            p["spec"]["nodeName"] = node
            key = f"default/{name}"
            live_pods[key] = node
            pods_by_node[node].add(key)
            lines.append(_line("ADDED", p))
        in_window += 1
        if in_window >= bookmark_every:
            rv += 1
            lines.append(_line("BOOKMARK", {
                "kind": "Pod",
                "metadata": {"resourceVersion": str(rv)}}))
            deletable = sorted(live_pods)
            in_window = 0
    if in_window:
        rv += 1
        lines.append(_line("BOOKMARK", {
            "kind": "Pod", "metadata": {"resourceVersion": str(rv)}}))
    return nodes, bound, lines


def synth_cluster_store(
    n_nodes: int,
    n_pods: int,
    hard_predicates: bool = False,
):
    """Columnar twin of synth_cluster: the SAME cluster and workload, emitted
    as a (NodeStore, PodStore) pair (simulator/store.py) — one node template
    block and one pod template block per synth "app" instead of n dicts. The
    double-encode parity suite (tests/test_store.py) asserts a Simulator over
    this form encodes and places bit-identically to the dict form; at 1M+
    pods this is the only form that fits in host memory at all."""
    from ..simulator.store import NodeStore, PodStore

    def node_template(taint: bool = False) -> dict:
        t = synth_node(0)
        t["metadata"] = {}
        if not taint:
            t.get("spec", {}).pop("taints", None)
        return t

    def pod_template(**kw) -> dict:
        t = synth_pod(0, **kw)
        t["metadata"].pop("name", None)
        return t

    ns = NodeStore()
    ps = PodStore()
    if not hard_predicates:
        ns.add_block(node_template(), n_nodes, name_fmt="node-{0:05d}",
                     index_labels=("node-index",))
        ps.add_block(pod_template(), n_pods, name_fmt="pod-{0:06d}")
        return ns, ps

    ns.add_block(
        node_template(), n_nodes, name_fmt="node-{0:05d}",
        index_labels=("node-index",),
        zone_cycle=("topology.kubernetes.io/zone", "zone-{0}", 8),
        taint=({"key": "synth/dedicated", "value": "batch",
                "effect": "NoSchedule"}, 10))
    block = max(1, n_pods // 50)
    made = 0
    k = 0
    while made < n_pods:
        n = min(block, n_pods - made)
        kind = k % 5
        app = f"synth-{k}"
        if kind == 1:
            ps.add_block(pod_template(labels={"app": app}, tolerate=True),
                         n, name_fmt="pod-{0:06d}")
        elif kind == 3:
            cap = min(n, max(1, n_nodes // 2))
            ps.add_block(pod_template(labels={"app": app},
                                      anti_affinity_on=app),
                         cap, name_fmt="pod-{0:06d}")
            if n > cap:
                ps.add_block(pod_template(labels={"app": app}), n - cap,
                             name_fmt="pod-{0:06d}")
        elif kind == 4:
            ps.add_block(pod_template(spread_zone=True), n,
                         name_fmt="pod-{0:06d}")
        else:
            ps.add_block(pod_template(labels={"app": app}), n,
                         name_fmt="pod-{0:06d}")
        made += n
        k += 1
    return ns, ps
