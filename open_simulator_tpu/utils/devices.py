"""Backend/device plumbing shared by tests, bench, and the multichip dry-run.

Some images inject a TPU plugin that prepends itself to `jax_platforms`, defeating the
JAX_PLATFORMS=cpu env var; and the virtual-CPU device count flag is only read at the
CPU backend's lazy initialization. This module is the one place that handles both.
"""

from __future__ import annotations

import os
import re

_FLAG = "--xla_force_host_platform_device_count"


def request_cpu_devices(n: int) -> None:
    """Raise the virtual CPU device count to ≥ n via XLA_FLAGS. Must run before the
    CPU backend's lazy initialization; harmless (but ineffective) afterwards."""
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(re.escape(_FLAG) + r"=(\d+)", flags)
    cur = int(m.group(1)) if m else 0
    if cur < n:
        flags = re.sub(re.escape(_FLAG) + r"=\d+", "", flags).strip()
        os.environ["XLA_FLAGS"] = (flags + f" {_FLAG}={n}").strip()


def force_cpu_platform() -> None:
    """Make CPU the default JAX platform regardless of injected plugin priority.
    Silently a no-op when a backend is already initialized."""
    import jax

    try:
        if not str(jax.config.jax_platforms or "").startswith("cpu"):
            jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass


def cpu_devices(n: int):
    """Best-effort list of ≥ n devices, preferring the default platform and falling
    back to virtual CPU devices. May return fewer if the CPU backend already
    initialized with a smaller count."""
    request_cpu_devices(n)
    import jax

    devs = jax.devices()
    if len(devs) < n:
        devs = jax.devices("cpu")
    return devs
