"""Backend/device plumbing shared by tests, bench, and the multichip dry-run.

Some images inject a TPU plugin that prepends itself to `jax_platforms`, defeating the
JAX_PLATFORMS=cpu env var; and the virtual-CPU device count flag is only read at the
CPU backend's lazy initialization. This module is the one place that handles both.
"""

from __future__ import annotations

import os
import re

_FLAG = "--xla_force_host_platform_device_count"


def request_cpu_devices(n: int) -> None:
    """Raise the virtual CPU device count to ≥ n via XLA_FLAGS. Must run before the
    CPU backend's lazy initialization; harmless (but ineffective) afterwards."""
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(re.escape(_FLAG) + r"=(\d+)", flags)
    cur = int(m.group(1)) if m else 0
    if cur < n:
        flags = re.sub(re.escape(_FLAG) + r"=\d+", "", flags).strip()
        os.environ["XLA_FLAGS"] = (flags + f" {_FLAG}={n}").strip()


def force_cpu_platform() -> None:
    """Make CPU the default JAX platform regardless of injected plugin priority.
    Silently a no-op when a backend is already initialized."""
    import jax

    try:
        if not str(jax.config.jax_platforms or "").startswith("cpu"):
            jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass


_cache_enabled = False


def enable_compilation_cache() -> None:
    """Turn on JAX's persistent compilation cache (idempotent). The engine's
    kernels take ~15-40s to compile (CPU/TPU); every fresh process — each CLI
    run, each server worker, every capacity-probe shape bucket — used to pay
    that again. The cache keys on backend + jaxlib version + HLO, so entries
    persist across runs and machines sharing the directory.

    Opt-out / redirect via OPEN_SIMULATOR_COMPILE_CACHE: "0"/"off" disables,
    any other non-empty value is the cache directory (default
    ~/.cache/open-simulator-tpu/xla)."""
    global _cache_enabled
    if _cache_enabled:
        return
    _cache_enabled = True  # one attempt per process, success or not
    setting = os.environ.get("OPEN_SIMULATOR_COMPILE_CACHE", "")
    if setting.lower() in ("0", "off", "false", "no"):
        return
    if setting.lower() in ("1", "on", "true", "yes"):
        setting = ""  # plain enable → default directory
    cache_dir = setting or os.path.join(
        os.environ.get("XDG_CACHE_HOME")
        or os.path.join(os.path.expanduser("~"), ".cache"),
        "open-simulator-tpu", "xla")
    try:
        os.makedirs(cache_dir, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # JAX's default gates apply: entries are persisted for programs past
        # jax_persistent_cache_min_compile_time_secs (1s) — every engine scan
        # kernel clears that by an order of magnitude
    except Exception as e:  # cache is an optimization; never fail the caller
        import logging

        logging.getLogger("open_simulator_tpu").warning(
            "persistent compilation cache unavailable (%s); "
            "kernels will recompile per process", e)


def cpu_devices(n: int):
    """Best-effort list of ≥ n devices, preferring the default platform and falling
    back to virtual CPU devices. May return fewer if the CPU backend already
    initialized with a smaller count."""
    request_cpu_devices(n)
    import jax

    devs = jax.devices()
    if len(devs) < n:
        devs = jax.devices("cpu")
    return devs
