"""Backend/device plumbing shared by tests, bench, and the multichip dry-run.

Some images inject a TPU plugin that prepends itself to `jax_platforms`, defeating the
JAX_PLATFORMS=cpu env var; and the virtual-CPU device count flag is only read at the
CPU backend's lazy initialization. This module is the one place that handles both.
"""

from __future__ import annotations

import os
import re

_FLAG = "--xla_force_host_platform_device_count"


def request_cpu_devices(n: int) -> None:
    """Raise the virtual CPU device count to ≥ n via XLA_FLAGS. Must run before the
    CPU backend's lazy initialization; harmless (but ineffective) afterwards."""
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(re.escape(_FLAG) + r"=(\d+)", flags)
    cur = int(m.group(1)) if m else 0
    if cur < n:
        flags = re.sub(re.escape(_FLAG) + r"=\d+", "", flags).strip()
        os.environ["XLA_FLAGS"] = (flags + f" {_FLAG}={n}").strip()


def force_cpu_platform() -> None:
    """Make CPU the default JAX platform regardless of injected plugin priority.
    Silently a no-op when a backend is already initialized."""
    import jax

    try:
        if not str(jax.config.jax_platforms or "").startswith("cpu"):
            jax.config.update("jax_platforms", "cpu")
    # simonlint: ignore[swallowed-exception] -- documented no-op when a
    # backend already initialized; the caller proceeds on whatever platform
    except Exception:
        pass


_cache_enabled = False


def enable_compilation_cache() -> None:
    """Turn on JAX's persistent compilation cache (idempotent). The engine's
    kernels take ~15-40s to compile (CPU/TPU); every fresh process — each CLI
    run, each server worker, every capacity-probe shape bucket — used to pay
    that again. The cache keys on backend + jaxlib version + HLO, so entries
    persist across runs and machines sharing the directory.

    Opt-out / redirect via OPEN_SIMULATOR_COMPILE_CACHE: "0"/"off" disables,
    any other non-empty value is the cache directory (default
    ~/.cache/open-simulator-tpu/xla)."""
    global _cache_enabled
    if _cache_enabled:
        return
    _cache_enabled = True  # one attempt per process, success or not
    setting = os.environ.get("OPEN_SIMULATOR_COMPILE_CACHE", "")
    if setting.lower() in ("0", "off", "false", "no"):
        return
    if setting.lower() in ("1", "on", "true", "yes"):
        setting = ""  # plain enable → default directory
    cache_dir = setting or os.path.join(
        os.environ.get("XDG_CACHE_HOME")
        or os.path.join(os.path.expanduser("~"), ".cache"),
        "open-simulator-tpu", "xla")
    try:
        os.makedirs(cache_dir, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # JAX's default gates apply: entries are persisted for programs past
        # jax_persistent_cache_min_compile_time_secs (1s) — every engine scan
        # kernel clears that by an order of magnitude
    except Exception as e:  # cache is an optimization; never fail the caller
        import logging

        logging.getLogger("open_simulator_tpu").warning(
            "persistent compilation cache unavailable (%s); "
            "kernels will recompile per process", e)


def _probe_state_path() -> str:
    """Where the last probe outcome persists across processes. ONE shared
    default (under the XDG cache, alongside the XLA cache) for every caller
    — CLI, server, bench, the background probe logger — so any process's
    wedge observation cools down all of them. OPEN_SIMULATOR_PROBE_STATE
    overrides (point it at a per-host shared location when $HOME isn't)."""
    p = os.environ.get("OPEN_SIMULATOR_PROBE_STATE", "")
    if p:
        return p
    return os.path.join(
        os.environ.get("XDG_CACHE_HOME")
        or os.path.join(os.path.expanduser("~"), ".cache"),
        "open-simulator-tpu", "probe_state.json")


def _read_probe_state(path: str):
    import json

    try:
        with open(path) as f:
            doc = json.load(f)
        return doc if isinstance(doc, dict) else None
    except (OSError, ValueError):
        return None  # missing/corrupt state: probe normally


def _write_probe_state(path: str, rec: dict) -> None:
    """Atomic best-effort persist (tmp + rename): a torn write must never
    leave a half-record that later parses as a wedge."""
    import json

    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, path)
    except OSError as e:
        import logging

        logging.getLogger("open_simulator_tpu").debug(
            "probe state not persisted (%s)", e)


def probe_cooldown_s() -> float:
    """Seconds a persisted wedge outcome short-circuits re-probing
    (OPEN_SIMULATOR_PROBE_COOLDOWN_S; 0 disables). Re-probing a known-wedged
    host burns the full probe timeout (60-120s) on EVERY run — the r5
    pattern: 20/20 probe attempts timing out across a round — so within the
    window the run skips straight to the CPU fallback."""
    try:
        return float(os.environ.get("OPEN_SIMULATOR_PROBE_COOLDOWN_S", "600"))
    except ValueError:
        return 600.0


def probe_default_backend(timeout: float = 60.0,
                          state_path: str = "") -> tuple:
    """Probe `jax.devices()` on the default platform in a SUBPROCESS with a
    deadline. The single shared implementation of the wedge-safe probe (bench,
    the background probe logger, and the CLI all use it): a wedged accelerator
    tunnel blocks backend init forever holding a global lock, so the probe must
    never run in-process, and the killed child may be unkillable (D-state in a
    driver ioctl) — kill then bounded-wait to reap when possible.

    The last outcome persists at `state_path` (default _probe_state_path());
    a wedge outcome within the probe_cooldown_s window short-circuits to
    (False, {"outcome": "cooldown", ...}) without burning another probe
    timeout — a known-wedged host goes straight to cpu-fallback.

    Returns (ok, record) where record carries ts/outcome/elapsed_s plus
    rc/platform/stderr_tail on non-timeout exits — the stderr tail is what
    distinguishes "tunnel wedged" from "plugin crashed at import" in the logs."""
    import subprocess
    import sys
    import tempfile
    import time

    state_path = state_path or _probe_state_path()
    t0 = time.time()
    rec = {"ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(t0)),
           "timeout_s": timeout}
    cooldown = probe_cooldown_s()
    st = _read_probe_state(state_path) if cooldown > 0 else None
    if st and st.get("outcome") in ("timeout", "error"):
        age = t0 - float(st.get("ts_epoch") or 0)
        if 0 <= age < cooldown:
            rec.update(outcome="cooldown", last_outcome=st.get("outcome"),
                       cooldown_remaining_s=round(cooldown - age, 1),
                       elapsed_s=0.0)
            return False, rec
    # stderr to a FILE, not a pipe: a chatty plugin writing >64KB to an
    # undrained pipe would wedge an otherwise-healthy probe into a timeout
    with tempfile.TemporaryFile() as errf:
        probe = subprocess.Popen(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); print(d[0].platform, len(d))"],
            stdout=subprocess.PIPE, stderr=errf, text=True,
            start_new_session=True,
        )
        try:
            out, _ = probe.communicate(timeout=timeout)
            ok = probe.returncode == 0
            rec.update(outcome="ok" if ok else "error", rc=probe.returncode,
                       platform=(out or "").strip() or None,
                       elapsed_s=round(time.time() - t0, 1))
            if not ok:
                try:
                    errf.seek(0)
                    rec["stderr_tail"] = errf.read()[-400:].decode(
                        "utf-8", "replace").strip()
                except OSError:
                    pass
        except subprocess.TimeoutExpired:
            ok = False
            probe.kill()
            try:
                probe.wait(timeout=5)  # reap; a D-state child won't die
            except subprocess.TimeoutExpired:
                pass
            rec.update(outcome="timeout", elapsed_s=round(time.time() - t0, 1))
    # persist the outcome next to the probe log so the NEXT process can
    # honor the cooldown (a wedge rarely clears within minutes)
    _write_probe_state(state_path, {"ts_epoch": t0, "outcome": rec["outcome"],
                                    "ts": rec["ts"]})
    return ok, rec


# --- chip lock: serializes would-be accelerator clients on one machine --------
# A killed mid-compile client is the suspected tunnel-wedge trigger, so the
# bench, the background probe logger, and (opt-in via OPEN_SIMULATOR_TPU_LOCK)
# the CLI coordinate through one pidfile.


def tpu_lock_holder(lock_path: str):
    """PID holding the lock, or None when missing/unreadable/stale (dead PID)."""
    try:
        with open(lock_path) as f:
            pid = int(f.read().strip() or 0)
    except (OSError, ValueError):
        return None
    if pid <= 0:
        return None
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return None  # holder died without cleanup: stale
    except PermissionError:
        return pid  # alive under another user (EPERM): a LIVE holder, never steal
    except OSError:
        return pid  # unknown kill failure: assume live rather than steal
    return pid


def acquire_tpu_lock(lock_path: str) -> bool:
    """Atomically acquire (O_CREAT|O_EXCL), stealing a stale dead-PID lock.
    Returns False when a live process holds it."""
    for _ in range(2):
        try:
            fd = os.open(lock_path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.write(fd, str(os.getpid()).encode())
            os.close(fd)
            return True
        except FileExistsError:
            if tpu_lock_holder(lock_path) is not None:
                return False
            try:
                os.remove(lock_path)  # stale: steal and retry the O_EXCL create
            except OSError:
                pass
    return False


def release_tpu_lock(lock_path: str) -> None:
    try:
        os.remove(lock_path)
    except OSError:
        pass


def ensure_responsive_backend(timeout: float = 60.0) -> str:
    """Guard a CLI/server/library run against a wedged accelerator: probe the
    default JAX backend with a deadline (probe_default_backend) and force the
    CPU platform on failure (config route — the env-var override can itself
    hang at import under injected plugins), so the run proceeds degraded
    instead of hanging forever at first device use.

    Returns "default" (probe ok), "cpu" (fell back), or "skipped".
    Skipped when: OPEN_SIMULATOR_BACKEND_PROBE=0; the platform is already
    pinned to cpu (env var, or in-process jax config — how tests pin it);
    falls straight back to CPU without probing when OPEN_SIMULATOR_TPU_LOCK
    names a lockfile held by a live process (another client owns the chip —
    two concurrent clients are the suspected wedge trigger).
    OPEN_SIMULATOR_BACKEND_PROBE_TIMEOUT overrides the deadline (seconds)."""
    import sys

    env_probe = os.environ.get("OPEN_SIMULATOR_BACKEND_PROBE", "")
    if env_probe.lower() in ("0", "off", "false", "no"):
        return "skipped"
    if str(os.environ.get("JAX_PLATFORMS", "")).startswith("cpu"):
        return "skipped"  # explicitly CPU: nothing to probe
    j = sys.modules.get("jax")
    if j is not None:
        try:
            if str(j.config.jax_platforms or "").startswith("cpu"):
                return "skipped"  # already pinned in-process (force_cpu_platform)
        # simonlint: ignore[swallowed-exception] -- unreadable config just
        # means the probe below runs; that path logs its own outcome
        except Exception:
            pass
    import logging

    log = logging.getLogger("open_simulator_tpu")
    lock_path = os.environ.get("OPEN_SIMULATOR_TPU_LOCK", "")
    if lock_path and tpu_lock_holder(lock_path) is not None:
        log.warning("accelerator lock %s is held; using CPU for this run",
                    lock_path)
        os.environ.pop("JAX_PLATFORMS", None)
        force_cpu_platform()
        return "cpu"
    try:
        timeout = float(
            os.environ.get("OPEN_SIMULATOR_BACKEND_PROBE_TIMEOUT", timeout))
    except ValueError:
        pass
    ok, rec = probe_default_backend(timeout)
    if ok:
        return "default"
    log.warning("default JAX backend unresponsive (%s); falling back to CPU",
                rec.get("stderr_tail") or rec["outcome"])
    os.environ.pop("JAX_PLATFORMS", None)
    force_cpu_platform()
    return "cpu"


def cpu_devices(n: int):
    """Best-effort list of ≥ n devices, preferring the default platform and falling
    back to virtual CPU devices. May return fewer if the CPU backend already
    initialized with a smaller count."""
    request_cpu_devices(n)
    import jax

    devs = jax.devices()
    if len(devs) < n:
        devs = jax.devices("cpu")
    return devs
