"""Kubernetes resource.Quantity parsing/formatting.

Reimplements the subset of k8s.io/apimachinery/pkg/api/resource.Quantity semantics the
simulator needs (reference uses it everywhere, e.g. /root/reference/pkg/simulator/plugin/
simon.go:45-68 via resourcehelper.PodRequestsAndLimits): binary suffixes (Ki..Ei), decimal
suffixes (k..E, and m for milli), plain integers/decimals, and scientific notation.

Values are held as exact integers of the smallest unit we care about:
- `parse_quantity` returns a float of the *base unit* (bytes, cores, counts).
- `parse_milli` returns integer milli-units (k8s CPU math is done in milli-cores;
  kube-scheduler's Resource struct stores MilliCPU + bytes).
"""

from __future__ import annotations

import re
from decimal import Decimal
from functools import lru_cache

_BIN = {"Ki": 1024, "Mi": 1024**2, "Gi": 1024**3, "Ti": 1024**4, "Pi": 1024**5, "Ei": 1024**6}
_DEC = {
    "n": Decimal("1e-9"),
    "u": Decimal("1e-6"),
    "m": Decimal("1e-3"),
    "": Decimal(1),
    "k": Decimal(1000),
    "M": Decimal(1000**2),
    "G": Decimal(1000**3),
    "T": Decimal(1000**4),
    "P": Decimal(1000**5),
    "E": Decimal(1000**6),
}

_QUANT_RE = re.compile(
    r"^(?P<sign>[+-]?)(?P<num>[0-9]+(?:\.[0-9]*)?|\.[0-9]+)"
    r"(?:(?P<suffix>Ki|Mi|Gi|Ti|Pi|Ei|[numkMGTPE])|(?P<exp>[eE][+-]?[0-9]+))?$"
)


class InvalidQuantity(ValueError):
    pass


def parse_decimal(value) -> Decimal:
    """Parse a k8s quantity (str/int/float) into an exact Decimal of base units."""
    if isinstance(value, bool):
        raise InvalidQuantity(f"boolean is not a quantity: {value!r}")
    if isinstance(value, str):
        return _parse_decimal_str(value)
    if isinstance(value, (int, float)):
        return Decimal(str(value))
    if value is None:
        return Decimal(0)
    return _parse_decimal_str(str(value))


@lru_cache(maxsize=65536)
def _parse_decimal_str(value: str) -> Decimal:
    s = value.strip()
    if not s:
        return Decimal(0)
    m = _QUANT_RE.match(s)
    if not m:
        raise InvalidQuantity(f"unparseable quantity: {s!r}")
    num = Decimal(m.group("num"))
    if m.group("sign") == "-":
        num = -num
    suffix = m.group("suffix")
    if suffix:
        if suffix in _BIN:
            num *= _BIN[suffix]
        else:
            num *= _DEC[suffix]
    elif m.group("exp"):
        num *= Decimal(10) ** int(m.group("exp")[1:])
    return num


def parse_quantity(value) -> float:
    """Quantity → float of base units (cores, bytes, counts)."""
    return float(parse_decimal(value))


def parse_milli(value) -> int:
    """Quantity → integer milli-units, rounding up like k8s ScaledValue(resource.Milli)."""
    d = parse_decimal(value) * 1000
    i = int(d)
    if d != i and d > 0:
        i += 1  # k8s rounds up when scaling down to milli
    return i


def format_quantity(value: float, binary: bool = False) -> str:
    """Pretty-print base-unit value, picking the largest clean suffix (report output only)."""
    if value == 0:
        return "0"
    if binary:
        for suf in ("Ei", "Pi", "Ti", "Gi", "Mi", "Ki"):
            f = _BIN[suf]
            if value % f == 0:
                return f"{int(value // f)}{suf}"
        # fall through: not a clean multiple of any binary suffix
    if float(value).is_integer():
        return str(int(value))
    milli = value * 1000
    if float(milli).is_integer():
        return f"{int(milli)}m"
    return f"{value:g}"
