"""Local-storage annotation codec (the open-local data model).

Mirrors NodeStorage/Volume (/root/reference/pkg/utils/utils.go:510-525) and
open-local's SharedResource/ExclusiveResource (vendor/github.com/alibaba/open-local/
pkg/scheduler/algorithm/cache/types.go:39-70). The reference's Go structs use
`json:",string"` tags, so numbers and booleans arrive as strings
("capacity": "107374182400", "isAllocated": "false"); this codec accepts both.
"""

from __future__ import annotations

import json
from typing import List, Optional

from ..core import constants as C
from .objutil import annotations_of


def to_int(v, default: int = 0) -> int:
    if v is None:
        return default
    if isinstance(v, bool):
        return int(v)
    if isinstance(v, (int, float)):
        return int(v)
    s = str(v).strip()
    if not s:
        return default
    try:
        return int(float(s))
    except ValueError:
        return default


def to_bool(v) -> bool:
    if isinstance(v, bool):
        return v
    return str(v).strip().lower() == "true"


class VG:
    """A shared LVM volume group (open-local SharedResource)."""

    def __init__(self, name: str, capacity: int, requested: int = 0) -> None:
        self.name = name
        self.capacity = capacity
        self.requested = requested

    def to_json(self) -> dict:
        return {"name": self.name, "capacity": str(self.capacity),
                "requested": str(self.requested)}


class Device:
    """An exclusive block device (open-local ExclusiveResource)."""

    def __init__(self, device: str, capacity: int, media_type: str = "hdd",
                 is_allocated: bool = False, name: str = "") -> None:
        self.device = device
        self.name = name or device
        self.capacity = capacity
        self.media_type = media_type
        self.is_allocated = is_allocated

    def to_json(self) -> dict:
        return {"name": self.name, "device": self.device,
                "capacity": str(self.capacity), "mediaType": self.media_type,
                "isAllocated": str(self.is_allocated).lower()}


class NodeStorage:
    def __init__(self, vgs: Optional[List[VG]] = None,
                 devices: Optional[List[Device]] = None) -> None:
        self.vgs = vgs or []
        self.devices = devices or []

    @classmethod
    def from_json(cls, raw: str) -> "NodeStorage":
        data = json.loads(raw) or {}
        vgs = [
            VG(v.get("name", ""), to_int(v.get("capacity")), to_int(v.get("requested")))
            for v in data.get("vgs") or []
        ]
        devices = [
            Device(
                d.get("device", d.get("name", "")),
                to_int(d.get("capacity")),
                d.get("mediaType", "hdd"),
                to_bool(d.get("isAllocated")),
                d.get("name", ""),
            )
            for d in data.get("devices") or []
        ]
        return cls(vgs, devices)

    def to_json(self) -> str:
        return json.dumps(
            {"vgs": [v.to_json() for v in self.vgs],
             "devices": [d.to_json() for d in self.devices]}
        )


def get_node_storage(node: dict) -> Optional[NodeStorage]:
    """GetNodeStorage (utils.go:527-538): decode the node annotation, None if absent."""
    raw = annotations_of(node).get(C.AnnoNodeLocalStorage)
    if not raw:
        return None
    return NodeStorage.from_json(raw)


def set_node_storage(node: dict, storage: NodeStorage) -> None:
    node.setdefault("metadata", {}).setdefault("annotations", {})[
        C.AnnoNodeLocalStorage
    ] = storage.to_json()


class Volume:
    """A pod's local-storage volume request (utils.go:516-521)."""

    def __init__(self, size: int, kind: str, sc_name: str) -> None:
        self.size = size
        self.kind = kind  # "LVM" | "HDD" | "SSD"
        self.sc_name = sc_name


def get_pod_local_volumes(pod: dict) -> List[Volume]:
    """Decode the simon/pod-local-storage annotation's VolumeRequest."""
    raw = annotations_of(pod).get(C.AnnoPodLocalStorage)
    if not raw:
        return []
    data = json.loads(raw) or {}
    return [
        Volume(to_int(v.get("size")), v.get("kind", ""), v.get("scName", ""))
        for v in data.get("volumes") or []
    ]
