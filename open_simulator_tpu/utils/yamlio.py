"""YAML ingestion: recursive directory walk → decoded k8s objects → ResourceTypes.

Mirrors the reference's cluster/app file loading (/root/reference/pkg/utils/utils.go:43-130
`GetYamlContentFromDirectory`, and /root/reference/pkg/simulator/utils.go:233-275
`GetObjectFromYamlContent`): walk a directory tree, split multi-document YAML, bucket each
object by kind, error on unknown kinds.
"""

from __future__ import annotations

import json
import os
from typing import Iterable, List

import yaml

from ..core import constants as C
from ..core.types import KIND_TO_FIELD, ResourceTypes
from .objutil import name_of


class UnknownKindError(ValueError):
    pass


def read_yaml_files(directory: str) -> List[str]:
    """Recursively collect .yaml/.yml file contents under `directory` (sorted walk)."""
    contents = []
    if not os.path.isdir(directory):
        raise FileNotFoundError(f"not a directory: {directory}")
    for root, dirs, files in os.walk(directory):
        dirs.sort()
        for fname in sorted(files):
            if fname.endswith((".yaml", ".yml")):
                with open(os.path.join(root, fname), "r", encoding="utf-8") as f:
                    contents.append(f.read())
    return contents


# libyaml-backed loader when present: 5-10x faster parsing, which matters for
# multi-thousand-node cluster dumps; semantics identical to SafeLoader
_YAML_LOADER = getattr(yaml, "CSafeLoader", yaml.SafeLoader)


def decode_yaml_content(contents: Iterable[str]) -> List[dict]:
    """Split multi-doc YAML strings into object dicts, skipping empty docs."""
    objs = []
    for content in contents:
        for doc in yaml.load_all(content, Loader=_YAML_LOADER):
            if isinstance(doc, dict) and doc:
                objs.append(doc)
    return objs


def bucket_objects(objs: Iterable[dict], strict: bool = True) -> ResourceTypes:
    """Dispatch decoded objects into ResourceTypes by `kind`.

    `strict=True` raises on unsupported kinds, matching GetObjectFromYamlContent's
    "unknown struct type" error; strict=False skips them (server-mode snapshots may carry
    kinds the simulator ignores).
    """
    rt = ResourceTypes()
    for obj in objs:
        kind = obj.get("kind")
        field = KIND_TO_FIELD.get(kind)
        if field is None:
            if strict:
                raise UnknownKindError(f"unknown struct type: kind={kind!r}")
            continue
        getattr(rt, field).append(obj)
    return rt


def load_resources_from_directory(directory: str, strict: bool = True) -> ResourceTypes:
    return bucket_objects(decode_yaml_content(read_yaml_files(directory)), strict=strict)


def match_and_set_local_storage_annotation(nodes: List[dict], directory: str) -> None:
    """MatchAndSetLocalStorageAnnotationOnNode (pkg/simulator/utils.go:385-401):
    node-name-matched .json files in `directory` become the node's
    simon/node-local-storage annotation."""
    storage = load_json_files(directory)
    for node in nodes:
        info = storage.get(name_of(node))
        if info is not None:
            node.setdefault("metadata", {}).setdefault("annotations", {})[
                C.AnnoNodeLocalStorage
            ] = json.dumps(info)


def _load_cluster_from_directory(directory: str, strict: bool = True) -> ResourceTypes:
    """CreateClusterResourceFromClusterConfig (simulator.go:604-619): YAML objects
    plus node-name-matched local-storage specs applied as node annotations."""
    rt = load_resources_from_directory(directory, strict=strict)
    match_and_set_local_storage_annotation(rt.nodes, directory)
    return rt


def load_json_files(directory: str) -> dict:
    """name → parsed JSON for .json files in a dir (local-storage node specs,
    /root/reference/pkg/simulator/utils.go:385-401 matches node-name.json to nodes)."""
    import json

    out = {}
    if not os.path.isdir(directory):
        return out
    for root, dirs, files in os.walk(directory):
        dirs.sort()
        for fname in sorted(files):
            if fname.endswith(".json"):
                with open(os.path.join(root, fname), "r", encoding="utf-8") as f:
                    out[os.path.splitext(fname)[0]] = json.load(f)
    return out


def load_cluster_from_directory(directory: str, strict: bool = True) -> ResourceTypes:
    """Traced wrapper — same 100ms LogIfLong as the live-cluster fetch."""
    from .trace import Span

    with Span("load cluster from directory", log_if_longer=0.1):
        return _load_cluster_from_directory(directory, strict)
