"""simonsync: resilient live-cluster watch sync (see sync.py).

The typed error taxonomy (AuthError / TransientError / ProtocolError) is
defined in simulator/live.py and re-exported here so live/ modules share
one classification discipline — the `unclassified-network-error` lint rule
enforces that every network catch under live/ routes through it.
"""

from ..simulator.live import (  # noqa: F401
    AuthError,
    LiveClusterError,
    ProtocolError,
    TransientError,
)
from .decode import TemplateInterner, WatchLine, parse_line, reconcile, to_delta  # noqa: F401
from .sync import (  # noqa: F401
    BOOKMARK_NAME,
    HttpWatchSource,
    QueueSource,
    RecordedSource,
    ScriptedSource,
    WatchSource,
    WatchSync,
    kube_watch_sources,
)

__all__ = [
    "AuthError", "LiveClusterError", "ProtocolError", "TransientError",
    "TemplateInterner", "WatchLine", "parse_line", "reconcile", "to_delta",
    "BOOKMARK_NAME", "HttpWatchSource", "QueueSource", "RecordedSource",
    "ScriptedSource", "WatchSource", "WatchSync", "kube_watch_sources",
]
