"""simonsync columnar decode: kube watch JSON -> resident-image delta events.

One ``json.loads`` per watch line is the floor, but nothing downstream of it
needs a fresh object tree per pod: the decoder interns pod *templates* — the
heavy spec subtree (containers, resources, affinity, tolerations) and the
label map are parsed once per distinct shape and shared by reference across
every pod that matches, which is ``PodStore.add_block``'s template-block
idiom applied to the delta path. Each decoded pod is still a distinct top
dict (the engine's ``_sig_of`` bookkeeping is identity-keyed), but a 10k-pod
stream of 8 templates retains 8 spec trees, not 10k. Node objects ride the
image's ``node_add`` path, which extends ``NodeArrays`` columnar in place.

The other half of this module is :func:`reconcile` — the 410-Gone recovery
diff. It compares a freshly listed cluster against the resident image's
*index structures* (``sync_snapshot`` reads the pod index and the node-name
column directly; no per-object materialization) and emits the minimal delta
batch: only what actually changed in the gap window, never a
generation-bumping rebuild unless the diff finds a change the delta path
cannot express (today: a drained node coming back).
"""

from __future__ import annotations

import json
from typing import Dict, List, NamedTuple, Optional, Tuple

from ..simulator.live import ProtocolError

WATCH_TYPES = ("ADDED", "MODIFIED", "DELETED", "BOOKMARK")


class WatchLine(NamedTuple):
    """One parsed watch-stream line."""

    type: str   # ADDED | MODIFIED | DELETED | BOOKMARK
    kind: str   # Node | Pod | ...
    key: str    # "namespace/name" for pods, bare name for nodes
    rv: int     # object resourceVersion (monotone per stream)
    obj: dict


def _rv_of(meta: dict) -> int:
    raw = meta.get("resourceVersion")
    try:
        return int(raw)
    except (TypeError, ValueError):
        raise ProtocolError(f"unparseable resourceVersion {raw!r}")


def parse_line(raw: str) -> WatchLine:
    """Parse one watch line; classify every malformation as ProtocolError.

    A server-side ``ERROR`` status line raises ProtocolError carrying the
    status code — 410 is the relist trigger, exactly like live.py's GET
    classification."""
    try:
        d = json.loads(raw)
    except ValueError as e:
        raise ProtocolError(f"undecodable watch line: {e}")
    if not isinstance(d, dict):
        raise ProtocolError("watch line is not an object")
    typ = d.get("type")
    obj = d.get("object") or {}
    if typ == "ERROR":
        code = obj.get("code")
        raise ProtocolError(obj.get("message") or "watch error stream",
                            code=code if isinstance(code, int) else None)
    if typ not in WATCH_TYPES:
        raise ProtocolError(f"unknown watch event type {typ!r}")
    if not isinstance(obj, dict):
        raise ProtocolError("watch object is not a dict")
    meta = obj.get("metadata") or {}
    rv = _rv_of(meta)
    kind = obj.get("kind") or ""
    if typ == "BOOKMARK":
        return WatchLine("BOOKMARK", kind, "", rv, obj)
    if kind == "Pod":
        name = meta.get("name") or ""
        key = f"{meta.get('namespace') or 'default'}/{name}"
    else:
        name = key = meta.get("name") or ""
    if not name:
        raise ProtocolError(f"watch {kind or 'object'} without a name")
    return WatchLine(typ, kind, key, rv, obj)


class TemplateInterner:
    """Share spec subtrees across pods of the same shape (and strip node
    metadata the image never reads). ``hits`` counts pods that reused an
    already-parsed template — the bench's interning-efficacy stat."""

    def __init__(self) -> None:
        self._pods: Dict[str, Tuple[dict, dict]] = {}
        self.hits = 0

    @property
    def templates(self) -> int:
        return len(self._pods)

    def pod(self, obj: dict) -> dict:
        meta = obj.get("metadata") or {}
        spec = obj.get("spec") or {}
        labels = meta.get("labels") or {}
        shape = {k: v for k, v in spec.items() if k != "nodeName"}
        sig = json.dumps((meta.get("namespace") or "default", labels, shape),
                         sort_keys=True, separators=(",", ":"))
        got = self._pods.get(sig)
        if got is None:
            got = (labels, shape)
            self._pods[sig] = got
        else:
            self.hits += 1
        pod: dict = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {"name": meta.get("name"),
                         "namespace": meta.get("namespace") or "default",
                         "labels": got[0]},
            "spec": dict(got[1]),
        }
        node = spec.get("nodeName")
        if node:
            pod["spec"]["nodeName"] = node
        return pod

    def node(self, obj: dict) -> dict:
        # nodes are unique; just drop the bookkeeping subtrees the image
        # never reads so the resident store doesn't retain them
        meta = dict(obj.get("metadata") or {})
        meta.pop("managedFields", None)
        meta.pop("resourceVersion", None)
        out = dict(obj)
        out["metadata"] = meta
        return out


def to_delta(line: WatchLine, interner: TemplateInterner
             ) -> Tuple[Optional[dict], Optional[str]]:
    """WatchLine -> (resident-image delta event, None) or (None, skip
    reason). The image only tracks committed (bound) pods and schedulable
    nodes; everything else is an explicit skip, counted by the sync loop."""
    obj = line.obj
    if line.kind == "Pod":
        if line.type == "DELETED":
            return {"type": "pod_delete", "key": line.key}, None
        meta = obj.get("metadata") or {}
        spec = obj.get("spec") or {}
        if not spec.get("nodeName") or meta.get("deletionTimestamp"):
            return None, "unbound"
        return {"type": "pod_add", "pod": interner.pod(obj)}, None
    if line.kind == "Node":
        name = line.key
        if line.type == "DELETED":
            return {"type": "node_delete", "name": name}, None
        if line.type == "MODIFIED":
            if (obj.get("spec") or {}).get("unschedulable"):
                return {"type": "node_drain", "name": name}, None
            return None, "untracked_change"
        return {"type": "node_add", "node": interner.node(obj)}, None
    return None, "unknown_kind"


def pod_key_of(obj: dict) -> str:
    meta = obj.get("metadata") or {}
    return f"{meta.get('namespace') or 'default'}/{meta.get('name') or ''}"


def reconcile(image, listed_nodes: List[dict], listed_pods: List[dict],
              interner: TemplateInterner
              ) -> Tuple[List[dict], List[str]]:
    """Columnar relist diff: listed truth vs the resident index structures.

    Returns (delta events, inexpressible changes). The event batch is
    canonically ordered — node adds, drains, pod deletes, pod adds, each
    name-sorted — so a reconciled gap applies deterministically regardless
    of the order the list endpoint returned objects in. An inexpressible
    change (a drained node resurrected) is reported instead of approximated;
    the caller rebuilds and re-reconciles."""
    res_pods, res_live = image.sync_snapshot()

    listed_live: Dict[str, dict] = {}
    listed_node_names = set()
    for n in listed_nodes:
        name = (n.get("metadata") or {}).get("name") or ""
        if not name:
            continue
        listed_node_names.add(name)
        if not (n.get("spec") or {}).get("unschedulable"):
            listed_live[name] = n

    inexpressible: List[str] = []
    node_adds: List[dict] = []
    drains: List[dict] = []
    for name in sorted(set(listed_live) - res_live):
        if image.node_state(name) == "drained":
            # the delta path cannot resurrect a drained slot in place
            inexpressible.append(f"resurrected-node:{name}")
        else:
            node_adds.append({"type": "node_add",
                              "node": interner.node(listed_live[name])})
    for name in sorted(res_live - set(listed_live)):
        drains.append({"type": "node_drain", "name": name})

    # committed pods = listed pods bound to a live listed node; pods bound
    # to drained/absent nodes are evicted by the drain above (kube drain
    # semantics, same as the image's own node_drain path)
    listed_bound: Dict[str, Tuple[dict, str]] = {}
    for p in listed_pods:
        meta = p.get("metadata") or {}
        node = (p.get("spec") or {}).get("nodeName")
        if not node or node not in listed_live or meta.get("deletionTimestamp"):
            continue
        listed_bound[pod_key_of(p)] = (p, node)

    deletes: List[dict] = []
    adds: List[dict] = []
    for key in sorted(set(res_pods) - set(listed_bound)):
        deletes.append({"type": "pod_delete", "key": key})
    for key in sorted(set(listed_bound) - set(res_pods)):
        adds.append({"type": "pod_add",
                     "pod": interner.pod(listed_bound[key][0])})
    for key in sorted(set(res_pods) & set(listed_bound)):
        if res_pods[key] != listed_bound[key][1]:  # rebound to another node
            deletes.append({"type": "pod_delete", "key": key})
            adds.append({"type": "pod_add",
                         "pod": interner.pod(listed_bound[key][0])})
    return node_adds + drains + deletes + adds, inexpressible


def verify_parity(image, listed_nodes: List[dict],
                  listed_pods: List[dict]) -> List[str]:
    """Post-reconcile exactness check: the resident sets must now equal the
    listed truth. Any surviving difference is a reconciliation bug, counted
    by the MUST_BE_ZERO parity tripwire."""
    res_pods, res_live = image.sync_snapshot()
    listed_live = set()
    for n in listed_nodes:
        name = (n.get("metadata") or {}).get("name") or ""
        if name and not (n.get("spec") or {}).get("unschedulable"):
            listed_live.add(name)
    listed_keys = set()
    for p in listed_pods:
        node = (p.get("spec") or {}).get("nodeName")
        meta = p.get("metadata") or {}
        if node and node in listed_live and not meta.get("deletionTimestamp"):
            listed_keys.add(pod_key_of(p))
    problems = []
    for name in sorted(res_live ^ listed_live):
        problems.append(f"node:{name}")
    for key in sorted(set(res_pods) ^ listed_keys):
        problems.append(f"pod:{key}")
    return problems
