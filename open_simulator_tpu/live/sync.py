"""simonsync: resilient live-cluster watch sync for the resident image.

A first-party reflector/informer equivalent: one `WatchSync` keeps a
`ResidentImage` (optionally behind the simonha `HAState` WAL) consistent
against an unreliable watch source. The contract, in kube terms:

- **Resumable watch.** The sync tracks a resourceVersion *bookmark* — the
  high-water mark through which every event has been applied. Connection
  flaps reconnect from the bookmark with the policy's seeded backoff
  schedule, so reconnect timing is bit-replayable like every other fault
  path (`RetryPolicy.schedule()`).
- **Exactly-once apply.** Three dedup layers, cheapest first: a global
  `rv <= bookmark` stale filter (everything at or under the bookmark is
  already applied), a per-(kind, name) resourceVersion table (the informer
  cache: duplicates and out-of-order re-deliveries lose the RV race), and a
  presence probe against the resident index (re-deliveries after a crash,
  when the in-memory RV table is gone). Batches are sorted by RV before
  apply, so a reordered wire never changes apply order.
- **Bookmark-delimited batches.** Events buffer until the stream's BOOKMARK
  line (the server's declared safe point) and apply as ONE image batch — so
  the epoch lineage (`generation.seq`) of a chaos-wracked run is identical
  to the flap-free replay: one seq per window, however many times the
  window's events were re-served.
- **410 Gone -> relist reconciliation.** When the server has compacted away
  the bookmark, the sync lists current state and diffs it *columnar*
  against the resident stores (`decode.reconcile` reads the pod index and
  node-name column; no object materialization), emitting only the gap's
  delta events — never a generation-bumping full rebuild unless the diff
  finds an inexpressible change. The gap window rides the simonha
  bounded-staleness machinery (`note_stall`), so degraded-mode headers and
  the staleness ceiling apply while the gap is open.
- **Crash-consistent resume.** When a `state_dir` is given, the bookmark is
  persisted (tmp + fsync + atomic rename) BEFORE each batch applies,
  stamped with the image seq the apply will produce. On restart the seq
  disambiguates: seq reached the stamp -> the batch landed, resume from
  `next_rv`; it didn't -> the batch was lost, resume from `prev_rv`.
  Combined with the PR 19 WAL that makes SIGKILL mid-stream resume exact:
  checkpoint + WAL tail rebuild the image, the bookmark file pins the
  stream position, and re-delivered windows dedup to empty batches.

Fault sites: `watch_read` (one line read), `watch_parse` (one line decode),
`watch_gone` (server-side compaction -> forced 410), `relist` (the recovery
list call). All four join the simonfault registry's replay-equality
contract.
"""

from __future__ import annotations

import http.client
import json
import os
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from ..obs import instruments as obs
from ..obs import pulse
from ..resilience import faults
from ..resilience.policy import CircuitBreaker, RetryPolicy
from ..simulator.live import AuthError, ProtocolError, TransientError
from . import decode

BOOKMARK_NAME = "sync.bookmark.json"

__all__ = [
    "WatchSource", "RecordedSource", "QueueSource", "ScriptedSource",
    "HttpWatchSource", "WatchSync", "BOOKMARK_NAME", "kube_watch_sources",
]


# ------------------------------------------------------------------ sources ---


class WatchSource:
    """One unreliable delta feed. `watch(since_rv)` yields raw JSON lines
    and is expected to fail: TransientError tears the stream down for a
    bookmark reconnect, ProtocolError(code=410) forces relist
    reconciliation, AuthError aborts. BOOKMARK lines are the server's safe
    points — reorders never cross them and batches flush at them."""

    def watch(self, since_rv: int) -> Iterator[str]:
        raise NotImplementedError

    def list(self) -> Tuple[int, List[dict], List[dict]]:
        """(resourceVersion, nodes, pods) — current state, for relist."""
        raise ProtocolError("this watch source cannot list")

    def close(self) -> None:
        pass


class RecordedSource(WatchSource):
    """A recorded JSONL stream (bench/CI): every line is replayed on every
    connect; the sync's stale/dedup filters make resumption exact."""

    def __init__(self, lines: Optional[List[str]] = None,
                 path: Optional[str] = None) -> None:
        if (lines is None) == (path is None):
            raise ValueError("exactly one of lines/path")
        self._lines = lines
        self._path = path

    def watch(self, since_rv: int) -> Iterator[str]:
        if self._lines is not None:
            yield from self._lines
            return
        with open(self._path, "r", encoding="utf-8") as f:
            for raw in f:
                raw = raw.strip()
                if raw:
                    yield raw


class QueueSource(WatchSource):
    """An in-process push feed (loadgen churn, tests): `push()` lines in,
    `close()` ends the stream cleanly."""

    _CLOSE = object()

    def __init__(self, maxsize: int = 4096) -> None:
        import queue

        # bounded: a sync thread that falls behind back-pressures the
        # producer at push() instead of absorbing the backlog into heap
        self._q = queue.Queue(maxsize=maxsize)

    def push(self, line: str) -> None:
        self._q.put(line)

    def close(self) -> None:
        self._q.put(self._CLOSE)

    def watch(self, since_rv: int) -> Iterator[str]:
        while True:
            item = self._q.get()
            if item is self._CLOSE:
                return
            yield item


class ScriptedSource(WatchSource):
    """A scripted in-process apiserver for chaos tests: serves a clean
    recorded stream with seeded flaps, duplicates, adjacent reorders, and
    410-Gone compactions injected at deterministic positions. Compactions
    land on bookmark boundaries and swallow exactly one window, so a
    reconciled gap costs exactly the one image batch its lost window would
    have — the epoch-parity construction the chaos gate asserts.
    """

    def __init__(self, lines: List[str], seed: int = 0, flap_p: float = 0.0,
                 dup_p: float = 0.0, reorder_p: float = 0.0,
                 gone_p: float = 0.0,
                 base_nodes: Optional[List[dict]] = None,
                 base_pods: Optional[List[dict]] = None) -> None:
        import random

        # the cluster state that predates the stream — list() answers must
        # include it or a relist would "reconcile away" the whole base
        self._base_nodes = list(base_nodes or [])
        self._base_pods = list(base_pods or [])
        self._clean: List[decode.WatchLine] = [decode.parse_line(x)
                                               for x in lines]
        self._floor = 0
        self._fired: Dict[int, bool] = {}  # wire index -> one-shot fault spent
        rng = random.Random(seed)

        # group into bookmark-delimited windows
        windows: List[List[int]] = [[]]
        for i, ln in enumerate(self._clean):
            windows[-1].append(i)
            if ln.type == "BOOKMARK":
                windows.append([])
        if not windows[-1]:
            windows.pop()

        # wire plan: ("line", rv, raw) | ("flap", after_rv) |
        #            ("gone", trigger_rv, floor_rv)
        wire: List[tuple] = []
        for w, idxs in enumerate(windows):
            events = [i for i in idxs if self._clean[i].type != "BOOKMARK"]
            bmarks = [i for i in idxs if self._clean[i].type == "BOOKMARK"]
            if w > 0 and events and bmarks and rng.random() < gone_p:
                wire.append(("gone", self._clean[events[0]].rv,
                             self._clean[bmarks[-1]].rv))
            order = list(events)
            k = 0
            while k < len(order) - 1:
                if rng.random() < reorder_p:
                    order[k], order[k + 1] = order[k + 1], order[k]
                    k += 2
                else:
                    k += 1
            for i in order:
                ln = self._clean[i]
                wire.append(("line", ln.rv, lines[i]))
                if rng.random() < dup_p:
                    wire.append(("line", ln.rv, lines[i]))
                if rng.random() < flap_p:
                    wire.append(("flap", ln.rv))
            for i in bmarks:
                wire.append(("line", self._clean[i].rv, lines[i]))
        self._wire = wire
        self.flaps_planned = sum(1 for e in wire if e[0] == "flap")
        self.gones_planned = sum(1 for e in wire if e[0] == "gone")

    def watch(self, since_rv: int) -> Iterator[str]:
        if since_rv < self._floor:
            raise ProtocolError("resourceVersion too old", code=410)
        for wi, entry in enumerate(self._wire):
            kind = entry[0]
            if kind == "line":
                if entry[1] > since_rv:
                    yield entry[2]
            elif kind == "flap":
                # one-shot: a reconnect replaying the same window must not
                # trip over the same scripted flap forever
                if entry[1] > since_rv and not self._fired.get(wi):
                    self._fired[wi] = True
                    raise TransientError("connection reset by chaos script")
            else:  # gone
                trigger_rv, floor_rv = entry[1], entry[2]
                if trigger_rv > since_rv and not self._fired.get(wi):
                    self._fired[wi] = True
                    self._floor = max(self._floor, floor_rv)
                    raise ProtocolError(
                        "resourceVersion compacted", code=410)

    def list(self) -> Tuple[int, List[dict], List[dict]]:
        rv = self._floor or (self._clean[-1].rv if self._clean else 0)
        return self.state_at(rv)

    def state_at(self, rv: int) -> Tuple[int, List[dict], List[dict]]:
        """Replay the clean stream to `rv`: the apiserver's list answer.
        Node drains/deletes evict bound pods, mirroring the cluster's own
        lifecycle (and the image's node_drain semantics)."""
        nodes: Dict[str, dict] = {
            (n.get("metadata") or {}).get("name") or "": n
            for n in self._base_nodes}
        pods: Dict[str, dict] = {decode.pod_key_of(p): p
                                 for p in self._base_pods}
        for ln in self._clean:
            if ln.rv > rv:
                break
            if ln.type == "BOOKMARK":
                continue
            if ln.kind == "Node":
                if ln.type == "DELETED":
                    nodes.pop(ln.key, None)
                else:
                    nodes[ln.key] = ln.obj
                if ln.type == "DELETED" or (
                        (ln.obj.get("spec") or {}).get("unschedulable")):
                    pods = {k: p for k, p in pods.items()
                            if (p.get("spec") or {}).get("nodeName") != ln.key}
            elif ln.kind == "Pod":
                if ln.type == "DELETED":
                    pods.pop(ln.key, None)
                else:
                    pods[ln.key] = ln.obj
        return rv, list(nodes.values()), list(pods.values())


class HttpWatchSource(WatchSource):
    """The real chunked-HTTP watch, classified through live.py's typed
    taxonomy: 401/403 AuthError (never retried), 410 ProtocolError(code)
    (relist), 429/5xx and every socket-level failure TransientError
    (bookmark reconnect), undecodable bodies ProtocolError."""

    def __init__(self, watch_url: str, list_url: Optional[str] = None,
                 token: Optional[str] = None, ssl_ctx=None,
                 timeout: float = 30.0) -> None:
        self.watch_url = watch_url
        self.list_url = list_url
        self.token = token
        self.ssl_ctx = ssl_ctx
        self.timeout = timeout

    def _open(self, url: str):
        headers = {"Accept": "application/json"}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        req = urllib.request.Request(url, headers=headers)
        try:
            return urllib.request.urlopen(req, timeout=self.timeout,
                                          context=self.ssl_ctx)
        except urllib.error.HTTPError as e:
            code = e.code
            if code in (401, 403):
                raise AuthError(f"HTTP {code} from {url}")
            if code == 410:
                raise ProtocolError(f"HTTP 410 from {url}", code=410)
            if code == 429 or 500 <= code <= 599:
                ra = 0.0
                try:
                    ra = float(e.headers.get("Retry-After") or 0.0)
                except (TypeError, ValueError):
                    ra = 0.0
                raise TransientError(f"HTTP {code} from {url}",
                                     retry_after=ra, code=code)
            raise ProtocolError(f"HTTP {code} from {url}", code=code)
        except (OSError, http.client.HTTPException) as e:
            raise TransientError(f"connect to {url} failed: {e}")

    def watch(self, since_rv: int) -> Iterator[str]:
        sep = "&" if "?" in self.watch_url else "?"
        url = f"{self.watch_url}{sep}resourceVersion={since_rv}"
        resp = self._open(url)
        try:
            while True:
                try:
                    raw = resp.readline()
                except (OSError, http.client.HTTPException) as e:
                    raise TransientError(f"watch read failed: {e}")
                if not raw:
                    # server closed the stream: reflectors re-watch, so a
                    # clean EOF is a transient teardown, not completion
                    raise TransientError("watch stream ended")
                line = raw.decode("utf-8", "replace").strip()
                if line:
                    yield line
        finally:
            try:
                resp.close()
            # simonlint: ignore[unclassified-network-error] -- best-effort
            # close of an already-failed stream; the read path above has
            # already routed the real failure
            except OSError:
                pass

    def list(self) -> Tuple[int, List[dict], List[dict]]:
        if not self.list_url:
            raise ProtocolError("no list endpoint configured")
        resp = self._open(self.list_url)
        try:
            try:
                body = resp.read()
            except (OSError, http.client.HTTPException) as e:
                raise TransientError(f"list read failed: {e}")
        finally:
            try:
                resp.close()
            # simonlint: ignore[unclassified-network-error] -- best-effort
            # close after the body is already read (or its failure routed)
            except OSError:
                pass
        try:
            d = json.loads(body.decode("utf-8", "replace"))
        except ValueError as e:
            raise ProtocolError(f"undecodable list body: {e}")
        meta = d.get("metadata") or {}
        try:
            rv = int(d.get("resourceVersion") or meta.get("resourceVersion"))
        except (TypeError, ValueError):
            raise ProtocolError("list body without a resourceVersion")
        if "items" in d:  # kube-style single-resource list
            kind = (d.get("kind") or "").replace("List", "")
            items = d.get("items") or []
            for it in items:
                it.setdefault("kind", kind)
            nodes = [it for it in items if it.get("kind") == "Node"]
            pods = [it for it in items if it.get("kind") == "Pod"]
            return rv, nodes, pods
        return rv, d.get("nodes") or [], d.get("pods") or []


def kube_watch_sources(client) -> List["HttpWatchSource"]:
    """Two sources (nodes, pods) over a live apiserver, reusing the
    KubeClient's endpoint, bearer token, and TLS context."""
    base = client.server.rstrip("/")
    return [
        HttpWatchSource(f"{base}/api/v1/nodes?watch=1",
                        list_url=f"{base}/api/v1/nodes",
                        token=client.token, ssl_ctx=client.ssl_ctx),
        HttpWatchSource(f"{base}/api/v1/pods?watch=1",
                        list_url=f"{base}/api/v1/pods",
                        token=client.token, ssl_ctx=client.ssl_ctx),
    ]


# --------------------------------------------------------------------- sync ---

# watch-loop defaults: quicker first retry than the GET policy (a torn
# stream usually reconnects instantly) but the same determinism contract
WATCH_RETRY = RetryPolicy(max_attempts=6, base=0.05, mult=2.0, cap=2.0,
                          jitter=0.2, max_elapsed=60.0, seed=0)


class WatchSync:
    """The reflector loop. Drives `source` into `image` (or, when `ha` is
    given, through `HAState.ingest` so every batch rides the WAL)."""

    def __init__(self, source: WatchSource, image=None, ha=None,
                 state_dir: Optional[str] = None,
                 retry: Optional[RetryPolicy] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 max_flap_streak: int = 12, name: str = "") -> None:
        self.name = name
        if ha is not None:
            image = ha.image
            state_dir = state_dir or ha.state_dir
        if image is None:
            raise ValueError("WatchSync needs an image or an HAState")
        self.source = source
        self.image = image
        self.ha = ha
        self.state_dir = state_dir
        self.retry = retry or WATCH_RETRY
        self.breaker = breaker
        self.sleep = sleep
        self.max_flap_streak = int(max_flap_streak)
        self.interner = decode.TemplateInterner()
        self._rv: Dict[Tuple[str, str], int] = {}
        self.bookmark = self._load_bookmark()
        self.sleeps: List[float] = []  # observed backoff (determinism tests)
        self.batches = 0
        self.applied = 0
        self.duplicates = 0
        self.stale = 0
        self.skipped = 0
        self.reconnects = 0
        self.relists = 0
        self.full_rebuilds = 0
        self.parity_mismatches = 0
        self._t_decode = 0.0

    # --------------------------------------------------------- bookmarking ---

    def _seq(self) -> int:
        return int(self.image.seq)

    def _bookmark_path(self) -> str:
        # one bookmark file per named source (kube mode runs nodes + pods
        # loops against one state dir)
        base = (BOOKMARK_NAME if not self.name
                else BOOKMARK_NAME.replace(".json", f".{self.name}.json"))
        return os.path.join(self.state_dir, base)

    def _load_bookmark(self) -> int:
        if not self.state_dir:
            return 0
        path = self._bookmark_path()
        try:
            with open(path, "r", encoding="utf-8") as f:
                d = json.load(f)
        # simonlint: ignore[unclassified-network-error] -- local bookmark
        # file read, not a network path: missing/torn file means cold start
        except (OSError, ValueError):
            return 0
        try:
            if self._seq() >= int(d.get("expected_seq", 0)):
                rv = int(d.get("next_rv", 0))
            else:
                rv = int(d.get("prev_rv", 0))
        except (TypeError, ValueError):
            return 0
        obs.SYNC_BOOKMARK_RV.set(float(rv))
        return rv

    def _write_bookmark(self, prev_rv: int, next_rv: int,
                        expected_seq: int) -> None:
        """Persist BEFORE the apply, stamped with the seq the apply will
        produce; restart resolves prev/next by comparing the restored seq
        against the stamp (crash on either side of the apply is exact)."""
        if not self.state_dir:
            return
        path = self._bookmark_path()
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"prev_rv": int(prev_rv), "next_rv": int(next_rv),
                       "expected_seq": int(expected_seq)}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    # --------------------------------------------------------------- dedup ---

    def _effective(self, ev: dict, staged: Dict[Tuple[str, str], str]
                   ) -> Optional[Tuple[Tuple[str, str], str]]:
        """(staged key, new staged state) when the event changes effective
        state; None when it is a presence duplicate (already reflected by
        the image or by an earlier event staged in this batch)."""
        typ = ev["type"]
        if typ == "pod_add":
            key = decode.pod_key_of(ev["pod"])
            k = ("Pod", key)
            cur = staged.get(k) or (
                "present" if self.image.has_pod(key) else "absent")
            return (k, "present") if cur == "absent" else None
        if typ == "pod_delete":
            k = ("Pod", ev["key"])
            cur = staged.get(k) or (
                "present" if self.image.has_pod(ev["key"]) else "absent")
            return (k, "absent") if cur == "present" else None
        if typ == "node_add":
            name = ((ev.get("node") or {}).get("metadata") or {}).get(
                "name") or ""
            k = ("Node", name)
            cur = staged.get(k) or self.image.node_state(name)
            return (k, "live") if cur == "absent" else None
        if typ in ("node_drain", "node_delete"):
            k = ("Node", ev["name"])
            cur = staged.get(k) or self.image.node_state(ev["name"])
            return (k, "drained") if cur == "live" else None
        return None

    # --------------------------------------------------------------- apply ---

    def _apply(self, events: List[dict]) -> None:
        t0 = time.perf_counter()
        if self.ha is not None:
            self.ha.ingest(events)
        else:
            self.image.apply_events(events)
        pulse.phase("sync_apply", time.perf_counter() - t0)

    def _flush(self, window: List[decode.WatchLine], new_rv: int) -> None:
        """Decode, dedup, and apply one bookmark-delimited window.

        Dedup runs over the rv-SORTED window, not arrival order: deciding
        per line would let a wire reorder poison the window (a re-add of a
        resident pod arriving before its own delete reads as a presence
        duplicate, and its higher rv then swallows the delete from the
        per-key rv table — the window nets to nothing where the in-order
        stream applied delete+add). Sorting first makes arrival order
        unobservable, so chaos and clean replays stage identical batches."""
        t0 = time.perf_counter()
        window.sort(key=lambda ln: ln.rv)
        batch: List[dict] = []
        pend_rv: Dict[Tuple[str, str], int] = {}
        staged: Dict[Tuple[str, str], str] = {}
        for line in window:
            k = (line.kind, line.key)
            if line.rv <= max(self._rv.get(k, 0), pend_rv.get(k, 0)):
                self.duplicates += 1
                obs.SYNC_EVENTS.labels(outcome="duplicate").inc()
                continue
            pend_rv[k] = line.rv
            ev, _skip = decode.to_delta(line, self.interner)
            if ev is None:
                self.skipped += 1
                obs.SYNC_EVENTS.labels(outcome="skipped").inc()
                continue
            eff = self._effective(ev, staged)
            if eff is None:
                self.duplicates += 1
                obs.SYNC_EVENTS.labels(outcome="duplicate").inc()
                continue
            staged[eff[0]] = eff[1]
            batch.append(ev)
        self._t_decode += time.perf_counter() - t0
        if self._t_decode:
            pulse.phase("sync_decode", self._t_decode)
            self._t_decode = 0.0
        if batch:
            self._write_bookmark(self.bookmark, max(new_rv, self.bookmark),
                                 self._seq() + 1)
            self._apply(batch)
            self.batches += 1
            self.applied += len(batch)
            obs.SYNC_EVENTS.labels(outcome="applied").inc(len(batch))
        elif new_rv > self.bookmark:
            self._write_bookmark(new_rv, new_rv, 0)
        if new_rv > self.bookmark:
            self.bookmark = new_rv
            obs.SYNC_BOOKMARK_RV.set(float(new_rv))
        self._rv.update(pend_rv)

    # ------------------------------------------------------------- consume ---

    def _consume(self, stop: Optional[threading.Event]) -> bool:
        it = self.source.watch(self.bookmark)
        window: List[decode.WatchLine] = []
        max_rv = self.bookmark
        made_progress = False
        for raw in it:
            if stop is not None and stop.is_set():
                self._flush(window, max_rv)
                return True
            faults.maybe_fail("watch_read")
            try:
                faults.maybe_fail("watch_gone")
            except ProtocolError as e:
                # this site models the SERVER compacting our horizon away
                raise ProtocolError(f"watch expired: {e}", code=410)
            t0 = time.perf_counter()
            try:
                faults.maybe_fail("watch_parse")
                line = decode.parse_line(raw)
            finally:
                self._t_decode += time.perf_counter() - t0
            if line.type == "BOOKMARK":
                # flush outside the decode timer: sync_decode and
                # sync_apply must decompose the wall, not overlap it
                self._flush(window, max(max_rv, line.rv))
                window = []
                max_rv = self.bookmark
                made_progress = True
                continue
            if line.rv <= self.bookmark:
                self.stale += 1
                obs.SYNC_EVENTS.labels(outcome="stale").inc()
                continue
            window.append(line)
            max_rv = max(max_rv, line.rv)
        self._flush(window, max_rv)
        return True

    # -------------------------------------------------------------- relist ---

    def _relist(self) -> None:
        self.relists += 1
        obs.SYNC_RELISTS.inc()
        if self.ha is not None:
            self.ha.note_stall("watch_gone")

        def _do():
            faults.maybe_fail("relist")
            return self.source.list()

        rv, nodes, pods = self.retry.call(
            _do, site="sync_relist",
            retryable=lambda e: isinstance(e, TransientError),
            breaker=self.breaker, sleep=self.sleep)
        t0 = time.perf_counter()
        events, inexpressible = decode.reconcile(
            self.image, nodes, pods, self.interner)
        if inexpressible:
            # the delta path declined; take the image's documented escape
            # hatch (generation bump) and re-diff against the fresh truth
            self.full_rebuilds += 1
            obs.SYNC_FULL_REBUILDS.inc()
            if self.ha is not None:
                self.ha.resync()
            else:
                with self.image._lock:
                    self.image._rebuild()
            events, _ = decode.reconcile(self.image, nodes, pods,
                                         self.interner)
        pulse.phase("sync_reconcile", time.perf_counter() - t0)
        # a reconciled gap costs exactly ONE image batch — the same seq its
        # lost window would have cost the flap-free run — even when the diff
        # turns out empty
        self._write_bookmark(self.bookmark, max(rv, self.bookmark),
                             self._seq() + 1)
        self._apply(events)
        self.batches += 1
        self.applied += len(events)
        if events:
            obs.SYNC_EVENTS.labels(outcome="applied").inc(len(events))
        problems = decode.verify_parity(self.image, nodes, pods)
        if problems:
            self.parity_mismatches += len(problems)
            obs.SYNC_PARITY.inc(len(problems))
        if rv > self.bookmark:
            self.bookmark = rv
            obs.SYNC_BOOKMARK_RV.set(float(rv))
        self._rv = {k: v for k, v in self._rv.items() if v > rv}

    # ----------------------------------------------------------------- run ---

    def run(self, stop: Optional[threading.Event] = None) -> dict:
        """Consume the source to completion (recorded/queue streams end;
        live streams run until `stop`). Flaps reconnect from the bookmark
        on the seeded schedule; 410 relists; auth errors and exhausted
        backoff raise."""
        sched = self.retry.schedule()
        streak = 0
        last_fail_bookmark = -1
        while not (stop is not None and stop.is_set()):
            try:
                if self.breaker is not None:
                    self.breaker.before_call()
                done = self._consume(stop)
                if self.breaker is not None:
                    self.breaker.record_success()
                if done:
                    break
            except AuthError:
                raise  # never retried: actionable, not transient
            except (TransientError, ProtocolError) as e:
                if isinstance(e, ProtocolError):
                    if getattr(e, "code", None) == 410:
                        self._relist()
                        streak = 0
                        continue
                    # undecodable stream: tear down and re-watch, bounded
                if self.breaker is not None:
                    self.breaker.record_failure()
                if self.bookmark > last_fail_bookmark:
                    # the stream advanced since the last failure: a flap on
                    # a moving watch, not a wedged endpoint — the streak
                    # bound guards consecutive NO-PROGRESS failures only
                    streak = 0
                last_fail_bookmark = self.bookmark
                streak += 1
                self.reconnects += 1
                obs.SYNC_RECONNECTS.inc()
                if streak > self.max_flap_streak:
                    raise
                delay = max(sched[min(streak - 1, len(sched) - 1)],
                            float(getattr(e, "retry_after", 0.0) or 0.0))
                self.sleeps.append(delay)
                self.sleep(delay)
        return self.stats()

    def start_thread(self, stop: threading.Event) -> threading.Thread:
        t = threading.Thread(target=self.run, args=(stop,),
                             name="watch-sync", daemon=True)
        t.start()
        return t

    def stats(self) -> dict:
        return {
            "bookmark": self.bookmark,
            "batches": self.batches,
            "applied": self.applied,
            "duplicates": self.duplicates,
            "stale": self.stale,
            "skipped": self.skipped,
            "reconnects": self.reconnects,
            "relists": self.relists,
            "full_rebuilds": self.full_rebuilds,
            "parity_mismatches": self.parity_mismatches,
            "templates": self.interner.templates,
            "template_hits": self.interner.hits,
        }
