"""simonpulse: roofline cost accounting + the per-dispatch performance ledger.

The fourth observability layer (metrics → xray → scope → **pulse**): the
first three answer *what happened* (counters), *why this pod* (decisions),
and *where a request's latency went* (traces); pulse answers *was the device
work as fast as it should have been* — continuously, per dispatch, against
the compiled cost model. Clipper's argument (PAPERS.md) is that latency
objectives are only enforceable when every request's cost is continuously
attributed per model/endpoint; here the unit is one kernel dispatch per
static-shape bucket per mesh.

Three parts:

1. **Performance ledger.** Every `guard.supervised` kernel dispatch appends
   one bounded-ring-buffer record: kernel, dispatch digest (the
   static-shape-bucket identity, sha256 over the same (kernel, static dims)
   payload family simonaudit certificates digest — analysis/hlo.py
   `dispatch_digest`), mesh label, pod count, supervised unit wall,
   warm/cold compile flag, and the enclosing run id whose record carries
   the encode / table_build / to_device / dispatch / fetch / commit wall
   decomposition from the engine's existing Span steps. A digest change
   across a slowdown means "executable changed"; the same digest means
   "same executable, slower environment". Optional JSONL spill with size
   rotation keeps every record; the ring keeps the most recent
   OPEN_SIMULATOR_PULSE_CAP and counts every eviction
   (simon_pulse_records_dropped_total — never silent).

2. **Roofline cost model.** `compiled.cost_analysis()` FLOPs / bytes
   accessed are harvested (a) statically for every HOT_KERNELS entry at the
   canonical audit buckets × 1/2/8-shard meshes — the `cost` field of the
   simonaudit certificates, read back by `roofline_table()` — and (b)
   optionally at dispatch time (OPEN_SIMULATOR_PULSE_ROOFLINE=1) on each
   COLD dispatch at the real shape, giving per-(kernel, digest)
   model-optimal seconds `max(flops/peak_flops, bytes/peak_bw)` and an
   achieved-fraction gauge per warm dispatch. Peaks come from
   OPEN_SIMULATOR_PEAK_GFLOPS / OPEN_SIMULATOR_PEAK_GBS (conservative host
   defaults; set them to the accelerator's datasheet numbers there).

3. **Drift detection.** Rolling per-(kernel, digest) warm-wall windows with
   MAD outlier flagging: a warm dispatch slower than
   `median + k·1.4826·MAD` (k = OPEN_SIMULATOR_PULSE_MAD_K, with an
   absolute floor so deterministic µs-scale walls cannot false-positive)
   increments `simon_pulse_regressions_total{kernel,bucket}` and flags the
   record. Surfaced via `simon pulse`, `GET /v1/pulse`, and perfetto
   counter tracks merged into the scope trace dump.

Attribution contract (the part that must not drift): `record_dispatch`
(obs/instruments.py) is THE definition of one kernel dispatch; pulse hooks
it (`_DISPATCH_HOOK`) and parks each note on a contextvar pending list.
`guard.supervised` calls `ensure_window()` BEFORE copying the context —
the list object itself crosses into the worker thread by reference (the
scope phase-sink pattern), so sites that note inside the supervised body
(simulator/probe.py's multi-segment rounds) land in the caller-visible
list — and drains it into ledger records after the unit returns, cold or
warm, success or failure. Sites therefore pair `record_dispatch` with the
`guard.supervised` that dispatches it; the simonlint `unattributed-dispatch`
rule warns on hot-kernel dispatches outside this pairing.

Off by default. Pulse off costs one global read per dispatch and moves ZERO
metric samples: every simon_pulse_* family is labeled, and an untouched
labeled family renders no samples, so placements AND /metrics stay
bit-identical to pre-pulse builds (tests/test_pulse.py proves both).
Host-side only; no jax imports, ever.
"""

from __future__ import annotations

import contextlib
import contextvars
import functools
import json
import os
import statistics
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from . import instruments
from .instruments import (
    PULSE_ACHIEVED,
    PULSE_DROPPED,
    PULSE_PHASE_SECONDS,
    PULSE_RECORDS,
    PULSE_REGRESSIONS,
)

DEFAULT_CAP = 4096
DEFAULT_MAD_K = 5.0
DEFAULT_MAD_WINDOW = 64
DEFAULT_MAD_MIN = 8
DEFAULT_JSONL_MAX_MB = 64.0
# Conservative single-host defaults: a few-core AVX2 box sustains tens of
# GFLOP/s and tens of GB/s on the kernels' mixed int/float work. They exist
# so achieved-fraction is always computable; absolute calibration comes from
# the env knobs on real accelerators.
DEFAULT_PEAK_GFLOPS = 50.0
DEFAULT_PEAK_GBS = 20.0

RUN_PHASES = ("encode", "table_build", "to_device", "dispatch", "fetch",
              "commit")


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        return default


# ------------------------------------------------------------ roofline math ---


def peak_rates() -> Tuple[float, float]:
    """(peak FLOP/s, peak bytes/s) from the env knobs (GFLOPS / GB/s)."""
    return (_env_float("OPEN_SIMULATOR_PEAK_GFLOPS", DEFAULT_PEAK_GFLOPS) * 1e9,
            _env_float("OPEN_SIMULATOR_PEAK_GBS", DEFAULT_PEAK_GBS) * 1e9)


def normalize_cost(raw) -> Optional[Dict[str, float]]:
    """cost_analysis() output → {"flops", "bytes_accessed"}, or None.

    jax returns a dict on current versions and a one-element list of dicts
    on older ones; bytes may be keyed "bytes accessed" or split per operand
    ("bytes accessed operand 0 {}" etc. — the total key wins when present)."""
    if isinstance(raw, (list, tuple)):
        raw = raw[0] if raw else None
    if not isinstance(raw, dict):
        return None
    flops = float(raw.get("flops", 0.0) or 0.0)
    by = raw.get("bytes accessed", raw.get("bytes_accessed"))
    if by is None:
        by = sum(float(v) for k, v in raw.items()
                 if isinstance(k, str) and k.startswith("bytes accessed"))
    by = float(by or 0.0)
    if flops <= 0.0 and by <= 0.0:
        return None
    return {"flops": flops, "bytes_accessed": by}


def model_optimal_s(cost: Dict[str, float],
                    peak_flops: Optional[float] = None,
                    peak_bw: Optional[float] = None) -> float:
    """Roofline model-optimal seconds: the kernel cannot run faster than its
    FLOPs at peak compute nor its bytes at peak bandwidth — whichever wall
    it hits first is the model optimum."""
    pf, pb = peak_rates()
    if peak_flops:
        pf = peak_flops
    if peak_bw:
        pb = peak_bw
    return max(cost.get("flops", 0.0) / pf, cost.get("bytes_accessed", 0.0) / pb)


def roofline_table(golden_dir: Optional[str] = None) -> List[dict]:
    """The static roofline: one row per (kernel, bucket, mesh) audit
    certificate carrying a `cost` field — {kernel, bucket, mesh, flops,
    bytes_accessed, model_optimal_s}. Reads the checked-in simonaudit
    goldens; no jax, no compilation."""
    if golden_dir is None:
        from ..analysis.hlo import _default_golden_dir

        golden_dir = _default_golden_dir()
    rows: List[dict] = []
    if not os.path.isdir(golden_dir):
        return rows
    for fname in sorted(os.listdir(golden_dir)):
        if not fname.endswith(".json"):
            continue
        try:
            with open(os.path.join(golden_dir, fname), encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        for key in sorted(doc.get("certs", {})):
            cert = doc["certs"][key]
            cost = normalize_cost(cert.get("cost"))
            if cost is None:
                continue
            rows.append({
                "kernel": cert.get("kernel", fname[:-5]),
                "bucket": cert.get("bucket", ""),
                "mesh": cert.get("mesh", ""),
                "flops": cost["flops"],
                "bytes_accessed": cost["bytes_accessed"],
                "model_optimal_s": model_optimal_s(cost),
            })
    return rows


# ------------------------------------------------- attribution contextvars ----

# The pending list: (kernel, dims, cold) notes parked between record_dispatch
# and the guard.supervised unit that dispatches them. The list OBJECT is
# shared by reference into supervised's copied context (ensure_window runs
# before copy_context), so worker-side notes land in the caller's list.
_PENDING: contextvars.ContextVar[Optional[list]] = contextvars.ContextVar(
    "simon_pulse_pending", default=None)

# The enclosing scheduling run (dict with id / pods / phases), if any.
_RUN: contextvars.ContextVar[Optional[dict]] = contextvars.ContextVar(
    "simon_pulse_run", default=None)


def note_dispatch(kernel: str, dims: Dict[str, Any], cold: bool) -> None:
    """The instruments._DISPATCH_HOOK target: park one dispatch note for the
    supervised unit that will execute it. No-op overhead path lives in
    record_dispatch itself (hook is None when pulse is off)."""
    pending = _PENDING.get()
    if pending is None:
        pending = []
        _PENDING.set(pending)
    pending.append((kernel, dims, cold))


def ensure_window() -> Optional[list]:
    """Make the pending list exist in THIS context before guard.supervised
    copies it into a worker thread, so worker-side record_dispatch calls
    (probe rounds) append to the caller-visible list by reference."""
    pending = _PENDING.get()
    if pending is None:
        pending = []
        _PENDING.set(pending)
    return pending


# ---------------------------------------------------------------- the ledger --


class Pulse:
    """Process-wide performance ledger + drift detector. Build via
    `enable()`; `active()` is the zero-cost gate every site starts from."""

    def __init__(self, capacity: int = 0, jsonl: Optional[str] = None,
                 jsonl_max_mb: float = 0.0, mad_k: float = 0.0,
                 mad_window: int = DEFAULT_MAD_WINDOW,
                 mad_min: int = DEFAULT_MAD_MIN,
                 roofline_dispatch: Optional[bool] = None) -> None:
        self.capacity = capacity or _env_int("OPEN_SIMULATOR_PULSE_CAP",
                                             DEFAULT_CAP)
        self.mad_k = mad_k or _env_float("OPEN_SIMULATOR_PULSE_MAD_K",
                                         DEFAULT_MAD_K)
        self.mad_window = mad_window
        self.mad_min = mad_min
        if roofline_dispatch is None:
            roofline_dispatch = os.environ.get(
                "OPEN_SIMULATOR_PULSE_ROOFLINE", "") not in ("", "0", "false")
        self.roofline_dispatch = roofline_dispatch
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self.n_total = 0
        self.n_dropped = 0
        self._seq = 0
        self._run_seq = 0
        # per-(kernel, digest): rolling warm walls, regression counts,
        # harvested dispatch-shape costs, digest memo
        self._windows: Dict[Tuple[str, str], deque] = {}
        self._reg_counts: Dict[Tuple[str, str], int] = {}
        self._costs: Dict[Tuple[str, str], Dict[str, float]] = {}
        self._digests: Dict[tuple, str] = {}
        self._phase_totals: Dict[str, float] = {}
        # JSONL spill (complete record stream; the ring is the bounded view)
        self._jsonl_path = jsonl if jsonl is not None else os.environ.get(
            "OPEN_SIMULATOR_PULSE_JSONL", "") or None
        self._jsonl_max = (jsonl_max_mb or _env_float(
            "OPEN_SIMULATOR_PULSE_JSONL_MAX_MB", DEFAULT_JSONL_MAX_MB)) * 1e6
        self._jsonl_f = None
        self._jsonl_warned = False

    # ----------------------------------------------------------- appending --

    def _append(self, rec: dict) -> None:
        kind = rec["kind"]
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            if len(self._ring) == self._ring.maxlen:
                self.n_dropped += 1
                PULSE_DROPPED.labels(kind=self._ring[0]["kind"]).inc()
            self._ring.append(rec)
            self.n_total += 1
        PULSE_RECORDS.labels(kind=kind).inc()
        self._spill(rec)

    def _spill(self, rec: dict) -> None:
        try:
            with self._lock:
                path = self._jsonl_path
                if not path:
                    return
                line = json.dumps(rec, sort_keys=True) + "\n"
                f = self._jsonl_f
                if f is None:
                    f = self._jsonl_f = open(path, "a", encoding="utf-8")
                f.write(line)
                if f.tell() >= self._jsonl_max:
                    # one rotation level: the previous generation is enough
                    # to cover "the slowdown started before the current file"
                    f.close()
                    self._jsonl_f = None
                    os.replace(path, path + ".1")
        except OSError:
            # a full disk must never fail a scheduling call; stop spilling
            # loudly once (the ring + counters keep working). The `with
            # self._lock` above released on the way out, so re-acquire.
            with self._lock:
                self._jsonl_path = None
                self._jsonl_f = None
            if not self._jsonl_warned:
                self._jsonl_warned = True
                import logging

                logging.getLogger("open_simulator_tpu").exception(
                    "pulse: JSONL spill failed; disabling spill for this "
                    "process (in-memory ledger unaffected)")

    # --------------------------------------------------------------- digest --

    def _digest_for(self, kernel: str, dims: Dict[str, Any]) -> str:
        key = (kernel,) + tuple(sorted((k, repr(v)) for k, v in dims.items()))
        d = self._digests.get(key)
        if d is None:
            from ..analysis.hlo import dispatch_digest

            d = self._digests[key] = dispatch_digest(kernel, dims)
        return d

    # ------------------------------------------------------- unit lifecycle --

    def commit_unit(self, *, site: str, pods: int, wall_s: float,
                    ok: bool = True, fn=None) -> None:
        """Drain this context's pending dispatch notes into ledger records,
        all sharing the supervised unit's wall. Called by guard.supervised
        after the unit returns (cold or warm, success or failure); a unit
        with no notes (fetch units, un-instrumented callables) records
        nothing."""
        pending = _PENDING.get()
        if not pending:
            return
        entries = list(pending)
        del pending[:]
        n = len(entries)
        run = _RUN.get()
        now = time.time()
        # multi-dispatch units (probe rounds) share one wall; the per-entry
        # share keeps warm baselines comparable across unit groupings
        share = wall_s / n
        for kernel, dims, cold in entries:
            digest = self._digest_for(kernel, dims)
            rec: dict = {
                "kind": "dispatch",
                "t": round(now, 6),
                "kernel": kernel,
                "digest": digest,
                "mesh": str(dims.get("mesh", "")),
                "site": site,
                "pods": int(dims.get("P", pods) or pods),
                "n_in_unit": n,
                "unit_wall_s": round(wall_s, 9),
                "wall_s": round(share, 9),
                "cold": bool(cold),
                "ok": bool(ok),
                "dims": {k: (v if isinstance(v, (int, float, bool, str))
                             else repr(v)) for k, v in sorted(dims.items())},
            }
            if run is not None:
                rec["run"] = run["id"]
            key = (kernel, digest)
            if cold:
                if self.roofline_dispatch and n == 1 and fn is not None:
                    cost = self._harvest_cost(fn)
                    if cost is not None:
                        with self._lock:
                            self._costs[key] = cost
            elif ok:
                self._warm_stats(key, share, rec)
            self._append(rec)

    def _warm_stats(self, key: Tuple[str, str], wall_s: float,
                    rec: dict) -> None:
        """MAD drift check + achieved-roofline fraction for one warm wall.
        The new wall is checked against the PRIOR window, then appended —
        an injected slow dispatch cannot raise its own baseline."""
        kernel, digest = key
        with self._lock:
            win = self._windows.get(key)
            if win is None:
                win = self._windows[key] = deque(maxlen=self.mad_window)
            samples = list(win)
            win.append(wall_s)
            cost = self._costs.get(key)
        if len(samples) >= self.mad_min:
            med = statistics.median(samples)
            mad = statistics.median(abs(x - med) for x in samples)
            thresh = med + self.mad_k * 1.4826 * mad
            # absolute + relative floors: deterministic µs-scale walls have
            # MAD ~ 0, and scheduler jitter alone reaches ~1.5x median
            thresh = max(thresh, med * 1.5, med + 1e-4)
            if wall_s > thresh:
                rec["regression"] = True
                rec["baseline_med_s"] = round(med, 9)
                PULSE_REGRESSIONS.labels(kernel=kernel, bucket=digest).inc()
                with self._lock:
                    self._reg_counts[key] = self._reg_counts.get(key, 0) + 1
        if cost is not None and wall_s > 0.0:
            opt = model_optimal_s(cost)
            if opt > 0.0:
                frac = min(1.0, opt / wall_s)
                rec["achieved_frac"] = round(frac, 6)
                rec["model_optimal_s"] = round(opt, 9)
                PULSE_ACHIEVED.labels(kernel=kernel, bucket=digest).set(
                    round(frac, 6))

    def _harvest_cost(self, fn) -> Optional[Dict[str, float]]:
        """Dispatch-shape cost_analysis harvest, cold dispatches only
        (OPEN_SIMULATOR_PULSE_ROOFLINE=1): when the supervised callable is a
        partial over a lowerable jit (the single-device kernels), lower at
        the REAL arguments and read the compiled cost model. Re-lowering
        roughly doubles the cold dispatch's cost, never the warm path;
        wrapper methods (sharded kernel namespaces) and multi-dispatch units
        are skipped — their static costs come from the audit goldens."""
        if not isinstance(fn, functools.partial):
            return None
        lower = getattr(fn.func, "lower", None)
        if lower is None:
            return None
        try:
            compiled = lower(*fn.args, **fn.keywords).compile()
            return normalize_cost(compiled.cost_analysis())
        # simonlint: ignore[swallowed-exception] -- best-effort cost probe on
        # a DIAGNOSTICS path; any lowering quirk (non-jit callable, abstract
        # mismatch) must never fail the dispatch that already succeeded
        except Exception:
            return None

    # -------------------------------------------------------- run lifecycle --

    def run_begin(self, pods: int, kind: str = "schedule") -> tuple:
        with self._lock:
            self._run_seq += 1
            rid = self._run_seq
        run = {"id": rid, "kind": kind, "pods": int(pods), "phases": {},
               "t0": time.perf_counter()}
        token = _RUN.set(run)
        return token, run

    def run_end(self, token, run: dict) -> None:
        _RUN.reset(token)
        wall = time.perf_counter() - run.pop("t0")
        rec = {
            "kind": "run",
            "t": round(time.time(), 6),
            "run": run["id"],
            "run_kind": run["kind"],
            "pods": run["pods"],
            "wall_s": round(wall, 9),
            "phases": {k: round(v, 9) for k, v in sorted(run["phases"].items())},
        }
        self._append(rec)
        self._emit_scope_counters()

    def phase(self, name: str, seconds: float) -> None:
        PULSE_PHASE_SECONDS.labels(phase=name).inc(seconds)
        with self._lock:
            self._phase_totals[name] = (
                self._phase_totals.get(name, 0.0) + seconds)
        run = _RUN.get()
        if run is not None:
            run["phases"][name] = run["phases"].get(name, 0.0) + seconds

    def _emit_scope_counters(self) -> None:
        """Merge pulse into the scope trace as perfetto counter tracks:
        cumulative per-phase wall + the regression count, sampled once per
        run end (cheap, and exactly when the values move)."""
        from . import scope

        sc = scope.active()
        if sc is None:
            return
        now = time.perf_counter()
        with self._lock:
            phases = dict(self._phase_totals)
            regressions = sum(self._reg_counts.values())
            records = self.n_total
        if phases:
            sc.emit_counter("pulse_phase_seconds", now, phases)
        sc.emit_counter("pulse_ledger", now, {
            "records": records, "regressions": regressions,
        })

    # --------------------------------------------------------------- views ---

    def records(self) -> List[dict]:
        with self._lock:
            return [dict(r) for r in self._ring]

    def summary(self) -> dict:
        """The `simon pulse` / GET /v1/pulse document: ledger totals, one
        row per (kernel, digest) with warm-wall baseline stats, and the run
        phase decomposition."""
        with self._lock:
            recs = [dict(r) for r in self._ring]
            windows = {k: list(v) for k, v in self._windows.items()}
            reg_counts = dict(self._reg_counts)
            costs = {k: dict(v) for k, v in self._costs.items()}
            phase_totals = dict(self._phase_totals)
            n_total, n_dropped = self.n_total, self.n_dropped
        by_key: Dict[Tuple[str, str], dict] = {}
        runs = {"n": 0, "pods": 0}
        for r in recs:
            if r["kind"] == "run":
                runs["n"] += 1
                runs["pods"] += r["pods"]
                continue
            key = (r["kernel"], r["digest"])
            row = by_key.get(key)
            if row is None:
                row = by_key[key] = {
                    "kernel": key[0], "digest": key[1], "mesh": r["mesh"],
                    "n": 0, "cold": 0, "warm": 0, "pods": 0,
                    "wall_s": 0.0, "last_wall_s": 0.0,
                }
            row["n"] += 1
            row["pods"] += r["pods"]
            row["wall_s"] += r["wall_s"]
            row["last_wall_s"] = r["wall_s"]
            row["cold" if r["cold"] else "warm"] += 1
            if "achieved_frac" in r:
                row["achieved_frac"] = r["achieved_frac"]
        for key, row in by_key.items():
            win = windows.get(key) or []
            if win:
                med = statistics.median(win)
                row["warm_med_s"] = round(med, 9)
                row["warm_mad_s"] = round(
                    statistics.median(abs(x - med) for x in win), 9)
            row["regressions"] = reg_counts.get(key, 0)
            cost = costs.get(key)
            if cost is not None:
                row["flops"] = cost["flops"]
                row["bytes_accessed"] = cost["bytes_accessed"]
                row["model_optimal_s"] = round(model_optimal_s(cost), 9)
            row["wall_s"] = round(row["wall_s"], 9)
        pf, pb = peak_rates()
        return {
            "records_total": n_total,
            "records_dropped": n_dropped,
            "ring_len": len(recs),
            "capacity": self.capacity,
            "regressions_total": sum(reg_counts.values()),
            "peaks": {"gflops": pf / 1e9, "gbs": pb / 1e9},
            "phase_seconds": {k: round(v, 9)
                              for k, v in sorted(phase_totals.items())},
            "runs": runs,
            "kernels": [by_key[k] for k in sorted(by_key)],
        }

    def close(self) -> None:
        with self._lock:
            if self._jsonl_f is not None:
                try:
                    self._jsonl_f.close()
                except OSError:
                    pass
                self._jsonl_f = None


# ----------------------------------------------------------- module surface ---

_PULSE: Optional[Pulse] = None


def active() -> Optional[Pulse]:
    """The enabled Pulse, or None. THE zero-cost check: every
    instrumentation site starts here."""
    return _PULSE


def enable(**kw) -> Pulse:
    """Enable simonpulse process-wide (idempotent) and install the
    record_dispatch attribution hook."""
    global _PULSE
    if _PULSE is None:
        _PULSE = Pulse(**kw)
        instruments._DISPATCH_HOOK = note_dispatch
    return _PULSE


def disable() -> None:
    """Disable and tear down (hook removed; spill file closed; ring
    dropped). Any notes still pending in live contexts are discarded — with
    the hook gone they can never be committed."""
    global _PULSE
    p = _PULSE
    _PULSE = None
    instruments._DISPATCH_HOOK = None
    if p is not None:
        p.close()


def env_enabled(default: bool = False) -> bool:
    """The OPEN_SIMULATOR_PULSE switch ('' keeps the caller's default)."""
    raw = os.environ.get("OPEN_SIMULATOR_PULSE", "")
    if raw == "":
        return default
    return raw not in ("0", "false", "no", "off")


def maybe_enable_from_env() -> Optional[Pulse]:
    """Engine/serve bootstrap: enable iff OPEN_SIMULATOR_PULSE says so."""
    if env_enabled(default=False):
        return enable()
    return active()


@contextlib.contextmanager
def run_window(pods: int, kind: str = "schedule"):
    """One scheduling run: dispatch records inside reference the run id;
    the run record carries the phase decomposition. No-op when pulse is
    off (and when it flips mid-run, the begin-time decision wins)."""
    p = _PULSE
    if p is None:
        yield None
        return
    token, run = p.run_begin(pods, kind)
    try:
        yield run
    finally:
        p.run_end(token, run)


def phase(name: str, seconds: float) -> None:
    """Attribute `seconds` of wall to a run phase (module-level convenience;
    no-op when pulse is off)."""
    p = _PULSE
    if p is not None:
        p.phase(name, seconds)


def reset_for_tests() -> None:
    """Tear down pulse AND forget context-local state. Tests only."""
    disable()
    try:
        _PENDING.set(None)
        _RUN.set(None)
    except LookupError:  # pragma: no cover
        pass


# ------------------------------------------------------------- CLI rendering --


def summarize_records(recs: List[dict]) -> dict:
    """Offline aggregation of raw ledger records (a JSONL spill read back,
    or Pulse.records()) into the same document shape summary() produces —
    minus live-only fields (ring capacity, regression counters, harvested
    costs), which only exist on a running Pulse."""
    by_key: Dict[Tuple[str, str], dict] = {}
    runs = {"n": 0, "pods": 0}
    phase_totals: Dict[str, float] = {}
    warm_walls: Dict[Tuple[str, str], List[float]] = {}
    n_reg = 0
    for r in recs:
        if r.get("kind") == "run":
            runs["n"] += 1
            runs["pods"] += r.get("pods", 0)
            for k, v in (r.get("phases") or {}).items():
                phase_totals[k] = phase_totals.get(k, 0.0) + v
            continue
        key = (r.get("kernel", "?"), r.get("digest", "?"))
        row = by_key.get(key)
        if row is None:
            row = by_key[key] = {
                "kernel": key[0], "digest": key[1],
                "mesh": r.get("mesh"), "n": 0, "cold": 0, "warm": 0,
                "pods": 0, "wall_s": 0.0, "regressions": 0,
            }
        row["n"] += 1
        row["pods"] += r.get("pods", 0)
        row["wall_s"] += r.get("wall_s", 0.0)
        row["cold" if r.get("cold") else "warm"] += 1
        if r.get("regression"):
            row["regressions"] += 1
            n_reg += 1
        if "achieved_frac" in r:
            row["achieved_frac"] = r["achieved_frac"]
        if not r.get("cold") and r.get("ok", True):
            warm_walls.setdefault(key, []).append(r.get("wall_s", 0.0))
    for key, row in by_key.items():
        win = warm_walls.get(key) or []
        if win:
            med = statistics.median(win)
            row["warm_med_s"] = round(med, 9)
            row["warm_mad_s"] = round(
                statistics.median(abs(x - med) for x in win), 9)
        row["wall_s"] = round(row["wall_s"], 9)
    pf, pb = peak_rates()
    return {
        "records_total": len(recs),
        "records_dropped": 0,
        "ring_len": len(recs),
        "capacity": 0,
        "regressions_total": n_reg,
        "peaks": {"gflops": pf / 1e9, "gbs": pb / 1e9},
        "phase_seconds": {k: round(v, 9)
                          for k, v in sorted(phase_totals.items())},
        "runs": runs,
        "kernels": [by_key[k] for k in sorted(by_key)],
    }


def format_summary(doc: dict) -> str:
    """Human table for `simon pulse` from a summary() document."""
    out: List[str] = []
    out.append(
        f"pulse ledger: {doc.get('records_total', 0)} records "
        f"({doc.get('ring_len', 0)} in ring / cap {doc.get('capacity', 0)}, "
        f"{doc.get('records_dropped', 0)} evicted), "
        f"{doc.get('regressions_total', 0)} regressions flagged")
    runs = doc.get("runs") or {}
    if runs.get("n"):
        out.append(f"runs: {runs['n']} ({runs['pods']} pods)")
    phases = doc.get("phase_seconds") or {}
    if phases:
        dec = "  ".join(f"{k}={v * 1e3:.1f}ms" for k, v in phases.items())
        out.append(f"phase wall: {dec}")
    rows = doc.get("kernels") or []
    if rows:
        out.append("")
        hdr = (f"{'kernel':<28} {'digest':<16} {'n':>5} {'cold':>4} "
               f"{'warm med':>10} {'mad':>9} {'roofline':>8} {'regr':>4}")
        out.append(hdr)
        out.append("-" * len(hdr))
        for r in rows:
            med = r.get("warm_med_s")
            mad = r.get("warm_mad_s")
            frac = r.get("achieved_frac")
            out.append(
                f"{r['kernel']:<28} {r['digest']:<16} {r['n']:>5} "
                f"{r['cold']:>4} "
                f"{(f'{med * 1e3:.2f}ms' if med is not None else '-'):>10} "
                f"{(f'{mad * 1e6:.0f}us' if mad is not None else '-'):>9} "
                f"{(f'{frac * 100:.1f}%' if frac is not None else '-'):>8} "
                f"{r.get('regressions', 0):>4}")
    return "\n".join(out)


def format_roofline(rows: List[dict]) -> str:
    """Human table for `simon pulse --roofline` from roofline_table()."""
    pf, pb = peak_rates()
    out = [f"roofline @ {pf / 1e9:g} GFLOP/s, {pb / 1e9:g} GB/s "
           f"(OPEN_SIMULATOR_PEAK_GFLOPS / OPEN_SIMULATOR_PEAK_GBS)"]
    hdr = (f"{'kernel':<28} {'bucket':<8} {'mesh':<10} {'GFLOP':>10} "
           f"{'MB':>10} {'optimal':>10} {'bound':>5}")
    out.append(hdr)
    out.append("-" * len(hdr))
    for r in rows:
        opt = r["model_optimal_s"]
        flop_s = r["flops"] / pf
        bound = "flop" if flop_s >= opt - 1e-18 and flop_s > 0 else "mem"
        out.append(
            f"{r['kernel']:<28} {r['bucket']:<8} {r['mesh']:<10} "
            f"{r['flops'] / 1e9:>10.4f} {r['bytes_accessed'] / 1e6:>10.3f} "
            f"{opt * 1e6:>9.1f}us {bound:>5}")
    return "\n".join(out)
