"""Chrome trace-event export for utils/trace spans.

JAX profiling practice exports device timelines as Chrome trace-event JSON
loadable in perfetto / chrome://tracing; this module gives the HOST spans
(utils/trace.Span trees: Simulate → schedule_run → encode/dispatch steps)
the same treatment, so a `--trace-out FILE.json` run drops one file that
perfetto renders as a nested flame chart.

Format: the JSON-object form of the trace-event spec — a `traceEvents`
array of complete ("ph": "X") events with microsecond `ts`/`dur`, plus a
`metadata` object carrying the metrics-registry snapshot (unknown top-level
keys are legal and ignored by viewers).
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Sequence

from ..utils.trace import Span


def _span_events(span: Span, pid: int, out: List[dict]) -> None:
    # annotations (Span.annotate — e.g. simonxray's per-batch decision
    # summary) merge into the event args, so each schedule_run span carries
    # its decision records straight into the perfetto UI
    args = dict(getattr(span, "meta", None) or {})
    if span.failed:
        args["failed"] = True
    out.append({
        "name": span.name,
        "ph": "X",
        "ts": round(span.t0 * 1e6, 3),
        "dur": round(span.total * 1e6, 3),
        "pid": pid,
        "tid": span.tid,
        "cat": "span",
        "args": args,
    })
    # steps are contiguous sub-intervals from the span start (utiltrace
    # semantics: step(i) measures since the previous mark)
    t = span.t0
    for name, dt in span.steps:
        out.append({
            "name": name,
            "ph": "X",
            "ts": round(t * 1e6, 3),
            "dur": round(dt * 1e6, 3),
            "pid": pid,
            "tid": span.tid,
            "cat": "step",
            "args": {},
        })
        t += dt
    for child in span.children:
        _span_events(child, pid, out)


def chrome_trace(spans: Sequence[Span], metrics: Optional[dict] = None) -> dict:
    """Build the trace-event JSON object for a list of root spans."""
    events: List[dict] = []
    pid = os.getpid()
    for sp in spans:
        _span_events(sp, pid, events)
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "metadata": {"tool": "open-simulator-tpu"},
    }
    if metrics is not None:
        doc["metadata"]["metrics"] = metrics
    return doc


def write_chrome_trace(path: str, spans: Sequence[Span],
                       metrics: Optional[dict] = None) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(chrome_trace(spans, metrics), f, indent=1)
        f.write("\n")
