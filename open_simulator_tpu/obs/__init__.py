"""simonmetrics: first-party observability for the TPU scheduling engine.

- `obs.metrics` — process-wide thread-safe registry (Counter / Gauge /
  Histogram with fixed buckets, labels, zero deps), Prometheus-text
  rendering for the server's `GET /metrics`, JSON snapshots for
  `--metrics-out`, bench rows, and `/debug/vars`.
- `obs.instruments` — the metric catalog (scheduler-parity names mapped to
  kube-scheduler's in PARITY.md) plus the compile-cache dispatch tracker
  and the jax.monitoring backend-compile listener.
- `obs.chrome` — Chrome trace-event (perfetto-loadable) export of
  utils/trace.Span trees for `--trace-out FILE.json`, including span
  annotations (Span.annotate) as event args.
- `obs.xray` — simonxray, the opt-in per-pod scheduling flight recorder
  (`--xray` / OPEN_SIMULATOR_XRAY=1): decision records with kube-parity
  explanations, queryable via `simon explain`, `GET /explain/<pod>`, and
  the Chrome trace. Imported lazily by consumers (not re-exported here) so
  the metrics registry stays import-light.
- `obs.scope` — simonscope, serving-grade observability (on by default
  under `simon serve`, off elsewhere): end-to-end request tracing with
  cross-thread flow stitching, the rolling-window SLO engine
  (queue/dispatch/fetch/total decomposition, error-budget burn), and the
  device-runtime telemetry sampler (pool-attributed buffer bytes,
  compile-cache deltas, transfer rate). Surfaced on `simon slo`,
  `simon top`, `GET /v1/serve/stats`, and `GET /v1/serve/trace`. Imported
  lazily by consumers for the same reason as xray.
- `obs.pulse` — simonpulse, the continuous per-dispatch performance ledger
  (OPEN_SIMULATOR_PULSE=1): every guard.supervised kernel dispatch lands
  one bounded-ring record (kernel, shape-bucket digest, mesh, pods,
  cold/warm, wall) with optional JSONL spill; scheduling-run records carry
  the encode/table_build/to_device/dispatch/fetch/commit wall
  decomposition; warm walls are checked against rolling per-(kernel,
  digest) MAD baselines (`simon_pulse_regressions_total`); and a roofline
  cost model built from `compiled.cost_analysis()` (harvested into every
  audit certificate's `cost` field) turns warm walls into achieved-of-
  optimal fractions. Surfaced on `simon pulse`, `GET /v1/pulse`, and as
  perfetto counter tracks in the scope trace. Imported lazily by
  consumers for the same reason as xray.

Instrumentation lives on the HOST side of the device boundary by contract:
the `metric-in-jit` simonlint rule rejects registry mutations or wall-clock
reads inside jit/scan bodies.
"""

from .metrics import (  # noqa: F401
    REGISTRY,
    Registry,
    counter,
    gauge,
    histogram,
    render_text_from_snapshot,
    values_from_snapshot,
)
