"""simonscope: serving-grade request tracing, SLO engine, and device telemetry.

PRs 10-11 turned the simulator into a serving system; this module gives that
system the three observability layers a serving stack needs (Clipper's
queue/batch/execute latency decomposition, Orca's resident-state footprint
tracking — PAPERS.md):

- **End-to-end request tracing.** A trace ID is minted at the edge (HTTP
  handler, gRPC bridge, CLI) and carried by contextvar through the
  micro-batch dispatcher's worker threads into kernel dispatch, fetch, and
  reply. Spans record into a bounded in-memory buffer in Chrome trace-event
  form; cross-thread hops (request -> coalesced micro-batch) are stitched
  with flow events, so one perfetto-loadable trace shows request ->
  queue-wait -> micro-batch -> serve_wave_fanout dispatch -> fetch -> demux
  -> reply, including failover replays and fresh-path detours under the SAME
  trace ID as the batched attempt they replaced.
- **Rolling-window SLO engine.** Sliding-window latency histograms per
  endpoint with the queue/dispatch/fetch/total phase decomposition,
  p50/p95/p99 gauges, configurable SLO targets, and error-budget burn
  tracking — surfaced on GET /v1/serve/stats, /metrics, `simon slo`, and
  `simon top`.
- **Device-runtime telemetry sampler.** A low-overhead background thread
  sampling live device-buffer bytes attributed to pools (image tables /
  carry cache / scratch), compile-cache hit/miss deltas, and host->device
  transfer bytes/s — emitted as gauges and as trace counter tracks, so a
  resident-image footprint leak under churn is a visible ramp instead of a
  latent OOM.

Zero-cost contract (the same one simonxray proved): recording is OPT-IN
(`simon serve` on by default, `--no-scope` / OPEN_SIMULATOR_SCOPE=0 off;
everything else off by default) and every instrumentation site is one
`scope.active()` None-check (or one contextvar read) when off. All scope
metric families are LABELED, so an untouched family renders no samples and
scope-off /metrics output stays byte-identical to pre-scope builds;
placements are untouched either way — tracing is passive.

Everything here is host-side and jax-free at import; the single JAX
touchpoint (live-buffer accounting in the sampler) only runs when jax is
ALREADY imported by the engine.
"""

from __future__ import annotations

import bisect
import contextlib
import contextvars
import itertools
import json
import math
import os
import sys
import threading
import time
import weakref
from typing import Dict, List, Optional, Tuple

from . import instruments as obs

# Phase names of the request decomposition, in pipeline order. `total` is
# always recorded; the serve path adds the queue/dispatch/fetch breakdown.
PHASES = ("queue", "dispatch", "fetch", "total")

# Rolling-window histogram bucket bounds in SECONDS: geometric 0.25ms..16s,
# fine enough for p99 interpolation at serving latencies (tens of ms).
_WINDOW_BOUNDS = tuple(0.00025 * (2.0 ** i) for i in range(17))

DEFAULT_WINDOW_S = 60.0
DEFAULT_SLICES = 12
DEFAULT_TRACE_CAP = 200_000

# Default SLO targets per endpoint (ROADMAP item 3: p99 < 50ms at >= 1k
# req/s; availability leaves a 0.1% error budget). Override per process via
# OPEN_SIMULATOR_SLO_JSON='{"whatif": {"p99_ms": 25, "availability": 0.99}}'
# or programmatically through enable(slo_targets=...).
DEFAULT_SLO_TARGETS: Dict[str, Dict[str, float]] = {
    "whatif": {"p99_ms": 50.0, "availability": 0.999},
}


# ------------------------------------------------------------ trace context ---

class TraceCtx:
    """One request's identity as it crosses threads: the trace id plus the
    endpoint the edge minted it for. Immutable — hand the object itself to
    another thread (the dispatcher does) and bind it there with use_ctx."""

    __slots__ = ("trace_id", "endpoint")

    def __init__(self, trace_id: int, endpoint: str) -> None:
        self.trace_id = trace_id
        self.endpoint = endpoint


_CTX: contextvars.ContextVar[Optional[TraceCtx]] = contextvars.ContextVar(
    "simon_scope_ctx", default=None)

# Phase-mark sink: a plain dict shared with whatever worker thread the guard
# watchdog runs the dispatch on (contextvars.copy_context() carries the
# REFERENCE, so marks made in the worker land in the caller's dict).
_PHASES_SINK: contextvars.ContextVar[Optional[dict]] = contextvars.ContextVar(
    "simon_scope_phases", default=None)


def mark(name: str) -> None:
    """Record one phase boundary (perf_counter seconds) into the collecting
    caller's sink, if any. One contextvar read when no collection is active —
    cheap enough for kernel dispatch sites. `*_begin` marks keep their FIRST
    value, everything else its last: a micro-batch that dispatches both a
    wave lane and a serial lane spans from the first kernel_begin to the
    last fetch_end."""
    sink = _PHASES_SINK.get()
    if sink is not None:
        if name.endswith("_begin"):
            sink.setdefault(name, time.perf_counter())
        else:
            sink[name] = time.perf_counter()


@contextlib.contextmanager
def collect_phases(sink: dict):
    """Collect mark() calls from this context (and any guard.supervised
    worker it spawns) into `sink`."""
    token = _PHASES_SINK.set(sink)
    try:
        yield sink
    finally:
        _PHASES_SINK.reset(token)


def current_ctx() -> Optional[TraceCtx]:
    return _CTX.get()


# --------------------------------------------------------------- SLO engine ---

class _WindowHist:
    """One (endpoint, phase) sliding-window histogram: a ring of time slices,
    each a fixed-bound bucket-count array + sum + count. Old slices expire as
    the window slides; quantiles interpolate over the merged live slices."""

    __slots__ = ("slices", "slice_s", "n_slices")

    def __init__(self, window_s: float, n_slices: int) -> None:
        self.n_slices = max(2, int(n_slices))
        self.slice_s = float(window_s) / self.n_slices
        # [(slice_index, counts, sum, count)]
        self.slices: List[list] = []

    def _slice_for(self, now: float) -> list:
        si = int(now / self.slice_s)
        if self.slices and self.slices[-1][0] == si:
            return self.slices[-1]
        sl = [si, [0] * (len(_WINDOW_BOUNDS) + 1), 0.0, 0]
        self.slices.append(sl)
        live = si - self.n_slices
        while self.slices and self.slices[0][0] <= live:
            self.slices.pop(0)
        return sl

    def record(self, v_s: float, now: float) -> None:
        sl = self._slice_for(now)
        sl[1][bisect.bisect_left(_WINDOW_BOUNDS, v_s)] += 1
        sl[2] += v_s
        sl[3] += 1

    def merged(self, now: float) -> Tuple[List[int], float, int]:
        live = int(now / self.slice_s) - self.n_slices
        counts = [0] * (len(_WINDOW_BOUNDS) + 1)
        total = 0.0
        n = 0
        for si, c, s, k in self.slices:
            if si <= live:
                continue
            for i, v in enumerate(c):
                counts[i] += v
            total += s
            n += k
        return counts, total, n

    @staticmethod
    def quantile(counts: List[int], n: int, q: float) -> float:
        """Seconds at quantile q, linearly interpolated within the bucket
        (kube-scheduler histogram_quantile practice)."""
        if n <= 0:
            return 0.0
        rank = q * n
        cum = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if cum + c >= rank:
                lo = _WINDOW_BOUNDS[i - 1] if i > 0 else 0.0
                hi = (_WINDOW_BOUNDS[i] if i < len(_WINDOW_BOUNDS)
                      else _WINDOW_BOUNDS[-1] * 2)
                frac = (rank - cum) / c
                return lo + (hi - lo) * frac
            cum += c
        return _WINDOW_BOUNDS[-1] * 2


class SLOEngine:
    """Rolling-window per-endpoint latency/SLO accounting.

    record() is the single write point: it feeds (a) the sliding-window
    histograms behind the p50/p95/p99 snapshot, (b) the CUMULATIVE labeled
    Prometheus families (simon_scope_requests_total / _request_phase_seconds
    / _slo_violations_total), and (c) the error-budget ledger. snapshot()
    (and refresh_gauges(), which mirrors it into gauges for /metrics) is the
    single read point."""

    def __init__(self, targets: Optional[Dict[str, Dict[str, float]]] = None,
                 window_s: float = DEFAULT_WINDOW_S,
                 n_slices: int = DEFAULT_SLICES) -> None:
        self.window_s = float(window_s)
        self.n_slices = int(n_slices)
        self.targets = dict(DEFAULT_SLO_TARGETS)
        env = os.environ.get("OPEN_SIMULATOR_SLO_JSON", "")
        if env:
            try:
                for ep, t in (json.loads(env) or {}).items():
                    self.targets[str(ep)] = {k: float(v) for k, v in t.items()}
            except (ValueError, TypeError, AttributeError):
                import logging

                logging.getLogger("open_simulator_tpu").warning(
                    "OPEN_SIMULATOR_SLO_JSON is not a {endpoint: {p99_ms, "
                    "availability}} object; using defaults")
        if targets:
            self.targets.update(targets)
        self._lock = threading.Lock()
        self._hists: Dict[Tuple[str, str], _WindowHist] = {}
        self._routes: Dict[Tuple[str, str], int] = {}
        # cumulative error-budget ledger per endpoint: [requests, bad]
        self._budget: Dict[str, List[int]] = {}
        # pre-resolved metric children (the instruments contract: resolve
        # labels once, hold the child — record() sits on the per-request
        # hot path and a .labels() call re-validates the label set)
        self._req_children: Dict[Tuple[str, str], object] = {}
        self._phase_children: Dict[Tuple[str, str], object] = {}
        self._viol_children: Dict[str, object] = {}

    def record(self, endpoint: str, route: str, phases: Dict[str, float],
               error: bool = False) -> None:
        """One finished request: `phases` maps phase name -> seconds and must
        include 'total'. The exact float recorded here is the one the span
        exporter carries, so trace and histogram sums reconcile."""
        now = time.monotonic()
        total = float(phases.get("total", 0.0))
        target = self.targets.get(endpoint)
        bad = bool(error) or (
            target is not None and total * 1000.0 > target.get(
                "p99_ms", math.inf))
        with self._lock:
            for phase, v in phases.items():
                key = (endpoint, phase)
                h = self._hists.get(key)
                if h is None:
                    h = self._hists[key] = _WindowHist(
                        self.window_s, self.n_slices)
                h.record(float(v), now)
            rkey = (endpoint, route)
            self._routes[rkey] = self._routes.get(rkey, 0) + 1
            ledger = self._budget.setdefault(endpoint, [0, 0])
            ledger[0] += 1
            ledger[1] += 1 if bad else 0
        child = self._req_children.get((endpoint, route))
        if child is None:
            child = self._req_children[(endpoint, route)] = (
                obs.SCOPE_REQUESTS.labels(endpoint=endpoint, route=route))
        child.inc()
        for phase, v in phases.items():
            h = self._phase_children.get((endpoint, phase))
            if h is None:
                h = self._phase_children[(endpoint, phase)] = (
                    obs.SCOPE_PHASE_SECONDS.labels(
                        endpoint=endpoint, phase=phase))
            h.observe(float(v))
        if bad:
            vc = self._viol_children.get(endpoint)
            if vc is None:
                vc = self._viol_children[endpoint] = (
                    obs.SCOPE_SLO_VIOLATIONS.labels(endpoint=endpoint))
            vc.inc()

    def snapshot(self) -> dict:
        """The /v1/serve/stats "slo" section: per endpoint, windowed rps +
        per-phase quantiles + route mix + SLO target/burn accounting.
        Window merges run UNDER the engine lock — record() mutates the
        slice lists in place, and a merge racing it would be exactly the
        torn-scrape class metrics.py's samples() fix removes."""
        now = time.monotonic()
        with self._lock:
            merged = {key: h.merged(now)
                      for key, h in sorted(self._hists.items())}
            routes = dict(self._routes)
            budget = {k: list(v) for k, v in self._budget.items()}
        endpoints: Dict[str, dict] = {}
        for (ep, phase), (counts, total, n) in merged.items():
            q = _WindowHist.quantile
            d = endpoints.setdefault(ep, {"phases": {}, "routes": {}})
            d["phases"][phase] = {
                "count": n,
                "sum_s": total,
                "mean_ms": round(total / n * 1000.0, 3) if n else 0.0,
                "p50_ms": round(q(counts, n, 0.50) * 1000.0, 3),
                "p95_ms": round(q(counts, n, 0.95) * 1000.0, 3),
                "p99_ms": round(q(counts, n, 0.99) * 1000.0, 3),
            }
        for (ep, route), n in sorted(routes.items()):
            endpoints.setdefault(ep, {"phases": {}, "routes": {}})[
                "routes"][route] = n
        for ep, d in endpoints.items():
            tot = d["phases"].get("total", {})
            d["window_s"] = self.window_s
            d["rps"] = round(tot.get("count", 0) / self.window_s, 2)
            target = self.targets.get(ep)
            ledger = budget.get(ep, [0, 0])
            if target is not None:
                allowed = max(1e-9, 1.0 - target.get("availability", 0.999))
                served, bad = ledger
                d["slo"] = {
                    "target_p99_ms": target.get("p99_ms"),
                    "availability_target": target.get("availability", 0.999),
                    "requests": served,
                    "violations": bad,
                    # >1.0 = burning budget faster than the target allows
                    "budget_burn": round((bad / served) / allowed, 4)
                    if served else 0.0,
                    "budget_remaining_frac": round(
                        1.0 - (bad / (served * allowed)) if served else 1.0, 4),
                }
        return {"window_s": self.window_s, "endpoints": endpoints}

    def refresh_gauges(self) -> None:
        """Mirror the windowed quantiles/burn into labeled gauges so a
        /metrics scrape carries them (called from the scrape handler when
        scope is active — scope-off scrapes never touch these families)."""
        snap = self.snapshot()
        for ep, d in snap["endpoints"].items():
            for phase, q in d["phases"].items():
                for quant in ("p50", "p95", "p99"):
                    obs.SCOPE_QUANTILE_MS.labels(
                        endpoint=ep, phase=phase,
                        quantile=quant).set(q[f"{quant}_ms"])
            if "slo" in d:
                obs.SCOPE_BUDGET_BURN.labels(endpoint=ep).set(
                    d["slo"]["budget_burn"])


# ------------------------------------------------------------ pool registry ---

# Device-buffer pool providers (objects exposing device_pool_bytes() ->
# {pool: bytes}), registered unconditionally (WeakSet: registration is cheap
# and leak-free whether or not a scope/sampler ever starts).
_POOL_PROVIDERS: "weakref.WeakSet" = weakref.WeakSet()


def register_pools(provider) -> None:
    """Register a device-buffer owner (e.g. serve.ResidentImage) for the
    runtime sampler's pool attribution. `provider.device_pool_bytes()` must
    return {pool_name: bytes} without blocking on device work."""
    _POOL_PROVIDERS.add(provider)


class RuntimeSampler:
    """The device-runtime telemetry thread: every `interval_s`, sample pool
    bytes, compile-cache deltas, and transfer rate; emit gauges + trace
    counter tracks. stop() joins the thread — shutdown leaves no thread
    behind (tools/scope_smoke.py asserts it)."""

    def __init__(self, scope: "Scope", interval_s: float = 1.0) -> None:
        self.scope = scope
        self.interval_s = max(0.05, float(interval_s))
        self._stop = threading.Event()
        self._last: Dict[str, float] = {}
        self._last_t = 0.0
        self._thread = threading.Thread(
            target=self._loop, name="simon-scope-sampler", daemon=True)

    def start(self) -> None:
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout)

    @property
    def alive(self) -> bool:
        return self._thread.is_alive()

    def _loop(self) -> None:
        # one immediate sample (tests and short smokes need >=1 tick), then
        # the steady interval
        while True:
            try:
                self.sample_once()
            except Exception:
                obs.SCOPE_SAMPLER_ERRORS.labels(kind="tick").inc()
            if self._stop.wait(self.interval_s):
                return

    def _counter_total(self, family) -> float:
        return sum(s.get("value", 0.0) for s in family.samples())

    def sample_once(self) -> None:
        """One telemetry tick (public: tests and the smoke drive it
        synchronously)."""
        now = time.perf_counter()
        pools: Dict[str, int] = {}
        for provider in list(_POOL_PROVIDERS):
            try:
                for pool, nbytes in provider.device_pool_bytes().items():
                    pools[pool] = pools.get(pool, 0) + int(nbytes)
            except Exception:
                obs.SCOPE_SAMPLER_ERRORS.labels(kind="tick").inc()
        # scratch: live device bytes not attributed to a named pool. Only
        # when the engine already imported jax — the sampler must never be
        # the thing that initializes a backend.
        jax = sys.modules.get("jax")
        if jax is not None and hasattr(jax, "live_arrays"):
            try:
                total = sum(int(getattr(a, "nbytes", 0) or 0)
                            for a in jax.live_arrays())
                pools["scratch"] = max(0, total - sum(pools.values()))
            except Exception:
                obs.SCOPE_SAMPLER_ERRORS.labels(kind="tick").inc()
        for pool, nbytes in pools.items():
            obs.SCOPE_POOL_BYTES.labels(pool=pool).set(nbytes)

        hits = self._counter_total(obs.COMPILE_HITS)
        misses = self._counter_total(obs.COMPILE_MISSES)
        xfer = obs.TRANSFER_BYTES.samples()
        xfer_total = xfer[0]["value"] if xfer else 0.0
        dt = now - self._last_t if self._last_t else 0.0
        d_hits = hits - self._last.get("hits", hits)
        d_misses = misses - self._last.get("misses", misses)
        d_xfer = xfer_total - self._last.get("xfer", xfer_total)
        rate = d_xfer / dt if dt > 0 else 0.0
        self._last = {"hits": hits, "misses": misses, "xfer": xfer_total}
        self._last_t = now
        obs.SCOPE_COMPILE_DELTA.labels(kind="hits").set(d_hits)
        obs.SCOPE_COMPILE_DELTA.labels(kind="misses").set(d_misses)
        obs.SCOPE_TRANSFER_RATE.labels(direction="h2d").set(rate)
        obs.SCOPE_SAMPLES.labels(kind="tick").inc()
        sc = self.scope
        sc.emit_counter("device_pool_bytes", now, pools or {"scratch": 0})
        sc.emit_counter("compile_cache_delta", now,
                        {"hits": d_hits, "misses": d_misses})
        sc.emit_counter("transfer_bytes_per_s", now, {"h2d": round(rate, 1)})


# -------------------------------------------------------------------- scope ---

class Scope:
    """The enabled simonscope instance: trace buffer + SLO engine + optional
    runtime sampler. One per process (module global, like the xray
    recorder); hot paths reach it through active()."""

    def __init__(self, slo_targets: Optional[Dict[str, Dict[str, float]]] = None,
                 trace_cap: int = DEFAULT_TRACE_CAP,
                 sampler: bool = False,
                 sampler_interval_s: float = 1.0) -> None:
        self.slo = SLOEngine(slo_targets)
        self.trace_cap = int(trace_cap)
        self._events: List[dict] = []
        # raw per-request records (endpoint, tm, t_end, total, route):
        # the request hot path appends ONE tuple; the span tree + flow
        # events expand lazily in events() — render cost moves off the
        # serving path (the <=10% overhead gate is won here)
        self._requests: List[tuple] = []
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        # pre-resolved trace-event counter children (hot path)
        self._ev_children = {
            kind: obs.SCOPE_TRACE_EVENTS.labels(kind=kind)
            for kind in ("span", "flow", "counter", "request")}
        self.pid = os.getpid()
        self.t_enabled = time.perf_counter()
        self.sampler: Optional[RuntimeSampler] = None
        if sampler:
            self.sampler = RuntimeSampler(self, sampler_interval_s)
            self.sampler.start()

    # ------------------------------------------------------------- identity --

    def mint_trace(self, endpoint: str) -> TraceCtx:
        return TraceCtx(next(self._ids), endpoint)

    def mint_flow(self) -> int:
        return next(self._ids)

    @contextlib.contextmanager
    def use_ctx(self, ctx: Optional[TraceCtx]):
        """Bind a TraceCtx in this thread (the dispatcher replaying a
        request's failover under the request's own trace id)."""
        token = _CTX.set(ctx)
        try:
            yield ctx
        finally:
            _CTX.reset(token)

    # ------------------------------------------------------------- emission --

    def _push(self, ev: dict, kind: str) -> None:
        with self._lock:
            if len(self._events) >= self.trace_cap:
                obs.SCOPE_TRACE_DROPPED.labels(kind=kind).inc()
                return
            self._events.append(ev)
        self._ev_children[kind].inc()

    def emit_span(self, name: str, t0_s: float, dur_s: float,
                  tid: Optional[int] = None,
                  ctx: Optional[TraceCtx] = None, cat: str = "scope",
                  **args) -> None:
        """One complete ('X') event with explicit timing — the exporter for
        post-hoc per-request span trees assembled from recorded phase
        timestamps."""
        ctx = ctx if ctx is not None else _CTX.get()
        if ctx is not None:
            args.setdefault("trace_id", ctx.trace_id)
        self._push({
            "name": name, "ph": "X", "cat": cat,
            "ts": round(t0_s * 1e6, 3), "dur": round(dur_s * 1e6, 3),
            "pid": self.pid,
            "tid": tid if tid is not None else threading.get_ident(),
            "args": args,
        }, "span")

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "scope", **args):
        """Live span around a code block on the current thread; inherits the
        active trace ctx (which guard.supervised's copied contextvars carry
        into its worker thread)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.emit_span(name, t0, time.perf_counter() - t0,
                           cat=cat, **args)

    @contextlib.contextmanager
    def request_span(self, endpoint: str, **args):
        """Edge span: mint a trace id (unless one is already bound — a CLI
        harness may pre-bind) and record the root request span."""
        ctx = _CTX.get()
        token = None
        if ctx is None or ctx.endpoint != endpoint:
            ctx = self.mint_trace(endpoint)
            token = _CTX.set(ctx)
        t0 = time.perf_counter()
        try:
            yield ctx
        finally:
            self.emit_span(f"request:{endpoint}", t0,
                           time.perf_counter() - t0, ctx=ctx,
                           cat="request", **args)
            if token is not None:
                _CTX.reset(token)

    def emit_flow(self, flow_id: int, phase: str, t_s: float,
                  tid: Optional[int] = None) -> None:
        """Flow event ('s' start on the request thread, 'f' finish on the
        dispatcher) binding a request span to the micro-batch that served
        it. Perfetto draws the arrow."""
        ev = {
            "name": "req-flow", "ph": phase, "cat": "flow",
            "id": flow_id, "ts": round(t_s * 1e6, 3), "pid": self.pid,
            "tid": tid if tid is not None else threading.get_ident(),
        }
        if phase == "f":
            ev["bp"] = "e"  # bind to the enclosing slice
        self._push(ev, "flow")

    def emit_counter(self, name: str, t_s: float,
                     values: Dict[str, float]) -> None:
        """Counter-track sample ('C'): the sampler's pool-bytes /
        compile-delta / transfer tracks."""
        self._push({
            "name": name, "ph": "C", "cat": "telemetry",
            "ts": round(t_s * 1e6, 3), "pid": self.pid, "tid": 0,
            "args": dict(values),
        }, "counter")

    def record_request(self, endpoint: str, tm: dict, t_end: float,
                       total: float, route: str) -> None:
        """One finished request's raw trace record (hot path: one lock, one
        append). The per-request span tree — root, queue_wait,
        batched_dispatch, fetch, reply, and the flow stitch — expands from
        `tm` lazily when the trace is read."""
        with self._lock:
            if len(self._requests) + len(self._events) >= self.trace_cap:
                obs.SCOPE_TRACE_DROPPED.labels(kind="request").inc()
                return
            self._requests.append((endpoint, tm, t_end, total, route))
        self._ev_children["request"].inc()

    def _expand_request(self, endpoint: str, tm: dict, t_end: float,
                        total: float, route: str, out: List[dict]) -> None:
        ctx: TraceCtx = tm["ctx"]
        tid = tm.get("tid", 0)
        btid = tm.get("batch_tid", tid)

        def span(name, t0, dur, stid, cat="serve", **args):
            args["trace_id"] = ctx.trace_id
            out.append({"name": name, "ph": "X", "cat": cat,
                        "ts": round(t0 * 1e6, 3),
                        "dur": round(dur * 1e6, 3),
                        "pid": self.pid, "tid": stid, "args": args})

        t_enq, t_batch = tm.get("t_enq"), tm.get("t_batch")
        ke, fe = tm.get("kernel_end"), tm.get("fetch_end")
        if t_enq is not None and t_batch is not None:
            span("queue_wait", t_enq, t_batch - t_enq, tid)
            fid = tm.get("flow")
            if fid is not None:
                out.append({"name": "req-flow", "ph": "s", "cat": "flow",
                            "id": fid, "ts": round(t_enq * 1e6, 3),
                            "pid": self.pid, "tid": tid})
                out.append({"name": "req-flow", "ph": "f", "bp": "e",
                            "cat": "flow", "id": fid,
                            "ts": round(t_batch * 1e6, 3),
                            "pid": self.pid, "tid": btid})
        if t_batch is not None and ke is not None:
            span("batched_dispatch", t_batch, ke - t_batch, btid,
                 lanes=tm.get("lanes"))
        if ke is not None and fe is not None:
            span("fetch", ke, fe - ke, btid)
        if tm.get("t_fresh0") is not None and tm.get("t_fresh1") is not None:
            span("fresh_detour", tm["t_fresh0"],
                 tm["t_fresh1"] - tm["t_fresh0"], tid,
                 gate=tm.get("gate", ""))
        last = fe if fe is not None else tm.get("t_fresh1", tm["t_sub"])
        span("reply", last, t_end - last, tid)
        span(f"request:{endpoint}", tm["t_sub"], total, tid, cat="request",
             route=route, total_s=total, lanes=tm.get("lanes", 1),
             attempts=list(tm["attempts"]))

    # -------------------------------------------------------------- exports --

    def events(self) -> List[dict]:
        """The full trace-event list: live-emitted events plus the lazily
        expanded per-request span trees."""
        with self._lock:
            evs = list(self._events)
            reqs = list(self._requests)
        for rec in reqs:
            self._expand_request(*rec, out=evs)
        return evs

    def chrome_trace(self, metrics: Optional[dict] = None) -> dict:
        doc = {
            "traceEvents": self.events(),
            "displayTimeUnit": "ms",
            "metadata": {"tool": "open-simulator-tpu/simonscope",
                         "slo": self.slo.snapshot()},
        }
        if metrics is not None:
            doc["metadata"]["metrics"] = metrics
        return doc

    def write_trace(self, path: str, metrics: Optional[dict] = None) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.chrome_trace(metrics), f, indent=1)
            f.write("\n")

    def stats(self) -> dict:
        with self._lock:
            n = len(self._events)
            r = len(self._requests)
        return {
            "trace_events": n,
            "trace_requests": r,
            "trace_cap": self.trace_cap,
            "sampler": bool(self.sampler and self.sampler.alive),
            "uptime_s": round(time.perf_counter() - self.t_enabled, 3),
        }

    def close(self) -> None:
        if self.sampler is not None:
            self.sampler.stop()
            self.sampler = None


_SCOPE: Optional[Scope] = None


def active() -> Optional[Scope]:
    """The enabled Scope, or None. THE zero-cost check: every
    instrumentation site starts here."""
    return _SCOPE


def enable(slo_targets: Optional[Dict[str, Dict[str, float]]] = None,
           sampler: bool = False, sampler_interval_s: float = 1.0,
           trace_cap: int = DEFAULT_TRACE_CAP) -> Scope:
    """Enable simonscope process-wide (idempotent: an existing scope is
    returned untouched so a server restartless re-enable cannot orphan a
    sampler thread)."""
    global _SCOPE
    if _SCOPE is None:
        _SCOPE = Scope(slo_targets=slo_targets, sampler=sampler,
                       sampler_interval_s=sampler_interval_s,
                       trace_cap=trace_cap)
    return _SCOPE


def disable() -> None:
    """Disable and tear down (sampler joined; trace buffer dropped)."""
    global _SCOPE
    sc = _SCOPE
    _SCOPE = None
    if sc is not None:
        sc.close()


def env_enabled(default: bool = False) -> bool:
    """The OPEN_SIMULATOR_SCOPE switch ('' keeps the caller's default)."""
    raw = os.environ.get("OPEN_SIMULATOR_SCOPE", "")
    if raw == "":
        return default
    return raw not in ("0", "false", "no", "off")


@contextlib.contextmanager
def cli_edge(name: str, **args):
    """The ONE CLI edge (cmd_apply, cmd_sweep, future commands): env-gated
    enable (OPEN_SIMULATOR_SCOPE=1), one request span covering the whole
    command so engine/probe/sweep spans share its trace id, and — FAILED
    runs included, since a failed run's partial trace is exactly the
    evidence it leaves behind — an OPEN_SIMULATOR_SCOPE_OUT trace dump on
    exit. Yields the Scope, or None when scope is off."""
    if not env_enabled(default=False):
        yield None
        return
    sc = enable()
    try:
        with sc.request_span(name, **args):
            yield sc
    finally:
        out = os.environ.get("OPEN_SIMULATOR_SCOPE_OUT", "")
        if out:
            sc.write_trace(out)
            print(f"scope trace -> {out}", file=sys.stderr)
