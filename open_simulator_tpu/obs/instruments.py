"""The metric catalog: every counter/gauge/histogram the engine family emits.

One module owns the names so the README catalog, the PARITY.md mapping to
kube-scheduler's metrics, and the call sites cannot drift apart. Everything
here is host-side and jax-free at import; the one JAX touchpoint
(`install_jax_monitoring`) is called lazily from Simulator.__init__, after
the engine has already decided to import jax.

kube-scheduler parity (PARITY.md "Metrics parity" for the full table):
`simon_scheduling_attempts_total{result}` ↔ `schedule_attempts_total`,
`simon_e2e_scheduling_duration_seconds` ↔ `e2e_scheduling_duration_seconds`,
`simon_filter_rejections_total{reason}` ↔ the per-extension-point failure
accounting behind `PodUnschedulable` events; the compile-cache / transfer /
segment metrics are XLA-native with no k8s analog.
"""

from __future__ import annotations

import threading
from typing import Dict, Set, Tuple

from .metrics import PODS_BUCKETS, SECONDS_BUCKETS, counter, gauge, histogram

# ------------------------------------------------------------------ engine ----

SCHED_ATTEMPTS = counter(
    "simon_scheduling_attempts_total",
    "Pod scheduling attempts by outcome (kube-scheduler "
    "schedule_attempts_total). bound = pre-bound direct commit; homeless = "
    "bound to an unknown node (dropped from reports, reference parity).",
    ("result",))  # scheduled | unschedulable | bound | homeless
E2E_SECONDS = histogram(
    "simon_e2e_scheduling_duration_seconds",
    "Wall seconds per schedule_pods call, end to end "
    "(kube-scheduler e2e_scheduling_duration_seconds).",
    buckets=SECONDS_BUCKETS)
ENCODE_SECONDS = histogram(
    "simon_encode_seconds",
    "Host-side batch encode time (pods -> device tables) per scheduling run.",
    buckets=SECONDS_BUCKETS)
HOST_COMMIT_SECONDS = histogram(
    "simon_host_commit_seconds",
    "Host-side commit time per scheduling run: placements applied to the "
    "placed census / per-node registry / pod state after the device fetch "
    "(the encode/commit/device decomposition of "
    "simon_e2e_scheduling_duration_seconds — ROADMAP item 2's 60%-of-wall "
    "slice, now measured on every run).",
    buckets=SECONDS_BUCKETS)
ENCODE_BYTES = counter(
    "simon_encode_bytes_total",
    "Host bytes of encoded batch tables + carry seeds produced per "
    "scheduling/probe run (batch_tables_nbytes at encode time; the "
    "device-transfer counter tracks the same bytes at staging).")
STREAM_CHUNKS = counter(
    "simon_stream_chunks_total",
    "Scheduling runs dispatched as streaming chunks "
    "(OPEN_SIMULATOR_STREAM_PODS): host encode of chunk k+1 overlaps the "
    "device dispatch of chunk k.")
BATCH_PODS = histogram(
    "simon_batch_pods",
    "Pods per contiguous unbound scheduling run handed to the device.",
    buckets=PODS_BUCKETS)
SEGMENTS = counter(
    "simon_segments_total",
    "Device dispatch segments by kind (wave / affinity / spread / serial).",
    ("kind",))
SEGMENT_PODS = counter(
    "simon_segment_pods_total",
    "Pods carried by device dispatch segments, by segment kind.",
    ("kind",))
SEGMENT_WALL = counter(
    "simon_segment_wall_seconds_total",
    "Blocking wall seconds per dispatch segment kind. Only collected when "
    "OPEN_SIMULATOR_SEGMENT_TIMING=1 (the engine then blocks on each "
    "segment's result, defeating async dispatch — bench attribution runs "
    "only; see bench.py's hard-predicate segment breakdown).",
    ("kind",))
TRANSFER_BYTES = counter(
    "simon_device_transfer_bytes_total",
    "Host->device bytes staged for scheduling/probe table uploads.")
RESHARD_BYTES = counter(
    "simon_reshard_bytes_total",
    "Bytes of carry state whose post-dispatch sharding layout diverged from "
    "the declared carry shardings — what a chained dispatch would have to "
    "move across ICI to reconcile. The sharded executables pin out_shardings "
    "to in_shardings, so this stays 0; nonzero means a mesh dispatch path "
    "dropped its explicit shardings (parallel/mesh.py carry_reshard_bytes).")
COMMITS = counter(
    "simon_commits_total",
    "Pods committed onto nodes (placements materialized on cluster state). "
    "Monotonic reconciliation: commits - simon_commit_rollbacks_total - "
    "simon_preemption_victims_total = placements currently live.")
COMMIT_ROLLBACKS = counter(
    "simon_commit_rollbacks_total",
    "Commits undone by preemption rewinds (the replay then re-commits and "
    "re-counts them; see simon_commits_total for the reconciliation).")
FILTER_REJECTIONS = counter(
    "simon_filter_rejections_total",
    "Per-node filter-stage rejections behind failed pods, keyed by the "
    "FitError reason label (_reasons_from_stages) — the per-extension-point "
    "failure accounting of kube-scheduler's framework metrics.",
    ("reason",))

# compile-cache accounting: a dispatch whose static shape signature was seen
# before in this process hits the jit cache; a fresh signature compiles (or
# loads the persistent XLA cache). Ground truth backend compiles come from
# install_jax_monitoring below.
COMPILE_HITS = counter(
    "simon_compile_cache_hits_total",
    "Kernel dispatches whose static shape bucket was already compiled.",
    ("kernel",))
COMPILE_MISSES = counter(
    "simon_compile_cache_misses_total",
    "Kernel dispatches that triggered a fresh compile, with the shape "
    "bucket that triggered it.",
    ("kernel", "shape"))
XLA_COMPILES = counter(
    "simon_xla_backend_compiles_total",
    "XLA backend compiles observed via jax.monitoring (all programs).")
XLA_COMPILE_SECONDS = counter(
    "simon_xla_backend_compile_seconds_total",
    "Total XLA backend compile wall seconds (jax.monitoring).")

# ------------------------------------------------------------------- probe ----

PROBE_SESSIONS = counter(
    "simon_probe_sessions_total",
    "Incremental ProbeSessions built (encode-once capacity probing).")
PROBE_PROBES = counter(
    "simon_probe_candidates_total",
    "Candidate node counts evaluated through ProbeSession.probe_many.")
PROBE_DISPATCHES = counter(
    "simon_probe_dispatches_total",
    "Device round-trips spent on capacity probing (fan-out dispatches).")
PROBE_ENCODES = counter(
    "simon_probe_encodes_total",
    "Pod-batch encodes paid by probe sessions (1 per session on the "
    "incremental path).")
PROBE_ENCODE_SECONDS = counter(
    "simon_probe_encode_seconds_total",
    "One-time session build/encode wall seconds.")
PROBE_EXTENSIONS = counter(
    "simon_probe_extensions_total",
    "Template-column node-axis extensions (bucket outgrown mid-search).")
PROBE_FANOUT = histogram(
    "simon_probe_fanout_width",
    "Candidate lanes per fan-out dispatch (post power-of-two quantization).",
    buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0))

# ------------------------------------------------------------------- serve ----
# simonserve (serve/): resident what-if serving — one persistent
# device-resident cluster image, delta ingest, micro-batched request fan-out.

SERVE_REQUESTS = counter(
    "simon_serve_whatif_requests_total",
    "What-if requests served, by route: 'batched' rode a micro-batched "
    "serve_whatif_fanout lane on the resident image, 'fresh' re-simulated "
    "from a fresh encode (ineligible request or contained device failure).",
    ("path",))
SERVE_BATCHES = counter(
    "simon_serve_batches_total",
    "Micro-batched serve dispatches (one device round-trip each; lane "
    "width in simon_serve_batch_lanes).")
SERVE_LANES = histogram(
    "simon_serve_batch_lanes",
    "Requests coalesced per serve dispatch (pre lane-padding).",
    buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0))
SERVE_INGEST_EVENTS = counter(
    "simon_serve_ingest_events_total",
    "Live watch-event deltas applied to the resident cluster image, by "
    "kind (node_add / node_drain / pod_add / pod_delete).",
    ("kind",))
SERVE_RESTAGES = counter(
    "simon_serve_restages_total",
    "Resident-image device re-stages (full table re-upload), by cause: "
    "'groups' (a request interned a new pod group -> new [G, N] rows), "
    "'nodes' (delta node-add extended the node axis), 'rebuild' (an event "
    "the delta path cannot express forced a from-scratch re-encode). Pod "
    "churn never lands here — it refreshes the host-side carry seeds only.",
    ("cause",))
SERVE_SEED_REFRESHES = counter(
    "simon_serve_seed_refreshes_total",
    "Pod-churn seed rebuilds: the host-side carry seeds were re-aggregated "
    "from the placed registry with ZERO device table bytes moved.")
SERVE_STALE_SESSIONS = counter(
    "simon_serve_stale_sessions_total",
    "What-if sessions detected stale (the image generation moved under "
    "them) and transparently re-encoded before dispatch.")

# simonha (serve/ha.py): crash-consistent serving — ingest WAL +
# checkpoint/restore, overload admission control, bounded-staleness
# degraded mode. Labeled families render no samples until touched (the
# byte-identity contract for a serve that never enables --state-dir); the
# two tripwire counters below are deliberately UNLABELED so they always
# render 0 and the bench gate can pin them to zero.

SERVE_WAL_OPS = counter(
    "simon_serve_wal_ops_total",
    "Ingest write-ahead-log operations, by op: 'append' (one fsync'd "
    "record written BEFORE the image mutates), 'replay' (one record "
    "re-applied on restart), 'skip' (replay record at-or-below the "
    "checkpoint seq — the idempotence path), 'truncate' (a torn tail "
    "dropped on open), 'rotate' (the WAL reset after a compaction "
    "checkpoint sealed its records).",
    ("op",))
SERVE_CHECKPOINTS = counter(
    "simon_serve_checkpoints_total",
    "Resident-image checkpoint operations, by op: 'write' (compaction "
    "snapshot sealed via tmp-file + atomic rename), 'restore' (a restart "
    "rebuilt the image from the checkpoint + WAL tail).",
    ("op",))
SERVE_SHEDS = counter(
    "simon_serve_sheds_total",
    "Requests shed by admission control before any queue/device work, by "
    "reason: 'queue_full' (bounded admission queue at capacity), "
    "'deadline' (remaining Deadline cannot cover the observed p95 "
    "queue+dispatch wall), 'rate_limit' (per-tenant-route token bucket "
    "empty), 'payload' (in-flight ingest payload byte cap). Every shed is "
    "a structured 429/413 with Retry-After, never a downstream timeout.",
    ("reason",))
SERVE_BACKPRESSURE = counter(
    "simon_serve_backpressure_total",
    "Micro-batch window adaptations under load, by action: 'shrink' "
    "(sustained queue growth halved the batching window), 'recover' (the "
    "queue drained and the window grew back toward its configured width).",
    ("action",))
SERVE_DEGRADED = gauge(
    "simon_serve_degraded",
    "1 while serving in bounded-staleness degraded mode (ingest stalled, "
    "WAL append failing, or backend quarantined mid-rebuild): answers "
    "keep flowing against the last consistent epoch with staleness_s "
    "stamped on each; 0 when ingest is healthy.")
SERVE_STALENESS = gauge(
    "simon_serve_staleness_seconds",
    "Seconds since the last consistent ingest while degraded (0 when "
    "healthy). Crossing the configured ceiling flips /healthz to 503.")
SERVE_WRONG_EPOCH = counter(
    "simon_serve_wrong_epoch_answers_total",
    "Answers that would have been stamped with an epoch other than the "
    "serving image's consistent epoch. Never nonzero: the HA layer fails "
    "the request loudly instead of lying about its epoch (bench-gate "
    "MUST_BE_ZERO pin).")
SERVE_WAL_MISMATCHES = counter(
    "simon_serve_wal_parity_mismatches_total",
    "WAL/checkpoint lineage-digest mismatches or replay parity failures "
    "detected on restore. Never nonzero: a mismatch refuses the state dir "
    "loudly rather than serving from doubted state (bench-gate "
    "MUST_BE_ZERO pin).")

# -------------------------------------------------------------------- sync ----
# simonsync (live/sync.py): resilient watch ingest keeping the resident
# image consistent against an unreliable delta source.

SYNC_EVENTS = counter(
    "simon_sync_events_total",
    "Watch events seen by the sync loop, by disposition. 'applied' rode a "
    "delta batch into the image; 'duplicate' was already present (informer "
    "cache semantics); 'stale' lost the per-(kind,name) resourceVersion "
    "race; 'skipped' expressed no change the image tracks.",
    ("outcome",))
SYNC_RECONNECTS = counter(
    "simon_sync_reconnects_total",
    "Watch stream teardowns survived by reconnecting from the bookmark "
    "with the seeded backoff schedule.")
SYNC_RELISTS = counter(
    "simon_sync_relists_total",
    "410-Gone recoveries: the sync listed current state and reconciled it "
    "against the resident stores via columnar diff, emitting only delta "
    "events for the gap window.")
SYNC_FULL_REBUILDS = counter(
    "simon_sync_full_rebuilds_total",
    "Relist reconciliations that found an inexpressible change and had to "
    "fall back to a generation-bumping rebuild. Never nonzero in the chaos "
    "gate's traces (bench-gate MUST_BE_ZERO pin).")
SYNC_PARITY = counter(
    "simon_sync_parity_mismatches_total",
    "Post-reconcile parity failures: the resident image's node/pod sets "
    "disagreed with the freshly listed state after applying the diff. "
    "Never nonzero: reconciliation is exact by construction (bench-gate "
    "MUST_BE_ZERO pin).")
SYNC_BOOKMARK_RV = gauge(
    "simon_sync_bookmark_rv",
    "The resourceVersion high-water mark the watch would resume from "
    "after a reconnect or restart.")

# ------------------------------------------------------------------- sweep ----
# simonsweep (sweep/): batched scenario sweeps — Monte-Carlo what-if fleets
# coalesced onto the scenario axis of the sweep_*_fanout kernels.

SWEEP_SCENARIOS = counter(
    "simon_sweep_scenarios_total",
    "Sweep scenarios evaluated, by family and route: 'wave' rode the "
    "per-lane wave-chain fast lane (sweep_wave_fanout), 'scan' the "
    "per-lane serial-scan lane (sweep_whatif_fanout), 'fresh' a "
    "single-scenario fresh Simulator run (census-dependent gate or "
    "contained device failure).",
    ("family", "route"))
SWEEP_DISPATCHES = counter(
    "simon_sweep_dispatches_total",
    "Batched sweep dispatches (one device round-trip per scenario chunk), "
    "by kernel.",
    ("kernel",))
SWEEP_LANES = histogram(
    "simon_sweep_batch_lanes",
    "Scenario lanes coalesced per sweep dispatch (pre lane-padding).",
    buckets=(1.0, 4.0, 16.0, 64.0, 256.0, 1024.0))
SWEEP_PARITY_CHECKS = counter(
    "simon_sweep_parity_checks_total",
    "Sweep lanes re-run on a fresh serial Simulator and census-compared "
    "against the batched placements (the standing parity fuzzer).")
SWEEP_PARITY_MISMATCHES = counter(
    "simon_sweep_parity_mismatches_total",
    "Sweep lanes whose batched placement census diverged from the fresh "
    "serial run. Never nonzero: a mismatch fails the sweep loudly.")

# -------------------------------------------------------------- preemption ----

PREEMPT_ATTEMPTS = counter(
    "simon_preemption_attempts_total",
    "PostFilter runs for failed pods, by outcome (kube-scheduler "
    "preemption_attempts_total).",
    ("outcome",))  # nominated | no_candidates
PREEMPT_VICTIMS = counter(
    "simon_preemption_victims_total",
    "Pods evicted by preemption (kube-scheduler preemption_victims).")
PREEMPT_REPLAY_PODS = counter(
    "simon_preemption_replay_pods_total",
    "Pods re-scheduled by rewind/replay passes — the simulator-specific "
    "cost of exact mid-batch preemption (PARITY.md cost envelope).")

# ---------------------------------------------------------------- resilience --

RETRIES = counter(
    "simon_retries_total",
    "Retried attempts by fault site (resilience/policy.py RetryPolicy; "
    "counts each retry, not first attempts).",
    ("site",))
DEADLINE_EXCEEDED = counter(
    "simon_deadline_exceeded_total",
    "Operations abandoned because the contextvar deadline budget ran out, "
    "by the site that noticed.",
    ("site",))
BREAKER_STATE = gauge(
    "simon_breaker_state",
    "Circuit-breaker state: 0 closed, 1 half-open, 2 open "
    "(resilience/policy.py CircuitBreaker).",
    ("name",))
FAULTS_INJECTED = counter(
    "simon_faults_injected_total",
    "Injected failures fired by the active FaultPlan, by site "
    "(resilience/faults.py; zero in production).",
    ("site",))
HTTP_ERRORS = counter(
    "simon_http_errors_total",
    "Server request failures by endpoint and HTTP status code "
    "(structured JSON error bodies, server/http.py).",
    ("endpoint", "code"))

# ------------------------------------------------------------------- guard ----
# simonguard (resilience/guard.py): mid-run device-failure containment. The
# acceptance contract is "no silent degradation" — every watchdog expiry,
# bisection, failover, and quarantine moves one of these.

GUARD_WATCHDOG_EXPIRIES = counter(
    "simon_guard_watchdog_expiries_total",
    "Supervised device computations declared wedged (watchdog deadline "
    "expired or injected watchdog_wedge fault), by dispatch site.",
    ("site",))
GUARD_OOM_BISECTIONS = counter(
    "simon_guard_oom_bisections_total",
    "Pod-batch halvings performed to contain a device OOM, by the stage "
    "that OOM'd (to_device / dispatch).",
    ("site",))
GUARD_FAILOVERS = counter(
    "simon_guard_failovers_total",
    "Mid-run backend failovers to the CPU fallback, by cause "
    "(watchdog_wedge / oom_exhausted / oom). Each also appends to the "
    "result's backend_path.",
    ("cause",))
GUARD_QUARANTINED = gauge(
    "simon_guard_quarantined",
    "1 while the labeled backend is quarantined for this process "
    "(wedged mid-run; all later device work routes to the CPU fallback).",
    ("backend",))
JOURNAL_RECORDS = counter(
    "simon_journal_records_total",
    "Probe verdicts appended (write+flush+fsync) to a capacity-search "
    "journal (resilience/guard.py SearchJournal).")
JOURNAL_REPLAYS = counter(
    "simon_journal_replayed_probes_total",
    "Capacity-search probes skipped because a resumed journal already "
    "held their verdict.")

# ------------------------------------------------------------------- xray -----
# simonxray (obs/xray.py): both counters are LABELED on purpose — an
# untouched labeled family renders no samples, so a recording-off run's
# /metrics and --metrics-out output stays byte-identical to pre-xray builds.

XRAY_RECORDS = counter(
    "simon_xray_records_total",
    "Flight-recorder records committed, by kind (batch / pod / set / "
    "preempt / probe). Zero unless --xray / OPEN_SIMULATOR_XRAY=1.",
    ("kind",))
XRAY_DROPPED = counter(
    "simon_xray_dropped_total",
    "Flight-recorder records dropped by the bounded-memory caps, by kind "
    "(set: OPEN_SIMULATOR_XRAY_MAX_SETS; pod_index: the in-memory explain "
    "index, the JSONL trace keeps everything). Never silent: the first "
    "drop logs a warning.",
    ("kind",))

# ------------------------------------------------------------------- scope ----
# simonscope (obs/scope.py): request tracing + SLO engine + device-runtime
# telemetry. Every family here is LABELED on purpose (the xray contract): an
# untouched labeled family renders no samples, so a scope-off run's /metrics
# and --metrics-out output stays byte-identical to pre-scope builds.

SCOPE_REQUESTS = counter(
    "simon_scope_requests_total",
    "Requests finished under simonscope SLO accounting, by endpoint and "
    "route (batched / fresh / error). Zero unless scope is on "
    "(`simon serve`'s default; OPEN_SIMULATOR_SCOPE=1 elsewhere).",
    ("endpoint", "route"))
SCOPE_PHASE_SECONDS = histogram(
    "simon_scope_request_phase_seconds",
    "Cumulative per-request latency decomposition (queue-wait in the "
    "micro-batch dispatcher / kernel dispatch / device fetch / total), by "
    "endpoint and phase — the Clipper-style breakdown that makes the "
    "batching window tunable. The rolling-window quantiles live in "
    "simon_scope_latency_ms.",
    ("endpoint", "phase"), buckets=SECONDS_BUCKETS)
SCOPE_SLO_VIOLATIONS = counter(
    "simon_scope_slo_violations_total",
    "Requests that violated their endpoint's SLO target (latency over the "
    "p99 target, or an error response), by endpoint.",
    ("endpoint",))
SCOPE_QUANTILE_MS = gauge(
    "simon_scope_latency_ms",
    "Rolling-window latency quantiles per endpoint and phase "
    "(refreshed on each scoped /metrics or /v1/serve/stats read).",
    ("endpoint", "phase", "quantile"))
SCOPE_BUDGET_BURN = gauge(
    "simon_scope_error_budget_burn",
    "Error-budget burn rate per endpoint: (bad-request fraction) / "
    "(allowed fraction from the availability target); >1 means the budget "
    "is burning faster than the SLO allows.",
    ("endpoint",))
SCOPE_TRACE_EVENTS = counter(
    "simon_scope_trace_events_total",
    "Trace events recorded into the in-memory buffer, by kind "
    "(span / flow / counter).",
    ("kind",))
SCOPE_TRACE_DROPPED = counter(
    "simon_scope_trace_dropped_total",
    "Trace events dropped because the bounded buffer was full, by kind. "
    "Never silent: a full buffer drops NEW events and counts every one.",
    ("kind",))
SCOPE_POOL_BYTES = gauge(
    "simon_scope_device_pool_bytes",
    "Live device-buffer bytes attributed to a pool by the runtime sampler "
    "(image_tables / carry_cache / scratch) — the Orca-style resident-state "
    "footprint track that makes image leaks under churn visible.",
    ("pool",))
SCOPE_COMPILE_DELTA = gauge(
    "simon_scope_compile_cache_delta",
    "Compile-cache hit/miss deltas over the sampler's last interval, by "
    "kind; a nonzero 'misses' track during steady serving means requests "
    "are minting fresh shape buckets.",
    ("kind",))
SCOPE_TRANSFER_RATE = gauge(
    "simon_scope_transfer_bytes_per_s",
    "Host->device transfer rate over the sampler's last interval, by "
    "direction (steady serving on a warm image should hold this at ~0).",
    ("direction",))
SCOPE_SAMPLES = counter(
    "simon_scope_runtime_samples_total",
    "Telemetry ticks completed by the device-runtime sampler thread, by "
    "kind.",
    ("kind",))
SCOPE_SAMPLER_ERRORS = counter(
    "simon_scope_sampler_errors_total",
    "Telemetry tick failures (a pool provider raised, live-array walk "
    "failed). The sampler keeps running; failures are counted, not silent.",
    ("kind",))

# ------------------------------------------------------------------- pulse ----
# simonpulse (obs/pulse.py): roofline cost accounting + the per-dispatch
# performance ledger. Every family here is LABELED on purpose (the xray/scope
# contract): an untouched labeled family renders no samples, so a pulse-off
# run's /metrics and --metrics-out output stays byte-identical to pre-pulse
# builds.

PULSE_RECORDS = counter(
    "simon_pulse_records_total",
    "Performance-ledger records appended, by kind (dispatch / run). Zero "
    "unless pulse is on (OPEN_SIMULATOR_PULSE=1 or pulse.enable()).",
    ("kind",))
PULSE_DROPPED = counter(
    "simon_pulse_records_dropped_total",
    "Ledger records evicted from the bounded ring buffer, by kind "
    "(OPEN_SIMULATOR_PULSE_CAP; the JSONL spill, when configured, keeps "
    "every record). Never silent: every eviction is counted here.",
    ("kind",))
PULSE_REGRESSIONS = counter(
    "simon_pulse_regressions_total",
    "Warm dispatches flagged as MAD outliers against their rolling "
    "per-(kernel, dispatch-digest) warm-wall baseline — 'same executable, "
    "slower environment' drift (OPEN_SIMULATOR_PULSE_MAD_K).",
    ("kernel", "bucket"))
PULSE_PHASE_SECONDS = counter(
    "simon_pulse_phase_seconds_total",
    "Scheduling-run wall seconds by phase (encode / table_build / to_device "
    "/ dispatch / fetch / commit) — the per-run decomposition of "
    "simon_e2e_scheduling_duration_seconds the ledger's run records carry. "
    "table_build is the node-axis [*, N] table construction inside encode, "
    "counted per chunk on the streaming path (ROADMAP item 5).",
    ("phase",))
PULSE_ACHIEVED = gauge(
    "simon_pulse_achieved_fraction",
    "Most recent achieved fraction of the roofline model-optimal time per "
    "warm dispatch: model_optimal_s / measured wall, from cost_analysis "
    "FLOPs/bytes at OPEN_SIMULATOR_PEAK_GFLOPS / OPEN_SIMULATOR_PEAK_GBS.",
    ("kernel", "bucket"))

# ---------------------------------------------------------- capacity search ---

CAPACITY_SEARCHES = counter(
    "simon_capacity_searches_total",
    "Add-node capacity-planner searches, by probe path.",
    ("path",))  # incremental | fresh
CAPACITY_ROUNDS = counter(
    "simon_capacity_search_rounds_total",
    "Search rounds (device dispatches) spent by capacity searches.")

# ------------------------------------------------- dispatch shape tracking ----

_SEEN_SHAPES: Set[Tuple] = set()
_SEEN_LOCK = threading.Lock()

# simonpulse attribution hook: pulse.enable() installs its note_dispatch here
# so every record_dispatch call (THE definition of "one kernel dispatch")
# also lands in the performance ledger; None keeps pulse-off dispatches at
# exactly one extra global read. instruments never imports pulse — the hook
# direction keeps the catalog import-light.
_DISPATCH_HOOK = None


def record_dispatch(kernel: str, **dims) -> bool:
    """Count one kernel dispatch against the compile cache: the first time a
    (kernel, static-shape) signature is seen in this process it is a miss
    (XLA compiles or loads the persistent cache), afterwards a hit. `dims`
    must contain exactly the dispatch's static/shape-defining parts — traced
    values never belong here. Returns True on miss (fresh compile)."""
    key = (kernel,) + tuple(sorted(dims.items()))
    with _SEEN_LOCK:
        miss = key not in _SEEN_SHAPES
        if miss:
            _SEEN_SHAPES.add(key)
    if miss:
        shape = ",".join(f"{k}={v}" for k, v in sorted(dims.items()))
        COMPILE_MISSES.labels(kernel=kernel, shape=shape).inc()
    else:
        COMPILE_HITS.labels(kernel=kernel).inc()
    hook = _DISPATCH_HOOK
    if hook is not None:
        hook(kernel, dims, miss)
    return miss


def record_filter_reasons(reasons: Dict[str, int]) -> None:
    """Fold one failed pod's FitError reason counts (label -> node count)
    into the rejection counters."""
    for label, n in reasons.items():
        FILTER_REJECTIONS.labels(reason=label).inc(n)


_jaxmon_installed = False


def install_jax_monitoring() -> None:
    """Register the jax.monitoring listener that counts real XLA backend
    compiles (idempotent; safe when jax is absent/old). Called from
    Simulator.__init__, which has already committed to importing jax."""
    global _jaxmon_installed
    if _jaxmon_installed:
        return
    _jaxmon_installed = True
    try:
        from jax import monitoring

        def _on_duration(event: str, duration: float, **kw) -> None:
            if event == "/jax/core/compile/backend_compile_duration":
                XLA_COMPILES.inc()
                XLA_COMPILE_SECONDS.inc(duration)

        monitoring.register_event_duration_secs_listener(_on_duration)
    # simonlint: ignore[swallowed-exception] -- diagnostics-only listener; a
    # jax too old for monitoring must never break the engine, and there is
    # nothing to count into (this IS the metrics bootstrap)
    except Exception:
        pass
