"""First-party metrics registry: Counter / Gauge / Histogram, zero deps.

The reference leans on kube-scheduler's component-base metrics surface
(schedule_attempts_total, e2e_scheduling_duration_seconds, the framework
extension-point histograms) exposed over /metrics; this is the same idea
without a prometheus_client dependency: a process-wide thread-safe registry
of typed metric families with label support, a Prometheus-text renderer for
the server's `GET /metrics`, and a JSON snapshot form used by the CLI's
`--metrics-out`, bench rows, and `/debug/vars`.

Design constraints, in order:
- **Host-side only.** Nothing here may run under a JAX trace — the
  `metric-in-jit` simonlint rule enforces the call-site half of that
  contract. No jax imports, ever.
- **Cheap increments.** One lock acquisition per update on a pre-resolved
  child (`.labels()` is amortized: resolve once, hold the child). The hot
  engine paths update per BATCH, not per pod.
- **Get-or-create.** `counter(name, ...)` returns the existing family when
  already registered (the engine is constructed many times per process);
  re-registering under a different type or label set is a programming error
  and raises.
"""

from __future__ import annotations

import math
import threading
from bisect import bisect_left
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# Default histogram buckets for wall-clock seconds (scheduling spans many
# decades: µs-scale host bookkeeping to multi-second cold compiles).
SECONDS_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0)
# Pod-count buckets: powers of ~4 up to the north-star batch size.
PODS_BUCKETS = (1.0, 8.0, 32.0, 128.0, 512.0, 2048.0, 8192.0, 32768.0, 131072.0)


def _fmt(v: float) -> str:
    """Prometheus sample value: integers render bare (stable goldens)."""
    if v == math.inf:
        return "+Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_str(names: Sequence[str], values: Sequence[str]) -> str:
    if not names:
        return ""
    inner = ",".join(f'{n}="{_escape_label(v)}"' for n, v in zip(names, values))
    return "{" + inner + "}"


class _Child:
    """One labeled time series. Updates lock the family's lock (uncontended
    in practice: the engine updates from one thread per Simulator)."""

    __slots__ = ("_family", "_value")

    def __init__(self, family: "MetricFamily") -> None:
        self._family = family
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0 and self._family.type == "counter":
            raise ValueError("counters only go up")
        with self._family._lock:
            self._value += amount

    def set(self, value: float) -> None:
        if self._family.type != "gauge":
            raise TypeError(f"set() on a {self._family.type}")
        with self._family._lock:
            self._value = float(value)

    @property
    def value(self) -> float:
        return self._value


class _HistChild:
    __slots__ = ("_family", "_counts", "_sum", "_count")

    def __init__(self, family: "MetricFamily") -> None:
        self._family = family
        self._counts = [0] * (len(family.buckets) + 1)  # +1: the +Inf bucket
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        # Prometheus bucket semantics: le is INCLUSIVE (value <= bound).
        i = bisect_left(self._family.buckets, value)
        with self._family._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1


class MetricFamily:
    """One named metric with a fixed label-name tuple and typed children."""

    def __init__(self, name: str, help: str, type: str,
                 label_names: Tuple[str, ...] = (),
                 buckets: Optional[Tuple[float, ...]] = None) -> None:
        self.name = name
        self.help = help
        self.type = type  # "counter" | "gauge" | "histogram"
        self.label_names = tuple(label_names)
        if type == "histogram":
            bs = tuple(float(b) for b in (buckets or SECONDS_BUCKETS))
            if list(bs) != sorted(bs):
                raise ValueError(f"{name}: buckets must be sorted")
            self.buckets: Tuple[float, ...] = bs
        else:
            self.buckets = ()
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}

    # ------------------------------------------------------------- children --

    def labels(self, **kv: str):
        if set(kv) != set(self.label_names):
            raise ValueError(
                f"{self.name}: labels {sorted(kv)} != declared "
                f"{sorted(self.label_names)}")
        key = tuple(str(kv[n]) for n in self.label_names)
        # simonlint: ignore[race-unguarded-attr] -- double-checked fast path:
        # dict.get is GIL-atomic and a miss re-checks under _lock below, which
        # is the only publisher; a stale miss costs one lock round-trip
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.get(key)
                if child is None:
                    child = (_HistChild(self) if self.type == "histogram"
                             else _Child(self))
                    self._children[key] = child
        return child

    def _default(self):
        if self.label_names:
            raise ValueError(f"{self.name}: labeled metric needs .labels(...)")
        return self.labels()

    # unlabeled conveniences
    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def set(self, value: float) -> None:
        self._default().set(value)

    def observe(self, value: float) -> None:
        self._default().observe(value)

    # ------------------------------------------------------------ rendering --

    def samples(self) -> List[dict]:
        """JSON-able per-child samples (snapshot form). Child state is COPIED
        under the family lock, so every row is internally consistent even
        while 16 serve threads update it — a torn histogram (counts bumped,
        sum not yet) can never escape into a scrape (the /metrics +
        /debug/pprof concurrent-scrape fix; tests/test_scope.py hammers it)."""
        with self._lock:
            items = sorted(self._children.items())
            if self.type == "histogram":
                rows = [(key, list(child._counts), child._sum, child._count)
                        for key, child in items]
            else:
                rows = [(key, child._value) for key, child in items]
        out: List[dict] = []
        if self.type == "histogram":
            for key, counts, hsum, count in rows:
                out.append({
                    "labels": dict(zip(self.label_names, key)),
                    "buckets": [[b, c] for b, c in
                                zip(list(self.buckets) + ["+Inf"], counts)],
                    "sum": hsum,
                    "count": count,
                })
        else:
            for key, value in rows:
                out.append({"labels": dict(zip(self.label_names, key)),
                            "value": value})
        return out

    def render(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.type}"]
        for s in self.samples():  # one locked copy; render from the snapshot
            key = tuple(str(s["labels"][n]) for n in self.label_names)
            if self.type == "histogram":
                cum = 0
                for b, c in s["buckets"]:
                    cum += c
                    le = "+Inf" if b == "+Inf" else _fmt(float(b))
                    ls = _label_str(self.label_names + ("le",), key + (le,))
                    lines.append(f"{self.name}_bucket{ls} {cum}")
                base = _label_str(self.label_names, key)
                lines.append(f"{self.name}_sum{base} {_fmt(s['sum'])}")
                lines.append(f"{self.name}_count{base} {s['count']}")
            else:
                ls = _label_str(self.label_names, key)
                lines.append(f"{self.name}{ls} {_fmt(s['value'])}")
        return lines


class Registry:
    """Process-wide metric store. `REGISTRY` below is the default instance;
    tests build private ones."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._families: Dict[str, MetricFamily] = {}

    def _get_or_create(self, name: str, help: str, type: str,
                       label_names: Iterable[str],
                       buckets: Optional[Tuple[float, ...]] = None
                       ) -> MetricFamily:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.type != type or fam.label_names != tuple(label_names):
                    raise ValueError(
                        f"metric {name!r} re-registered with different "
                        f"type/labels ({fam.type}{fam.label_names} vs "
                        f"{type}{tuple(label_names)})")
                return fam
            fam = MetricFamily(name, help, type, tuple(label_names), buckets)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str,
                labels: Iterable[str] = ()) -> MetricFamily:
        return self._get_or_create(name, help, "counter", labels)

    def gauge(self, name: str, help: str,
              labels: Iterable[str] = ()) -> MetricFamily:
        return self._get_or_create(name, help, "gauge", labels)

    def histogram(self, name: str, help: str, labels: Iterable[str] = (),
                  buckets: Optional[Tuple[float, ...]] = None) -> MetricFamily:
        return self._get_or_create(name, help, "histogram", labels, buckets)

    # ------------------------------------------------------------- exports ---

    def render_text(self) -> str:
        """Prometheus exposition format (text/plain; version=0.0.4). Built
        from ONE snapshot() pass so a scrape racing concurrent updates sees
        every family at a single locked copy (no partially-applied rows) —
        and /metrics, /debug/vars, and --metrics-out all flatten the same
        snapshot shape."""
        return render_text_from_snapshot(self.snapshot())

    def snapshot(self) -> dict:
        """JSON-able full dump: {name: {type, help, labels, samples}}."""
        with self._lock:
            fams = [self._families[n] for n in sorted(self._families)]
        return {
            fam.name: {
                "type": fam.type,
                "help": fam.help,
                "label_names": list(fam.label_names),
                **({"bucket_bounds": list(fam.buckets)}
                   if fam.type == "histogram" else {}),
                "samples": fam.samples(),
            }
            for fam in fams
        }

    def values(self) -> Dict[str, float]:
        """Flat {name{labels}: value} view — /debug/vars and bench rows.
        Histograms flatten to _sum/_count only (buckets stay in snapshot())."""
        return values_from_snapshot(self.snapshot())


def values_from_snapshot(snap: dict) -> Dict[str, float]:
    """Flat {name{labels}: value} view of a snapshot() dump — shared by
    Registry.values() and `simon metrics --diff`, so live and saved dumps
    flatten identically (same sample keys, same histogram _sum/_count
    treatment) and a diff can line them up one-to-one."""
    out: Dict[str, float] = {}
    for name, fam in sorted(snap.items()):
        for s in fam.get("samples", []):
            labels = s.get("labels", {})
            ls = _label_str(tuple(sorted(labels)),
                            tuple(v for _, v in sorted(labels.items())))
            if fam.get("type") == "histogram":
                out[f"{name}_sum{ls}"] = s.get("sum", 0.0)
                out[f"{name}_count{ls}"] = s.get("count", 0)
            else:
                out[f"{name}{ls}"] = s.get("value", 0.0)
    return out


def render_text_from_snapshot(snap: dict) -> str:
    """Rebuild Prometheus text from a snapshot() dump — `simon metrics
    FILE.json` renders saved dumps without re-running anything."""
    lines: List[str] = []
    for name in sorted(snap):
        fam = snap[name]
        label_names = tuple(fam.get("label_names") or ())
        lines.append(f"# HELP {name} {fam.get('help', '')}")
        lines.append(f"# TYPE {name} {fam.get('type', 'untyped')}")
        for s in fam.get("samples", []):
            key = tuple(str(s.get("labels", {}).get(n, "")) for n in label_names)
            if fam.get("type") == "histogram":
                cum = 0
                for b, c in s.get("buckets", []):
                    cum += c
                    le = "+Inf" if b == "+Inf" else _fmt(float(b))
                    ls = _label_str(label_names + ("le",), key + (le,))
                    lines.append(f"{name}_bucket{ls} {cum}")
                base = _label_str(label_names, key)
                lines.append(f"{name}_sum{base} {_fmt(float(s.get('sum', 0.0)))}")
                lines.append(f"{name}_count{base} {int(s.get('count', 0))}")
            else:
                ls = _label_str(label_names, key)
                lines.append(f"{name}{ls} {_fmt(float(s.get('value', 0.0)))}")
    return "\n".join(lines) + ("\n" if lines else "")


REGISTRY = Registry()


def counter(name: str, help: str, labels: Iterable[str] = ()) -> MetricFamily:
    return REGISTRY.counter(name, help, labels)


def gauge(name: str, help: str, labels: Iterable[str] = ()) -> MetricFamily:
    return REGISTRY.gauge(name, help, labels)


def histogram(name: str, help: str, labels: Iterable[str] = (),
              buckets: Optional[Tuple[float, ...]] = None) -> MetricFamily:
    return REGISTRY.histogram(name, help, labels, buckets)
