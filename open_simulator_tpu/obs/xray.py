"""simonxray: the per-pod scheduling flight recorder.

The reference's most-consumed output is not placements but *explanations*:
kube-scheduler's `FailedScheduling` event strings ("0/N nodes are available:
X Insufficient cpu, ...") and the unschedulable-pod report. The batched
wave/affinity kernels made the hot path fast but opaque — aggregate
`simon_filter_rejections_total{reason}` counters cannot answer "why THIS
pod, on THIS node, in THIS wave". simonxray records, per pod, a compact
decision record:

- **segment attribution**: which dispatch batch / segment (kind, group,
  epoch/round/head-fallback stats for affinity waves) placed or failed it;
- **per-plugin filter bitmask over nodes**: the named per-stage feasibility
  masks the fused kernels already compute (ops/kernels.explain_pod), fetched
  ONCE per committed (group, segment) — never per pod, never inside the
  dispatch loop;
- **per-plugin score vector**: weighted component scores
  (kernels.score_components) for the top-k candidate nodes with margins,
  plus the full [N] total/component arrays in the npz sidecar;
- **kube-parity reason strings** for unschedulable pods (the engine's
  FitError text, whose per-reason node counts sum to N) and **preemption
  victim chains** for preemptors.

Recording is OPT-IN (`simon apply --xray`, `simon server --xray`,
`OPEN_SIMULATOR_XRAY=1`) and zero-cost when off: the engine takes one
`xray.begin_run()` None-check per schedule/probe call and dispatches nothing
extra. When on, the trace spills to a columnar JSONL file (one line per
batch, pods as parallel arrays) plus an `.npz` sidecar for the full-width
mask/score arrays, and is queryable three ways: `simon explain POD`,
`GET /explain/<pod>` + the unscheduled summary on `/debug/vars`, and the
decision annotations carried by each schedule_run span in the `--trace-out`
Chrome trace.

Crash/failover discipline: records stage per engine *attempt* and only
commit after the call succeeds — a batch rolled back by the transaction (an
injected fault, a wedge about to fail over) never leaves phantom records,
and committed records carry the simulator's backend_path so a degraded
(failed-over) run is visible on every record it produced.

Record kinds (first JSONL line is the header):

    {"kind": "header", "version": 1, ...}
    {"kind": "nodes", "id": H, "names": [...]}          # deduped node lists
    {"kind": "set",   "id": S, ...}                     # per (group, segment)
    {"kind": "batch", "id": B, "pods": [...], ...}      # columnar pod rows
    {"kind": "preempt", "pod": ..., "victims": [...]}
    {"kind": "probe", "scheduled": X, "total": Y, ...}

Everything here is host-side and numpy/stdlib-only; the `fetch-in-wave-loop`
simonlint rule guards the engine half of the contract (no device→host
fetches inside per-segment/per-epoch loops outside the designated spill
points).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import instruments as obs

# Stage names in the engine's diagnosis order (engine._STAGE_ORDER plus the
# root static mask); each packs one [N] feasibility-mask row per set.
STAGE_NAMES = (
    "static", "unsched", "taint", "affinity", "extra", "ports", "fit",
    "spread", "pod_affinity", "pod_anti", "gpu", "storage",
)

# Per-plugin score component names, mirroring ops/kernels.COMPONENT_ORDER.
# Duplicated HERE (tests/test_xray.py asserts equality) so the offline query
# path — `simon explain` over a saved trace — never imports jax.
COMPONENT_NAMES = (
    "least", "balanced", "openlocal", "simon", "nodeaff", "taint",
    "interpod", "selector_spread", "topology_spread", "avoid", "image",
    "extra",
)

# Result codes for the columnar pod rows (compact ints, stable on disk).
SCHEDULED, UNSCHEDULABLE, BOUND, HOMELESS, PREEMPTED = 0, 1, 2, 3, 4
RESULT_NAMES = {
    SCHEDULED: "scheduled",
    UNSCHEDULABLE: "unschedulable",
    BOUND: "bound",           # pre-bound spec.nodeName direct commit
    HOMELESS: "homeless",     # bound to an unknown node (dropped from reports)
    PREEMPTED: "preempted",   # evicted by a higher-priority preemptor
}

VERSION = 1


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:  # tuning knob: fall back, don't crash
        return default


def pod_key(pod: dict) -> str:
    """The index key for a pod: 'namespace/name' (kube event addressing)."""
    md = pod.get("metadata") or {}
    return f"{md.get('namespace') or 'default'}/{md.get('name') or ''}"


# ------------------------------------------------------------------ staging ----


class XrayBatch:
    """Columnar staging for one dispatch batch (one `_dispatch_and_commit` /
    direct-commit stretch): parallel pod-row arrays plus batch metadata."""

    __slots__ = ("nodes_names", "cfg", "segments", "call", "pods", "result",
                 "node", "seg", "set_ids", "reasons", "groups")

    def __init__(self, nodes_names: List[str], cfg: str,
                 segments: List[dict], call: str) -> None:
        self.nodes_names = nodes_names
        self.cfg = cfg
        self.segments = segments  # [{kind,start,len,group,...,stats?}]
        self.call = call
        self.pods: List[str] = []
        self.result: List[int] = []
        self.node: List[int] = []
        self.seg: List[int] = []
        self.set_ids: List[int] = []
        self.groups: List[int] = []
        self.reasons: Dict[int, str] = {}  # row -> FitError reason string

    def add_pod(self, key: str, result: int, node_i: int, seg: int,
                set_id: int, group: int = -1,
                reason: Optional[str] = None) -> None:
        if reason is not None:
            self.reasons[len(self.pods)] = reason
        self.pods.append(key)
        self.result.append(result)
        self.node.append(node_i)
        self.seg.append(seg)
        self.set_ids.append(set_id)
        self.groups.append(group)


class XraySet:
    """One decision set: the per-stage masks and per-plugin scores for a
    (group, forced, segment) key, computed once and shared by every pod of
    that key. Arrays are full-width [N]; the JSONL record carries counts and
    the top-k table, the arrays go to the npz sidecar / in-memory store."""

    __slots__ = ("group", "forced", "seg", "n_feasible", "stage_reject",
                 "mask_bits", "feas_bits", "total", "comp", "topk", "reasons")

    def __init__(self, group: int, forced: int, seg: int,
                 stages: Dict[str, np.ndarray], total: np.ndarray,
                 comp: Dict[str, np.ndarray], feasible: np.ndarray,
                 node_names: List[str], topk: int = 8) -> None:
        self.group, self.forced, self.seg = group, forced, seg
        N = int(total.shape[0])
        self.n_feasible = int(feasible.sum())
        mask_rows = np.stack([np.asarray(stages[s], bool)
                              for s in STAGE_NAMES])          # [stages, N]
        self.stage_reject = {
            s: int(N - mask_rows[i].sum()) for i, s in enumerate(STAGE_NAMES)
            if int(N - mask_rows[i].sum())
        }
        self.mask_bits = np.packbits(mask_rows, axis=1)       # [stages, ⌈N/8⌉]
        self.feas_bits = np.packbits(np.asarray(feasible, bool))  # [⌈N/8⌉]
        self.total = np.asarray(total, np.float32)
        self.comp = np.stack([np.asarray(comp[c], np.float32)
                              for c in COMPONENT_NAMES])      # [C, N]
        self.reasons: Optional[Dict[str, int]] = None  # failed sets only
        # top-k candidates under serial's exact tie-break (score desc, node
        # index asc) — the chosen node of the segment's first pick is topk[0]
        idx = np.nonzero(np.asarray(feasible, bool))[0]
        self.topk = []
        if idx.size:
            order = idx[np.lexsort((idx, -self.total[idx]))][:topk]
            best = float(self.total[order[0]])
            for i in order:
                self.topk.append({
                    "node": node_names[int(i)],
                    "total": round(float(self.total[i]), 4),
                    "margin": round(best - float(self.total[i]), 4),
                    "components": {
                        c: round(float(self.comp[ci, i]), 4)
                        for ci, c in enumerate(COMPONENT_NAMES)
                    },
                })

    def record(self, sid: int, batch: int) -> dict:
        rec = {
            "kind": "set", "id": sid, "batch": batch, "group": self.group,
            "forced": self.forced, "seg": self.seg,
            "n_feasible": self.n_feasible,
            "stage_reject": self.stage_reject, "topk": self.topk,
        }
        if self.reasons is not None:
            rec["reasons"] = self.reasons
        return rec


class XrayRun:
    """Per-attempt staging for one schedule/probe call. Thrown away when the
    attempt fails (the transaction rolled the placements back too); committed
    to the recorder — with the final backend_path — on success."""

    def __init__(self, recorder: "XrayRecorder", call: str) -> None:
        self.recorder = recorder
        self.call = call
        self.batches: List[XrayBatch] = []
        self.sets: List[XraySet] = []
        self.preempts: List[dict] = []
        self.probes: List[dict] = []

    def new_batch(self, nodes_names: List[str], cfg: str,
                  segments: List[dict]) -> XrayBatch:
        b = XrayBatch(nodes_names, cfg, segments, self.call)
        self.batches.append(b)
        return b

    def add_set(self, s: XraySet) -> int:
        """Stage a decision set; returns its run-local id (remapped to a
        recorder-global id at commit). The set belongs to the batch being
        processed — always the latest staged one (the engine builds sets
        inside that batch's commit loop)."""
        self.sets.append((len(self.batches) - 1, s))
        return len(self.sets) - 1

    def add_preempt(self, preemptor: str, node: str, victims: List[str],
                    reason: str, reasons: Dict[str, int],
                    nominated: bool) -> None:
        self.preempts.append({
            "kind": "preempt", "pod": preemptor, "node": node,
            "victims": victims, "reason": reason, "reasons": reasons,
            "nominated": nominated,
        })

    def add_probe(self, scheduled: int, total: int,
                  candidate: Optional[int] = None) -> None:
        rec = {"kind": "probe", "scheduled": scheduled, "total": total}
        if candidate is not None:
            rec["candidate_nodes"] = candidate
        self.probes.append(rec)


# ----------------------------------------------------------------- recorder ----


class XrayRecorder:
    """The process-wide flight recorder: commits staged runs to the columnar
    JSONL trace (plus npz sidecar at close) and keeps a bounded in-memory
    index for `GET /explain/<pod>` / `/debug/vars`."""

    def __init__(self, path: Optional[str] = None,
                 max_sets: Optional[int] = None,
                 max_pods_mem: Optional[int] = None) -> None:
        self.path = path  # prefix: writes <path>.jsonl + <path>.npz
        self.max_sets = (max_sets if max_sets is not None
                         else _env_int("OPEN_SIMULATOR_XRAY_MAX_SETS", 4096))
        self.max_pods_mem = (
            max_pods_mem if max_pods_mem is not None
            else _env_int("OPEN_SIMULATOR_XRAY_MAX_PODS", 500_000))
        self._lock = threading.Lock()
        self._f = None
        self._next_set = 0
        self._next_batch = 0
        self._sets: Dict[int, dict] = {}          # sid -> set record
        self._arrays: Dict[str, np.ndarray] = {}  # npz payload (bounded)
        self._nodes: Dict[int, List[str]] = {}    # nodes-list id -> names
        self._node_ids: Dict[int, int] = {}       # content hash -> nodes id
        self._index: Dict[str, dict] = {}         # pod key -> resolved row
        self._unscheduled: Dict[str, str] = {}    # pod key -> reason string
        # LAZY indexing: building one row dict per pod costs ~2-3us x pods,
        # which on a 100k-pod run is most of the recording overhead — so
        # commit() only queues the (already-serialized) batch/preempt
        # records and the query paths index on demand. _PENDING_FLUSH bounds
        # the queue for long-lived unqueried servers.
        self._pending: List[Tuple[str, dict]] = []
        self._pod_rows = 0          # committed pod rows (exact, cheap)
        self._unscheduled_rows = 0  # result==UNSCHEDULABLE rows (pre-index)
        self._dropped_sets = 0
        self._warned_cap = False
        self.closed = False

    _PENDING_FLUSH = 512  # index inline once this many records queue up

    # ------------------------------------------------------------- writing --

    def _file_locked(self):
        if self.path and self._f is None:
            self._f = open(self.path + ".jsonl", "w", encoding="utf-8")
            self._write_locked(self._header())
        return self._f

    def _header(self) -> dict:
        return {
            "kind": "header", "xray": VERSION, "version": VERSION,
            "pid": os.getpid(), "created_unix": round(time.time(), 3),
            "stage_names": list(STAGE_NAMES),
            "component_names": list(_component_order()),
        }

    def _write_locked(self, rec: dict) -> None:
        f = self._f
        if f is not None:
            f.write(json.dumps(rec, separators=(",", ":")) + "\n")

    def _nodes_id(self, names: List[str]) -> int:
        # content-keyed dedupe (NOT id(): a freed list's id can be reused):
        # capacity searches re-simulate over near-identical clusters, so one
        # nodes record serves every batch that shares the name list
        key = hash(tuple(names))
        nid = self._node_ids.get(key)
        if nid is None:
            nid = len(self._nodes)
            self._node_ids[key] = nid
            self._nodes[nid] = list(names)
            self._write_locked({"kind": "nodes", "id": nid, "names": self._nodes[nid]})
        return nid

    def commit(self, run: XrayRun, backend_path: List[str],
               cfg_digest: str = "") -> None:
        """Fold one successful call's staging into the trace + index."""
        with self._lock:
            if self.closed:
                return
            self._file_locked()
            sid_of: Dict[int, int] = {}
            dropped = 0
            first_bid = self._next_batch  # run-local batch k -> first_bid + k
            for local, (batch_local, s) in enumerate(run.sets):
                if len(self._sets) >= self.max_sets:
                    dropped += 1
                    sid_of[local] = -1
                    continue
                sid = self._next_set
                self._next_set += 1
                sid_of[local] = sid
                rec = s.record(sid, first_bid + max(batch_local, 0))
                self._sets[sid] = rec
                self._arrays[f"s{sid}_total"] = s.total
                self._arrays[f"s{sid}_comp"] = s.comp
                self._arrays[f"s{sid}_mask"] = s.mask_bits
                self._arrays[f"s{sid}_feas"] = s.feas_bits
                self._write_locked(rec)
                obs.XRAY_RECORDS.labels(kind="set").inc()
            if dropped:
                # counted on EVERY commit that drops (not only the first):
                # the never-silent contract is a running total in /metrics
                self._dropped_sets += dropped
                obs.XRAY_DROPPED.labels(kind="set").inc(dropped)
                if not self._warned_cap:
                    self._warned_cap = True
                    import logging

                    logging.getLogger("open_simulator_tpu").warning(
                        "xray: decision-set cap reached (%d); later sets are "
                        "dropped (pods keep their rows with set=-1; raise "
                        "OPEN_SIMULATOR_XRAY_MAX_SETS to keep them)",
                        self.max_sets)
            # last-writer-wins pod ownership: preemption rewind/replay stages
            # a pod's row more than once within one call; only the final row
            # describes the committed outcome
            owner: Dict[str, Tuple[int, int]] = {}
            for bi, b in enumerate(run.batches):
                for ri, key in enumerate(b.pods):
                    owner[key] = (bi, ri)
            for bi, b in enumerate(run.batches):
                keep = [ri for ri, key in enumerate(b.pods)
                        if owner.get(key) == (bi, ri)]
                bid = self._next_batch
                self._next_batch += 1
                rec = {
                    "kind": "batch", "id": bid, "call": b.call,
                    "cfg": b.cfg or cfg_digest,
                    "backend_path": list(backend_path),
                    "nodes": self._nodes_id(b.nodes_names),
                    "n_nodes": len(b.nodes_names),
                    "segments": b.segments,
                    "pods": [b.pods[ri] for ri in keep],
                    "result": [b.result[ri] for ri in keep],
                    "node": [b.node[ri] for ri in keep],
                    "seg": [b.seg[ri] for ri in keep],
                    "set": [sid_of.get(b.set_ids[ri], -1) if b.set_ids[ri] >= 0
                            else -1 for ri in keep],
                    "group": [b.groups[ri] for ri in keep],
                    "reasons": {str(new_ri): b.reasons[ri]
                                for new_ri, ri in enumerate(keep)
                                if ri in b.reasons},
                }
                self._write_locked(rec)
                obs.XRAY_RECORDS.labels(kind="batch").inc()
                obs.XRAY_RECORDS.labels(kind="pod").inc(len(keep))
                self._pod_rows += len(keep)
                self._unscheduled_rows += rec["result"].count(UNSCHEDULABLE)
                self._pending.append(("batch", rec))
            for p in run.preempts:
                p = dict(p, backend_path=list(backend_path))
                self._write_locked(p)
                obs.XRAY_RECORDS.labels(kind="preempt").inc()
                self._pending.append(("preempt", p))
            for p in run.probes:
                p = dict(p, backend_path=list(backend_path))
                self._write_locked(p)
                obs.XRAY_RECORDS.labels(kind="probe").inc()
            if len(self._pending) >= self._PENDING_FLUSH:
                self._reindex_locked()
            f = self._f
            if f is not None:
                f.flush()

    def _reindex_locked(self) -> None:
        """Fold queued batch/preempt records into the explain index (caller
        holds the lock). Replayed in commit order so preempt overrides land
        after the rows they amend."""
        if not self._pending:
            return
        pending, self._pending = self._pending, []
        for kind, rec in pending:
            if kind == "batch":
                _index_batch_into(self._index, self._unscheduled, rec)
            else:
                _apply_preempt(self._index, self._unscheduled, rec)
        # bound the in-memory index (the JSONL keeps everything)
        over = len(self._index) - self.max_pods_mem
        if over > 0:
            for key in list(self._index)[:over]:
                self._index.pop(key, None)
                self._unscheduled.pop(key, None)
            obs.XRAY_DROPPED.labels(kind="pod_index").inc(over)

    # ------------------------------------------------------------- queries --

    def explain(self, pod: str) -> Optional[dict]:
        """Resolved decision record for a pod key ('ns/name', or bare name
        matched across namespaces), or None."""
        with self._lock:
            self._reindex_locked()
            return _resolve(self._index, self._sets, self._nodes,
                            self._arrays, pod)

    def unscheduled_summary(self, limit: int = 256) -> List[dict]:
        with self._lock:
            self._reindex_locked()
            items = list(self._unscheduled.items())[-limit:]
        return [{"pod": k, "reason": r} for k, r in items]

    def counts(self) -> dict:
        # cheap by design (no reindex): _pod_rows/_unscheduled_rows track raw
        # committed rows; the indexed views refine them on first query
        with self._lock:
            return {
                "pods": self._pod_rows,
                "unscheduled": self._unscheduled_rows,
                "sets": len(self._sets),
                "dropped_sets": self._dropped_sets,
                "batches": self._next_batch,
                "path": self.path,
            }

    def close(self) -> None:
        """Flush + close the JSONL and write the npz sidecar."""
        with self._lock:
            if self.closed:
                return
            self.closed = True
            if self._f is not None:
                self._f.close()
                self._f = None
            if self.path and self._arrays:
                np.savez_compressed(self.path + ".npz", **self._arrays)


# ------------------------------------------------------------- shared resolve --


def _component_order() -> Tuple[str, ...]:
    return COMPONENT_NAMES


def _index_batch_into(index: Dict[str, dict], unscheduled: Dict[str, str],
                      rec: dict) -> None:
    segments = rec.get("segments") or []
    for ri, key in enumerate(rec.get("pods") or []):
        seg_i = rec["seg"][ri]
        seg = segments[seg_i] if 0 <= seg_i < len(segments) else None
        result = rec["result"][ri]
        row = {
            "pod": key, "result": result, "batch": rec["id"],
            "call": rec.get("call", "schedule"),
            "node": rec["node"][ri], "seg": seg_i, "segment": seg,
            "set": rec["set"][ri], "group": rec["group"][ri],
            "nodes": rec.get("nodes", -1),
            "n_nodes": rec.get("n_nodes", 0),
            "backend_path": rec.get("backend_path") or [],
            "reason": (rec.get("reasons") or {}).get(str(ri)),
        }
        index[key] = row
        if result == UNSCHEDULABLE and row["reason"]:
            unscheduled[key] = row["reason"]
        else:
            unscheduled.pop(key, None)


def _apply_preempt(index: Dict[str, dict], unscheduled: Dict[str, str],
                   rec: dict) -> None:
    key = rec["pod"]
    row = index.get(key)
    if row is None:
        row = index[key] = {"pod": key, "result": UNSCHEDULABLE, "node": -1,
                            "set": -1, "seg": -1, "segment": None,
                            "group": -1, "batch": -1, "nodes": -1,
                            "n_nodes": 0, "call": "schedule",
                            "backend_path": rec.get("backend_path") or []}
    row["result"] = UNSCHEDULABLE
    row["reason"] = rec.get("reason")
    row["reasons"] = rec.get("reasons")
    if rec.get("nominated"):
        row["nominated_node"] = rec.get("node")
    row["victims"] = rec.get("victims") or []
    if row["reason"]:
        unscheduled[key] = row["reason"]
    for v in rec.get("victims") or []:
        vrow = index.get(v)
        if vrow is not None:
            vrow["result"] = PREEMPTED
            vrow["preempted_by"] = key
            unscheduled.pop(v, None)


def _resolve(index: Dict[str, dict], sets: Dict[int, dict],
             nodes: Dict[int, List[str]], arrays: Dict[str, np.ndarray],
             pod: str) -> Optional[dict]:
    row = index.get(pod)
    if row is None and "/" not in pod:
        # bare name: match across namespaces, unique hit only
        hits = [r for k, r in index.items() if k.split("/", 1)[-1] == pod]
        if len(hits) == 1:
            row = hits[0]
    if row is None:
        return None
    out = dict(row)
    out["result_name"] = RESULT_NAMES.get(row["result"], str(row["result"]))
    names = nodes.get(row.get("nodes", -1)) or []
    ni = row.get("node", -1)
    out["node_name"] = names[ni] if 0 <= ni < len(names) else None
    sid = row.get("set", -1)
    srec = sets.get(sid)
    if srec is not None:
        out["set_record"] = srec
        total = arrays.get(f"s{sid}_total")
        comp = arrays.get(f"s{sid}_comp")
        feas = arrays.get(f"s{sid}_feas")
        if total is not None and 0 <= ni < total.shape[0]:
            # margin vs the best FEASIBLE node: infeasible nodes can carry
            # high raw totals (the chooser masks them to -inf, the stored
            # per-plugin vectors do not), so the chosen node's margin must
            # be measured inside the feasible set it actually won
            if feas is not None:
                fmask = np.unpackbits(feas)[:total.shape[0]].astype(bool)
            else:
                fmask = np.ones(total.shape[0], bool)
            best = float(total[fmask].max()) if fmask.any() else float(total[ni])
            out["node_scores"] = {
                "total": round(float(total[ni]), 4),
                "margin": round(best - float(total[ni]), 4),
                "components": {
                    c: round(float(comp[ci, ni]), 4)
                    for ci, c in enumerate(_component_order())
                } if comp is not None else {},
            }
    return out


# --------------------------------------------------------------- trace files ---


class XrayTrace:
    """A trace loaded back from `<prefix>.jsonl` (+ optional `.npz`): the
    offline query surface behind `simon explain`."""

    def __init__(self) -> None:
        self.header: dict = {}
        self.index: Dict[str, dict] = {}
        self.unscheduled: Dict[str, str] = {}
        self.sets: Dict[int, dict] = {}
        self.nodes: Dict[int, List[str]] = {}
        self.arrays: Dict[str, np.ndarray] = {}
        self.probes: List[dict] = []

    @classmethod
    def load(cls, prefix: str) -> "XrayTrace":
        """Load a trace by prefix (accepts the .jsonl path too)."""
        if prefix.endswith(".jsonl"):
            prefix = prefix[:-len(".jsonl")]
        tr = cls()
        with open(prefix + ".jsonl", encoding="utf-8") as f:
            first = True
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                kind = rec.get("kind")
                if first:
                    if kind != "header" or rec.get("xray") != VERSION:
                        raise ValueError(
                            f"{prefix}.jsonl is not a simonxray v{VERSION} "
                            "trace")
                    tr.header = rec
                    first = False
                    continue
                if kind == "nodes":
                    tr.nodes[rec["id"]] = rec["names"]
                elif kind == "set":
                    tr.sets[rec["id"]] = rec
                elif kind == "batch":
                    _index_batch_into(tr.index, tr.unscheduled, rec)
                elif kind == "preempt":
                    _apply_preempt(tr.index, tr.unscheduled, rec)
                elif kind == "probe":
                    tr.probes.append(rec)
            if first:
                raise ValueError(f"{prefix}.jsonl is empty")
        npz = prefix + ".npz"
        if os.path.exists(npz):
            with np.load(npz) as z:
                tr.arrays = {k: z[k] for k in z.files}
        return tr

    def explain(self, pod: str) -> Optional[dict]:
        return _resolve(self.index, self.sets, self.nodes, self.arrays, pod)

    def unscheduled_summary(self) -> List[dict]:
        return [{"pod": k, "reason": r} for k, r in self.unscheduled.items()]


# ---------------------------------------------------------------- rendering ----


def render_explanation(exp: dict) -> str:
    """Human rendering of a resolved decision record, leading with the
    kube-scheduler-parity event line (PARITY.md "Event parity")."""
    lines = [f"pod: {exp['pod']}"]
    seg = exp.get("segment") or {}
    attrib = []
    if exp.get("batch", -1) >= 0:
        attrib.append(f"batch {exp['batch']}")
    if seg:
        s = f"segment {exp.get('seg')} [{seg.get('kind')}]"
        st = seg.get("stats")
        if st:
            s += (f" epochs={st.get('epochs')} rounds={st.get('rounds')}"
                  f" head_fallbacks={st.get('head_fallbacks')}")
        attrib.append(s)
    if exp.get("group", -1) >= 0:
        attrib.append(f"group {exp['group']}")
    bp = exp.get("backend_path") or []
    if bp:
        attrib.append("backend_path=" + "->".join(bp))
    result = exp.get("result_name", "?")
    lines.append(f"result: {result}"
                 + (f" ({', '.join(attrib)})" if attrib else ""))
    if result == "scheduled":
        # kube event: reason=Scheduled, message as emitted by the binder
        lines.append(f"event: Scheduled: Successfully assigned "
                     f"{exp['pod']} to {exp.get('node_name')}")
    elif result == "preempted":
        lines.append(f"event: Preempted: pod evicted by "
                     f"{exp.get('preempted_by')} (preemption victim)")
    elif result == "bound":
        lines.append(f"event: Scheduled: pod was pre-bound to "
                     f"{exp.get('node_name')} (no scheduling cycle)")
    elif result == "homeless":
        lines.append("event: pod bound to a node this cluster does not know "
                     "(dropped from reports, reference parity)")
    else:
        reason = exp.get("reason") or ""
        # the engine reason string is "failed to schedule pod (ns/name):
        # Unschedulable: 0/N nodes are available: ..."; the event form is the
        # kube FailedScheduling message after the status reason
        msg = reason.split(": ", 2)[-1] if reason else "no record"
        lines.append(f"event: FailedScheduling: {msg}")
        if exp.get("nominated_node"):
            lines.append(f"nominated node: {exp['nominated_node']} "
                         f"(victims evicted; pod recorded unschedulable with "
                         f"status.nominatedNodeName, reference parity)")
        if exp.get("victims"):
            lines.append("preemption victims: " + ", ".join(exp["victims"]))
    ns = exp.get("node_scores")
    if ns:
        comps = " ".join(f"{k}={v:g}" for k, v in ns["components"].items()
                         if v)
        lines.append(f"node score ({exp.get('node_name')}): "
                     f"total={ns['total']:g} margin_to_best={ns['margin']:g}"
                     + (f"  [{comps}]" if comps else ""))
    srec = exp.get("set_record")
    if srec:
        rej = srec.get("stage_reject") or {}
        lines.append(f"filter masks (segment start): "
                     f"{srec.get('n_feasible')} feasible node(s)"
                     + ("; per-stage rejections: "
                        + ", ".join(f"{k}={v}" for k, v in rej.items())
                        if rej else ""))
        top = srec.get("topk") or []
        if top:
            lines.append("top candidates (score desc, node asc):")
            for t in top:
                comps = " ".join(f"{k}={v:g}" for k, v in
                                 (t.get("components") or {}).items() if v)
                lines.append(f"  {t['node']}: total={t['total']:g} "
                             f"margin={t['margin']:g}"
                             + (f"  [{comps}]" if comps else ""))
    return "\n".join(lines)


# ------------------------------------------------------------- module gate -----

_RECORDER: Optional[XrayRecorder] = None
_ENV_CHECKED = False
_LOCK = threading.Lock()


def enable(path: Optional[str] = None, **kw) -> XrayRecorder:
    """Activate the process recorder (idempotent when already active)."""
    global _RECORDER, _ENV_CHECKED
    with _LOCK:
        _ENV_CHECKED = True
        if _RECORDER is None or _RECORDER.closed:
            _RECORDER = XrayRecorder(path, **kw)
        return _RECORDER


def disable() -> None:
    """Close and detach the process recorder (tests / end of CLI run)."""
    global _RECORDER, _ENV_CHECKED
    with _LOCK:
        rec = _RECORDER
        _RECORDER = None
        _ENV_CHECKED = False
    if rec is not None:
        rec.close()


def active() -> Optional[XrayRecorder]:
    """The live recorder, auto-created from OPEN_SIMULATOR_XRAY=1 /
    OPEN_SIMULATOR_XRAY_OUT on first use. None when recording is off — the
    engine's whole obligation when off is this one None-check."""
    global _RECORDER, _ENV_CHECKED
    if _RECORDER is not None:
        return _RECORDER
    # simonlint: ignore[race-unguarded-attr] -- double-checked init: _ENV_CHECKED
    # is set under _LOCK before any recorder publish, and a stale False only
    # routes this reader through the locked slow path once more
    if _ENV_CHECKED:
        return None
    with _LOCK:
        if not _ENV_CHECKED:
            _ENV_CHECKED = True
            if os.environ.get("OPEN_SIMULATOR_XRAY", "") not in (
                    "", "0", "false", "no"):
                _RECORDER = XrayRecorder(
                    os.environ.get("OPEN_SIMULATOR_XRAY_OUT") or None)
    # simonlint: ignore[race-unguarded-attr] -- reference read is GIL-atomic;
    # _RECORDER is published exactly once under _LOCK and never reassigned
    return _RECORDER


def begin_run(call: str) -> Optional[XrayRun]:
    """Fresh staging for one schedule/probe attempt, or None when off."""
    rec = active()
    return XrayRun(rec, call) if rec is not None else None


def commit_run(run: Optional[XrayRun], backend_path: List[str],
               cfg_digest: str = "") -> None:
    if run is not None:
        run.recorder.commit(run, backend_path, cfg_digest)
