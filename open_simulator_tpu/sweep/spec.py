"""simonsweep: sweep-spec parsing and validation.

A sweep spec (YAML/JSON, kind: SweepSpec) names ONE base cluster, ONE shared
baseline workload (an ordered list of pod templates), and N scenario
families. Each family compiles (sweep/families.py) into independent
scenarios — node drains, zone outages, priority-ordered preemption storms,
rollout waves, heterogeneous nodepool mixes, seeded Monte-Carlo workload
draws — that the runner (sweep/runner.py) batches onto the scenario axis of
the sweep fan-out kernels.

Determinism contract: everything random derives from the spec's `seed`
through explicit numpy SeedSequence keys (seed, family_index,
scenario_index) — no wall clock, no ambient entropy — so `simon sweep
--seed K` twice produces byte-identical report JSON (tests/test_sweep.py
asserts it).

Probe semantics: scenarios are what-if probes (like serve/), so pod
templates may NOT set spec.priority — mixed priorities would arm the serial
oracle's DefaultPreemption PostFilter, which probe lanes deliberately do not
run. The preemption_storm family models preemption pressure by
priority-ORDERED admission instead (storm pods first, the order the
reference's priority queue produces); see PARITY.md "Sweep fuzzing".
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, NamedTuple, Optional, Sequence, Tuple

SCHEMA = 1

FAMILY_KINDS = ("zone_outage", "node_drain", "preemption_storm",
                "rollout_wave", "nodepool_mix", "monte_carlo")


class SweepSpecError(ValueError):
    """A malformed sweep spec — always raised with the offending field."""


class PodTemplate(NamedTuple):
    """One workload template: `replicas` identical pods, contiguous in the
    batch (the shape real apps produce, and what the wave lane fuses)."""

    name: str
    replicas: int
    cpu: str = "500m"
    memory: str = "512Mi"
    labels: Tuple[Tuple[str, str], ...] = ()
    anti_affinity_on: str = ""   # required anti-affinity vs app=<value>
    affinity_on: str = ""        # required co-location affinity vs app=<value>
    tier: str = "baseline"       # baseline | storm | rollout (report tiers)


class SyntheticBase(NamedTuple):
    nodes: int
    zones: int = 0
    cpu: str = "8"
    memory: str = "16Gi"
    pods: str = "110"
    bound: int = 0               # bound pods committed round-robin
    bound_cpu: str = "500m"
    bound_memory: str = "512Mi"


class BaseSpec(NamedTuple):
    """Either a synthetic cluster or a path of YAML Node (+ bound Pod)
    objects; exactly one of the two is set."""

    synthetic: Optional[SyntheticBase] = None
    cluster: str = ""


class FamilySpec(NamedTuple):
    kind: str
    options: Tuple[Tuple[str, object], ...]  # normalized, hashable

    def opt(self, key: str, default=None):
        for k, v in self.options:
            if k == key:
                return v
        return default


class SweepSpec(NamedTuple):
    name: str
    seed: int
    base: BaseSpec
    workload: Tuple[PodTemplate, ...]
    families: Tuple[FamilySpec, ...]

    def digest(self) -> str:
        """Stable identity of the spec (pre-seed-override): what the report
        records so two runs are comparable only when the spec matched."""
        payload = json.dumps(_normalize(self), sort_keys=True)
        return hashlib.sha256(payload.encode()).hexdigest()[:16]


def _normalize(spec: SweepSpec):
    return {
        "schema": SCHEMA,
        "name": spec.name,
        "seed": spec.seed,
        "base": (spec.base.synthetic._asdict() if spec.base.synthetic
                 else {"cluster": spec.base.cluster}),
        "workload": [t._asdict() for t in spec.workload],
        "families": [{"kind": f.kind, "options": list(f.options)}
                     for f in spec.families],
    }


# ------------------------------------------------------------------ parsing ---


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise SweepSpecError(msg)


def _as_int(doc: dict, key: str, default=None, minimum=0) -> int:
    v = doc.get(key, default)
    _require(v is not None, f"missing required field '{key}'")
    _require(isinstance(v, int) and not isinstance(v, bool) and v >= minimum,
             f"'{key}' must be an integer >= {minimum} (got {v!r})")
    return v


def _as_str(doc: dict, key: str, default=None) -> str:
    v = doc.get(key, default)
    _require(v is not None, f"missing required field '{key}'")
    return str(v)


def _as_int_list(doc: dict, key: str, minimum=0) -> Tuple[int, ...]:
    v = doc.get(key)
    _require(isinstance(v, (list, tuple)) and v,
             f"'{key}' must be a non-empty list of integers")
    out = []
    for x in v:
        _require(isinstance(x, int) and not isinstance(x, bool)
                 and x >= minimum,
                 f"'{key}' entries must be integers >= {minimum} (got {x!r})")
        out.append(x)
    return tuple(out)


def _parse_template(doc: dict, tier: str = "baseline") -> PodTemplate:
    _require(isinstance(doc, dict), f"workload template must be a mapping "
                                    f"(got {type(doc).__name__})")
    _require("priority" not in doc and "priorityClassName" not in doc,
             "pod templates may not set a priority: sweep lanes are what-if "
             "probes (no PostFilter preemption); the preemption_storm family "
             "models priority by admission ORDER instead")
    name = _as_str(doc, "name")
    labels = doc.get("labels") or {}
    _require(isinstance(labels, dict), "'labels' must be a mapping")
    return PodTemplate(
        name=name,
        replicas=_as_int(doc, "replicas", minimum=0),
        cpu=_as_str(doc, "cpu", "500m"),
        memory=_as_str(doc, "memory", "512Mi"),
        labels=tuple(sorted((str(k), str(v)) for k, v in labels.items())),
        anti_affinity_on=str(doc.get("antiAffinityOn", "") or ""),
        affinity_on=str(doc.get("affinityOn", "") or ""),
        tier=tier,
    )


def _parse_base(doc: dict) -> BaseSpec:
    _require(isinstance(doc, dict) and doc, "spec.base must be a mapping with "
                                            "'synthetic' or 'cluster'")
    syn, cluster = doc.get("synthetic"), doc.get("cluster", "")
    _require(bool(syn) != bool(cluster),
             "spec.base needs exactly one of 'synthetic' or 'cluster'")
    if cluster:
        return BaseSpec(cluster=str(cluster))
    _require(isinstance(syn, dict), "'synthetic' must be a mapping")
    return BaseSpec(synthetic=SyntheticBase(
        nodes=_as_int(syn, "nodes", minimum=1),
        zones=_as_int(syn, "zones", 0),
        cpu=_as_str(syn, "cpu", "8"),
        memory=_as_str(syn, "memory", "16Gi"),
        pods=_as_str(syn, "pods", "110"),
        bound=_as_int(syn, "bound", 0),
        bound_cpu=_as_str(syn, "boundCpu", "500m"),
        bound_memory=_as_str(syn, "boundMemory", "512Mi"),
    ))


def _parse_family(doc: dict, workload: Sequence[PodTemplate]) -> FamilySpec:
    _require(isinstance(doc, dict), "family must be a mapping")
    kind = _as_str(doc, "kind")
    _require(kind in FAMILY_KINDS,
             f"unknown family kind {kind!r} (known: {', '.join(FAMILY_KINDS)})")
    opts: Dict[str, object] = {}
    if kind == "zone_outage":
        zones = doc.get("zones", "all")
        if zones != "all":
            _require(isinstance(zones, (list, tuple)) and zones,
                     "'zones' must be 'all' or a non-empty list of zone names")
            zones = tuple(str(z) for z in zones)
        width = _as_int(doc, "width", 1, minimum=1)
        _require(width <= 2, "'width' must be 1 (single zones) or 2 (pairs)")
        opts = {"zones": zones, "width": width}
    elif kind == "node_drain":
        opts = {"counts": _as_int_list(doc, "counts", minimum=1),
                "draws": _as_int(doc, "draws", 1, minimum=1)}
    elif kind == "preemption_storm":
        opts = {"storms": _as_int_list(doc, "storms", minimum=1),
                "cpu": _as_str(doc, "cpu", "1"),
                "memory": _as_str(doc, "memory", "1Gi")}
    elif kind == "rollout_wave":
        target = _as_str(doc, "workload")
        _require(any(t.name == target for t in workload),
                 f"rollout_wave targets unknown workload {target!r}")
        steps = _as_int_list(doc, "steps", minimum=0)
        _require(all(s <= 100 for s in steps),
                 "'steps' are percentages (0-100)")
        opts = {"workload": target, "steps": steps,
                "cpu": _as_str(doc, "cpu", "750m"),
                "memory": _as_str(doc, "memory", "768Mi")}
    elif kind == "nodepool_mix":
        opts = {"counts": _as_int_list(doc, "counts", minimum=1),
                "cpu": _as_str(doc, "cpu", "16"),
                "memory": _as_str(doc, "memory", "32Gi"),
                "pods": _as_str(doc, "pods", "110")}
    elif kind == "monte_carlo":
        raw = doc.get("templates")
        _require(isinstance(raw, (list, tuple)) and raw,
                 "'templates' must be a non-empty list")
        templates = []
        for t in raw:
            _require(isinstance(t, dict),
                     f"monte_carlo 'templates' entries must be mappings "
                     f"(got {type(t).__name__})")
            rng = t.get("replicas")
            _require(isinstance(rng, (list, tuple)) and len(rng) == 2
                     and all(isinstance(x, int) for x in rng)
                     and 0 <= rng[0] <= rng[1],
                     "monte_carlo template 'replicas' must be [lo, hi]")
            base = _parse_template({**t, "replicas": 0})
            templates.append((base, int(rng[0]), int(rng[1])))
        opts = {"draws": _as_int(doc, "draws", 1, minimum=1),
                "templates": tuple(templates)}
    return FamilySpec(kind=kind, options=tuple(sorted(opts.items())))


def parse_spec(doc: dict) -> SweepSpec:
    _require(isinstance(doc, dict), "sweep spec must be a mapping")
    kind = doc.get("kind", "SweepSpec")
    _require(kind == "SweepSpec", f"kind must be SweepSpec (got {kind!r})")
    spec = doc.get("spec") or {}
    _require(isinstance(spec, dict) and spec, "missing 'spec' body")
    name = ((doc.get("metadata") or {}).get("name")
            or spec.get("name") or "sweep")
    workload_raw = spec.get("workload")
    _require(isinstance(workload_raw, (list, tuple)) and workload_raw,
             "spec.workload must be a non-empty list of pod templates")
    workload = tuple(_parse_template(t) for t in workload_raw)
    names = [t.name for t in workload]
    _require(len(set(names)) == len(names),
             f"duplicate workload template names: {names}")
    fams_raw = spec.get("families")
    _require(isinstance(fams_raw, (list, tuple)) and fams_raw,
             "spec.families must be a non-empty list")
    return SweepSpec(
        name=str(name),
        seed=_as_int(spec, "seed", 0),
        base=_parse_base(spec.get("base") or {}),
        workload=workload,
        families=tuple(_parse_family(f, workload) for f in fams_raw),
    )


def load_spec(path: str) -> SweepSpec:
    """Parse a sweep spec from a YAML or JSON file."""
    if not os.path.isfile(path):
        raise SweepSpecError(f"no such sweep spec file: {path}")
    with open(path, "r", encoding="utf-8") as fh:
        text = fh.read()
    import yaml

    try:
        doc = (json.loads(text) if path.endswith(".json")
               else yaml.safe_load(text))
    except (ValueError, yaml.YAMLError) as e:
        # json.JSONDecodeError is a ValueError; the CLI handles
        # SweepSpecError, so a syntax typo prints one line, not a traceback
        raise SweepSpecError(f"{path}: unparseable spec: {e}") from None
    try:
        return parse_spec(doc)
    except SweepSpecError as e:
        raise SweepSpecError(f"{path}: {e}") from None
