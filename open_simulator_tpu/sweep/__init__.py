"""simonsweep: batched scenario sweeps — Monte-Carlo what-if fleets on the
scenario axis.

The reference's planner answers one question per run (apply.go:203-259);
this subsystem answers hundreds in one dispatch: a sweep spec (spec.py)
compiles scenario families (families.py) into copy-on-write overlays on one
shared device-resident cluster image, the runner (runner.py) batches them
onto the sweep_*_fanout kernels, and every batched lane doubles as a parity
fuzz case against a fresh serial Simulator run (PARITY.md "Sweep fuzzing").

    from open_simulator_tpu.sweep import SweepRunner, load_spec, build_report
    runner = SweepRunner(load_spec("examples/sweeps/zone-outage.yaml"))
    results = runner.run()            # raises on any parity mismatch
    report = build_report(runner)     # deterministic JSON-able dict
"""

from .families import Scenario, build_base, compile_families
from .report import build_report, render_report, report_json
from .runner import ScenarioResult, SweepParityError, SweepRunner
from .spec import SweepSpec, SweepSpecError, load_spec, parse_spec

__all__ = [
    "Scenario", "ScenarioResult", "SweepParityError", "SweepRunner",
    "SweepSpec", "SweepSpecError", "build_base", "build_report",
    "compile_families", "load_spec", "parse_spec", "render_report",
    "report_json",
]
