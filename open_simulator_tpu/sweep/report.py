"""simonsweep: the cross-scenario report.

Per-scenario metrics rows plus per-family aggregates — schedulable-fraction
distributions, the nodepool capacity envelope, the preemption-storm victim
histogram — rendered by the CLI and dumped as JSON.

Determinism contract: the report carries NO wall-clock, hostname, or other
ambient state — every field derives from (spec, seed, results), so two runs
of `simon sweep --seed K` produce byte-identical JSON (the regression test's
whole assertion). Timings go to the CLI's stderr, never in here.
"""

from __future__ import annotations

import json
from statistics import median
from typing import Dict, List

from .families import zones_of
from .runner import ScenarioResult, SweepRunner

SCHEMA = 1


def _frac(scheduled: int, total: int) -> float:
    return round(scheduled / total, 6) if total else 1.0


def _dist(values: List[float]) -> Dict[str, float]:
    return {"min": min(values), "p50": round(median(values), 6),
            "max": max(values)}


def _scenario_row(res: ScenarioResult) -> dict:
    sc = res.scenario
    return {
        "id": sc.sid,
        "family": sc.family,
        "label": sc.label,
        "key": list(sc.key),
        "route": res.route,
        **({"gate": res.gate} if res.gate else {}),
        "pods": res.total,
        "scheduled": res.scheduled,
        "unscheduled": res.total - res.scheduled,
        "fraction": _frac(res.scheduled, res.total),
        "nodes": res.nodes_live,
        "drains": len(sc.drains),
        "activates": len(sc.activates),
        "tiers": {k: res.tiers[k] for k in sorted(res.tiers)},
        "utilization": res.utilization,
        "meta": {k: v for k, v in sc.meta},
    }


def _victims(res: ScenarioResult, baseline: ScenarioResult) -> int:
    """The storm's displaced-baseline count: baseline-tier pods that
    scheduled in the anchor lane but not under the storm — the set
    DefaultPreemption would evict on a capacity-bound cluster, modeled by
    priority-ordered admission (PARITY.md "Sweep fuzzing")."""
    return max(0, baseline.tiers.get("baseline", 0)
               - res.tiers.get("baseline", 0))


def _victim_bucket(v: int) -> str:
    if v == 0:
        return "0"
    if v < 10:
        return "1-9"
    if v < 50:
        return "10-49"
    return "50+"


def _family_summary(family: str, rows: List[dict],
                    results: List[ScenarioResult],
                    baseline: ScenarioResult) -> dict:
    out: dict = {
        "scenarios": len(rows),
        "fraction": _dist([r["fraction"] for r in rows]),
        "scheduled": _dist([float(r["scheduled"]) for r in rows]),
    }
    if family == "preemption_storm":
        victims = [_victims(res, baseline) for res in results]
        hist: Dict[str, int] = {}
        for v in victims:
            hist[_victim_bucket(v)] = hist.get(_victim_bucket(v), 0) + 1
        out["victims"] = {
            "per_scenario": [
                {"label": res.scenario.label, "storm": res.scenario
                 .meta_dict().get("storm"), "victims": v}
                for res, v in zip(results, victims)],
            "hist": {k: hist[k] for k in sorted(hist)},
            "max": max(victims) if victims else 0,
        }
    if family == "nodepool_mix":
        env = sorted(
            ({"pool": res.scenario.meta_dict().get("pool"),
              "nodes": res.nodes_live, "scheduled": res.scheduled,
              "fraction": _frac(res.scheduled, res.total)}
             for res in results),
            key=lambda e: e["pool"])
        out["capacity_envelope"] = env
    if family == "zone_outage":
        out["per_zone"] = [
            {"zones": res.scenario.meta_dict().get("zones"),
             "fraction": _frac(res.scheduled, res.total),
             "drained_nodes": len(res.scenario.drains)}
            for res in results]
    return out


def build_report(runner: SweepRunner) -> dict:
    spec = runner.spec
    ordered = [runner.results[sid] for sid in sorted(runner.results)]
    baseline = ordered[0]
    rows = [_scenario_row(res) for res in ordered]
    fam_order: List[str] = []
    by_family: Dict[str, List[int]] = {}
    for i, res in enumerate(ordered):
        fam = res.scenario.family
        if fam not in by_family:
            fam_order.append(fam)
            by_family[fam] = []
        by_family[fam].append(i)
    routes: Dict[str, int] = {}
    for res in ordered:
        routes[res.route] = routes.get(res.route, 0) + 1
    return {
        "kind": "SweepReport",
        "schema": SCHEMA,
        "name": spec.name,
        "seed": runner.seed,
        "spec_digest": spec.digest(),
        "base": {
            "nodes": len(runner._base_nodes),
            "bound_pods": len(runner._bound),
            "pool_nodes": len(runner._pool_nodes),
            "zones": sorted(zones_of(runner._base_nodes)),
            "resident_image": runner.image is not None,
        },
        "lanes": {k: routes[k] for k in sorted(routes)},
        "dispatches": {k: runner.dispatches[k]
                       for k in sorted(runner.dispatches)},
        "parity": {
            "mode": runner.parity,
            "checked": runner.parity_checked,
            "mismatches": 0,   # a mismatch raises before a report exists
        },
        "scenarios": rows,
        "families": {
            fam: _family_summary(fam, [rows[i] for i in by_family[fam]],
                                 [ordered[i] for i in by_family[fam]],
                                 baseline)
            for fam in fam_order
        },
    }


def report_json(report: dict) -> str:
    """THE byte-stable serialization: sorted keys, fixed separators, one
    trailing newline — what --out writes and the determinism test hashes."""
    return json.dumps(report, sort_keys=True, indent=1) + "\n"


def render_report(report: dict) -> str:
    """Human rendering for the CLI: per-family summary lines + the worst
    scenarios by schedulable fraction."""
    lines = [
        f"sweep {report['name']!r}: {len(report['scenarios'])} scenarios, "
        f"seed {report['seed']}, lanes {report['lanes']}, "
        f"dispatches {report['dispatches'] or '(none batched)'}",
        f"  base: {report['base']['nodes']} nodes"
        + (f" / zones {', '.join(report['base']['zones'])}"
           if report['base']['zones'] else "")
        + (f" / {report['base']['bound_pods']} bound pods"
           if report['base']['bound_pods'] else "")
        + (f" / {report['base']['pool_nodes']} pool nodes"
           if report['base']['pool_nodes'] else ""),
        f"  parity: {report['parity']['mode']} "
        f"({report['parity']['checked']} lanes re-run serially, "
        f"{report['parity']['mismatches']} mismatches)",
    ]
    for fam, summary in report["families"].items():
        fr = summary["fraction"]
        lines.append(
            f"  {fam:<18} {summary['scenarios']:>3} scenario(s)  "
            f"schedulable {fr['min']:.3f} / {fr['p50']:.3f} / "
            f"{fr['max']:.3f} (min/p50/max)")
        if "victims" in summary:
            lines.append(f"    victims: max {summary['victims']['max']}, "
                         f"hist {summary['victims']['hist']}")
        if "capacity_envelope" in summary:
            env = " -> ".join(
                f"+{e['pool']}:{e['scheduled']}"
                for e in summary["capacity_envelope"])
            lines.append(f"    capacity envelope (pool:scheduled): {env}")
    worst = sorted(report["scenarios"], key=lambda r: r["fraction"])[:5]
    lines.append("  tightest scenarios:")
    for r in worst:
        lines.append(
            f"    [{r['id']:>3}] {r['label']:<24} {r['scheduled']}/"
            f"{r['pods']} scheduled ({r['fraction']:.3f}) on {r['nodes']} "
            f"nodes via {r['route']}")
    return "\n".join(lines)
