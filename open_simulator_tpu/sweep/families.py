"""simonsweep: scenario-family compilers.

Each family compiles into a list of `Scenario`s — pure data: the scenario's
pod batch (ordered, contiguous per template), the node names it drains, the
pool nodes it activates, and its explicit PRNG key. Everything random draws
from numpy SeedSequence entropy (seed, family_index, scenario_index); the
SAME spec + seed always compiles the SAME scenarios, byte for byte.

The runner never re-derives any of this: a Scenario IS the overlay — the
copy-on-write machinery (serve/image.py lane_overlay) turns it into one
active-mask row + seed copy on the shared device-resident image.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from .spec import PodTemplate, SweepSpec, SweepSpecError

ZONE_LABEL = "topology.kubernetes.io/zone"
TIER_LABEL = "simon.sweep/tier"
POOL_PREFIX = "sweep-pool-"


class Scenario(NamedTuple):
    """One independent cluster future: what changes vs the base cluster."""

    sid: int                 # report id, dense from 0 (0 = baseline)
    family: str
    label: str
    key: Tuple[int, int, int]          # (seed, family_index, scenario_index)
    pods: List[dict]                   # the scenario's what-if workload
    drains: Tuple[str, ...] = ()       # node names removed (with their pods)
    activates: Tuple[str, ...] = ()    # pool node names added
    meta: Tuple[Tuple[str, object], ...] = ()

    def meta_dict(self) -> Dict[str, object]:
        return dict(self.meta)


# ------------------------------------------------------------ pod building ---


def build_pod(name: str, tmpl: PodTemplate) -> dict:
    labels = {"app": tmpl.name, TIER_LABEL: tmpl.tier, **dict(tmpl.labels)}
    spec: dict = {
        "containers": [{
            "name": "main",
            "image": "simon-sweep",
            "resources": {"requests": {"cpu": tmpl.cpu,
                                       "memory": tmpl.memory}},
        }]
    }
    affinity = {}
    if tmpl.anti_affinity_on:
        affinity["podAntiAffinity"] = {
            "requiredDuringSchedulingIgnoredDuringExecution": [{
                "labelSelector": {
                    "matchLabels": {"app": tmpl.anti_affinity_on}},
                "topologyKey": "kubernetes.io/hostname",
            }]}
    if tmpl.affinity_on:
        # self-matching required affinity routes OFF the plain wave (the
        # engine's affinity route) — the sweep then rides the exact
        # per-lane serial-scan lane (sweep_whatif_fanout)
        affinity["podAffinity"] = {
            "requiredDuringSchedulingIgnoredDuringExecution": [{
                "labelSelector": {
                    "matchLabels": {"app": tmpl.affinity_on}},
                "topologyKey": "kubernetes.io/hostname",
            }]}
    if affinity:
        spec["affinity"] = affinity
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default", "labels": labels},
        "spec": spec,
    }


def build_workload(templates: Sequence[PodTemplate],
                   _cache: Optional[dict] = None):
    """The ordered pod batch for one scenario as a columnar PodStore
    (simulator/store.py): one template block per PodTemplate, each block's
    replicas contiguous (one wave segment each), names unique within the
    scenario (block-local numbering) so the serial oracle's census filters
    on them. Scenarios with an IDENTICAL template list share one store
    (`_cache`) — at 256 scenarios x 10k pods the drain/outage grid would
    otherwise hold millions of identical dicts — and the store's lane
    encode is one gather per template instead of a dict hit per pod.
    Consumers that read pods back (the scan-lane census, the serial
    oracle's deepcopy) materialize lazily through the Sequence protocol,
    exactly the dicts the old list held."""
    key = tuple(templates)
    if _cache is not None and key in _cache:
        return _cache[key]
    from ..simulator.store import PodStore

    store = PodStore()
    for tmpl in templates:
        proto = build_pod("sw-proto", tmpl)
        proto["metadata"].pop("name", None)
        store.add_block(proto, tmpl.replicas,
                        name_fmt=f"sw-{tmpl.name}-{{0:05d}}", name_start=0)
    if _cache is not None:
        _cache[key] = store
    return store


# ----------------------------------------------------------- base building ---


def build_node(name: str, cpu: str, memory: str, pods: str,
               zone: str = "", extra_labels: Optional[dict] = None) -> dict:
    labels = {"kubernetes.io/hostname": name, **(extra_labels or {})}
    if zone:
        labels[ZONE_LABEL] = zone
    alloc = {"cpu": cpu, "memory": memory, "pods": pods}
    return {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {"name": name, "labels": labels},
        "spec": {},
        "status": {"allocatable": dict(alloc), "capacity": dict(alloc)},
    }


def build_base(spec: SweepSpec) -> Tuple[List[dict], List[dict]]:
    """(nodes, bound_pods) for the spec's base cluster."""
    if spec.base.cluster:
        return _load_cluster(spec.base.cluster)
    syn = spec.base.synthetic
    assert syn is not None
    nodes = [build_node(
        f"sweep-node-{i:05d}", syn.cpu, syn.memory, syn.pods,
        zone=(f"zone-{i % syn.zones}" if syn.zones else ""))
        for i in range(syn.nodes)]
    bound = []
    for i in range(syn.bound):
        tmpl = PodTemplate(name="bound", replicas=0, cpu=syn.bound_cpu,
                           memory=syn.bound_memory, tier="bound")
        pod = build_pod(f"sweep-bound-{i:05d}", tmpl)
        pod["spec"]["nodeName"] = nodes[i % len(nodes)]["metadata"]["name"]
        bound.append(pod)
    return nodes, bound


def _load_cluster(path: str) -> Tuple[List[dict], List[dict]]:
    """Nodes + bound pods from a YAML file or directory (kind: Node / Pod;
    a pod without spec.nodeName in cluster files is rejected — the base
    cluster is committed state, workloads belong in spec.workload)."""
    import os

    from ..utils.yamlio import decode_yaml_content, read_yaml_files

    if os.path.isdir(path):
        contents = read_yaml_files(path)
    elif os.path.isfile(path):
        with open(path, "r", encoding="utf-8") as fh:
            contents = [fh.read()]
    else:
        raise SweepSpecError(f"base.cluster path not found: {path}")
    nodes: List[dict] = []
    bound: List[dict] = []
    for obj in decode_yaml_content(contents):
        kind = obj.get("kind", "")
        if kind == "Node":
            nodes.append(obj)
        elif kind == "Pod":
            if not (obj.get("spec") or {}).get("nodeName"):
                raise SweepSpecError(
                    f"base.cluster pod "
                    f"{(obj.get('metadata') or {}).get('name')!r} has no "
                    f"spec.nodeName; unbound workloads belong in "
                    f"spec.workload")
            bound.append(obj)
    if not nodes:
        raise SweepSpecError(f"base.cluster {path} contains no Node objects")
    return nodes, bound


def zones_of(nodes: Sequence[dict]) -> Dict[str, List[str]]:
    """zone name -> node names, in node order (insertion-ordered)."""
    out: Dict[str, List[str]] = {}
    for n in nodes:
        zone = ((n.get("metadata") or {}).get("labels") or {}).get(ZONE_LABEL)
        if zone:
            out.setdefault(zone, []).append(
                (n.get("metadata") or {}).get("name", ""))
    return out


# ------------------------------------------------------------- compilation ---


def _rng(key: Tuple[int, int, int]) -> np.random.Generator:
    """The ONLY entropy source in the sweep path: an explicit SeedSequence
    key. No wall clock, no global numpy state."""
    return np.random.default_rng(np.random.SeedSequence(entropy=list(key)))


class CompiledSweep(NamedTuple):
    scenarios: List[Scenario]
    pool_nodes: List[dict]   # union nodepool, pre-encoded into the image


def compile_families(spec: SweepSpec, seed: int,
                     base_nodes: Sequence[dict]) -> CompiledSweep:
    """Every scenario of every family, plus the union pool-node list. The
    baseline scenario (the unmodified shared workload) is always sid 0 —
    the anchor lane storm-victim counts and capacity envelopes compare
    against."""
    node_names = [(n.get("metadata") or {}).get("name", "")
                  for n in base_nodes]
    name_set = set(node_names)
    zone_map = zones_of(base_nodes)
    scenarios: List[Scenario] = []

    wl_cache: Dict[tuple, List[dict]] = {}

    def workload(templates):
        return build_workload(templates, _cache=wl_cache)

    def add(family: str, label: str, key, pods, drains=(), activates=(),
            meta=()):
        scenarios.append(Scenario(
            sid=len(scenarios), family=family, label=label, key=tuple(key),
            pods=pods, drains=tuple(drains), activates=tuple(activates),
            meta=tuple(meta)))

    add("baseline", "baseline", (seed, -1, 0), workload(spec.workload))

    pool_max = 0
    pool_tmpl: Optional[Tuple[str, str, str]] = None
    for fi, fam in enumerate(spec.families):
        if fam.kind == "zone_outage":
            zones = fam.opt("zones")
            zone_names = (sorted(zone_map) if zones == "all"
                          else list(zones))
            for z in zone_names:
                if z not in zone_map:
                    raise SweepSpecError(
                        f"zone_outage names unknown zone {z!r} "
                        f"(cluster zones: {sorted(zone_map) or 'none'})")
            if not zone_names:
                raise SweepSpecError(
                    "zone_outage on a cluster with no "
                    f"{ZONE_LABEL} labels")
            groups = ([(z,) for z in zone_names] if fam.opt("width") == 1
                      else [(a, b) for i, a in enumerate(zone_names)
                            for b in zone_names[i + 1:]])
            if not groups:
                # width=2 with a single zone: refuse loudly — silently
                # compiling zero scenarios would report a grid that never ran
                raise SweepSpecError(
                    f"zone_outage width=2 needs at least 2 zones "
                    f"(cluster has {len(zone_names)}: {zone_names})")
            for si, grp in enumerate(groups):
                drains = [n for z in grp for n in zone_map[z]]
                add("zone_outage", f"outage:{'+'.join(grp)}",
                    (seed, fi, si), workload(spec.workload),
                    drains=drains,
                    meta=(("zones", list(grp)),))
        elif fam.kind == "node_drain":
            si = 0
            for k in fam.opt("counts"):
                if k >= len(node_names):
                    raise SweepSpecError(
                        f"node_drain count {k} >= cluster size "
                        f"{len(node_names)}")
                for _ in range(fam.opt("draws")):
                    key = (seed, fi, si)
                    drains = sorted(_rng(key).choice(
                        np.asarray(node_names, dtype=object), size=k,
                        replace=False).tolist())
                    add("node_drain", f"drain:k={k}#{si}", key,
                        workload(spec.workload),
                        drains=drains, meta=(("k", k),))
                    si += 1
        elif fam.kind == "preemption_storm":
            for si, m in enumerate(fam.opt("storms")):
                storm = PodTemplate(
                    name=f"storm{m}", replicas=m, cpu=fam.opt("cpu"),
                    memory=fam.opt("memory"), tier="storm")
                # priority-ordered admission: storm pods FIRST (the order
                # the reference's priority queue would produce), then the
                # baseline workload — displaced baseline pods are the
                # victim proxy (PARITY.md "Sweep fuzzing")
                add("preemption_storm", f"storm:m={m}", (seed, fi, si),
                    workload((storm,) + spec.workload),
                    meta=(("storm", m),))
        elif fam.kind == "rollout_wave":
            target = fam.opt("workload")
            for si, pct in enumerate(fam.opt("steps")):
                templates: List[PodTemplate] = []
                for t in spec.workload:
                    if t.name != target:
                        templates.append(t)
                        continue
                    moved = (t.replicas * pct) // 100
                    if t.replicas - moved:
                        templates.append(
                            t._replace(replicas=t.replicas - moved))
                    if moved:
                        templates.append(PodTemplate(
                            name=f"{t.name}-v2", replicas=moved,
                            cpu=fam.opt("cpu"), memory=fam.opt("memory"),
                            labels=t.labels, tier="rollout"))
                add("rollout_wave", f"rollout:{target}@{pct}%",
                    (seed, fi, si), workload(tuple(templates)),
                    meta=(("step", pct), ("workload", target)))
        elif fam.kind == "nodepool_mix":
            counts = fam.opt("counts")
            tmpl = (fam.opt("cpu"), fam.opt("memory"), fam.opt("pods"))
            if pool_tmpl is not None and pool_tmpl != tmpl:
                raise SweepSpecError(
                    "multiple nodepool_mix families must share one node "
                    "template (one pre-encoded pool)")
            pool_tmpl = tmpl
            pool_max = max(pool_max, max(counts))
            for si, k in enumerate(counts):
                activates = [f"{POOL_PREFIX}{i:05d}" for i in range(k)]
                add("nodepool_mix", f"pool:k={k}", (seed, fi, si),
                    workload(spec.workload),
                    activates=activates, meta=(("pool", k),))
        elif fam.kind == "monte_carlo":
            for si in range(fam.opt("draws")):
                key = (seed, fi, si)
                rng = _rng(key)
                templates = []
                for base, lo, hi in fam.opt("templates"):
                    templates.append(base._replace(
                        replicas=int(rng.integers(lo, hi + 1))))
                add("monte_carlo", f"mc:#{si}", key,
                    workload(tuple(templates)),
                    meta=(("draw", si),))
    for sc in scenarios:
        for name in sc.drains:
            if name not in name_set:
                raise SweepSpecError(
                    f"scenario {sc.label!r} drains unknown node {name!r}")
    pool_nodes: List[dict] = []
    if pool_max:
        cpu, memory, pods = pool_tmpl
        for i in range(pool_max):
            name = f"{POOL_PREFIX}{i:05d}"
            if name in name_set:
                raise SweepSpecError(
                    f"base cluster already has a node named {name!r} "
                    f"(the nodepool prefix {POOL_PREFIX!r} is reserved)")
            pool_nodes.append(build_node(
                name, cpu, memory, pods,
                extra_labels={"simon.sweep/pool": "true"}))
    return CompiledSweep(scenarios=scenarios, pool_nodes=pool_nodes)
