"""simonsweep: the batched scenario-sweep runner.

N independent cluster futures evaluated as lanes on the scenario axis of one
(or a few bucketed) fan-out dispatches, against ONE shared device-resident
cluster image (serve/image.py):

- **Stage once, overlay per lane.** The base cluster (plus the union
  nodepool, built drained) encodes and device-stages once; every scenario
  becomes a copy-on-write overlay — an active-mask row (drains off, pool
  activations on) and, only when drains evict committed pods, a private seed
  copy (ResidentImage.lane_overlay). Zero per-scenario table bytes.
- **Route like the engine.** A scenario whose batch is entirely contiguous
  runs of wave-eligible groups (the engine's own _wave_eligibility) rides
  sweep_wave_fanout: each lane is a lax.scan CHAIN of schedule_wave segments
  — K fused waves instead of P serial steps, the same fast lane the engine's
  segmented dispatch uses. Anything else batched rides sweep_whatif_fanout
  (per-lane serial scans, exact by construction). Census-dependent workloads
  (topology spread, live SelectorSpread, gpu/storage, pre-bound pods) and
  clusters the image declines run the fresh single-scenario path.
- **Standing parity fuzzer.** Every batched lane (or a seeded sample) is
  re-run on a fresh serial Simulator over that scenario's cluster and the
  per-(node, scheduling-signature) placement censuses must match EXACTLY —
  pods of one group are interchangeable (the engine's own stitching rule),
  so census equality is placement bit-identity. A mismatch raises; it never
  degrades silently (simon_sweep_parity_mismatches_total).

On the 1-core bench host this is a pure work-reduction story: one encode +
one jitted fan-out replaces N full serial simulations' worth of Python
encode/dispatch overhead — not a parallelism story (see BENCH_DETAIL.json
notes). On a real scenario mesh the [S] axis shards one lane per device.
"""

from __future__ import annotations

import contextlib
import copy
import functools
from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from ..obs import instruments as obs
from ..resilience import faults
from ..resilience import guard
from ..simulator.encode import bucket_capped, scheduling_signature
from ..utils.objutil import name_of
from .families import (
    TIER_LABEL,
    Scenario,
    build_base,
    compile_families,
)
from .spec import SweepSpec

_jnp = None


def _jax():
    global _jnp
    if _jnp is None:
        import jax.numpy as jnp

        _jnp = jnp
    return _jnp


PARITY_MODES = ("full", "sample", "off")

# census: {(node_name | "" for unscheduled, scheduling_signature): count}
Census = Dict[Tuple[str, str], int]


class SweepParityError(AssertionError):
    """A batched lane's placement census diverged from the fresh serial
    oracle — the invariant the sweep exists to fuzz. Never swallowed."""


class ScenarioResult(NamedTuple):
    scenario: Scenario
    route: str                   # wave | scan | fresh
    scheduled: int
    total: int
    census: Census
    tiers: Dict[str, int]        # tier -> scheduled count
    utilization: Dict[str, float]
    nodes_live: int
    gate: str = ""               # fresh-route reason, "" on batched routes


class _WaveSeg(NamedTuple):
    g: int
    m: int
    cap1: bool
    start: int                   # offset into scenario.pods
    sig: str
    tier: str


class SweepRunner:
    """One sweep execution: compile -> stage -> route -> batch-dispatch ->
    parity -> report. Build once, run() once."""

    def __init__(self, spec: SweepSpec, seed: Optional[int] = None,
                 parity: str = "full", parity_sample: int = 8,
                 fanout: int = 64, mesh=None) -> None:
        if parity not in PARITY_MODES:
            raise ValueError(f"parity must be one of {PARITY_MODES}")
        self.spec = spec
        self.seed = spec.seed if seed is None else int(seed)
        self.parity = parity
        self.parity_sample = max(1, int(parity_sample))
        self.fanout = max(1, int(fanout))
        self._mesh = mesh
        self.image = None
        self.scenarios: List[Scenario] = []
        self.results: Dict[int, ScenarioResult] = {}
        self.dispatches: Dict[str, int] = {}
        self.parity_checked = 0
        self._base_nodes: List[dict] = []
        self._bound: List[dict] = []
        self._pool_nodes: List[dict] = []

    # --------------------------------------------------------------- run -----

    def run(self) -> Dict[int, ScenarioResult]:
        """Evaluate every scenario; returns {sid: ScenarioResult} (also kept
        on self.results). Raises SweepParityError on any census mismatch."""
        self._base_nodes, self._bound = build_base(self.spec)
        compiled = compile_families(self.spec, self.seed, self._base_nodes)
        self.scenarios = compiled.scenarios
        self._pool_nodes = compiled.pool_nodes
        self._build_image()
        wave: List[Tuple[Scenario, object, List[_WaveSeg]]] = []
        scan: List[Tuple[Scenario, object]] = []
        fresh: List[Tuple[Scenario, str]] = []
        for sc in self.scenarios:
            route = self._route(sc)
            if route[0] == "wave":
                wave.append((sc, route[1], route[2]))
            elif route[0] == "scan":
                scan.append((sc, route[1]))
            else:
                fresh.append((sc, route[1]))
        # Shape-bucketed chunking: lanes sharing one dispatch share its
        # STATIC shapes (K, block, kmax / P_pad), so one storm-sized lane
        # in a chunk would inflate every lane's score table and top-k
        # width. Bucketing by shape keeps the common chunks at their own
        # natural sizes — on the 1-core host this is also the cache story:
        # a [S, N, B] table for a modest S stays resident where one sized
        # for the outlier thrashes.
        from ..obs import scope as scope_mod

        scope_ = scope_mod.active()  # simonscope: sweep chunks become spans
        #          in the same trace buffer the serve path fills — None-check
        #          only when off (a `simon sweep` under a scoped server
        #          shares the perfetto timeline)
        for _, chunk_lanes in sorted(_grouped(wave, self._wave_shape_key)):
            for chunk in _chunks(chunk_lanes, self.fanout):
                with (scope_.span("sweep.wave_chunk", cat="dispatch",
                                  lanes=len(chunk))
                      if scope_ is not None else contextlib.nullcontext()):
                    self._run_contained(chunk, self._dispatch_wave_chunk)
        for _, chunk_lanes in sorted(_grouped(
                scan, lambda item: bucket_capped(
                    max(1, len(item[1].batch)), 2048))):
            for chunk in _chunks(chunk_lanes, self.fanout):
                with (scope_.span("sweep.scan_chunk", cat="dispatch",
                                  lanes=len(chunk))
                      if scope_ is not None else contextlib.nullcontext()):
                    self._run_contained(chunk, self._dispatch_scan_chunk)
        for sc, gate in fresh:
            self._finish(self._serial_result(sc, route="fresh", gate=gate))
        self._check_parity()
        self._xray_results()
        return self.results

    def _build_image(self) -> None:
        from ..serve.image import ResidentImage

        self.image = ResidentImage.try_build(
            self._base_nodes + self._pool_nodes, pods=self._bound,
            mesh=self._mesh)
        if self.image is not None and self._pool_nodes:
            # the union nodepool stages INTO the image but starts drained:
            # each nodepool_mix lane re-activates its k pool columns (zero
            # seed bytes — a fresh pool node holds no pods)
            self.image.apply_events([
                {"type": "node_drain", "name": name_of(n)}
                for n in self._pool_nodes])

    # ----------------------------------------------------------- routing -----

    def _route(self, sc: Scenario):
        """('wave', session, segs) | ('scan', session) | ('fresh', gate)."""
        if self.image is None:
            return ("fresh", "image declined (cluster gate)")
        session = self.image.session(sc.pods, drains=sc.drains)
        gate = self.image.eligible(session.batch, sc.pods)
        if gate is not None:
            return ("fresh", gate)
        segs = self._wave_segments(sc, session.batch)
        if segs is not None:
            return ("wave", session, segs)
        return ("scan", session)

    def _wave_segments(self, sc: Scenario,
                       batch) -> Optional[List[_WaveSeg]]:
        """The scenario's batch as a chain of wave segments — one per
        contiguous (group, unpinned) run, every run wave-eligible by the
        engine's OWN routing — or None (the scan lane is the exact
        fallback, mirroring the engine's serial segments)."""
        sim = self.image._sim
        segs: List[_WaveSeg] = []
        start = 0
        while start < len(batch):
            g, f = batch[start]
            end = start
            while end < len(batch) and batch[end] == (g, f):
                end += 1
            if f >= 0:
                return None
            route = sim._wave_eligibility(g)
            if route.kind != "wave" or route.gpu_live:
                return None
            pod = sc.pods[start]
            segs.append(_WaveSeg(
                g=g, m=end - start, cap1=bool(route.cap1), start=start,
                sig=scheduling_signature(pod),
                tier=pod["metadata"]["labels"].get(TIER_LABEL, "baseline")))
            start = end
        return segs

    def _wave_shape_key(self, item) -> tuple:
        """The static dispatch shape a wave lane compiles under: (K, block,
        kmax). Lanes grouped by this key share one dispatch without any
        lane paying for another's outlier segment sizes."""
        from ..ops import kernels

        segs = item[2]
        K = 1
        while K < max(1, len(segs)):
            K *= 2
        max_m = max((s.m for s in segs), default=1)
        n_real = self.image._sim.na.N
        block = kernels.wave_block_for(max(max_m, 1), n_real)
        return (K, block, kernels.wave_kmax(max(max_m, 1), n_real, block))

    # ------------------------------------------------------ lane assembly ----

    def _lane_arrays(self, lanes: List[Tuple[Scenario, object]]):
        """(S, active_s, carry_np): the image's shared lane assembly (pow2
        quantization, mesh shard multiple, base-seed device-cache reuse)
        with each lane's copy-on-write overlay routed through lane_overlay
        for the nodepool activations."""
        return self.image._lane_arrays(
            [session for _, session in lanes],
            activates=[sc.activates for sc, _ in lanes])

    def _run_contained(self, chunk, dispatch) -> None:
        """One batched dispatch, with contained device failures (watchdog
        wedge, OOM) failing the chunk over to per-scenario fresh serial runs
        — never silent (simon_guard_failovers_total moves)."""
        if not chunk:
            return
        try:
            for res in dispatch(chunk):
                self._finish(res)
        except BaseException as e:
            cause = guard.containment_cause(e)
            if cause is None:
                raise
            guard.count_failover(cause, "sweep")
            for item in chunk:
                sc = item[0]
                self._finish(self._serial_result(
                    sc, route="fresh", gate=f"contained failure: {cause}"))

    def _finish(self, res: ScenarioResult) -> None:
        self.results[res.scenario.sid] = res
        obs.SWEEP_SCENARIOS.labels(family=res.scenario.family,
                                   route=res.route).inc()

    # ---------------------------------------------------- wave dispatch -----

    def _dispatch_wave_chunk(self, chunk) -> List[ScenarioResult]:
        from ..ops import kernels

        image = self.image
        with image._lock:
            for _, session, _ in chunk:
                session.ensure_current()
            image.ensure_staged()
            image.check_backend()
            S, active_s, carry_np = self._lane_arrays(
                [(sc, session) for sc, session, _ in chunk])
            K = 1
            max_segs = max((len(segs) for _, _, segs in chunk), default=1)
            while K < max_segs:
                K *= 2
            g_sk = np.zeros((S, K), np.int32)
            m_sk = np.zeros((S, K), np.int32)
            cap1_sk = np.zeros((S, K), bool)
            total_pods = 0
            for li, (_, _, segs) in enumerate(chunk):
                for k, seg in enumerate(segs):
                    g_sk[li, k], m_sk[li, k] = seg.g, seg.m
                    cap1_sk[li, k] = seg.cap1
                    total_pods += seg.m
            g_sk[len(chunk):] = g_sk[0]
            m_sk[len(chunk):] = m_sk[0]
            cap1_sk[len(chunk):] = cap1_sk[0]
            max_m = int(m_sk.max()) if m_sk.size else 0
            n_real = image._sim.na.N
            block = kernels.wave_block_for(max(max_m, 1), n_real)
            kmax = kernels.wave_kmax(max(max_m, 1), n_real, block)
            self._count_dispatch("sweep_wave_fanout", len(chunk))
            obs.record_dispatch("sweep_wave_fanout", K=K, block=block,
                                k=kmax, **image._dims(S))
            counts_skn, requested_s = guard.supervised(
                functools.partial(self._wave_round, carry_np, active_s,
                                  g_sk, m_sk, cap1_sk, block, kmax),
                site="dispatch", pods=max(1, total_pods))
            image.assert_image_alive()
            out = []
            for li, (sc, _, segs) in enumerate(chunk):
                out.append(self._wave_result(sc, segs, counts_skn[li],
                                             requested_s[li], active_s[li]))
            return out

    def _wave_round(self, carry_np, active_s, g_sk, m_sk, cap1_sk, block,
                    kmax):
        jnp = _jax()
        image = self.image
        sim = image._sim
        kns, carry_s, active, ctx = image._stage_lane_inputs(
            carry_np, active_s)
        with ctx:
            faults.maybe_fail("dispatch")
            faults.maybe_fail("oom_dispatch")
            carry_s, counts = kns.sweep_wave_fanout(
                image._tables, carry_s, active,
                jnp.asarray(g_sk), jnp.asarray(m_sk), jnp.asarray(cap1_sk),
                w=sim.score_w, filters=sim.filter_flags, block=block,
                kmax=kmax)
            faults.maybe_fail("fetch")
            return np.asarray(counts), np.asarray(carry_s.requested)

    def _wave_result(self, sc: Scenario, segs: List[_WaveSeg], counts_kn,
                     requested, active_row) -> ScenarioResult:
        image = self.image
        names = image._sim.na.names
        N = image._sim.na.N
        census: Census = {}
        tiers: Dict[str, int] = {}
        scheduled = 0
        for k, seg in enumerate(segs):
            row = counts_kn[k][:N]
            placed = int(row.sum())
            scheduled += placed
            tiers[seg.tier] = tiers.get(seg.tier, 0) + placed
            for ni in np.flatnonzero(row):
                key = (names[int(ni)], seg.sig)
                census[key] = census.get(key, 0) + int(row[ni])
            if seg.m - placed:
                key = ("", seg.sig)
                census[key] = census.get(key, 0) + seg.m - placed
        return ScenarioResult(
            scenario=sc, route="wave", scheduled=scheduled,
            total=len(sc.pods), census=census, tiers=tiers,
            utilization=image._utilization(active_row, requested),
            nodes_live=int(active_row[:N].sum()))

    # ---------------------------------------------------- scan dispatch -----

    def _dispatch_scan_chunk(self, chunk) -> List[ScenarioResult]:
        image = self.image
        with image._lock:
            for _, session in chunk:
                session.ensure_current()
            image.ensure_staged()
            image.check_backend()
            S, active_s, carry_np = self._lane_arrays(list(chunk))
            P = max(len(sc.pods) for sc, _ in chunk)
            P_pad = bucket_capped(max(P, 1), 2048)
            pod_group_s = np.zeros((S, P_pad), np.int32)
            forced_node_s = np.full((S, P_pad), -1, np.int32)
            valid_s = np.zeros((S, P_pad), bool)
            total_pods = 0
            for li, (sc, session) in enumerate(chunk):
                for i, (g, f) in enumerate(session.batch):
                    pod_group_s[li, i] = g
                    forced_node_s[li, i] = f
                valid_s[li, :len(session.batch)] = True
                total_pods += len(session.batch)
            pod_group_s[len(chunk):] = pod_group_s[0]
            forced_node_s[len(chunk):] = forced_node_s[0]
            valid_s[len(chunk):] = valid_s[0]
            self._count_dispatch("sweep_whatif_fanout", len(chunk))
            obs.record_dispatch("sweep_whatif_fanout", P=P_pad,
                                zones=image._bt.n_zones, **image._dims(S))
            choices_s, requested_s = guard.supervised(
                functools.partial(self._scan_round, carry_np, active_s,
                                  pod_group_s, forced_node_s, valid_s),
                site="dispatch", pods=max(1, total_pods))
            image.assert_image_alive()
            out = []
            for li, (sc, _) in enumerate(chunk):
                out.append(self._scan_result(sc, choices_s[li],
                                             requested_s[li], active_s[li]))
            return out

    def _scan_round(self, carry_np, active_s, pod_group_s, forced_node_s,
                    valid_s):
        jnp = _jax()
        image = self.image
        sim = image._sim
        kns, carry_s, active, ctx = image._stage_lane_inputs(
            carry_np, active_s)
        with ctx:
            faults.maybe_fail("dispatch")
            faults.maybe_fail("oom_dispatch")
            # gpu/storage pinned False: the image gates decline those
            # clusters AND requests (same reasoning as serve's serial round)
            carry_s, choices = kns.sweep_whatif_fanout(
                image._tables, carry_s, active,
                jnp.asarray(pod_group_s), jnp.asarray(forced_node_s),
                jnp.asarray(valid_s),
                n_zones=image._bt.n_zones, enable_gpu=False,
                enable_storage=False, w=sim.score_w,
                filters=sim.filter_flags)
            faults.maybe_fail("fetch")
            return np.asarray(choices), np.asarray(carry_s.requested)

    def _scan_result(self, sc: Scenario, choices, requested,
                     active_row) -> ScenarioResult:
        image = self.image
        names = image._sim.na.names
        N = image._sim.na.N
        census: Census = {}
        tiers: Dict[str, int] = {}
        scheduled = 0
        for i, pod in enumerate(sc.pods):
            sig = scheduling_signature(pod)
            tier = pod["metadata"]["labels"].get(TIER_LABEL, "baseline")
            ni = int(choices[i])
            if ni >= 0:
                scheduled += 1
                tiers[tier] = tiers.get(tier, 0) + 1
                key = (names[ni], sig)
            else:
                key = ("", sig)
            census[key] = census.get(key, 0) + 1
        return ScenarioResult(
            scenario=sc, route="scan", scheduled=scheduled,
            total=len(sc.pods), census=census, tiers=tiers,
            utilization=image._utilization(active_row, requested),
            nodes_live=int(active_row[:N].sum()))

    def _count_dispatch(self, kernel: str, lanes: int) -> None:
        self.dispatches[kernel] = self.dispatches.get(kernel, 0) + 1
        obs.SWEEP_DISPATCHES.labels(kernel=kernel).inc()
        obs.SWEEP_LANES.observe(lanes)

    # ------------------------------------------------------ serial oracle ----

    def _fresh_sim(self, sc: Scenario):
        """(sim, bound_pods) — the scenario's cluster from scratch: live
        nodes minus drains plus activated pool nodes, bound pods replayed
        (minus the drained nodes'), the image's cluster objects registered."""
        if self.image is not None:
            sim, bound, _ = self.image.fresh_simulator(
                drains=sc.drains, include=sc.activates)
            return sim, bound
        from ..simulator.engine import Simulator

        skip = set(sc.drains)
        act = set(sc.activates)
        nodes = [copy.deepcopy(n) for n in self._base_nodes
                 if name_of(n) not in skip]
        nodes += [copy.deepcopy(n) for n in self._pool_nodes
                  if name_of(n) in act]
        bound = [copy.deepcopy(p) for p in self._bound
                 if (p.get("spec") or {}).get("nodeName") not in skip]
        return Simulator(nodes), bound

    def serial_result(self, sc: Scenario, route: str = "serial",
                      gate: str = "") -> ScenarioResult:
        """One scenario evaluated the reference way: a fresh Simulator over
        that scenario's cluster, the full engine path (its own wave
        segmentation and all). This is BOTH the fresh route and the parity
        oracle — and what the bench's serial loop times."""
        sim, bound = self._fresh_sim(sc)
        request = [copy.deepcopy(p) for p in sc.pods]
        # signatures snapshot BEFORE scheduling: _commit_pod writes
        # spec.nodeName (part of the signature subtree) and pops the memo,
        # so a post-schedule signature would be node-dependent and never
        # match the batched lane's pre-schedule census keys
        sig_of = {(p["metadata"].get("namespace", "default"),
                   p["metadata"]["name"]): scheduling_signature(p)
                  for p in request}
        failed = sim.schedule_pods(bound + request)

        def req_key(pod):
            md = pod.get("metadata") or {}
            return (md.get("namespace", "default"), md.get("name"))

        census: Census = {}
        tiers: Dict[str, int] = {}
        scheduled = 0
        for ni, pods in enumerate(sim.pods_on_node):
            nname = sim.na.names[ni]
            for pod in pods:
                sig = sig_of.get(req_key(pod))
                if sig is None:
                    continue  # a bound pod, not request material
                scheduled += 1
                tier = (pod["metadata"].get("labels") or {}).get(
                    TIER_LABEL, "baseline")
                tiers[tier] = tiers.get(tier, 0) + 1
                key = (nname, sig)
                census[key] = census.get(key, 0) + 1
        for u in failed:
            sig = sig_of.get(req_key(u.pod))
            if sig is not None:
                key = ("", sig)
                census[key] = census.get(key, 0) + 1
        return ScenarioResult(
            scenario=sc, route=route, scheduled=scheduled,
            total=len(sc.pods), census=census, tiers=tiers,
            utilization=sim.probe_utilization(), nodes_live=sim.na.N,
            gate=gate)

    def _serial_result(self, sc: Scenario, route: str,
                       gate: str) -> ScenarioResult:
        return self.serial_result(sc, route=route, gate=gate)

    # ------------------------------------------------------------ parity -----

    def _parity_lanes(self) -> List[int]:
        batched = sorted(sid for sid, r in self.results.items()
                         if r.route in ("wave", "scan"))
        if self.parity == "off" or not batched:
            return []
        if self.parity == "full" or len(batched) <= self.parity_sample:
            return batched
        rng = np.random.default_rng(
            np.random.SeedSequence(entropy=[self.seed, 0x9A617]))
        pick = rng.choice(len(batched), size=self.parity_sample,
                          replace=False)
        return sorted(batched[i] for i in pick)

    def _check_parity(self) -> None:
        mismatches: List[str] = []
        for sid in self._parity_lanes():
            res = self.results[sid]
            oracle = self.serial_result(res.scenario)
            self.parity_checked += 1
            obs.SWEEP_PARITY_CHECKS.inc()
            if (res.census != oracle.census
                    or res.scheduled != oracle.scheduled
                    or res.utilization != oracle.utilization):
                obs.SWEEP_PARITY_MISMATCHES.inc()
                mismatches.append(self._describe_mismatch(res, oracle))
        if mismatches:
            raise SweepParityError(
                f"{len(mismatches)} sweep lane(s) diverged from the fresh "
                f"serial oracle:\n" + "\n".join(mismatches))

    @staticmethod
    def _describe_mismatch(res: ScenarioResult,
                           oracle: ScenarioResult) -> str:
        diff = []
        keys = set(res.census) | set(oracle.census)
        for key in sorted(keys):
            a, b = res.census.get(key, 0), oracle.census.get(key, 0)
            if a != b:
                diff.append(f"{key[0] or '<unscheduled>'}: "
                            f"batched={a} serial={b}")
                if len(diff) >= 6:
                    break
        return (f"  scenario {res.scenario.sid} ({res.scenario.label}, "
                f"route={res.route}): scheduled {res.scheduled} vs "
                f"{oracle.scheduled}; " + "; ".join(diff))

    # -------------------------------------------------------------- xray -----

    def _xray_results(self) -> None:
        """simonxray ride-along: one probe record per swept scenario."""
        from ..obs import xray

        run = xray.begin_run("sweep")
        if run is None:
            return
        for sid in sorted(self.results):
            r = self.results[sid]
            run.add_probe(r.scheduled, r.total, candidate=sid)
        xray.commit_run(run, [guard.current_backend()])


def _chunks(items: List, size: int):
    for i in range(0, len(items), size):
        yield items[i:i + size]


def _grouped(items: List, key):
    """[(key, lanes)] preserving scenario order within each group."""
    out: Dict[object, List] = {}
    for item in items:
        out.setdefault(key(item), []).append(item)
    return list(out.items())
