"""`python -m open_simulator_tpu.cli` → the simon CLI (same as the package
entry point; exists so scripted invocations can bypass the top-level
__main__'s import of the full package surface)."""

import sys

from .main import main

sys.exit(main(sys.argv[1:]))
