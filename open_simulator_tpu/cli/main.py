"""The `simon` CLI: apply / server / version / gen-doc.

Mirrors the reference's cobra command tree (/root/reference/cmd/): same
subcommands, flags (including shorthands), and the `LogLevel` env knob
(cmd/simon/simon.go:46-66).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
from typing import List, Optional, Tuple

from .. import __version__
from ..core import constants as C

COMMIT_ID = ""  # stamped by packaging, like the reference's ldflags (Makefile:9-10)

_LOG_LEVELS = {
    "Panic": logging.CRITICAL,
    "Fatal": logging.CRITICAL,
    "Error": logging.ERROR,
    "Warn": logging.WARNING,
    "Info": logging.INFO,
    "Debug": logging.DEBUG,
    "Trace": logging.DEBUG,
}


def _init_logging() -> None:
    level = _LOG_LEVELS.get(os.environ.get(C.EnvLogLevel, ""), logging.INFO)
    logging.basicConfig(level=level, format="%(levelname)s %(message)s")


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="simon",
        description=(
            "Simon is a simulator, which will simulate a cluster and simulate "
            "workload scheduling."
        ),
    )
    sub = parser.add_subparsers(dest="command", metavar="command")

    p_apply = sub.add_parser(
        "apply",
        help="Make a reasonable cluster capacity planning based on application "
             "resource requirements",
    )
    p_apply.add_argument(
        "-f", "--simon-config", required=True,
        help="path of the simon config file (simon/v1alpha1 Config)",
    )
    p_apply.add_argument(
        "--default-scheduler-config", default="",
        help="path to JSON or YAML file containing scheduler configuration.",
    )
    p_apply.add_argument("--output-file", default="", help="save report to output file.")
    p_apply.add_argument(
        "--profile", default="", metavar="DIR",
        help="write a jax.profiler device trace of the run to DIR "
             "(view with TensorBoard); the device-side analog of the "
             "reference's pprof endpoints.")
    p_apply.add_argument(
        "--use-greed", action="store_true", help="use greedy algorithm when queue pods"
    )
    p_apply.add_argument(
        "-i", "--interactive", action="store_true", help="interactive mode"
    )
    p_apply.add_argument(
        "--extended-resources", default="",
        help="show extended resources when reporting, comma-separated "
             "(e.g. open-local,gpu)",
    )
    p_apply.add_argument(
        "--placement-dump", default="",
        help="write a JSON placement dump for the parity tool",
    )
    p_apply.add_argument(
        "--trace-out", default="", metavar="FILE.json",
        help="write a Chrome trace-event JSON of the run's host spans "
             "(perfetto-loadable; includes the metrics snapshot as metadata)")
    p_apply.add_argument(
        "--metrics-out", default="", metavar="FILE.json",
        help="write the metrics-registry snapshot of the run as JSON "
             "(render later with `simon metrics FILE.json`)")
    p_apply.add_argument(
        "--deadline", type=float, default=0.0, metavar="SECONDS",
        help="wall-clock budget for the whole run; the capacity search and "
             "every simulation slice the remaining budget and the run fails "
             "cleanly when it expires (0 = unbounded)")
    p_apply.add_argument(
        "--resume-journal", default="", metavar="FILE.jsonl",
        help="crash-consistent capacity-search journal: probe verdicts are "
             "fsync'd to FILE as the search runs, and a re-run of the SAME "
             "search (options digest must match) resumes from it, skipping "
             "completed probes instead of recomputing an hour of search "
             "after a crash/SIGKILL")
    p_apply.add_argument(
        "--fault-plan", default="", metavar="SPEC",
        help="activate a deterministic fault-injection plan for the run: a "
             "JSON file, inline JSON, 'seed=N', or "
             "'site=S,attempt=K,error=E[;...]' (sites: see "
             "open_simulator_tpu.resilience.SITES). Testing/CI only.")
    p_apply.add_argument(
        "--xray", action="store_true",
        help="record per-pod scheduling decision records (simonxray flight "
             "recorder): segment attribution, per-plugin filter masks and "
             "score breakdowns, preemption victim chains. Query afterwards "
             "with `simon explain POD`.")
    p_apply.add_argument(
        "--xray-out", default="simon-xray", metavar="PREFIX",
        help="trace file prefix for --xray (writes PREFIX.jsonl + "
             "PREFIX.npz; default: simon-xray)")

    p_metrics = sub.add_parser(
        "metrics", help="Render a saved metrics snapshot (--metrics-out / "
                        "--trace-out file) as Prometheus text, or diff two "
                        "snapshots with --diff")
    p_metrics.add_argument(
        "snapshot", nargs="+",
        help="snapshot or trace JSON file (two files with --diff)")
    p_metrics.add_argument(
        "--diff", action="store_true",
        help="render per-metric deltas between TWO dumps (A B: changes from "
             "A to B), flagging counter regressions — compile-cache misses, "
             "retries, rollbacks and friends that grew, and counters that "
             "went backwards (different-process baselines)")
    p_metrics.add_argument(
        "--fail-on-regression", action="store_true",
        help="with --diff: exit 1 when any regression-direction counter grew")

    p_explain = sub.add_parser(
        "explain", help="Explain one pod's scheduling decision from a "
                        "simonxray trace (apply --xray): the kube-parity "
                        "event string, per-plugin filter rejections, and the "
                        "score breakdown vs the runner-up nodes")
    p_explain.add_argument("pod", nargs="?", default="",
                           help="pod to explain ('namespace/name', or a bare "
                                "name when unambiguous)")
    p_explain.add_argument(
        "--trace", default="simon-xray", metavar="PREFIX",
        help="xray trace prefix or .jsonl path (default: simon-xray)")
    p_explain.add_argument(
        "--unscheduled", action="store_true",
        help="list every unscheduled pod in the trace with its reason "
             "string instead of explaining one pod")
    p_explain.add_argument("--json", action="store_true",
                           help="emit the raw decision record as JSON")

    p_parity = sub.add_parser(
        "parity", help="Compute the placement match-rate between two dumps "
                       "written by `apply --placement-dump`")
    p_parity.add_argument("dump_a")
    p_parity.add_argument("dump_b")
    p_parity.add_argument("--threshold", type=float, default=0.99,
                          help="exit nonzero below this rate")
    p_parity.add_argument("-v", "--verbose", action="store_true",
                          help="list disagreeing placements")

    p_lint = sub.add_parser(
        "lint", add_help=False,
        help="Run simonlint, the JAX/TPU-hazard static analyzer, over the "
             "given paths (default: the open_simulator_tpu package)")
    p_lint.add_argument("lint_args", nargs=argparse.REMAINDER)

    p_audit = sub.add_parser(
        "audit", add_help=False,
        help="Run simonaudit: lower every registered hot kernel on CPU and "
             "diff its compile-time dispatch certificate (collective census, "
             "donation, host-callback escapes, recompile digest) against the "
             "goldens in tests/golden/audit/ (--check / --update)")
    p_audit.add_argument("audit_args", nargs=argparse.REMAINDER)

    p_server = sub.add_parser("server", help="Start a HTTP server that simulates "
                                             "deploy/scale requests against a live cluster")
    p_server.add_argument("--kubeconfig", default="", help="path of the kubeconfig file")
    p_server.add_argument("--master", default="", help="URL of the kube-apiserver")
    p_server.add_argument("--port", type=int, default=8080, help="listen port")
    p_server.add_argument(
        "--grpc-port", type=int, default=0, metavar="PORT",
        help="also serve the gRPC bridge (server/proto/simon.proto) on PORT "
             "(0 = disabled)")
    p_server.add_argument(
        "--drain-deadline", type=float, default=None, metavar="SECONDS",
        help="graceful-drain budget on SIGTERM: stop accepting (503), let "
             "in-flight requests finish up to SECONDS, then exit "
             "(default 25)")
    p_server.add_argument(
        "--debug-faults", action="store_true",
        help="enable the POST /debug/fault-plan injection endpoint "
             "(testing/CI only; never enable on a production server)")
    p_server.add_argument(
        "--xray", action="store_true",
        help="keep in-memory scheduling decision records and serve them on "
             "GET /explain/<pod> (+ the unscheduled summary on /debug/vars)")

    p_serve = sub.add_parser(
        "serve", help="Start the resident what-if server (simonserve): a "
                      "persistent device-resident cluster image with delta "
                      "ingest and micro-batched /v1/whatif serving")
    p_serve.add_argument("--kubeconfig", default="", help="path of the kubeconfig file")
    p_serve.add_argument("--master", default="", help="URL of the kube-apiserver")
    p_serve.add_argument("--port", type=int, default=8080, help="listen port")
    p_serve.add_argument(
        "--grpc-port", type=int, default=0, metavar="PORT",
        help="also serve the gRPC bridge (incl. the WhatIf RPC) on PORT "
             "(0 = disabled)")
    p_serve.add_argument(
        "--window-ms", type=float, default=2.0, metavar="MS",
        help="micro-batching window: concurrent what-if requests arriving "
             "within MS coalesce onto one fan-out dispatch (default 2)")
    p_serve.add_argument(
        "--fanout", type=int, default=8,
        help="max requests per micro-batched dispatch (scenario-axis lanes; "
             "default 8)")
    p_serve.add_argument(
        "--synthetic-nodes", type=int, default=0, metavar="N",
        help="serve a synthetic N-node cluster instead of a live snapshot "
             "(demos / load generation; no kubeconfig needed)")
    p_serve.add_argument(
        "--drain-deadline", type=float, default=None, metavar="SECONDS",
        help="graceful-drain budget on SIGTERM (default 25)")
    p_serve.add_argument(
        "--debug-faults", action="store_true",
        help="enable the POST /debug/fault-plan injection endpoint "
             "(testing/CI only)")
    p_serve.add_argument(
        "--xray", action="store_true",
        help="record per-request decision records; /v1/whatif responses "
             "then ride the flight recorder (GET /explain, /debug/vars)")
    p_serve.add_argument(
        "--no-scope", action="store_true",
        help="disable simonscope (request tracing + SLO engine + runtime "
             "telemetry sampler) — it is ON by default in serve mode; "
             "tracing-off serving reproduces bit-identical placements and "
             "byte-identical metrics")
    p_serve.add_argument(
        "--state-dir", default=None, metavar="DIR",
        help="simonha crash consistency: fsync every /v1/ingest delta to an "
             "epoch-numbered WAL in DIR before it mutates the image, "
             "checkpoint periodically, and on restart restore checkpoint + "
             "WAL tail to a bit-identical image (default: off, in-memory "
             "only)")
    p_serve.add_argument(
        "--staleness-ceiling", type=float, default=None, metavar="SECONDS",
        help="degraded mode serves the last consistent epoch at most this "
             "stale before /healthz flips 503 (default 120)")
    p_serve.add_argument(
        "--checkpoint-every", type=int, default=None, metavar="N",
        help="compact the WAL into a checkpoint every N ingest records "
             "(default 64)")
    p_serve.add_argument(
        "--max-queue", type=int, default=None, metavar="N",
        help="admission control: shed /v1/whatif with 429 once N requests "
             "are queued (default 256 in serve mode; deadline-aware "
             "shedding rides the same controller)")
    p_serve.add_argument(
        "--tenant-rate", type=float, default=None, metavar="RPS",
        help="per-(tenant, route) token-bucket rate limit in requests/s "
             "(0 = unlimited, the default)")
    p_serve.add_argument(
        "--ingest-max-bytes", type=int, default=None, metavar="BYTES",
        help="shed any /v1/ingest payload over BYTES with 413 before "
             "reading it (default 8 MiB; in-flight total bounded at 4x)")
    p_serve.add_argument(
        "--watch", default=None, metavar="SPEC",
        help="simonsync: keep the resident image current from a watch "
             "source instead of (only) /v1/ingest. SPEC is "
             "'file:stream.jsonl' (recorded JSONL replay), a chunked-HTTP "
             "watch URL (optionally 'watch_url|list_url' so 410-Gone can "
             "relist-reconcile), or 'kube' (watch the kubeconfig cluster's "
             "nodes+pods). Resumes from the persisted resourceVersion "
             "bookmark when --state-dir is set")

    p_slo = sub.add_parser(
        "slo", help="Render a running serve instance's SLO snapshot "
                    "(simonscope): per-endpoint rps, queue/dispatch/fetch/"
                    "total latency quantiles over the rolling window, SLO "
                    "targets and error-budget burn")
    p_slo.add_argument("--url", default="http://127.0.0.1:8080",
                       help="server base URL (default http://127.0.0.1:8080)")
    p_slo.add_argument("--json", action="store_true",
                       help="emit the raw /v1/serve/stats payload as JSON")

    p_top = sub.add_parser(
        "top", help="Refreshing terminal view of a running serve instance "
                    "(simonscope): rps, latency decomposition, lane "
                    "coalescing, route mix, device pool footprint")
    p_top.add_argument("--url", default="http://127.0.0.1:8080",
                       help="server base URL (default http://127.0.0.1:8080)")
    p_top.add_argument("--interval", type=float, default=2.0, metavar="S",
                       help="refresh period in seconds (default 2)")
    p_top.add_argument("--count", type=int, default=0, metavar="N",
                       help="exit after N refreshes (0 = until interrupted)")
    p_top.add_argument("--no-clear", action="store_true",
                       help="append frames instead of clearing the screen "
                            "(logs / CI)")

    p_pulse = sub.add_parser(
        "pulse", help="Render the simonpulse performance ledger: per-"
                      "dispatch wall decomposition, warm-wall MAD baselines "
                      "and flagged regressions, and the static roofline "
                      "cost table (cost_analysis FLOPs/bytes at the audit "
                      "buckets)")
    p_pulse.add_argument("--url", default="", metavar="URL",
                         help="fetch GET {URL}/v1/pulse from a running "
                              "server instead of reading locally")
    p_pulse.add_argument("--jsonl", default="", metavar="FILE",
                         help="summarize a spilled ledger file "
                              "(OPEN_SIMULATOR_PULSE_JSONL) offline")
    p_pulse.add_argument("--roofline", action="store_true",
                         help="print the static roofline table from the "
                              "audit goldens' cost census (every "
                              "HOT_KERNELS entry x bucket x mesh)")
    p_pulse.add_argument("--json", action="store_true",
                         help="emit the raw summary document as JSON")

    p_sweep = sub.add_parser(
        "sweep", help="Run a batched scenario sweep (simonsweep): N "
                      "independent what-if futures — drains, zone outages, "
                      "preemption storms, rollout waves, nodepool mixes, "
                      "Monte-Carlo workload draws — evaluated on the "
                      "scenario axis of a few fan-out dispatches, every "
                      "lane parity-checked against a fresh serial run")
    p_sweep.add_argument("spec", help="sweep spec file (YAML/JSON, kind: "
                                      "SweepSpec; see examples/sweeps/)")
    p_sweep.add_argument(
        "--seed", type=int, default=None, metavar="K",
        help="override the spec's seed: every random draw (Monte-Carlo "
             "replicas, drain picks, the parity sample) derives from it "
             "through explicit PRNG keys, so the same seed is byte-identical "
             "report JSON")
    p_sweep.add_argument(
        "--out", default="", metavar="FILE.json",
        help="write the full report as deterministic JSON")
    p_sweep.add_argument(
        "--json", action="store_true",
        help="print the report JSON on stdout instead of the summary table")
    p_sweep.add_argument(
        "--parity", choices=("full", "sample", "off"), default="full",
        help="batched==serial placement-census fuzzing: re-run every "
             "batched lane ('full', default), a seeded sample, or skip "
             "('off', bench timing only); any mismatch exits nonzero")
    p_sweep.add_argument(
        "--parity-sample", type=int, default=8, metavar="N",
        help="lanes re-run serially under --parity sample (default 8)")
    p_sweep.add_argument(
        "--fanout", type=int, default=64, metavar="S",
        help="max scenario lanes per batched dispatch (default 64)")

    sub.add_parser("version", help="Print the version of simon")

    p_doc = sub.add_parser("gen-doc", help="Generate markdown document for your project")
    p_doc.add_argument(
        "-d", "--output-directory", default="./docs/commandline",
        help="assign a directory to store documents",
    )
    return parser


def cmd_apply(args) -> int:
    from ..apply.applier import Applier, Options
    from ..utils.devices import ensure_responsive_backend

    # a wedged accelerator tunnel would otherwise hang the whole run at first
    # device use; probe it with a deadline and degrade to CPU instead
    ensure_responsive_backend()

    ext = [e.strip() for e in (args.extended_resources or "").split(",") if e.strip()]
    trace_out = getattr(args, "trace_out", "")
    metrics_out = getattr(args, "metrics_out", "")
    fault_plan = None
    xray_on = bool(getattr(args, "xray", False))
    if xray_on:
        from ..obs import xray

        xray.enable(getattr(args, "xray_out", "") or "simon-xray")
    try:
        if getattr(args, "fault_plan", ""):
            from ..resilience import FaultPlan, install_plan

            fault_plan = install_plan(FaultPlan.parse(args.fault_plan))
        applier = Applier(Options(
            simon_config=args.simon_config,
            default_scheduler_config=args.default_scheduler_config,
            use_greed=args.use_greed,
            interactive=args.interactive,
            extended_resources=ext,
            output_file=args.output_file,
            deadline=getattr(args, "deadline", 0.0) or 0.0,
            resume_journal=getattr(args, "resume_journal", "") or "",
        ))
        if trace_out:
            from ..utils.trace import start_collection

            start_collection()
        # simonscope CLI edge (OPEN_SIMULATOR_SCOPE=1): the apply run gets
        # one trace id, so engine schedule/probe spans group per run;
        # OPEN_SIMULATOR_SCOPE_OUT dumps the perfetto file afterwards
        # (failed runs included — scope.cli_edge owns the lifecycle)
        from ..obs import scope as scope_mod

        try:
            with scope_mod.cli_edge("cli:apply", config=args.simon_config):
                if args.profile:
                    import jax

                    with jax.profiler.trace(args.profile):
                        result = applier.run()
                else:
                    result = applier.run()
        finally:
            # dumps are written on FAILED runs too — a raising run records
            # failed=True spans, which is exactly when the trace matters —
            # and collection always stops (a leaked collector would grow for
            # the life of the process)
            if trace_out or metrics_out:
                from ..obs import REGISTRY

                if trace_out:
                    from ..obs.chrome import write_chrome_trace
                    from ..utils.trace import stop_collection

                    write_chrome_trace(trace_out, stop_collection(),
                                       metrics=REGISTRY.snapshot())
                if metrics_out:
                    with open(metrics_out, "w") as f:
                        json.dump(REGISTRY.snapshot(), f, indent=1)
                        f.write("\n")
        if result is not None and args.placement_dump:
            from ..parity import placement_dump, save_dump

            save_dump(placement_dump(result), args.placement_dump)
    except Exception as e:  # mirror `apply error: ...` + exit 1 (cmd/apply/apply.go:17-24)
        print(f"apply error: {e}", file=sys.stderr)
        return 1
    finally:
        if xray_on:
            # close on FAILED runs too — the partial trace is exactly the
            # evidence a failed run leaves behind
            from ..obs import xray

            rec = xray.active()
            counts = rec.counts() if rec is not None else {}
            xray.disable()
            # only point at the trace when something was actually recorded
            # (the JSONL is opened lazily on the first committed batch)
            if counts.get("batches"):
                print(f"xray: {counts.get('pods', 0)} decision records "
                      f"({counts.get('unscheduled', 0)} unscheduled, "
                      f"{counts.get('sets', 0)} decision sets) -> "
                      f"{counts.get('path')}.jsonl; query with "
                      f"`simon explain POD --trace {counts.get('path')}`",
                      file=sys.stderr)
        if fault_plan is not None:
            from ..resilience import clear_plan

            clear_plan()
            # the fired-injection trace on stderr: the replay-equality
            # artifact CI diffs across identical runs
            print(f"fault plan trace: {json.dumps(fault_plan.to_json()['trace'])}",
                  file=sys.stderr)
    # None = planning failed / user exited without a schedulable outcome; scripts
    # need a nonzero exit to distinguish it from success.
    return 0 if result is not None else 1


def cmd_lint(args) -> int:
    """simonlint — static analysis of JAX/TPU hazards (analysis/runner.py).
    Normally short-circuited in main(); this handles parse_args callers."""
    from ..analysis.runner import run_lint

    return run_lint(args.lint_args)


def cmd_audit(args) -> int:
    """simonaudit — compile-time dispatch certificates (analysis/hlo.py).
    Normally short-circuited in main(); this handles parse_args callers."""
    from ..analysis.hlo import run_audit

    return run_audit(args.audit_args)


def cmd_server(args) -> int:
    from ..server.http import Server
    from ..utils.devices import ensure_responsive_backend

    ensure_responsive_backend()

    try:
        server = Server(kubeconfig=args.kubeconfig, master=args.master,
                        debug_faults=True if args.debug_faults else None,
                        xray=True if getattr(args, "xray", False) else None)
        if args.grpc_port:
            # same Server object behind both surfaces: the TryLock busy
            # semantics hold across REST and gRPC clients
            from ..server.grpcbridge import GrpcBridge

            bridge = GrpcBridge(server=server)
            grpc_server, bound = bridge.build_grpc_server(args.grpc_port)
            grpc_server.start()
            print(f"simon grpc bridge listening on :{bound}")
        server.start(port=args.port,
                     drain_deadline=getattr(args, "drain_deadline", None))
    except KeyboardInterrupt:
        return 0
    except Exception as e:
        print(f"failed to start server: {e}", file=sys.stderr)
        return 1
    return 0


def cmd_serve(args) -> int:
    """`simon serve`: the `simon server` stack with resident what-if serving
    enabled — the image stages on the first /v1/whatif and stays current via
    /v1/ingest deltas. --synthetic-nodes N serves a generated cluster so the
    closed-loop load generator (tools/loadgen.py) and demos need no live
    kube-apiserver."""
    from ..server.http import ClusterSnapshot, Server
    from ..utils.devices import ensure_responsive_backend

    ensure_responsive_backend()
    snapshot_fn = None
    if args.synthetic_nodes:
        from ..core.types import ResourceTypes
        from ..utils.synth import synth_node

        n = int(args.synthetic_nodes)
        rt = ResourceTypes(nodes=[synth_node(i) for i in range(n)])
        snapshot_fn = lambda: ClusterSnapshot(rt, [], [], [])  # noqa: E731
    try:
        # simonscope is serve mode's default observability posture
        # (request tracing + SLO engine + runtime sampler); --no-scope /
        # OPEN_SIMULATOR_SCOPE=0 opts out
        from ..obs import scope as scope_mod

        scope_on = (False if getattr(args, "no_scope", False)
                    else scope_mod.env_enabled(default=True))
        server = Server(kubeconfig=args.kubeconfig, master=args.master,
                        snapshot_fn=snapshot_fn,
                        debug_faults=True if args.debug_faults else None,
                        xray=True if getattr(args, "xray", False) else None,
                        whatif=True, whatif_window_ms=args.window_ms,
                        whatif_fanout=args.fanout, scope=scope_on,
                        state_dir=getattr(args, "state_dir", None),
                        staleness_ceiling_s=getattr(
                            args, "staleness_ceiling", None),
                        checkpoint_every=getattr(
                            args, "checkpoint_every", None),
                        # serve mode bounds its queue by default: an
                        # unbounded admission queue is the exact hazard
                        # simonha closes (simonlint: unbounded-queue)
                        max_queue=(args.max_queue
                                   if getattr(args, "max_queue", None)
                                   is not None else 256),
                        tenant_rate=getattr(args, "tenant_rate", None),
                        ingest_max_bytes=getattr(
                            args, "ingest_max_bytes", None),
                        watch=getattr(args, "watch", None))
        if args.grpc_port:
            from ..server.grpcbridge import GrpcBridge

            bridge = GrpcBridge(server=server)
            grpc_server, bound = bridge.build_grpc_server(args.grpc_port)
            grpc_server.start()
            print(f"simon grpc bridge listening on :{bound}")
        server.start(port=args.port,
                     drain_deadline=getattr(args, "drain_deadline", None))
    except KeyboardInterrupt:
        return 0
    except Exception as e:
        print(f"failed to start serve: {e}", file=sys.stderr)
        return 1
    return 0


def cmd_sweep(args) -> int:
    """`simon sweep`: batched scenario sweeps over one resident cluster
    image, with the batched==serial parity fuzzer on by default."""
    import time

    from ..sweep import (
        SweepParityError,
        SweepRunner,
        SweepSpecError,
        build_report,
        load_spec,
        render_report,
        report_json,
    )
    from ..utils.devices import ensure_responsive_backend

    ensure_responsive_backend()
    try:
        spec = load_spec(args.spec)
    except SweepSpecError as e:
        print(f"sweep error: {e}", file=sys.stderr)
        return 1
    runner = SweepRunner(spec, seed=args.seed, parity=args.parity,
                         parity_sample=args.parity_sample,
                         fanout=args.fanout)
    # simonscope CLI edge (OPEN_SIMULATOR_SCOPE=1): the whole sweep becomes
    # one trace — chunk dispatch spans (sweep/runner.py) and engine probe
    # spans share the run's trace id; OPEN_SIMULATOR_SCOPE_OUT dumps the
    # perfetto file on exit, parity failures included (scope.cli_edge)
    from ..obs import scope as scope_mod

    t0 = time.perf_counter()
    try:
        with scope_mod.cli_edge("cli:sweep", spec=args.spec):
            runner.run()
    except SweepParityError as e:
        print(f"sweep PARITY FAILURE: {e}", file=sys.stderr)
        return 1
    except SweepSpecError as e:
        print(f"sweep error: {e}", file=sys.stderr)
        return 1
    wall = time.perf_counter() - t0
    report = build_report(runner)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            fh.write(report_json(report))
    if args.json:
        sys.stdout.write(report_json(report))
    else:
        print(render_report(report))
    # wall time on stderr ONLY: the report (and --out bytes) must be
    # deterministic across runs of the same seed
    print(f"sweep: {len(report['scenarios'])} scenarios in {wall:.2f}s "
          f"({len(report['scenarios']) / wall:.1f} scenarios/s)"
          + (f" -> {args.out}" if args.out else ""), file=sys.stderr)
    return 0


def _load_metrics_snapshot(path: str) -> dict:
    """A registry snapshot from a --metrics-out dump or the metadata of a
    --trace-out Chrome trace. Raises ValueError on anything else."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "traceEvents" in doc:
        doc = (doc.get("metadata") or {}).get("metrics")
        if not doc:
            raise ValueError(f"{path}: trace file carries no metrics snapshot")
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: not a metrics snapshot")
    return doc


# Counter families whose GROWTH between two runs is a regression signal when
# comparing bench/CI dumps (everything here counts failures, rework, or
# compile churn — never useful work).
_BAD_WHEN_UP = (
    "simon_compile_cache_misses_total",
    "simon_xla_backend_compiles_total",
    "simon_commit_rollbacks_total",
    "simon_http_errors_total",
    "simon_retries_total",
    "simon_deadline_exceeded_total",
    "simon_faults_injected_total",
    "simon_guard_watchdog_expiries_total",
    "simon_guard_oom_bisections_total",
    "simon_guard_failovers_total",
    "simon_preemption_replay_pods_total",
    "simon_xray_dropped_total",
    # serving/scope rework-and-loss families (PR 14): stale sessions are
    # transparent re-encodes (rework), parity mismatches are correctness
    # failures, dropped trace events / sampler errors are observability loss
    "simon_serve_stale_sessions_total",
    "simon_sweep_parity_mismatches_total",
    "simon_scope_trace_dropped_total",
    "simon_scope_sampler_errors_total",
    # simonpulse (PR 18): a flagged warm-wall regression is a performance
    # defect by definition; evicted ledger records are observability loss
    "simon_pulse_regressions_total",
    "simon_pulse_records_dropped_total",
    # simonha (PR 19): a wrong-epoch answer or a WAL/checkpoint lineage
    # mismatch is a crash-consistency correctness failure
    "simon_serve_wrong_epoch_answers_total",
    "simon_serve_wal_parity_mismatches_total",
    # simonsync (PR 20): a post-reconcile parity mismatch is a correctness
    # failure; a relist falling back to a generation-bumping rebuild means
    # the columnar diff declined — a robustness regression
    "simon_sync_parity_mismatches_total",
    "simon_sync_full_rebuilds_total",
)


def _diff_metrics(snap_a: dict, snap_b: dict, out) -> Tuple[int, int]:
    """Render per-metric deltas A -> B; returns (changed, regressions)."""
    from ..obs import values_from_snapshot

    va, vb = values_from_snapshot(snap_a), values_from_snapshot(snap_b)
    fam_type: dict = {}
    for snap in (snap_a, snap_b):
        for name, fam in snap.items():
            fam_type[name] = fam.get("type", "untyped")
    # longest-match family lookup: flat keys are name{labels} (+_sum/_count)
    fams = sorted(fam_type, key=len, reverse=True)
    changed = regressions = backwards = 0

    def fmt(v: float) -> str:
        return str(int(v)) if float(v).is_integer() else f"{v:.6g}"

    for key in sorted(set(va) | set(vb)):
        a, b = va.get(key, 0.0), vb.get(key, 0.0)
        if a == b:
            continue
        changed += 1
        fam = next((n for n in fams if key.startswith(n)), "")
        delta = b - a
        flags = []
        if fam_type.get(fam) == "counter":
            if delta < 0:
                backwards += 1
                flags.append("counter went backwards (different baseline?)")
            elif any(fam.startswith(p) for p in _BAD_WHEN_UP):
                regressions += 1
                flags.append("REGRESSION")
        sign = "+" if delta >= 0 else ""
        print(f"{key}  {fmt(a)} -> {fmt(b)}  ({sign}{fmt(delta)})"
              + (f"  [{'; '.join(flags)}]" if flags else ""), file=out)
    print(f"# {changed} metric(s) changed, {regressions} regression(s), "
          f"{backwards} counter(s) went backwards", file=out)
    return changed, regressions


def cmd_metrics(args) -> int:
    """Render a saved registry snapshot (apply --metrics-out, or the metadata
    of a --trace-out Chrome trace) as Prometheus text on stdout — or, with
    --diff A B, the per-metric deltas between two dumps."""
    from ..obs import render_text_from_snapshot

    try:
        if args.diff:
            if len(args.snapshot) != 2:
                print("metrics error: --diff needs exactly two snapshot "
                      "files (A B)", file=sys.stderr)
                return 1
            _, regressions = _diff_metrics(
                _load_metrics_snapshot(args.snapshot[0]),
                _load_metrics_snapshot(args.snapshot[1]), sys.stdout)
            return 1 if regressions and args.fail_on_regression else 0
        if len(args.snapshot) != 1:
            print("metrics error: one snapshot file expected (use --diff "
                  "for two)", file=sys.stderr)
            return 1
        doc = _load_metrics_snapshot(args.snapshot[0])
    except (OSError, ValueError) as e:
        print(f"metrics error: {e}", file=sys.stderr)
        return 1
    sys.stdout.write(render_text_from_snapshot(doc))
    return 0


def cmd_explain(args) -> int:
    """Explain one pod's scheduling decision from a simonxray trace: the
    kube-scheduler-parity event line, per-plugin filter rejections, and the
    chosen-node score breakdown vs the runner-ups."""
    from ..obs import xray

    if not args.pod and not args.unscheduled:
        print("explain error: name a pod ('namespace/name') or pass "
              "--unscheduled", file=sys.stderr)
        return 1
    try:
        tr = xray.XrayTrace.load(args.trace)
    except (OSError, ValueError) as e:
        print(f"explain error: {e}", file=sys.stderr)
        return 1
    if args.unscheduled:
        rows = tr.unscheduled_summary()
        if args.json:
            print(json.dumps(rows, indent=1))
        else:
            for r in rows:
                print(f"{r['pod']}: {r['reason']}")
            print(f"# {len(rows)} unscheduled pod(s)")
        return 0
    exp = tr.explain(args.pod)
    if exp is None:
        print(f"explain error: no decision record for pod {args.pod!r} in "
              f"{args.trace} (run with --xray, and use 'namespace/name' "
              "when the bare name is ambiguous)", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(exp, indent=1, default=str))
    else:
        print(xray.render_explanation(exp))
    return 0


def _fetch_serve_stats(url: str) -> dict:
    """GET {url}/v1/serve/stats (the one snapshot `simon slo` and
    `simon top` are both built on)."""
    import urllib.error
    import urllib.request

    target = url.rstrip("/") + "/v1/serve/stats"
    try:
        with urllib.request.urlopen(target, timeout=10) as resp:
            return json.loads(resp.read())
    except urllib.error.HTTPError as e:
        body = e.read().decode(errors="replace")
        raise RuntimeError(f"{target} -> HTTP {e.code}: {body}") from e
    except (urllib.error.URLError, OSError) as e:
        raise RuntimeError(f"{target}: {e}") from e


def _render_slo(stats: dict) -> str:
    """The `simon slo` table: per endpoint, windowed rps + phase quantiles +
    SLO budget accounting, from one /v1/serve/stats snapshot."""
    slo = stats.get("slo")
    if not slo:
        return ("no SLO data: simonscope is off on this server "
                "(start with `simon serve`, without --no-scope)")
    lines = [f"window: {slo.get('window_s', 0):g}s   epoch: "
             f"{stats.get('epoch', '?')}   nodes: {stats.get('nodes', '?')}"
             f"   queued: {stats.get('queued', 0)}"]
    for ep, d in sorted(slo.get("endpoints", {}).items()):
        routes = ", ".join(f"{r}={n}" for r, n in sorted(
            d.get("routes", {}).items()))
        lines.append(f"\n{ep}  ({d.get('rps', 0):g} rps; {routes})")
        lines.append(f"  {'phase':<10}{'count':>7}{'mean':>9}{'p50':>9}"
                     f"{'p95':>9}{'p99':>9}  (ms)")
        for phase in ("queue", "dispatch", "fetch", "total"):
            q = d.get("phases", {}).get(phase)
            if q is None:
                continue
            lines.append(
                f"  {phase:<10}{q['count']:>7}{q['mean_ms']:>9.2f}"
                f"{q['p50_ms']:>9.2f}{q['p95_ms']:>9.2f}{q['p99_ms']:>9.2f}")
        s = d.get("slo")
        if s:
            # availability-only targets leave target_p99_ms None (the
            # latency check then defaults to +inf in the engine)
            p99t = s.get("target_p99_ms")
            lines.append(
                f"  SLO: p99 target "
                f"{'—' if p99t is None else f'{p99t:g}ms'}, availability "
                f"{s['availability_target']:g} — {s['violations']}/"
                f"{s['requests']} violations, budget burn "
                f"{s['budget_burn']:g}x"
                + (" [BURNING]" if s["budget_burn"] > 1.0 else ""))
    sc = stats.get("scope") or {}
    pools = sc.get("pools") or {}
    if pools:
        lines.append("\ndevice pools: " + "  ".join(
            f"{k}={v / 1e6:.2f}MB" for k, v in sorted(pools.items())))
    if sc:
        lines.append(f"trace: {sc.get('trace_events', 0)} events buffered"
                     f" (cap {sc.get('trace_cap', 0)}); sampler "
                     f"{'on' if sc.get('sampler') else 'off'}")
    return "\n".join(lines)


def cmd_slo(args) -> int:
    """`simon slo`: one SLO snapshot from a running serve instance."""
    try:
        stats = _fetch_serve_stats(args.url)
    except RuntimeError as e:
        print(f"slo error: {e}", file=sys.stderr)
        return 1
    try:
        if args.json:
            print(json.dumps(stats, indent=1, sort_keys=True))
        else:
            print(_render_slo(stats))
    except BrokenPipeError:
        return 0  # `simon slo | head` closing the pipe early is fine
    return 0


def cmd_top(args) -> int:
    """`simon top`: the refreshing terminal view over the same snapshots
    `simon slo` renders once."""
    import time as _time

    n = 0
    try:
        while True:
            try:
                stats = _fetch_serve_stats(args.url)
                frame = _render_slo(stats)
            except RuntimeError as e:
                frame = f"top: {e}"
            if not args.no_clear and sys.stdout.isatty():
                sys.stdout.write("\x1b[2J\x1b[H")  # clear + home
            print(f"simon top — {args.url}  "
                  f"(refresh {args.interval:g}s; ctrl-c to exit)")
            print(frame, flush=True)
            n += 1
            if args.count and n >= args.count:
                return 0
            _time.sleep(max(0.1, args.interval))
    except KeyboardInterrupt:
        return 0
    except BrokenPipeError:
        return 0  # `simon top | head` closing the pipe early is fine


def cmd_pulse(args) -> int:
    """`simon pulse`: render the performance ledger — from a running server
    (--url), a spilled JSONL file (--jsonl), or this process's Pulse (mostly
    useful under --roofline, which needs no live ledger at all)."""
    from ..obs import pulse

    if args.roofline:
        rows = pulse.roofline_table()
        if not rows:
            print("pulse error: no cost data in the audit goldens — run "
                  "`simon audit --update` to (re)generate certificates "
                  "with a cost census", file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps(rows, indent=1, sort_keys=True))
        else:
            print(pulse.format_roofline(rows))
        return 0
    if args.url:
        import urllib.error
        import urllib.request

        base = args.url.rstrip("/")
        if "://" not in base:
            base = "http://" + base
        target = base + "/v1/pulse"
        try:
            with urllib.request.urlopen(target, timeout=10) as resp:
                doc = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            body = e.read().decode(errors="replace")
            print(f"pulse error: {target} -> HTTP {e.code}: {body}",
                  file=sys.stderr)
            return 1
        except (urllib.error.URLError, OSError) as e:
            print(f"pulse error: {target}: {e}", file=sys.stderr)
            return 1
    elif args.jsonl:
        recs = []
        try:
            with open(args.jsonl, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if line:
                        recs.append(json.loads(line))
        except (OSError, ValueError) as e:
            print(f"pulse error: {args.jsonl}: {e}", file=sys.stderr)
            return 1
        doc = pulse.summarize_records(recs)
    else:
        p = pulse.active()
        if p is None:
            print("pulse error: simonpulse is off in this process; use "
                  "--url against a server started with "
                  "OPEN_SIMULATOR_PULSE=1, --jsonl on a spilled ledger, "
                  "or --roofline for the static cost table",
                  file=sys.stderr)
            return 1
        doc = p.summary()
    try:
        if args.json:
            print(json.dumps(doc, indent=1, sort_keys=True))
        else:
            print(pulse.format_summary(doc))
    except BrokenPipeError:
        return 0  # `simon pulse | head` closing the pipe early is fine
    return 0


def cmd_version(_args) -> int:
    print(f"Version: {__version__}")
    print(f"Commit: {COMMIT_ID}")
    return 0


def cmd_gen_doc(args) -> int:
    """cobra doc.GenMarkdownTree equivalent: one markdown page per command."""
    out = args.output_directory
    if not os.path.isdir(out):
        print(f"Invalid output directory({out})", file=sys.stderr)
        return 1
    parser = build_parser()
    pages = {"simon": parser}
    for action in parser._subparsers._group_actions:  # noqa: SLF001
        for name, sp in action.choices.items():
            pages[f"simon_{name.replace('-', '_')}"] = sp
    for page, p in pages.items():
        with open(os.path.join(out, f"{page}.md"), "w") as f:
            title = page.replace("_", " ")
            f.write(f"## {title}\n\n{p.description or p.format_usage()}\n\n")
            f.write("```\n" + p.format_help() + "```\n")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    _init_logging()
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv[:1] == ["lint"]:
        # Dispatch before argparse: REMAINDER would reject flags placed ahead
        # of the first path (`simon lint --format json pkg/`), and run_lint
        # owns its own --help.
        from ..analysis.runner import run_lint

        return run_lint(argv[1:])
    if argv[:1] == ["audit"]:
        # same REMAINDER workaround; run_audit owns its own --help, and must
        # set the virtual-CPU device flag before anything imports jax
        from ..analysis.hlo import run_audit

        return run_audit(argv[1:])
    parser = build_parser()
    args = parser.parse_args(argv)
    from ..parity import cmd_parity

    handlers = {
        "apply": cmd_apply,
        "audit": cmd_audit,
        "explain": cmd_explain,
        "lint": cmd_lint,
        "metrics": cmd_metrics,
        "serve": cmd_serve,
        "server": cmd_server,
        "slo": cmd_slo,
        "sweep": cmd_sweep,
        "top": cmd_top,
        "version": cmd_version,
        "gen-doc": cmd_gen_doc,
        "parity": cmd_parity,
        "pulse": cmd_pulse,
    }
    if not args.command:
        parser.print_help()
        return 0
    return handlers[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
