"""The `simon` CLI: apply / server / version / gen-doc.

Mirrors the reference's cobra command tree (/root/reference/cmd/): same
subcommands, flags (including shorthands), and the `LogLevel` env knob
(cmd/simon/simon.go:46-66).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
from typing import List, Optional

from .. import __version__
from ..core import constants as C

COMMIT_ID = ""  # stamped by packaging, like the reference's ldflags (Makefile:9-10)

_LOG_LEVELS = {
    "Panic": logging.CRITICAL,
    "Fatal": logging.CRITICAL,
    "Error": logging.ERROR,
    "Warn": logging.WARNING,
    "Info": logging.INFO,
    "Debug": logging.DEBUG,
    "Trace": logging.DEBUG,
}


def _init_logging() -> None:
    level = _LOG_LEVELS.get(os.environ.get(C.EnvLogLevel, ""), logging.INFO)
    logging.basicConfig(level=level, format="%(levelname)s %(message)s")


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="simon",
        description=(
            "Simon is a simulator, which will simulate a cluster and simulate "
            "workload scheduling."
        ),
    )
    sub = parser.add_subparsers(dest="command", metavar="command")

    p_apply = sub.add_parser(
        "apply",
        help="Make a reasonable cluster capacity planning based on application "
             "resource requirements",
    )
    p_apply.add_argument(
        "-f", "--simon-config", required=True,
        help="path of the simon config file (simon/v1alpha1 Config)",
    )
    p_apply.add_argument(
        "--default-scheduler-config", default="",
        help="path to JSON or YAML file containing scheduler configuration.",
    )
    p_apply.add_argument("--output-file", default="", help="save report to output file.")
    p_apply.add_argument(
        "--profile", default="", metavar="DIR",
        help="write a jax.profiler device trace of the run to DIR "
             "(view with TensorBoard); the device-side analog of the "
             "reference's pprof endpoints.")
    p_apply.add_argument(
        "--use-greed", action="store_true", help="use greedy algorithm when queue pods"
    )
    p_apply.add_argument(
        "-i", "--interactive", action="store_true", help="interactive mode"
    )
    p_apply.add_argument(
        "--extended-resources", default="",
        help="show extended resources when reporting, comma-separated "
             "(e.g. open-local,gpu)",
    )
    p_apply.add_argument(
        "--placement-dump", default="",
        help="write a JSON placement dump for the parity tool",
    )
    p_apply.add_argument(
        "--trace-out", default="", metavar="FILE.json",
        help="write a Chrome trace-event JSON of the run's host spans "
             "(perfetto-loadable; includes the metrics snapshot as metadata)")
    p_apply.add_argument(
        "--metrics-out", default="", metavar="FILE.json",
        help="write the metrics-registry snapshot of the run as JSON "
             "(render later with `simon metrics FILE.json`)")
    p_apply.add_argument(
        "--deadline", type=float, default=0.0, metavar="SECONDS",
        help="wall-clock budget for the whole run; the capacity search and "
             "every simulation slice the remaining budget and the run fails "
             "cleanly when it expires (0 = unbounded)")
    p_apply.add_argument(
        "--resume-journal", default="", metavar="FILE.jsonl",
        help="crash-consistent capacity-search journal: probe verdicts are "
             "fsync'd to FILE as the search runs, and a re-run of the SAME "
             "search (options digest must match) resumes from it, skipping "
             "completed probes instead of recomputing an hour of search "
             "after a crash/SIGKILL")
    p_apply.add_argument(
        "--fault-plan", default="", metavar="SPEC",
        help="activate a deterministic fault-injection plan for the run: a "
             "JSON file, inline JSON, 'seed=N', or "
             "'site=S,attempt=K,error=E[;...]' (sites: see "
             "open_simulator_tpu.resilience.SITES). Testing/CI only.")

    p_metrics = sub.add_parser(
        "metrics", help="Render a saved metrics snapshot (--metrics-out / "
                        "--trace-out file) as Prometheus text")
    p_metrics.add_argument("snapshot", help="snapshot or trace JSON file")

    p_parity = sub.add_parser(
        "parity", help="Compute the placement match-rate between two dumps "
                       "written by `apply --placement-dump`")
    p_parity.add_argument("dump_a")
    p_parity.add_argument("dump_b")
    p_parity.add_argument("--threshold", type=float, default=0.99,
                          help="exit nonzero below this rate")
    p_parity.add_argument("-v", "--verbose", action="store_true",
                          help="list disagreeing placements")

    p_lint = sub.add_parser(
        "lint", add_help=False,
        help="Run simonlint, the JAX/TPU-hazard static analyzer, over the "
             "given paths (default: the open_simulator_tpu package)")
    p_lint.add_argument("lint_args", nargs=argparse.REMAINDER)

    p_server = sub.add_parser("server", help="Start a HTTP server that simulates "
                                             "deploy/scale requests against a live cluster")
    p_server.add_argument("--kubeconfig", default="", help="path of the kubeconfig file")
    p_server.add_argument("--master", default="", help="URL of the kube-apiserver")
    p_server.add_argument("--port", type=int, default=8080, help="listen port")
    p_server.add_argument(
        "--grpc-port", type=int, default=0, metavar="PORT",
        help="also serve the gRPC bridge (server/proto/simon.proto) on PORT "
             "(0 = disabled)")
    p_server.add_argument(
        "--drain-deadline", type=float, default=None, metavar="SECONDS",
        help="graceful-drain budget on SIGTERM: stop accepting (503), let "
             "in-flight requests finish up to SECONDS, then exit "
             "(default 25)")
    p_server.add_argument(
        "--debug-faults", action="store_true",
        help="enable the POST /debug/fault-plan injection endpoint "
             "(testing/CI only; never enable on a production server)")

    sub.add_parser("version", help="Print the version of simon")

    p_doc = sub.add_parser("gen-doc", help="Generate markdown document for your project")
    p_doc.add_argument(
        "-d", "--output-directory", default="./docs/commandline",
        help="assign a directory to store documents",
    )
    return parser


def cmd_apply(args) -> int:
    from ..apply.applier import Applier, Options
    from ..utils.devices import ensure_responsive_backend

    # a wedged accelerator tunnel would otherwise hang the whole run at first
    # device use; probe it with a deadline and degrade to CPU instead
    ensure_responsive_backend()

    ext = [e.strip() for e in (args.extended_resources or "").split(",") if e.strip()]
    trace_out = getattr(args, "trace_out", "")
    metrics_out = getattr(args, "metrics_out", "")
    fault_plan = None
    try:
        if getattr(args, "fault_plan", ""):
            from ..resilience import FaultPlan, install_plan

            fault_plan = install_plan(FaultPlan.parse(args.fault_plan))
        applier = Applier(Options(
            simon_config=args.simon_config,
            default_scheduler_config=args.default_scheduler_config,
            use_greed=args.use_greed,
            interactive=args.interactive,
            extended_resources=ext,
            output_file=args.output_file,
            deadline=getattr(args, "deadline", 0.0) or 0.0,
            resume_journal=getattr(args, "resume_journal", "") or "",
        ))
        if trace_out:
            from ..utils.trace import start_collection

            start_collection()
        try:
            if args.profile:
                import jax

                with jax.profiler.trace(args.profile):
                    result = applier.run()
            else:
                result = applier.run()
        finally:
            # dumps are written on FAILED runs too — a raising run records
            # failed=True spans, which is exactly when the trace matters —
            # and collection always stops (a leaked collector would grow for
            # the life of the process)
            if trace_out or metrics_out:
                from ..obs import REGISTRY

                if trace_out:
                    from ..obs.chrome import write_chrome_trace
                    from ..utils.trace import stop_collection

                    write_chrome_trace(trace_out, stop_collection(),
                                       metrics=REGISTRY.snapshot())
                if metrics_out:
                    with open(metrics_out, "w") as f:
                        json.dump(REGISTRY.snapshot(), f, indent=1)
                        f.write("\n")
        if result is not None and args.placement_dump:
            from ..parity import placement_dump, save_dump

            save_dump(placement_dump(result), args.placement_dump)
    except Exception as e:  # mirror `apply error: ...` + exit 1 (cmd/apply/apply.go:17-24)
        print(f"apply error: {e}", file=sys.stderr)
        return 1
    finally:
        if fault_plan is not None:
            from ..resilience import clear_plan

            clear_plan()
            # the fired-injection trace on stderr: the replay-equality
            # artifact CI diffs across identical runs
            print(f"fault plan trace: {json.dumps(fault_plan.to_json()['trace'])}",
                  file=sys.stderr)
    # None = planning failed / user exited without a schedulable outcome; scripts
    # need a nonzero exit to distinguish it from success.
    return 0 if result is not None else 1


def cmd_lint(args) -> int:
    """simonlint — static analysis of JAX/TPU hazards (analysis/runner.py).
    Normally short-circuited in main(); this handles parse_args callers."""
    from ..analysis.runner import run_lint

    return run_lint(args.lint_args)


def cmd_server(args) -> int:
    from ..server.http import Server
    from ..utils.devices import ensure_responsive_backend

    ensure_responsive_backend()

    try:
        server = Server(kubeconfig=args.kubeconfig, master=args.master,
                        debug_faults=True if args.debug_faults else None)
        if args.grpc_port:
            # same Server object behind both surfaces: the TryLock busy
            # semantics hold across REST and gRPC clients
            from ..server.grpcbridge import GrpcBridge

            bridge = GrpcBridge(server=server)
            grpc_server, bound = bridge.build_grpc_server(args.grpc_port)
            grpc_server.start()
            print(f"simon grpc bridge listening on :{bound}")
        server.start(port=args.port,
                     drain_deadline=getattr(args, "drain_deadline", None))
    except KeyboardInterrupt:
        return 0
    except Exception as e:
        print(f"failed to start server: {e}", file=sys.stderr)
        return 1
    return 0


def cmd_metrics(args) -> int:
    """Render a saved registry snapshot (apply --metrics-out, or the metadata
    of a --trace-out Chrome trace) as Prometheus text on stdout."""
    from ..obs import render_text_from_snapshot

    try:
        with open(args.snapshot) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"metrics error: {e}", file=sys.stderr)
        return 1
    if isinstance(doc, dict) and "traceEvents" in doc:
        doc = (doc.get("metadata") or {}).get("metrics")
        if not doc:
            print("metrics error: trace file carries no metrics snapshot",
                  file=sys.stderr)
            return 1
    if not isinstance(doc, dict):
        print("metrics error: not a metrics snapshot", file=sys.stderr)
        return 1
    sys.stdout.write(render_text_from_snapshot(doc))
    return 0


def cmd_version(_args) -> int:
    print(f"Version: {__version__}")
    print(f"Commit: {COMMIT_ID}")
    return 0


def cmd_gen_doc(args) -> int:
    """cobra doc.GenMarkdownTree equivalent: one markdown page per command."""
    out = args.output_directory
    if not os.path.isdir(out):
        print(f"Invalid output directory({out})", file=sys.stderr)
        return 1
    parser = build_parser()
    pages = {"simon": parser}
    for action in parser._subparsers._group_actions:  # noqa: SLF001
        for name, sp in action.choices.items():
            pages[f"simon_{name.replace('-', '_')}"] = sp
    for page, p in pages.items():
        with open(os.path.join(out, f"{page}.md"), "w") as f:
            title = page.replace("_", " ")
            f.write(f"## {title}\n\n{p.description or p.format_usage()}\n\n")
            f.write("```\n" + p.format_help() + "```\n")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    _init_logging()
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv[:1] == ["lint"]:
        # Dispatch before argparse: REMAINDER would reject flags placed ahead
        # of the first path (`simon lint --format json pkg/`), and run_lint
        # owns its own --help.
        from ..analysis.runner import run_lint

        return run_lint(argv[1:])
    parser = build_parser()
    args = parser.parse_args(argv)
    from ..parity import cmd_parity

    handlers = {
        "apply": cmd_apply,
        "lint": cmd_lint,
        "metrics": cmd_metrics,
        "server": cmd_server,
        "version": cmd_version,
        "gen-doc": cmd_gen_doc,
        "parity": cmd_parity,
    }
    if not args.command:
        parser.print_help()
        return 0
    return handlers[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
