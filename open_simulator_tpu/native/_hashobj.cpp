// Canonical 128-bit hashing of JSON-ish Python object trees.
//
// The host-side encoder keys pod "scheduling groups" by a canonical form of the
// scheduling-relevant pod subtree (simulator/encode.py scheduling_signature). The
// pure-Python tuple-freeze walk is the hottest host path when ingesting large
// clusters of heterogeneous raw pods; this extension performs the same walk in
// C++ against the CPython API and returns a 128-bit digest as a Python int.
//
// Canonicalization rules (must match encode._freeze semantics):
// - dict: entries hashed in ascending key order (keys must be strings)
// - list/tuple: order-preserving
// - str/bytes: UTF-8 bytes
// - bool, int, float, None: tagged scalar values; bool is distinct from int,
//   and int vs float follow Python equality (1 == 1.0 → same hash, like a dict
// key's behavior in the frozen-tuple form? No: tuples distinguish by hash AND
// eq; (1,) == (1.0,) in Python, so the frozen forms collide there too — we hash
// numeric values by their float64 bits when exactly representable, else by
// decimal string, reproducing tuple equality).
//
// Digest: two independent 64-bit FNV-1a streams with different offset bases;
// collision probability is negligible (~2^-128) for group identity.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstdio>
#include <cstring>

namespace {

struct H128 {
    uint64_t a = 1469598103934665603ULL;   // FNV-1a offset basis
    uint64_t b = 14695981039346656037ULL;  // alternate stream
    inline void feed(const void* data, size_t n) {
        const unsigned char* p = static_cast<const unsigned char*>(data);
        for (size_t i = 0; i < n; i++) {
            a = (a ^ p[i]) * 1099511628211ULL;
            b = (b ^ p[i]) * 1099511628211ULL;
            b ^= b >> 29;  // extra mixing keeps the streams independent
        }
    }
    inline void tag(char t) { feed(&t, 1); }
};

int hash_obj(PyObject* o, H128& h);  // fwd

int hash_scalar_number(PyObject* o, H128& h) {
    // Python tuple equality treats 1 == 1.0 == True; we key booleans separately
    // ONLY when they appear as dict values/list items where _freeze kept the bool
    // object — but (True,) == (1,) in Python too, so bools hash as numbers.
    double d = PyFloat_AsDouble(o);
    if (d == -1.0 && PyErr_Occurred()) {
        PyErr_Clear();
        // huge int: fall back to decimal string
        PyObject* s = PyObject_Str(o);
        if (!s) return -1;
        Py_ssize_t n;
        const char* buf = PyUnicode_AsUTF8AndSize(s, &n);
        if (!buf) { Py_DECREF(s); return -1; }
        h.tag('I');
        h.feed(buf, static_cast<size_t>(n));
        Py_DECREF(s);
        return 0;
    }
    // exact float64 path; ints representable as float64 hash identically to the
    // equal float, matching tuple equality
    if (PyLong_Check(o)) {
        // verify exactness: round-trip compare
        PyObject* back = PyLong_FromDouble(d);
        if (!back) { PyErr_Clear(); h.tag('I'); return hash_scalar_number(o, h); }
        int eq = PyObject_RichCompareBool(o, back, Py_EQ);
        Py_DECREF(back);
        if (eq < 0) return -1;
        if (!eq) {
            PyObject* s = PyObject_Str(o);
            if (!s) return -1;
            Py_ssize_t n;
            const char* buf = PyUnicode_AsUTF8AndSize(s, &n);
            if (!buf) { Py_DECREF(s); return -1; }
            h.tag('I');
            h.feed(buf, static_cast<size_t>(n));
            Py_DECREF(s);
            return 0;
        }
    }
    h.tag('N');
    h.feed(&d, sizeof(d));
    return 0;
}

int hash_obj(PyObject* o, H128& h) {
    if (o == Py_None) {
        h.tag('0');
        return 0;
    }
    if (PyUnicode_Check(o)) {
        Py_ssize_t n;
        const char* buf = PyUnicode_AsUTF8AndSize(o, &n);
        if (!buf) return -1;
        h.tag('S');
        h.feed(buf, static_cast<size_t>(n));
        return 0;
    }
    if (PyBool_Check(o) || PyLong_Check(o) || PyFloat_Check(o)) {
        return hash_scalar_number(o, h);
    }
    if (PyBytes_Check(o)) {
        char* buf;
        Py_ssize_t n;
        if (PyBytes_AsStringAndSize(o, &buf, &n) < 0) return -1;
        h.tag('S');  // bytes canonicalize like their utf-8 string
        h.feed(buf, static_cast<size_t>(n));
        return 0;
    }
    if (PyList_Check(o) || PyTuple_Check(o)) {
        h.tag('L');
        PyObject* seq = PySequence_Fast(o, "sequence");
        if (!seq) return -1;
        Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
        for (Py_ssize_t i = 0; i < n; i++) {
            if (hash_obj(PySequence_Fast_GET_ITEM(seq, i), h) < 0) {
                Py_DECREF(seq);
                return -1;
            }
            h.tag(',');
        }
        Py_DECREF(seq);
        return 0;
    }
    if (PyDict_Check(o)) {
        h.tag('D');
        PyObject* keys = PyDict_Keys(o);
        if (!keys) return -1;
        if (PyList_Sort(keys) < 0) {
            Py_DECREF(keys);
            return -1;
        }
        Py_ssize_t n = PyList_GET_SIZE(keys);
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject* k = PyList_GET_ITEM(keys, i);
            PyObject* v = PyDict_GetItemWithError(o, k);
            if (!v) {
                Py_DECREF(keys);
                return -1;
            }
            if (hash_obj(k, h) < 0 || (h.tag(':'), hash_obj(v, h)) < 0) {
                Py_DECREF(keys);
                return -1;
            }
            h.tag(';');
        }
        Py_DECREF(keys);
        return 0;
    }
    PyErr_Format(PyExc_TypeError, "canon_hash: unsupported type %s",
                 Py_TYPE(o)->tp_name);
    return -1;
}

PyObject* canon_hash(PyObject* /*self*/, PyObject* arg) {
    H128 h;
    if (hash_obj(arg, h) < 0) return nullptr;
    // compose a 128-bit Python int: (a << 64) | b
    PyObject* pa = PyLong_FromUnsignedLongLong(h.a);
    PyObject* pb = PyLong_FromUnsignedLongLong(h.b);
    PyObject* sixty_four = PyLong_FromLong(64);
    PyObject* out = nullptr;
    if (pa && pb && sixty_four) {
        PyObject* shift = PyNumber_Lshift(pa, sixty_four);
        if (shift) {
            out = PyNumber_Or(shift, pb);
            Py_DECREF(shift);
        }
    }
    Py_XDECREF(pa);
    Py_XDECREF(pb);
    Py_XDECREF(sixty_four);
    return out;
}

PyMethodDef methods[] = {
    {"canon_hash", canon_hash, METH_O,
     "128-bit canonical hash of a JSON-ish object tree (dict keys sorted)."},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_hashobj",
    "Native canonical hashing for scheduling-group signatures.", -1, methods,
    nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit__hashobj(void) { return PyModule_Create(&moduledef); }
