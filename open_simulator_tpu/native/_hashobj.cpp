// Canonical 128-bit hashing of JSON-ish Python object trees.
//
// The host-side encoder keys pod "scheduling groups" by a canonical form of the
// scheduling-relevant pod subtree (simulator/encode.py scheduling_signature). The
// pure-Python tuple-freeze walk is the hottest host path when ingesting large
// clusters of heterogeneous raw pods; this extension performs the same walk in
// C++ against the CPython API and returns a 128-bit digest as a Python int.
//
// Canonicalization rules (must match encode._freeze semantics):
// - dict: entries hashed in ascending key order (keys must be strings)
// - list/tuple: order-preserving
// - str/bytes: UTF-8 bytes
// - bool, int, float, None: tagged scalar values; bool is distinct from int,
//   and int vs float follow Python equality (1 == 1.0 → same hash, like a dict
// key's behavior in the frozen-tuple form? No: tuples distinguish by hash AND
// eq; (1,) == (1.0,) in Python, so the frozen forms collide there too — we hash
// numeric values by their float64 bits when exactly representable, else by
// decimal string, reproducing tuple equality).
//
// Digest: two independent 64-bit FNV-1a streams with different offset bases;
// collision probability is negligible (~2^-128) for group identity.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct H128 {
    uint64_t a = 1469598103934665603ULL;   // FNV-1a offset basis
    uint64_t b = 14695981039346656037ULL;  // alternate stream
    inline void feed(const void* data, size_t n) {
        const unsigned char* p = static_cast<const unsigned char*>(data);
        for (size_t i = 0; i < n; i++) {
            a = (a ^ p[i]) * 1099511628211ULL;
            b = (b ^ p[i]) * 1099511628211ULL;
            b ^= b >> 29;  // extra mixing keeps the streams independent
        }
    }
    inline void tag(char t) { feed(&t, 1); }
};

int hash_obj(PyObject* o, H128& h);  // fwd

int hash_scalar_number(PyObject* o, H128& h) {
    // Python tuple equality treats 1 == 1.0 == True; we key booleans separately
    // ONLY when they appear as dict values/list items where _freeze kept the bool
    // object — but (True,) == (1,) in Python too, so bools hash as numbers.
    double d = PyFloat_AsDouble(o);
    if (d == -1.0 && PyErr_Occurred()) {
        PyErr_Clear();
        // huge int: fall back to decimal string
        PyObject* s = PyObject_Str(o);
        if (!s) return -1;
        Py_ssize_t n;
        const char* buf = PyUnicode_AsUTF8AndSize(s, &n);
        if (!buf) { Py_DECREF(s); return -1; }
        h.tag('I');
        h.feed(buf, static_cast<size_t>(n));
        Py_DECREF(s);
        return 0;
    }
    // exact float64 path; ints representable as float64 hash identically to the
    // equal float, matching tuple equality
    if (PyLong_Check(o)) {
        // verify exactness: round-trip compare
        PyObject* back = PyLong_FromDouble(d);
        if (!back) { PyErr_Clear(); h.tag('I'); return hash_scalar_number(o, h); }
        int eq = PyObject_RichCompareBool(o, back, Py_EQ);
        Py_DECREF(back);
        if (eq < 0) return -1;
        if (!eq) {
            PyObject* s = PyObject_Str(o);
            if (!s) return -1;
            Py_ssize_t n;
            const char* buf = PyUnicode_AsUTF8AndSize(s, &n);
            if (!buf) { Py_DECREF(s); return -1; }
            h.tag('I');
            h.feed(buf, static_cast<size_t>(n));
            Py_DECREF(s);
            return 0;
        }
    }
    h.tag('N');
    h.feed(&d, sizeof(d));
    return 0;
}

int hash_obj(PyObject* o, H128& h) {
    if (o == Py_None) {
        h.tag('0');
        return 0;
    }
    if (PyUnicode_Check(o)) {
        Py_ssize_t n;
        const char* buf = PyUnicode_AsUTF8AndSize(o, &n);
        if (!buf) return -1;
        h.tag('S');
        h.feed(buf, static_cast<size_t>(n));
        return 0;
    }
    if (PyBool_Check(o) || PyLong_Check(o) || PyFloat_Check(o)) {
        return hash_scalar_number(o, h);
    }
    if (PyBytes_Check(o)) {
        char* buf;
        Py_ssize_t n;
        if (PyBytes_AsStringAndSize(o, &buf, &n) < 0) return -1;
        h.tag('S');  // bytes canonicalize like their utf-8 string
        h.feed(buf, static_cast<size_t>(n));
        return 0;
    }
    if (PyList_Check(o) || PyTuple_Check(o)) {
        h.tag('L');
        PyObject* seq = PySequence_Fast(o, "sequence");
        if (!seq) return -1;
        Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
        for (Py_ssize_t i = 0; i < n; i++) {
            if (hash_obj(PySequence_Fast_GET_ITEM(seq, i), h) < 0) {
                Py_DECREF(seq);
                return -1;
            }
            h.tag(',');
        }
        Py_DECREF(seq);
        return 0;
    }
    if (PyDict_Check(o)) {
        h.tag('D');
        PyObject* keys = PyDict_Keys(o);
        if (!keys) return -1;
        if (PyList_Sort(keys) < 0) {
            Py_DECREF(keys);
            return -1;
        }
        Py_ssize_t n = PyList_GET_SIZE(keys);
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject* k = PyList_GET_ITEM(keys, i);
            PyObject* v = PyDict_GetItemWithError(o, k);
            if (!v) {
                Py_DECREF(keys);
                return -1;
            }
            if (hash_obj(k, h) < 0 || (h.tag(':'), hash_obj(v, h)) < 0) {
                Py_DECREF(keys);
                return -1;
            }
            h.tag(';');
        }
        Py_DECREF(keys);
        return 0;
    }
    PyErr_Format(PyExc_TypeError, "canon_hash: unsupported type %s",
                 Py_TYPE(o)->tp_name);
    return -1;
}

PyObject* compose_digest(const H128& h) {
    // compose a 128-bit Python int: (a << 64) | b
    PyObject* pa = PyLong_FromUnsignedLongLong(h.a);
    PyObject* pb = PyLong_FromUnsignedLongLong(h.b);
    PyObject* sixty_four = PyLong_FromLong(64);
    PyObject* out = nullptr;
    if (pa && pb && sixty_four) {
        PyObject* shift = PyNumber_Lshift(pa, sixty_four);
        if (shift) {
            out = PyNumber_Or(shift, pb);
            Py_DECREF(shift);
        }
    }
    Py_XDECREF(pa);
    Py_XDECREF(pb);
    Py_XDECREF(sixty_four);
    return out;
}

PyObject* canon_hash(PyObject* /*self*/, PyObject* arg) {
    H128 h;
    if (hash_obj(arg, h) < 0) return nullptr;
    return compose_digest(h);
}

// ---------------------------------------------------------------------------
// pod_sig(pod, anno_keys): the scheduling-signature extraction + hash in one
// call. Hash-identical to canon_hash() over the tuple the Python caller used
// to build (simulator/encode.py scheduling_signature's native path):
//
//   ( namespace_of(pod), labels, nodeSelector, affinity, tolerations,
//     topologySpreadConstraints, nodeName, hostNetwork, containers,
//     initContainers, overhead, sorted({ref.kind}), [annotations[k]...] )
//
// Building that tuple cost ~15 dict lookups + allocations per pod in Python —
// the hottest line of the 100k-pod headline bench. Unsupported/exotic values
// raise TypeError, and the caller falls back to the computed-tuple path.

// borrowed ref to d[k], or nullptr when d is not a dict / key missing
inline PyObject* dget(PyObject* d, PyObject* key) {
    if (!d || !PyDict_Check(d)) return nullptr;
    return PyDict_GetItemWithError(d, key);  // clears no errors; caller checks
}

// hash one tuple element (missing → None), followed by the ',' separator
inline int hash_elem(PyObject* v, H128& h) {
    if (hash_obj(v ? v : Py_None, h) < 0) return -1;
    h.tag(',');
    return 0;
}

struct Interned {
    PyObject *metadata, *spec, *nmspace, *labels, *annotations, *nodeSelector,
        *affinity, *tolerations, *topologySpreadConstraints, *nodeName,
        *hostNetwork, *containers, *initContainers, *overhead, *ownerReferences,
        *kind;
    bool ok;
};

Interned& interned() {
    static Interned s = [] {
        Interned i{};
        i.metadata = PyUnicode_InternFromString("metadata");
        i.spec = PyUnicode_InternFromString("spec");
        i.nmspace = PyUnicode_InternFromString("namespace");
        i.labels = PyUnicode_InternFromString("labels");
        i.annotations = PyUnicode_InternFromString("annotations");
        i.nodeSelector = PyUnicode_InternFromString("nodeSelector");
        i.affinity = PyUnicode_InternFromString("affinity");
        i.tolerations = PyUnicode_InternFromString("tolerations");
        i.topologySpreadConstraints =
            PyUnicode_InternFromString("topologySpreadConstraints");
        i.nodeName = PyUnicode_InternFromString("nodeName");
        i.hostNetwork = PyUnicode_InternFromString("hostNetwork");
        i.containers = PyUnicode_InternFromString("containers");
        i.initContainers = PyUnicode_InternFromString("initContainers");
        i.overhead = PyUnicode_InternFromString("overhead");
        i.ownerReferences = PyUnicode_InternFromString("ownerReferences");
        i.kind = PyUnicode_InternFromString("kind");
        i.ok = i.metadata && i.spec && i.nmspace && i.labels && i.annotations &&
               i.nodeSelector && i.affinity && i.tolerations &&
               i.topologySpreadConstraints && i.nodeName && i.hostNetwork &&
               i.containers && i.initContainers && i.overhead &&
               i.ownerReferences && i.kind;
        return i;
    }();
    return s;
}

PyObject* pod_sig(PyObject* /*self*/, PyObject* args) {
    PyObject* pod;
    PyObject* anno_keys;  // sequence of annotation-key strings
    if (!PyArg_ParseTuple(args, "OO", &pod, &anno_keys)) return nullptr;
    Interned& I = interned();
    if (!I.ok) return PyErr_NoMemory();
    if (!PyDict_Check(pod)) {
        PyErr_SetString(PyExc_TypeError, "pod_sig: pod must be a dict");
        return nullptr;
    }

    PyObject* md = dget(pod, I.metadata);
    PyObject* spec = dget(pod, I.spec);
    if (PyErr_Occurred()) return nullptr;
    // `or {}` semantics: falsy (None/""/[]) → missing; a truthy non-dict is a
    // malformed pod the Python extraction would have errored on — raise, so
    // the caller's computed-tuple fallback surfaces the object loudly
    if (md && !PyDict_Check(md)) {
        int t = PyObject_IsTrue(md);
        if (t < 0) return nullptr;
        if (t) {
            PyErr_SetString(PyExc_TypeError, "pod_sig: metadata is not a dict");
            return nullptr;
        }
        md = nullptr;
    }
    if (spec && !PyDict_Check(spec)) {
        int t = PyObject_IsTrue(spec);
        if (t < 0) return nullptr;
        if (t) {
            PyErr_SetString(PyExc_TypeError, "pod_sig: spec is not a dict");
            return nullptr;
        }
        spec = nullptr;
    }

    H128 h;
    h.tag('L');  // the outer tuple

    // 1. namespace_of: metadata.namespace if truthy, else "default"
    PyObject* ns = dget(md, I.nmspace);
    if (PyErr_Occurred()) return nullptr;
    int truthy = ns ? PyObject_IsTrue(ns) : 0;
    if (truthy < 0) return nullptr;
    if (!truthy) {
        h.tag('S');
        h.feed("default", 7);
        h.tag(',');
    } else if (hash_elem(ns, h) < 0) {
        return nullptr;
    }

    // 2-11. raw subtrees, in the exact tuple order
    PyObject* fields[10] = {
        dget(md, I.labels),
        dget(spec, I.nodeSelector),
        dget(spec, I.affinity),
        dget(spec, I.tolerations),
        dget(spec, I.topologySpreadConstraints),
        dget(spec, I.nodeName),
        dget(spec, I.hostNetwork),
        dget(spec, I.containers),
        dget(spec, I.initContainers),
        dget(spec, I.overhead),
    };
    if (PyErr_Occurred()) return nullptr;
    for (PyObject* f : fields) {
        if (hash_elem(f, h) < 0) return nullptr;
    }

    // 12. sorted unique owner-reference kinds (UTF-8 byte order == code-point
    // order, so std::string sorting matches Python's str sorting)
    PyObject* owners = dget(md, I.ownerReferences);
    if (PyErr_Occurred()) return nullptr;
    h.tag('L');
    if (owners && owners != Py_None) {
        PyObject* seq = PySequence_Fast(owners, "ownerReferences");
        if (!seq) return nullptr;
        Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
        std::vector<std::string> kinds;
        kinds.reserve(static_cast<size_t>(n));
        for (Py_ssize_t k = 0; k < n; k++) {
            PyObject* ref = PySequence_Fast_GET_ITEM(seq, k);
            if (!PyDict_Check(ref)) {
                Py_DECREF(seq);
                PyErr_SetString(PyExc_TypeError,
                                "pod_sig: ownerReferences item is not a dict");
                return nullptr;
            }
            PyObject* kind = dget(ref, I.kind);
            if (PyErr_Occurred()) { Py_DECREF(seq); return nullptr; }
            if (kind == nullptr || kind == Py_None) {
                // r.get("kind", "") — missing defaults to ""; an explicit None
                // would make Python's sorted() raise TypeError, so do the same
                if (kind == Py_None) {
                    Py_DECREF(seq);
                    PyErr_SetString(PyExc_TypeError,
                                    "pod_sig: ownerReference kind is None");
                    return nullptr;
                }
                kinds.emplace_back();
            } else {
                Py_ssize_t sn;
                const char* sb = PyUnicode_AsUTF8AndSize(kind, &sn);
                if (!sb) { Py_DECREF(seq); return nullptr; }
                kinds.emplace_back(sb, static_cast<size_t>(sn));
            }
        }
        Py_DECREF(seq);
        std::sort(kinds.begin(), kinds.end());
        kinds.erase(std::unique(kinds.begin(), kinds.end()), kinds.end());
        for (const std::string& ks : kinds) {
            h.tag('S');
            h.feed(ks.data(), ks.size());
            h.tag(',');
        }
    }
    h.tag(',');

    // 13. [annotations.get(k) for k in anno_keys]
    PyObject* anns = dget(md, I.annotations);
    if (PyErr_Occurred()) return nullptr;
    if (anns && !PyDict_Check(anns)) {
        int t = PyObject_IsTrue(anns);
        if (t < 0) return nullptr;
        if (t) {
            PyErr_SetString(PyExc_TypeError, "pod_sig: annotations is not a dict");
            return nullptr;
        }
        anns = nullptr;
    }
    PyObject* keys = PySequence_Fast(anno_keys, "anno_keys");
    if (!keys) return nullptr;
    Py_ssize_t nk = PySequence_Fast_GET_SIZE(keys);
    h.tag('L');
    for (Py_ssize_t k = 0; k < nk; k++) {
        PyObject* v = dget(anns, PySequence_Fast_GET_ITEM(keys, k));
        if (PyErr_Occurred()) { Py_DECREF(keys); return nullptr; }
        if (hash_elem(v, h) < 0) { Py_DECREF(keys); return nullptr; }
    }
    Py_DECREF(keys);
    h.tag(',');

    return compose_digest(h);
}

PyMethodDef methods[] = {
    {"canon_hash", canon_hash, METH_O,
     "128-bit canonical hash of a JSON-ish object tree (dict keys sorted)."},
    {"pod_sig", pod_sig, METH_VARARGS,
     "pod_sig(pod, anno_keys): scheduling-signature digest of a pod dict — "
     "hash-identical to canon_hash over the extracted signature tuple."},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_hashobj",
    "Native canonical hashing for scheduling-group signatures.", -1, methods,
    nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit__hashobj(void) { return PyModule_Create(&moduledef); }
