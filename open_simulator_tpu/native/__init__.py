"""Native (C++) host runtime components, with transparent Python fallbacks.

The compute path of this framework is XLA-compiled (ops/kernels.py); this package
holds the native pieces of the HOST runtime around it. Currently:

- `_hashobj.canon_hash(obj)` — 128-bit canonical hash of JSON-ish object trees,
  used to key pod scheduling groups (simulator/encode.py). Compiled lazily from
  `_hashobj.cpp` with the toolchain's C++ compiler on first use; results are
  cached next to the source. Set SIMON_NO_NATIVE=1 to force the Python fallback.

Build strategy: no pybind11 in this environment, so the extension uses the raw
CPython C API and is compiled with a direct compiler invocation (no setuptools
temp-dir dance), which keeps cold-start under a second.
"""

from __future__ import annotations

import importlib.util
import logging
import os
import subprocess
import sysconfig
from typing import Callable, Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "_hashobj.cpp")
_SO = os.path.join(_DIR, "_hashobj" + (sysconfig.get_config_var("EXT_SUFFIX") or ".so"))

_canon_hash: Optional[Callable] = None
_pod_sig: Optional[Callable] = None
_tried = False


def _build() -> bool:
    cc = os.environ.get("CXX", "g++")
    include = sysconfig.get_paths()["include"]
    cmd = [
        cc, "-O2", "-shared", "-fPIC", "-std=c++17",
        f"-I{include}", _SRC, "-o", _SO,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        logging.debug("native build failed to run: %s", e)
        return False
    if proc.returncode != 0:
        logging.debug("native build failed:\n%s", proc.stderr)
        return False
    return True


def _load():
    spec = importlib.util.spec_from_file_location("open_simulator_tpu.native._hashobj", _SO)
    if spec is None or spec.loader is None:
        return None
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _ensure_built() -> None:
    global _canon_hash, _pod_sig, _tried
    if _tried:
        return
    _tried = True
    if os.environ.get("SIMON_NO_NATIVE"):
        return
    try:
        # <= so equal mtimes (e.g. both stamped by a checkout) rebuild: loading a
        # stale binary would silently change signature semantics
        stale = (not os.path.exists(_SO)
                 or os.path.getmtime(_SO) <= os.path.getmtime(_SRC))
        if stale and not _build():
            return
        mod = _load()
        if mod is not None:
            _canon_hash = mod.canon_hash
            _pod_sig = getattr(mod, "pod_sig", None)
    except Exception as e:  # any failure → Python fallback
        logging.debug("native hash unavailable: %s", e)
        _canon_hash = _pod_sig = None


def canon_hash_fn() -> Optional[Callable]:
    """The native hash function, building it on first call; None when unavailable
    (missing compiler, SIMON_NO_NATIVE=1, ...)."""
    _ensure_built()
    return _canon_hash


def pod_sig_fn() -> Optional[Callable]:
    """The native one-call pod-signature function (extraction + hash); None when
    the extension is unavailable."""
    _ensure_built()
    return _pod_sig
