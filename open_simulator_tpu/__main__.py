"""`python -m open_simulator_tpu` → the simon CLI."""

import sys

from .cli.main import main

sys.exit(main())
