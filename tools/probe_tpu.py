"""Background TPU-tunnel probe logger.

The accelerator tunnel can wedge for hours (jax.devices() blocks forever in
backend init — see utils/devices.py probe_default_backend). This script probes
it in a subprocess on an interval and appends one JSON line per attempt to
TPU_PROBE_LOG.jsonl, producing a round-long record of tunnel availability:
either the evidence that on-chip numbers were impossible, or the signal that
the tunnel recovered and the bench should be re-run on the device.

Protocol: each probe attempt holds the `.tpu_lock` pidfile (stale dead-PID
locks are stolen); if a live process — the bench — holds it, the attempt is
skipped entirely. Two concurrent clients can wedge the tunnel, which is the
failure being monitored in the first place.

Usage: python tools/probe_tpu.py [--interval 600] [--timeout 120] [--once]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "TPU_PROBE_LOG.jsonl")
LOCK = os.path.join(REPO, ".tpu_lock")
sys.path.insert(0, REPO)

from open_simulator_tpu.utils.devices import (  # noqa: E402
    acquire_tpu_lock,
    probe_default_backend,
    release_tpu_lock,
)


def probe_once(timeout: float) -> dict:
    """One lock-guarded subprocess probe. Never touches the backend in-process.

    The logger's OWN probes bypass the cooldown window (its whole job is to
    keep probing), but the outcome is persisted at the SHARED state path
    (OPEN_SIMULATOR_PROBE_STATE, default under the XDG cache — the same
    path every probe_default_backend caller reads) so every other run —
    CLI, server, bench — honors the cooldown and skips straight to
    cpu-fallback while the tunnel stays wedged."""
    if not acquire_tpu_lock(LOCK):
        return {"ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                "outcome": "skipped-lock", "elapsed_s": 0.0}
    os.environ["OPEN_SIMULATOR_PROBE_COOLDOWN_S"] = "0"
    try:
        _, rec = probe_default_backend(timeout)
        return rec
    finally:
        release_tpu_lock(LOCK)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=600.0)
    ap.add_argument("--timeout", type=float, default=120.0)
    ap.add_argument("--once", action="store_true")
    args = ap.parse_args()
    while True:
        rec = probe_once(args.timeout)
        with open(LOG, "a") as f:
            f.write(json.dumps(rec) + "\n")
        print(json.dumps(rec), flush=True)
        if args.once:
            break
        time.sleep(args.interval)


if __name__ == "__main__":
    main()
