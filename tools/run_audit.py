#!/usr/bin/env python3
"""CI entry for simonaudit: certificate-check every registered hot kernel.

    python tools/run_audit.py --check          # the CI gate (default mode)
    python tools/run_audit.py --update         # regenerate tests/golden/audit/

Equivalent to `python -m open_simulator_tpu.cli audit` with the repo-root
golden directory; defaults to --check so a bare CI invocation is the gate.
The virtual-CPU device flag is set here, before jax can initialize."""

from __future__ import annotations

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from open_simulator_tpu.utils.devices import (  # noqa: E402
    force_cpu_platform, request_cpu_devices)


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    if not any(a in ("--check", "--update", "--help", "-h") for a in args):
        args.insert(0, "--check")
    request_cpu_devices(8)
    force_cpu_platform()
    from open_simulator_tpu.analysis.hlo import run_audit

    return run_audit(args)


if __name__ == "__main__":
    sys.exit(main())
