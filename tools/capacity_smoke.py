#!/usr/bin/env python
"""CI smoke for the incremental capacity planner (fast, CPU-only).

Runs a small synthetic add-node search through CapacityPlanner and asserts the
properties the bench relies on, so incremental-path regressions fail in CI
instead of in the bench:

- the search finds the expected minimal node count;
- it runs on the incremental (encode-once) path with pod encoding paid
  exactly once and a bounded candidate/dispatch budget;
- the answer agrees with the fresh-Simulator probe at n and fails at n-1.

Prints one JSON line with the measured numbers.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["MaxCPU"] = "60"

from open_simulator_tpu.apply.applier import CapacityPlanner  # noqa: E402
from open_simulator_tpu.utils.synth import synth_node, synth_pod  # noqa: E402

# 2000 pods x 100m on 8x32-core base nodes under a 60% MaxCPU envelope:
# int(200000 / alloc * 100) <= 60 needs alloc >= 333,334m -> 11 nodes -> +3.
EXPECTED_N = 3
MAX_PROBES = 40
MAX_DISPATCHES = 6


def main() -> int:
    base = [synth_node(i) for i in range(8)]
    template = synth_node(0)
    pods = [synth_pod(i) for i in range(2000)]
    t0 = time.perf_counter()
    planner = CapacityPlanner(base, template, pods)
    found, n, _hist = planner.search()
    dt = time.perf_counter() - t0
    row = {
        "metric": "capacity_smoke_2k_pods",
        "found": found,
        "nodes_added": n,
        "wall_s": round(dt, 3),
        **{k: planner.stats.get(k)
           for k in ("path", "probes", "dispatches", "encodes", "encode_s")},
    }
    print(json.dumps(row), flush=True)
    assert found, "search did not converge"
    assert n == EXPECTED_N, f"nodes_added {n} != expected {EXPECTED_N}"
    assert planner.stats["path"] == "incremental", planner.stats
    assert planner.stats["encodes"] == 1, "pod encoding must run exactly once"
    assert planner.stats["probes"] <= MAX_PROBES, planner.stats
    assert planner.stats["dispatches"] <= MAX_DISPATCHES, planner.stats
    ok_n, _ = planner.probe(n)
    assert ok_n, "fresh probe disagrees at n"
    ok_prev, _ = planner.probe(n - 1)
    assert not ok_prev, "answer is not minimal"
    return 0


if __name__ == "__main__":
    sys.exit(main())
