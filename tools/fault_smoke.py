#!/usr/bin/env python
"""CI smoke for simonfault (fast, CPU-only).

For EVERY named fault site, one bounded run asserting the acceptance
criteria of the robustness layer:

- engine sites (encode / to_device / dispatch / fetch / commit /
  preempt_evict): the injected failure surfaces as a clean exception, the
  census and the caller's pod dicts are bit-identical to the pre-call state,
  and the commits − rollbacks − victims metric reconciliation is unchanged;
- live_get: an injected transient fault is retried per the policy — the
  retry counter moves by exactly the injected-fault count and the request
  then succeeds;
- every seeded plan replays an IDENTICAL injection trace on a second
  identical run (bit-for-bit reproducibility), and the retry/backoff
  schedule is a pure function of the policy.

Plus a server drain smoke: start a server, park a slow request in flight,
deliver a real SIGTERM, and assert the in-flight request completes 200 while
requests arriving mid-drain get structured 503s.

simonguard containment sites (watchdog_wedge / oom_to_device / oom_dispatch /
journal_write) assert the CONTAINMENT criteria instead of clean failure: an
injected fault produces (a) final placements identical to the fault-free run
after bisection/failover/resume and (b) a replay-equal injection + guard-event
trace across two identical runs. The journal half additionally SIGKILLs a
capacity search mid-probe in a child process and asserts the resumed search
reaches the same nodes_added without re-running the journaled probes.

Prints one JSON line with the measured numbers.
"""

import copy
import json
import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from open_simulator_tpu.obs import REGISTRY  # noqa: E402
from open_simulator_tpu.resilience import (  # noqa: E402
    FaultPlan,
    FaultSpec,
    RetryPolicy,
    guard,
    installed,
)
from open_simulator_tpu.simulator.encode import scheduling_signature  # noqa: E402
from open_simulator_tpu.simulator.engine import Simulator  # noqa: E402
from open_simulator_tpu.utils.synth import synth_cluster  # noqa: E402

ENGINE_SITES = ("encode", "to_device", "dispatch", "fetch", "commit")


def _sum(prefix):
    return sum(v for k, v in REGISTRY.values().items() if k.startswith(prefix))


def reconciliation():
    return (_sum("simon_commits_total")
            - _sum("simon_commit_rollbacks_total")
            - _sum("simon_preemption_victims_total"))


def census(sim):
    out = {}
    for i, nps in enumerate(sim.pods_on_node):
        for p in nps:
            k = (i, scheduling_signature(p))
            out[k] = out.get(k, 0) + 1
    return out


def engine_site_sweep(row):
    nodes, pods = synth_cluster(16, 120)
    traces = {}
    for site in ENGINE_SITES:
        for rep in range(2):  # twice: the replay-equality criterion
            sim = Simulator(copy.deepcopy(nodes))
            p = copy.deepcopy(pods)
            pre_pods = copy.deepcopy(p)
            pre_recon = reconciliation()
            # commit at arrival 40: a mid-batch partial commit must roll back
            # the 39 already-committed pods; other sites fire on first arrival
            plan = FaultPlan([FaultSpec(site, 40 if site == "commit" else 1)])
            raised = False
            try:
                with installed(plan):
                    sim.schedule_pods(p)
            except Exception:
                raised = True
            assert raised, f"{site}: injected fault did not surface"
            assert census(sim) == {}, f"{site}: census residue after rollback"
            assert p == pre_pods, f"{site}: pod dicts mutated after rollback"
            assert reconciliation() == pre_recon, \
                f"{site}: commits-rollbacks-victims drifted"
            assert plan.trace, f"{site}: no injection recorded"
            traces.setdefault(site, []).append(list(plan.trace))
        assert traces[site][0] == traces[site][1], \
            f"{site}: replay produced a different trace"
    row["engine_sites"] = len(ENGINE_SITES)


def preempt_evict_smoke(row):
    def node(name):
        return {"apiVersion": "v1", "kind": "Node",
                "metadata": {"name": name, "labels": {}},
                "status": {"allocatable": {"cpu": "2000m", "memory": str(4 << 30),
                                           "pods": "10"}}}

    def pod(name, cpu, mem, prio):
        return {"apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": name, "namespace": "default"},
                "spec": {"priority": prio, "containers": [{
                    "name": "c", "image": "x",
                    "resources": {"requests": {"cpu": cpu, "memory": mem}}}]}}

    pods = [pod("low-0", "900m", str(1 << 30), 0),
            pod("low-1", "900m", str(1 << 30), 0),
            pod("high-0", "1800m", str(2 << 30), 100)]
    pre_recon = reconciliation()
    sim = Simulator([node("n1")])
    p = copy.deepcopy(pods)
    pre_pods = copy.deepcopy(p)
    raised = False
    try:
        with installed(FaultPlan([FaultSpec("preempt_evict", 1)])):
            sim.schedule_pods(p)
    except Exception:
        raised = True
    assert raised, "preempt_evict fault did not surface"
    assert census(sim) == {} and sim.preempted == []
    assert p == pre_pods, "preempt_evict rollback left pod residue"
    assert reconciliation() == pre_recon
    # the same simulator then preempts normally
    sim.schedule_pods(p)
    assert len(sim.preempted) == 2
    row["preempt_evict_ok"] = True


def live_get_smoke(row):
    """Injected transient live_get fault: retried per policy, counters match
    the plan, and the seeded backoff schedule is replay-identical."""
    import http.server
    import yaml

    from open_simulator_tpu.simulator.live import KubeClient

    class H(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            body = json.dumps({"kind": "NodeList", "apiVersion": "v1",
                               "items": [{"metadata": {"name": "n0"}}],
                               "metadata": {}}).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    port = httpd.server_address[1]
    cfg = {"current-context": "c",
           "contexts": [{"name": "c", "context": {"cluster": "cl", "user": "u"}}],
           "clusters": [{"name": "cl",
                         "cluster": {"server": f"http://127.0.0.1:{port}"}}],
           "users": [{"name": "u", "user": {}}]}
    path = "/tmp/fault_smoke_kubeconfig.yaml"
    with open(path, "w") as f:
        yaml.safe_dump(cfg, f)
    try:
        policy = RetryPolicy(max_attempts=3, base=0.001, cap=0.01,
                             jitter=0.2, seed=7)
        assert policy.schedule() == policy.schedule(), "backoff not pure"
        client = KubeClient(path)
        client.retry = policy
        plan = FaultPlan([FaultSpec("live_get", 1, "transient"),
                          FaultSpec("live_get", 2, "transient")])
        r0 = _sum("simon_retries_total")
        f0 = _sum("simon_faults_injected_total")
        with installed(plan):
            nodes = client.list("/api/v1/nodes")
        assert len(nodes) == 1, "list failed despite retries"
        retries = _sum("simon_retries_total") - r0
        injected = _sum("simon_faults_injected_total") - f0
        assert injected == 2, f"expected 2 injected faults, saw {injected}"
        assert retries == 2, f"retry counters must match the plan, saw {retries}"
        assert plan.trace == [("live_get", 1, "transient"),
                              ("live_get", 2, "transient")]
        row["live_get_retries"] = retries
    finally:
        httpd.shutdown()
        os.unlink(path)


def server_drain_smoke(row):
    """Real-SIGTERM drain: in-flight completes 200, mid-drain requests 503."""
    import http.client
    import signal
    import time

    from open_simulator_tpu.core.types import ResourceTypes
    from open_simulator_tpu.server.http import ClusterSnapshot, Server

    release = threading.Event()
    entered = threading.Event()

    def slow_snapshot():
        entered.set()
        assert release.wait(timeout=30)
        node = {"apiVersion": "v1", "kind": "Node",
                "metadata": {"name": "n1", "labels": {}},
                "status": {"allocatable": {"cpu": "8", "memory": str(16 << 30),
                                           "pods": "110"}}}
        return ClusterSnapshot(ResourceTypes(nodes=[node]), [], [], [])

    server = Server(snapshot_fn=slow_snapshot)
    httpd = server.build_httpd(port=0, host="127.0.0.1")
    port = httpd.server_address[1]
    server.install_sigterm_handler(drain_deadline=20.0)
    serve_t = threading.Thread(target=httpd.serve_forever, daemon=True)
    serve_t.start()

    results = {}

    def inflight():
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        deploy = {"apiVersion": "apps/v1", "kind": "Deployment",
                  "metadata": {"name": "web", "namespace": "default"},
                  "spec": {"replicas": 1,
                           "selector": {"matchLabels": {"app": "web"}},
                           "template": {
                               "metadata": {"labels": {"app": "web"}},
                               "spec": {"containers": [{
                                   "name": "c", "image": "x",
                                   "resources": {"requests": {
                                       "cpu": "1", "memory": "1Gi"}}}]}}}}
        conn.request("POST", "/api/deploy-apps",
                     body=json.dumps({"deployments": [deploy]}),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        results["inflight"] = resp.status
        resp.read()

    t = threading.Thread(target=inflight)
    t.start()
    assert entered.wait(timeout=15), "slow request never reached the handler"

    os.kill(os.getpid(), signal.SIGTERM)  # the real signal path
    deadline = time.monotonic() + 10
    while not server.draining and time.monotonic() < deadline:
        time.sleep(0.01)
    assert server.draining, "SIGTERM did not start the drain"

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", "/healthz")
    resp = conn.getresponse()
    body = json.loads(resp.read())
    assert resp.status == 503 and "draining" in body["error"], \
        f"mid-drain request got {resp.status}: {body}"

    release.set()
    t.join(timeout=30)
    serve_t.join(timeout=30)
    assert results.get("inflight") == 200, \
        f"in-flight request did not complete cleanly: {results}"
    assert not serve_t.is_alive(), "listener still running after drain"
    signal.signal(signal.SIGTERM, signal.SIG_DFL)
    row["drain_ok"] = True


# --------------------------------------------------------------- simonguard --

GUARD_SITES = ("watchdog_wedge", "oom_to_device", "oom_dispatch")


def guard_site_sweep(row):
    """Containment criteria for the guard sites: the faulted run SUCCEEDS,
    converges bit-for-bit with the fault-free baseline, and two identical
    runs produce identical injection + guard-event traces."""
    nodes, pods = synth_cluster(16, 120)
    sim0 = Simulator(copy.deepcopy(nodes))
    failed0 = len(sim0.schedule_pods(copy.deepcopy(pods)))
    baseline = census(sim0)
    for site in GUARD_SITES:
        traces = []
        for rep in range(2):  # replay-equality criterion
            guard.reset_for_tests()
            sim = Simulator(copy.deepcopy(nodes))
            plan = FaultPlan([FaultSpec(site, 1)])
            with installed(plan):
                failed = sim.schedule_pods(copy.deepcopy(pods))
            assert plan.trace, f"{site}: no injection recorded"
            assert census(sim) == baseline, f"{site}: placements diverged"
            assert len(failed) == failed0, f"{site}: failure count diverged"
            assert guard.events(), f"{site}: containment left no event trace"
            if site == "watchdog_wedge":
                assert sim.backend_path[-1] == "cpu" and len(sim.backend_path) == 2,                     f"{site}: failover missing from backend_path"
            traces.append((list(plan.trace), guard.events()))
        assert traces[0] == traces[1], f"{site}: replay produced a different trace"
    guard.reset_for_tests()
    row["guard_sites"] = len(GUARD_SITES)


def _journal_workload():
    """lb-inexact fragmentation search (several probe rounds → several
    journal records): 10 pods of 3000m on 4000m nodes, answer 8 added."""
    def node(name):
        return {"apiVersion": "v1", "kind": "Node",
                "metadata": {"name": name, "labels": {}},
                "status": {"allocatable": {"cpu": "4000m",
                                           "memory": str(8 << 30),
                                           "pods": "20"}}}

    def pod(name):
        return {"apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": name, "namespace": "default"},
                "spec": {"containers": [{
                    "name": "c", "image": "x",
                    "resources": {"requests": {"cpu": "3000m",
                                               "memory": str(128 << 20)}}}]}}

    base = [node(f"b{i}") for i in range(2)]
    return base, node("tmpl"), [pod(f"w{j}") for j in range(10)]


def journal_fault_smoke(row):
    """journal_write containment: the injected fault kills the search, the
    journal's valid prefix resumes to the fault-free answer, and the
    injection trace replays identically."""
    from open_simulator_tpu.apply.applier import CapacityPlanner

    base, template, pods = _journal_workload()
    p0 = CapacityPlanner(base, template, copy.deepcopy(pods))
    found0, n0, _ = p0.search()
    assert found0

    traces = []
    for rep in range(2):
        guard.reset_for_tests()
        path = f"/tmp/fault_smoke_journal_{rep}.jsonl"
        if os.path.exists(path):
            os.unlink(path)
        p1 = CapacityPlanner(base, template, copy.deepcopy(pods))
        p1.attach_journal(path)
        plan = FaultPlan([FaultSpec("journal_write", 2)])
        raised = False
        try:
            with installed(plan):
                p1.search()
        except Exception:
            raised = True
        assert raised, "journal_write fault did not surface"
        assert plan.trace, "no injection recorded"
        traces.append(list(plan.trace))
        p2 = CapacityPlanner(base, template, copy.deepcopy(pods))
        p2.attach_journal(path)
        found2, n2, _ = p2.search()
        assert (found2, n2) == (found0, n0),             f"resumed search diverged: {(found2, n2)} != {(found0, n0)}"
        assert p2.stats["journal_hits"] >= 1, "resume replayed no verdicts"
        os.unlink(path)
    assert traces[0] == traces[1], "journal_write trace not replay-equal"
    guard.reset_for_tests()
    row["journal_fault_ok"] = True


def journal_crash_resume_smoke(row):
    """Real-SIGKILL crash-resume: a child process runs the search with a
    journal and SIGKILLs itself after the 2nd fsync'd verdict; the resumed
    search reaches the same nodes_added with the completed probes replayed
    from the journal, not re-run."""
    import signal
    import subprocess

    from open_simulator_tpu.apply.applier import CapacityPlanner

    base, template, pods = _journal_workload()
    p0 = CapacityPlanner(base, template, copy.deepcopy(pods))
    found0, n0, _ = p0.search()
    fresh_dispatches = p0.stats["dispatches"]

    path = "/tmp/fault_smoke_journal_kill.jsonl"
    if os.path.exists(path):
        os.unlink(path)
    child = r"""
import os, signal, sys
sys.path.insert(0, %r)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from open_simulator_tpu.resilience import guard
from open_simulator_tpu.apply.applier import CapacityPlanner
import tools.fault_smoke as fs

base, template, pods = fs._journal_workload()
real = guard.SearchJournal.record
state = {"n": 0}
def record(self, n, ok, nf):
    real(self, n, ok, nf)          # fsync'd BEFORE the kill
    state["n"] += 1
    if state["n"] >= 2:
        os.kill(os.getpid(), signal.SIGKILL)
guard.SearchJournal.record = record
p = CapacityPlanner(base, template, pods)
p.attach_journal(%r)
p.search()
print("UNREACHABLE")
""" % (os.path.dirname(os.path.dirname(os.path.abspath(__file__))), path)
    proc = subprocess.run([sys.executable, "-c", child],
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == -signal.SIGKILL,         f"child did not die by SIGKILL: rc={proc.returncode} {proc.stderr[-400:]}"
    assert "UNREACHABLE" not in proc.stdout

    p2 = CapacityPlanner(base, template, copy.deepcopy(pods))
    p2.attach_journal(path)
    found2, n2, _ = p2.search()
    assert (found2, n2) == (found0, n0),         f"crash-resumed search diverged: {(found2, n2)} != {(found0, n0)}"
    assert p2.stats["journal_hits"] >= 2,         "the SIGKILL'd probes were not replayed from the journal"
    assert p2.stats["dispatches"] < fresh_dispatches,         "resume re-ran every probe (journal saved nothing)"
    os.unlink(path)
    row["journal_crash_resume"] = {"nodes_added": n2,
                                   "replayed": p2.stats["journal_hits"],
                                   "dispatches": p2.stats["dispatches"],
                                   "fresh_dispatches": fresh_dispatches}


def main() -> int:
    row = {"metric": "fault_smoke"}
    engine_site_sweep(row)
    preempt_evict_smoke(row)
    guard_site_sweep(row)
    journal_fault_smoke(row)
    journal_crash_resume_smoke(row)
    live_get_smoke(row)
    server_drain_smoke(row)
    row["faults_injected_total"] = _sum("simon_faults_injected_total")
    print(json.dumps(row), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
