#!/usr/bin/env python
"""CI smoke for the hard-predicate fast path (fast, CPU-only).

Runs a scaled-down version of the hard-predicate bench workload (taints +
tolerations + hostname self-anti-affinity + zone-level DoNotSchedule spread,
utils/synth.py block structure) through a waves-on and a waves-off Simulator
and asserts the properties the bench acceptance relies on, so affinity-wave
regressions fail in CI instead of in the bench:

- the zone-spread groups actually route onto schedule_affinity_wave
  ('affinity' segments — not silently back to group-serial or serial);
- placement census agreement vs the serial scan is >= 99% (it is expected to
  be exactly 1.0; the bench gate is 0.99);
- every pod lands or fails identically often on both paths (total parity).

Prints one JSON line with the measured numbers.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import copy  # noqa: E402

from open_simulator_tpu.simulator.encode import scheduling_signature  # noqa: E402
from open_simulator_tpu.simulator.engine import Simulator  # noqa: E402
from open_simulator_tpu.utils.synth import synth_cluster  # noqa: E402

N_NODES = 120
N_PODS = 1200
MIN_AGREEMENT = 0.99


def census(sim, failed):
    placed = {}
    for i, node_pods in enumerate(sim.pods_on_node):
        for p in node_pods:
            key = (i, scheduling_signature(p))
            placed[key] = placed.get(key, 0) + 1
    fails = {}
    for u in failed:
        sig = scheduling_signature(u.pod)
        fails[sig] = fails.get(sig, 0) + 1
    return placed, fails


def main() -> int:
    nodes, pods = synth_cluster(N_NODES, N_PODS, hard_predicates=True)

    sims = {}
    for waves in (True, False):
        sim = Simulator(copy.deepcopy(nodes))
        sim.use_waves = waves
        failed = sim.schedule_pods(copy.deepcopy(pods))
        sims[waves] = census(sim, failed)
        if waves:
            bt = sim.encode_batch(copy.deepcopy(pods))
            kinds = [s[0] for s in sim._segments(bt, len(pods))]

    (wave_c, wave_f), (serial_c, serial_f) = sims[True], sims[False]
    total = sum(serial_c.values()) + sum(serial_f.values())
    agree = sum(min(c, wave_c.get(k, 0)) for k, c in serial_c.items())
    agree += sum(min(c, wave_f.get(s, 0)) for s, c in serial_f.items())
    agreement = agree / total if total else 1.0

    rec = {
        "nodes": N_NODES, "pods": N_PODS,
        "agreement": round(agreement, 6),
        "segment_kinds": sorted(set(kinds)),
        "affinity_segments": sum(1 for k in kinds if k == "affinity"),
        "total_parity": total == N_PODS,
    }
    print(json.dumps(rec), flush=True)

    assert rec["affinity_segments"] > 0, (
        f"no affinity-wave segments routed (kinds: {kinds}) — the zone-spread "
        "blocks fell back off the fast path")
    assert agreement >= MIN_AGREEMENT, (
        f"census agreement {agreement:.4f} < {MIN_AGREEMENT} vs the serial scan")
    assert total == N_PODS, f"pod totals diverged: {total} != {N_PODS}"
    return 0


if __name__ == "__main__":
    sys.exit(main())
