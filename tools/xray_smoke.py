#!/usr/bin/env python
"""CI smoke for the simonxray flight recorder (fast, CPU-only).

Runs the scaled-down hard-predicate demo workload (tools/hard_smoke.py
shape: taints + self-anti-affinity + zone DoNotSchedule spread) once with
recording OFF and once with recording ON (same pods, fresh simulators) and
asserts the xray acceptance properties:

- **bit-identical placements**: every pod lands on the same node (or fails
  with the same reason string) with recording on vs off;
- **exact reconciliation**: the sum of per-reason node counts across the
  recorder's unscheduled decision records equals the
  `simon_filter_rejections_total{reason}` deltas of the recorded run, per
  reason label — the aggregate counters and the flight recorder can never
  tell different stories;
- **counts sum to N**: every unscheduled pod's reasons dict sums to the
  node count (the kube FitError invariant);
- **trace round-trip**: the written JSONL+npz trace loads, `simon explain`
  resolves a scheduled and an unscheduled pod, and unknown pods are a clean
  error;
- **bounded overhead**: the recording run's warm wall time stays within
  1.15x of the non-recording run (plus a small absolute floor so a tiny CI
  workload cannot flake on scheduler jitter).

Prints one JSON line with the measured numbers.
"""

import copy
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from open_simulator_tpu.obs import REGISTRY, xray  # noqa: E402
from open_simulator_tpu.simulator.engine import Simulator  # noqa: E402
from open_simulator_tpu.utils.synth import synth_cluster  # noqa: E402
from tests.fixtures import make_pod  # noqa: E402

N_NODES = 120
N_PODS = 1200
N_GIANTS = 6            # unschedulable riders: every reason string must sum
OVERHEAD_BUDGET = 1.15  # acceptance: xray-on wall <= 1.15x xray-off
# Absolute slack: this smoke is deliberately segment-heavy (~100 decision
# sets for ~1.2k pods), so the fixed per-set explain-dispatch cost dominates
# a sub-second run. The 15% RELATIVE budget is enforced where it is
# meaningful — the 100k-pod unconstrained bench row
# (xray_overhead_frac_100k_pods_10k_nodes, measured ~2%); here the floor
# absorbs the fixed cost so CI scheduler jitter cannot flake the gate.
OVERHEAD_FLOOR_S = 0.6


def build_workload():
    nodes, pods = synth_cluster(N_NODES, N_PODS, hard_predicates=True)
    for i in range(N_GIANTS):
        pods.append(make_pod(f"giant-{i}", cpu="4000"))
    return nodes, pods


def run_once(nodes, pods):
    sim = Simulator(copy.deepcopy(nodes))
    t0 = time.perf_counter()
    failed = sim.schedule_pods(copy.deepcopy(pods))
    dt = time.perf_counter() - t0
    placements = {}
    for i, node_pods in enumerate(sim.pods_on_node):
        for p in node_pods:
            placements[p["metadata"]["name"]] = i
    reasons = {u.pod["metadata"]["name"]: u.reason for u in failed}
    return dt, placements, reasons


def rejections():
    out = {}
    prefix = 'simon_filter_rejections_total{reason="'
    for key, val in REGISTRY.values().items():
        if key.startswith(prefix):
            out[key[len(prefix):-2]] = float(val)
    return out


def main() -> int:
    nodes, pods = build_workload()

    # warm both code paths once (compiles), then time a warm run each
    run_once(nodes, pods)
    t_off, placed_off, reasons_off = run_once(nodes, pods)

    prefix = os.path.join(tempfile.mkdtemp(prefix="xray-smoke-"), "trace")
    xray.enable(prefix)
    run_once(nodes, pods)                       # warm the explain dispatches
    rej_before = rejections()
    t_on, placed_on, reasons_on = run_once(nodes, pods)
    rej_delta = {k: v - rej_before.get(k, 0.0)
                 for k, v in rejections().items()
                 if v - rej_before.get(k, 0.0)}
    rec = xray.active()
    counts = rec.counts()

    # (b) placements bit-identical with recording on vs off
    assert placed_on == placed_off, "xray-on placements diverged from xray-off"
    assert reasons_on == reasons_off, "xray-on failure reasons diverged"

    # (a) per-reason totals reconcile EXACTLY with simonmetrics; per-pod
    # reasons sum to the node count
    xray_totals = {}
    unscheduled = 0
    for row in rec.unscheduled_summary(limit=10_000):
        exp = rec.explain(row["pod"])
        assert exp["result_name"] == "unschedulable"
        unscheduled += 1
        reasons = (exp.get("set_record") or {}).get("reasons") or {}
        assert sum(reasons.values()) == N_NODES, (
            f"{row['pod']}: reason counts {reasons} sum to "
            f"{sum(reasons.values())}, not N={N_NODES}")
        for label, n in reasons.items():
            xray_totals[label] = xray_totals.get(label, 0) + n
    assert unscheduled == len(reasons_on) == N_GIANTS, (
        unscheduled, len(reasons_on))
    assert xray_totals == {k: int(v) for k, v in rej_delta.items()}, (
        f"xray reason totals {xray_totals} != filter_rejections_total "
        f"deltas {rej_delta}")

    xray.disable()  # flush JSONL + write the npz sidecar

    # trace round-trip: explain a scheduled and an unscheduled pod offline
    tr = xray.XrayTrace.load(prefix)
    giant = tr.explain("default/giant-0")
    assert giant is not None and "0/%d nodes are available" % N_NODES in giant["reason"]
    some_placed = next(iter(placed_on))
    sched = tr.explain(f"default/{some_placed}")
    assert sched is not None and sched["result_name"] == "scheduled"
    assert sched["node_name"] is not None
    assert tr.explain("default/no-such-pod") is None
    rendered = xray.render_explanation(giant)
    assert "FailedScheduling" in rendered

    # (c) bounded overhead on the warm smoke workload
    budget = max(t_off * OVERHEAD_BUDGET, t_off + OVERHEAD_FLOOR_S)
    row = {
        "metric": "xray_smoke",
        "nodes": N_NODES, "pods": N_PODS + N_GIANTS,
        "wall_off_s": round(t_off, 3), "wall_on_s": round(t_on, 3),
        "overhead_frac": round((t_on - t_off) / t_off, 4) if t_off else 0.0,
        "unscheduled": unscheduled,
        "decision_sets": counts["sets"],
        "reason_labels": sorted(xray_totals),
        "trace_bytes": os.path.getsize(prefix + ".jsonl"),
    }
    print(json.dumps(row), flush=True)
    assert t_on <= budget, (
        f"xray-on wall {t_on:.3f}s exceeds budget {budget:.3f}s "
        f"(off: {t_off:.3f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
