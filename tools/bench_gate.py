"""CI bench gate: `simon metrics --diff --fail-on-regression` over the
serve/sweep workloads' obs_metrics vs a committed baseline.

Runs the fixed gate workloads in THIS process (a fresh interpreter, so the
compile-cache accounting starts from zero exactly like the baseline run):

1. **serve** — a scaled-down closed-loop loadgen run on the resident image
   (the serve_whatif_rps shape: warm templates, micro-batching, live churn,
   a scoped window);
2. **sweep** — the committed zone-outage example sweep with full parity
   fuzzing;
3. **host_1m RSS gate** — the 1M-pod columnar host-path workload
   (PodStore/NodeStore, streaming encode forced on) in its own interpreter,
   with a hard peak-RSS budget: the struct-of-arrays store must CUT host
   memory vs the dict path, and streaming must cap per-run buffers
   (RSS_1M_BUDGET_MB; see the constant's comment for measurements).

Then diffs the fresh registry snapshot against the committed baseline
(tests/golden/bench_gate_baseline.json) with the SAME machinery as
`simon metrics --diff --fail-on-regression` (cli/main.py _diff_metrics +
_BAD_WHEN_UP), so bad-direction drift fails CI: fresh compile-cache misses
(a new shape bucket snuck into the warm path), stale-session re-encodes,
sweep parity mismatches, retries/rollbacks/faults, dropped trace events.

On top of the diff, a small set of families must be ABSOLUTELY zero in the
fresh run — parity mismatches or guard containment events in a fault-free
fixed workload are failures regardless of what the baseline says.

Families that drift with the installed jax version (XLA backend compile
counts/seconds) are excluded from both sides: the gate checks THIS repo's
dispatch accounting, not jaxlib's compiler internals.

Usage:
  python tools/bench_gate.py --check     # CI gate (exit 1 on regression)
  python tools/bench_gate.py --update    # regenerate the committed baseline
"""

from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("OPEN_SIMULATOR_MESH", "0")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE = os.path.join(REPO, "tests", "golden", "bench_gate_baseline.json")

# Counter families that must be ZERO in the fresh gate run, full stop: the
# workload injects no faults and runs parity-fuzzed, so any of these moving
# is a live regression even if a (stale) baseline contained it.
MUST_BE_ZERO = (
    "simon_sweep_parity_mismatches_total",
    "simon_serve_stale_sessions_total",
    "simon_http_errors_total",
    "simon_guard_watchdog_expiries_total",
    "simon_guard_oom_bisections_total",
    "simon_guard_failovers_total",
    "simon_faults_injected_total",
    "simon_retries_total",
    "simon_commit_rollbacks_total",
    "simon_scope_trace_dropped_total",
    "simon_scope_sampler_errors_total",
)

# jax-version-dependent families excluded from the baseline diff (see
# module docstring).
VERSION_DEPENDENT = ("simon_xla_backend_compile",)

# Peak-RSS budget for the 1M-pod columnar host-path workload (PR 15): the
# struct-of-arrays store + streaming encode must CUT host memory, not grow
# it. Measured: ~300MB peak (store + jax runtime + streamed chunks) vs
# ~2.8GB for the same workload as 1M pod dicts — the budget sits 3x above
# the columnar measurement and far below the dict floor, so a regression
# back toward per-pod dict state trips it long before it ships.
RSS_1M_BUDGET_MB = 1024
RSS_WORKLOAD = r"""
import json, os, resource, sys, time
sys.path.insert(0, {repo!r})
from open_simulator_tpu.utils.synth import synth_cluster_store
from open_simulator_tpu.simulator.engine import Simulator

t0 = time.perf_counter()
ns, ps = synth_cluster_store(10_000, 1_000_000)
sim = Simulator(ns, use_mesh=False)
failed = sim.schedule_pods(ps)
print(json.dumps({{
    "wall_s": round(time.perf_counter() - t0, 2),
    "placed": sim.pods_on_node.total(),
    "failed": len(failed),
    "rss_mb": round(
        resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0, 1),
}}))
"""


def run_rss_gate() -> dict:
    """The 1M-row RSS probe, in its own interpreter (the gate process'
    serve/sweep allocations would pollute ru_maxrss). A small explicit
    OPEN_SIMULATOR_STREAM_PODS forces the store batch through the streaming
    path, so the gate also proves chunking caps the per-run buffers."""
    import subprocess

    env = dict(os.environ)
    env["OPEN_SIMULATOR_STREAM_PODS"] = "262144"
    out = subprocess.run(
        [sys.executable, "-c", RSS_WORKLOAD.format(repo=REPO)],
        env=env, capture_output=True, text=True, timeout=900)
    row = None
    for line in reversed(out.stdout.strip().splitlines()):
        if line.startswith("{"):
            row = json.loads(line)
            break
    if row is None:
        raise SystemExit(
            f"rss gate workload produced no row (rc={out.returncode}, "
            f"stderr tail: {out.stderr[-300:]!r})")
    if row["placed"] != 1_000_000 or row["failed"]:
        raise SystemExit(f"rss gate workload mis-scheduled: {row}")
    return row


def run_workloads() -> dict:
    """The fixed gate workloads; returns the fresh serve row (the sweep's
    effect lands in the shared registry)."""
    from loadgen import run_loadgen

    from open_simulator_tpu.sweep import SweepRunner, load_spec

    args = argparse.Namespace(
        nodes=600, base_load=0.5, duration=1.5, concurrency=4,
        window_ms=2.0, fanout=4, templates=8, parity_sample=2,
        churn=True, http=False, scope_window=1.0, out="")
    row = run_loadgen(args)
    if row["errors"] or not row["parity_ok"]:
        raise SystemExit(f"gate serve workload failed: {row}")
    spec = load_spec(os.path.join(REPO, "examples", "sweeps",
                                  "zone-outage.yaml"))
    runner = SweepRunner(spec, parity="full")
    runner.run()
    return row


def fresh_snapshot() -> dict:
    from open_simulator_tpu.obs import REGISTRY

    return filter_snapshot(REGISTRY.snapshot())


def filter_snapshot(snap: dict) -> dict:
    return {name: fam for name, fam in snap.items()
            if not any(name.startswith(p) for p in VERSION_DEPENDENT)}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    mode = parser.add_mutually_exclusive_group(required=True)
    mode.add_argument("--check", action="store_true",
                      help="run the gate against the committed baseline")
    mode.add_argument("--update", action="store_true",
                      help="regenerate the committed baseline snapshot")
    args = parser.parse_args(argv)

    row = run_workloads()
    snap = fresh_snapshot()
    print(f"gate serve row: {row['value']} req/s, "
          f"{row['requests']} requests, parity_ok={row['parity_ok']}")

    rss = run_rss_gate()
    print(f"gate 1M-row rss: {rss['rss_mb']}MB peak "
          f"(budget {RSS_1M_BUDGET_MB}MB), {rss['wall_s']}s, "
          f"{rss['placed']} placed")
    rss_failure = None
    if rss["rss_mb"] > RSS_1M_BUDGET_MB:
        rss_failure = (f"1M-pod columnar workload peaked at "
                       f"{rss['rss_mb']}MB > {RSS_1M_BUDGET_MB}MB budget — "
                       f"the host path is growing per-pod state again")

    if args.update:
        with open(BASELINE, "w") as f:
            json.dump(snap, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"bench gate baseline written: {BASELINE}")
        return 0

    from open_simulator_tpu.obs import values_from_snapshot

    vals = values_from_snapshot(snap)
    hard_failures = []
    for fam in MUST_BE_ZERO:
        moved = {k: v for k, v in vals.items()
                 if k.startswith(fam) and v != 0}
        if moved:
            hard_failures.append(f"{fam} nonzero in a fault-free gate "
                                 f"run: {moved}")
    try:
        with open(BASELINE) as f:
            base = filter_snapshot(json.load(f))
    except OSError as e:
        print(f"bench gate: no baseline ({e}); run --update and commit it",
              file=sys.stderr)
        return 1

    # the satellite contract: the SAME diff surface as
    # `simon metrics --diff --fail-on-regression`, A=baseline B=fresh
    from open_simulator_tpu.cli.main import _diff_metrics

    changed, regressions = _diff_metrics(base, snap, sys.stdout)
    for msg in hard_failures:
        print(f"GATE FAILURE: {msg}", file=sys.stderr)
    if rss_failure:
        print(f"GATE FAILURE: {rss_failure}", file=sys.stderr)
    if regressions:
        print(f"bench gate: {regressions} regression-direction counter(s) "
              f"grew vs {os.path.relpath(BASELINE, REPO)} (re-baseline "
              f"with --update ONLY if the growth is intended)",
              file=sys.stderr)
    if hard_failures or regressions or rss_failure:
        return 1
    print(f"bench gate: OK ({changed} metric(s) changed, 0 regressions)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
