"""CI bench gate: `simon metrics --diff --fail-on-regression` over the
serve/sweep workloads' obs_metrics vs a committed baseline.

Runs the fixed gate workloads in THIS process (a fresh interpreter, so the
compile-cache accounting starts from zero exactly like the baseline run):

1. **serve** — a scaled-down closed-loop loadgen run on the resident image
   (the serve_whatif_rps shape: warm templates, micro-batching, live churn,
   a scoped window);
2. **sweep** — the committed zone-outage example sweep with full parity
   fuzzing;
3. **hard** — a fixed single-device hard-predicate batch (taints +
   required anti-affinity + zone spread, the affinity-wave route): its
   registry families (segment counts per kind, compile-cache misses for
   the hard shapes, commit totals) join the baseline diff, so shape churn
   or a route regression on the hard path fails CI like any other
   bad-direction drift;
4. **mesh8_hard** — the sharded hard-predicate wave on an 8-virtual-device
   CPU mesh, in its own interpreter (the epoch-amortized collective path):
   placements must be bit-identical to the single-device engine on the
   same workload, reshard_bytes must be 0, and the rate must clear a
   generous floor (MESH8_HARD_FLOOR; see the constant's comment);
5. **host_1m RSS gate** — the 1M-pod columnar host-path workload
   (PodStore/NodeStore, streaming encode forced on) in its own interpreter,
   with a hard peak-RSS budget: the struct-of-arrays store must CUT host
   memory vs the dict path, and streaming must cap per-run buffers
   (RSS_1M_BUDGET_MB; see the constant's comment for measurements);
6. **restart gate (simonha)** — restart-to-ready wall for a 10k-node image
   in its own interpreter: a checkpoint+WAL-tail restore must come up at
   the exact pre-crash epoch with bit-identical answers, and must be at
   least RESTORE_SPEEDUP_FLOOR x faster than rebuilding the image from the
   materialized node dicts (the apiserver-relist baseline a restart would
   otherwise pay).

Then diffs the fresh registry snapshot against the committed baseline
(tests/golden/bench_gate_baseline.json) with the SAME machinery as
`simon metrics --diff --fail-on-regression` (cli/main.py _diff_metrics +
_BAD_WHEN_UP), so bad-direction drift fails CI: fresh compile-cache misses
(a new shape bucket snuck into the warm path), stale-session re-encodes,
sweep parity mismatches, retries/rollbacks/faults, dropped trace events.

On top of the diff, a small set of families must be ABSOLUTELY zero in the
fresh run — parity mismatches or guard containment events in a fault-free
fixed workload are failures regardless of what the baseline says.

Families that drift with the installed jax version (XLA backend compile
counts/seconds) are excluded from both sides: the gate checks THIS repo's
dispatch accounting, not jaxlib's compiler internals.

Usage:
  python tools/bench_gate.py --check     # CI gate (exit 1 on regression)
  python tools/bench_gate.py --update    # regenerate the committed baseline
"""

from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("OPEN_SIMULATOR_MESH", "0")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE = os.path.join(REPO, "tests", "golden", "bench_gate_baseline.json")

# Counter families that must be ZERO in the fresh gate run, full stop: the
# workload injects no faults and runs parity-fuzzed, so any of these moving
# is a live regression even if a (stale) baseline contained it.
MUST_BE_ZERO = (
    "simon_sweep_parity_mismatches_total",
    "simon_serve_stale_sessions_total",
    "simon_http_errors_total",
    "simon_guard_watchdog_expiries_total",
    "simon_guard_oom_bisections_total",
    "simon_guard_failovers_total",
    "simon_faults_injected_total",
    "simon_retries_total",
    "simon_commit_rollbacks_total",
    "simon_scope_trace_dropped_total",
    "simon_scope_sampler_errors_total",
    # simonpulse (PR 18): the gate workloads run with the ledger OFF, so any
    # pulse sample moving means pulse self-enabled on the default path (the
    # pulse-off byte-identity contract); regressions/drops are additionally
    # _BAD_WHEN_UP in the shared diff machinery for runs that enable it
    "simon_pulse_records_total",
    "simon_pulse_records_dropped_total",
    "simon_pulse_regressions_total",
    "simon_pulse_phase_seconds_total",
    # simonha (PR 19): an answer stamped ahead of the image, or a WAL/
    # checkpoint lineage/integrity mismatch, is a crash-consistency
    # correctness failure no baseline can excuse
    "simon_serve_wrong_epoch_answers_total",
    "simon_serve_wal_parity_mismatches_total",
    # simonsync (PR 20): the resident image diverging from the listed
    # cluster after a relist, or a watch gap degrading into a
    # generation-bumping full rebuild, breaks the delta-only convergence
    # contract — the chaos gate proves both stay zero under injected faults
    "simon_sync_parity_mismatches_total",
    "simon_sync_full_rebuilds_total",
)

# jax-version-dependent families excluded from the baseline diff (see
# module docstring).
VERSION_DEPENDENT = ("simon_xla_backend_compile",)

# Rate floor for the sharded hard-predicate gate workload (pods/s). This is
# a CORRECTNESS-adjacent floor, not a perf target: on the 1-core CI host the
# 8 virtual devices serialize the replicated selection tail, so the rate
# mostly measures host contention. Measured ~5.3k pods/s warm at the full
# 10k/1k bench shape; the scaled-down gate shape runs hotter per pod. The
# floor sits far below both so only a pathological regression (e.g. the
# epoch loop re-growing per-round collectives, or an accidental fall back
# to serial per-pod scheduling) trips it — bit-identity and reshard_bytes
# are the strict gates.
MESH8_HARD_FLOOR = 500

# Peak-RSS budget for the 1M-pod columnar host-path workload (PR 15): the
# struct-of-arrays store + streaming encode must CUT host memory, not grow
# it. Measured: ~300MB peak (store + jax runtime + streamed chunks) vs
# ~2.8GB for the same workload as 1M pod dicts — the budget sits 3x above
# the columnar measurement and far below the dict floor, so a regression
# back toward per-pod dict state trips it long before it ships.
RSS_1M_BUDGET_MB = 1024
RSS_WORKLOAD = r"""
import json, os, resource, sys, time
sys.path.insert(0, {repo!r})
from open_simulator_tpu.utils.synth import synth_cluster_store
from open_simulator_tpu.simulator.engine import Simulator

t0 = time.perf_counter()
ns, ps = synth_cluster_store(10_000, 1_000_000)
sim = Simulator(ns, use_mesh=False)
failed = sim.schedule_pods(ps)
print(json.dumps({{
    "wall_s": round(time.perf_counter() - t0, 2),
    "placed": sim.pods_on_node.total(),
    "failed": len(failed),
    "rss_mb": round(
        resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0, 1),
}}))
"""


# Restart-to-ready floor for the simonha gate (PR 19): restoring a 10k-node
# resident image from checkpoint + WAL tail vs rebuilding it from the
# materialized node dicts (what a restart without --state-dir pays: a full
# apiserver relist + per-dict encode). The columnar checkpoint rides the
# NodeStore whole, so restore skips the per-node dict parse entirely —
# measured ~15-30x on CI-class hosts; the 5x floor only trips if restore
# falls back to the dict path (or checkpointing silently degrades to a
# rebuild), not on host-speed jitter.
RESTORE_SPEEDUP_FLOOR = 5.0
RESTART_WORKLOAD = r"""
import json, os, shutil, sys, tempfile, time
sys.path.insert(0, {repo!r})
from open_simulator_tpu.serve import HAState, ResidentImage
from open_simulator_tpu.utils.synth import synth_cluster_store

N_NODES = 10_000
ns, _ = synth_cluster_store(N_NODES, 0)


def build():
    return ResidentImage.try_build(ns)


def pod(i, node):
    meta = dict(name="ha-gate-%d" % i, namespace="default",
                uid="ha-gate-uid-%d" % i, labels=dict(app="ha-gate"))
    spec = dict(containers=[dict(
        name="c", image="nginx",
        resources=dict(requests=dict(cpu="500m", memory="1Gi")))])
    if node:
        spec["nodeName"] = node
    return dict(apiVersion="v1", kind="Pod", metadata=meta, spec=spec,
                status=dict(phase="Running" if node else "Pending"))


probe = [pod(1000 + j, None) for j in range(3)]
state_dir = tempfile.mkdtemp(prefix="ha_restart_gate_")
try:
    ha = HAState.open(state_dir, build, checkpoint_every=4)
    for step in range(5):  # checkpoint seals batch 4; batch 5 stays in WAL
        ha.ingest([dict(type="pod_add",
                        pod=pod(step, "node-%05d" % (step % 8)))])
    want = ha.image.session(probe).run()
    want_epoch = ha.image.epoch
    relist_nodes = ha.image.current_nodes()  # the apiserver-relist payload
    ha.close()

    t0 = time.perf_counter()
    ha2 = HAState.open(state_dir, build, checkpoint_every=4)
    restore_s = time.perf_counter() - t0
    got = ha2.image.session(probe).run()
    match = (ha2.image.epoch == want_epoch and all(
        got[k] == want[k]
        for k in ("scheduled", "total", "unscheduled", "utilization")))
    replayed = ha2.replayed
    ha2.close()

    t0 = time.perf_counter()
    img = ResidentImage.try_build(relist_nodes)
    rebuild_s = time.perf_counter() - t0
    rebuilt_ok = len(img.current_nodes()) == N_NODES
finally:
    shutil.rmtree(state_dir, ignore_errors=True)

print(json.dumps(dict(
    n_nodes=N_NODES, restore_s=round(restore_s, 3),
    rebuild_s=round(rebuild_s, 3),
    speedup=round(rebuild_s / max(restore_s, 1e-9), 1),
    replayed=replayed, answers_match=bool(match),
    rebuilt_ok=bool(rebuilt_ok))))
"""


def run_restart_gate() -> dict:
    """The simonha restart-to-ready probe, in its own interpreter: its WAL/
    checkpoint counter families must NOT leak into this process' registry
    snapshot (the baseline diff covers the serve/sweep/hard workloads only),
    and both timed sides — checkpoint restore and dict-relist rebuild — run
    in the same warmed process, so the speedup compares work, not imports."""
    import subprocess

    out = subprocess.run(
        [sys.executable, "-c", RESTART_WORKLOAD.format(repo=REPO)],
        env=dict(os.environ), capture_output=True, text=True, timeout=900)
    row = None
    for line in reversed(out.stdout.strip().splitlines()):
        if line.startswith("{"):
            row = json.loads(line)
            break
    if row is None:
        raise SystemExit(
            f"restart gate workload produced no row (rc={out.returncode}, "
            f"stderr tail: {out.stderr[-300:]!r})")
    if not row["rebuilt_ok"] or row["replayed"] < 1:
        raise SystemExit(f"restart gate workload malformed: {row}")
    return row


def run_rss_gate() -> dict:
    """The 1M-row RSS probe, in its own interpreter (the gate process'
    serve/sweep allocations would pollute ru_maxrss). A small explicit
    OPEN_SIMULATOR_STREAM_PODS forces the store batch through the streaming
    path, so the gate also proves chunking caps the per-run buffers."""
    import subprocess

    env = dict(os.environ)
    env["OPEN_SIMULATOR_STREAM_PODS"] = "262144"
    out = subprocess.run(
        [sys.executable, "-c", RSS_WORKLOAD.format(repo=REPO)],
        env=env, capture_output=True, text=True, timeout=900)
    row = None
    for line in reversed(out.stdout.strip().splitlines()):
        if line.startswith("{"):
            row = json.loads(line)
            break
    if row is None:
        raise SystemExit(
            f"rss gate workload produced no row (rc={out.returncode}, "
            f"stderr tail: {out.stderr[-300:]!r})")
    if row["placed"] != 1_000_000 or row["failed"]:
        raise SystemExit(f"rss gate workload mis-scheduled: {row}")
    return row


def run_workloads() -> dict:
    """The fixed gate workloads; returns the fresh serve row (the sweep's
    and hard batch's effects land in the shared registry)."""
    from loadgen import run_loadgen

    from open_simulator_tpu.sweep import SweepRunner, load_spec

    args = argparse.Namespace(
        nodes=600, base_load=0.5, duration=1.5, concurrency=4,
        window_ms=2.0, fanout=4, templates=8, parity_sample=2,
        churn=True, http=False, scope_window=1.0, out="")
    row = run_loadgen(args)
    if row["errors"] or not row["parity_ok"]:
        raise SystemExit(f"gate serve workload failed: {row}")
    spec = load_spec(os.path.join(REPO, "examples", "sweeps",
                                  "zone-outage.yaml"))
    runner = SweepRunner(spec, parity="full")
    runner.run()
    run_hard_workload()
    return row


def run_hard_workload() -> None:
    """The fixed single-device hard-predicate batch (the affinity-wave
    route). Runs in THIS process so its registry families enter the
    baseline diff: a new compile-cache shape on the hard path, a segment
    routed off the wave kernels, or any parity/guard family moving shows
    up as bad-direction drift against the committed golden."""
    from open_simulator_tpu.simulator.engine import Simulator
    from open_simulator_tpu.utils.synth import synth_cluster

    nodes, pods = synth_cluster(500, 5_000, hard_predicates=True)
    sim = Simulator(nodes, use_mesh=False)
    failed = sim.schedule_pods(pods)
    placed = sum(len(p) for p in sim.pods_on_node)
    if failed or placed != 5_000:
        raise SystemExit(f"gate hard workload mis-scheduled: "
                         f"placed={placed}, failed={len(failed)}")


def run_mesh8_hard_gate() -> dict:
    """The sharded hard-predicate wave (epoch-amortized collectives) on an
    8-virtual-device CPU mesh, via bench.bench_mesh_cpu's own fresh
    interpreter (this process' jax is already initialized single-device).
    The strict gates are bit-identity against the single-device engine and
    reshard_bytes == 0; the rate floor only catches pathologies (see
    MESH8_HARD_FLOOR)."""
    from bench import bench_mesh_cpu

    rate, wall, placed, total, match, reshard, _transfer, _pulse, err = \
        bench_mesh_cpu(n_nodes=256, n_pods=2_000, shards=8, hard=True,
                       repeats=1, timeout=600, check_single=True)
    row = {"rate": round(rate, 1), "wall_s": round(wall, 3),
           "placed": placed, "total": total, "match": match,
           "reshard_bytes": reshard}
    if err:
        raise SystemExit(f"gate mesh8_hard workload errored: {err}")
    if placed != total or total != 2_000:
        raise SystemExit(f"gate mesh8_hard workload mis-scheduled: {row}")
    return row


def fresh_snapshot() -> dict:
    from open_simulator_tpu.obs import REGISTRY

    return filter_snapshot(REGISTRY.snapshot())


def filter_snapshot(snap: dict) -> dict:
    return {name: fam for name, fam in snap.items()
            if not any(name.startswith(p) for p in VERSION_DEPENDENT)}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    mode = parser.add_mutually_exclusive_group(required=True)
    mode.add_argument("--check", action="store_true",
                      help="run the gate against the committed baseline")
    mode.add_argument("--update", action="store_true",
                      help="regenerate the committed baseline snapshot")
    args = parser.parse_args(argv)

    row = run_workloads()
    snap = fresh_snapshot()
    print(f"gate serve row: {row['value']} req/s, "
          f"{row['requests']} requests, parity_ok={row['parity_ok']}")

    mesh = run_mesh8_hard_gate()
    print(f"gate mesh8_hard row: {mesh['rate']} pods/s "
          f"(floor {MESH8_HARD_FLOOR}), {mesh['wall_s']}s, "
          f"match={mesh['match']}, reshard_bytes={mesh['reshard_bytes']}")
    mesh_failures = []
    if mesh["match"] is not True:
        mesh_failures.append(
            "mesh8_hard placements diverged from the single-device engine "
            "— the epoch-amortized collective path broke bit-identity")
    if mesh["reshard_bytes"] != 0:
        mesh_failures.append(
            f"mesh8_hard resharded {mesh['reshard_bytes']} bytes — a "
            f"dispatch-boundary or shard_map layout regression")
    if mesh["rate"] < MESH8_HARD_FLOOR:
        mesh_failures.append(
            f"mesh8_hard rate {mesh['rate']} pods/s under the "
            f"{MESH8_HARD_FLOOR} floor — per-round collectives (or a "
            f"serial fallback) are back in the epoch loop")

    rss = run_rss_gate()
    print(f"gate 1M-row rss: {rss['rss_mb']}MB peak "
          f"(budget {RSS_1M_BUDGET_MB}MB), {rss['wall_s']}s, "
          f"{rss['placed']} placed")
    rss_failure = None
    if rss["rss_mb"] > RSS_1M_BUDGET_MB:
        rss_failure = (f"1M-pod columnar workload peaked at "
                       f"{rss['rss_mb']}MB > {RSS_1M_BUDGET_MB}MB budget — "
                       f"the host path is growing per-pod state again")

    restart = run_restart_gate()
    print(f"gate restart row: restore {restart['restore_s']}s vs rebuild "
          f"{restart['rebuild_s']}s = {restart['speedup']}x "
          f"(floor {RESTORE_SPEEDUP_FLOOR}x), replayed={restart['replayed']}, "
          f"answers_match={restart['answers_match']}")
    restart_failures = []
    if not restart["answers_match"]:
        restart_failures.append(
            "restart gate: the checkpoint+WAL restore came up at a "
            "different epoch or with different what-if answers than the "
            "pre-restart image — crash consistency is broken")
    if restart["speedup"] < RESTORE_SPEEDUP_FLOOR:
        restart_failures.append(
            f"restart gate: checkpoint restore only {restart['speedup']}x "
            f"faster than the dict-relist rebuild (floor "
            f"{RESTORE_SPEEDUP_FLOOR}x) — the columnar store fast path "
            f"fell off the restore")

    if args.update:
        with open(BASELINE, "w") as f:
            json.dump(snap, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"bench gate baseline written: {BASELINE}")
        return 0

    from open_simulator_tpu.obs import values_from_snapshot

    vals = values_from_snapshot(snap)
    hard_failures = []
    for fam in MUST_BE_ZERO:
        moved = {k: v for k, v in vals.items()
                 if k.startswith(fam) and v != 0}
        if moved:
            hard_failures.append(f"{fam} nonzero in a fault-free gate "
                                 f"run: {moved}")
    try:
        with open(BASELINE) as f:
            base = filter_snapshot(json.load(f))
    except OSError as e:
        print(f"bench gate: no baseline ({e}); run --update and commit it",
              file=sys.stderr)
        return 1

    # the satellite contract: the SAME diff surface as
    # `simon metrics --diff --fail-on-regression`, A=baseline B=fresh
    from open_simulator_tpu.cli.main import _diff_metrics

    changed, regressions = _diff_metrics(base, snap, sys.stdout)
    for msg in hard_failures + mesh_failures + restart_failures:
        print(f"GATE FAILURE: {msg}", file=sys.stderr)
    if rss_failure:
        print(f"GATE FAILURE: {rss_failure}", file=sys.stderr)
    if regressions:
        print(f"bench gate: {regressions} regression-direction counter(s) "
              f"grew vs {os.path.relpath(BASELINE, REPO)} (re-baseline "
              f"with --update ONLY if the growth is intended)",
              file=sys.stderr)
    if (hard_failures or regressions or rss_failure or mesh_failures
            or restart_failures):
        return 1
    print(f"bench gate: OK ({changed} metric(s) changed, 0 regressions)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
