"""Regenerate examples/ from the reference's example manifests.

The reference ships demo scenarios (cluster dirs, app dirs, newnode templates)
that the parity tests replay. This tool derives self-contained in-repo
equivalents by LOADING each reference manifest and keeping only the
scheduling-relevant subset of fields — requests/limits, replicas, selectors,
affinity, tolerations, taints, allocatable, storage/gpu annotations — because
that is exactly the surface MakeValidPod keeps after sanitization
(/root/reference/pkg/utils/utils.go:378-463). Probes, commands, env, images,
conditions and other runtime fields are dropped. Output is re-serialized with
sorted keys, so the files are a distilled dataset, not copies.

Usage: python tools/make_examples.py  (run from the repo root; needs
/root/reference mounted — the committed examples/ are its output, so normal
builds and tests never need the reference.)
"""

from __future__ import annotations

import json
import os
import shutil
import sys

import yaml

REF = "/root/reference/example"
OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples")


def _keep(d: dict, keys) -> dict:
    return {k: d[k] for k in keys if k in d and d[k] not in (None, {}, [])}


def strip_container(c: dict) -> dict:
    out = _keep(c, ("name", "resources", "ports"))
    out.setdefault("name", "main")
    out["image"] = c.get("image", "app:latest").split("/")[-1]  # basename only
    if "ports" in out:
        out["ports"] = [
            _keep(p, ("containerPort", "hostPort", "hostIP", "protocol", "name"))
            for p in out["ports"]
        ]
    return out


def strip_pod_spec(spec: dict) -> dict:
    out = _keep(
        spec,
        ("nodeSelector", "affinity", "tolerations", "nodeName", "hostNetwork",
         "topologySpreadConstraints", "priorityClassName", "priority",
         "schedulerName", "overhead"),
    )
    out["containers"] = [strip_container(c) for c in spec.get("containers") or []]
    if spec.get("initContainers"):
        out["initContainers"] = [strip_container(c) for c in spec["initContainers"]]
    vols = []
    for v in spec.get("volumes") or []:
        kept = _keep(v, ("name", "persistentVolumeClaim", "hostPath"))
        if len(kept) > 1:
            vols.append(kept)
    if vols:
        out["volumes"] = vols
    return out


def strip_meta(meta: dict) -> dict:
    out = _keep(meta, ("name", "namespace", "labels", "generateName"))
    anns = {
        k: v for k, v in (meta.get("annotations") or {}).items()
        if k.startswith(("simon/", "alibabacloud.com/", "scheduler.alpha"))
    }
    if anns:
        out["annotations"] = anns
    return out


def strip_template(tpl: dict) -> dict:
    return {
        "metadata": strip_meta(tpl.get("metadata") or {}),
        "spec": strip_pod_spec(tpl.get("spec") or {}),
    }


def strip_object(obj: dict):
    kind = obj.get("kind")
    meta = strip_meta(obj.get("metadata") or {})
    spec = obj.get("spec") or {}
    if kind == "Node":
        out_spec = _keep(spec, ("taints", "unschedulable"))
        status = _keep(obj.get("status") or {}, ("allocatable", "capacity"))
        out = {"apiVersion": "v1", "kind": kind, "metadata": meta}
        if out_spec:
            out["spec"] = out_spec
        out["status"] = status
        return out
    if kind == "Pod":
        return {"apiVersion": "v1", "kind": kind, "metadata": meta,
                "spec": strip_pod_spec(spec)}
    if kind in ("Deployment", "ReplicaSet", "ReplicationController", "DaemonSet",
                "StatefulSet"):
        out_spec = _keep(spec, ("replicas", "selector", "serviceName",
                                "podManagementPolicy"))
        out_spec["template"] = strip_template(spec.get("template") or {})
        vcts = []
        for v in spec.get("volumeClaimTemplates") or []:
            vcts.append({
                "metadata": strip_meta(v.get("metadata") or {}),
                "spec": _keep(v.get("spec") or {},
                              ("accessModes", "storageClassName", "resources")),
            })
        if vcts:
            out_spec["volumeClaimTemplates"] = vcts
        return {"apiVersion": obj.get("apiVersion", "apps/v1"), "kind": kind,
                "metadata": meta, "spec": out_spec}
    if kind == "Job":
        out_spec = _keep(spec, ("completions", "parallelism"))
        out_spec["template"] = strip_template(spec.get("template") or {})
        return {"apiVersion": "batch/v1", "kind": kind, "metadata": meta,
                "spec": out_spec}
    if kind == "CronJob":
        js = (spec.get("jobTemplate") or {}).get("spec") or {}
        out_spec = {
            "schedule": spec.get("schedule", "* * * * *"),
            "jobTemplate": {"spec": {
                **_keep(js, ("completions", "parallelism")),
                "template": strip_template(js.get("template") or {}),
            }},
        }
        return {"apiVersion": obj.get("apiVersion", "batch/v1"), "kind": kind,
                "metadata": meta, "spec": out_spec}
    if kind == "Service":
        return {"apiVersion": "v1", "kind": kind, "metadata": meta,
                "spec": _keep(spec, ("selector", "ports", "clusterIP"))}
    if kind == "StorageClass":
        return {"apiVersion": "storage.k8s.io/v1", "kind": kind, "metadata": meta,
                **_keep(obj, ("provisioner", "parameters", "volumeBindingMode",
                              "reclaimPolicy"))}
    if kind == "PodDisruptionBudget":
        return {"apiVersion": obj.get("apiVersion", "policy/v1"), "kind": kind,
                "metadata": meta, "spec": spec}
    if kind in ("ConfigMap", "PersistentVolumeClaim"):
        return {"apiVersion": "v1", "kind": kind, "metadata": meta,
                **({"spec": spec} if kind == "PersistentVolumeClaim" else {})}
    return None  # CRDs, RBAC etc.: not scheduling inputs


def convert_tree(src: str, dst: str) -> None:
    for root, _dirs, files in os.walk(src):
        rel = os.path.relpath(root, src)
        for fn in sorted(files):
            sp = os.path.join(root, fn)
            dp = os.path.join(dst, rel, fn) if rel != "." else os.path.join(dst, fn)
            os.makedirs(os.path.dirname(dp), exist_ok=True)
            if fn.endswith(".json"):  # local-storage device/VG descriptors
                with open(sp) as f:
                    data = json.load(f)
                with open(dp, "w") as f:
                    json.dump(data, f, indent=2, sort_keys=True)
                continue
            if not (fn.endswith(".yaml") or fn.endswith(".yml")):
                continue
            with open(sp) as f:
                docs = [d for d in yaml.safe_load_all(f) if isinstance(d, dict)]
            kept = [o for o in (strip_object(d) for d in docs) if o]
            if not kept:
                continue
            with open(dp, "w") as f:
                yaml.safe_dump_all(kept, f, sort_keys=True, default_flow_style=False)


def main() -> None:
    if not os.path.isdir(REF):
        sys.exit("reference examples not mounted; committed examples/ are final")
    for sub in ("cluster/demo_1", "cluster/gpushare", "newnode/demo_1",
                "newnode/gpushare", "application/simple", "application/complicate",
                "application/more_pods", "application/gpushare",
                "application/open_local"):
        src = os.path.join(REF, sub)
        if not os.path.isdir(src):
            print(f"skip {sub} (absent)", file=sys.stderr)
            continue
        dst = os.path.join(OUT, sub)
        if os.path.isdir(dst):
            shutil.rmtree(dst)
        convert_tree(src, dst)
        print(f"wrote {dst}")


if __name__ == "__main__":
    main()
