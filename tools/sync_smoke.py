#!/usr/bin/env python
"""CI smoke for simonsync (fast, CPU-only).

The resilient-watch-sync acceptance criteria, end to end and against REAL
process/socket boundaries (tests/test_sync.py covers the in-process half):

- **Socket-level connection kill mid-watch.** A stdlib HTTP server streams
  a recorded watch over chunked HTTP and hard-closes the TCP connection
  mid-stream on the first attempt. HttpWatchSource must classify the torn
  read as TransientError, reconnect from the bookmark on the seeded
  schedule, and converge to the flap-free oracle.
- **Real SIGKILL between bookmark stamp and apply.** A child process syncs
  a recorded stream into an HAState and SIGKILLs itself after the bookmark
  file is written but BEFORE the batch applies — the nastiest point of the
  crash window. The parent restarts from (checkpoint + WAL tail +
  bookmark): the stamped-but-unapplied window must replay, and the final
  image must be bit-identical (truth, epoch) to the never-crashed run.
- **Fault-site replay equality.** watch_read / watch_parse / watch_gone /
  relist, each injected twice under the same plan, fire identical traces
  (the simonfault contract) and still converge to the oracle.
- **Tripwires.** simon_sync_parity_mismatches_total and
  simon_sync_full_rebuilds_total are zero at exit, and no run ever bumped
  the image generation (delta events only, never a full rebuild).

Prints one JSON line with the measured numbers.
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from open_simulator_tpu.live import (  # noqa: E402
    HttpWatchSource,
    RecordedSource,
    ScriptedSource,
    WatchSync,
)
from open_simulator_tpu.obs import REGISTRY  # noqa: E402
from open_simulator_tpu.resilience import FaultPlan, installed  # noqa: E402
from open_simulator_tpu.serve import HAState, ResidentImage  # noqa: E402
from open_simulator_tpu.utils.synth import synth_watch_stream  # noqa: E402

STATE_DIR = "/tmp/sync_smoke_state"
KILL_AT_BATCH = 4  # SIGKILL after batch 4's bookmark stamp, before its apply
CHECKPOINT_EVERY = 2


def _workload():
    return synth_watch_stream(24, 200, seed=6, bookmark_every=20, n_bound=16)


def _image(nodes, bound):
    img = ResidentImage.try_build(
        [json.loads(json.dumps(n)) for n in nodes],
        pods=[json.loads(json.dumps(p)) for p in bound])
    assert img is not None, "resident image declined the synthetic cluster"
    return img


def _build_image():
    nodes, bound, _ = _workload()
    return _image(nodes, bound)


def _truth(image):
    pods, live = image.sync_snapshot()
    return json.dumps({"pods": sorted(pods.items()),
                       "nodes": sorted(live)}, sort_keys=True)


def _oracle():
    nodes, bound, lines = _workload()
    img = _image(nodes, bound)
    WatchSync(RecordedSource(lines=lines), image=img).run()
    return img


# ------------------------------------------- socket-level connection kill ----


def socket_kill_smoke(row):
    """Stream the recorded watch over real HTTP; hard-close the socket
    mid-stream on the first connection."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
    from urllib.parse import parse_qs, urlparse

    nodes, bound, lines = _workload()
    oracle = _oracle()
    final_rv = max(
        int(json.loads(ln)["object"]["metadata"]["resourceVersion"])
        for ln in lines)
    attempts = {"n": 0}

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            q = parse_qs(urlparse(self.path).query)
            since = int(q.get("resourceVersion", ["0"])[0])
            attempts["n"] += 1
            first = attempts["n"] == 1
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            sent = 0
            for ln in lines:
                rv = int(json.loads(ln)["object"]["metadata"]
                         ["resourceVersion"])
                if rv <= since:
                    continue
                self.wfile.write(ln.encode() + b"\n")
                self.wfile.flush()
                sent += 1
                if first and sent >= 37:
                    # hard TCP close mid-stream: no terminator, no
                    # trailing newline — the reader sees a torn stream
                    self.connection.close()
                    return

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()

    img = _image(nodes, bound)
    src = HttpWatchSource(f"http://127.0.0.1:{port}/watch", timeout=10.0)
    stop = threading.Event()
    sync = WatchSync(src, image=img, sleep=lambda s: stop.wait(s))
    t = threading.Thread(target=sync.run, args=(stop,), daemon=True)
    t.start()
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline and sync.bookmark < final_rv:
        time.sleep(0.01)
    stop.set()
    t.join(timeout=30.0)
    httpd.shutdown()
    assert not t.is_alive(), "sync thread wedged after stop"
    assert sync.bookmark >= final_rv, \
        f"never converged: bookmark {sync.bookmark} < {final_rv}"
    assert attempts["n"] >= 2, "the socket kill never forced a reconnect"
    assert sync.reconnects >= 1, "torn read did not classify as transient"
    assert _truth(img) == _truth(oracle), \
        "socket-kill run diverged from flap-free oracle"
    assert img.epoch == oracle.epoch, \
        f"epoch diverged: {img.epoch} != {oracle.epoch}"
    assert img.generation == 1 and sync.full_rebuilds == 0
    row["socket_kill"] = {"connections": attempts["n"],
                          "reconnects": sync.reconnects,
                          "applied": sync.applied,
                          "final_epoch": img.epoch}


# ------------------------------------------------- SIGKILL crash-restart -----


def sigkill_resume_smoke(row):
    import shutil
    import signal
    import subprocess

    oracle = _oracle()
    nodes, bound, lines = _workload()
    stream_path = os.path.join("/tmp", "sync_smoke_stream.jsonl")
    with open(stream_path, "w") as f:
        f.write("\n".join(lines) + "\n")

    if os.path.exists(STATE_DIR):
        shutil.rmtree(STATE_DIR)
    child = r"""
import os, signal, sys
sys.path.insert(0, %r)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import tools.sync_smoke as sm
from open_simulator_tpu.live import RecordedSource, WatchSync
from open_simulator_tpu.serve import HAState

real = WatchSync._apply
state = {"n": 0}
def apply(self, events):
    # the bookmark stamp for this batch is ALREADY on disk (_flush writes
    # it before applying): dying here leaves a stamped-but-unapplied window
    state["n"] += 1
    if state["n"] >= %d:
        os.kill(os.getpid(), signal.SIGKILL)
    real(self, events)
WatchSync._apply = apply

ha = HAState.open(%r, sm._build_image, checkpoint_every=sm.CHECKPOINT_EVERY)
sync = WatchSync(RecordedSource(path=%r), ha=ha)
sync.run()
print("UNREACHABLE")
""" % (os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
       KILL_AT_BATCH, STATE_DIR, stream_path)
    proc = subprocess.run([sys.executable, "-c", child],
                          capture_output=True, text=True, timeout=540)
    assert proc.returncode == -signal.SIGKILL, \
        f"child did not die by SIGKILL: rc={proc.returncode} " \
        f"{proc.stderr[-400:]}"
    assert "UNREACHABLE" not in proc.stdout

    # restart: checkpoint + WAL tail restore the applied prefix; the
    # bookmark file's expected_seq detects the stamped-but-unapplied
    # window and resumes from prev_rv so it replays
    ha = HAState.open(STATE_DIR, _build_image,
                      checkpoint_every=CHECKPOINT_EVERY)
    restored_seq = ha.image.seq
    assert restored_seq == KILL_AT_BATCH - 1, \
        f"restored seq {restored_seq}, want {KILL_AT_BATCH - 1} " \
        f"(batch {KILL_AT_BATCH} stamped but never applied)"
    sync = WatchSync(RecordedSource(path=stream_path), ha=ha)
    stats = sync.run()
    assert _truth(ha.image) == _truth(oracle), \
        "resumed host truth != never-crashed host truth"
    assert ha.image.epoch == oracle.epoch, \
        f"epoch diverged: {ha.image.epoch} != {oracle.epoch}"
    assert stats["full_rebuilds"] == 0 and ha.image.generation == 1
    ha.close()
    shutil.rmtree(STATE_DIR)
    os.unlink(stream_path)
    row["sigkill_resume"] = {
        "killed_at_batch": KILL_AT_BATCH,
        "restored_seq": restored_seq,
        "resumed_from_rv": stats["bookmark"],
        "final_epoch": oracle.epoch,
    }


# --------------------------------------------------- fault-site replay -------


def site_sweep_smoke(row):
    nodes, bound, lines = _workload()
    oracle = _oracle()
    fired = {}
    for site, error in (("watch_read", "transient"),
                        ("watch_parse", "transient"),
                        ("watch_gone", "protocol"),
                        ("relist", "transient")):
        traces = []
        for rep in range(2):
            img = _image(nodes, bound)
            src = ScriptedSource(
                lines, seed=1, base_nodes=nodes, base_pods=bound,
                gone_p=1.0 if site == "relist" else 0.0)
            sync = WatchSync(src, image=img, sleep=lambda s: None)
            plan = FaultPlan.from_json({"faults": [
                {"site": site, "attempt": 2, "error": error}]})
            with installed(plan) as active:
                stats = sync.run()
                traces.append(list(active.trace))
            assert _truth(img) == _truth(oracle), f"{site}: diverged"
            assert stats["full_rebuilds"] == 0, site
        assert traces[0] == traces[1], f"{site}: traces differ"
        assert traces[0], f"{site}: never fired"
        fired[site] = len(traces[0])
    row["site_sweep"] = fired


def main() -> int:
    row = {}
    socket_kill_smoke(row)
    sigkill_resume_smoke(row)
    site_sweep_smoke(row)
    vals = REGISTRY.values()
    for fam in ("simon_sync_parity_mismatches_total",
                "simon_sync_full_rebuilds_total"):
        assert int(vals.get(fam, 0)) == 0, f"{fam} nonzero"
    row["tripwires_zero"] = True
    print(json.dumps(row))
    return 0


if __name__ == "__main__":
    sys.exit(main())
