"""CI smoke for simonscope (obs/scope.py): tracing-grade serving checks.

In-process serve stack (resident image + micro-batch dispatcher) under
16-concurrent load with tracing ON, asserting the acceptance contract:

1. **Span/counter reconciliation** — every request produces one complete
   span tree (request:whatif root + queue_wait + reply for batched routes),
   the root-span count equals both simon_scope_requests_total and the SLO
   engine's total-phase histogram count, the summed root-span total_s equals
   the histogram sum (same floats), flow start/finish events pair up, and
   serve_batch spans equal the simon_serve_batches_total delta.
2. **Trace-off bit-identity** — the same request set served with scope off
   returns identical responses (placements), and moves NO simon_scope_*
   metric sample (scope-off /metrics output byte-identical in the scope
   families).
3. **Sampler shutdown** — scope.disable() joins the telemetry thread;
   no 'simon-scope-sampler' thread survives.
4. **Overhead gate** — tracing on sustains >= (1 - GATE) x the tracing-off
   request rate on the same host (GATE defaults to the ISSUE's 10%;
   OPEN_SIMULATOR_SCOPE_GATE overrides for noisy hosts).
5. **Perfetto-loadable trace** — the dumped Chrome trace parses, and every
   batched request's span tree is complete.

Run: JAX_PLATFORMS=cpu OPEN_SIMULATOR_MESH=0 python tools/scope_smoke.py
"""

from __future__ import annotations

import json
import math
import os
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

CONCURRENCY = 16
# 4k nodes: per-request device work in the single-digit-ms range, the same
# order as the serve_whatif_rps bench row the ISSUE's <=10% gate is stated
# against. At toy node counts a request is ~0.6ms and the fixed ~15us of
# per-request tracing work reads as an inflated 6-8% "overhead" that says
# nothing about the serve row.
NODES = 4000
WINDOW_S = 3.0
GATE = float(os.environ.get("OPEN_SIMULATOR_SCOPE_GATE", "0.10"))


def drive(svc, pool, duration_s: float, seed_base: int):
    """Closed-loop window: CONCURRENCY clients, returns (requests, wall_s,
    responses-by-template)."""
    import numpy as np

    stop_at = time.monotonic() + duration_s
    counts = [0] * CONCURRENCY
    errors: list = []
    lock = threading.Lock()

    def client(ci: int) -> None:
        rng = np.random.default_rng(seed_base + ci)
        done = 0
        while time.monotonic() < stop_at:
            try:
                svc.submit(pool[int(rng.integers(0, len(pool)))])
            except Exception as e:
                with lock:
                    errors.append(repr(e))
                break
            done += 1
        counts[ci] = done

    t0 = time.perf_counter()
    ts = [threading.Thread(target=client, args=(i,))
          for i in range(CONCURRENCY)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errors, f"request errors under load: {errors[:3]}"
    return sum(counts), time.perf_counter() - t0


def scope_sample_lines() -> list:
    """Rendered simon_scope_* SAMPLE lines (HELP/TYPE headers excluded:
    registering a family is free, emitting samples is what scope-off must
    never do)."""
    from open_simulator_tpu.obs import REGISTRY

    return [l for l in REGISTRY.render_text().splitlines()
            if l.startswith("simon_scope_") and not l.startswith("#")]


def main() -> int:
    from loadgen import build_image, request_pool

    from open_simulator_tpu.obs import REGISTRY
    from open_simulator_tpu.obs import scope
    from open_simulator_tpu.serve import WhatIfService

    image = build_image(NODES, base_load_frac=0.3)
    svc = WhatIfService(image, window_ms=2.0, fanout=8)
    pool = request_pool(12)

    # warm every template first (final group axis), then every lane bucket
    # at that G — same ordering rationale as tools/loadgen.py
    for pods in pool:
        svc.submit(pods)
    s = 1
    while s <= 8:
        image.dispatch_sessions(
            [image.session(pool[i % len(pool)]) for i in range(s)])
        s *= 2

    # throwaway window: concurrent-load shapes (lane buckets hit under real
    # contention) finish compiling before anything is measured
    drive(svc, pool, 1.5, seed_base=900)

    # ---- trace-off: responses recorded + scope families stay silent
    assert scope.active() is None
    off_responses = [svc.submit(pods) for pods in pool]
    leaked = scope_sample_lines()
    assert not leaked, (
        f"scope-off run emitted simon_scope_* samples (byte-identity "
        f"broken): {leaked[:4]}")

    # ---- bit-identity under tracing: same requests, same answers
    sc = scope.enable(sampler=False)
    on_responses = [svc.submit(pods) for pods in pool]
    assert on_responses == off_responses, (
        "tracing changed responses: placements must be bit-identical "
        f"({on_responses[0]} vs {off_responses[0]})")
    scope.disable()

    # ---- overhead measurement: ALTERNATING off/on window pairs, gated on
    # the median pairwise overhead. A single off->on comparison is
    # confounded on a 1-core CI host: throughput drifts several percent
    # between windows regardless of tracing, so each on-window is judged
    # against its adjacent off-window and the median damps the noise.
    import gc
    import statistics

    pair_overheads = []
    n_on = 0
    rps_off = rps_on = 0.0
    vals0 = vals1 = None
    for i in range(3):
        gc.collect()
        a_n, a_wall = drive(svc, pool, WINDOW_S, seed_base=100 + i)
        sc = scope.enable(sampler=True, sampler_interval_s=0.5)
        if i == 2:  # the reconciliation pair: metric deltas must cover
            vals0 = REGISTRY.values()  # exactly this scope's trace buffer
        gc.collect()
        b_n, b_wall = drive(svc, pool, WINDOW_S, seed_base=100 + i)
        if i == 2:
            vals1 = REGISTRY.values()
            n_on = b_n
        pair_overheads.append(1.0 - (b_n / b_wall) / (a_n / a_wall))
        rps_off, rps_on = a_n / a_wall, b_n / b_wall
        if i < 2:
            # tear scope down between pairs so the next off-window is a
            # true off-window; the LAST scope stays alive for the
            # reconciliation checks below
            scope.disable()
    n_off = a_n
    overhead = statistics.median(pair_overheads)

    # ---- span/counter reconciliation
    events = sc.events()
    roots = [e for e in events if e.get("cat") == "request"
             and e["name"] == "request:whatif"]
    queue_spans = [e for e in events if e["name"] == "queue_wait"]
    reply_spans = [e for e in events if e["name"] == "reply"]
    batch_spans = [e for e in events if e["name"] == "serve_batch"]
    flows_s = [e for e in events if e.get("cat") == "flow"
               and e.get("ph") == "s"]
    flows_f = [e for e in events if e.get("cat") == "flow"
               and e.get("ph") == "f"]
    assert len(roots) == n_on, (len(roots), n_on)

    d_req = (vals1.get('simon_scope_requests_total{endpoint="whatif",'
                       'route="batched"}', 0)
             - vals0.get('simon_scope_requests_total{endpoint="whatif",'
                         'route="batched"}', 0))
    batched_roots = [e for e in roots if e["args"].get("route") == "batched"]
    assert len(batched_roots) == d_req, (len(batched_roots), d_req)
    assert len(queue_spans) == len(batched_roots), (
        len(queue_spans), len(batched_roots))
    assert len(reply_spans) == len(roots), (len(reply_spans), len(roots))
    assert len(flows_s) == len(flows_f) == len(batched_roots), (
        len(flows_s), len(flows_f), len(batched_roots))
    d_batches = (vals1.get("simon_serve_batches_total", 0)
                 - vals0.get("simon_serve_batches_total", 0))
    assert len(batch_spans) == d_batches, (len(batch_spans), d_batches)
    # every batched root's span tree is complete: queue_wait + reply share
    # its trace id
    by_trace: dict = {}
    for e in events:
        t = (e.get("args") or {}).get("trace_id")
        if t is not None:
            by_trace.setdefault(t, set()).add(e["name"])
    for e in batched_roots:
        names = by_trace[e["args"]["trace_id"]]
        assert {"queue_wait", "reply"} <= names, names
    # histogram sums reconcile with the span totals (same floats)
    span_total = math.fsum(e["args"]["total_s"] for e in roots)
    hist_sum = (vals1.get('simon_scope_request_phase_seconds_sum'
                          '{endpoint="whatif",phase="total"}', 0.0)
                - vals0.get('simon_scope_request_phase_seconds_sum'
                            '{endpoint="whatif",phase="total"}', 0.0))
    assert abs(span_total - hist_sum) <= 1e-9 * max(1.0, abs(span_total)), (
        span_total, hist_sum)
    hist_n = (vals1.get('simon_scope_request_phase_seconds_count'
                        '{endpoint="whatif",phase="total"}', 0)
              - vals0.get('simon_scope_request_phase_seconds_count'
                          '{endpoint="whatif",phase="total"}', 0))
    assert hist_n == len(roots), (hist_n, len(roots))

    # ---- perfetto-loadable dump
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "scope-trace.json")
        sc.write_trace(path, metrics=REGISTRY.snapshot())
        with open(path) as f:
            doc = json.load(f)
        assert doc["traceEvents"], "empty trace"
        assert "slo" in doc["metadata"]

    # ---- sampler shutdown leaves no thread
    assert any(t.name == "simon-scope-sampler" for t in threading.enumerate())
    scope.disable()
    deadline = time.monotonic() + 5
    while (any(t.name == "simon-scope-sampler"
               for t in threading.enumerate())
           and time.monotonic() < deadline):
        time.sleep(0.05)
    assert not any(t.name == "simon-scope-sampler"
                   for t in threading.enumerate()), (
        "sampler thread survived scope.disable()")

    svc.stop()

    # ---- overhead gate
    print(json.dumps({
        "requests_off": n_off, "rps_off": round(rps_off, 1),
        "requests_on": n_on, "rps_on": round(rps_on, 1),
        "pair_overheads": [round(o, 4) for o in pair_overheads],
        "overhead_frac": round(overhead, 4), "gate": GATE,
        "spans": len(roots), "batches": len(batch_spans),
        "flows": len(flows_s) + len(flows_f),
    }))
    assert overhead <= GATE, (
        f"median tracing overhead {overhead:.1%} exceeds the {GATE:.0%} "
        f"gate (pairs: {[f'{o:.1%}' for o in pair_overheads]})")
    print("scope smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
