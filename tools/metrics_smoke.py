#!/usr/bin/env python
"""CI smoke for the simonmetrics registry (fast, CPU-only).

Runs two IDENTICAL warm Simulator.schedule_pods batches and asserts the
acceptance properties of the observability layer:

- `simon_scheduling_attempts_total` grows by exactly the pod count per run
  (every pod is accounted once, scheduled or unschedulable);
- `simon_compile_cache_misses_total` is UNCHANGED between run 1 and run 2
  (the warm run dispatches only already-compiled shape buckets) while hits
  keep growing;
- commits / segments / encode metrics are non-zero and the Prometheus text
  rendering of the full registry parses line-by-line.

Prints one JSON line with the measured numbers.
"""

import copy
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from open_simulator_tpu.obs import REGISTRY  # noqa: E402
from open_simulator_tpu.simulator.engine import Simulator  # noqa: E402
from open_simulator_tpu.utils.synth import synth_cluster  # noqa: E402

N_NODES, N_PODS = 32, 400

# one sample line: name{optional labels} value
_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{.*\})? (-?[0-9.]+([eE][+-]?[0-9]+)?|\+Inf)$')


def _sum(values, prefix):
    return sum(v for k, v in values.items() if k.startswith(prefix))


def main() -> int:
    nodes, pods = synth_cluster(N_NODES, N_PODS)

    def run():
        sim = Simulator(copy.deepcopy(nodes))
        failed = sim.schedule_pods(copy.deepcopy(pods))
        return len(failed)

    v0 = REGISTRY.values()
    run()
    v1 = REGISTRY.values()
    run()
    v2 = REGISTRY.values()

    def attempts(v):
        return _sum(v, "simon_scheduling_attempts_total")

    def misses(v):
        return _sum(v, "simon_compile_cache_misses_total")

    def hits(v):
        return _sum(v, "simon_compile_cache_hits_total")

    row = {
        "metric": "metrics_smoke",
        "attempts_run1": attempts(v1) - attempts(v0),
        "attempts_run2": attempts(v2) - attempts(v1),
        "compile_misses_run1": misses(v1) - misses(v0),
        "compile_misses_run2": misses(v2) - misses(v1),
        "compile_hits_run2": hits(v2) - hits(v1),
        "commits": _sum(v2, "simon_commits_total"),
        "segments": _sum(v2, "simon_segments_total"),
        "transfer_bytes": _sum(v2, "simon_device_transfer_bytes_total"),
    }
    print(json.dumps(row), flush=True)

    assert row["attempts_run1"] == N_PODS, row
    assert row["attempts_run2"] == N_PODS, row
    assert row["compile_misses_run1"] > 0, "cold run must register shape buckets"
    assert row["compile_misses_run2"] == 0, \
        "warm identical run must trigger ZERO fresh compiles"
    assert row["compile_hits_run2"] > 0, row
    assert row["commits"] > 0 and row["segments"] > 0, row
    assert row["transfer_bytes"] > 0, row

    text = REGISTRY.render_text()
    assert "# TYPE simon_scheduling_attempts_total counter" in text
    for line in text.splitlines():
        if line.startswith("#") or not line:
            continue
        assert _SAMPLE_RE.match(line), f"unparseable sample line: {line!r}"
    return 0


if __name__ == "__main__":
    sys.exit(main())
