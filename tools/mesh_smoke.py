#!/usr/bin/env python
"""CI smoke for end-to-end GSPMD sharding (fast, CPU-only, 2 virtual shards).

Runs the product path (Simulator with a pinned 2-shard node mesh) against the
single-device engine on a mixed wave/affinity/serial workload and asserts the
properties the mesh bench rows rely on, so sharding regressions fail in CI
instead of in the bench:

- per-(node, scheduling-signature) placement census is BIT-identical to the
  single-device run (not just >=99% agreement: sharding must be invisible);
- zero reshard bytes between chained dispatches
  (simon_reshard_bytes_total == 0: every segment's output carry left the
  dispatch already in the next segment's declared input sharding);
- every output carry leaf sits in the declared carry sharding;
- a watchdog wedge during a SHARDED dispatch fails over to the single-device
  CPU fallback and resumes from the committed prefix: the first call's
  placements survive untouched and the replayed call converges to the
  fault-free final census.

Prints one JSON line with the measured numbers.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# 2 virtual CPU devices BEFORE backend init; config route (see utils/devices)
from open_simulator_tpu.utils.devices import (  # noqa: E402
    force_cpu_platform,
    request_cpu_devices,
)

request_cpu_devices(2)
force_cpu_platform()
os.environ["OPEN_SIMULATOR_MESH"] = "1"

import copy  # noqa: E402

import numpy as np  # noqa: E402

from open_simulator_tpu.obs import REGISTRY  # noqa: E402
from open_simulator_tpu.parallel.mesh import (  # noqa: E402
    carry_reshard_bytes,
    make_node_mesh,
    sharded_kernels,
)
from open_simulator_tpu.resilience import guard  # noqa: E402
from open_simulator_tpu.resilience.faults import (  # noqa: E402
    FaultPlan,
    FaultSpec,
    installed,
)
from open_simulator_tpu.simulator.encode import scheduling_signature  # noqa: E402
from open_simulator_tpu.simulator.engine import Simulator  # noqa: E402
from open_simulator_tpu.utils.synth import synth_cluster  # noqa: E402

N_NODES = 100
N_PODS = 900


def census(sim):
    placed = {}
    for i, node_pods in enumerate(sim.pods_on_node):
        for p in node_pods:
            key = (i, scheduling_signature(p))
            placed[key] = placed.get(key, 0) + 1
    return placed


def run(nodes, pods, use_mesh):
    sim = Simulator(copy.deepcopy(nodes), use_mesh=use_mesh)
    failed = sim.schedule_pods(copy.deepcopy(pods))
    return sim, len(failed)


def main() -> int:
    nodes, pods = synth_cluster(N_NODES, N_PODS, hard_predicates=True)

    mesh_sim, mesh_failed = run(nodes, pods, use_mesh=True)
    assert mesh_sim._mesh is not None, "mesh path did not engage"
    single_sim, single_failed = run(nodes, pods, use_mesh=False)

    identical = census(mesh_sim) == census(single_sim)
    reshard = int(REGISTRY.values().get("simon_reshard_bytes_total") or 0)

    # every final carry leaf sits in its declared sharding
    sk = sharded_kernels(mesh_sim._mesh)
    carry_layout_ok = (
        carry_reshard_bytes(mesh_sim._last_carry, sk.carry_sh) == 0)

    # wedge mid-run on the SHARDED path: committed prefix survives, the
    # replay (single-device CPU fallback) converges to the fault-free state
    first, second = pods[:300], pods[300:]
    base = Simulator(copy.deepcopy(nodes), use_mesh=True)
    base.schedule_pods(copy.deepcopy(first))
    committed = census(base)
    base.schedule_pods(copy.deepcopy(second))
    baseline = census(base)

    wedged = Simulator(copy.deepcopy(nodes), use_mesh=True)
    wedged.schedule_pods(copy.deepcopy(first))
    prefix_ok = census(wedged) == committed
    with installed(FaultPlan([FaultSpec("watchdog_wedge", 1)])):
        wedged.schedule_pods(copy.deepcopy(second))
    failover_ok = (census(wedged) == baseline
                   and wedged.backend_path[-1] == "cpu"
                   and census(wedged) is not None
                   and prefix_ok)
    guard.reset_for_tests()  # drop the injected quarantine before exiting

    rec = {
        "nodes": N_NODES, "pods": N_PODS, "shards": 2,
        "placements_bit_identical": identical,
        "failed_parity": mesh_failed == single_failed,
        "reshard_bytes": reshard,
        "carry_layout_ok": bool(carry_layout_ok),
        "wedge_failover_resumes_from_prefix": bool(failover_ok),
    }
    print(json.dumps(rec), flush=True)

    assert identical, "mesh placements diverged from single-device"
    assert mesh_failed == single_failed, "failure counts diverged"
    assert reshard == 0, f"chained dispatches resharded {reshard} bytes"
    assert carry_layout_ok, "final carry left the declared sharding"
    assert failover_ok, "sharded wedge failover did not resume from prefix"
    sweep_ok = sweep_on_mesh()
    assert sweep_ok, "sharded sweep lanes diverged from the serial oracle"
    return 0


def sweep_on_mesh() -> bool:
    """simonsweep over a 2-shard scenario mesh: both sweep fan-out kernels
    dispatch with the [S] lane axis sharded one-lane-per-device, and every
    lane's placement census must still equal a fresh serial run (the
    runner's full-parity mode raises on any divergence)."""
    from open_simulator_tpu.parallel.mesh import make_scenario_mesh
    from open_simulator_tpu.sweep import SweepRunner, build_report, parse_spec

    doc = {"kind": "SweepSpec", "spec": {
        "seed": 4,
        "base": {"synthetic": {"nodes": 10, "zones": 2, "cpu": "8",
                               "memory": "16Gi", "bound": 6}},
        "workload": [
            {"name": "web", "replicas": 20, "cpu": "1", "memory": "1Gi"},
            {"name": "cache", "replicas": 9, "cpu": "500m",
             "memory": "512Mi"},
        ],
        "families": [
            {"kind": "node_drain", "counts": [1, 2], "draws": 2},
            {"kind": "preemption_storm", "storms": [8], "cpu": "2",
             "memory": "2Gi"},
            {"kind": "monte_carlo", "draws": 2, "templates": [
                {"name": "pair", "replicas": [2, 6], "cpu": "250m",
                 "memory": "256Mi", "affinityOn": "pair"}]},
        ],
    }}
    runner = SweepRunner(parse_spec(doc), parity="full", fanout=4,
                         mesh=make_scenario_mesh(2))
    runner.run()  # raises SweepParityError on any census mismatch
    report = build_report(runner)
    print(json.dumps({"sweep_on_mesh": report["lanes"],
                      "sweep_dispatches": report["dispatches"],
                      "sweep_parity": report["parity"]}), flush=True)
    return (report["lanes"].get("wave", 0) > 0
            and report["lanes"].get("scan", 0) > 0
            and report["parity"]["checked"] == sum(report["lanes"].values())
            and report["parity"]["mismatches"] == 0)


if __name__ == "__main__":
    sys.exit(main())
