#!/usr/bin/env python
"""CI smoke for simonha (fast, CPU-only).

The crash-consistent-serving acceptance criteria, end to end:

- **Real SIGKILL mid-ingest-burst.** A child process boots an HAState over a
  --state-dir, ingests a deterministic delta burst, and SIGKILLs itself from
  inside a WAL append (record durable, apply never ran). The parent restarts
  from the same state dir — checkpoint + WAL-tail replay — finishes the
  burst, and asserts epoch, host truth, and what-if answers bit-identical to
  an uninterrupted run.
- **Fault-site replay equality.** Each new site (wal_write / wal_fsync /
  checkpoint_write / ingest_stall), injected twice under the same plan,
  fires an identical trace (the simonfault contract), degrades the HA state,
  and the next good ingest recovers it.
- **Overload.** A concurrent burst against a bounded admission queue: every
  request either completes or sheds (completions + sheds == burst size, all
  threads join), sheds are counted, and the service takes new work
  afterwards — overload never wedges in-flight requests. A scripted-clock
  token-bucket slice pins the EXACT shed count and its determinism.
- **Tripwires.** simon_serve_wrong_epoch_answers_total and
  simon_serve_wal_parity_mismatches_total are zero at exit (the bench-gate
  MUST_BE_ZERO families).

Prints one JSON line with the measured numbers.
"""

import json
import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from open_simulator_tpu.obs import REGISTRY  # noqa: E402
from open_simulator_tpu.resilience import FaultPlan, installed  # noqa: E402
from open_simulator_tpu.serve import (  # noqa: E402
    AdmissionController,
    HAState,
    ResidentImage,
    ShedError,
    WhatIfService,
)
from open_simulator_tpu.utils.synth import synth_node  # noqa: E402

STATE_DIR = "/tmp/ha_smoke_state"
N_BATCHES = 10
KILL_AFTER_APPENDS = 6  # SIGKILL inside the append of batch 6's record
CHECKPOINT_EVERY = 4    # so the restart exercises checkpoint + WAL tail


def _pod(i, node=None):
    pod = {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": f"ha-{i}", "namespace": "default",
                     "uid": f"ha-uid-{i}", "labels": {"app": "ha"}},
        "spec": {"containers": [{"name": "c", "image": "nginx",
                                 "resources": {"requests": {
                                     "cpu": "500m", "memory": "1Gi"}}}]},
        "status": {"phase": "Running" if node else "Pending"},
    }
    if node:
        pod["spec"]["nodeName"] = node
    return pod


def _workload():
    """Deterministic boot cluster + ingest burst, shared with the child."""
    nodes = [synth_node(i) for i in range(8)]
    batches = []
    for step in range(N_BATCHES):
        if step == 4:
            batches.append([{"type": "node_drain", "name": "node-00006"}])
        elif step == 8:
            batches.append([{"type": "node_drain", "name": "node-00007"}])
        else:
            batches.append([
                {"type": "pod_add",
                 "pod": _pod(step * 4 + j, node=f"node-{step % 6:05d}")}
                for j in range(2)])
    return nodes, batches


def _build_image():
    nodes, _ = _workload()
    return ResidentImage.try_build(nodes)


def _req():
    return [_pod(1000 + j) for j in range(3)]


def _host_truth(image):
    return json.dumps({"nodes": image.current_nodes(),
                       "pods": image.cluster_pods()},
                      sort_keys=True, default=str)


def _sum(prefix):
    return sum(v for k, v in REGISTRY.values().items()
               if k.startswith(prefix))


def _same_answer(a, b, what):
    for key in ("scheduled", "total", "unscheduled", "utilization"):
        assert a[key] == b[key], f"{what}: {key} {a[key]} != {b[key]}"


# ------------------------------------------------- SIGKILL crash-restart -----


def sigkill_restart_smoke(row):
    import shutil
    import signal
    import subprocess

    nodes, batches = _workload()

    # the never-crashed oracle
    oracle = ResidentImage.try_build(nodes)
    for evs in batches:
        oracle.apply_events(evs)
    want = oracle.session(_req()).run()

    if os.path.exists(STATE_DIR):
        shutil.rmtree(STATE_DIR)
    child = r"""
import os, signal, sys
sys.path.insert(0, %r)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import tools.ha_smoke as hs
from open_simulator_tpu.serve import HAState, IngestWAL

real = IngestWAL.append
state = {"n": 0}
def append(self, seq, events):
    real(self, seq, events)        # the record is fsync'd BEFORE the kill
    state["n"] += 1
    if state["n"] >= %d:
        os.kill(os.getpid(), signal.SIGKILL)
IngestWAL.append = append

_, batches = hs._workload()
ha = HAState.open(%r, hs._build_image,
                  checkpoint_every=hs.CHECKPOINT_EVERY)
for evs in batches:
    ha.ingest(evs)
print("UNREACHABLE")
""" % (os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
       KILL_AFTER_APPENDS, STATE_DIR)
    proc = subprocess.run([sys.executable, "-c", child],
                          capture_output=True, text=True, timeout=540)
    assert proc.returncode == -signal.SIGKILL, \
        f"child did not die by SIGKILL: rc={proc.returncode} " \
        f"{proc.stderr[-400:]}"
    assert "UNREACHABLE" not in proc.stdout

    # restart from the state dir: checkpoint (first CHECKPOINT_EVERY
    # batches sealed) + WAL-tail replay, then finish the burst
    ha = HAState.open(STATE_DIR, _build_image,
                      checkpoint_every=CHECKPOINT_EVERY)
    assert os.path.exists(os.path.join(STATE_DIR, "checkpoint.bin")), \
        "child never compacted: the restart exercised no checkpoint"
    assert ha.replayed >= 1, "restart replayed nothing from the WAL tail"
    applied = ha.image.seq
    assert applied == KILL_AFTER_APPENDS, \
        f"restart seq {applied}: the durable-but-unapplied record must " \
        f"replay (WAL-ahead), expected {KILL_AFTER_APPENDS}"
    for evs in batches[applied:]:
        ha.ingest(evs)
    got = ha.image.session(_req()).run()
    assert ha.image.epoch == oracle.epoch, \
        f"epoch diverged: {ha.image.epoch} != {oracle.epoch}"
    assert _host_truth(ha.image) == _host_truth(oracle), \
        "restarted host truth != never-crashed host truth"
    _same_answer(got, want, "crash-restart answer")
    ha.close()
    shutil.rmtree(STATE_DIR)
    row["sigkill_restart"] = {
        "killed_after_appends": KILL_AFTER_APPENDS,
        "replayed": ha.replayed, "skipped": ha.skipped,
        "final_epoch": oracle.epoch,
    }


# --------------------------------------------------- fault-site replay -------


def ha_site_sweep(row):
    import shutil
    import tempfile

    fired = {}
    for site in ("wal_write", "wal_fsync", "checkpoint_write",
                 "ingest_stall"):
        traces = []
        for rep in range(2):
            d = tempfile.mkdtemp(prefix=f"ha_smoke_{site}_")
            ha = HAState.open(d, _build_image, checkpoint_every=1)
            plan = FaultPlan.from_json({"faults": [
                {"site": site, "attempt": 1, "error": "transient"}]})
            with installed(plan) as active:
                raised = False
                try:
                    ha.ingest([{"type": "node_drain", "name": "node-00000"}])
                except Exception:
                    raised = True  # the clean-failure surface
                if site == "checkpoint_write":
                    # the batch was durable + applied before compaction
                    # failed: the ingest must SUCCEED (a 500 would make the
                    # client double-apply via retry) and degrade instead
                    assert not raised, f"{site}: landed ingest failed"
                else:
                    assert raised, f"{site}: injected fault vanished"
                traces.append(list(active.trace))
            assert ha.degraded_reason() is not None, \
                f"{site}: ingest failure did not flip degraded mode"
            # recovery: the next good ingest marks healthy again
            ha.ingest([{"type": "node_drain", "name": "node-00001"}])
            assert ha.degraded_reason() is None and ha.healthy(), \
                f"{site}: recovery ingest did not clear degraded mode"
            ha.close()
            shutil.rmtree(d)
        assert traces[0] == traces[1], f"{site}: trace not replay-equal"
        assert traces[0], f"{site}: site never fired"
        fired[site] = len(traces[0])
    row["ha_sites_replay_equal"] = fired


# ------------------------------------------------------------- overload ------


def overload_smoke(row):
    nodes, _ = _workload()
    img = ResidentImage.try_build(nodes)
    ac = AdmissionController(max_queue=2, seed=7)
    svc = WhatIfService(img, window_ms=300.0, fanout=4, admission=ac)
    svc.submit([_pod(2000)])  # pay the compile before the burst

    results = []
    lock = threading.Lock()

    def go(i):
        try:
            out = svc.submit([_pod(3000 + i)])
            with lock:
                results.append(("ok", out["scheduled"]))
        except ShedError as e:
            assert e.retry_after > 0
            with lock:
                results.append(("shed", e.reason))

    threads = [threading.Thread(target=go, args=(i,)) for i in range(24)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert not any(t.is_alive() for t in threads), \
        "overload wedged a request thread"
    ok = [r for r in results if r[0] == "ok"]
    shed = [r for r in results if r[0] == "shed"]
    assert len(ok) + len(shed) == 24, results
    assert shed, "bounded queue never shed under a 24-wide burst"
    assert ac.sheds == len(shed), "shed decisions not counted"
    after = svc.submit([_pod(4000)])  # the service takes new work post-burst
    assert after["total"] == 1
    svc.stop()

    # deterministic slice: scripted clock + token bucket pins exact sheds
    t = [0.0]
    ac2 = AdmissionController(max_queue=64, tenant_rate=1.0,
                              tenant_burst=4.0, seed=0, clock=lambda: t[0])
    svc2 = WhatIfService(img, window_ms=0.0, admission=ac2)
    outcomes = []
    for i in range(8):  # clock frozen: exactly the 4-token burst admits
        try:
            svc2.submit([_pod(5000 + i)], tenant="tb")
            outcomes.append("ok")
        except ShedError as e:
            outcomes.append(e.reason)
    assert outcomes.count("ok") == 4 and \
        outcomes.count("rate_limit") == 4, outcomes
    svc2.stop()
    row["overload"] = {"burst": 24, "completed": len(ok),
                       "shed": len(shed),
                       "sheds_total": _sum("simon_serve_sheds_total")}


def main() -> int:
    row = {"metric": "ha_smoke"}
    ha_site_sweep(row)
    sigkill_restart_smoke(row)
    overload_smoke(row)
    wrong = _sum("simon_serve_wrong_epoch_answers_total")
    mism = _sum("simon_serve_wal_parity_mismatches_total")
    assert wrong == 0, f"wrong-epoch tripwire fired {wrong}x"
    assert mism == 0, f"WAL parity-mismatch tripwire fired {mism}x"
    row["wrong_epoch_total"] = wrong
    row["wal_mismatches_total"] = mism
    row["wal_ops_total"] = _sum("simon_serve_wal_ops_total")
    row["checkpoints_total"] = _sum("simon_serve_checkpoints_total")
    print(json.dumps(row), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
