#!/usr/bin/env python3
"""CI entry for simonlint: lint the package tree, record the bench, gate the build.

    python tools/run_analysis.py                  # cold+warm lint of open_simulator_tpu/,
                                                  # update BENCH_ANALYSIS.json
    python tools/run_analysis.py --no-bench p1 p2 # lint explicit paths, no bench record
    python tools/run_analysis.py --format json    # one-off flagged run; never rewrites
                                                  # BENCH_ANALYSIS.json (bare run only)

Equivalent to `python -m open_simulator_tpu.cli lint` plus the repo-root
bench bookkeeping: BENCH_ANALYSIS.json tracks analyzer wall time (budget:
<10s on the full tree) and per-rule finding counts so a future PR that slows
the pass down or starts leaning on suppressions shows up in the diff. The
bare invocation runs the tree TWICE — a cold pass against a cleared
.simonlint_cache.json, then a warm cache-backed pass — and records both
timings, proving the content-hash cache keeps the warm path inside budget as
the repo grows."""

from __future__ import annotations

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


_VALUE_FLAGS = {"--format", "--select", "--fail-on", "--bench-out", "--cache"}


def _has_positional(args) -> bool:
    skip = False
    for a in args:
        if skip:
            skip = False
        elif a in _VALUE_FLAGS:
            skip = True
        elif a.startswith("--") and "=" in a:
            continue
        elif not a.startswith("-"):
            return True
    return False


def _bench_cold_warm() -> int:
    """The default CI/bench path: cold pass (cleared cache) + warm pass over
    the package tree, both recorded in BENCH_ANALYSIS.json."""
    from open_simulator_tpu.analysis.runner import (
        LintCache, Severity, analyze_paths, format_human, write_bench)

    cache_path = os.path.join(REPO_ROOT, ".simonlint_cache.json")
    if os.path.exists(cache_path):
        os.remove(cache_path)  # an honest cold timing, not a stale-hit mix
    tree = os.path.join(REPO_ROOT, "open_simulator_tpu")
    cold = analyze_paths([tree], cache=LintCache(cache_path))
    warm = analyze_paths([tree], cache=LintCache(cache_path))
    print(format_human(cold))
    print(f"simonlint warm pass: {warm.elapsed_s:.2f}s "
          f"({warm.cache_hits} hit(s), {warm.cache_misses} miss(es))")

    # simonrace flow tier in isolation: the CFG/dataflow rules dominate the
    # analyzer's cost growth, so their cold/warm seconds get their own bench
    # row and budget. Separate scratch cache — select-restricted results must
    # never seed the full-ruleset cache above.
    flow_rules = ["race-unguarded-attr", "lock-order-cycle",
                  "entropy-into-report", "thread-owner"]
    flow_cache = os.path.join(REPO_ROOT, ".simonlint_flow_cache.json")
    if os.path.exists(flow_cache):
        os.remove(flow_cache)
    flow_cold = analyze_paths([tree], select=flow_rules,
                              cache=LintCache(flow_cache))
    flow_warm = analyze_paths([tree], select=flow_rules,
                              cache=LintCache(flow_cache))
    if os.path.exists(flow_cache):
        os.remove(flow_cache)  # scratch only; the real cache is above
    flow_budget_s = 8.0
    print(f"simonrace flow pass: cold {flow_cold.elapsed_s:.2f}s / warm "
          f"{flow_warm.elapsed_s:.2f}s (budget {flow_budget_s:.0f}s)")

    write_bench(cold, os.path.join(REPO_ROOT, "BENCH_ANALYSIS.json"),
                warm=warm,
                extra={"flow": {
                    "rules": flow_rules,
                    "elapsed_cold_s": round(flow_cold.elapsed_s, 4),
                    "elapsed_warm_s": round(flow_warm.elapsed_s, 4),
                    "budget_s": flow_budget_s,
                    "within_budget": flow_cold.elapsed_s <= flow_budget_s,
                }})
    if flow_cold.elapsed_s > flow_budget_s:
        print(f"simonrace flow pass over budget: {flow_cold.elapsed_s:.2f}s "
              f"> {flow_budget_s:.0f}s", file=sys.stderr)
        return 1
    return 1 if cold.active(Severity.WARNING) else 0


def main(argv=None) -> int:
    from open_simulator_tpu.analysis.runner import run_lint

    args = list(sys.argv[1:] if argv is None else argv)
    if "--no-bench" in args:
        args.remove("--no-bench")
        if not _has_positional(args):
            args.append(os.path.join(REPO_ROOT, "open_simulator_tpu"))
        return run_lint(args)
    if not args:
        return _bench_cold_warm()
    # flagged invocations never rewrite BENCH_ANALYSIS.json: only the bare
    # cold+warm run produces the full record (a legacy single-pass write
    # would silently drop the warm-cache fields); pass --bench-out FILE
    # explicitly to record a one-off run elsewhere
    if not _has_positional(args):
        args.append(os.path.join(REPO_ROOT, "open_simulator_tpu"))
    return run_lint(args)


if __name__ == "__main__":
    sys.exit(main())
