#!/usr/bin/env python3
"""CI entry for simonlint: lint the package tree, record the bench, gate the build.

    python tools/run_analysis.py                  # lint open_simulator_tpu/, update BENCH_ANALYSIS.json
    python tools/run_analysis.py --no-bench p1 p2 # lint explicit paths, no bench record

Equivalent to `python -m open_simulator_tpu.cli lint` plus the repo-root
bench bookkeeping: BENCH_ANALYSIS.json tracks analyzer wall time (budget:
<10s on the full tree) and per-rule finding counts so a future PR that slows
the pass down or starts leaning on suppressions shows up in the diff."""

from __future__ import annotations

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


_VALUE_FLAGS = {"--format", "--select", "--fail-on", "--bench-out"}


def _has_positional(args) -> bool:
    skip = False
    for a in args:
        if skip:
            skip = False
        elif a in _VALUE_FLAGS:
            skip = True
        elif a.startswith("--") and "=" in a:
            continue
        elif not a.startswith("-"):
            return True
    return False


def main(argv=None) -> int:
    from open_simulator_tpu.analysis.runner import run_lint

    args = list(sys.argv[1:] if argv is None else argv)
    if "--no-bench" in args:
        args.remove("--no-bench")
    elif "--bench-out" not in args:
        args = ["--bench-out", os.path.join(REPO_ROOT, "BENCH_ANALYSIS.json")] + args
    if not _has_positional(args):
        args.append(os.path.join(REPO_ROOT, "open_simulator_tpu"))
    return run_lint(args)


if __name__ == "__main__":
    sys.exit(main())
