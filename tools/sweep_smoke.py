"""CI smoke for simonsweep (the batched scenario-sweep engine).

Asserts, on a small all-family sweep:
  1. batched==serial parity on EVERY lane (the runner's full-parity mode —
     a census mismatch raises and fails the smoke);
  2. seeded determinism: two runs of the same spec+seed produce
     byte-identical report JSON, and a different seed changes the
     Monte-Carlo draws;
  3. report schema: required keys, fraction bounds, lane accounting;
  4. counters: simon_sweep_* reconcile exactly with the report;
  5. the CLI end to end: `simon sweep examples/sweeps/zone-outage.yaml`
     exits 0 and reproduces the committed expected-report snippet.

Run: JAX_PLATFORMS=cpu python tools/sweep_smoke.py
"""

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SPEC = {
    "kind": "SweepSpec",
    "metadata": {"name": "smoke"},
    "spec": {
        "seed": 9,
        "base": {"synthetic": {"nodes": 15, "zones": 3, "cpu": "8",
                               "memory": "16Gi", "bound": 10,
                               "boundCpu": "1", "boundMemory": "1Gi"}},
        "workload": [
            {"name": "web", "replicas": 48, "cpu": "1250m",
             "memory": "1Gi"},
            {"name": "pair", "replicas": 6, "cpu": "250m",
             "memory": "256Mi", "affinityOn": "pair"},
        ],
        "families": [
            {"kind": "zone_outage", "zones": "all"},
            {"kind": "node_drain", "counts": [2], "draws": 2},
            {"kind": "preemption_storm", "storms": [12, 30], "cpu": "2",
             "memory": "2Gi"},
            {"kind": "rollout_wave", "workload": "web", "steps": [50, 100],
             "cpu": "1500m", "memory": "1536Mi"},
            {"kind": "nodepool_mix", "counts": [2, 4], "cpu": "16",
             "memory": "32Gi"},
            {"kind": "monte_carlo", "draws": 3, "templates": [
                {"name": "mc-a", "replicas": [10, 50], "cpu": "750m",
                 "memory": "768Mi"}]},
        ],
    },
}


def fail(msg: str) -> None:
    print(f"SWEEP SMOKE FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def run_once(seed=None):
    from open_simulator_tpu.sweep import (
        SweepRunner, build_report, parse_spec, report_json)

    runner = SweepRunner(parse_spec(SPEC), seed=seed, parity="full",
                         fanout=8)
    runner.run()
    report = build_report(runner)
    return report, report_json(report)


def check_schema(report: dict) -> None:
    for key in ("kind", "schema", "name", "seed", "spec_digest", "base",
                "lanes", "dispatches", "parity", "scenarios", "families"):
        if key not in report:
            fail(f"report missing key {key!r}")
    n = len(report["scenarios"])
    if sum(report["lanes"].values()) != n:
        fail(f"lane counts {report['lanes']} do not sum to {n} scenarios")
    for row in report["scenarios"]:
        if not (0.0 <= row["fraction"] <= 1.0):
            fail(f"scenario {row['id']} fraction out of bounds: {row}")
        if row["scheduled"] + row["unscheduled"] != row["pods"]:
            fail(f"scenario {row['id']} pod accounting broken: {row}")
    fams = {f["kind"] for f in SPEC["spec"]["families"]} | {"baseline"}
    if set(report["families"]) != fams:
        fail(f"family summaries {set(report['families'])} != {fams}")
    storms = report["families"]["preemption_storm"]
    if "victims" not in storms or storms["victims"]["max"] < 1:
        fail(f"storm victims missing/empty on a capacity-bound cluster: "
             f"{storms}")
    env = report["families"]["nodepool_mix"].get("capacity_envelope", [])
    if [e["pool"] for e in env] != [2, 4]:
        fail(f"capacity envelope malformed: {env}")


def check_counters(report: dict) -> None:
    from open_simulator_tpu.obs import REGISTRY

    vals = REGISTRY.values()

    def total(prefix: str) -> float:
        return sum(v for k, v in vals.items() if k.startswith(prefix))

    n = len(report["scenarios"]) * 2  # two full runs before this check
    if total("simon_sweep_scenarios_total") != n:
        fail(f"simon_sweep_scenarios_total {total('simon_sweep_scenarios_total')} != {n}")
    want_dispatch = sum(report["dispatches"].values()) * 2
    if total("simon_sweep_dispatches_total") != want_dispatch:
        fail(f"simon_sweep_dispatches_total != {want_dispatch}")
    checked = report["parity"]["checked"] * 2
    if vals.get("simon_sweep_parity_checks_total") != checked:
        fail(f"simon_sweep_parity_checks_total != {checked}")
    if vals.get("simon_sweep_parity_mismatches_total"):
        fail("parity mismatch counter moved")


def check_cli() -> None:
    spec = os.path.join(REPO, "examples", "sweeps", "zone-outage.yaml")
    expected_path = os.path.join(REPO, "examples", "sweeps",
                                 "zone-outage.expected.json")
    out = os.path.join(tempfile.mkdtemp(prefix="sweep-smoke-"),
                       "report.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "open_simulator_tpu.cli", "sweep", spec,
         "--out", out], env=env, capture_output=True, text=True,
        timeout=240, cwd=REPO)
    if proc.returncode != 0:
        fail(f"CLI sweep exited {proc.returncode}: {proc.stderr[-500:]}")
    with open(out) as fh:
        report = json.load(fh)
    with open(expected_path) as fh:
        expected = json.load(fh)
    for key in ("name", "seed", "spec_digest", "lanes", "families"):
        if report[key] != expected[key]:
            fail(f"CLI report {key} diverged from the committed snippet:\n"
                 f"  got  {report[key]}\n  want {expected[key]}")
    got_rows = [{k: r[k] for k in ("id", "label", "route", "pods",
                                   "scheduled", "fraction", "nodes")}
                for r in report["scenarios"]]
    if got_rows != expected["scenarios"]:
        fail("CLI per-scenario rows diverged from the committed snippet")


def main() -> None:
    report1, json1 = run_once()
    check_schema(report1)
    if report1["parity"]["checked"] != sum(
            report1["lanes"].get(r, 0) for r in ("wave", "scan")):
        fail(f"full parity did not cover every batched lane: "
             f"{report1['parity']} vs {report1['lanes']}")
    _, json2 = run_once()
    if json1 != json2:
        fail("same seed produced different report JSON (determinism broken)")
    check_counters(report1)
    report3, _ = run_once(seed=1234)
    mc1 = [r["pods"] for r in report1["scenarios"]
           if r["family"] == "monte_carlo"]
    mc3 = [r["pods"] for r in report3["scenarios"]
           if r["family"] == "monte_carlo"]
    if mc1 == mc3:
        fail(f"--seed did not change the Monte-Carlo draws: {mc1}")
    check_cli()
    print(f"sweep smoke ok: {len(report1['scenarios'])} scenarios, "
          f"lanes {report1['lanes']}, dispatches {report1['dispatches']}, "
          f"{report1['parity']['checked']} parity lanes, byte-identical "
          f"re-run, seeded MC divergence, CLI snippet match")


if __name__ == "__main__":
    main()
