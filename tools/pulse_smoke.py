#!/usr/bin/env python
"""CI smoke for simonpulse (obs/pulse.py): the per-dispatch performance
ledger, fast and CPU-only.

Closed-loop Simulator.schedule_pods workload with the ledger ON, asserting
the acceptance contract:

1. **Ledger/counter reconciliation** — the number of dispatch records the
   ledger holds for the measured run equals the
   simon_compile_cache_{hits,misses}_total delta EXACTLY (record_dispatch is
   the single definition of "one dispatch happened": the census and the
   ledger are fed by the same call), and the run records' pod total equals
   the simon_scheduling_attempts_total delta.
2. **Pulse-off bit-identity** — the same workload with pulse off returns
   identical placements and failure reasons, and moves NO simon_pulse_*
   metric sample (pulse-off /metrics output byte-identical in the pulse
   families).
3. **Phase decomposition** — every run record decomposes into the
   encode/table_build/to_device/dispatch/fetch/commit phases and the phase
   sum never exceeds the run wall.
4. **JSONL spill round-trip** — the spilled ledger re-read through
   `simon pulse --jsonl` machinery (pulse.summarize_records) agrees with the
   live summary on record counts per (kernel, digest).
5. **Overhead gate** — warm scheduling with the ledger on stays within
   GATE (default the ISSUE's 10%; OPEN_SIMULATOR_PULSE_GATE overrides for
   noisy hosts) of the pulse-off wall, judged on the MEDIAN of alternating
   off/on window pairs like tools/scope_smoke.py (a single off->on
   comparison is confounded by throughput drift on a 1-core CI host).

Run: JAX_PLATFORMS=cpu python tools/pulse_smoke.py
"""

import copy
import gc
import json
import os
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from open_simulator_tpu.obs import REGISTRY, pulse  # noqa: E402
from open_simulator_tpu.simulator.engine import Simulator  # noqa: E402
from open_simulator_tpu.utils.synth import synth_cluster  # noqa: E402

N_NODES, N_PODS = 64, 800
PAIRS = 3          # off/on window pairs for the overhead gate
RUNS_PER_WINDOW = 3
GATE = float(os.environ.get("OPEN_SIMULATOR_PULSE_GATE", "0.10"))


def run_once(nodes, pods):
    sim = Simulator(copy.deepcopy(nodes))
    t0 = time.perf_counter()
    failed = sim.schedule_pods(copy.deepcopy(pods))
    dt = time.perf_counter() - t0
    placements = {}
    for i, node_pods in enumerate(sim.pods_on_node):
        for p in node_pods:
            placements[p["metadata"]["name"]] = i
    reasons = {u.pod["metadata"]["name"]: u.reason for u in failed}
    return dt, placements, reasons


def pulse_sample_lines() -> list:
    """Rendered simon_pulse_* SAMPLE lines (HELP/TYPE headers excluded:
    registering a family is free, emitting samples is what pulse-off must
    never do)."""
    return [l for l in REGISTRY.render_text().splitlines()
            if l.startswith("simon_pulse_") and not l.startswith("#")]


def _sum(values, prefix):
    return sum(v for k, v in values.items() if k.startswith(prefix))


def main() -> int:
    nodes, pods = synth_cluster(N_NODES, N_PODS, hard_predicates=True)

    # ---- pulse-off: warm the compile caches, record the oracle placements,
    # and assert the pulse families stay silent
    assert pulse.active() is None
    run_once(nodes, pods)                       # cold compiles
    _, placed_off, reasons_off = run_once(nodes, pods)
    leaked = pulse_sample_lines()
    assert not leaked, (
        f"pulse-off run emitted simon_pulse_* samples (byte-identity "
        f"broken): {leaked[:4]}")

    # ---- pulse-on: bit-identity + exact reconciliation on one warm run
    spill = os.path.join(tempfile.mkdtemp(prefix="pulse-smoke-"),
                         "ledger.jsonl")
    p = pulse.enable(jsonl=spill)
    run_once(nodes, pods)                       # ledger warm-up run
    before = len(p.records())
    v0 = REGISTRY.values()
    _, placed_on, reasons_on = run_once(nodes, pods)
    v1 = REGISTRY.values()
    new = p.records()[before:]

    assert placed_on == placed_off, (
        "pulse-on placements diverged from pulse-off")
    assert reasons_on == reasons_off, "pulse-on failure reasons diverged"

    d_hits = _sum(v1, "simon_compile_cache_hits_total") - _sum(
        v0, "simon_compile_cache_hits_total")
    d_miss = _sum(v1, "simon_compile_cache_misses_total") - _sum(
        v0, "simon_compile_cache_misses_total")
    d_attempts = _sum(v1, "simon_scheduling_attempts_total") - _sum(
        v0, "simon_scheduling_attempts_total")
    disp_recs = [r for r in new if r["kind"] == "dispatch"]
    run_recs = [r for r in new if r["kind"] == "run"]
    assert len(disp_recs) == d_hits + d_miss, (
        f"ledger holds {len(disp_recs)} dispatch records but the compile "
        f"census moved {d_hits + d_miss} (hits {d_hits} + misses {d_miss}) "
        f"— an unattributed or double-counted dispatch")
    assert sum(r["pods"] for r in run_recs) == d_attempts, (
        sum(r["pods"] for r in run_recs), d_attempts)
    d_ledger = _sum(v1, "simon_pulse_records_total") - _sum(
        v0, "simon_pulse_records_total")
    assert d_ledger == len(new), (d_ledger, len(new))

    # every dispatch record is attributed and keyed
    for r in disp_recs:
        assert r["kernel"] and len(r["digest"]) == 16, r
        assert r["site"] in ("dispatch", "fetch"), r
    # phase decomposition: all phases present across run records, and the
    # per-run DISJOINT phase sum never exceeds the run wall (table_build is
    # a slice of encode — the ROADMAP-5 per-chunk instrument — so it is
    # excluded from the disjointness check)
    phases_seen = set()
    for r in run_recs:
        phases_seen |= set(r["phases"])
        disjoint = sum(v for k, v in r["phases"].items()
                       if k != "table_build")
        assert disjoint <= r["wall_s"] * 1.001 + 1e-6, r
        assert r["phases"].get("table_build", 0.0) <= r["phases"].get(
            "encode", 0.0) * 1.001 + 1e-6, r
    assert {"encode", "to_device", "dispatch", "fetch",
            "commit"} <= phases_seen, phases_seen

    # ---- JSONL spill round-trip (counts per (kernel, digest) agree)
    live = p.summary()
    pulse.disable()                             # closes the spill file
    with open(spill, encoding="utf-8") as f:
        spilled = [json.loads(l) for l in f if l.strip()]
    offline = pulse.summarize_records(spilled)
    live_n = {(r["kernel"], r["digest"]): r["n"] for r in live["kernels"]}
    off_n = {(r["kernel"], r["digest"]): r["n"] for r in offline["kernels"]}
    assert live_n == off_n, (
        f"JSONL round-trip diverged from the live ledger: "
        f"{sorted(set(live_n.items()) ^ set(off_n.items()))[:4]}")
    assert offline["records_total"] == live["records_total"], (
        offline["records_total"], live["records_total"])

    # ---- overhead gate: alternating off/on warm-window pairs
    pair_overheads = []
    t_off = t_on = 0.0
    for i in range(PAIRS):
        gc.collect()
        a = min(run_once(nodes, pods)[0] for _ in range(RUNS_PER_WINDOW))
        pulse.enable()
        gc.collect()
        b = min(run_once(nodes, pods)[0] for _ in range(RUNS_PER_WINDOW))
        pulse.disable()
        pair_overheads.append(b / a - 1.0)
        t_off, t_on = a, b
    overhead = statistics.median(pair_overheads)

    print(json.dumps({
        "dispatch_records": len(disp_recs), "run_records": len(run_recs),
        "census_delta": d_hits + d_miss, "attempts_delta": d_attempts,
        "spilled": len(spilled),
        "phase_seconds": live["phase_seconds"],
        "wall_off_s": round(t_off, 4), "wall_on_s": round(t_on, 4),
        "pair_overheads": [round(o, 4) for o in pair_overheads],
        "overhead_frac": round(overhead, 4), "gate": GATE,
    }))
    assert overhead <= GATE, (
        f"median ledger overhead {overhead:.1%} exceeds the {GATE:.0%} "
        f"gate (pairs: {[f'{o:.1%}' for o in pair_overheads]})")
    print("pulse smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
