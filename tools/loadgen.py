"""Closed-loop load generator for simonserve (the /v1/whatif serving bench).

Builds a warm resident image over a synthetic N-node cluster (default 10k
nodes with a committed base load), then drives C closed-loop clients — each
issues a what-if request drawn from a small template pool (the warm-serving
shape: repeated what-if templates mean every group is already interned and a
request encode is a dict hit per pod), waits for the response, and
immediately issues the next. Concurrency is what the micro-batching
dispatcher coalesces; the loop measures sustained requests/s and latency
percentiles, verifies a sample of responses against the serial fresh-encode
oracle (ResidentImage.fresh_probe), and optionally sprinkles live ingest
deltas mid-run to prove serving survives churn.

Default drive is in-process through WhatIfService.submit — the serving
engine (image + batcher + device dispatch) is the system under test;
--http routes every request through the real HTTP stack instead (stdlib
http.server framing then dominates the measurement).

Emits one JSON row on stdout and merges a `serve_whatif_rps` row into
BENCH_DETAIL.json (replacing any previous serve row) with --out.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# requests/s the ROADMAP serving target names (item 3: >=1k req/s sustained,
# p99 < 50ms on a warm 10k-node image)
BASELINE_RPS = 1000.0


def build_image(n_nodes: int, base_load_frac: float):
    from open_simulator_tpu.serve import ResidentImage
    from open_simulator_tpu.utils.synth import synth_node, synth_pod

    nodes = [synth_node(i) for i in range(n_nodes)]
    bound = []
    n_bound = int(n_nodes * base_load_frac)
    for i in range(n_bound):
        pod = synth_pod(i, cpu_milli=4000, mem_bytes=8 << 30,
                        labels={"app": f"base-{i % 16}"})
        pod["spec"]["nodeName"] = f"node-{i % n_nodes:05d}"
        bound.append(pod)
    image = ResidentImage.try_build(nodes, pods=bound)
    if image is None:
        raise SystemExit("resident image declined the synthetic cluster")
    return image


def request_pool(n_templates: int):
    """A pool of small what-if shapes cycling pod counts/sizes — the repeated
    templates real what-if traffic asks (deploy X more replicas of app Y)."""
    from open_simulator_tpu.utils.synth import synth_pod

    pool = []
    for t in range(n_templates):
        n = 1 + t % 4
        pods = [synth_pod(100000 + t * 10 + j,
                          cpu_milli=100 * (1 + t % 3),
                          mem_bytes=(256 << 20) * (1 + t % 2),
                          labels={"app": f"whatif-{t}"})
                for j in range(n)]
        pool.append(pods)
    return pool


def run_loadgen(args) -> dict:
    import numpy as np

    from open_simulator_tpu.serve import WhatIfService

    t0 = time.perf_counter()
    image = build_image(args.nodes, args.base_load)
    build_s = time.perf_counter() - t0
    svc = WhatIfService(image, window_ms=args.window_ms, fanout=args.fanout)
    pool = request_pool(args.templates)

    submit = svc.submit
    if args.http:
        submit = _http_submit(svc, args)

    # warmup: intern every template FIRST (the group axis G reaches its
    # final padded size), THEN compile every lane-count bucket (1, 2, 4,
    # ..., fanout) at that final G. The other order leaves (G_final, S)
    # holes that compile mid-measurement only when coalescing happens to
    # form an S-lane batch — a timing-dependent compile-miss set the bench
    # gate would flag as nondeterministic drift.
    for pods in pool:
        submit(pods)
    S = 1
    while S <= args.fanout:
        image.dispatch_sessions(
            [image.session(pool[i % len(pool)]) for i in range(S)])
        S *= 2
    warm = [None] * args.concurrency

    def warm_lane(i):
        warm[i] = submit(pool[i % len(pool)])

    ts = [threading.Thread(target=warm_lane, args=(i,))
          for i in range(args.concurrency)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()

    errors: list = []
    lock = threading.Lock()

    def drive(duration: float, seed_base: int, err_sink: list = errors):
        """One closed-loop window: C clients for `duration` seconds.
        Returns (requests, wall_s, latencies_s). `err_sink` defaults to the
        ROW's error list; the scoped window passes its own so a failure
        there cannot blame the measured tracing-off workload."""
        stop_at = time.monotonic() + duration
        lat: list = []
        counts = [0] * args.concurrency

        def client(ci: int) -> None:
            rng = np.random.default_rng(seed_base + ci)
            local_lat = []
            done = 0
            while time.monotonic() < stop_at:
                pods = pool[int(rng.integers(0, len(pool)))]
                t1 = time.perf_counter()
                try:
                    submit(pods)
                except Exception as e:  # counted, never silent
                    with lock:
                        err_sink.append(repr(e))
                    break
                local_lat.append(time.perf_counter() - t1)
                done += 1
            with lock:
                lat.extend(local_lat)
                counts[ci] = done

        t_run = time.perf_counter()
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(args.concurrency)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return sum(counts), time.perf_counter() - t_run, lat

    churn_stop = threading.Event()
    churn_direct = bool(getattr(args, "churn_direct", False))
    churn_q = None
    churn_sync = None
    churn_sync_thread = None

    def _churn_delta(i: int):
        """One churn step's (added pod, deleted name|None) — shared by both
        drive modes so the A/B compares paths, not workloads."""
        from open_simulator_tpu.utils.synth import synth_pod

        pod = synth_pod(900000 + i, labels={"app": "churn"})
        pod["spec"]["nodeName"] = f"node-{i % args.nodes:05d}"
        deleted = f"pod-{900000 + i - 4:06d}" if i > 4 else None
        return pod, deleted

    def churner_direct() -> None:
        """Legacy mid-run churn: hand-built ingest deltas applied straight
        to the image (--churn-direct, kept as the A/B reference for the
        watch-path mode below)."""
        i = 0
        while not churn_stop.wait(0.25):
            i += 1
            pod, deleted = _churn_delta(i)
            image.apply_events([
                {"type": "pod_add", "pod": pod}] + ([
                    {"type": "pod_delete", "namespace": "default",
                     "name": deleted}] if deleted else []))

    def churner_watch() -> None:
        """Default mid-run churn: the same deltas as watch JSON lines (with
        monotone resourceVersions and a BOOKMARK safe point per burst)
        pushed into a QueueSource; the WatchSync thread decodes, dedups,
        and applies them — churn exercises the production live-sync ingest
        path, not a hand-rolled shortcut."""
        rv = 10_000_000
        i = 0
        while not churn_stop.wait(0.25):
            i += 1
            pod, deleted = _churn_delta(i)
            rv += 1
            pod["kind"] = "Pod"
            pod["metadata"]["resourceVersion"] = str(rv)
            lines = [json.dumps({"type": "ADDED", "object": pod})]
            if deleted:
                rv += 1
                lines.append(json.dumps({"type": "DELETED", "object": {
                    "kind": "Pod", "metadata": {
                        "name": deleted, "namespace": "default",
                        "resourceVersion": str(rv)}}}))
            rv += 1
            lines.append(json.dumps({"type": "BOOKMARK", "object": {
                "kind": "Pod",
                "metadata": {"resourceVersion": str(rv)}}}))
            for ln in lines:
                churn_q.push(ln)

    ch = threading.Thread(
        target=churner_direct if churn_direct else churner_watch,
        daemon=True)
    if args.churn:
        if not churn_direct:
            from open_simulator_tpu.live import QueueSource, WatchSync

            churn_q = QueueSource()
            churn_sync = WatchSync(churn_q, image=image)
            churn_sync_thread = churn_sync.start_thread(churn_stop)
        ch.start()
    # the MEASURED window runs with simonscope OFF: the serve_whatif_rps
    # row stays comparable across PRs, and the scoped window below reports
    # its own rps so the overhead is an explicit column instead of silent
    # drift. Batch/coalescing stats are COUNTER DELTAS around this window —
    # warmup, parity-sample, and scope-window batches must not contaminate
    # the row's lanes_mean.
    from open_simulator_tpu.obs import REGISTRY

    batches0 = REGISTRY.values().get("simon_serve_batches_total", 0)
    n, wall, lat = drive(args.duration, seed_base=1000)
    batches = int(REGISTRY.values().get("simon_serve_batches_total", 0)
                  - batches0)
    churn_stop.set()
    churn_cols: dict = {}
    if args.churn:
        ch.join(timeout=5.0)
        if churn_sync is not None:
            # drain: close the queue (sentinel) and wait for the sync
            # thread to flush its last bookmark-batched apply — the parity
            # sample below must see the fully-applied image
            churn_q.close()
            churn_sync_thread.join(timeout=10.0)
            st = churn_sync.stats()
            churn_cols = {"churn_drive": "watch",
                          "churn_events_applied": st["applied"],
                          "churn_batches": st["batches"]}
        else:
            churn_cols = {"churn_drive": "direct"}

    # parity sample: resident answers vs the serial fresh-encode oracle
    parity_ok = True
    for pods in pool[:args.parity_sample]:
        got = svc.submit(pods)
        want = image.fresh_probe(pods)
        if (got["scheduled"] != want["scheduled"]
                or got["total"] != want["total"]
                or got["utilization"] != want["utilization"]):
            parity_ok = False
            errors.append(f"parity mismatch: {got} != {want}")

    # simonscope window: a second (shorter) scoped run on the same warm
    # image, measuring (a) the queue/dispatch/fetch latency decomposition
    # the bench row now carries and (b) the tracing-on rps for the <=10%
    # overhead gate (tools/scope_smoke.py enforces it; the row reports it)
    scope_cols: dict = {}
    if args.scope_window > 0:
        from open_simulator_tpu.obs import scope as scope_mod

        sc = scope_mod.enable(sampler=True, sampler_interval_s=0.5)
        scope_errors: list = []
        n_on, wall_on, _ = drive(args.scope_window, seed_base=5000,
                                 err_sink=scope_errors)
        rps_on = n_on / wall_on if wall_on > 0 else 0.0
        snap = sc.slo.snapshot()["endpoints"].get("whatif", {})
        phases = snap.get("phases", {})
        rps_off_est = n / wall if wall > 0 else 0.0
        scope_cols = {
            **{f"{ph}_ms_{q}": phases.get(ph, {}).get(f"{q}_ms", 0.0)
               for ph in ("queue", "dispatch", "fetch")
               for q in ("p50", "p99")},
            "scope_rps": round(rps_on, 1),
            "scope_overhead_frac": round(
                max(0.0, 1.0 - rps_on / rps_off_est)
                if rps_off_est > 0 else 0.0, 4),
            "scope_trace_events": (sc.stats()["trace_events"]
                                   + sc.stats()["trace_requests"]),
            "scope_errors": len(scope_errors),
            "scope_error_sample": scope_errors[:3],
        }
        scope_mod.disable()
    svc.stop()

    lat_ms = sorted(x * 1000.0 for x in lat)

    def pct(p: float) -> float:
        if not lat_ms:
            return 0.0
        return round(lat_ms[min(len(lat_ms) - 1, int(p * len(lat_ms)))], 3)

    vals = REGISTRY.values()
    rps = n / wall if wall > 0 else 0.0
    return {
        "metric": "serve_whatif_rps",
        "value": round(rps, 1),
        "unit": "req/s",
        "vs_baseline": round(rps / BASELINE_RPS, 4),
        "requests": n,
        "errors": len(errors),
        "error_sample": errors[:3],
        "duration_s": round(wall, 3),
        "p50_ms": pct(0.50),
        "p99_ms": pct(0.99),
        "nodes": args.nodes,
        "concurrency": args.concurrency,
        "window_ms": args.window_ms,
        "fanout": args.fanout,
        "drive": "http" if args.http else "inproc",
        "churn": bool(args.churn),
        **churn_cols,
        "image_build_s": round(build_s, 3),
        "epoch": image.epoch,
        "batches": batches,
        "lanes_mean": round(n / max(1, batches), 2),
        "seed_refreshes": int(
            vals.get("simon_serve_seed_refreshes_total", 0)),
        **scope_cols,
        "parity_ok": parity_ok,
        "backend": "default",
        # the full flat registry dump rides the row (like bench.py's rows):
        # tools/bench_gate.py diffs it against the committed baseline
        "obs_metrics": vals,
    }


def _http_submit(svc, args):
    """Route requests through the real HTTP stack (one server, per-thread
    connections)."""
    import http.client

    from open_simulator_tpu.server.http import Server

    server = Server(snapshot_fn=lambda: (_ for _ in ()).throw(
        RuntimeError("loadgen injects the image directly")), whatif=True)
    server._whatif_svc = svc
    httpd = server.build_httpd(port=0)
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    local = threading.local()

    def submit(pods):
        conn = getattr(local, "conn", None)
        if conn is None:
            conn = local.conn = http.client.HTTPConnection(
                "127.0.0.1", port, timeout=120)
        conn.request("POST", "/v1/whatif", json.dumps({"pods": pods}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        body = json.loads(resp.read())
        if resp.status != 200:
            raise RuntimeError(f"http {resp.status}: {body}")
        return body

    return submit


def merge_row(row: dict, path: str) -> None:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        doc = {"results": []}
    results = [r for r in doc.get("results", [])
               if r.get("metric") != row["metric"]]
    results.append(row)
    doc["results"] = results
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="closed-loop what-if serving load generator (simonserve)")
    parser.add_argument("--nodes", type=int, default=10_000)
    parser.add_argument("--base-load", type=float, default=0.5, metavar="FRAC",
                        help="bound base-load pods as a fraction of nodes")
    parser.add_argument("--duration", type=float, default=5.0)
    parser.add_argument("--concurrency", type=int, default=16)
    parser.add_argument("--window-ms", type=float, default=2.0)
    parser.add_argument("--fanout", type=int, default=8)
    parser.add_argument("--templates", type=int, default=12)
    parser.add_argument("--parity-sample", type=int, default=4)
    parser.add_argument("--churn", action="store_true",
                        help="apply live pod-churn ingest deltas mid-run "
                             "(through the simonsync watch path by default)")
    parser.add_argument("--churn-direct", action="store_true",
                        dest="churn_direct",
                        help="with --churn: apply deltas straight to the "
                             "image (legacy path, kept for A/B against the "
                             "watch-source mode)")
    parser.add_argument("--http", action="store_true",
                        help="drive through the real HTTP stack instead of "
                             "in-process submit")
    parser.add_argument("--scope-window", type=float, default=2.0,
                        metavar="S",
                        help="after the measured (tracing-off) window, run a "
                             "scoped window of S seconds for the "
                             "queue/dispatch/fetch breakdown columns and the "
                             "tracing-on rps (0 disables; default 2)")
    parser.add_argument("--out", default="",
                        help="merge the row into this BENCH_DETAIL.json")
    args = parser.parse_args(argv)

    row = run_loadgen(args)
    print(json.dumps(row))
    if args.out:
        merge_row(row, args.out)
    return 0 if (row["parity_ok"] and not row["errors"]) else 1


if __name__ == "__main__":
    sys.exit(main())
