"""Headline benchmark: batched scheduling throughput.

Workload (BASELINE.md config #2): 1,000-node synthetic cluster, 10,000 nginx-shaped
pods with cpu/mem requests — the NodeResourcesFit-dominated shape. The metric is
end-to-end pods scheduled per second with a warm compile cache: host-side batch
encoding + one compiled `lax.scan` over all 10k pods on the accelerator, preserving
the reference's strictly serial placement semantics
(/root/reference/pkg/simulator/simulator.go:309-348 schedules one pod per channel
handshake; here one scan step per pod).

Baseline for `vs_baseline` is the BASELINE.json north star: 100k pods onto 10k nodes
in <2s ⇒ 50,000 pods/s. vs_baseline = value / 50_000.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import sys
import time

N_NODES = 1_000
N_PODS = 10_000
BASELINE_PODS_PER_SEC = 50_000.0


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from open_simulator_tpu.ops import kernels
    from open_simulator_tpu.simulator.engine import Simulator
    from open_simulator_tpu.utils.synth import synth_cluster

    nodes, pods = synth_cluster(N_NODES, N_PODS)

    # Host encode (counted): pods -> device tables.
    t0 = time.perf_counter()
    sim = Simulator(nodes)
    bt = sim.encode_batch(pods)
    t_encode = time.perf_counter() - t0

    from open_simulator_tpu.simulator.encode import plugin_flags

    tables, carry = sim._to_device(bt)
    pg = jnp.asarray(bt.pod_group)
    fn = jnp.asarray(bt.forced_node)
    vd = jnp.asarray(bt.valid)
    enable_gpu, enable_storage = plugin_flags(bt)

    # Cold run: compile + execute (discarded). np.asarray forces a device→host
    # transfer as the sync point (block_until_ready alone can return early through
    # remote-device tunnels).
    out = kernels.schedule_batch(tables, carry, pg, fn, vd, n_zones=bt.n_zones,
                                 enable_gpu=enable_gpu, enable_storage=enable_storage)
    np.asarray(out[1])

    # Warm runs from the same initial carry.
    times = []
    for _ in range(3):
        t1 = time.perf_counter()
        final, choices = kernels.schedule_batch(
            tables, carry, pg, fn, vd, n_zones=bt.n_zones,
            enable_gpu=enable_gpu, enable_storage=enable_storage,
        )
        choices = np.asarray(choices)
        times.append(time.perf_counter() - t1)
    t_exec = min(times)
    scheduled = int((choices[np.asarray(bt.valid)] >= 0).sum())
    if scheduled != N_PODS:
        print(
            f"WARNING: only {scheduled}/{N_PODS} pods schedulable", file=sys.stderr
        )

    wall = t_encode + t_exec
    value = scheduled / wall
    print(json.dumps({
        "metric": f"pods_scheduled_per_sec_{N_PODS//1000}k_pods_{N_NODES}_nodes",
        "value": round(value, 1),
        "unit": "pods/s",
        "vs_baseline": round(value / BASELINE_PODS_PER_SEC, 4),
    }))
    print(
        f"encode {t_encode*1e3:.1f} ms, device scan {t_exec*1e3:.1f} ms, "
        f"scheduled {scheduled}/{N_PODS} on {N_NODES} nodes",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
