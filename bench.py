"""Benchmarks: batched scheduling throughput across the BASELINE.md configs.

Headline (stdout, ONE JSON line): the north-star shape — 100,000 pods onto
10,000 nodes, end-to-end through the engine (host encode + wave/serial device
scheduling + commit bookkeeping), warm compile cache. Baseline for
`vs_baseline` is BASELINE.json's target: 100k pods in <2s ⇒ 50,000 pods/s.

The remaining configs print as JSON lines on stderr and are also written to
BENCH_DETAIL.json:
  - throughput_10k_1k:   config 2, 10k nginx pods / 1k nodes (round-1 headline)
  - gpushare_1k:         config 3, GPU-memory bin-packing on 1k GPU nodes
  - hard_predicates_50k_5k: config 4, 50k pods / 5k nodes with taints +
    anti-affinity + zone topology spread (wave + fused group-serial segments)
  - mesh8_cpu:           the mesh-sharded product path on an 8-device virtual
    CPU mesh, with a placements-match check against single-device
  - capacity_plan_100k:  config 5, add-node auto-search until 100k pods fit

All runs preserve the reference's serial placement semantics
(/root/reference/pkg/simulator/simulator.go:309-348 schedules one pod per
channel handshake; here wave segments provably reproduce consecutive serial
steps — see ops/kernels.py schedule_wave — and everything else is one
lax.scan step per pod).
"""

from __future__ import annotations

import json
import os
import sys
import time

BASELINE_PODS_PER_SEC = 50_000.0


def _schedule_run(nodes, pods):
    """One timed end-to-end engine run. Returns (seconds, scheduled, total)."""
    from open_simulator_tpu.simulator.engine import Simulator

    sim = Simulator(nodes)
    t0 = time.perf_counter()
    failed = sim.schedule_pods(pods)
    dt = time.perf_counter() - t0
    total = sum(len(p) for p in sim.pods_on_node)
    return dt, total, total + len(failed)


def bench_throughput(n_nodes, n_pods, hard=False, repeats=2):
    from open_simulator_tpu.utils.synth import synth_cluster

    best = None
    for _ in range(repeats + 1):  # first run pays the compile; keep best warm run
        nodes, pods = synth_cluster(n_nodes, n_pods, hard_predicates=hard)
        dt, placed, total = _schedule_run(nodes, pods)
        if best is None or dt < best[0]:
            best = (dt, placed, total)
    dt, placed, total = best
    return placed / dt, placed, total, dt


def bench_gpushare(n_nodes=1_000, n_pods=5_000, repeats=2):
    """Config 3: pods requesting shared GPU memory via alibabacloud.com annotations
    (open-gpu-share.go Filter/Reserve semantics; ledger in the scan carry)."""
    from open_simulator_tpu.utils.synth import synth_node, synth_pod

    best = None
    for _ in range(repeats + 1):
        nodes = []
        for i in range(n_nodes):
            n = synth_node(i)
            for sect in ("capacity", "allocatable"):  # plugin reads capacity
                n["status"][sect]["alibabacloud.com/gpu-count"] = "8"
                n["status"][sect]["alibabacloud.com/gpu-mem"] = str(8 * 16 << 30)
            nodes.append(n)
        pods = []
        for i in range(n_pods):
            p = synth_pod(i)
            p["metadata"].setdefault("annotations", {})[
                "alibabacloud.com/gpu-mem"] = str(4 << 30)
            p["metadata"]["annotations"]["alibabacloud.com/gpu-count"] = "1"
            pods.append(p)
        dt, placed, total = _schedule_run(nodes, pods)
        if best is None or dt < best[0]:
            best = (dt, placed, total)
    dt, placed, total = best
    return placed / dt, placed, total, dt


def bench_placement_agreement(n_nodes=1_000, n_pods=10_000):
    """BASELINE's second metric: placement agreement vs the serial scheduler.
    The serial scan IS this framework's kube-scheduler semantics (one
    filter+score+commit cycle per pod; score parity unit-tested per plugin in
    tests/test_scores.py); the batched wave path must agree >=99%. Pods within
    one scheduling group are interchangeable (the reference tie-breaks
    randomly, generic_scheduler.go:188), so agreement compares per-(node,
    group) placement censuses on the hard-predicate workload."""
    import copy

    from open_simulator_tpu.simulator.encode import scheduling_signature
    from open_simulator_tpu.simulator.engine import Simulator
    from open_simulator_tpu.utils.synth import synth_cluster

    nodes, pods = synth_cluster(n_nodes, n_pods, hard_predicates=True)

    def census(use_waves):
        sim = Simulator(nodes)  # the engine deep-copies its node objects
        sim.use_waves = use_waves
        failed = sim.schedule_pods(copy.deepcopy(pods))
        placed = {}
        for i, node_pods in enumerate(sim.pods_on_node):
            for p in node_pods:
                # true interchangeability key: the scheduling signature, NOT a
                # label — synth blocks mix constraint-distinct pods under one
                # app label, which must count as disagreements when swapped
                key = (i, scheduling_signature(p))
                placed[key] = placed.get(key, 0) + 1
        fails = {}
        for u in failed:
            sig = scheduling_signature(u.pod)
            fails[sig] = fails.get(sig, 0) + 1
        return placed, fails

    wave_c, wave_f = census(True)
    serial_c, serial_f = census(False)
    total = sum(serial_c.values()) + sum(serial_f.values())
    agree = sum(min(c, wave_c.get(k, 0)) for k, c in serial_c.items())
    agree += sum(min(c, wave_f.get(s, 0)) for s, c in serial_f.items())
    return (agree / total if total else 1.0), total


def bench_capacity_plan(n_pods=100_000, repeats=1):
    """Config 5: add-node auto search — find the minimal simon-node count that
    schedules all pods within a 60% MaxCPU envelope, timing the whole search.

    Uses the applier's CapacityPlanner: the workload is expanded and encoded
    once, the search starts at the arithmetic lower bound (below which
    scheduling provably fails), and each candidate is one non-mutating device
    probe — versus the reference's loop of full re-simulations per candidate
    (apply.go:203-259). The planner's answer is exactly minimal, not the
    doubling-granularity answer the old loop produced."""
    import os

    from open_simulator_tpu.apply.applier import CapacityPlanner
    from open_simulator_tpu.utils.synth import synth_node, synth_pod

    os.environ["MaxCPU"] = "60"
    try:
        base_nodes = [synth_node(i) for i in range(64)]
        template = synth_node(0)
        pods = [synth_pod(i) for i in range(n_pods)]
        best = None
        for _ in range(repeats + 1):
            t0 = time.perf_counter()
            planner = CapacityPlanner(base_nodes, template, pods)
            found, n, _ = planner.search()
            dt = time.perf_counter() - t0
            result_nodes = n if found else None
            if best is None or dt < best[0]:
                best = (dt, result_nodes)
        dt, added = best
        return n_pods / dt, added, dt
    finally:
        os.environ.pop("MaxCPU", None)


def bench_mesh_cpu(n_nodes=1_000, n_pods=10_000, shards=8):
    """Mesh-sharded product path on a virtual CPU mesh: same workload through
    Simulator(use_mesh=True) over `shards` devices and the single-device
    engine, in a subprocess (the CPU device count must be set before backend
    init). Returns (pods_per_sec, placements_match, error)."""
    import json as _json
    import subprocess

    code = f"""
import json, os, sys, time
sys.path.insert(0, {repr(__file__.rsplit('/', 1)[0])})
# config-based CPU forcing BEFORE any backend init: some images inject an
# accelerator plugin whose env-var platform override can hang at import
from open_simulator_tpu.utils.devices import force_cpu_platform, request_cpu_devices
request_cpu_devices({shards})
force_cpu_platform()
from open_simulator_tpu.utils.synth import synth_cluster
from open_simulator_tpu.simulator.engine import Simulator

def census(sim):
    out = {{}}
    for i, pods in enumerate(sim.pods_on_node):
        out[i] = len(pods)
    return out

nodes, pods = synth_cluster({n_nodes}, {n_pods})
import copy
best = None
for use_mesh in (True, True):  # first run pays the distributed compile
    sim = Simulator(copy.deepcopy(nodes), use_mesh=True)
    t0 = time.perf_counter()
    sim.schedule_pods(copy.deepcopy(pods))
    dt = time.perf_counter() - t0
    mesh_census = census(sim)
    if best is None or dt < best:
        best = dt
single = Simulator(copy.deepcopy(nodes), use_mesh=False)
single.schedule_pods(copy.deepcopy(pods))
print(json.dumps({{"rate": {n_pods} / best, "match": census(single) == mesh_census}}))
"""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # see the subprocess preamble
    env["OPEN_SIMULATOR_MESH"] = "1"
    try:
        out = subprocess.run(
            [sys.executable, "-c", code], env=env, capture_output=True,
            text=True, timeout=900,
        )
        line = out.stdout.strip().splitlines()[-1]
        data = _json.loads(line)
        return data["rate"], bool(data["match"]), ""
    except Exception as e:  # the mesh metric is best-effort; report, don't die
        return 0.0, False, f"{type(e).__name__}: {e}"


def _ensure_live_backend(probe_timeout: float = 180.0) -> str:
    """Probe the default JAX backend in a SUBPROCESS before this process
    touches it: a wedged accelerator tunnel hangs backend init holding a
    global lock, which would turn the whole bench into a silent timeout.
    On probe failure, force the CPU backend (config route — the env-var
    override can itself hang at import under injected plugins) so the bench
    still emits its JSON lines. Returns the backend label used."""
    import subprocess
    import time as _time

    import tempfile

    detail = ""
    # Popen + poll, NOT subprocess.run: run's timeout path blocks in wait()
    # after SIGKILL, which never returns for a child wedged in a D-state
    # driver ioctl — the exact failure mode being probed for. stderr goes to a
    # FILE, not a pipe: a chatty plugin writing >64KB to an undrained pipe
    # would wedge an otherwise-healthy probe into a phantom timeout.
    with tempfile.TemporaryFile() as errf:
        probe = subprocess.Popen(
            [sys.executable, "-c", "import jax; jax.devices()"],
            stdout=subprocess.DEVNULL, stderr=errf,
            start_new_session=True,
        )
        deadline = _time.time() + probe_timeout
        while _time.time() < deadline:
            rc = probe.poll()
            if rc == 0:
                return "default"
            if rc is not None:
                try:
                    errf.seek(0)
                    tail = errf.read()[-400:].decode("utf-8", "replace")
                except Exception:
                    tail = ""
                detail = f"probe exited rc={rc}: {tail.strip()}"
                break
            _time.sleep(0.5)
        else:
            probe.kill()  # best effort; no wait() — the child may be unkillable
            detail = f"probe timed out after {probe_timeout:.0f}s"
    os.environ.pop("JAX_PLATFORMS", None)
    print(json.dumps({"warning": "default backend unreachable; benching on CPU",
                      "detail": detail}),
          file=sys.stderr, flush=True)
    try:
        from open_simulator_tpu.utils.devices import force_cpu_platform

        force_cpu_platform()
    except Exception as e:  # even a broken jax install shouldn't kill the warning
        print(json.dumps({"warning": f"cpu fallback failed: {e}"}),
              file=sys.stderr, flush=True)
    return "cpu-fallback"


def main() -> None:
    backend = _ensure_live_backend()
    results = []

    # ---- headline: north star ------------------------------------------------
    rate, placed, total, dt = bench_throughput(10_000, 100_000)
    headline = {
        "metric": "pods_scheduled_per_sec_100k_pods_10k_nodes",
        "value": round(rate, 1),
        "unit": "pods/s",
        "vs_baseline": round(rate / BASELINE_PODS_PER_SEC, 4),
        **({"backend": backend} if backend != "default" else {}),
    }
    results.append(dict(headline, wall_s=round(dt, 3), scheduled=placed, total=total))
    print(json.dumps(headline), flush=True)

    # ---- config 2: 10k/1k ----------------------------------------------------
    rate, placed, total, dt = bench_throughput(1_000, 10_000)
    results.append({
        "metric": "pods_scheduled_per_sec_10k_pods_1000_nodes",
        "value": round(rate, 1), "unit": "pods/s",
        "vs_baseline": round(rate / BASELINE_PODS_PER_SEC, 4),
        "wall_s": round(dt, 3), "scheduled": placed, "total": total,
    })

    # ---- config 3: gpushare --------------------------------------------------
    rate, placed, total, dt = bench_gpushare()
    results.append({
        "metric": "gpushare_pods_per_sec_5k_pods_1k_nodes",
        "value": round(rate, 1), "unit": "pods/s",
        "vs_baseline": round(rate / BASELINE_PODS_PER_SEC, 4),
        "wall_s": round(dt, 3), "scheduled": placed, "total": total,
    })

    # ---- config 4: hard predicates ------------------------------------------
    rate, placed, total, dt = bench_throughput(5_000, 50_000, hard=True)
    results.append({
        "metric": "hard_predicate_pods_per_sec_50k_pods_5k_nodes",
        "value": round(rate, 1), "unit": "pods/s",
        "vs_baseline": round(rate / BASELINE_PODS_PER_SEC, 4),
        "wall_s": round(dt, 3), "scheduled": placed, "total": total,
    })

    # ---- placement agreement vs the serial scheduler -------------------------
    rate, total = bench_placement_agreement()
    results.append({
        "metric": "placement_agreement_waves_vs_serial_10k_hard",
        "value": round(rate, 6), "unit": "fraction",
        "vs_baseline": round(rate / 0.99, 4),  # target: >=99% agreement
        "pods": total,
    })

    # ---- mesh: sharded product path on a virtual CPU mesh --------------------
    rate, match, err = bench_mesh_cpu()
    results.append({
        "metric": "mesh8_cpu_pods_per_sec_10k_pods_1k_nodes",
        "value": round(rate, 1), "unit": "pods/s",
        "vs_baseline": round(rate / BASELINE_PODS_PER_SEC, 4),
        "placements_match_single_device": match,
        **({"error": err} if err else {}),
    })

    # ---- config 5: capacity planning ----------------------------------------
    rate, added, dt = bench_capacity_plan()
    results.append({
        "metric": "capacity_plan_pods_per_sec_100k_pods",
        # a search that exhausted its node budget has no meaningful throughput
        "value": round(rate, 1) if added is not None else 0.0,
        "unit": "pods/s",
        "vs_baseline": round(rate / BASELINE_PODS_PER_SEC, 4) if added is not None else 0.0,
        "wall_s": round(dt, 3), "nodes_added": added,
        "search_exhausted": added is None,
    })

    if backend != "default":
        # every in-process config ran on the fallback backend, not just the
        # headline — label them all so records stay backend-comparable
        for r in results:
            r.setdefault("backend", backend)
    for r in results[1:]:
        print(json.dumps(r), file=sys.stderr, flush=True)
    with open("BENCH_DETAIL.json", "w") as f:
        json.dump(results, f, indent=1)


if __name__ == "__main__":
    main()
