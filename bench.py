"""Benchmarks: batched scheduling throughput across the BASELINE.md configs.

Headline (stdout, ONE JSON line): the north-star shape — 100,000 pods onto
10,000 nodes, end-to-end through the engine (host encode + wave/serial device
scheduling + commit bookkeeping), warm compile cache. Baseline for
`vs_baseline` is BASELINE.json's target: 100k pods in <2s ⇒ 50,000 pods/s.

The remaining configs print as JSON lines on stderr and are also written to
BENCH_DETAIL.json:
  - throughput_10k_1k:   config 2, 10k nginx pods / 1k nodes (round-1 headline)
  - gpushare_1k:         config 3, GPU-memory bin-packing on 1k GPU nodes
  - hard_predicates_50k_5k: config 4, 50k pods / 5k nodes with taints +
    anti-affinity + zone topology spread (wave + fused group-serial segments)
  - mesh8_cpu:           the mesh-sharded product path on an 8-device virtual
    CPU mesh, with a placements-match check against single-device
  - mesh8_1m / mesh8_10m: the scale rows (1M pods / 100k nodes and
    10M pods / 1M nodes) on the columnar host path — PodStore/NodeStore
    template blocks, vectorized bulk commit (simulator/store.py)
  - capacity_plan_100k:  config 5, add-node auto-search until 100k pods fit
  - sweep_scenarios_256x10k: simonsweep — 256 what-if scenarios x 10k pods
    batched on the scenario axis vs a serial per-scenario Simulator loop,
    every lane's placement census parity-asserted inside the row

Wedge resilience: the accelerator tunnel can hang backend init forever (an
uninterruptible block inside jax.devices()), so this process NEVER initializes
JAX itself. It probes the default backend in a subprocess with a deadline,
runs every metric in its own subprocess (default backend if the probe
succeeded, CPU otherwise), and RE-PROBES before each metric whenever the
backend was last seen down — a tunnel that recovers mid-run still yields
partial on-chip rows. Every probe attempt is recorded (timestamps + outcome)
in BENCH_DETAIL.json's "probe_log" and appended to TPU_PROBE_LOG.jsonl. A
metric subprocess that wedges on the default backend is killed, marked, and
re-run on CPU. `.tpu_lock` is held for the duration so the background probe
logger (tools/probe_tpu.py) never pokes the chip concurrently — two clients
at once is the suspected wedge trigger.

All runs preserve the reference's serial placement semantics
(/root/reference/pkg/simulator/simulator.go:309-348 schedules one pod per
channel handshake; here wave segments provably reproduce consecutive serial
steps — see ops/kernels.py schedule_wave — and everything else is one
lax.scan step per pod).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BASELINE_PODS_PER_SEC = 50_000.0

# watch-ingest events/s the live-sync tier targets on the recorded 10k-node
# stream. Real 10k-node clusters churn O(10) events/s sustained; 1k/s of
# headroom means ingest is never the bottleneck behind the >=1k req/s
# what-if tier. The wall is dominated by the image's per-batch node-table
# restage when a window carries node adds/drains, not by decode.
BASELINE_INGEST_EVENTS_PER_SEC = 1_000.0
REPO = os.path.dirname(os.path.abspath(__file__))
LOCK = os.path.join(REPO, ".tpu_lock")
PROBE_LOG_FILE = os.path.join(REPO, "TPU_PROBE_LOG.jsonl")

INITIAL_PROBE_TIMEOUT = 120.0
RETRY_PROBE_TIMEOUT = 60.0


# --------------------------------------------------------------------------
# metric workers (run in subprocesses; the only code here that imports jax)
# --------------------------------------------------------------------------

def _schedule_run(nodes, pods):
    """One timed end-to-end engine run. Returns (seconds, scheduled, total)."""
    from open_simulator_tpu.simulator.engine import Simulator

    sim = Simulator(nodes)
    t0 = time.perf_counter()
    failed = sim.schedule_pods(pods)
    dt = time.perf_counter() - t0
    total = sum(len(p) for p in sim.pods_on_node)
    return dt, total, total + len(failed)


def bench_throughput(n_nodes, n_pods, hard=False, repeats=2):
    from open_simulator_tpu.utils.synth import synth_cluster

    best = None
    for _ in range(repeats + 1):  # first run pays the compile; keep best warm run
        nodes, pods = synth_cluster(n_nodes, n_pods, hard_predicates=hard)
        dt, placed, total = _schedule_run(nodes, pods)
        if best is None or dt < best[0]:
            best = (dt, placed, total)
    dt, placed, total = best
    return placed / dt, placed, total, dt


def bench_gpushare(n_nodes=1_000, n_pods=5_000, repeats=2):
    """Config 3: pods requesting shared GPU memory via alibabacloud.com annotations
    (open-gpu-share.go Filter/Reserve semantics; ledger in the scan carry)."""
    from open_simulator_tpu.utils.synth import synth_node, synth_pod

    best = None
    for _ in range(repeats + 1):
        nodes = []
        for i in range(n_nodes):
            n = synth_node(i)
            for sect in ("capacity", "allocatable"):  # plugin reads capacity
                n["status"][sect]["alibabacloud.com/gpu-count"] = "8"
                n["status"][sect]["alibabacloud.com/gpu-mem"] = str(8 * 16 << 30)
            nodes.append(n)
        pods = []
        for i in range(n_pods):
            p = synth_pod(i)
            p["metadata"].setdefault("annotations", {})[
                "alibabacloud.com/gpu-mem"] = str(4 << 30)
            p["metadata"]["annotations"]["alibabacloud.com/gpu-count"] = "1"
            pods.append(p)
        dt, placed, total = _schedule_run(nodes, pods)
        if best is None or dt < best[0]:
            best = (dt, placed, total)
    dt, placed, total = best
    return placed / dt, placed, total, dt


def bench_placement_agreement(n_nodes=1_000, n_pods=10_000):
    """BASELINE's second metric: placement agreement vs the serial scheduler.
    The serial scan IS this framework's kube-scheduler semantics (one
    filter+score+commit cycle per pod; score parity unit-tested per plugin in
    tests/test_scores.py); the batched wave path must agree >=99%. Pods within
    one scheduling group are interchangeable (the reference tie-breaks
    randomly, generic_scheduler.go:188), so agreement compares per-(node,
    group) placement censuses on the hard-predicate workload."""
    import copy

    from open_simulator_tpu.simulator.encode import scheduling_signature
    from open_simulator_tpu.simulator.engine import Simulator
    from open_simulator_tpu.utils.synth import synth_cluster

    nodes, pods = synth_cluster(n_nodes, n_pods, hard_predicates=True)

    def census(use_waves):
        sim = Simulator(nodes)  # the engine deep-copies its node objects
        sim.use_waves = use_waves
        failed = sim.schedule_pods(copy.deepcopy(pods))
        placed = {}
        for i, node_pods in enumerate(sim.pods_on_node):
            for p in node_pods:
                # true interchangeability key: the scheduling signature, NOT a
                # label — synth blocks mix constraint-distinct pods under one
                # app label, which must count as disagreements when swapped
                key = (i, scheduling_signature(p))
                placed[key] = placed.get(key, 0) + 1
        fails = {}
        for u in failed:
            sig = scheduling_signature(u.pod)
            fails[sig] = fails.get(sig, 0) + 1
        return placed, fails

    wave_c, wave_f = census(True)
    serial_c, serial_f = census(False)
    total = sum(serial_c.values()) + sum(serial_f.values())
    agree = sum(min(c, wave_c.get(k, 0)) for k, c in serial_c.items())
    agree += sum(min(c, wave_f.get(s, 0)) for s, c in serial_f.items())
    return (agree / total if total else 1.0), total


def bench_capacity_plan(n_pods=100_000, repeats=1):
    """Config 5: add-node auto search — find the minimal simon-node count that
    schedules all pods within a 60% MaxCPU envelope, timing the whole search.

    Uses the applier's CapacityPlanner: the workload is expanded and encoded
    once, the search starts at the arithmetic lower bound (below which
    scheduling provably fails), and each candidate is one non-mutating device
    probe — versus the reference's loop of full re-simulations per candidate
    (apply.go:203-259). The planner's answer is exactly minimal, not the
    doubling-granularity answer the old loop produced."""
    from open_simulator_tpu.apply.applier import CapacityPlanner
    from open_simulator_tpu.utils.synth import synth_node, synth_pod

    os.environ["MaxCPU"] = "60"
    try:
        base_nodes = [synth_node(i) for i in range(64)]
        template = synth_node(0)
        pods = [synth_pod(i) for i in range(n_pods)]
        best = None
        for _ in range(repeats + 1):
            t0 = time.perf_counter()
            planner = CapacityPlanner(base_nodes, template, pods)
            found, n, _ = planner.search()
            dt = time.perf_counter() - t0
            result_nodes = n if found else None
            if best is None or dt < best[0]:
                best = (dt, result_nodes, dict(planner.stats))
        dt, added, stats = best
        return n_pods / dt, added, dt, stats
    finally:
        os.environ.pop("MaxCPU", None)


def bench_mesh_cpu(n_nodes=1_000, n_pods=10_000, shards=8, hard=False,
                   check_single=True, repeats=2, timeout=900, store=False):
    """Mesh-sharded product path on a virtual CPU mesh, in a subprocess (the
    CPU device count must be set before backend init). Measurement protocol
    matches bench_throughput exactly — fresh synth inputs per repeat, the
    timer brackets only schedule_pods — so the mesh rows compare 1:1 against
    the single-chip rows (the old protocol deep-copied the 10k-pod list
    INSIDE the timed region, ~0.35s of host copying billed to the mesh).

    With check_single the same workload also runs single-device and the
    per-(node, scheduling-signature) censuses must match bit-for-bit. The row
    embeds the run's sharding-layout health: reshard_bytes (the
    simon_reshard_bytes_total counter — carry bytes whose post-dispatch
    layout diverged from the declared shardings; 0 = chained dispatches never
    reshard) and transfer_bytes (host→device staging).

    Returns (pods_per_sec, wall_s, scheduled, total, match, reshard_bytes,
    transfer_bytes, pulse_block, error) — pulse_block is the subprocess'
    simonpulse summary (phase wall decomposition, per-kernel roofline
    numbers, streaming chunk count), or {} when the run errored."""
    code = f"""
import json, os, sys, time
sys.path.insert(0, {repr(REPO)})
# config-based CPU forcing BEFORE any backend init: some images inject an
# accelerator plugin whose env-var platform override can hang at import
from open_simulator_tpu.utils.devices import force_cpu_platform, request_cpu_devices
request_cpu_devices({shards})
force_cpu_platform()
from open_simulator_tpu.utils.synth import synth_cluster, synth_cluster_store
from open_simulator_tpu.simulator.engine import Simulator
from open_simulator_tpu.simulator.encode import scheduling_signature
from open_simulator_tpu.obs import REGISTRY, pulse
pulse.enable(roofline_dispatch=True)

def census(sim):
    out = {{}}
    for i, pods in enumerate(sim.pods_on_node):
        for p in pods:
            key = (i, scheduling_signature(p))
            out[key] = out.get(key, 0) + 1
    return out

def one_run(use_mesh, want_census):
    # store=True rides the columnar host path (simulator/store.py): the
    # workload is template blocks, encode is one gather per template, and
    # the commit is one bulk array pass — at 1M+ pods the dict form is the
    # thing being replaced (and at 10M it does not fit in host memory)
    if {store}:
        nodes, pods = synth_cluster_store({n_nodes}, {n_pods},
                                          hard_predicates={hard})
    else:
        nodes, pods = synth_cluster({n_nodes}, {n_pods},
                                    hard_predicates={hard})
    sim = Simulator(nodes, use_mesh=use_mesh)
    t0 = time.perf_counter()
    failed = sim.schedule_pods(pods)
    dt = time.perf_counter() - t0
    total = sim.pods_on_node.total()
    # census materializes every placed pod (the lazy read-back boundary):
    # only compute it when a single-device comparison will consume it
    c = census(sim) if want_census else None
    return dt, total, total + len(failed), c

best = None
n_runs = {repeats} + 1
for _ in range(n_runs):  # first run pays the distributed compile
    dt, placed, total, mesh_census = one_run(True, {check_single})
    if best is None or dt < best[0]:
        best = (dt, placed, total, mesh_census)
dt, placed, total, mesh_census = best
# snapshot the sharding-health counters BEFORE the single-device comparison
# run, which would otherwise pollute them: reshard_bytes covers EVERY mesh
# run (0 across all is the stronger claim), transfer_bytes is per-run (each
# repeat stages the same tables once)
vals = REGISTRY.values()
reshard = int(vals.get("simon_reshard_bytes_total") or 0)
transfer = int(vals.get("simon_device_transfer_bytes_total") or 0) // n_runs
match = True
if {check_single}:
    _, _, _, single_census = one_run(False, True)
    match = single_census == mesh_census
# the simonpulse ledger ran across every repeat: ship the wall decomposition,
# per-kernel roofline numbers, and the streaming chunk count back to the row
summ = pulse.active().summary()
print(json.dumps({{
    "rate": placed / dt, "wall_s": dt, "scheduled": placed, "total": total,
    "match": match,
    "reshard_bytes": reshard,
    "transfer_bytes": transfer,
    "pulse": {{
        "phase_seconds": summ["phase_seconds"],
        "records": summ["records_total"],
        "regressions": summ["regressions_total"],
        "stream_chunks": int(vals.get("simon_stream_chunks_total") or 0),
        "kernels": summ["kernels"],
    }},
}}))
"""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # see the subprocess preamble
    env["OPEN_SIMULATOR_MESH"] = "1"
    try:
        out = subprocess.run(
            [sys.executable, "-c", code], env=env, capture_output=True,
            text=True, timeout=timeout,
        )
        data = None
        for line in reversed(out.stdout.strip().splitlines()):
            if line.startswith("{"):
                data = json.loads(line)
                break
        if data is None:
            raise ValueError(f"no row line (rc={out.returncode}, "
                             f"stderr tail: {out.stderr[-300:]!r})")
        return (data["rate"], data["wall_s"], data["scheduled"],
                data["total"], bool(data["match"]), data["reshard_bytes"],
                data["transfer_bytes"], data.get("pulse") or {}, "")
    except Exception as e:  # the mesh metric is best-effort; report, don't die
        return 0.0, 0.0, 0, 0, False, -1, -1, {}, f"{type(e).__name__}: {e}"


# --------------------------------------------------------------------------
# metric registry: name -> (row builder, subprocess timeout seconds)
# --------------------------------------------------------------------------

def _row_north_star():
    rate, placed, total, dt = bench_throughput(10_000, 100_000)
    return {
        "metric": "pods_scheduled_per_sec_100k_pods_10k_nodes",
        "value": round(rate, 1), "unit": "pods/s",
        "vs_baseline": round(rate / BASELINE_PODS_PER_SEC, 4),
        "wall_s": round(dt, 3), "scheduled": placed, "total": total,
    }


def _row_throughput_10k_1k():
    rate, placed, total, dt = bench_throughput(1_000, 10_000)
    return {
        "metric": "pods_scheduled_per_sec_10k_pods_1000_nodes",
        "value": round(rate, 1), "unit": "pods/s",
        "vs_baseline": round(rate / BASELINE_PODS_PER_SEC, 4),
        "wall_s": round(dt, 3), "scheduled": placed, "total": total,
    }


def _row_gpushare():
    rate, placed, total, dt = bench_gpushare()
    return {
        "metric": "gpushare_pods_per_sec_5k_pods_1k_nodes",
        "value": round(rate, 1), "unit": "pods/s",
        "vs_baseline": round(rate / BASELINE_PODS_PER_SEC, 4),
        "wall_s": round(dt, 3), "scheduled": placed, "total": total,
    }


def _hard_segment_breakdown(n_nodes=5_000, n_pods=50_000):
    """Per-segment-kind pod counts and wall share for the hard-predicate
    workload, from ONE extra instrumented run (OPEN_SIMULATOR_SEGMENT_TIMING
    blocks on every segment, so it never taints the timed rows). Registry
    deltas isolate this run from the timed repeats in the same process."""
    import re

    from open_simulator_tpu.obs import REGISTRY
    from open_simulator_tpu.utils.synth import synth_cluster

    def seg_values():
        out = {}
        pat = re.compile(
            r"^simon_segment_(pods_total|wall_seconds_total)\{kind=\"(\w+)\"\}$")
        for key, val in REGISTRY.values().items():
            mt = pat.match(key)
            if mt:
                out[(mt.group(2), mt.group(1))] = float(val)
        return out

    before = seg_values()
    os.environ["OPEN_SIMULATOR_SEGMENT_TIMING"] = "1"
    try:
        nodes, pods = synth_cluster(n_nodes, n_pods, hard_predicates=True)
        _schedule_run(nodes, pods)
    finally:
        os.environ.pop("OPEN_SIMULATOR_SEGMENT_TIMING", None)
    after = seg_values()
    kinds = sorted({k for k, _ in after})
    wall = {k: after.get((k, "wall_seconds_total"), 0.0)
            - before.get((k, "wall_seconds_total"), 0.0) for k in kinds}
    total_wall = sum(wall.values()) or 1.0
    return {
        k: {
            "pods": int(after.get((k, "pods_total"), 0.0)
                        - before.get((k, "pods_total"), 0.0)),
            "wall_s": round(wall[k], 3),
            "wall_share": round(wall[k] / total_wall, 4),
        }
        for k in kinds
    }


def _row_hard():
    rate, placed, total, dt = bench_throughput(5_000, 50_000, hard=True)
    row = {
        "metric": "hard_predicate_pods_per_sec_50k_pods_5k_nodes",
        "value": round(rate, 1), "unit": "pods/s",
        "vs_baseline": round(rate / BASELINE_PODS_PER_SEC, 4),
        "wall_s": round(dt, 3), "scheduled": placed, "total": total,
    }
    # attribution ride-along: which segment kind owns this row's wall time,
    # so a future regression is explainable without a profile run
    try:
        row["segments"] = _hard_segment_breakdown()
    except Exception as e:  # the breakdown must never fail the metric
        row["segments_error"] = f"{type(e).__name__}: {e}"
    return row


def _row_xray_overhead():
    """xray acceptance row: the flight recorder's wall-time overhead on the
    100k-pod unconstrained bench (budget: <= 15%). Runs the workload warm
    with recording OFF then ON (same synth config; trace spills to a temp
    prefix, JSONL + npz included in the measured time) and reports the
    fraction plus the recorder's own record counts."""
    import tempfile

    from open_simulator_tpu.obs import xray

    rate_off, placed_off, total_off, dt_off = bench_throughput(
        10_000, 100_000, repeats=1)
    prefix = os.path.join(tempfile.mkdtemp(prefix="bench-xray-"), "trace")
    xray.enable(prefix)
    try:
        rate_on, placed_on, total_on, dt_on = bench_throughput(
            10_000, 100_000, repeats=1)
    finally:
        rec = xray.active()
        counts = rec.counts() if rec is not None else {}
        xray.disable()
    frac = (dt_on - dt_off) / dt_off if dt_off else 0.0
    return {
        "metric": "xray_overhead_frac_100k_pods_10k_nodes",
        "value": round(frac, 4), "unit": "fraction",
        # budget-relative: >= 1.0 means within the 15% acceptance budget
        "vs_baseline": round(0.15 / frac, 4) if frac > 0 else 99.0,
        "budget_frac": 0.15, "within_budget": frac <= 0.15,
        "wall_off_s": round(dt_off, 3), "wall_on_s": round(dt_on, 3),
        "pods_per_sec_off": round(rate_off, 1),
        "pods_per_sec_on": round(rate_on, 1),
        # scheduled/total COUNT parity only — per-pod placement bit-identity
        # is asserted by tools/xray_smoke.py and tests/test_xray.py
        "scheduled_counts_match": (placed_on == placed_off
                                   and total_on == total_off),
        "decision_records": counts.get("pods"),
        "decision_sets": counts.get("sets"),
        "trace_bytes": (os.path.getsize(prefix + ".jsonl")
                        if os.path.exists(prefix + ".jsonl") else 0),
    }


def _row_agreement():
    rate, total = bench_placement_agreement()
    return {
        "metric": "placement_agreement_waves_vs_serial_10k_hard",
        "value": round(rate, 6), "unit": "fraction",
        "vs_baseline": round(rate / 0.99, 4),  # target: >=99% agreement
        "pods": total,
    }


def _mesh_row(metric, **kw):
    (rate, wall, placed, total, match, reshard, transfer, pblock,
     err) = bench_mesh_cpu(**kw)
    return {
        "metric": metric,
        "value": round(rate, 1), "unit": "pods/s",
        "vs_baseline": round(rate / BASELINE_PODS_PER_SEC, 4),
        "wall_s": round(wall, 3), "scheduled": placed, "total": total,
        "placements_match_single_device": match,
        # sharding-layout health: reshard_bytes must stay 0 (chained
        # dispatches reuse the declared carry shardings end-to-end); a
        # nonzero value localizes a layout regression to this row
        "reshard_bytes": reshard, "transfer_bytes": transfer,
        # subprocess simonpulse block (phase decomposition + roofline);
        # _run_worker's setdefault leaves this one in place
        **({"pulse": pblock} if pblock else {}),
        **({"error": err} if err else {}),
    }


def _streaming_verdict(pblock: dict) -> str:
    """ROADMAP item 5 adjudication, from the row's own pulse counters: the
    streaming path DOES re-pay the node-axis table build once per chunk
    (build_batch_tables runs per streamed chunk), so quantify it — per-chunk
    seconds and share of the run wall decide whether hoisting the node side
    out of the chunk loop is worth an engine change."""
    chunks = pblock.get("stream_chunks") or 0
    phases = pblock.get("phase_seconds") or {}
    tb = phases.get("table_build") or 0.0
    if chunks < 2:
        return ("streaming not engaged (single batch): no per-chunk "
                "table-build re-payment to measure")
    wall = sum(phases.values()) or 1.0
    share = tb / wall
    per_chunk_ms = tb / chunks * 1e3
    verdict = ("CONFIRMED but minor" if share < 0.05 else "CONFIRMED, "
               "significant — hoist the node-axis build out of the chunk "
               "loop")
    return (f"ROADMAP-5 ({chunks:.0f} chunks): node-axis table build "
            f"re-paid per chunk at {per_chunk_ms:.1f}ms/chunk, "
            f"{share:.1%} of phase wall — {verdict} (measured ~27ms/chunk, "
            f"2.6% at 100k nodes; 0.6ms/chunk, 0.1% at 1k nodes)")


def _row_mesh8():
    return _mesh_row("mesh8_cpu_pods_per_sec_10k_pods_1k_nodes")


def _row_mesh8_hard():
    """The affinity-wave route (zone spread / anti-affinity / taints) under
    sharding: epoch-batched counter-live segments whose normalizer min/max
    and winner argmax are the only values crossing shard boundaries."""
    return _mesh_row("mesh8_hard_pods_per_sec_10k_pods_1k_nodes", hard=True,
                     timeout=1500)


def _row_mesh8_1m():
    """The scale proof: 1M pods onto 100k nodes only fits as a sharded
    program (the 'millions of users' shape, ~10x the north star). One timed
    run — at this size the single-device comparison would double a
    multi-minute row, and bit-identity is already asserted per-route by the
    10k mesh rows, tests/test_mesh_sharding.py, and tools/mesh_smoke.py.
    Rides the columnar host path (store=True): workload as PodStore/NodeStore
    template blocks, vectorized bulk commit — the dict-path encode/commit
    loops were ~60% of this row's wall (ROADMAP item 2); double-encode
    bit-identity columnar==dict is tests/test_store.py's job."""
    row = _mesh_row("mesh8_1m_pods_per_sec_1m_pods_100k_nodes",
                    n_nodes=100_000, n_pods=1_000_000, check_single=False,
                    repeats=1, timeout=2700, store=True)
    row["placements_match_single_device"] = None  # not run at this size
    if "pulse" in row:
        row["note"] = _streaming_verdict(row["pulse"])
    return row


def _row_mesh8_10m():
    """Planet scale: 10M pods onto 1M nodes. Only expressible on the
    columnar host path — 10M pod dicts alone would need ~25GB of host
    memory before the first encode; the store holds the batch as template
    blocks + three [P] columns (~200MB). One timed run, no single-device
    comparison (same policy as the 1M row)."""
    row = _mesh_row("mesh8_10m_pods_per_sec_10m_pods_1m_nodes",
                    n_nodes=1_000_000, n_pods=10_000_000, check_single=False,
                    repeats=0, timeout=2700, store=True)
    row["placements_match_single_device"] = None  # not run at this size
    if "pulse" in row:
        row["note"] = _streaming_verdict(row["pulse"])
    return row


def bench_sweep(n_scenarios=256, n_nodes=960):
    """simonsweep: 256 scenarios x 10k pods — the batched scenario sweep
    (one shared device-resident image, copy-on-write per-lane overlays, a
    few sweep_wave_fanout dispatches) vs the reference-style serial loop
    (one fresh Simulator + full engine run per scenario). Parity is asserted
    inside the row: every lane's placement census must equal its serial
    run's bit-for-bit. On the 1-core bench host both paths run the same
    math serially, so the ratio measures DISPATCH AMORTIZATION (one encode
    + a few compiled fan-outs vs 256 rebuild/encode/dispatch cycles), not
    parallel speedup; a real scenario mesh shards the lanes one-per-device
    on top of this."""
    from open_simulator_tpu.sweep import SweepRunner, parse_spec

    templates = [
        {"name": f"app-{i}", "replicas": 1250,
         "cpu": f"{400 + 70 * i}m", "memory": f"{256 + 64 * i}Mi"}
        for i in range(8)
    ]  # 8 x 1250 = 10k pods, ~6.1k cpu on an 8k-cpu cluster (tight)
    spec_doc = {
        "kind": "SweepSpec",
        "metadata": {"name": "bench-256x10k"},
        "spec": {
            "seed": 20260804,
            "base": {"synthetic": {"nodes": n_nodes, "zones": 8,
                                   "cpu": "8", "memory": "16Gi"}},
            "workload": templates,
            "families": [
                {"kind": "zone_outage", "zones": "all", "width": 1},   # 8
                {"kind": "zone_outage", "zones": "all", "width": 2},   # 28
                {"kind": "node_drain", "counts": [4, 8, 16, 32, 64],
                 "draws": 36},                                         # 180
                {"kind": "preemption_storm",
                 "storms": [250, 500, 1000, 2000],
                 "cpu": "2", "memory": "2Gi"},                         # 4
                {"kind": "rollout_wave", "workload": "app-0",
                 "steps": [20, 40, 60, 80, 100],
                 "cpu": "600m", "memory": "640Mi"},                    # 5
                {"kind": "nodepool_mix", "counts": [8, 16, 32, 64],
                 "cpu": "16", "memory": "32Gi"},                       # 4
                {"kind": "monte_carlo", "draws": 26, "templates": [
                    {"name": f"mc-{i}", "replicas": [900, 1600],
                     "cpu": f"{450 + 60 * i}m",
                     "memory": f"{256 + 48 * i}Mi"}
                    for i in range(8)]},                               # 26
            ],
        },
    }
    # fanout 32: the cache sweet spot on the 1-core host (a [S, N, B]
    # score table for 32 lanes stays resident; 256 lanes thrash), and the
    # shape-bucketed chunking keeps storm-sized lanes out of the common
    # chunks' static shapes. 960 base + 64 pool nodes = exactly the 1024
    # node bucket (1000 would pad every table to 2048 columns).
    runner = SweepRunner(parse_spec(spec_doc), parity="off", fanout=32)
    t0 = time.perf_counter()
    results = runner.run()
    batched_s = time.perf_counter() - t0
    assert len(results) == n_scenarios, len(results)
    routes = {}
    for res in results.values():
        routes[res.route] = routes.get(res.route, 0) + 1
    pods_total = sum(res.total for res in results.values())
    sched_total = sum(res.scheduled for res in results.values())

    # the serial comparison loop doubles as the parity oracle: every lane's
    # placement census must match its fresh serial run exactly
    mismatches = 0
    t0 = time.perf_counter()
    for sid in sorted(results):
        res = results[sid]
        oracle = runner.serial_result(res.scenario)
        if (res.census != oracle.census
                or res.scheduled != oracle.scheduled):
            mismatches += 1
        # free the big per-lane census as we go (256 lanes x ~6k entries)
        results[sid] = res._replace(census={})
    serial_s = time.perf_counter() - t0
    return (batched_s, serial_s, routes, pods_total, sched_total,
            mismatches, dict(runner.dispatches))


def _row_sweep():
    (batched_s, serial_s, routes, pods_total, sched_total, mismatches,
     dispatches) = bench_sweep()
    n = sum(routes.values())
    ratio = serial_s / batched_s if batched_s else 0.0
    return {
        "metric": "sweep_scenarios_256x10k",
        "value": round(n / batched_s, 2), "unit": "scenarios/s",
        # vs_baseline is the work-reduction ratio: batched sweep vs the
        # reference-style serial per-scenario loop on the same host
        "vs_baseline": round(ratio, 4),
        "wall_s": round(batched_s, 3),
        "serial_wall_s": round(serial_s, 3),
        "work_reduction": round(ratio, 2),
        "scenarios": n, "pods_total": pods_total,
        "scheduled_total": sched_total,
        "routes": routes, "dispatches": dispatches,
        "parity_mismatches": mismatches,
        "parity_ok": mismatches == 0,
        "note": "1-core bench host: both paths run the same scheduling "
                "math serially, so work_reduction measures dispatch "
                "amortization (1 encode + a few compiled fan-outs vs 256 "
                "rebuild/encode/dispatch cycles), not parallel speedup; "
                "the scenario axis shards one-lane-per-device on a real "
                "mesh",
    }


def _row_capacity():
    rate, added, dt, stats = bench_capacity_plan()
    return {
        "metric": "capacity_plan_pods_per_sec_100k_pods",
        # a search that exhausted its node budget has no meaningful throughput
        "value": round(rate, 1) if added is not None else 0.0,
        "unit": "pods/s",
        "vs_baseline": round(rate / BASELINE_PODS_PER_SEC, 4) if added is not None else 0.0,
        "wall_s": round(dt, 3), "nodes_added": added,
        "search_exhausted": added is None,
        # incremental-probe accounting: candidate evaluations, device
        # dispatches, the one-time encode wall split, pod encodings (must be
        # 1 on the incremental path), and which path ran
        "probes": stats.get("probes"),
        "dispatches": stats.get("dispatches"),
        "encode_s": round(float(stats.get("encode_s") or 0.0), 3),
        "encodes": stats.get("encodes"),
        "search_path": stats.get("path"),
    }


def _row_serve_ingest():
    """simonsync watch-ingest throughput: replay a recorded 10k-node watch
    stream (bound-pod churn + node adds/drains, bookmark-delimited) through
    the full live-sync path — parse, template-interned decode, dedup,
    bookmark-batched apply into the resident image. The pulse ledger rides
    the run, so the row decomposes into sync_decode / sync_apply wall."""
    import time as _time

    from open_simulator_tpu.live import RecordedSource, WatchSync
    from open_simulator_tpu.obs import REGISTRY, pulse
    from open_simulator_tpu.serve import ResidentImage
    from open_simulator_tpu.utils.synth import synth_watch_stream

    n_nodes, n_events = 10_000, 20_000
    t0 = time.perf_counter()
    nodes, bound, lines = synth_watch_stream(
        n_nodes, n_events, seed=11, bookmark_every=64, n_bound=n_nodes // 2)
    gen_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    image = ResidentImage.try_build(nodes, pods=bound)
    build_s = time.perf_counter() - t0
    if image is None:
        return {"metric": "serve_ingest_events_per_sec", "value": 0.0,
                "unit": "events/s", "vs_baseline": 0.0,
                "error": "resident image declined the synthetic cluster"}
    sync = WatchSync(RecordedSource(lines=lines), image=image)
    t0 = _time.perf_counter()
    st = sync.run()
    wall = _time.perf_counter() - t0
    rate = n_events / wall if wall > 0 else 0.0
    act = pulse.active()
    phases = (act.summary().get("phase_seconds", {}) if act else {})
    vals = REGISTRY.values()
    return {
        "metric": "serve_ingest_events_per_sec",
        "value": round(rate, 1), "unit": "events/s",
        "vs_baseline": round(rate / BASELINE_INGEST_EVENTS_PER_SEC, 4),
        "wall_s": round(wall, 3),
        "events": n_events,
        "batches": st["batches"],
        "applied": st["applied"],
        "skipped": st["skipped"],
        "nodes": n_nodes,
        "stream_gen_s": round(gen_s, 3),
        "image_build_s": round(build_s, 3),
        "epoch": image.epoch,
        # dict-free decode: pods from the wire intern onto shared template
        # blocks; hits/total is the fraction that never built a fresh spec
        "templates": st["templates"],
        "template_hits": st["template_hits"],
        # phase decomposition from the pulse ledger riding the run
        "decode_s": round(float(phases.get("sync_decode", 0.0)), 3),
        "apply_s": round(float(phases.get("sync_apply", 0.0)), 3),
        "reconcile_s": round(float(phases.get("sync_reconcile", 0.0)), 3),
        # a clean recorded replay must never reconcile or rebuild, and the
        # bench gate pins these families MUST_BE_ZERO
        "relists": st["relists"],
        "full_rebuilds": int(
            vals.get("simon_sync_full_rebuilds_total", 0)),
        "parity_mismatches": int(
            vals.get("simon_sync_parity_mismatches_total", 0)),
        "parity_ok": st["parity_mismatches"] == 0,
    }


# (name, builder, timeout_s, needs_device_backend). mesh8* always run on a
# virtual CPU mesh by definition, so they never probe or occupy the chip.
METRICS = [
    ("north_star", _row_north_star, 1800, True),
    ("throughput_10k_1k", _row_throughput_10k_1k, 900, True),
    ("gpushare", _row_gpushare, 900, True),
    ("hard", _row_hard, 1800, True),
    ("xray_overhead", _row_xray_overhead, 1800, True),
    ("agreement", _row_agreement, 1800, True),
    ("mesh8", _row_mesh8, 1200, False),
    ("mesh8_hard", _row_mesh8_hard, 1800, False),
    ("mesh8_1m", _row_mesh8_1m, 3000, False),
    ("mesh8_10m", _row_mesh8_10m, 3000, False),
    ("capacity", _row_capacity, 1800, True),
    ("sweep", _row_sweep, 3000, True),
    ("serve_ingest", _row_serve_ingest, 1800, False),
]


def _pulse_block(summ: dict) -> dict:
    """Trim a pulse summary() document to the fields a BENCH_DETAIL row
    carries: the run-phase wall decomposition plus per-kernel
    cost_analysis FLOPs/bytes, model-optimal seconds, and the achieved
    roofline fraction of the warm dispatches."""
    kernels = []
    for r in summ.get("kernels", []):
        k = {f: r[f] for f in ("kernel", "digest", "n", "cold", "warm")
             if f in r}
        for f in ("warm_med_s", "flops", "bytes_accessed",
                  "model_optimal_s", "achieved_frac", "regressions"):
            if f in r:
                k[f] = r[f]
        kernels.append(k)
    return {
        "phase_seconds": summ.get("phase_seconds", {}),
        "records": summ.get("records_total", 0),
        "regressions": summ.get("regressions_total", 0),
        "kernels": kernels,
    }


def _run_worker(name: str) -> None:
    """Subprocess entry: select platform, run one metric, print its row.

    The row must be the ONLY thing on fd 1: XLA/absl can log C++-side chatter
    (e.g. the cpu_aot_loader machine-feature warning) straight to the stdout
    fd, which breaks the orchestrator's row parsing. Dup the real stdout
    aside, point fd 1 at stderr for the whole run, and write the row through
    the saved fd at the end."""
    real_stdout = os.dup(1)
    os.dup2(2, 1)
    sys.stdout = sys.stderr
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        # config route, not env var: the injected accelerator plugin can hang
        # at import when JAX_PLATFORMS is set (see utils/devices.py)
        os.environ.pop("JAX_PLATFORMS", None)
        from open_simulator_tpu.utils.devices import force_cpu_platform

        force_cpu_platform()
    # the simonpulse ledger rides every metric run (dispatch-time roofline
    # harvest on, so the cost numbers match THIS row's shapes, not just the
    # audit buckets); its per-dispatch cost is microseconds against rows
    # measured in seconds, and tools/pulse_smoke.py CI-gates the overhead
    try:
        from open_simulator_tpu.obs import pulse

        pulse.enable(roofline_dispatch=True)
    except Exception:
        pulse = None  # observability must never fail the bench
    builder = {n: b for n, b, _, _ in METRICS}[name]
    row = builder()
    # each metric runs in its own subprocess, so the registry holds exactly
    # this run's counters: embed them so a perf regression row in
    # BENCH_DETAIL.json carries its own explanation (segment mix, compile
    # misses, transfer bytes, probe accounting)
    try:
        from open_simulator_tpu.obs import REGISTRY

        row["obs_metrics"] = REGISTRY.values()
    except Exception:
        pass  # observability must never fail the bench
    # every row carries its pulse block; mesh rows already embedded the
    # one their subprocess measured (setdefault keeps it)
    try:
        if pulse is not None and pulse.active() is not None:
            row.setdefault("pulse", _pulse_block(pulse.active().summary()))
    except Exception:
        pass
    os.write(real_stdout, (json.dumps(row) + "\n").encode())


# --------------------------------------------------------------------------
# orchestrator (never imports jax)
# --------------------------------------------------------------------------

def _log_probe(rec: dict, probe_log: list) -> None:
    probe_log.append(rec)
    try:
        with open(PROBE_LOG_FILE, "a") as f:
            f.write(json.dumps(dict(rec, source="bench")) + "\n")
    except OSError:
        pass
    print(json.dumps(dict(rec, probe=True)), file=sys.stderr, flush=True)


def _probe_backend(timeout: float, probe_log: list) -> bool:
    """One wedge-safe subprocess probe (shared implementation in
    open_simulator_tpu/utils/devices.py), recorded into the probe log."""
    from open_simulator_tpu.utils.devices import probe_default_backend

    ok, rec = probe_default_backend(timeout)
    _log_probe(rec, probe_log)
    return ok


# Benign XLA:CPU chatter that buries real bench output: the cpu_aot_loader
# machine-feature mismatch warning is ~2KB of feature-list spam per compile
# (it means only "this AOT cache entry was compiled on a different CPU
# model"). The driver that runs `python bench.py` records the stderr tail,
# so drop these lines before they reach our stderr — everything else passes
# through untouched.
_XLA_NOISE_MARKERS = (
    "cpu_aot_loader.cc",
    "Machine type used for XLA:CPU compilation",
    "Compile machine features:",
    "Host machine features:",
    "could lead to execution errors such as SIGILL",
)


def _is_xla_noise(line: str) -> bool:
    return any(m in line for m in _XLA_NOISE_MARKERS)


def _pump_stderr(pipe) -> None:
    """Forward a child's stderr line-by-line, dropping the known-benign
    XLA noise (see _XLA_NOISE_MARKERS)."""
    try:
        for line in pipe:
            if not _is_xla_noise(line):
                sys.stderr.write(line)
                sys.stderr.flush()
    except (OSError, ValueError):
        pass
    finally:
        try:
            pipe.close()
        except OSError:
            pass


def _run_metric(name: str, timeout: float, force_cpu: bool) -> dict | None:
    """Run one metric in a subprocess; returns its row or None on failure."""
    import threading

    env = dict(os.environ)
    if force_cpu:
        env.pop("JAX_PLATFORMS", None)
        env["BENCH_FORCE_CPU"] = "1"
    else:
        env.pop("BENCH_FORCE_CPU", None)  # a stray export would silently turn
        # "default"-labeled rows into CPU runs
    child = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--metric", name],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        start_new_session=True,
    )
    out_buf: list = []
    t_out = threading.Thread(
        target=lambda: out_buf.append(child.stdout.read()), daemon=True)
    t_err = threading.Thread(
        target=_pump_stderr, args=(child.stderr,), daemon=True)
    t_out.start()
    t_err.start()
    try:
        child.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        child.kill()
        try:
            child.wait(timeout=5)
        except subprocess.TimeoutExpired:
            pass
        return None
    t_out.join(timeout=10)
    t_err.join(timeout=10)
    out = out_buf[0] if out_buf else ""
    if child.returncode != 0:
        return None
    # the worker writes its row as the final fd-1 line, but scan backwards
    # for the last parseable JSON object anyway — belt and braces against
    # C++-side chatter that ignores the worker's fd redirection
    for line in reversed(out.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except ValueError:
                continue
    return None


def main() -> None:
    from open_simulator_tpu.utils.devices import acquire_tpu_lock, release_tpu_lock

    probe_log: list = []
    results: list = []
    headline: dict = {"metric": "pods_scheduled_per_sec_100k_pods_10k_nodes",
                      "error": "north_star did not run"}
    # hold the chip lock so tools/probe_tpu.py skips its attempts while the
    # bench may be running device work (two concurrent clients can wedge it).
    # A prober may be mid-probe (up to ~120s): wait it out, then proceed
    # regardless — benching beats deadlocking on a crashed lock holder. Track
    # whether WE got the lock: past the deadline it may still belong to a live
    # prober, and deleting a live holder's lockfile would let the next client
    # run concurrently with its in-flight probe.
    deadline = time.time() + 180
    lock_owned = acquire_tpu_lock(LOCK)
    while not lock_owned and time.time() < deadline:
        time.sleep(5)
        lock_owned = acquire_tpu_lock(LOCK)
    try:
        device_ok = _probe_backend(INITIAL_PROBE_TIMEOUT, probe_log)
        for name, _, timeout, needs_device in METRICS:
            if needs_device and not device_ok:
                # re-probe before every metric: a late-recovering tunnel
                # still yields partial on-chip rows
                device_ok = _probe_backend(RETRY_PROBE_TIMEOUT, probe_log)
            use_device = needs_device and device_ok
            row = _run_metric(name, timeout, force_cpu=not use_device)
            if row is None and use_device:
                # the device run wedged or crashed: mark the backend down and
                # redo this metric on CPU so the record stays complete
                device_ok = False
                _log_probe({"ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                            "outcome": "metric-failed-on-device", "metric": name},
                           probe_log)
                row = _run_metric(name, timeout, force_cpu=True)
                use_device = False
            if row is None:
                row = {"metric": name, "error": "metric subprocess failed",
                       "value": 0.0, "unit": "pods/s", "vs_baseline": 0.0}
            if name.startswith("mesh8"):
                row["backend"] = "cpu-virtual-mesh"
            else:
                row["backend"] = "default" if use_device else "cpu-fallback"
            results.append(row)
            if name == "north_star":
                headline = {k: row[k] for k in
                            ("metric", "value", "unit", "vs_baseline",
                             "backend") if k in row}
            print(json.dumps(row), file=sys.stderr, flush=True)
    finally:
        if lock_owned:
            release_tpu_lock(LOCK)
        # THE one stdout line, printed last: `python bench.py` piped through
        # tail/last-line parsing must always see the headline JSON, never
        # XLA/absl chatter (which all routes to stderr). Printed BEFORE the
        # detail-file write so an unwritable REPO cannot break the contract.
        print(json.dumps(headline), flush=True)
        with open(os.path.join(REPO, "BENCH_DETAIL.json"), "w") as f:
            json.dump({"results": results, "probe_log": probe_log}, f, indent=1)


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--metric":
        _run_worker(sys.argv[2])
    else:
        main()
