"""gRPC bridge: wire-codec correctness (cross-checked against protoc) and a
full channel round trip against the same Server the REST tests use."""

import json
import shutil
import subprocess
import sys
import tempfile

import pytest

from fixtures import make_deployment, make_node
from open_simulator_tpu.core.types import ResourceTypes
from open_simulator_tpu.server.grpcbridge import (
    SERVICE,
    GrpcBridge,
    decode_health_response,
    decode_simulate_request,
    decode_simulate_response,
    encode_simulate_request,
    encode_simulate_response,
)
from open_simulator_tpu.server.http import ClusterSnapshot, Server


def _snapshot(nodes):
    return ClusterSnapshot(
        ResourceTypes(nodes=list(nodes)), replica_sets=[], stateful_sets=[],
        pending_pods=[])


# ------------------------------------------------------------- wire codec ------


def test_codec_round_trip():
    payload = json.dumps({"deployments": [{"a": 1}]}).encode()
    assert decode_simulate_request(encode_simulate_request(payload)) == payload
    assert decode_simulate_request(b"") == b""
    for code, body in ((200, b'{"ok":1}'), (503, b'"busy"'), (0, b""), (70000, b"x")):
        assert decode_simulate_response(encode_simulate_response(code, body)) == (code, body)


def test_codec_skips_unknown_fields():
    # field 3 varint + field 4 length-delimited, then field 1
    data = b"\x18\x05" + b"\x22\x02ab" + encode_simulate_request(b"hi")
    assert decode_simulate_request(data) == b"hi"


@pytest.mark.skipif(shutil.which("protoc") is None, reason="protoc unavailable")
def test_codec_matches_protoc_generated():
    """The hand-rolled codec must be byte-compatible with canonical protobuf:
    generate the real module from simon.proto and compare serializations."""
    import os

    proto_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "open_simulator_tpu", "server", "proto")
    with tempfile.TemporaryDirectory() as td:
        subprocess.run(
            ["protoc", f"-I{proto_dir}", f"--python_out={td}", "simon.proto"],
            check=True, capture_output=True)
        sys.path.insert(0, td)
        try:
            import simon_pb2  # noqa: generated

            req = simon_pb2.SimulateRequest(request_json=b'{"pods": []}')
            assert req.SerializeToString() == encode_simulate_request(b'{"pods": []}')
            assert decode_simulate_request(req.SerializeToString()) == b'{"pods": []}'

            resp = simon_pb2.SimulateResponse(code=503, response_json=b'"busy"')
            assert resp.SerializeToString() == encode_simulate_response(503, b'"busy"')
            parsed = simon_pb2.SimulateResponse()
            parsed.ParseFromString(encode_simulate_response(200, b"{}"))
            assert (parsed.code, parsed.response_json) == (200, b"{}")

            health = simon_pb2.HealthResponse()
            from open_simulator_tpu.server.grpcbridge import encode_health_response

            health.ParseFromString(encode_health_response("ok"))
            assert health.message == "ok"
        finally:
            sys.path.remove(td)
            sys.modules.pop("simon_pb2", None)


# ------------------------------------------------------------ round trip -------


@pytest.fixture(scope="module")
def grpc_mod():
    return pytest.importorskip("grpc")


def test_grpc_round_trip(grpc_mod):
    grpc = grpc_mod
    nodes = [make_node("n1")]
    bridge = GrpcBridge(server=Server(snapshot_fn=lambda: _snapshot(nodes)))
    server, port = bridge.build_grpc_server(port=0, host="127.0.0.1")
    server.start()
    try:
        channel = grpc.insecure_channel(f"127.0.0.1:{port}")
        ident = lambda b: b  # noqa: E731

        health = channel.unary_unary(f"/{SERVICE}/Health",
                                     request_serializer=ident,
                                     response_deserializer=ident)
        assert decode_health_response(health(b"")) == "ok"

        deploy = channel.unary_unary(f"/{SERVICE}/DeployApps",
                                     request_serializer=ident,
                                     response_deserializer=ident)
        req = json.dumps({
            "deployments": [make_deployment("api", replicas=2, cpu="1", memory="1Gi")]
        }).encode()
        code, body = decode_simulate_response(deploy(encode_simulate_request(req)))
        assert code == 200
        result = json.loads(body)
        assert sum(len(ns["pods"]) for ns in result["nodeStatus"]) == 2
        assert result["unscheduledPods"] == []

        # malformed JSON → 400, mirroring the REST surface
        code, body = decode_simulate_response(
            deploy(encode_simulate_request(b"{not json")))
        assert code == 400

        # invalid UTF-8 payload (UnicodeDecodeError, not JSONDecodeError)
        # also stays in-band as 400 (not a grpc error)
        code, body = decode_simulate_response(
            deploy(encode_simulate_request(b"\x80abc")))
        assert code == 400

        # truncated protobuf framing (declared length > buffer) → in-band 400
        code, body = decode_simulate_response(deploy(b"\x0a\x64{}"))
        assert code == 400
    finally:
        server.stop(0)


def test_grpc_bind_failure_raises(grpc_mod):
    nodes = [make_node("n1")]
    bridge = GrpcBridge(server=Server(snapshot_fn=lambda: _snapshot(nodes)))
    server, port = bridge.build_grpc_server(port=0, host="127.0.0.1")
    server.start()
    try:
        with pytest.raises(OSError, match="failed to bind"):
            GrpcBridge(server=Server(snapshot_fn=lambda: _snapshot(nodes))) \
                .build_grpc_server(port=port, host="127.0.0.1")
    finally:
        server.stop(0)


def test_grpc_scale_and_busy(grpc_mod):
    grpc = grpc_mod
    nodes = [make_node("n1")]
    http_server = Server(snapshot_fn=lambda: _snapshot(nodes))
    bridge = GrpcBridge(server=http_server)
    server, port = bridge.build_grpc_server(port=0, host="127.0.0.1")
    server.start()
    try:
        channel = grpc.insecure_channel(f"127.0.0.1:{port}")
        ident = lambda b: b  # noqa: E731
        scale = channel.unary_unary(f"/{SERVICE}/ScaleApps",
                                    request_serializer=ident,
                                    response_deserializer=ident)
        req = json.dumps({
            "deployments": [make_deployment("api", replicas=1, cpu="1", memory="1Gi")]
        }).encode()
        code, _ = decode_simulate_response(scale(encode_simulate_request(req)))
        assert code == 200

        # the gRPC surface shares the REST TryLock: busy → 503
        assert http_server.deploy_lock.acquire(blocking=False)
        try:
            deploy = channel.unary_unary(f"/{SERVICE}/DeployApps",
                                         request_serializer=ident,
                                         response_deserializer=ident)
            code, body = decode_simulate_response(deploy(encode_simulate_request(b"{}")))
            assert code == 503
            assert "busy" in json.loads(body)["error"]  # structured error contract
        finally:
            http_server.deploy_lock.release()
    finally:
        server.stop(0)
