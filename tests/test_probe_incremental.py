"""Incremental capacity probing (simulator/probe.py): the encode-once session
must be BIT-identical to fresh-Simulator probes — counts and utilization —
across candidate sweeps, node-padding bucket boundaries, and the node-axis
extension path; and CapacityPlanner.search must use it with a bounded probe
count while agreeing with the fresh-probe search."""

import pytest

from fixtures import make_node, make_pod
from open_simulator_tpu.apply.applier import CapacityPlanner
from open_simulator_tpu.core.types import ResourceTypes
from open_simulator_tpu.models.fakenode import new_fake_nodes
from open_simulator_tpu.simulator.engine import Simulator
from open_simulator_tpu.simulator.probe import ProbeSession


@pytest.fixture(autouse=True)
def _no_envelope(monkeypatch):
    monkeypatch.delenv("MaxCPU", raising=False)
    monkeypatch.delenv("MaxMemory", raising=False)


def _cluster(n_base=2):
    base = [make_node(f"base-{i}", cpu="8", memory="16Gi") for i in range(n_base)]
    template = make_node("tpl", cpu="8", memory="16Gi")
    return base, template


def _fresh_probe(base, template, pods, n, cluster_objects=None):
    """(scheduled, total), utilization via a fresh Simulator — the reference
    probe the incremental path must reproduce exactly."""
    sim = Simulator(base + new_fake_nodes(template, n))
    if cluster_objects is not None:
        sim.register_cluster_objects(cluster_objects)
    counts = sim.probe_pods(list(pods))
    return counts, sim.probe_utilization()


def _assert_matches(session, base, template, pods, ns, cluster_objects=None):
    res = session.probe_many(ns)
    for n in ns:
        scheduled, total, u = res[n]
        fresh_counts, fresh_u = _fresh_probe(base, template, pods, n,
                                             cluster_objects)
        assert (scheduled, total) == fresh_counts, f"counts diverge at n={n}"
        assert u == fresh_u, f"utilization diverges at n={n}"


def test_incremental_matches_fresh_across_bucket_boundary():
    """Sweep candidates whose FRESH probes straddle a node-padding bucket
    (2 base + n: n=5 pads to 8 nodes, n=7 pads to 16) while the session stays
    at one padded shape — the masked-column ≡ phantom-column equivalence."""
    base, template = _cluster()
    pods = [make_pod(f"p-{i}", cpu="2", memory="2Gi") for i in range(40)]
    session = ProbeSession.try_build(base, template, pods, n_new=12)
    assert session is not None
    _assert_matches(session, base, template, pods, [0, 3, 5, 6, 7, 8, 11, 14])


def test_extension_path_matches_fresh():
    """Growing the session via extend_node_axis (appended template columns,
    fresh hostname domains) must stay bit-identical — including candidates
    beyond the originally encoded bucket."""
    base, template = _cluster()
    pods = [make_pod(f"p-{i}", cpu="2", memory="2Gi") for i in range(40)]
    session = ProbeSession.try_build(base, template, pods, n_new=2)
    assert session is not None
    before = session.n_new
    session.ensure_capacity(12)
    assert session.n_new >= 12 and session.extensions == 1
    assert session.encodes == 1  # extension never re-encodes the pod batch
    _assert_matches(session, base, template, pods,
                    [before - 1, before, before + 1, 12])


def test_serial_segments_match():
    """Alternating pod shapes force serial (scan) segments instead of waves."""
    base, template = _cluster()
    pods = []
    for i in range(24):
        if i % 3 == 0:
            pods.append(make_pod(f"s-{i}", cpu="3", memory="1Gi"))
        else:
            pods.append(make_pod(f"t-{i}", cpu="1", memory="3Gi"))
    session = ProbeSession.try_build(base, template, pods, n_new=8)
    assert session is not None
    assert {s[0] for s in session._segs} == {"serial"}
    _assert_matches(session, base, template, pods, [0, 2, 4, 6])


def test_selector_spread_live_matches():
    """A service selecting the batch routes it through the fused group-serial
    kernel with a live SelectorSpread counter — vmapped, still exact."""
    base, template = _cluster()
    svc = {"apiVersion": "v1", "kind": "Service",
           "metadata": {"name": "svc", "namespace": "default"},
           "spec": {"selector": {"app": "web"}}}
    cluster = ResourceTypes()
    cluster.services = [svc]
    pods = [make_pod(f"w-{i}", cpu="1", memory="1Gi", labels={"app": "web"})
            for i in range(30)]
    session = ProbeSession.try_build(base, template, pods,
                                     cluster_objects=cluster, n_new=8)
    assert session is not None
    assert {s[0] for s in session._segs} == {"spread"}
    _assert_matches(session, base, template, pods, [0, 2, 4, 6],
                    cluster_objects=cluster)


def test_session_gates():
    base, template = _cluster()
    plain = [make_pod(f"p-{i}", cpu="1", memory="1Gi") for i in range(10)]
    # topology spread: eligible-domain sets depend on the node census
    sp = make_pod("sp-0", cpu="1", memory="1Gi", labels={"app": "x"})
    sp["spec"]["topologySpreadConstraints"] = [{
        "maxSkew": 1, "topologyKey": "kubernetes.io/hostname",
        "whenUnsatisfiable": "DoNotSchedule",
        "labelSelector": {"matchLabels": {"app": "x"}}}]
    assert ProbeSession.try_build(base, template, [sp] * 10, n_new=4) is None
    # node-advertised images: ImageLocality divides by the total node count
    imgbase = [make_node("ib", cpu="8", memory="16Gi")]
    imgbase[0]["status"]["images"] = [{"names": ["busybox"], "sizeBytes": 100 << 20}]
    assert ProbeSession.try_build(imgbase, template, plain, n_new=4) is None
    # bound-after-unbound: probe order-inequivalent (planner guard mirrored)
    mixed = [make_pod("free"), make_pod("bound", node_name="base-0")]
    assert ProbeSession.try_build(base, template, mixed, n_new=4) is None
    # bound-BEFORE-unbound builds, and the bound commit is candidate-invariant
    ordered = [make_pod("bound", node_name="base-0", cpu="2", memory="2Gi"),
               make_pod("free", cpu="2", memory="2Gi")]
    session = ProbeSession.try_build(base, template, ordered, n_new=4)
    assert session is not None
    _assert_matches(session, base, template, ordered, [0, 1])


def test_search_incremental_minimal_and_bounded_probe_count():
    """Probe-count regression: the whole search must be a handful of fan-out
    dispatches with pod encoding paid exactly once, and the answer must match
    the fresh-probe search and be exactly minimal."""
    base, template = _cluster()
    pods = [make_pod(f"p-{i}", cpu="2", memory="2Gi") for i in range(20)]
    planner = CapacityPlanner(base, template, pods)
    found, n, hist = planner.search()
    assert found
    assert planner.stats["path"] == "incremental"
    assert planner.stats["encodes"] == 1
    assert planner.stats["probes"] <= 40
    assert planner.stats["dispatches"] <= 6
    # minimality against fresh probes
    ok_n, _ = planner.probe(n)
    assert ok_n
    if n > 0:
        ok_prev, _ = planner.probe(n - 1)
        assert not ok_prev
    # agreement with the fresh-probe search
    planner2 = CapacityPlanner(base, template, list(pods))
    found2, n2, _ = planner2._search_fresh()
    assert (found, n) == (found2, n2)


def test_search_falls_back_when_gated():
    """A spread-constrained workload rejects the session; search must still
    answer via fresh probes (path="fresh") with the same semantics."""
    base, template = _cluster()
    pods = []
    for i in range(12):
        p = make_pod(f"sp-{i}", cpu="2", memory="2Gi", labels={"app": "x"})
        p["spec"]["topologySpreadConstraints"] = [{
            "maxSkew": 4, "topologyKey": "kubernetes.io/hostname",
            "whenUnsatisfiable": "DoNotSchedule",
            "labelSelector": {"matchLabels": {"app": "x"}}}]
        pods.append(p)
    planner = CapacityPlanner(base, template, pods)
    found, n, _ = planner.search()
    assert planner.stats["path"] == "fresh"
    assert found
    ok_n, _ = planner.probe(n)
    assert ok_n
