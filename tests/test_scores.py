"""Numeric score parity: one exact hand-computed integer assertion per score
plugin, derived from the vendored formulas (NOT from this repo's code), so a
systematic error shared by both engine paths cannot pass. Each test isolates
its plugin with a ScoreWeights vector that zeroes every other weight — the
weight machinery itself is under test in test_schedconfig.py.

Sources (all under /root/reference/vendor/k8s.io/kubernetes/pkg/scheduler/
framework/plugins/ unless noted):
- noderesources/least_allocated.go:93-115  (per-resource floor, /2 floor)
- noderesources/balanced_allocation.go:96-120
- imagelocality/image_locality.go:60-112   (spread scaling, thresholds)
- interpodaffinity/scoring.go              (weighted counts, zero-init min/max)
- nodeaffinity (preferred weights, DefaultNormalizeScore)
- nodepreferavoidpods (0/100 by controller signature)
- podtopologyspread/scoring.go:270-289     (cnt*ln(size+2)+maxSkew-1;
  100*(max+min-s)/max integer division)
- tainttoleration (intolerable PreferNoSchedule count, reverse normalize)
- selectorspread/selector_spread.go:104-160
- /root/reference/pkg/simulator/plugin/simon.go:45-101 (max-share + min-max)
"""

import copy

import numpy as np
import pytest

from open_simulator_tpu.ops import kernels
from open_simulator_tpu.simulator.engine import Simulator

from fixtures import make_node, make_pod

ZERO = {f: 0.0 for f in kernels.ScoreWeights._fields}


def iso(**kw):
    """ScoreWeights with every plugin off except the given ones."""
    return kernels.ScoreWeights(**{**ZERO, **kw})


def plugin_scores(nodes, seed_pods, probe, w):
    """Exact per-node score vector for `probe` under weight vector w, after
    committing seed_pods (which must be pre-bound)."""
    import jax.numpy as jnp

    sim = Simulator(copy.deepcopy(nodes))
    if seed_pods:
        failed = sim.schedule_pods(copy.deepcopy(seed_pods))
        assert not failed
    bt = sim.encode_batch([copy.deepcopy(probe)])
    tables, carry = sim._to_device(bt)
    g = int(bt.pod_group[0])
    feasible, _ = kernels.feasibility_jit(
        tables, carry, jnp.int32(g), jnp.int32(-1), jnp.asarray(True))
    sc = kernels.scores(tables, carry, jnp.int32(g), feasible, bt.n_zones,
                        enable_storage=False, w=w)
    return np.asarray(sc)[: len(nodes)]


def bound(name, node, cpu="1", memory="1Gi", **kw):
    return make_pod(name, cpu=cpu, memory=memory, node_name=node, **kw)


def test_least_allocated_exact():
    """A: 10cpu/10Gi seeded 3cpu/4Gi; probe 1cpu/1Gi ->
    cpu floor((10000-4000)*100/10000)=60, mem floor((10-5)*100/10)=50,
    floor((60+50)/2)=55. B: 20cpu/20Gi empty -> floor((95+95)/2)=95."""
    nodes = [make_node("a", cpu="10", memory="10Gi"),
             make_node("b", cpu="20", memory="20Gi")]
    seeds = [bound("s0", "a", cpu="3", memory="4Gi")]
    got = plugin_scores(nodes, seeds, make_pod("p", cpu="1", memory="1Gi"),
                        iso(least=1.0))
    assert got.tolist() == [55.0, 95.0]


def test_balanced_allocation_exact():
    """A: 8cpu/8Gi seeded 1cpu/5Gi; probe 1cpu/1Gi -> cf=2/8=.25, mf=6/8=.75,
    floor((1-.5)*100)=50. B empty: cf=mf=1/8 -> 100."""
    nodes = [make_node("a", cpu="8", memory="8Gi"),
             make_node("b", cpu="8", memory="8Gi")]
    seeds = [bound("s0", "a", cpu="1", memory="5Gi")]
    got = plugin_scores(nodes, seeds, make_pod("p", cpu="1", memory="1Gi"),
                        iso(balanced=1.0))
    assert got.tolist() == [50.0, 100.0]


def test_simon_max_share_exact():
    """share = max_r req/(alloc-req), x100 floored, then min-max over feasible:
    A 8cpu/8Gi: 1/(8-1) -> floor(14.28)=14; B 16cpu/16Gi: 1/15 -> 6.
    normalize: A floor((14-6)*100/8)=100, B 0."""
    nodes = [make_node("a", cpu="8", memory="8Gi"),
             make_node("b", cpu="16", memory="16Gi")]
    got = plugin_scores(nodes, [], make_pod("p", cpu="1", memory="1Gi"),
                        iso(simon=1.0))
    assert got.tolist() == [100.0, 0.0]


def test_taint_toleration_exact():
    """Intolerable PreferNoSchedule taints counted, reverse-normalized:
    A 2 taints, B 0 -> A: 100-floor(2*100/2)=0, B: 100."""
    taints = [
        {"key": "k1", "value": "v", "effect": "PreferNoSchedule"},
        {"key": "k2", "value": "v", "effect": "PreferNoSchedule"},
    ]
    nodes = [make_node("a", taints=taints), make_node("b")]
    got = plugin_scores(nodes, [], make_pod("p", cpu="1", memory="1Gi"),
                        iso(taint=1.0))
    assert got.tolist() == [0.0, 100.0]


def test_node_affinity_preferred_exact():
    """Terms weight 3 (matches A) and 5 (matches B): raw [3, 5] ->
    A floor(3*100/5)=60, B 100 (DefaultNormalizeScore, reverse=false)."""
    nodes = [make_node("a", labels={"disk": "ssd"}),
             make_node("b", labels={"net": "fast"})]
    aff = {"nodeAffinity": {"preferredDuringSchedulingIgnoredDuringExecution": [
        {"weight": 3, "preference": {"matchExpressions": [
            {"key": "disk", "operator": "In", "values": ["ssd"]}]}},
        {"weight": 5, "preference": {"matchExpressions": [
            {"key": "net", "operator": "In", "values": ["fast"]}]}},
    ]}}
    got = plugin_scores(nodes, [], make_pod("p", cpu="1", memory="1Gi", affinity=aff),
                        iso(nodeaff=1.0))
    assert got.tolist() == [60.0, 100.0]


def test_interpod_affinity_preferred_exact():
    """Preferred affinity weight 4 to app=anchor on hostname; anchors: A x2,
    B x1, C x0 -> raw [8, 4, 0]; zero-initialized min/max -> floor(100*raw/8):
    [100, 50, 0]."""
    nodes = [make_node(n) for n in ("a", "b", "c")]
    seeds = [bound("an0", "a", labels={"app": "anchor"}),
             bound("an1", "a", labels={"app": "anchor"}),
             bound("an2", "b", labels={"app": "anchor"})]
    aff = {"podAffinity": {"preferredDuringSchedulingIgnoredDuringExecution": [
        {"weight": 4, "podAffinityTerm": {
            "labelSelector": {"matchLabels": {"app": "anchor"}},
            "topologyKey": "kubernetes.io/hostname"}}
    ]}}
    got = plugin_scores(nodes, seeds, make_pod("p", cpu="1", memory="1Gi", affinity=aff),
                        iso(interpod=1.0))
    assert got.tolist() == [100.0, 50.0, 0.0]


def test_selector_spread_exact():
    """Service selects app=web; placed web pods A:2 B:1 C:0; no zones ->
    floor(100*(max-cnt)/max) = [0, 50, 100]."""
    nodes = [make_node(n) for n in ("a", "b", "c")]
    sim_seeds = [bound(f"w{i}", "a", labels={"app": "web"}) for i in range(2)]
    sim_seeds += [bound("w2", "b", labels={"app": "web"})]
    svc = {"kind": "Service", "apiVersion": "v1",
           "metadata": {"name": "web", "namespace": "default"},
           "spec": {"selector": {"app": "web"}}}

    import jax.numpy as jnp

    sim = Simulator(copy.deepcopy(nodes))
    sim.model.services.append(svc)
    failed = sim.schedule_pods(copy.deepcopy(sim_seeds))
    assert not failed
    probe = make_pod("p", cpu="1", memory="1Gi", labels={"app": "web"})
    bt = sim.encode_batch([probe])
    tables, carry = sim._to_device(bt)
    g = int(bt.pod_group[0])
    feasible, _ = kernels.feasibility_jit(
        tables, carry, jnp.int32(g), jnp.int32(-1), jnp.asarray(True))
    sc = kernels.scores(tables, carry, jnp.int32(g), feasible, bt.n_zones,
                        enable_storage=False, w=iso(ss=1.0))
    assert np.asarray(sc)[:3].tolist() == [0.0, 50.0, 100.0]


def test_pod_topology_spread_score_exact():
    """ScheduleAnyway maxSkew=1 over zones z1={a,b}, z2={c}; matching seeds
    z1:3 z2:1. size=2 -> tpw=ln(4); raw=int(cnt*tpw): [4,4,1];
    normalize 100*(4+1-s)/4 int division: [25, 25, 100]."""
    nodes = [make_node("a", labels={"zone": "z1"}),
             make_node("b", labels={"zone": "z1"}),
             make_node("c", labels={"zone": "z2"})]
    seeds = [bound("s0", "a", labels={"app": "s"}),
             bound("s1", "a", labels={"app": "s"}),
             bound("s2", "b", labels={"app": "s"}),
             bound("s3", "c", labels={"app": "s"})]
    probe = make_pod("p", cpu="1", memory="1Gi", labels={"app": "s"})
    probe["spec"]["topologySpreadConstraints"] = [{
        "maxSkew": 1, "topologyKey": "zone",
        "whenUnsatisfiable": "ScheduleAnyway",
        "labelSelector": {"matchLabels": {"app": "s"}},
    }]
    got = plugin_scores(nodes, seeds, probe, iso(pts=1.0))
    assert got.tolist() == [25.0, 25.0, 100.0]


def test_image_locality_exact():
    """523MB image on A only, 2 nodes -> scaled = 523MB*(1/2) = 274202624;
    score = 100*(274202624-24117248)//(1048576000-24117248) = 24. B: 0."""
    mb = 1024 * 1024
    nodes = [make_node("a"), make_node("b")]
    nodes[0]["status"]["images"] = [
        {"names": ["registry/app:1"], "sizeBytes": 523 * mb}]
    nodes[1]["status"]["images"] = [
        {"names": ["registry/other:1"], "sizeBytes": 100 * mb}]
    probe = make_pod("p", cpu="1", memory="1Gi")
    probe["spec"]["containers"][0]["image"] = "registry/app:1"
    got = plugin_scores(nodes, [], probe, iso(image=1.0))
    assert got.tolist() == [24.0, 0.0]


def test_node_prefer_avoid_pods_exact():
    """A's preferAvoidPods annotation targets the pod's ReplicaSet controller
    -> 0 on A, 100 on B (node_prefer_avoid_pods.go)."""
    import json

    anno = json.dumps({"preferAvoidPods": [
        {"podSignature": {"podController": {
            "kind": "ReplicaSet", "name": "web-rs", "uid": "u1"}}}]})
    nodes = [
        make_node("a", annotations={
            "scheduler.alpha.kubernetes.io/preferAvoidPods": anno}),
        make_node("b"),
    ]
    probe = make_pod("p", cpu="1", memory="1Gi")
    probe["metadata"]["ownerReferences"] = [{
        "kind": "ReplicaSet", "name": "web-rs", "uid": "u1", "controller": True}]
    got = plugin_scores(nodes, [], probe, iso(avoid=1.0))
    assert got.tolist() == [0.0, 100.0]


def test_planted_off_by_one_would_fail():
    """Sanity on the harness itself: shifting any plugin's expected vector by
    one must not match (the tests have discriminating power)."""
    nodes = [make_node("a", cpu="10", memory="10Gi"),
             make_node("b", cpu="20", memory="20Gi")]
    seeds = [bound("s0", "a", cpu="3", memory="4Gi")]
    got = plugin_scores(nodes, seeds, make_pod("p", cpu="1", memory="1Gi"),
                        iso(least=1.0))
    assert got.tolist() != [56.0, 96.0]
