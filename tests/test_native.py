"""Native canon_hash extension + signature memoization."""

import numpy as np
import pytest

from open_simulator_tpu.native import canon_hash_fn

from fixtures import make_deployment, make_node
from open_simulator_tpu import simulate
from open_simulator_tpu.core.types import AppResource, ResourceTypes
from open_simulator_tpu.models.workloads import pods_from_deployment
from open_simulator_tpu.simulator.encode import SIG_MEMO_KEY, scheduling_signature


@pytest.fixture(scope="module")
def canon_hash():
    fn = canon_hash_fn()
    if fn is None:
        pytest.skip("native extension unavailable (no compiler?)")
    return fn


def test_native_builds_and_hashes(canon_hash):
    h = canon_hash({"a": 1, "b": [1, 2, {"c": "x"}]})
    assert isinstance(h, int) and h > 0


def test_dict_key_order_canonical(canon_hash):
    assert canon_hash({"a": 1, "b": 2}) == canon_hash({"b": 2, "a": 1})


def test_distinct_values_distinct_hashes(canon_hash):
    samples = [
        {"a": 1}, {"a": 2}, {"a": "1"}, {"a": [1]}, {"a": {"b": 1}},
        {"a": None}, {"a": 1.5}, {"b": 1}, [1, 2], [2, 1], "x", 7, None, True, False,
    ]
    hashes = [canon_hash(s) for s in samples]
    # bool True == 1 in Python tuple equality → allowed to collide with 7? no: 7 != True
    assert len(set(hashes)) == len(samples)


def test_numeric_equality_matches_python_tuples(canon_hash):
    # (1,) == (1.0,) == (True,) in Python → the frozen-tuple form collides; the
    # native hash must too, or equal groups would split forever
    assert canon_hash(1) == canon_hash(1.0) == canon_hash(True)
    assert canon_hash(0) == canon_hash(0.0) == canon_hash(False)
    big = 2**70
    assert canon_hash(big) == canon_hash(big)
    assert canon_hash(big) != canon_hash(big + 1)


def test_nested_list_vs_flat(canon_hash):
    assert canon_hash([1, [2, 3]]) != canon_hash([1, 2, 3])
    assert canon_hash([]) != canon_hash({})


def test_unsupported_type_raises(canon_hash):
    with pytest.raises(TypeError):
        canon_hash(object())


# ------------------------------------------------------------------ pod_sig ---------


ANNO_KEYS = ("simon/gpu-mem", "simon/gpu-count", "simon/gpu-index",
             "simon/local-storage")


def _sig_tuple(pod):
    """The exact tuple scheduling_signature's native path used to build in Python
    (simulator/encode.py) — pod_sig must be hash-identical to canon_hash over it."""
    md = pod.get("metadata") or {}
    spec = pod.get("spec") or {}
    anns = md.get("annotations") or {}
    return (
        md.get("namespace") or "default",
        md.get("labels"),
        spec.get("nodeSelector"),
        spec.get("affinity"),
        spec.get("tolerations"),
        spec.get("topologySpreadConstraints"),
        spec.get("nodeName"),
        spec.get("hostNetwork"),
        spec.get("containers"),
        spec.get("initContainers"),
        spec.get("overhead"),
        sorted({r.get("kind", "") for r in md.get("ownerReferences") or []}),
        [anns.get(k) for k in ANNO_KEYS],
    )


@pytest.fixture(scope="module")
def pod_sig():
    from open_simulator_tpu.native import pod_sig_fn

    fn = pod_sig_fn()
    if fn is None:
        pytest.skip("native extension unavailable (no compiler?)")
    return fn


def test_pod_sig_matches_tuple_hash(canon_hash, pod_sig):
    pods = [
        {},
        {"metadata": {"name": "a"}},
        {"metadata": {"namespace": "", "labels": {"a": "b", "c": "d"}}},
        {"metadata": {"namespace": "x", "ownerReferences": [
            {"kind": "ReplicaSet"}, {"kind": "Job"}, {"kind": "ReplicaSet"}]}},
        {"metadata": {"annotations": {"simon/gpu-mem": "4Gi", "other": "1"}},
         "spec": {"containers": [{"image": "nginx",
                                  "resources": {"requests": {"cpu": "100m"}}}],
                  "hostNetwork": True, "nodeName": "n1",
                  "tolerations": [{"key": "k", "operator": "Exists"}]}},
        {"spec": {"affinity": {"podAntiAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [
                {"labelSelector": {"matchLabels": {"app": "x"}},
                 "topologyKey": "kubernetes.io/hostname"}]}},
            "topologySpreadConstraints": [
                {"maxSkew": 2, "whenUnsatisfiable": "DoNotSchedule"}]}},
        {"metadata": None, "spec": None},
        {"metadata": {"ownerReferences": []},
         "spec": {"overhead": {"cpu": "10m"}, "initContainers": []}},
    ]
    for pod in pods:
        assert pod_sig(pod, ANNO_KEYS) == canon_hash(_sig_tuple(pod))


def test_pod_sig_distinguishes_scheduling_fields(pod_sig):
    base = {"metadata": {"namespace": "d", "labels": {"app": "x"}},
            "spec": {"containers": [{"image": "a",
                                     "resources": {"requests": {"cpu": "1"}}}]}}
    import copy

    variants = []
    for mutate in (
        lambda p: p["metadata"].__setitem__("namespace", "other"),
        lambda p: p["metadata"]["labels"].__setitem__("app", "y"),
        lambda p: p["spec"].__setitem__("nodeSelector", {"k": "v"}),
        lambda p: p["spec"].__setitem__("nodeName", "n7"),
        lambda p: p["spec"]["containers"][0].__setitem__("image", "b"),
        lambda p: p["spec"]["containers"][0]["resources"]["requests"].__setitem__("cpu", "2"),
        lambda p: p["metadata"].setdefault("annotations", {}).__setitem__(
            "simon/gpu-mem", "1Gi"),
        lambda p: p["metadata"].__setitem__("ownerReferences", [{"kind": "DaemonSet"}]),
    ):
        p = copy.deepcopy(base)
        mutate(p)
        variants.append(pod_sig(p, ANNO_KEYS))
    variants.append(pod_sig(base, ANNO_KEYS))
    assert len(set(variants)) == len(variants)
    # name/uid are NOT scheduling-relevant: same signature
    named = copy.deepcopy(base)
    named["metadata"]["name"] = "pod-123"
    assert pod_sig(named, ANNO_KEYS) == pod_sig(base, ANNO_KEYS)


# ------------------------------------------------------------------ memoization -----


def test_workload_pods_share_memo():
    deploy = make_deployment("web", replicas=5, cpu="1", memory="1Gi")
    pods = pods_from_deployment(deploy)
    sigs = {scheduling_signature(p) for p in pods}
    assert len(sigs) == 1
    assert all(SIG_MEMO_KEY in p for p in pods)


def test_memo_stripped_from_results():
    nodes = [make_node("n1")]
    deploy = make_deployment("web", replicas=3, cpu="1", memory="1Gi")
    res = simulate(ResourceTypes(nodes=nodes),
                   [AppResource(name="a", resource=ResourceTypes(deployments=[deploy]))])
    for ns in res.node_status:
        for p in ns.pods:
            assert SIG_MEMO_KEY not in p
    for up in res.unscheduled_pods:
        assert SIG_MEMO_KEY not in up.pod
