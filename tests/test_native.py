"""Native canon_hash extension + signature memoization."""

import numpy as np
import pytest

from open_simulator_tpu.native import canon_hash_fn

from fixtures import make_deployment, make_node
from open_simulator_tpu import simulate
from open_simulator_tpu.core.types import AppResource, ResourceTypes
from open_simulator_tpu.models.workloads import pods_from_deployment
from open_simulator_tpu.simulator.encode import SIG_MEMO_KEY, scheduling_signature


@pytest.fixture(scope="module")
def canon_hash():
    fn = canon_hash_fn()
    if fn is None:
        pytest.skip("native extension unavailable (no compiler?)")
    return fn


def test_native_builds_and_hashes(canon_hash):
    h = canon_hash({"a": 1, "b": [1, 2, {"c": "x"}]})
    assert isinstance(h, int) and h > 0


def test_dict_key_order_canonical(canon_hash):
    assert canon_hash({"a": 1, "b": 2}) == canon_hash({"b": 2, "a": 1})


def test_distinct_values_distinct_hashes(canon_hash):
    samples = [
        {"a": 1}, {"a": 2}, {"a": "1"}, {"a": [1]}, {"a": {"b": 1}},
        {"a": None}, {"a": 1.5}, {"b": 1}, [1, 2], [2, 1], "x", 7, None, True, False,
    ]
    hashes = [canon_hash(s) for s in samples]
    # bool True == 1 in Python tuple equality → allowed to collide with 7? no: 7 != True
    assert len(set(hashes)) == len(samples)


def test_numeric_equality_matches_python_tuples(canon_hash):
    # (1,) == (1.0,) == (True,) in Python → the frozen-tuple form collides; the
    # native hash must too, or equal groups would split forever
    assert canon_hash(1) == canon_hash(1.0) == canon_hash(True)
    assert canon_hash(0) == canon_hash(0.0) == canon_hash(False)
    big = 2**70
    assert canon_hash(big) == canon_hash(big)
    assert canon_hash(big) != canon_hash(big + 1)


def test_nested_list_vs_flat(canon_hash):
    assert canon_hash([1, [2, 3]]) != canon_hash([1, 2, 3])
    assert canon_hash([]) != canon_hash({})


def test_unsupported_type_raises(canon_hash):
    with pytest.raises(TypeError):
        canon_hash(object())


# ------------------------------------------------------------------ memoization -----


def test_workload_pods_share_memo():
    deploy = make_deployment("web", replicas=5, cpu="1", memory="1Gi")
    pods = pods_from_deployment(deploy)
    sigs = {scheduling_signature(p) for p in pods}
    assert len(sigs) == 1
    assert all(SIG_MEMO_KEY in p for p in pods)


def test_memo_stripped_from_results():
    nodes = [make_node("n1")]
    deploy = make_deployment("web", replicas=3, cpu="1", memory="1Gi")
    res = simulate(ResourceTypes(nodes=nodes),
                   [AppResource(name="a", resource=ResourceTypes(deployments=[deploy]))])
    for ns in res.node_status:
        for p in ns.pods:
            assert SIG_MEMO_KEY not in p
    for up in res.unscheduled_pods:
        assert SIG_MEMO_KEY not in up.pod
