"""simonfault tests: policy determinism (backoff/jitter/deadline/breaker),
seeded fault plans, and crash-consistent rollback under every engine fault
site — census, pod dicts, and the commits−rollbacks−victims reconciliation
must be bit-identical to the pre-call state after any injected failure."""

import copy

import pytest

from open_simulator_tpu.obs import REGISTRY
from open_simulator_tpu.resilience import (
    SITES,
    BreakerOpen,
    CircuitBreaker,
    Deadline,
    DeadlineExceeded,
    FaultInjected,
    FaultPlan,
    FaultSpec,
    RetryPolicy,
    check_deadline,
    deadline_remaining,
    installed,
)
from open_simulator_tpu.simulator.engine import Simulator
from open_simulator_tpu.simulator.encode import scheduling_signature
from open_simulator_tpu.utils.synth import synth_cluster

from fixtures import make_node, make_pod


def prio_pod(name, priority, **kw):
    p = make_pod(name, **kw)
    p["spec"]["priority"] = priority
    return p

ENGINE_SITES = ("encode", "to_device", "dispatch", "fetch", "commit")


def test_engine_sites_are_registered():
    assert set(ENGINE_SITES) <= set(SITES)
    assert {"live_get", "preempt_evict"} <= set(SITES)


# --------------------------------------------------------------- helpers -----


def census(sim):
    out = {}
    for i, nps in enumerate(sim.pods_on_node):
        for p in nps:
            k = (i, scheduling_signature(p))
            out[k] = out.get(k, 0) + 1
    return out


def _sum(prefix):
    return sum(v for k, v in REGISTRY.values().items() if k.startswith(prefix))


def reconciliation():
    """commits − rollbacks − victims: the PR-3 invariant that must survive
    any rollback bit-identically."""
    return (_sum("simon_commits_total")
            - _sum("simon_commit_rollbacks_total")
            - _sum("simon_preemption_victims_total"))


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def sleep(self, dt):
        self.t += dt


# ---------------------------------------------------------- RetryPolicy ------


def test_backoff_schedule_is_deterministic_and_seeded():
    p = RetryPolicy(max_attempts=5, base=0.1, mult=2.0, cap=1.0,
                    jitter=0.3, seed=42)
    s1, s2 = p.schedule(), p.schedule()
    assert s1 == s2  # pure function of the policy
    assert s1 == RetryPolicy(max_attempts=5, base=0.1, mult=2.0, cap=1.0,
                             jitter=0.3, seed=42).schedule()
    # a different seed jitters differently; the un-jittered base is shared
    s3 = RetryPolicy(max_attempts=5, base=0.1, mult=2.0, cap=1.0,
                     jitter=0.3, seed=43).schedule()
    assert s1 != s3
    for d, d3, base in zip(s1, s3, (0.1, 0.2, 0.4, 0.8)):
        assert base <= d <= base * 1.3
        assert base <= d3 <= base * 1.3


def test_backoff_cap_and_zero_jitter():
    p = RetryPolicy(max_attempts=6, base=1.0, mult=10.0, cap=3.0, jitter=0.0)
    assert p.schedule() == [1.0, 3.0, 3.0, 3.0, 3.0]


def test_retry_call_retries_transient_then_succeeds():
    clock = FakeClock()
    sleeps = []
    calls = []
    p = RetryPolicy(max_attempts=4, base=0.1, jitter=0.0, seed=0)
    before = _sum("simon_retries_total")

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            e = RuntimeError("transient")
            e.transient = True
            raise e
        return "ok"

    out = p.call(flaky, site="test_site",
                 retryable=lambda e: getattr(e, "transient", False),
                 sleep=sleeps.append, clock=clock)
    assert out == "ok" and len(calls) == 3
    assert sleeps == p.schedule()[:2]
    assert _sum("simon_retries_total") - before == 2


def test_retry_call_honors_retry_after_floor():
    sleeps = []
    p = RetryPolicy(max_attempts=2, base=0.01, jitter=0.0)
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) == 1:
            e = RuntimeError("429")
            e.transient, e.retry_after = True, 7.5
            raise e
        return "ok"

    assert p.call(flaky, site="t", retryable=lambda e: True,
                  sleep=sleeps.append, clock=FakeClock()) == "ok"
    assert sleeps == [7.5]  # the Retry-After hint floors the backoff


def test_retry_call_gives_up_and_never_retries_unretryable():
    p = RetryPolicy(max_attempts=3, base=0.001, jitter=0.0)
    calls = []

    def always():
        calls.append(1)
        raise ValueError("permanent")

    with pytest.raises(ValueError):
        p.call(always, site="t", retryable=lambda e: False,
               sleep=lambda s: None, clock=FakeClock())
    assert len(calls) == 1  # unretryable: exactly one attempt

    calls.clear()
    with pytest.raises(ValueError):
        p.call(always, site="t", retryable=lambda e: True,
               sleep=lambda s: None, clock=FakeClock())
    assert len(calls) == 3  # retryable: bounded by max_attempts


def test_retry_call_bounded_by_max_elapsed():
    clock = FakeClock()
    p = RetryPolicy(max_attempts=10, base=1.0, mult=1.0, jitter=0.0,
                    max_elapsed=2.5)
    calls = []

    def always():
        calls.append(1)
        raise RuntimeError("transient")

    with pytest.raises(RuntimeError):
        p.call(always, site="t", retryable=lambda e: True,
               sleep=clock.sleep, clock=clock)
    assert len(calls) == 3  # attempts at t=0, 1, 2; a 4th would pass 2.5s


# -------------------------------------------------------------- Deadline -----


def test_deadline_slices_and_nested_only_tightens():
    clock = FakeClock()
    assert deadline_remaining(clock) is None
    with Deadline(10.0, clock=clock):
        assert deadline_remaining(clock) == pytest.approx(10.0)
        clock.sleep(4.0)
        assert deadline_remaining(clock) == pytest.approx(6.0)
        with Deadline(2.0, clock=clock):  # tighter: wins
            assert deadline_remaining(clock) == pytest.approx(2.0)
        with Deadline(100.0, clock=clock):  # looser: outer budget still caps
            assert deadline_remaining(clock) == pytest.approx(6.0)
        assert deadline_remaining(clock) == pytest.approx(6.0)
    assert deadline_remaining(clock) is None


def test_deadline_propagates_into_callees_and_check_raises():
    clock = FakeClock()
    before = _sum("simon_deadline_exceeded_total")

    def callee():
        check_deadline("callee_site", clock=clock)
        return deadline_remaining(clock)

    with Deadline(1.0, clock=clock):
        assert callee() == pytest.approx(1.0)
        clock.sleep(1.5)
        with pytest.raises(DeadlineExceeded):
            callee()
    assert _sum("simon_deadline_exceeded_total") - before == 1


def test_retry_never_sleeps_past_the_deadline():
    clock = FakeClock()
    p = RetryPolicy(max_attempts=5, base=10.0, jitter=0.0)

    def always():
        raise RuntimeError("transient")

    with Deadline(5.0, clock=clock):
        with pytest.raises(DeadlineExceeded):
            p.call(always, site="t", retryable=lambda e: True,
                   sleep=clock.sleep, clock=clock)


# --------------------------------------------------------- CircuitBreaker ----


def test_breaker_open_half_open_close_transitions():
    clock = FakeClock()
    br = CircuitBreaker("t1", failure_threshold=3, reset_after=10.0,
                        clock=clock)
    assert br.state == "closed"
    for _ in range(2):
        br.before_call()
        br.record_failure()
    assert br.state == "closed"  # below threshold
    br.before_call()
    br.record_failure()
    assert br.state == "open"  # threshold consecutive failures
    with pytest.raises(BreakerOpen):
        br.before_call()

    clock.sleep(10.1)  # cooldown elapsed: one probe admitted
    br.before_call()
    assert br.state == "half_open"
    with pytest.raises(BreakerOpen):
        br.before_call()  # second concurrent probe refused
    br.record_success()
    assert br.state == "closed"

    # a successful call resets the consecutive-failure count
    br.before_call()
    br.record_failure()
    br.before_call()
    br.record_success()
    for _ in range(2):
        br.before_call()
        br.record_failure()
    assert br.state == "closed"


def test_breaker_ignores_non_retryable_failures():
    """AuthError-class failures prove the dependency is ALIVE: they must not
    open the breaker (which would mask the actionable 401 behind BreakerOpen)."""
    br = CircuitBreaker("t_auth", failure_threshold=2, reset_after=60.0,
                        clock=FakeClock())
    p = RetryPolicy(max_attempts=1)

    def auth_fail():
        raise PermissionError("401")

    for _ in range(5):
        with pytest.raises(PermissionError):
            p.call(auth_fail, site="t", retryable=lambda e: False,
                   sleep=lambda s: None, clock=FakeClock(), breaker=br)
    assert br.state == "closed"


def test_breaker_probe_failure_reopens():
    clock = FakeClock()
    br = CircuitBreaker("t2", failure_threshold=1, reset_after=5.0, clock=clock)
    br.before_call()
    br.record_failure()
    assert br.state == "open"
    clock.sleep(5.1)
    br.before_call()  # the half-open probe
    br.record_failure()
    assert br.state == "open"
    with pytest.raises(BreakerOpen):
        br.before_call()


def test_breaker_state_gauge_exported():
    CircuitBreaker("gauge_check", failure_threshold=1, reset_after=5.0)
    vals = REGISTRY.values()
    assert vals['simon_breaker_state{name="gauge_check"}'] == 0


# -------------------------------------------------------------- FaultPlan ----


def test_fault_plan_seeded_is_deterministic():
    a = FaultPlan.seeded(7, n_faults=3, max_attempt=5)
    b = FaultPlan.seeded(7, n_faults=3, max_attempt=5)
    assert a.specs == b.specs
    assert FaultPlan.seeded(8, n_faults=3, max_attempt=5).specs != a.specs


def test_fault_plan_parse_forms(tmp_path):
    p = FaultPlan.parse("site=commit,attempt=3,error=transient;site=encode")
    assert p.specs == (FaultSpec("commit", 3, "transient"),
                       FaultSpec("encode", 1, "runtime"))
    assert FaultPlan.parse("seed=5").specs == FaultPlan.seeded(5).specs
    assert FaultPlan.parse('{"seed": 5}').specs == FaultPlan.seeded(5).specs
    f = tmp_path / "plan.json"
    f.write_text('{"faults": [{"site": "fetch", "attempt": 2}]}')
    assert FaultPlan.parse(str(f)).specs == (FaultSpec("fetch", 2, "runtime"),)
    with pytest.raises(ValueError):
        FaultPlan.parse("site=not_a_site")
    with pytest.raises(ValueError):
        FaultPlan.parse("site=commit,attempt=0")
    with pytest.raises(ValueError):
        FaultPlan.parse("site=commit,error=nonsense")
    with pytest.raises(ValueError):
        FaultPlan.parse("bogus clause")


def test_fault_plan_error_classes_map_to_live_hierarchy():
    from open_simulator_tpu.simulator.live import (
        AuthError, LiveClusterError, ProtocolError, TransientError)

    for err, cls in (("transient", TransientError), ("auth", AuthError),
                     ("protocol", ProtocolError)):
        plan = FaultPlan([FaultSpec("encode", 1, err)])
        with installed(plan), pytest.raises(cls) as ei:
            plan.on_arrival("encode")
        assert isinstance(ei.value, LiveClusterError)
        assert ei.value.injected


# ------------------------------------------- engine fault-site sweep ---------


@pytest.fixture(scope="module")
def small_cluster():
    return synth_cluster(8, 40)


@pytest.mark.parametrize("site", ENGINE_SITES)
def test_fault_site_rollback_invariance(site, small_cluster):
    """The acceptance criterion: an injected failure at every engine site
    leaves census, placements, caller pod dicts, and the metric
    reconciliation bit-identical to the pre-call state."""
    nodes, pods = small_cluster
    sim = Simulator(copy.deepcopy(nodes))
    p = copy.deepcopy(pods)
    pre_pods = copy.deepcopy(p)
    pre_recon = reconciliation()
    plan = FaultPlan([FaultSpec(site, 1)])
    with installed(plan), pytest.raises(FaultInjected):
        sim.schedule_pods(p)
    assert census(sim) == {}
    assert sim.placed == {}
    assert p == pre_pods
    assert reconciliation() == pre_recon
    assert plan.trace == [(site, 1, "runtime")]
    # the simulator is NOT poisoned: the same call now succeeds and matches
    # a fresh simulator bit-for-bit
    failed = sim.schedule_pods(p)
    fresh = Simulator(copy.deepcopy(nodes))
    fresh_failed = fresh.schedule_pods(copy.deepcopy(pods))
    assert census(sim) == census(fresh)
    assert len(failed) == len(fresh_failed)


def test_partial_commit_rolls_back_earlier_commits(small_cluster):
    """A commit fault mid-batch (after 19 pods committed) must undo all 19."""
    nodes, pods = small_cluster
    sim = Simulator(copy.deepcopy(nodes))
    p = copy.deepcopy(pods)
    pre_recon = reconciliation()
    with installed(FaultPlan([FaultSpec("commit", 20)])), \
            pytest.raises(FaultInjected):
        sim.schedule_pods(p)
    assert census(sim) == {}
    assert all("nodeName" not in (q.get("spec") or {}) for q in p)
    assert all("status" not in q for q in p)
    assert reconciliation() == pre_recon


def test_fault_replay_trace_is_identical(small_cluster):
    """Seeded plan + identical workload → bit-identical injection traces and
    arrival counts across two independent runs."""
    nodes, pods = small_cluster
    traces = []
    for _ in range(2):
        sim = Simulator(copy.deepcopy(nodes))
        plan = FaultPlan.seeded(1234, n_faults=2, sites=ENGINE_SITES,
                                max_attempt=3)
        try:
            with installed(plan):
                sim.schedule_pods(copy.deepcopy(pods))
        except Exception:
            pass
        traces.append((plan.trace, dict(plan.arrivals)))
    assert traces[0] == traces[1]
    assert traces[0][0], "the seeded plan must actually fire on this workload"


def test_prebound_pod_status_restored_exactly():
    """Pre-bound pods carry caller-owned status objects; a rollback must put
    the ORIGINAL contents back, not a synthesized one."""
    nodes = [make_node("n1"), make_node("n2")]
    bound = make_pod("bound-0", cpu="100m", memory="128Mi", node_name="n1")
    bound["status"] = {"phase": "Running", "conditions": [{"type": "Ready"}]}
    free = make_pod("free-0", cpu="100m", memory="128Mi")
    pods = [bound, free]
    pre = copy.deepcopy(pods)
    sim = Simulator(nodes)
    with installed(FaultPlan([FaultSpec("dispatch", 1)])), \
            pytest.raises(FaultInjected):
        sim.schedule_pods(pods)
    assert pods == pre
    assert census(sim) == {}


def test_preemption_eviction_fault_rolls_back_everything():
    """A fault during a preemption eviction: victims return to their nodes,
    the preemptor stays unplaced, reconciliation holds."""
    nodes = [make_node("n1", cpu="2000m", memory="4Gi", pods="10")]
    low = [prio_pod(f"low-{i}", cpu="900m", memory="1Gi", priority=0)
           for i in range(2)]
    high = [prio_pod("high-0", cpu="1800m", memory="2Gi", priority=100)]
    pods = low + high

    # baseline: preemption evicts both low pods and nominates the node
    base = Simulator(copy.deepcopy(nodes))
    base.schedule_pods(copy.deepcopy(pods))
    assert len(base.preempted) == 2

    sim = Simulator(copy.deepcopy(nodes))
    p = copy.deepcopy(pods)
    pre_pods = copy.deepcopy(p)
    pre_recon = reconciliation()
    with installed(FaultPlan([FaultSpec("preempt_evict", 1)])), \
            pytest.raises(FaultInjected):
        sim.schedule_pods(p)
    assert census(sim) == {}
    assert sim.preempted == []
    assert reconciliation() == pre_recon
    # the two low pods' dicts are rolled back; the preemptor never mutated
    assert [q for q in p if "nodeName" in (q.get("spec") or {})] == []
    assert p[2] == pre_pods[2]
    # and the run completes normally afterwards, matching the baseline
    sim.schedule_pods(p)
    assert len(sim.preempted) == 2
    assert census(sim) == census(base)


def test_preemption_mid_flow_commit_fault_reconciles():
    """Commit faults DURING the preemption rewind/replay machinery (late
    arrivals hit replayed commits) still roll back to a clean slate."""
    nodes = [make_node("n1", cpu="2000m", memory="4Gi", pods="10")]
    pods = ([prio_pod(f"low-{i}", cpu="900m", memory="1Gi", priority=0)
             for i in range(2)]
            + [prio_pod("high-0", cpu="1800m", memory="2Gi", priority=100)])
    pre_recon = reconciliation()
    sim = Simulator(copy.deepcopy(nodes))
    p = copy.deepcopy(pods)
    pre_pods = copy.deepcopy(p)
    # arrival 3 = the first replayed commit inside the preemption flow
    with installed(FaultPlan([FaultSpec("commit", 3)])), \
            pytest.raises(FaultInjected):
        sim.schedule_pods(p)
    assert census(sim) == {}
    assert sim.preempted == []
    assert p == pre_pods
    assert reconciliation() == pre_recon


def test_probe_pods_rollback_restores_bound_commits():
    """probe_pods commits pre-bound pods; a dispatch fault must roll those
    back (probe pods belong to the planner and are reused across probes)."""
    nodes = [make_node("n1"), make_node("n2")]
    bound = make_pod("bound-0", cpu="100m", memory="128Mi", node_name="n1")
    free = [make_pod(f"f-{i}", cpu="100m", memory="128Mi") for i in range(3)]
    pods = [bound] + free
    sim = Simulator(nodes)
    pre_recon = reconciliation()
    with installed(FaultPlan([FaultSpec("dispatch", 1)])), \
            pytest.raises(FaultInjected):
        sim.probe_pods(pods)
    assert census(sim) == {}
    assert "status" not in bound
    assert reconciliation() == pre_recon
    scheduled, total = sim.probe_pods(pods)  # works after the rollback
    assert (scheduled, total) == (4, 4)


def test_probe_session_build_fault_rolls_back_bound_pods():
    """A fault during ProbeSession build (after bound pods committed, during
    encode) must roll the caller's pod dicts back before propagating."""
    from open_simulator_tpu.simulator.probe import ProbeSession

    base = [make_node("n1")]
    template = make_node("template")
    bound = make_pod("bound-0", cpu="100m", memory="128Mi", node_name="n1")
    free = [make_pod(f"f-{i}", cpu="100m", memory="128Mi") for i in range(3)]
    pods = [bound] + free
    pre = copy.deepcopy(pods)
    with installed(FaultPlan([FaultSpec("encode", 1)])), \
            pytest.raises(FaultInjected):
        ProbeSession.try_build(base, template, pods)
    assert pods == pre
    # and the identical build succeeds afterwards
    session = ProbeSession.try_build(base, template, pods)
    assert session is not None


# --------------------------------------------- capacity search deadline ------


def test_capacity_search_respects_deadline():
    from open_simulator_tpu.apply.applier import CapacityPlanner

    nodes = [make_node("n1", cpu="1000m", memory="2Gi")]
    new_node = make_node("template", cpu="1000m", memory="2Gi")
    pods = [make_pod(f"p-{i}", cpu="800m", memory="1Gi") for i in range(6)]
    planner = CapacityPlanner([copy.deepcopy(n) for n in nodes],
                              new_node, copy.deepcopy(pods))
    before = _sum("simon_deadline_exceeded_total")
    with Deadline(1e-4), pytest.raises(DeadlineExceeded):
        planner.search()
    assert _sum("simon_deadline_exceeded_total") > before
    # without a deadline the identical search completes
    found, n, _hist = planner.search()
    assert found and n >= 5
