"""Placement parity: the demo_1 scenario against committed goldens.

The reference's demo_1 example is its primary end-to-end scenario (cluster +
simple/complicate/open_local/more_pods apps + newnode; see
/root/reference/example/simon-config.yaml). The in-repo examples/ tree is a
distilled, scheduling-equivalent replica (tools/make_examples.py) verified to
produce identical placements to the mounted originals. This suite locks the
scenario's full placement census as a golden file and exercises the parity
tool that BASELINE.md's >=99% match-rate metric is measured with.
"""

import copy
import json
import os

import pytest

from open_simulator_tpu.core.types import AppResource
from open_simulator_tpu.models.fakenode import new_fake_nodes
from open_simulator_tpu.parity import load_dump, match_rate, placement_dump, save_dump
from open_simulator_tpu.simulator.core import simulate
from open_simulator_tpu.utils.yamlio import (
    load_cluster_from_directory,
    load_resources_from_directory,
    match_and_set_local_storage_annotation,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN = os.path.join(REPO, "tests", "golden", "demo1_placements.json")

APPS = [("simple", "simple"), ("complicated", "complicate"),
        ("open_local", "open_local"), ("more_pods", "more_pods")]


def demo1_inputs():
    cluster = load_cluster_from_directory(os.path.join(REPO, "examples/cluster/demo_1"))
    nn_dir = os.path.join(REPO, "examples/newnode/demo_1")
    nn = load_resources_from_directory(nn_dir)
    match_and_set_local_storage_annotation(nn.nodes, nn_dir)
    # 18 new nodes = the minimal count the capacity planner lands on for this
    # scenario (asserted by the applier path); seeded names keep runs comparable
    cluster.nodes += new_fake_nodes(nn.nodes[0], 18, seed=42)
    apps = [
        AppResource(name=name, resource=load_resources_from_directory(
            os.path.join(REPO, "examples/application", path)))
        for name, path in APPS
    ]
    return cluster, apps


@pytest.fixture(scope="module")
def demo1_dump():
    cluster, apps = demo1_inputs()
    return placement_dump(simulate(cluster, apps))


def test_demo1_matches_golden(demo1_dump):
    golden = load_dump(GOLDEN)
    rate, detail = match_rate(demo1_dump, golden)
    assert rate == 1.0, f"disagreements: {dict(list(detail.items())[:10])}"
    assert demo1_dump["new_nodes"] == golden["new_nodes"] == 18
    assert demo1_dump["new_node_profiles"] == golden["new_node_profiles"]
    assert demo1_dump["unscheduled"] == {}


def test_demo1_pod_totals(demo1_dump):
    assert sum(demo1_dump["placements"].values()) == 322


def test_demo1_wave_vs_serial_parity():
    # the wave scheduler and the pure serial scan must produce the same census
    # on the full demo scenario end-to-end
    from open_simulator_tpu.simulator import engine as eng

    cluster, apps = demo1_inputs()
    serial_dump = {}
    orig_init = eng.Simulator.__init__

    def patched(self, *a, **kw):
        orig_init(self, *a, **kw)
        self.use_waves = False

    eng.Simulator.__init__ = patched
    try:
        serial = placement_dump(simulate(cluster, apps))
    finally:
        eng.Simulator.__init__ = orig_init
    cluster, apps = demo1_inputs()
    wave = placement_dump(simulate(cluster, apps))
    rate, detail = match_rate(wave, serial)
    assert rate == 1.0, f"disagreements: {dict(list(detail.items())[:10])}"


def test_match_rate_detects_disagreement():
    a = {"placements": {"ns/Deployment/web|n1": 3, "ns/Deployment/web|n2": 1}}
    b = {"placements": {"ns/Deployment/web|n1": 2, "ns/Deployment/web|n2": 2}}
    rate, detail = match_rate(a, b)
    assert rate == pytest.approx(3 / 4)
    assert set(detail) == {"ns/Deployment/web|n1", "ns/Deployment/web|n2"}


def test_parity_cli(tmp_path):
    from open_simulator_tpu.cli.main import main as cli_main

    golden = load_dump(GOLDEN)
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    save_dump(golden, str(a))
    worse = copy.deepcopy(golden)
    k = next(iter(worse["placements"]))
    worse["placements"][k] += 50
    save_dump(worse, str(b))
    assert cli_main(["parity", str(a), str(a)]) == 0
    assert cli_main(["parity", str(a), str(b), "--threshold", "0.999"]) == 1

