"""End-to-end GSPMD sharding (parallel/mesh.py ShardedKernels): placements
bit-identical to single-device at every shard count and on every kernel
route, zero recompiles on a warm second dispatch, carry donation actually
frees the old buffers, chained dispatches never reshard the carry, and the
phantom padding / node-axis growth invariants survive donation and reuse."""

import copy
import re

import numpy as np
import pytest

import jax

from fixtures import make_node, make_pod
from open_simulator_tpu.models.fakenode import new_fake_nodes
from open_simulator_tpu.obs import REGISTRY
from open_simulator_tpu.ops import kernels
from open_simulator_tpu.parallel.mesh import (
    carry_reshard_bytes,
    carry_shardings,
    make_node_mesh,
    make_scenario_mesh,
    sharded_kernels,
    table_shardings,
    to_device_sharded,
)
from open_simulator_tpu.simulator.encode import scheduling_signature
from open_simulator_tpu.simulator.engine import Simulator
from open_simulator_tpu.simulator.probe import ProbeSession


def _census(sim):
    out = {}
    for i, nps in enumerate(sim.pods_on_node):
        for p in nps:
            key = (i, scheduling_signature(p))
            out[key] = out.get(key, 0) + 1
    return out


def _mixed_workload():
    """One batch exercising every engine route: wave (identical pods),
    cap1 wave (host ports), affinity (self-matching hostname DNS spread),
    and serial (runs shorter than WAVE_MIN with alternating groups)."""
    nodes = [make_node(f"n{i}", cpu="16", memory="32Gi", pods="24")
             for i in range(26)]  # 26: not divisible by 8 → phantom padding
    pods = [make_pod(f"web-{i}", cpu="250m", memory="256Mi",
                     labels={"app": "web"}) for i in range(40)]
    for i in range(30):
        p = make_pod(f"sp-{i}", cpu="100m", memory="64Mi",
                     labels={"app": "sp"})
        p["spec"]["topologySpreadConstraints"] = [{
            "maxSkew": 2, "topologyKey": "kubernetes.io/hostname",
            "whenUnsatisfiable": "DoNotSchedule",
            "labelSelector": {"matchLabels": {"app": "sp"}}}]
        pods.append(p)
    pods += [make_pod(f"porty-{i}", cpu="100m", memory="64Mi",
                      labels={"app": "porty"}, host_ports=[9090])
             for i in range(10)]
    for i in range(6):  # alternating singletons → serial scan segment
        pods.append(make_pod(f"a-{i}", cpu="300m", memory="128Mi"))
        pods.append(make_pod(f"b-{i}", cpu="100m", memory="512Mi"))
    return nodes, pods


def _run(nodes, pods, mesh=None):
    sim = Simulator(copy.deepcopy(nodes), use_mesh=mesh is not None)
    if mesh is not None:
        sim._mesh = mesh  # pin the shard count (auto would take all devices)
    failed = sim.schedule_pods(copy.deepcopy(pods))
    return sim, _census(sim), len(failed)


@pytest.mark.parametrize("shards", [2, 4, 8])
def test_placements_bit_identical_across_shard_counts(shards):
    nodes, pods = _mixed_workload()
    _, want, want_failed = _run(nodes, pods, mesh=None)
    sim, got, got_failed = _run(nodes, pods, mesh=make_node_mesh(shards))
    kinds = {s[0] for s in sim._segments(sim._last_tables,
                                         len(sim._last_tables.valid))}
    assert got == want and got_failed == want_failed
    # the batch really covered the wave/affinity/serial routes
    assert {"wave", "affinity", "serial"} <= kinds


def _hard_affinity_workload():
    """Hard-predicate affinity batch: required self-anti-affinity on
    hostname (one per node, the overflow must FAIL), required self-affinity
    (the bootstrap-then-pack path), and DoNotSchedule spread — the gates
    the epoch-amortized sharded affinity kernel folds into its stacked
    per-epoch all-reduce and must reproduce bit-for-bit."""
    nodes = [make_node(f"h{i}", cpu="16", memory="32Gi", pods="24")
             for i in range(26)]  # 26: not divisible by 8 → phantom padding
    pods = []
    for i in range(30):  # 26 can place, 4 must fail identically
        p = make_pod(f"anti-{i}", cpu="100m", memory="64Mi",
                     labels={"app": "anti"})
        p["spec"]["affinity"] = {"podAntiAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [{
                "topologyKey": "kubernetes.io/hostname",
                "labelSelector": {"matchLabels": {"app": "anti"}}}]}}
        pods.append(p)
    for i in range(12):  # required self-affinity: bootstrap a node, pack it
        p = make_pod(f"pack-{i}", cpu="100m", memory="64Mi",
                     labels={"app": "pack"})
        p["spec"]["affinity"] = {"podAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [{
                "topologyKey": "kubernetes.io/hostname",
                "labelSelector": {"matchLabels": {"app": "pack"}}}]}}
        pods.append(p)
    for i in range(20):  # hard spread: DoNotSchedule at maxSkew 1
        p = make_pod(f"hs-{i}", cpu="100m", memory="64Mi",
                     labels={"app": "hs"})
        p["spec"]["topologySpreadConstraints"] = [{
            "maxSkew": 1, "topologyKey": "kubernetes.io/hostname",
            "whenUnsatisfiable": "DoNotSchedule",
            "labelSelector": {"matchLabels": {"app": "hs"}}}]
        pods.append(p)
    return nodes, pods


@pytest.mark.parametrize("shards", [2, 8])
def test_hard_predicate_affinity_bit_identical_across_shards(shards):
    """The epoch-amortized collective path (ONE stacked all-reduce + ONE
    payload all-gather per epoch, selection replicated post-gather) must
    not perturb a single placement on the hard-predicate wave: required
    anti-affinity overflow fails identically, the self-affinity bootstrap
    picks the same node, and hard spread balances identically."""
    nodes, pods = _hard_affinity_workload()
    _, want, want_failed = _run(nodes, pods, mesh=None)
    assert want_failed == 4  # the hard predicate really bites
    sim, got, got_failed = _run(nodes, pods, mesh=make_node_mesh(shards))
    kinds = {s[0] for s in sim._segments(sim._last_tables,
                                         len(sim._last_tables.valid))}
    assert "affinity" in kinds  # the batch really drove the affinity kernel
    assert got == want and got_failed == want_failed


def test_zero_recompiles_on_warm_second_dispatch():
    """Two Simulators over EQUAL meshes share one sharded-executable set:
    the second run must not trigger a single XLA backend compile
    (simon_xla_backend_compiles_total is jax.monitoring ground truth)."""
    nodes, pods = _mixed_workload()
    _run(nodes, pods, mesh=make_node_mesh(8))  # pays every compile
    before = REGISTRY.values().get("simon_xla_backend_compiles_total", 0)
    _run(nodes, pods, mesh=make_node_mesh(8))  # fresh EQUAL mesh, same shapes
    after = REGISTRY.values().get("simon_xla_backend_compiles_total", 0)
    assert after == before, "warm second dispatch recompiled"


def _encode_unconstrained(n_nodes=26, n_pods=32):
    nodes = [make_node(f"n{i}", cpu="16", memory="32Gi")
             for i in range(n_nodes)]
    pods = [make_pod(f"p-{i}", cpu="500m", memory="256Mi",
                     labels={"app": "w"}) for i in range(n_pods)]
    sim = Simulator(nodes)
    return sim, sim.encode_batch(pods)


def test_donation_gated_off_on_multi_device_cpu_mesh():
    """Dispatching donated executables on a multi-device CPU mesh is unsound
    under the XLA:CPU async runtime (intermittent in-place corruption — see
    parallel.mesh.donation_runtime_safe), so the factory must downgrade a
    donate=True request to the undonated view: inputs stay alive. The
    donated artifact itself is still certified (AOT, never executed) by
    simonaudit's goldens — donation.aliased == 8/8 for every engine kernel."""
    from open_simulator_tpu.parallel.mesh import donation_runtime_safe

    mesh = make_node_mesh(8)
    assert not donation_runtime_safe(mesh)  # 8 virtual CPU devices
    sim, bt = _encode_unconstrained()
    tables, carry, bt = to_device_sharded(bt, mesh)
    sk = sharded_kernels(mesh, donate=True)  # downgraded by the factory
    assert sk.donate is False
    final, choices = sk.schedule_batch(
        tables, carry, bt.pod_group, bt.forced_node, bt.valid,
        n_zones=bt.n_zones, enable_gpu=False, enable_storage=False)
    jax.block_until_ready(final)
    assert not carry.requested.is_deleted(), "carry donated despite the gate"
    assert not tables.alloc.is_deleted()  # tables are never donated

    # the explicit undonated view is the same object (shared jit cache)
    assert sharded_kernels(mesh, donate=False) is sk


def test_chained_dispatches_zero_reshard():
    """Wave N's output carry must already BE in wave N+1's declared input
    sharding — per-leaf equivalence, the carry_reshard_bytes audit, and the
    engine's simon_reshard_bytes_total all agree on zero."""
    mesh = make_node_mesh(8)
    sim, bt = _encode_unconstrained()
    tables, carry, bt = to_device_sharded(bt, mesh)
    sk = sharded_kernels(mesh, donate=False)
    declared = carry_shardings(mesh)
    c = carry
    for _ in range(2):  # chain two dispatches through the same executable
        c, _j, _p = sk.schedule_wave(
            tables, c, np.int32(0), np.int32(8), np.bool_(False))
        for leaf, want in zip(c, declared):
            assert leaf.sharding.is_equivalent_to(want, leaf.ndim)
    assert carry_reshard_bytes(c, sk.carry_sh) == 0

    # engine-level: a full multi-segment mesh run keeps the counter at zero
    before = REGISTRY.values().get("simon_reshard_bytes_total", 0)
    nodes, pods = _mixed_workload()
    _run(nodes, pods, mesh=make_node_mesh(8))
    assert REGISTRY.values().get("simon_reshard_bytes_total", 0) == before == 0


def _collective_count(compiled_text):
    return len(re.findall(
        r"\b(?:all-reduce|all-gather|reduce-scatter|collective-permute"
        r"|all-to-all)\b", compiled_text))


def test_chained_hlo_adds_no_boundary_collectives():
    """Compile one wave and a two-wave chain under the SAME in/out
    shardings: the chained program may contain at most 2x the single
    program's collectives — i.e. the dispatch boundary itself inserts zero
    resharding collectives."""
    mesh = make_node_mesh(8)
    sim, bt = _encode_unconstrained()
    tables, carry, bt = to_device_sharded(bt, mesh)
    ts, cs = table_shardings(mesh), carry_shardings(mesh)
    from jax.sharding import NamedSharding, PartitionSpec as P

    rep = NamedSharding(mesh, P())
    node_sh = NamedSharding(mesh, P("nodes"))
    raw = kernels.schedule_wave.__wrapped__

    def single(tb, cry, g, m, cap1):
        return raw(tb, cry, g, m, cap1)

    def chain(tb, cry, g, m, cap1):
        c1, j1, p1 = raw(tb, cry, g, m, cap1)
        c2, j2, p2 = raw(tb, c1, g, m, cap1)
        return c2, j1 + j2, p1 + p2

    args = (tables, carry, np.int32(0), np.int32(8), np.bool_(False))
    shard_kw = dict(in_shardings=(ts, cs, rep, rep, rep),
                    out_shardings=(cs, node_sh, rep))
    n1 = _collective_count(
        jax.jit(single, **shard_kw).lower(*args).compile().as_text())
    n2 = _collective_count(
        jax.jit(chain, **shard_kw).lower(*args).compile().as_text())
    assert n1 > 0  # the wave genuinely reduces across shards
    assert n2 <= 2 * n1, (
        f"chained program has {n2} collectives vs {n1} for one wave: "
        f"the dispatch boundary inserted resharding collectives")


def test_phantom_nodes_unwinnable_under_donation_and_reuse():
    """26 real nodes over 8 shards leave 6 phantom columns. Two back-to-back
    batches on ONE mesh simulator (donated carry chain, reused executables)
    under hard capacity pressure: every placement lands on a real node, the
    overflow fails instead of spilling onto phantoms, and the phantom carry
    rows stay untouched."""
    nodes = [make_node(f"n{i}", cpu="4", memory="8Gi", pods="8")
             for i in range(26)]  # 104 cpu-capacity pods cluster-wide
    mk = lambda i: make_pod(f"p-{i}", cpu="1", memory="128Mi",
                            labels={"app": "w"})
    sim = Simulator(nodes, use_mesh=True)
    sim._mesh = make_node_mesh(8)
    failed1 = sim.schedule_pods([mk(i) for i in range(80)])
    failed2 = sim.schedule_pods([mk(100 + i) for i in range(80)])
    assert len(failed1) == 0
    assert len(failed2) == 80 - (104 - 80)  # only real capacity remains
    assert sum(len(p) for p in sim.pods_on_node) == 104
    # the carry's phantom rows never accumulated anything
    req = np.asarray(sim._last_carry.requested)
    assert req.shape[0] >= 32 and not req[26:].any()
    # and the single-device engine agrees exactly
    sim1 = Simulator(nodes, use_mesh=False)
    f1 = sim1.schedule_pods([mk(i) for i in range(80)])
    f2 = sim1.schedule_pods([mk(100 + i) for i in range(80)])
    assert (len(f1), len(f2)) == (len(failed1), len(failed2))
    assert _census(sim1) == _census(sim)


def test_probe_fanout_scenario_mesh_matches_unsharded_session():
    """The capacity prober's fan-out on a ('scenarios','nodes') mesh — the
    sharded probe_*_fanout executables — must return the same counts and
    utilization as the unsharded session and fresh probes."""
    base = [make_node(f"base-{i}", cpu="8", memory="16Gi") for i in range(2)]
    template = make_node("tpl", cpu="8", memory="16Gi")
    pods = [make_pod(f"p-{i}", cpu="2", memory="2Gi") for i in range(40)]
    s_mesh = ProbeSession.try_build(base, template, pods, n_new=12,
                                    mesh=make_scenario_mesh(4))
    s_plain = ProbeSession.try_build(base, template, pods, n_new=12)
    assert s_mesh is not None and s_plain is not None
    ns = [0, 3, 5, 7, 11]
    assert s_mesh.probe_many(ns) == s_plain.probe_many(ns)


def test_device_extension_matches_host_reupload():
    """ensure_capacity's shard-local growth: the device-extended tables must
    be BIT-identical to a host re-upload of the extended host mirror, with
    zero bytes staged host→device for the table set."""
    base = [make_node(f"base-{i}", cpu="8", memory="16Gi") for i in range(2)]
    template = make_node("tpl", cpu="8", memory="16Gi")
    pods = [make_pod(f"p-{i}", cpu="2", memory="2Gi") for i in range(40)]
    session = ProbeSession.try_build(base, template, pods, n_new=2)
    assert session is not None
    assert not session._host_counters and not session._host_carriers
    before = REGISTRY.values().get("simon_device_transfer_bytes_total", 0)
    session.ensure_capacity(20)  # crosses the padding bucket → extension
    assert session.extensions == 1
    after = REGISTRY.values().get("simon_device_transfer_bytes_total", 0)
    assert after == before, "device extension staged table bytes from host"
    # bit-identity against the host path
    from open_simulator_tpu.parallel.mesh import tables_from_batch

    host = tables_from_batch(session._bt)
    for name, dev, want in zip(kernels.Tables._fields, session._tables, host):
        np.testing.assert_array_equal(
            np.asarray(dev), np.asarray(want), err_msg=name)
    # and probe results still match fresh probes at the extended size
    sim = Simulator(base + new_fake_nodes(template, 20))
    fresh = sim.probe_pods(list(pods))
    got = session.probe_many([20])[20]
    assert (got[0], got[1]) == fresh


def test_hostname_rows_fall_back_to_host_reupload():
    """Required self-anti-affinity on hostname gives the session
    hostname-keyed carrier/counter rows: extension must take the host
    re-upload path (per-node fresh domains) and stay exact."""
    base = [make_node(f"base-{i}", cpu="8", memory="16Gi") for i in range(2)]
    template = make_node("tpl", cpu="8", memory="16Gi")
    pods = []
    for i in range(12):
        p = make_pod(f"a-{i}", cpu="2", memory="2Gi", labels={"app": "anti"})
        p["spec"]["affinity"] = {"podAntiAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [{
                "topologyKey": "kubernetes.io/hostname",
                "labelSelector": {"matchLabels": {"app": "anti"}}}]}}
        pods.append(p)
    session = ProbeSession.try_build(base, template, pods, n_new=2)
    assert session is not None
    assert session._host_counters or session._host_carriers
    tb = REGISTRY.values().get("simon_device_transfer_bytes_total", 0)
    session.ensure_capacity(20)
    assert REGISTRY.values().get(
        "simon_device_transfer_bytes_total", 0) > tb  # host path re-staged
    got = session.probe_many([14])[14]
    sim = Simulator(base + new_fake_nodes(template, 14))
    assert (got[0], got[1]) == sim.probe_pods(list(pods))


def test_probe_fanout_utilization_stable_across_repeated_sessions():
    """Regression (found while goldening simonaudit's donation certificates):
    a DONATED fan-out dispatch of the [S, N, R] carry on a scenario mesh
    intermittently corrupted the fetched `requested` leaf on the XLA:CPU
    runtime (~1/3 of dispatches under a warm compile cache) — garbage
    utilization with correct placed counts. The probe path now dispatches
    the undonated view; several fresh sessions must agree exactly."""
    base = [make_node(f"base-{i}", cpu="8", memory="16Gi") for i in range(2)]
    template = make_node("tpl", cpu="8", memory="16Gi")
    pods = [make_pod(f"p-{i}", cpu="2", memory="2Gi") for i in range(40)]
    ns = [0, 3, 5, 7, 11]
    plain = ProbeSession.try_build(base, template, list(pods), n_new=12)
    want = plain.probe_many(ns)
    for _ in range(4):
        s = ProbeSession.try_build(base, template, list(pods), n_new=12,
                                   mesh=make_scenario_mesh(4))
        assert s.probe_many(ns) == want


def test_donation_still_frees_carry_on_single_device_mesh():
    """Where donation stays ENABLED (donation_runtime_safe: single-device
    meshes, accelerators), a donated dispatch must actually free its input
    carry — the end-to-end donation behavior the audit's AOT certificates
    cannot observe. A dispatch-time regression that stops donating would
    pass the goldens but fail here."""
    from open_simulator_tpu.parallel.mesh import donation_runtime_safe

    mesh = make_node_mesh(1)
    assert donation_runtime_safe(mesh)
    sim, bt = _encode_unconstrained()
    tables, carry, bt = to_device_sharded(bt, mesh)
    sk = sharded_kernels(mesh, donate=True)
    assert sk.donate is True
    final, choices = sk.schedule_batch(
        tables, carry, bt.pod_group, bt.forced_node, bt.valid,
        n_zones=bt.n_zones, enable_gpu=False, enable_storage=False)
    jax.block_until_ready(final)
    assert carry.requested.is_deleted(), "donated carry buffer still alive"
    assert not tables.alloc.is_deleted()  # tables are never donated
