"""simonha: crash-consistent serving (serve/ha.py).

The contract under test (README "High availability", ISSUE PR 19):

- **Crash-restart bit-identity.** `--state-dir` restart (checkpoint + WAL
  tail replay) produces an image bit-identical to the never-crashed process
  — same epoch, same host truth, same what-if answers — across the seeded
  churn traces the PR 10 delta-ingest property tests already pin.
- **WAL recovery.** A torn tail (SIGKILL mid-write) truncates to the valid
  prefix; duplicate records replay idempotently (seq <= image.seq skips); a
  seq gap or a lineage-digest mismatch is refused loudly (WalMismatch), and
  a doctored checkpoint never loads.
- **Admission determinism.** Seeded controller + injectable clock: the same
  request sequence sheds identically, with the same jittered Retry-After.
- **Bounded staleness.** Degraded mode serves the last consistent epoch,
  stamps staleness, flips /healthz at the ceiling, and recovers via the
  next good ingest or an explicit generation-bumping resync — never a
  wrong answer (the wrong-epoch tripwire).
"""

import json
import threading

import numpy as np
import pytest

from open_simulator_tpu.obs import REGISTRY
from open_simulator_tpu.resilience import FaultPlan, installed
from open_simulator_tpu.serve import (
    AdmissionController,
    HAState,
    IngestWAL,
    ResidentImage,
    ShedError,
    WalMismatch,
    WhatIfService,
    WrongEpochError,
    lineage_digest,
    load_checkpoint,
    save_checkpoint,
)
from open_simulator_tpu.serve.ha import CHECKPOINT_NAME, WAL_NAME

from fixtures import make_pod
from test_serve import (
    _trace_events,
    assert_same_response,
    make_cluster,
    whatif_pods,
)


def _builder(n_nodes=8, n_bound=5):
    """A build_image closure over a fixed boot cluster (fresh copies per
    call, like the server's snapshot_fn path)."""
    nodes, bound = make_cluster(n_nodes, n_bound)

    def build():
        return ResidentImage.try_build(
            [json.loads(json.dumps(n)) for n in nodes],
            pods=[json.loads(json.dumps(p)) for p in bound])

    return build, nodes


def _host_truth(image):
    return json.dumps({"nodes": image.current_nodes(),
                       "pods": image.cluster_pods()},
                      sort_keys=True, default=str)


# ------------------------------------------------- crash-restart identity ----


@pytest.mark.parametrize("seed", [7, 23, 101])
def test_crash_restart_bit_identity(seed, tmp_path):
    """The acceptance oracle: apply a seeded churn trace twice — once
    uninterrupted, once 'crashed' mid-trace (the HAState abandoned without
    close, a torn partial record on the WAL tail) and restarted from the
    state dir — and require identical epoch, host truth, and answers."""
    build, nodes = _builder()
    live = [0, 0, 0]
    rng = np.random.default_rng(seed)
    batches = [_trace_events(rng, nodes, live) for _ in range(6)]
    req = whatif_pods("ha", 5, anti_on="churn")

    ha_a = HAState.open(str(tmp_path / "a"), build, checkpoint_every=3)
    for evs in batches:
        ha_a.ingest(evs)

    ha_b = HAState.open(str(tmp_path / "b"), build, checkpoint_every=3)
    for evs in batches[:4]:
        ha_b.ingest(evs)
    # SIGKILL: no close, and a torn partial record on the tail
    with open(str(tmp_path / "b" / WAL_NAME), "a") as f:
        f.write('{"seq": 999, "events": [{"type": "pod_')
    ha_b2 = HAState.open(str(tmp_path / "b"), build, checkpoint_every=3)
    assert ha_b2.wal.truncated or not ha_b2.wal.records  # tail repaired
    for evs in batches[4:]:
        ha_b2.ingest(evs)

    assert ha_b2.image.epoch == ha_a.image.epoch
    assert _host_truth(ha_b2.image) == _host_truth(ha_a.image)
    assert_same_response(ha_b2.image.session(req).run(),
                         ha_a.image.session(req).run())
    ha_a.close()
    ha_b2.close()


def test_restart_without_checkpoint_replays_full_wal(tmp_path):
    build, _ = _builder()
    ha = HAState.open(str(tmp_path), build, checkpoint_every=100)
    for i in range(3):
        ha.ingest([{"type": "pod_add", "pod": make_pod(
            f"w-{i}", cpu="1", memory="1Gi", node_name="n-0")}])
    truth, epoch = _host_truth(ha.image), ha.image.epoch
    ha.close()
    ha2 = HAState.open(str(tmp_path), build, checkpoint_every=100)
    assert (ha2.replayed, ha2.skipped) == (3, 0)
    assert ha2.image.epoch == epoch and _host_truth(ha2.image) == truth
    ha2.close()


def test_compaction_seals_wal_and_restore_uses_checkpoint(tmp_path):
    build, _ = _builder()
    ha = HAState.open(str(tmp_path), build, checkpoint_every=2)
    for i in range(5):
        ha.ingest([{"type": "pod_add", "pod": make_pod(
            f"c-{i}", cpu="1", memory="1Gi", node_name="n-1")}])
    # 2 compactions landed; the WAL holds only the unsealed tail
    assert len(ha.wal.records) == 1
    truth, epoch = _host_truth(ha.image), ha.image.epoch
    ha.close()
    ha2 = HAState.open(str(tmp_path), build, checkpoint_every=2)
    assert ha2.replayed == 1  # checkpoint carried the sealed 4
    assert ha2.image.epoch == epoch and _host_truth(ha2.image) == truth
    ha2.close()


# ----------------------------------------------------------- WAL recovery ----


def test_wal_torn_tail_truncates_to_valid_prefix(tmp_path):
    path = str(tmp_path / "w.wal")
    wal = IngestWAL.open(path, "d1")
    wal.append(1, [{"type": "node_drain", "name": "n-0"}])
    wal.append(2, [{"type": "node_drain", "name": "n-1"}])
    wal.close()
    with open(path, "ab") as f:  # invalid utf-8 mid-record, no newline
        f.write(b'{"seq": 3, "events": [\xff\xfe')
    size_before = len(open(path, "rb").read())
    wal2 = IngestWAL.open(path, "d1")
    assert wal2.truncated
    assert [s for s, _ in wal2.records] == [1, 2]
    assert len(open(path, "rb").read()) < size_before  # bytes actually gone
    wal2.append(3, [])  # the repaired log accepts appends again
    wal2.close()


def test_wal_unterminated_parsable_tail_not_replayed(tmp_path):
    """A record without its newline is NOT durable even when it parses:
    fsync ordering only proves bytes up to the last terminator."""
    path = str(tmp_path / "w.wal")
    wal = IngestWAL.open(path, "d1")
    wal.append(1, [])
    wal.close()
    with open(path, "a") as f:
        f.write(json.dumps({"seq": 2, "events": []}))  # no \n
    wal2 = IngestWAL.open(path, "d1")
    assert [s for s, _ in wal2.records] == [1]
    wal2.close()


def test_wal_digest_mismatch_refused(tmp_path):
    path = str(tmp_path / "w.wal")
    IngestWAL.open(path, "lineage-a").close()
    before = REGISTRY.values().get(
        "simon_serve_wal_parity_mismatches_total", 0)
    with pytest.raises(WalMismatch, match="different serving lineage"):
        IngestWAL.open(path, "lineage-b")
    assert REGISTRY.values()[
        "simon_serve_wal_parity_mismatches_total"] == before + 1


def test_duplicate_epoch_replay_is_idempotent(tmp_path):
    build, _ = _builder()
    ha = HAState.open(str(tmp_path), build, checkpoint_every=100)
    for i in range(3):
        ha.ingest([{"type": "pod_add", "pod": make_pod(
            f"d-{i}", cpu="1", memory="1Gi", node_name="n-2")}])
    truth, epoch = _host_truth(ha.image), ha.image.epoch
    ha.close()
    # a duplicate of record 2 on the tail (e.g. an at-least-once shipper)
    with open(str(tmp_path / WAL_NAME)) as f:
        dup = f.readlines()[2]
    with open(str(tmp_path / WAL_NAME), "a") as f:
        f.write(dup)
    ha2 = HAState.open(str(tmp_path), build, checkpoint_every=100)
    assert (ha2.replayed, ha2.skipped) == (3, 1)
    assert ha2.image.epoch == epoch and _host_truth(ha2.image) == truth
    ha2.close()


def test_wal_seq_gap_refused(tmp_path):
    build, _ = _builder()
    ha = HAState.open(str(tmp_path), build, checkpoint_every=100)
    ha.ingest([{"type": "node_drain", "name": "n-0"}])
    ha.close()
    with open(str(tmp_path / WAL_NAME), "a") as f:
        f.write(json.dumps({"seq": 5, "events": []}) + "\n")
    with pytest.raises(WalMismatch, match="replay gap"):
        HAState.open(str(tmp_path), build, checkpoint_every=100)


def test_doctored_checkpoint_refused(tmp_path):
    build, _ = _builder()
    ha = HAState.open(str(tmp_path), build, checkpoint_every=1)
    ha.ingest([{"type": "node_drain", "name": "n-0"}])  # forces a checkpoint
    ha.close()
    path = str(tmp_path / CHECKPOINT_NAME)
    raw = bytearray(open(path, "rb").read())
    raw[-1] ^= 0xFF  # flip one payload byte; header sha256 now disagrees
    open(path, "wb").write(bytes(raw))
    with pytest.raises(WalMismatch, match="sha256 mismatch"):
        load_checkpoint(path)
    with pytest.raises(WalMismatch):
        HAState.open(str(tmp_path), build)
    # truncation (torn rename never happens — os.replace is atomic — but a
    # copy mid-write can truncate) is refused too
    open(path, "wb").write(bytes(raw[:len(raw) // 2]))
    with pytest.raises(WalMismatch):
        load_checkpoint(path)


def test_checkpoint_roundtrip_preserves_epoch_and_truth(tmp_path):
    nodes, bound = make_cluster(8, 5)
    img = ResidentImage.try_build(nodes, pods=bound)
    img.apply_events([{"type": "node_drain", "name": "n-7"}])
    digest = lineage_digest(img.current_nodes(), img.cluster_pods())
    path = str(tmp_path / "c.bin")
    head = save_checkpoint(path, img, digest)
    assert (head["generation"], head["seq"]) == (img.generation, img.seq)
    from open_simulator_tpu.serve import restore_image

    head2, state = load_checkpoint(path)
    img2 = restore_image(state)
    assert img2.epoch == img.epoch
    assert _host_truth(img2) == _host_truth(img)
    req = whatif_pods("ckpt", 4)
    assert_same_response(img2.session(req).run(), img.session(req).run())


def test_compaction_races_concurrent_ingest(tmp_path):
    """checkpoint() from a background thread serializes with ingest under
    the same locks: no torn capture, and the final restart is bit-identical
    to the live image."""
    build, _ = _builder()
    ha = HAState.open(str(tmp_path), build, checkpoint_every=10_000)
    errors = []

    def churn():
        try:
            for i in range(20):
                ha.ingest([{"type": "pod_add", "pod": make_pod(
                    f"r-{i}", cpu="1", memory="1Gi",
                    node_name=f"n-{i % 8}")}])
        except Exception as e:  # pragma: no cover - surfaced below
            errors.append(e)

    def compact():
        try:
            for _ in range(10):
                ha.checkpoint()
        except Exception as e:  # pragma: no cover - surfaced below
            errors.append(e)

    threads = [threading.Thread(target=churn),
               threading.Thread(target=compact)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    truth, epoch = _host_truth(ha.image), ha.image.epoch
    ha.close()
    ha2 = HAState.open(str(tmp_path), build)
    assert ha2.image.epoch == epoch and _host_truth(ha2.image) == truth
    ha2.close()


# ------------------------------------------------------- admission control ----


def _scripted_clock(start=0.0):
    t = [start]

    def clock():
        return t[0]

    return t, clock


def test_admission_queue_bound_sheds():
    ac = AdmissionController(max_queue=4, seed=0)
    ac.admit("whatif", "a", queued=3)  # under the bound: admitted
    with pytest.raises(ShedError) as ei:
        ac.admit("whatif", "a", queued=4)
    assert ei.value.reason == "queue_full" and ei.value.retry_after > 0
    assert ac.sheds == 1


def test_admission_tenant_buckets_isolate_and_refill():
    t, clock = _scripted_clock()
    ac = AdmissionController(max_queue=100, tenant_rate=1.0,
                             tenant_burst=2.0, seed=0, clock=clock)
    ac.admit("whatif", "a", 0)
    ac.admit("whatif", "a", 0)
    with pytest.raises(ShedError) as ei:
        ac.admit("whatif", "a", 0)  # burst of 2 spent
    assert ei.value.reason == "rate_limit"
    ac.admit("whatif", "b", 0)  # tenant b has its own bucket
    t[0] = 1.5  # 1.5s refill at 1 rps
    ac.admit("whatif", "a", 0)


def test_admission_deadline_shed_needs_evidence():
    t, clock = _scripted_clock()
    ac = AdmissionController(max_queue=100, seed=0, clock=clock)
    # cold controller: no p95 evidence, a tight deadline still admits
    ac.admit("whatif", "a", 0, deadline_s=0.001)
    for _ in range(20):
        ac.observe_wall(1.0)
    with pytest.raises(ShedError) as ei:
        ac.admit("whatif", "a", 0, deadline_s=0.5)  # p95=1.0 > remaining
    assert ei.value.reason == "deadline"
    ac.admit("whatif", "a", 0, deadline_s=2.0)  # covered: admitted


def test_admission_shed_sequence_is_deterministic():
    """Same seed + same scripted request sequence => identical shed
    decisions AND identical jittered retry_after values."""

    def run():
        t, clock = _scripted_clock()
        ac = AdmissionController(max_queue=2, tenant_rate=1.0,
                                 tenant_burst=1.0, seed=42, clock=clock)
        for _ in range(10):
            ac.observe_wall(0.4)
        out = []
        for step, (tenant, queued, deadline) in enumerate(
                [("a", 0, None), ("a", 0, None), ("b", 5, None),
                 ("b", 0, 0.1), ("a", 1, None), ("c", 2, 0.05)]):
            t[0] = 0.25 * step
            try:
                ac.admit("whatif", tenant, queued, deadline_s=deadline)
                out.append("ok")
            except ShedError as e:
                out.append((e.reason, round(e.retry_after, 9)))
        return out

    a, b = run(), run()
    assert a == b
    assert any(isinstance(x, tuple) for x in a)  # the script does shed


def test_service_submit_sheds_through_admission():
    nodes, bound = make_cluster(8, 3)
    img = ResidentImage.try_build(nodes, pods=bound)
    t, clock = _scripted_clock()
    ac = AdmissionController(max_queue=8, tenant_rate=1.0, tenant_burst=1.0,
                             seed=0, clock=clock)
    svc = WhatIfService(img, window_ms=0.0, admission=ac)
    req = whatif_pods("shed", 2)
    first = svc.submit(req, tenant="t1")
    assert first["total"] == 2
    with pytest.raises(ShedError):  # bucket of 1 spent, clock frozen
        svc.submit(req, tenant="t1")
    assert svc.stats()["sheds"] == 1
    assert "window_scale" in svc.stats()
    svc.stop()


# --------------------------------------------- degraded mode / staleness -----


def test_ingest_stall_degrades_then_ceiling_flips_health(tmp_path):
    t, clock = _scripted_clock()
    build, _ = _builder()
    ha = HAState.open(str(tmp_path), build, checkpoint_every=100,
                      staleness_ceiling_s=30.0, clock=clock)
    assert ha.healthy() and ha.staleness_s() == 0.0
    plan = FaultPlan.from_json({"faults": [
        {"site": "ingest_stall", "attempt": 1, "error": "transient"}]})
    with installed(plan):
        with pytest.raises(Exception):
            ha.ingest([{"type": "node_drain", "name": "n-0"}])
    assert ha.degraded_reason() == "ingest_stall"
    t[0] = 10.0
    assert ha.staleness_s() == 10.0 and ha.healthy()  # inside the ceiling
    assert ha.stats()["degraded"] == "ingest_stall"
    t[0] = 31.0
    assert not ha.healthy()  # the 503 flip
    # recovery: the next successful ingest clears staleness entirely
    ha.ingest([{"type": "node_drain", "name": "n-1"}])
    assert ha.degraded_reason() is None and ha.staleness_s() == 0.0
    assert ha.healthy()
    ha.close()


def test_wal_append_failure_degrades_and_image_untouched(tmp_path):
    build, _ = _builder()
    ha = HAState.open(str(tmp_path), build, checkpoint_every=100)
    epoch = ha.image.epoch
    plan = FaultPlan.from_json({"faults": [
        {"site": "wal_write", "attempt": 1, "error": "transient"}]})
    with installed(plan):
        with pytest.raises(Exception):
            ha.ingest([{"type": "node_drain", "name": "n-0"}])
    # WAL-ahead: the apply never ran, the image never moved
    assert ha.image.epoch == epoch and ha.degraded_reason() == "wal"
    # serving continues at the last consistent epoch, stamped stale
    resp = {"epoch": ha.image.epoch}
    headers = ha.stamp(resp)
    assert headers["X-Simon-Epoch"] == epoch
    assert resp["staleness_s"] >= 0.0
    ha.close()


def test_resync_recovers_with_generation_bump(tmp_path):
    build, _ = _builder()
    ha = HAState.open(str(tmp_path), build, checkpoint_every=100)
    gen = ha.image.generation
    ha._enter_degraded("ingest")
    ha.resync()
    assert ha.image.generation == gen + 1
    assert ha.degraded_reason() is None and ha.healthy()
    req = whatif_pods("resync", 3)
    assert_same_response(ha.image.session(req).run(),
                         ha.image.fresh_probe(req))
    ha.close()


def test_wrong_epoch_tripwire_fails_loudly(tmp_path):
    build, _ = _builder()
    ha = HAState.open(str(tmp_path), build)
    before = REGISTRY.values().get(
        "simon_serve_wrong_epoch_answers_total", 0)
    with pytest.raises(WrongEpochError):
        ha.stamp({"epoch": f"{ha.image.generation}.{ha.image.seq + 1}"})
    with pytest.raises(WrongEpochError):
        ha.stamp({"epoch": f"{ha.image.generation + 1}.0"})
    assert REGISTRY.values()[
        "simon_serve_wrong_epoch_answers_total"] == before + 2
    # at or behind the image: stamped fine (degraded mode's whole point)
    assert "X-Simon-Epoch" in ha.stamp({"epoch": ha.image.epoch})
    ha.close()


def test_fault_sites_replay_equal(tmp_path):
    """Every new simonha fault site, injected twice with the same plan,
    produces the same fired-injection trace (the simonfault contract)."""
    build, _ = _builder()
    for site in ("wal_write", "wal_fsync", "checkpoint_write",
                 "ingest_stall"):
        traces = []
        for rep in range(2):
            d = tmp_path / f"{site}-{rep}"
            ha = HAState.open(str(d), build, checkpoint_every=1)
            plan = FaultPlan.from_json({"faults": [
                {"site": site, "attempt": 1, "error": "transient"}]})
            with installed(plan) as active:
                if site == "checkpoint_write":
                    # the batch landed durably before compaction failed:
                    # the ingest succeeds and the state degrades instead
                    # (a 500 would retry a landed delta into double-apply)
                    ha.ingest([{"type": "node_drain", "name": "n-0"}])
                    assert ha.degraded_reason() == "checkpoint"
                else:
                    with pytest.raises(Exception):
                        ha.ingest([{"type": "node_drain", "name": "n-0"}])
                    assert ha.degraded_reason() is not None
                traces.append(list(active.trace))
            ha.close()
        assert traces[0] == traces[1], site
        assert traces[0], site  # the site actually fired
