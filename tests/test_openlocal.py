"""Open-Local plugin: VG/device allocators, batched filter/score, bind writeback."""

import json

import pytest

from open_simulator_tpu import simulate
from open_simulator_tpu.core.types import AppResource, ResourceTypes
from open_simulator_tpu.plugins.openlocal import (
    OpenLocalVolume,
    allocate_devices,
    allocate_lvm,
    resolve_pod_volumes,
    score_binpack,
)
from open_simulator_tpu.utils.storage import VG, Device, NodeStorage

from fixtures import make_node, make_pod, make_statefulset

GI = 1 << 30


def storage_node(name, vgs=None, devices=None, cpu="32", mem="64Gi"):
    st = NodeStorage(
        vgs=[VG(n, c) for n, c in (vgs or [])],
        devices=[Device(d, c, m) for d, c, m in (devices or [])],
    )
    return make_node(name, cpu=cpu, memory=mem,
                     annotations={"simon/node-local-storage": st.to_json()})


def lvm_sc(name="open-local-lvm", vg_name=None):
    sc = {"apiVersion": "storage.k8s.io/v1", "kind": "StorageClass",
          "metadata": {"name": name}, "provisioner": "local.csi.aliyun.com",
          "parameters": {"volumeType": "LVM"}}
    if vg_name:
        sc["parameters"]["vgName"] = vg_name
    return sc


def device_sc(name, media):
    return {"apiVersion": "storage.k8s.io/v1", "kind": "StorageClass",
            "metadata": {"name": name}, "provisioner": "local.csi.aliyun.com",
            "parameters": {"volumeType": "Device", "mediaType": media}}


def storage_pod(name, volumes, cpu="1", memory="1Gi"):
    """volumes: [(size, kind, scName)]"""
    pod = make_pod(name, cpu=cpu, memory=memory)
    payload = {"volumes": [
        {"size": str(s), "kind": k, "scName": sc} for s, k, sc in volumes
    ]}
    pod["metadata"]["annotations"] = {"simon/pod-local-storage": json.dumps(payload)}
    return pod


# ---------------------------------------------------------------- allocators --------


def test_allocate_lvm_binpack_tightest():
    vgs = [VG("a", 100), VG("b", 50)]
    ok, units = allocate_lvm(vgs, [OpenLocalVolume(40, "LVM", "sc", "", "")])
    assert ok and units == [(1, 40)]  # b has less free → tightest


def test_allocate_lvm_named_vg():
    vgs = [VG("a", 100), VG("b", 50)]
    ok, units = allocate_lvm(vgs, [OpenLocalVolume(40, "LVM", "sc", "a", "")])
    assert ok and units == [(0, 40)]
    ok, _ = allocate_lvm(vgs, [OpenLocalVolume(40, "LVM", "sc", "missing", "")])
    assert not ok


def test_allocate_lvm_sequential_accounting():
    vgs = [VG("a", 100)]
    ok, units = allocate_lvm(vgs, [OpenLocalVolume(60, "LVM", "sc", "", ""),
                                   OpenLocalVolume(60, "LVM", "sc", "", "")])
    assert not ok  # second volume sees only 40 free


def test_allocate_devices_media_and_size():
    devs = [Device("/dev/a", 100, "hdd"), Device("/dev/b", 50, "ssd"),
            Device("/dev/c", 200, "ssd")]
    vols = [OpenLocalVolume(60, "SSD", "sc", "", "ssd")]
    ok, units = allocate_devices(devs, vols)
    assert ok and units == [(2, 60)]  # only the 200 ssd fits
    ok, units = allocate_devices(devs, [OpenLocalVolume(40, "SSD", "sc", "", "ssd")])
    assert ok and units == [(1, 40)]  # smallest fitting ssd
    ok, _ = allocate_devices(devs, [OpenLocalVolume(300, "HDD", "sc", "", "hdd")])
    assert not ok


def test_score_binpack():
    vgs = [VG("a", 100)]
    devs = [Device("/dev/a", 100, "hdd")]
    # lvm: 50/100 → 5; device: 80/100 → 8 → total 13
    assert score_binpack(vgs, [(0, 50)], devs, [(0, 80)]) == 13
    assert score_binpack(vgs, [], devs, []) == 0


# ------------------------------------------------------------------- resolve --------


def test_resolve_orders_and_media():
    pod = storage_pod("p", [
        (10, "HDD", "hdd-sc"), (5, "SSD", "ssd-sc"), (20, "LVM", "open-local-lvm"),
        (7, "SSD", "ssd-sc"),
    ])
    scs = [lvm_sc(), device_sc("ssd-sc", "ssd"), device_sc("hdd-sc", "hdd")]
    lvm, dev = resolve_pod_volumes(pod, scs)
    assert [v.size for v in lvm] == [20]
    assert [(v.media, v.size) for v in dev] == [("ssd", 5), ("ssd", 7), ("hdd", 10)]


def test_resolve_drops_unknown_media():
    pod = storage_pod("p", [(10, "SSD", "typo-sc")])
    scs = [device_sc("typo-sc", "sdd")]  # the reference demo_1 typo
    lvm, dev = resolve_pod_volumes(pod, scs)
    assert not lvm and not dev


# ----------------------------------------------------------------- simulation -------


def _sim(nodes, pods, scs):
    cluster = ResourceTypes(nodes=nodes, storage_classes=scs)
    return simulate(cluster, [AppResource(name="app", resource=ResourceTypes(pods=pods))])


def test_lvm_filter_and_writeback():
    nodes = [storage_node("s0", vgs=[("pool", 10 * GI)]), make_node("plain")]
    pods = [storage_pod(f"p{i}", [(4 * GI, "LVM", "open-local-lvm")]) for i in range(2)]
    res = _sim(nodes, pods, [lvm_sc()])
    assert not res.unscheduled_pods
    by_name = {ns.node["metadata"]["name"]: ns for ns in res.node_status}
    assert len(by_name["s0"].pods) == 2 and not by_name["plain"].pods
    st = NodeStorage.from_json(
        by_name["s0"].node["metadata"]["annotations"]["simon/node-local-storage"]
    )
    assert st.vgs[0].requested == 8 * GI


def test_lvm_capacity_exhaustion():
    nodes = [storage_node("s0", vgs=[("pool", 10 * GI)])]
    pods = [storage_pod(f"p{i}", [(4 * GI, "LVM", "open-local-lvm")]) for i in range(3)]
    res = _sim(nodes, pods, [lvm_sc()])
    assert len(res.unscheduled_pods) == 1
    assert "local storage" in res.unscheduled_pods[0].reason


def test_device_exclusive_allocation():
    nodes = [storage_node("s0", devices=[("/dev/a", 100 * GI, "hdd"),
                                         ("/dev/b", 100 * GI, "hdd")])]
    pods = [storage_pod(f"p{i}", [(10 * GI, "HDD", "hdd-sc")]) for i in range(3)]
    res = _sim(nodes, pods, [device_sc("hdd-sc", "hdd")])
    # 2 devices, exclusive → third pod unschedulable
    assert len(res.unscheduled_pods) == 1
    st = NodeStorage.from_json(
        res.node_status[0].node["metadata"]["annotations"]["simon/node-local-storage"]
    )
    assert all(d.is_allocated for d in st.devices)


def test_storage_pod_unschedulable_without_storage_nodes():
    """Reference Filter: pod needs storage + node cache nil → Unschedulable
    (open-local.go:60-70), even when NO node in the cluster has storage."""
    nodes = [make_node("plain-1"), make_node("plain-2")]
    pods = [storage_pod("p0", [(1 * GI, "LVM", "open-local-lvm")])]
    res = _sim(nodes, pods, [lvm_sc()])
    assert len(res.unscheduled_pods) == 1


def test_kind_ignored_for_routing():
    """Routing is by SC name, not Kind: kind LVM + device SC → device demand."""
    nodes = [storage_node("s0", devices=[("/dev/a", 100 * GI, "ssd")])]
    pod = storage_pod("p0", [(10 * GI, "LVM", "ssd-sc")])
    res = _sim(nodes, [pod], [device_sc("ssd-sc", "ssd")])
    assert not res.unscheduled_pods
    st = NodeStorage.from_json(
        res.node_status[0].node["metadata"]["annotations"]["simon/node-local-storage"]
    )
    assert st.devices[0].is_allocated


def test_repeated_simulations_do_not_leak_storage():
    """The capacity planner re-simulates the same caller-owned cluster; plugin
    writebacks must stay inside each run's node copies."""
    nodes = [storage_node("s0", vgs=[("pool", 10 * GI)])]
    pods = [storage_pod(f"p{i}", [(4 * GI, "LVM", "open-local-lvm")]) for i in range(2)]
    for _ in range(3):
        res = _sim(nodes, pods, [lvm_sc()])
        assert not res.unscheduled_pods
    # the caller's node object is untouched
    st = NodeStorage.from_json(nodes[0]["metadata"]["annotations"]["simon/node-local-storage"])
    assert st.vgs[0].requested == 0


def test_device_merge_pass_silent_drop():
    """Reference quirk (CheckExclusiveResourceMeetsPVCSize): devices [20,40] and
    volumes [30,35] → the 20 is skipped, 40 takes the 30, devices run out, and the
    35 is silently dropped — the node still fits."""
    nodes = [storage_node("s0", devices=[("/dev/a", 20 * GI, "hdd"),
                                         ("/dev/b", 40 * GI, "hdd")])]
    pod = storage_pod("p0", [(30 * GI, "HDD", "hdd-sc"), (35 * GI, "HDD", "hdd-sc")])
    res = _sim(nodes, [pod], [device_sc("hdd-sc", "hdd")])
    assert not res.unscheduled_pods
    st = NodeStorage.from_json(
        res.node_status[0].node["metadata"]["annotations"]["simon/node-local-storage"]
    )
    assert [d.is_allocated for d in st.devices] == [False, True]


def test_device_count_precheck_fails():
    """But three volumes against two free devices fail the count pre-check."""
    nodes = [storage_node("s0", devices=[("/dev/a", 100 * GI, "hdd"),
                                         ("/dev/b", 100 * GI, "hdd")])]
    pod = storage_pod("p0", [(10 * GI, "HDD", "hdd-sc")] * 3)
    res = _sim(nodes, [pod], [device_sc("hdd-sc", "hdd")])
    assert len(res.unscheduled_pods) == 1


def test_sts_volume_claims_via_annotation():
    """StatefulSet volumeClaimTemplates flow through the pod annotation."""
    nodes = [storage_node("s0", vgs=[("pool", 100 * GI)])]
    sts = make_statefulset("db", replicas=2, cpu="1", memory="1Gi",
                           volume_claim_templates=[
                               {"metadata": {"name": "data"},
                                "spec": {"storageClassName": "open-local-lvm",
                                         "resources": {"requests": {"storage": "10Gi"}}}}
                           ])
    cluster = ResourceTypes(nodes=nodes, storage_classes=[lvm_sc()])
    rt = ResourceTypes(stateful_sets=[sts])
    res = simulate(cluster, [AppResource(name="db", resource=rt)])
    assert not res.unscheduled_pods
    st = NodeStorage.from_json(
        res.node_status[0].node["metadata"]["annotations"]["simon/node-local-storage"]
    )
    assert st.vgs[0].requested == 20 * GI


def test_reference_open_local_example():
    """The reference's open_local app (4-replica STS wanting yoda VGs + hdd device)
    against demo_1 nodes with yoda-pool VGs and /dev/vdd devices."""
    import os

    from open_simulator_tpu.utils.yamlio import load_cluster_from_directory, load_resources_from_directory

    base = "/root/reference/example"
    if not os.path.isdir(os.path.join(base, "application/open_local")):
        pytest.skip("reference examples not mounted")
    cluster = load_cluster_from_directory(os.path.join(base, "cluster/demo_1"))
    app = load_resources_from_directory(os.path.join(base, "application/open_local"))
    res = simulate(cluster, [AppResource(name="open_local", resource=app)])
    placed = [p for ns in res.node_status for p in ns.pods
              if "simon/pod-local-storage" in (p["metadata"].get("annotations") or {})]
    # each placed storage pod must have bumped some VG on its node
    assert placed
    for ns in res.node_status:
        if any(p in placed for p in ns.pods):
            st = NodeStorage.from_json(
                ns.node["metadata"]["annotations"]["simon/node-local-storage"]
            )
            assert any(vg.requested > 0 for vg in st.vgs)
