"""simonserve: resident what-if serving.

The contract under test (README "Serving", PARITY.md "Resident-vs-fresh"):

- **Resident-vs-fresh parity.** Every response served off the persistent
  device-resident cluster image — through delta ingest, copy-on-write drain
  overlays, and micro-batched dispatch — is bit-identical to probing the same
  request serially on a fresh Simulator built from scratch over the final
  cluster state (counts AND f64 utilization sums).
- **Micro-batching determinism.** Lane padding and union-batch padding never
  change a placement: each lane's per-request valid mask makes foreign rows
  provable no-ops.
- **Epoch safety.** A from-scratch image rebuild (generation bump) makes
  existing sessions stale — detected and re-encoded, never silently wrong.
- **Non-donation.** The shared image's device buffers survive every dispatch
  (the runtime half of the simonaudit image_leaf_aliased certificate).
"""

import json
import threading

import numpy as np
import pytest

from open_simulator_tpu.core.types import ResourceTypes
from open_simulator_tpu.serve import (
    ImageDonatedError,
    ResidentImage,
    StaleImageError,
    WhatIfService,
)
from open_simulator_tpu.server.http import ClusterSnapshot, Server

from fixtures import make_node, make_pod


def make_cluster(n_nodes=12, n_bound=6):
    nodes = [make_node(f"n-{i}", cpu="8", memory="16Gi") for i in range(n_nodes)]
    bound = [make_pod(f"bound-{i}", cpu="2", memory="2Gi",
                      node_name=f"n-{i % max(1, n_nodes // 3)}",
                      labels={"app": f"svc-{i % 2}"})
             for i in range(n_bound)]
    return nodes, bound


def whatif_pods(tag, n=4, cpu="1", memory="1Gi", anti_on=None):
    affinity = None
    if anti_on:
        affinity = {"podAntiAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [{
                "labelSelector": {"matchLabels": {"app": anti_on}},
                "topologyKey": "kubernetes.io/hostname",
            }]}}
    return [make_pod(f"wi-{tag}-{i}", cpu=cpu, memory=memory,
                     labels={"app": f"wi-{tag}"}, affinity=affinity)
            for i in range(n)]


def assert_same_response(resident: dict, fresh: dict) -> None:
    assert resident["scheduled"] == fresh["scheduled"], (resident, fresh)
    assert resident["total"] == fresh["total"]
    assert resident["unscheduled"] == fresh["unscheduled"]
    assert resident["utilization"] == fresh["utilization"], (
        resident["utilization"], fresh["utilization"])


# ----------------------------------------------------------- basic parity ----


def test_resident_matches_fresh_encode():
    nodes, bound = make_cluster()
    img = ResidentImage.try_build(nodes, pods=bound)
    assert img is not None
    req = whatif_pods("a", 5)
    assert_same_response(img.session(req).run(), img.fresh_probe(req))


def test_request_drain_overlay_parity():
    """Per-request drains overlay the shared image copy-on-write: the lane
    sees the cluster without the drained node AND without its pods —
    including their inter-pod-affinity counter contributions (the adjusted
    seed copy), which the anti-affinity request here reads."""
    nodes, bound = make_cluster(10, 8)
    img = ResidentImage.try_build(nodes, pods=bound)
    req = whatif_pods("anti", 6, anti_on="svc-0")
    for drains in ([], ["n-0"], ["n-0", "n-1"]):
        got = img.session(req, drains=drains).run()
        want = img.fresh_probe(req, drains=drains)
        assert_same_response(got, want)
    # the image itself is untouched by request overlays
    assert img.n_nodes == 10 and not img.drained


def test_overlarge_cluster_saturates_identically():
    nodes, _ = make_cluster(6, 0)
    img = ResidentImage.try_build(nodes)
    req = whatif_pods("big", 9, cpu="6", memory="12Gi")  # only 6 fit
    got = img.session(req).run()
    assert got["scheduled"] == 6 and got["unscheduled"] == 3
    assert_same_response(got, img.fresh_probe(req))


# ------------------------------------------------------------ delta ingest ----


def _trace_events(rng, nodes, live_counter):
    """One seeded event batch: pod churn + node drain + node add."""
    evs = []
    kind = rng.integers(0, 4)
    if kind == 0:  # pod adds onto random live nodes
        for j in range(int(rng.integers(1, 4))):
            i = int(rng.integers(0, len(nodes)))
            live_counter[0] += 1
            evs.append({"type": "pod_add", "pod": make_pod(
                f"churn-{live_counter[0]}", cpu="1", memory="1Gi",
                node_name=f"n-{i}", labels={"app": "churn"})})
    elif kind == 1:  # delete previously churned pods
        for j in range(int(rng.integers(1, 3))):
            if live_counter[0] > live_counter[1]:
                live_counter[1] += 1
                evs.append({"type": "pod_delete", "namespace": "default",
                            "name": f"churn-{live_counter[1]}"})
    elif kind == 2:  # drain a random node (it and its pods leave)
        evs.append({"type": "node_drain",
                    "name": f"n-{int(rng.integers(0, len(nodes)))}"})
    else:  # add a fresh node
        live_counter[2] += 1
        evs.append({"type": "node_add",
                    "node": make_node(f"added-{live_counter[2]}",
                                      cpu="16", memory="32Gi")})
    return evs


@pytest.mark.parametrize("seed", [7, 23, 101])
def test_delta_ingest_trace_matches_from_scratch(seed):
    """Property-style (ISSUE satellite): a seeded sequence of node add /
    node drain / pod churn event batches applied to the resident image must
    produce what-if answers bit-identical to (a) a fresh Simulator probe of
    the final cluster state and (b) a BRAND-NEW ResidentImage built from
    scratch over that final state."""
    rng = np.random.default_rng(seed)
    nodes, bound = make_cluster(8, 5)
    img = ResidentImage.try_build(nodes, pods=[dict(p) for p in bound])
    live_counter = [0, 0, 0]  # churn adds, churn deletes, node adds
    req = whatif_pods("trace", 5, anti_on="churn")
    for step in range(4):
        evs = _trace_events(rng, nodes, live_counter)
        img.apply_events(evs)
        got = img.session(req).run()
        assert_same_response(got, img.fresh_probe(req))
    # from-scratch image over the final state answers identically
    final_nodes = img.current_nodes()
    final_bound = img.cluster_pods()
    img2 = ResidentImage.try_build(final_nodes, pods=final_bound)
    assert img2 is not None
    assert_same_response(img.session(req).run(), img2.session(req).run())


def test_pod_churn_refreshes_seeds_without_restage():
    """Pod add/delete must move ZERO device table bytes: the staged tables
    are placed-independent, only the host-side seeds re-aggregate."""
    nodes, bound = make_cluster()
    img = ResidentImage.try_build(nodes, pods=bound)
    staged_before = img._tables
    out = img.apply_events([
        {"type": "pod_add", "pod": make_pod("c-1", cpu="1", memory="1Gi",
                                            node_name="n-2")},
        {"type": "pod_delete", "namespace": "default", "name": "bound-0"},
    ])
    assert out["applied"] == 2 and not out["restaged"]
    assert img._tables is staged_before  # same device buffers, untouched
    req = whatif_pods("churn", 4)
    assert_same_response(img.session(req).run(), img.fresh_probe(req))


def test_node_drain_moves_no_bytes_and_add_restages():
    nodes, bound = make_cluster()
    img = ResidentImage.try_build(nodes, pods=bound)
    staged = img._tables
    out = img.apply_events([{"type": "node_drain", "name": "n-3"}])
    assert out["applied"] == 1 and not out["restaged"]
    assert img._tables is staged and img.n_nodes == 11
    out = img.apply_events([
        {"type": "node_add", "node": make_node("n-new", cpu="4", memory="8Gi")}])
    assert out["restaged"] and img.n_nodes == 12
    req = whatif_pods("nodes", 6, cpu="3", memory="6Gi")
    assert_same_response(img.session(req).run(), img.fresh_probe(req))


def test_intra_batch_event_ordering():
    """Events inside ONE ingest batch must see each other: the natural
    watch-stream order [node_add X, pod_add onto X] commits the pod (the
    live mask extends mid-batch), and draining a just-added node sticks."""
    nodes, bound = make_cluster(6, 3)
    img = ResidentImage.try_build(nodes, pods=bound)
    out = img.apply_events([
        {"type": "node_add", "node": make_node("nx", cpu="16", memory="32Gi")},
        {"type": "pod_add", "pod": make_pod("on-nx", cpu="4", memory="4Gi",
                                            node_name="nx")},
    ])
    assert out["applied"] == 2 and out["skipped"] == 0
    req = whatif_pods("order", 4)
    assert_same_response(img.session(req).run(), img.fresh_probe(req))
    out = img.apply_events([
        {"type": "node_add", "node": make_node("ny", cpu="16", memory="32Gi")},
        {"type": "node_drain", "name": "ny"},
    ])
    assert out["applied"] == 2 and "ny" in img.drained
    assert_same_response(img.session(req).run(), img.fresh_probe(req))


def test_unexpressible_event_rebuilds_not_approximates():
    """A node-add the delta path cannot express (new resource axis) forces a
    from-scratch re-encode with a generation bump — never a wrong answer."""
    nodes, bound = make_cluster(8, 4)
    img = ResidentImage.try_build(nodes, pods=bound)
    gen = img.generation
    sess = img.session(whatif_pods("stale", 3))
    img.apply_events([{"type": "node_add", "node": make_node(
        "gpu-node", cpu="8", memory="16Gi",
        extra_resources={"example.com/widget": "4"})}])
    assert img.generation == gen + 1
    with pytest.raises(StaleImageError):
        sess.run()
    sess.ensure_current()  # the service's transparent path
    assert_same_response(sess.run(), img.fresh_probe(sess.pods))


# ----------------------------------------------------------- micro-batching ----


def test_micro_batch_demux_and_parity():
    """Concurrent heterogeneous requests coalesce onto one fan-out dispatch;
    every demuxed response equals the serial fresh-encode probe."""
    nodes, bound = make_cluster(10, 6)
    img = ResidentImage.try_build(nodes, pods=bound)
    svc = WhatIfService(img, window_ms=20.0, fanout=8)
    shapes = [whatif_pods("m0", 3), whatif_pods("m1", 5, cpu="2"),
              whatif_pods("m2", 2, anti_on="svc-1"),
              whatif_pods("m3", 4, memory="2Gi")]
    results = [None] * len(shapes)

    def go(i):
        results[i] = svc.submit(shapes[i])

    threads = [threading.Thread(target=go, args=(i,))
               for i in range(len(shapes))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(r is not None for r in results)
    assert max(r["lanes"] for r in results) > 1  # actually coalesced
    for i, r in enumerate(results):
        assert r["path"] == "batched"
        assert_same_response(r, img.fresh_probe(shapes[i]))
    svc.stop()


def test_ineligible_requests_route_fresh():
    nodes, bound = make_cluster(8, 3)
    img = ResidentImage.try_build(nodes, pods=bound)
    svc = WhatIfService(img, window_ms=0.0)
    spread = make_pod("spread-1", cpu="1", memory="1Gi",
                      labels={"app": "sp"})
    spread["spec"]["topologySpreadConstraints"] = [{
        "maxSkew": 1, "topologyKey": "kubernetes.io/hostname",
        "whenUnsatisfiable": "DoNotSchedule",
        "labelSelector": {"matchLabels": {"app": "sp"}}}]
    r = svc.submit([spread])
    assert r["path"] == "fresh" and r["total"] == 1
    prebound = make_pod("pre-1", cpu="1", memory="1Gi", node_name="n-0")
    assert svc.submit([prebound])["path"] == "fresh"
    svc.stop()


# ------------------------------------------------------------- non-donation ----


def test_image_buffers_survive_dispatches():
    nodes, bound = make_cluster()
    img = ResidentImage.try_build(nodes, pods=bound)
    for _ in range(3):
        img.session(whatif_pods("alive", 3)).run()
    img.assert_image_alive()  # also runs inside every dispatch


def test_assert_image_alive_catches_donation():
    """Negative control: a (forbidden) donating jit over the image tables
    consumes the buffers; the runtime assertion must catch it."""
    import jax

    nodes, _ = make_cluster(8, 0)
    img = ResidentImage.try_build(nodes)
    eat = jax.jit(lambda a: a + 1.0, donate_argnums=(0,))
    eat(img._tables.alloc)  # output aliases the donated buffer -> deleted
    with pytest.raises(ImageDonatedError):
        img.assert_image_alive()


def test_image_alias_census_flags_donating_jit():
    """Compile-time half (simonaudit): args_info-based census counts donated
    leaves inside the tables range — 0 for every registered kernel (asserted
    by the goldens), nonzero for a deliberately donating jit."""
    import jax
    import jax.numpy as jnp

    from open_simulator_tpu.analysis.hlo import image_alias_count

    args = (jnp.zeros((4, 4)), jnp.zeros((4,)))
    good = jax.jit(lambda t, c: (t * 1.0, c + 1.0), donate_argnums=(1,))
    bad = jax.jit(lambda t, c: (t * 1.0, c + 1.0), donate_argnums=(0, 1))
    assert image_alias_count(good.lower(*args), 1) == 0
    assert image_alias_count(bad.lower(*args), 1) == 1


def test_serve_goldens_pin_zero_image_alias():
    from pathlib import Path

    doc = json.loads((Path(__file__).parent / "golden" / "audit" /
                      "serve_whatif_fanout.json").read_text())
    assert doc["certs"], "serve kernel has no golden certificates"
    for key, cert in doc["certs"].items():
        assert cert["donation"]["image_leaf_aliased"] == 0, key
        assert cert["donation"]["held"], key


# ------------------------------------------------------------ HTTP serving ----


def _serve_server(n_nodes=10, n_bound=4, window_ms=20.0, fanout=8):
    nodes, bound = make_cluster(n_nodes, n_bound)
    rt = ResourceTypes(nodes=nodes, pods=bound)
    snap = ClusterSnapshot(rt, [], [], [])
    return Server(snapshot_fn=lambda: snap, whatif=True,
                  whatif_window_ms=window_ms, whatif_fanout=fanout)


def test_http_whatif_smoke_16_concurrent():
    """The CI smoke (ISSUE satellite): spin the server in-process, fire 16
    concurrent /v1/whatif requests through the REAL HTTP stack, assert every
    response demuxes to its own request and matches the serial fresh-encode
    probe; then ingest a drain delta and confirm the image moved."""
    import http.client

    server = _serve_server()
    httpd = server.build_httpd(port=0)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        results = [None] * 16

        def call(i):
            # generous timeout: the first requests pay the cold XLA compile
            # of the fan-out shape bucket
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=300)
            body = json.dumps({"pods": [
                {"metadata": {"name": f"h{i}-{j}", "namespace": "default",
                              "labels": {"app": f"h{i}"}},
                 "spec": {"containers": [{"name": "c", "image": "nginx",
                                          "resources": {"requests": {
                                              "cpu": "1",
                                              "memory": "1Gi"}}}]}}
                for j in range(1 + i % 3)]})
            try:
                conn.request("POST", "/v1/whatif", body,
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                results[i] = (resp.status, json.loads(resp.read()))
            except Exception as e:  # surfaced by the assertion below
                results[i] = (None, {"error": repr(e)})
            finally:
                conn.close()

        threads = [threading.Thread(target=call, args=(i,)) for i in range(16)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        svc = server.whatif_service()
        for i, (status, body) in enumerate(results):
            assert status == 200, body
            assert body["total"] == 1 + i % 3  # demuxed to the right request
            assert body["scheduled"] == body["total"]
            want = svc.image.fresh_probe([make_pod(
                f"h{i}-{j}", cpu="1", memory="1Gi", labels={"app": f"h{i}"})
                for j in range(1 + i % 3)])
            assert_same_response(body, want)
        assert any(body["lanes"] > 1 for _, body in results)  # coalesced

        # delta ingest over HTTP: drain one node, the image epoch moves
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        conn.request("POST", "/v1/ingest", json.dumps(
            {"events": [{"type": "node_drain", "name": "n-9"}]}),
            {"Content-Type": "application/json"})
        resp = conn.getresponse()
        out = json.loads(resp.read())
        assert resp.status == 200 and out["applied"] == 1
        conn.request("GET", "/v1/serve/stats", None, {})
        stats = json.loads(conn.getresponse().read())
        assert stats["nodes"] == 9 and stats["drained"] == ["n-9"]
        conn.close()
    finally:
        httpd.shutdown()


def test_whatif_off_by_default_404():
    nodes, _ = make_cluster(4, 0)
    snap = ClusterSnapshot(ResourceTypes(nodes=nodes), [], [], [])
    server = Server(snapshot_fn=lambda: snap, whatif=False)
    code, body = server.handle_whatif({"pods": [make_pod("x")]})
    assert code == 404 and "error" in body


def test_whatif_declined_cluster_501():
    # node-advertised images decline the resident image (ImageLocality)
    nodes, _ = make_cluster(4, 0)
    nodes[0]["status"]["images"] = [{"names": ["nginx:1.25"],
                                    "sizeBytes": 1 << 20}]
    snap = ClusterSnapshot(ResourceTypes(nodes=nodes), [], [], [])
    server = Server(snapshot_fn=lambda: snap, whatif=True)
    code, body = server.handle_whatif({"pods": [make_pod("x")]})
    assert code == 501


def test_whatif_empty_request_400():
    server = _serve_server(4, 0)
    code, body = server.handle_whatif({})
    assert code == 400


def test_grpc_whatif_rpc_roundtrip():
    from open_simulator_tpu.server.grpcbridge import (
        GrpcBridge,
        decode_simulate_response,
        encode_simulate_request,
    )

    bridge = GrpcBridge(server=_serve_server(6, 2))
    req = json.dumps({"pods": [make_pod("g-1", cpu="1", memory="1Gi")]}).encode()
    code, payload = decode_simulate_response(
        bridge._whatif(encode_simulate_request(req), None))
    assert code == 200
    body = json.loads(payload)
    assert body["total"] == 1 and body["path"] in ("batched", "fresh")


def test_cli_serve_parser():
    from open_simulator_tpu.cli.main import build_parser

    args = build_parser().parse_args(
        ["serve", "--synthetic-nodes", "8", "--window-ms", "1",
         "--fanout", "4", "--port", "0"])
    assert args.command == "serve" and args.synthetic_nodes == 8
    assert args.window_ms == 1.0 and args.fanout == 4


# ------------------------------------------------------- simonha over HTTP ----


def _ha_server(state_dir, n_nodes=8, n_bound=3, **kw):
    nodes, bound = make_cluster(n_nodes, n_bound)
    rt = ResourceTypes(nodes=nodes, pods=bound)
    snap = ClusterSnapshot(rt, [], [], [])
    return Server(snapshot_fn=lambda: snap, whatif=True,
                  whatif_window_ms=0.0, state_dir=str(state_dir), **kw)


def test_http_state_dir_stamps_epoch_and_staleness(tmp_path):
    server = _ha_server(tmp_path)
    code, body = server.handle_ingest(
        {"events": [{"type": "node_drain", "name": "n-7"}]})
    assert code == 200 and body["applied"] == 1
    code, body = server.handle_whatif({"pods": [make_pod("s-1", cpu="1",
                                                         memory="1Gi")]})
    assert code == 200
    assert body["staleness_s"] == 0.0  # healthy: stamped, not stale
    assert body["epoch"] == server._ha.image.epoch
    server.drain(deadline=0.1)


def test_http_restart_from_state_dir_bit_identical(tmp_path):
    """The serve-level restart oracle: kill server A (drain = the graceful
    half; test_ha covers SIGKILL semantics on the raw files), boot server B
    over the same --state-dir, require the same epoch and the same answers."""
    req = {"pods": [make_pod(f"rs-{j}", cpu="1", memory="1Gi")
                    for j in range(3)]}
    a = _ha_server(tmp_path, checkpoint_every=2)
    for i in range(3):
        code, _ = a.handle_ingest({"events": [{
            "type": "pod_add", "pod": make_pod(
                f"live-{i}", cpu="1", memory="1Gi",
                node_name=f"n-{i}")}]})
        assert code == 200
    code, want = a.handle_whatif(dict(req))
    assert code == 200
    epoch = a._ha.image.epoch
    a.drain(deadline=0.1)

    b = _ha_server(tmp_path, checkpoint_every=2)
    code, got = b.handle_whatif(dict(req))
    assert code == 200
    assert b._ha.image.epoch == epoch
    assert b._ha.skipped + b._ha.replayed >= 1  # restored, not rebuilt
    assert_same_response(got, want)
    assert got["epoch"] == want["epoch"]
    b.drain(deadline=0.1)


def test_http_healthz_flips_503_past_staleness_ceiling(tmp_path):
    import http.client

    server = _ha_server(tmp_path, staleness_ceiling_s=0.0)
    server.whatif_service()  # boot the HA state
    httpd = server.build_httpd(port=0)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("GET", "/healthz")
        assert conn.getresponse().read() and True
        server._ha._enter_degraded("ingest")
        server._ha._last_ok -= 1.0  # degraded for a solid second
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 503 and body["reason"] == "ingest"
        assert body["staleness_s"] > 0
        # recovery via successful ingest: healthz flips back
        server._ha.ingest([{"type": "node_drain", "name": "n-6"}])
        conn.request("GET", "/healthz")
        assert conn.getresponse().status == 200
        conn.close()
    finally:
        httpd.shutdown()
        server.drain(deadline=0.1)


def test_http_ingest_payload_caps(tmp_path):
    """Satellite: the unbounded-memory hazard is closed BEFORE the body is
    read — oversized payload 413, in-flight byte budget 429, both
    structured and counted."""
    import http.client

    server = _ha_server(tmp_path, ingest_max_bytes=1024)
    httpd = server.build_httpd(port=0)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        big = json.dumps({"events": [{"type": "node_drain",
                                      "name": "x" * 2048}]})
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("POST", "/v1/ingest", big,
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 413 and body["code"] == 413
        conn.close()  # the server dropped the connection with the body unread

        # in-flight budget: pre-load the accounting to the 4x cap
        server._ingest_bytes = 4 * 1024
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("POST", "/v1/ingest", json.dumps({"events": []}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 429
        assert resp.getheader("Retry-After") is not None
        body = json.loads(resp.read())
        assert body["code"] == 429
        conn.close()
        server._ingest_bytes = 0
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("POST", "/v1/ingest", json.dumps(
            {"events": [{"type": "node_drain", "name": "n-5"}]}),
            {"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200  # the budget released: normal service
        assert resp.getheader("X-Simon-Epoch") == server._ha.image.epoch
        conn.close()
    finally:
        httpd.shutdown()
        server.drain(deadline=0.1)


def test_http_whatif_shed_maps_to_429_with_retry_after(tmp_path):
    import http.client

    server = _ha_server(tmp_path, max_queue=8, tenant_rate=0.001)
    httpd = server.build_httpd(port=0)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=300)
        body = json.dumps({"pods": [make_pod("sh-1", cpu="1",
                                             memory="1Gi")]})
        codes = []
        for _ in range(10):  # burst past the 8-token burst at ~0 rps
            conn.request("POST", "/v1/whatif", body,
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            out = json.loads(resp.read())
            codes.append(resp.status)
            if resp.status == 429:
                assert out["reason"] == "rate_limit"
                assert out["retry_after_s"] > 0
                assert resp.getheader("Retry-After") is not None
        assert codes.count(200) == 8 and codes.count(429) == 2
        conn.close()
    finally:
        httpd.shutdown()
        server.drain(deadline=0.1)
