"""Chart renderer: Go-template subset + chart loading/values/install-order."""

import os
import textwrap

import pytest

from open_simulator_tpu.chart.gotmpl import TemplateError, render_template
from open_simulator_tpu.chart.render import ChartError, load_chart, process_chart, render_chart


# ------------------------------------------------------------ template engine -------

V = {"Values": {"name": "web", "replicas": 3, "enabled": True,
                "labels": {"team": "infra", "tier": "backend"},
                "ports": [80, 443],
                "resources": {"requests": {"cpu": "100m"}}},
     "Release": {"Name": "rel", "Namespace": "default"},
     "Chart": {"Name": "demo", "Version": "0.1.0"}}


def test_basic_substitution():
    assert render_template("name: {{ .Values.name }}", V) == "name: web"
    assert render_template("{{ .Release.Name }}-{{ .Chart.Name }}", V) == "rel-demo"


def test_missing_path_is_empty():
    assert render_template("x{{ .Values.absent.deep }}y", V) == "xy"


def test_pipelines_and_functions():
    assert render_template('{{ .Values.name | upper | quote }}', V) == '"WEB"'
    assert render_template('{{ default "fallback" .Values.absent }}', V) == "fallback"
    assert render_template('{{ printf "%s-%d" .Values.name 7 }}', V) == "web-7"
    assert render_template('{{ .Values.name | trunc 2 }}', V) == "we"


def test_if_else():
    t = "{{ if .Values.enabled }}on{{ else }}off{{ end }}"
    assert render_template(t, V) == "on"
    t2 = "{{ if eq .Values.name \"nope\" }}a{{ else if eq .Values.name \"web\" }}b{{ else }}c{{ end }}"
    assert render_template(t2, V) == "b"


def test_range_list_and_dict():
    t = "{{ range .Values.ports }}p{{ . }} {{ end }}"
    assert render_template(t, V) == "p80 p443 "
    t2 = "{{ range $k, $v := .Values.labels }}{{ $k }}={{ $v }};{{ end }}"
    assert render_template(t2, V) == "team=infra;tier=backend;"


def test_with_and_toyaml_nindent():
    t = "resources:{{ with .Values.resources }}{{ toYaml . | nindent 2 }}{{ end }}"
    out = render_template(t, V)
    assert "requests:" in out and "\n  requests:" in out


def test_whitespace_trimming():
    t = "a\n{{- if .Values.enabled }}\nb\n{{- end }}"
    assert render_template(t, V) == "a\nb"


def test_variables():
    t = '{{ $n := .Values.name }}{{ $n }}-{{ $n }}'
    assert render_template(t, V) == "web-web"


def test_define_include():
    t = ('{{ define "lbl" }}app: {{ .Values.name }}{{ end }}'
         '{{ include "lbl" . }}')
    assert render_template(t, V) == "app: web"


def test_unknown_function_raises():
    with pytest.raises(TemplateError):
        render_template("{{ .Values.name | definitelynotafunc }}", V)


def test_variable_block_scoping():
    # range loop vars and := declarations die at `end` (Go text/template scoping)
    t = ('{{ $x := "outer" }}'
         '{{ range $i, $p := .Values.ports }}{{ $x := "inner" }}{{ $x }}{{ end }}'
         '|{{ $x }}')
    assert render_template(t, V) == "innerinner|outer"
    # `=` assignment inside a block writes through to the outer declaration
    t2 = ('{{ $x := "a" }}{{ if .Values.enabled }}{{ $x = "b" }}{{ end }}{{ $x }}')
    assert render_template(t2, V) == "b"
    # sibling with-blocks reusing a name don't leak into each other
    t3 = ('{{ with .Values.labels }}{{ $v := .team }}{{ $v }}{{ end }}'
          '{{ with .Values.labels }}{{ $v }}{{ end }}')
    assert render_template(t3, V) == "infra"


def test_include_gets_fresh_variable_scope():
    # variables set at the call site are invisible inside the invoked template,
    # and $ inside the template is its dot argument
    t = ('{{ define "t" }}{{ $v }}:{{ $.team }}{{ end }}'
         '{{ $v := "caller" }}{{ include "t" .Values.labels }}')
    assert render_template(t, V) == ":infra"


def test_regex_replace_all_capture_groups():
    t = '{{ regexReplaceAll "(a)(b)" "ab-ab" "${2}${1}" }}'
    assert render_template(t, V) == "ba-ba"
    # Go reads `$1x` as group name "1x" (longest run) → empty when absent
    t2 = '{{ regexReplaceAll "a(b)" "zab" "$1x" }}'
    assert render_template(t2, V) == "z"
    t2b = '{{ regexReplaceAll "a(b)" "zab" "${1}x" }}'
    assert render_template(t2b, V) == "zbx"
    t3 = '{{ regexReplaceAll "b" "abc" "$$" }}'
    assert render_template(t3, V) == "a$c"
    # unclosed ${ keeps the literal text, as Go's regexp.Expand does
    t4 = '{{ regexReplaceAll "a" "Xa" "${foo" }}'
    assert render_template(t4, V) == "X${foo"


def test_with_if_variable_guard():
    # `with $x := pipeline` declares the var, sets dot to the value (Go semantics)
    t = '{{ with $x := .Values.labels }}Y{{ $x.team }}:{{ .tier }}{{ end }}'
    assert render_template(t, V) == "Yinfra:backend"
    t2 = '{{ if $n := .Values.replicas }}n={{ $n }}{{ end }}'
    assert render_template(t2, V) == "n=3"
    # falsy guard takes the else branch; dot unchanged there
    t3 = '{{ with $x := .Values.absent }}Y{{ else }}N{{ end }}'
    assert render_template(t3, V) == "N"


# ----------------------------------------------------------------- chart dirs -------


@pytest.fixture()
def demo_chart(tmp_path):
    root = tmp_path / "demo"
    (root / "templates").mkdir(parents=True)
    (root / "Chart.yaml").write_text("name: demo\nversion: 0.1.0\napiVersion: v2\n")
    (root / "values.yaml").write_text(textwrap.dedent("""\
        replicas: 2
        image: nginx:1.25
        service:
          enabled: true
    """))
    (root / "templates" / "_helpers.tpl").write_text(
        '{{ define "demo.fullname" }}{{ .Release.Name }}-demo{{ end }}'
    )
    (root / "templates" / "deploy.yaml").write_text(textwrap.dedent("""\
        apiVersion: apps/v1
        kind: Deployment
        metadata:
          name: {{ include "demo.fullname" . }}
        spec:
          replicas: {{ .Values.replicas }}
          selector:
            matchLabels:
              app: demo
          template:
            metadata:
              labels:
                app: demo
            spec:
              containers:
                - name: app
                  image: {{ .Values.image }}
    """))
    (root / "templates" / "svc.yaml").write_text(textwrap.dedent("""\
        {{- if .Values.service.enabled }}
        apiVersion: v1
        kind: Service
        metadata:
          name: {{ include "demo.fullname" . }}
        spec:
          selector:
            app: demo
        {{- end }}
    """))
    (root / "templates" / "NOTES.txt").write_text("Thanks for installing {{ .Chart.Name }}")
    return str(root)


def test_load_and_render_chart(demo_chart):
    chart = load_chart(demo_chart)
    assert chart.name == "demo"
    docs = render_chart(chart, release_name="myapp")
    # NOTES.txt dropped; Service sorts before Deployment (install order)
    import yaml as _y
    kinds = [(_y.safe_load(d) or {}).get("kind") for d in docs]
    assert kinds == ["Service", "Deployment"]


def test_process_chart_objects(demo_chart):
    objs = process_chart("myapp", demo_chart)
    dep = [o for o in objs if o["kind"] == "Deployment"][0]
    assert dep["metadata"]["name"] == "myapp-demo"
    assert dep["spec"]["replicas"] == 2


def test_values_override_disables_service(demo_chart):
    chart = load_chart(demo_chart)
    docs = render_chart(chart, overrides={"service": {"enabled": False}})
    import yaml as _y
    kinds = [(_y.safe_load(d) or {}).get("kind") for d in docs]
    assert kinds == ["Deployment"]


def test_library_chart_rejected(tmp_path):
    root = tmp_path / "lib"
    root.mkdir()
    (root / "Chart.yaml").write_text("name: lib\nversion: 0.1.0\ntype: library\n")
    with pytest.raises(ChartError):
        render_chart(load_chart(str(root)))


def test_assign_requires_declaration():
    """text/template semantics: `$x = v` without `$x :=` is an error; after a
    declaration, `=` assigns to the nearest enclosing scope."""
    import pytest

    from open_simulator_tpu.chart.gotmpl import TemplateError, render_template

    ok = render_template('{{ $x := 1 }}{{ $x = 2 }}{{ $x }}', {})
    assert ok.strip() == "2"
    with pytest.raises(TemplateError):
        render_template('{{ $y = 2 }}', {})
