"""GPU-share plugin: allocator parity, batched filter, reserve/annotations."""

import json

import numpy as np
import pytest

from open_simulator_tpu import simulate
from open_simulator_tpu.core.types import AppResource, ResourceTypes
from open_simulator_tpu.plugins.gpushare import (
    allocate_gpu_ids,
    gpu_id_str_to_list,
    pod_gpu_count,
    pod_gpu_mem,
)

from fixtures import make_node, make_pod

GI = 1 << 30


def gpu_node(name, count=2, total_mem=32 * GI, cpu="64", mem="256Gi", model="V100"):
    return make_node(
        name, cpu=cpu, memory=mem,
        labels={"alibabacloud.com/gpu-card-model": model},
        extra_resources={
            "alibabacloud.com/gpu-count": str(count),
            "alibabacloud.com/gpu-mem": str(total_mem),
        },
    )


def gpu_pod(name, mem_gi=1, count=1, cpu="1", memory="1Gi"):
    pod = make_pod(name, cpu=cpu, memory=memory)
    pod["metadata"]["annotations"] = {
        "alibabacloud.com/gpu-mem": f"{mem_gi}Gi",
        "alibabacloud.com/gpu-count": str(count),
    }
    return pod


# ------------------------------------------------------------------- allocator ------


def test_allocator_single_tightest_fit():
    # dev0 idle 10, dev1 idle 4, dev2 idle 6 -> request 3 lands on dev1 (tightest)
    ids, found = allocate_gpu_ids([10, 10, 10], [0, 6, 4], 3, 1)
    assert found and ids == "1"


def test_allocator_single_lowest_index_on_tie():
    ids, found = allocate_gpu_ids([10, 10], [2, 2], 4, 1)
    assert found and ids == "0"


def test_allocator_multi_packs_one_device():
    # 3 units of 2 onto dev0 (idle 10): two-pointer packs all on dev0
    ids, found = allocate_gpu_ids([10, 10], [0, 0], 2, 3)
    assert found and ids == "0-0-0"


def test_allocator_multi_spills_in_order():
    # dev0 idle 3 (1 unit of 2), dev1 idle 10 (rest)
    ids, found = allocate_gpu_ids([10, 10], [7, 0], 2, 3)
    assert found and ids == "0-1-1"


def test_allocator_infeasible():
    ids, found = allocate_gpu_ids([4, 4], [3, 3], 2, 3)
    assert not found
    assert allocate_gpu_ids([4], [0], 5, 1) == ("", False)
    assert allocate_gpu_ids([4], [0], 0, 1) == ("", False)
    assert allocate_gpu_ids([4], [0], 2, 0) == ("", False)


def test_allocator_preassigned_id_wins():
    ids, found = allocate_gpu_ids([10], [0], 2, 1, preassigned="7")
    assert found and ids == "7"


# ------------------------------------------------------------------ annotations -----


def test_pod_annotation_parsing():
    p = gpu_pod("p", mem_gi=2, count=3)
    assert pod_gpu_mem(p) == 2 * GI
    assert pod_gpu_count(p) == 3
    assert gpu_id_str_to_list("2-3-4") == [2, 3, 4]
    assert gpu_id_str_to_list("") == []
    assert pod_gpu_mem(make_pod("x")) == 0


# -------------------------------------------------------------------- simulation ----


def _sim(nodes, pods):
    cluster = ResourceTypes(nodes=nodes)
    rt = ResourceTypes(pods=pods)
    return simulate(cluster, [AppResource(name="gpu", resource=rt)])


def test_gpu_pods_scheduled_and_annotated():
    nodes = [gpu_node("g0", count=2, total_mem=4 * GI)]
    pods = [gpu_pod(f"p{i}", mem_gi=1, count=1) for i in range(4)]
    res = _sim(nodes, pods)
    assert not res.unscheduled_pods
    placed = res.node_status[0].pods
    assert len(placed) == 4
    for p in placed:
        assert p["metadata"]["annotations"]["alibabacloud.com/gpu-index"] in ("0", "1")
    # 2 devices × 2Gi each, 4 × 1Gi pods → 2 per device
    info = json.loads(
        res.node_status[0].node["metadata"]["annotations"]["simon/node-gpu-share"]
    )
    assert info["GpuCount"] == 2
    assert info["GpuAllocatable"] == 0  # both devices full
    assert info["NumPods"] == 4
    assert res.node_status[0].node["status"]["allocatable"]["alibabacloud.com/gpu-count"] == "0"


def test_gpu_memory_exhaustion_unschedulable():
    nodes = [gpu_node("g0", count=1, total_mem=2 * GI)]
    pods = [gpu_pod(f"p{i}", mem_gi=1, count=1) for i in range(3)]
    res = _sim(nodes, pods)
    assert len(res.unscheduled_pods) == 1
    assert "Node:g0" in res.unscheduled_pods[0].reason


def test_gpu_count_annotation_required():
    nodes = [gpu_node("g0")]
    pod = gpu_pod("p0", mem_gi=1)
    del pod["metadata"]["annotations"]["alibabacloud.com/gpu-count"]
    res = _sim(nodes, [pod])
    # GetGpuCountFromPodAnnotation -> 0 -> AllocateGpuId not found -> unschedulable
    assert len(res.unscheduled_pods) == 1


def test_non_gpu_node_filtered_for_gpu_pod():
    nodes = [make_node("cpu-only"), gpu_node("g0", count=1, total_mem=4 * GI)]
    res = _sim(nodes, [gpu_pod("p0", mem_gi=1)])
    assert not res.unscheduled_pods
    by_name = {ns.node["metadata"]["name"]: ns.pods for ns in res.node_status}
    assert len(by_name["g0"]) == 1 and not by_name["cpu-only"]


def test_multi_gpu_pod_allocation():
    nodes = [gpu_node("g0", count=4, total_mem=16 * GI)]  # 4 devs × 4Gi
    res = _sim(nodes, [gpu_pod("p0", mem_gi=3, count=3)])
    assert not res.unscheduled_pods
    idx = res.node_status[0].pods[0]["metadata"]["annotations"]["alibabacloud.com/gpu-index"]
    assert idx == "0-1-2"  # one 3Gi unit fits per 4Gi device


def test_preassigned_gpu_index_respected():
    """A pod with an existing gpu-index bypasses device-fit (reference early-return,
    gpunodeinfo.go:247-253) and charges the annotated device — even past capacity."""
    nodes = [gpu_node("g0", count=2, total_mem=4 * GI)]  # 2 devs × 2Gi
    pinned = gpu_pod("pinned", mem_gi=2, count=1)
    pinned["metadata"]["annotations"]["alibabacloud.com/gpu-index"] = "1"
    filler = gpu_pod("filler", mem_gi=2, count=1)  # must land on dev0 (dev1 full)
    res = _sim(nodes, [pinned, filler])
    assert not res.unscheduled_pods
    by_name = {p["metadata"]["name"]: p for p in res.node_status[0].pods}
    assert by_name["pinned"]["metadata"]["annotations"]["alibabacloud.com/gpu-index"] == "1"
    assert by_name["filler"]["metadata"]["annotations"]["alibabacloud.com/gpu-index"] == "0"


def test_reference_gpushare_example():
    """Drive the reference's gpushare example cluster + pods end to end."""
    import os

    from open_simulator_tpu.utils.yamlio import load_resources_from_directory

    base = "/root/reference/example"
    if not os.path.isdir(os.path.join(base, "cluster/gpushare")):
        pytest.skip("reference examples not mounted")
    cluster = load_resources_from_directory(os.path.join(base, "cluster/gpushare"))
    apps = load_resources_from_directory(os.path.join(base, "application/gpushare"))
    res = simulate(cluster, [AppResource(name="gpushare", resource=apps)])
    placed = [p for ns in res.node_status for p in ns.pods]
    # raw gpu pods 00-02 carry annotations and must be placed with device ids
    gpu_placed = [p for p in placed if pod_gpu_mem(p) > 0]
    assert gpu_placed, "expected annotated gpu pods to be placed"
    for p in gpu_placed:
        assert p["metadata"]["annotations"].get("alibabacloud.com/gpu-index")


def test_distilled_gpushare_example_pinned_outcome():
    """The in-repo distilled gpushare scenario (examples/, always present —
    unlike the mounted-reference variant above) with the full outcome pinned:
    annotation parsing, node-total + per-device filter, tightest-fit
    single-GPU and in-order multi-GPU allocation, gpu-index writeback, and
    the node ledger's per-device usage."""
    import json
    import os

    from open_simulator_tpu.core import constants as C
    from open_simulator_tpu.core.types import AppResource
    from open_simulator_tpu.utils.objutil import annotations_of, name_of
    from open_simulator_tpu.utils.yamlio import load_cluster_from_directory, \
        load_resources_from_directory

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cluster = load_cluster_from_directory(
        os.path.join(repo, "examples/cluster/gpushare"))
    app = AppResource(name="pai_gpu", resource=load_resources_from_directory(
        os.path.join(repo, "examples/application/gpushare")))
    result = simulate(cluster, [app])
    assert result.unscheduled_pods == []

    placed = {name_of(p): name_of(ns.node)
              for ns in result.node_status for p in ns.pods}
    assert len(placed) == 9  # 3 raw pods + 6 ReplicaSet replicas
    # exactly the two annotated GPU pods receive gpu-index writeback; the
    # tightest-fit allocator packs both onto pai-node-00 — the SMALLER GPU
    # node (2 devices vs pai-node-01's 4) — device 0 for the 1Gi pod,
    # spanning 0-1 for the 2x10Gi pod
    gpu_idx = {}
    for ns in result.node_status:
        for p in ns.pods:
            anno = annotations_of(p).get(C.AnnoGpuIndex)
            if anno:
                gpu_idx[name_of(p)] = (name_of(ns.node), anno)
    assert gpu_idx == {"gpu-pod-00": ("pai-node-00", "0"),
                       "gpu-pod-02": ("pai-node-00", "0-1")}
    counts = {name_of(ns.node): len(ns.pods)
              for ns in result.node_status if ns.pods}
    assert counts == {"pai-node-00": 4, "pai-node-01": 5}
    # the ledger records actual per-device usage, not just static capacity
    node0 = next(ns.node for ns in result.node_status
                 if name_of(ns.node) == "pai-node-00")
    ledger = json.loads(annotations_of(node0)[C.AnnoNodeGpuShare])
    assert ledger["GpuCount"] == 2
    briefs = {str(k): v for k, v in (ledger.get("DevsBrief") or {}).items()}
    used = {d: briefs[d].get("GpuUsedMemory") for d in ("0", "1")}
    assert all(used.values()), f"per-device usage missing: {ledger}"
