"""Double-encode parity + behavior of the columnar host path
(simulator/store.py): a Simulator fed a PodStore/NodeStore must encode
BIT-IDENTICAL BatchTables and produce bit-identical placements to the same
workload as plain dicts — including the workloads that route OFF the bulk
path (gpushare, local storage, pre-bound pods, armed preemption), where the
store transparently materializes. The lazy read-back boundary, bulk-commit
rollback, streaming chunk equivalence, and the serve image staged from a
store are covered here too (ISSUE 15 acceptance)."""

from __future__ import annotations

import copy
import dataclasses
import json
import os

import numpy as np
import pytest

from open_simulator_tpu.resilience import faults
from open_simulator_tpu.simulator.encode import scheduling_signature
from open_simulator_tpu.simulator.engine import Simulator
from open_simulator_tpu.simulator.store import (
    EncodedRows,
    NodeStore,
    PodStore,
)
from open_simulator_tpu.utils.synth import (
    synth_cluster,
    synth_cluster_store,
    synth_node,
    synth_pod,
)


def assert_tables_equal(a, b):
    """BatchTables bit-identity: every field, dtype and shape included."""
    for f in dataclasses.fields(a):
        va, vb = getattr(a, f.name), getattr(b, f.name)
        if isinstance(va, np.ndarray):
            assert va.dtype == vb.dtype, f.name
            assert va.shape == vb.shape, f.name
            assert np.array_equal(va, vb), f.name
        else:
            assert va == vb, f.name


def census_of(sim):
    out = {}
    for i, pods in enumerate(sim.pods_on_node):
        for p in pods:
            key = (i, scheduling_signature(p))
            out[key] = out.get(key, 0) + 1
    return out


def fail_names(failed):
    return sorted(u.pod["metadata"]["name"] for u in failed)


def pod_template(**kw):
    t = synth_pod(0, **kw)
    t["metadata"].pop("name", None)
    return t


def run_both(nodes, pods, store_nodes, store_pods, use_waves=True):
    """Schedule the dict form and the store form; assert encode + placement
    bit-identity; return the two simulators."""
    simd = Simulator(nodes, use_mesh=False)
    simd.use_waves = use_waves
    sims = Simulator(store_nodes, use_mesh=False)
    sims.use_waves = use_waves
    btd = simd.encode_batch(copy.deepcopy(pods))
    bts = sims.encode_batch(store_pods[:])
    assert_tables_equal(btd, bts)
    simd2 = Simulator(nodes, use_mesh=False)
    simd2.use_waves = use_waves
    sims2 = Simulator(store_nodes, use_mesh=False)
    sims2.use_waves = use_waves
    failed_d = simd2.schedule_pods(copy.deepcopy(pods))
    failed_s = sims2.schedule_pods(store_pods)
    assert census_of(simd2) == census_of(sims2)
    assert fail_names(failed_d) == fail_names(failed_s)
    return simd2, sims2


# ------------------------------------------------------ double-encode parity --


def test_parity_plain():
    nodes, pods = synth_cluster(64, 600)
    ns, ps = synth_cluster_store(64, 600)
    run_both(nodes, pods, ns, ps)


def test_parity_hard_predicates():
    # zones + taints + tolerations + self anti-affinity + zone spread:
    # wave, affinity-wave, spread, and serial segments all exercised
    nodes, pods = synth_cluster(48, 400, hard_predicates=True)
    ns, ps = synth_cluster_store(48, 400, hard_predicates=True)
    run_both(nodes, pods, ns, ps)


def test_parity_hard_serial_oracle():
    nodes, pods = synth_cluster(32, 200, hard_predicates=True)
    ns, ps = synth_cluster_store(32, 200, hard_predicates=True)
    run_both(nodes, pods, ns, ps, use_waves=False)


def gpu_cluster(n_nodes, n_pods):
    nodes = []
    for i in range(n_nodes):
        n = synth_node(i)
        for sect in ("capacity", "allocatable"):
            n["status"][sect]["alibabacloud.com/gpu-count"] = "4"
            n["status"][sect]["alibabacloud.com/gpu-mem"] = str(4 * 16 << 30)
        nodes.append(n)
    pods = []
    for i in range(n_pods):
        p = synth_pod(i)
        p["metadata"].setdefault("annotations", {})[
            "alibabacloud.com/gpu-mem"] = str(4 << 30)
        p["metadata"]["annotations"]["alibabacloud.com/gpu-count"] = "1"
        pods.append(p)
    return nodes, pods


def test_parity_gpushare():
    # gpu state forces the store off every fast path (NodeStore materializes
    # at ctor, commits go per-pod through reserve()) — parity must still be
    # exact, annotations included
    nodes, pods = gpu_cluster(16, 80)
    node_tmpl = copy.deepcopy(nodes[0])
    node_tmpl["metadata"] = {}
    ns = NodeStore().add_block(node_tmpl, 16, name_fmt="node-{0:05d}",
                               index_labels=("node-index",))
    pod_tmpl = copy.deepcopy(pods[0])
    pod_tmpl["metadata"].pop("name")
    ps = PodStore().add_block(pod_tmpl, 80, name_fmt="pod-{0:06d}")
    simd, sims = run_both(nodes, pods, ns, ps)
    # reserve() wrote per-pod gpu-index annotations on materialized dicts
    pd = simd.pods_on_node[0][0]
    pss = sims.pods_on_node[0][0]
    assert (pd["metadata"]["annotations"].get("alibabacloud.com/gpu-index")
            == pss["metadata"]["annotations"].get(
                "alibabacloud.com/gpu-index"))


def test_parity_local_storage():
    from open_simulator_tpu.utils.storage import VG, NodeStorage

    st = NodeStorage(vgs=[VG("vg0", 200 << 30)], devices=[])
    sc = {"apiVersion": "storage.k8s.io/v1", "kind": "StorageClass",
          "metadata": {"name": "open-local-lvm"},
          "provisioner": "local.csi.aliyun.com",
          "parameters": {"volumeType": "LVM"}}
    nodes = []
    for i in range(8):
        n = synth_node(i)
        n["metadata"].setdefault("annotations", {})[
            "simon/node-local-storage"] = st.to_json()
        nodes.append(n)
    pods = []
    for i in range(24):
        p = synth_pod(i)
        p["metadata"].setdefault("annotations", {})[
            "simon/pod-local-storage"] = json.dumps({"volumes": [
                {"size": str(1 << 30), "kind": "LVM",
                 "scName": "open-local-lvm"}]})
        pods.append(p)
    node_tmpl = copy.deepcopy(nodes[0])
    node_tmpl["metadata"].pop("name")
    node_tmpl["metadata"].pop("labels")
    ns = NodeStore().add_block(node_tmpl, 8, name_fmt="node-{0:05d}",
                               index_labels=("node-index",))
    pod_tmpl = copy.deepcopy(pods[0])
    pod_tmpl["metadata"].pop("name")
    ps = PodStore().add_block(pod_tmpl, 24, name_fmt="pod-{0:06d}")

    from open_simulator_tpu.core.types import ResourceTypes

    simd = Simulator(nodes, use_mesh=False)
    simd.register_cluster_objects(ResourceTypes(storage_classes=[sc]))
    sims = Simulator(ns, use_mesh=False)
    sims.register_cluster_objects(ResourceTypes(storage_classes=[sc]))
    assert sims.local_host.enabled  # store fell back to materialized dicts
    failed_d = simd.schedule_pods(copy.deepcopy(pods))
    failed_s = sims.schedule_pods(ps)
    assert census_of(simd) == census_of(sims)
    assert fail_names(failed_d) == fail_names(failed_s)


def test_parity_pre_bound():
    nodes, _ = synth_cluster(16, 0)
    pods = [synth_pod(i) for i in range(40)]
    bound = synth_pod(99)
    bound["metadata"]["name"] = "bound-one"
    bound["spec"]["nodeName"] = "node-00003"
    homeless = synth_pod(98)
    homeless["metadata"]["name"] = "homeless-one"
    homeless["spec"]["nodeName"] = "node-nowhere"
    dict_pods = pods[:20] + [bound] + pods[20:] + [homeless]

    ps = PodStore()
    ps.add_block(pod_template(), 20, name_fmt="pod-{0:06d}")
    ps.add_pod(copy.deepcopy(bound))
    tail = pod_template()
    ps.add_block(tail, 20, name_fmt="pod-{0:06d}", name_start=20)
    ps.add_pod(copy.deepcopy(homeless))
    # names must line up with the dict form for the fail/census comparison
    simd = Simulator(nodes, use_mesh=False)
    sims = Simulator(copy.deepcopy(nodes), use_mesh=False)
    failed_d = simd.schedule_pods(copy.deepcopy(dict_pods))
    failed_s = sims.schedule_pods(ps)
    assert census_of(simd) == census_of(sims)
    assert fail_names(failed_d) == fail_names(failed_s)
    assert len(simd.homeless) == len(sims.homeless) == 1


def test_parity_preemption_mixed_priorities():
    # mixed priorities arm the PostFilter: the store falls back to the
    # per-pod commit path (bulk is gated off) and must match exactly
    nodes = [synth_node(i, cpu_milli=1000, pods=8) for i in range(4)]
    low = pod_template(cpu_milli=400)
    low["spec"]["priority"] = 0
    high = pod_template(cpu_milli=400)
    high["spec"]["priority"] = 100
    dict_pods = []
    for i in range(8):
        p = copy.deepcopy(low)
        p["metadata"]["name"] = f"low-{i:02d}"
        dict_pods.append(p)
    for i in range(4):
        p = copy.deepcopy(high)
        p["metadata"]["name"] = f"high-{i:02d}"
        dict_pods.append(p)
    ps = PodStore()
    ps.add_block(copy.deepcopy(low), 8, name_fmt="low-{0:02d}", name_start=0)
    ps.add_block(copy.deepcopy(high), 4, name_fmt="high-{0:02d}",
                 name_start=0)
    simd = Simulator(nodes, use_mesh=False)
    sims = Simulator(copy.deepcopy(nodes), use_mesh=False)
    failed_d = simd.schedule_pods(copy.deepcopy(dict_pods))
    failed_s = sims.schedule_pods(ps)
    assert census_of(simd) == census_of(sims)
    assert fail_names(failed_d) == fail_names(failed_s)
    assert len(simd.preempted) == len(sims.preempted)


def test_parity_preemption_after_bulk_commit():
    # call 1: uniform priority → BULK commit; call 2: higher priority pods
    # arrive, arm preemption, and evict bulk-committed victims — the
    # _sig_rec fallback must resolve their signature/seq from the columns
    nodes = [synth_node(i, cpu_milli=1000, pods=8) for i in range(4)]
    low = pod_template(cpu_milli=400)
    low["spec"]["priority"] = 0
    high = pod_template(cpu_milli=400)
    high["spec"]["priority"] = 100
    dict_low = []
    for i in range(8):
        p = copy.deepcopy(low)
        p["metadata"]["name"] = f"low-{i:02d}"
        dict_low.append(p)
    dict_high = []
    for i in range(4):
        p = copy.deepcopy(high)
        p["metadata"]["name"] = f"high-{i:02d}"
        dict_high.append(p)
    ps_low = PodStore().add_block(copy.deepcopy(low), 8,
                                  name_fmt="low-{0:02d}", name_start=0)
    ps_high = PodStore().add_block(copy.deepcopy(high), 4,
                                   name_fmt="high-{0:02d}", name_start=0)
    simd = Simulator(nodes, use_mesh=False)
    sims = Simulator(copy.deepcopy(nodes), use_mesh=False)
    simd.schedule_pods(copy.deepcopy(dict_low))
    sims.schedule_pods(ps_low)
    failed_d = simd.schedule_pods(copy.deepcopy(dict_high))
    failed_s = sims.schedule_pods(ps_high)
    assert census_of(simd) == census_of(sims)
    assert fail_names(failed_d) == fail_names(failed_s)
    assert len(simd.preempted) == len(sims.preempted)
    if sims.preempted:
        victims = sorted(p["pod"]["metadata"]["name"]
                         for p in sims.preempted)
        victims_d = sorted(p["pod"]["metadata"]["name"]
                           for p in simd.preempted)
        assert victims == victims_d


# ---------------------------------------------------------- lazy read-back --


def test_lazy_readback_boundary():
    ns, ps = synth_cluster_store(32, 300)
    sim = Simulator(ns, use_mesh=False)
    sim.schedule_pods(ps)
    assert len(ps.base.cache) == 0  # nothing read back yet
    assert sim.pods_on_node.total() == 300  # counting never materializes
    assert len(ps.base.cache) == 0
    pod = sim.pods_on_node[0][0]  # flattening one node materializes it only
    assert pod["spec"]["nodeName"] == "node-00000"
    assert pod["status"] == {"phase": "Running"}
    assert 0 < len(ps.base.cache) <= len(sim.pods_on_node[0])
    # identity is stable across reads
    assert sim.pods_on_node[0][0] is pod


def test_materialized_before_commit_is_patched():
    ns, ps = synth_cluster_store(16, 50)
    early = ps[3]  # materialized BEFORE scheduling
    assert "nodeName" not in early.get("spec", {})
    sim = Simulator(ns, use_mesh=False)
    sim.schedule_pods(ps)
    # the bulk commit patched the already-materialized dict in place
    assert early["spec"].get("nodeName", "").startswith("node-")
    assert early.get("status") == {"phase": "Running"}


# -------------------------------------------------------- rollback / faults --


def test_bulk_commit_rollback_on_fault():
    ns, ps = synth_cluster_store(16, 120)
    early = ps[5]
    sim = Simulator(ns, use_mesh=False)
    faults.install_plan(faults.FaultPlan.parse("site=commit,attempt=100"))
    try:
        with pytest.raises(Exception):
            sim.schedule_pods(ps)
    finally:
        faults.clear_plan()
    # full rollback: no placements, columns reset, cached dict clean
    assert sim.pods_on_node.total() == 0
    assert not sim.placed or all(
        not pg.node_counts for pg in sim.placed.values())
    assert int((ps.node_rows() >= 0).sum()) == 0
    assert "nodeName" not in early.get("spec", {})
    assert "status" not in early
    # and the SAME store schedules cleanly afterwards
    sim2 = Simulator(ns, use_mesh=False)
    sim2.schedule_pods(ps)
    assert sim2.pods_on_node.total() == 120


def test_bulk_fault_arrivals_replay_equal():
    # maybe_fail_bulk must fire the same arrival a per-event loop would
    plan_a = faults.FaultPlan.parse("site=commit,attempt=7")
    for k in (3, 4):
        try:
            plan_a.on_arrivals("commit", k)
        except Exception:
            break
    plan_b = faults.FaultPlan.parse("site=commit,attempt=7")
    fired_at = None
    for i in range(1, 8):
        try:
            plan_b.on_arrival("commit")
        except Exception:
            fired_at = i
            break
    assert plan_a.trace == plan_b.trace
    assert fired_at == 7


# ----------------------------------------------------------------- streaming --


def test_streaming_chunks_bit_identical():
    nodes, pods = synth_cluster(48, 900, hard_predicates=True)
    base = Simulator(nodes, use_mesh=False)
    base_failed = base.schedule_pods(copy.deepcopy(pods))
    os.environ["OPEN_SIMULATOR_STREAM_PODS"] = "128"
    try:
        streamed = Simulator(nodes, use_mesh=False)
        assert streamed._stream_chunk == 128
        st_failed = streamed.schedule_pods(copy.deepcopy(pods))
    finally:
        os.environ.pop("OPEN_SIMULATOR_STREAM_PODS", None)
    assert census_of(base) == census_of(streamed)
    assert fail_names(base_failed) == fail_names(st_failed)
    from open_simulator_tpu.obs import REGISTRY

    assert REGISTRY.values().get("simon_stream_chunks_total", 0) > 0


def test_streaming_store_chunks_bit_identical():
    ns, ps = synth_cluster_store(32, 700)
    nodes, pods = synth_cluster(32, 700)
    base = Simulator(nodes, use_mesh=False)
    base.schedule_pods(pods)
    os.environ["OPEN_SIMULATOR_STREAM_PODS"] = "96"
    try:
        streamed = Simulator(ns, use_mesh=False)
        # store batches stream at a coarser floor — force it down for the
        # test by driving the chunk directly
        streamed._stream_chunk = 96
        failed = streamed._schedule_run_streaming(ps, 96)
    finally:
        os.environ.pop("OPEN_SIMULATOR_STREAM_PODS", None)
    assert not failed
    assert census_of(base) == census_of(streamed)


# ------------------------------------------------------------------- probing --


def test_probe_store_parity():
    nodes, pods = synth_cluster(24, 300)
    ns, ps = synth_cluster_store(24, 300)
    simd = Simulator(nodes, use_mesh=False)
    sims = Simulator(ns, use_mesh=False)
    assert simd.probe_pods(pods) == sims.probe_pods(ps)
    # probes never commit: the store's columns stay untouched
    assert int((ps.node_rows() >= 0).sum()) == 0


# ------------------------------------------------------------------- serving --


def test_serve_image_staged_from_store():
    from open_simulator_tpu.serve.image import ResidentImage

    ns, _ = synth_cluster_store(32, 0)
    nodes, _ = synth_cluster(32, 0)
    img_s = ResidentImage.try_build(ns)
    img_d = ResidentImage.try_build(nodes)
    assert img_s is not None and img_d is not None
    request = [synth_pod(i, cpu_milli=500) for i in range(6)]
    rs = img_s.session(copy.deepcopy(request)).run()
    rd = img_d.session(copy.deepcopy(request)).run()
    # staged-from-store == staged-from-dicts == resident contract fields
    for k in ("scheduled", "total", "unscheduled", "utilization"):
        assert rs[k] == rd[k], (k, rs, rd)
    assert rs["scheduled"] == 6 and rs["path"] != "fresh"


def test_serve_session_rides_store_batch():
    from open_simulator_tpu.serve.image import ResidentImage

    ns, _ = synth_cluster_store(16, 0)
    img = ResidentImage.try_build(ns)
    assert img is not None
    req = PodStore().add_block(pod_template(cpu_milli=300), 5,
                               name_fmt="req-{0:02d}", name_start=0)
    session = img.session(req)
    assert isinstance(session.batch, EncodedRows)
    assert img.eligible(session.batch, req) is None
    out = session.run()
    assert out["scheduled"] == 5 and out["path"] != "fresh"


# ------------------------------------------------------------- store basics --


def test_store_views_share_commit_state():
    ns, ps = synth_cluster_store(8, 40)
    view = ps[10:30]
    assert len(view) == 20
    assert view[0]["metadata"]["name"] == "pod-000010"
    dup = copy.deepcopy(ps)
    sim = Simulator(ns, use_mesh=False)
    sim.schedule_pods(ps)
    assert int((ps.node_rows() >= 0).sum()) == 40
    # the deepcopy took its own columns: still uncommitted
    assert int((dup.node_rows() >= 0).sum()) == 0


def test_encoded_rows_sequence_protocol():
    rows = EncodedRows(np.array([3, 3, 5], np.int32),
                       np.array([-1, -1, 2], np.int32))
    assert len(rows) == 3
    assert list(rows) == [(3, -1), (3, -1), (5, 2)]
    assert rows[0] == (3, -1)
    assert rows[2] == (5, 2)
    sub = rows[1:]
    assert isinstance(sub, EncodedRows) and len(sub) == 2


# ------------------------------------------------------------ review fixes --


def test_bulk_fault_window_preserves_later_specs():
    # two specs inside one bulk window: the counter must stop AT the firing
    # arrival (the serial loop died there), so a failover replay's window
    # still contains the second spec
    plan = faults.FaultPlan.parse(
        "site=commit,attempt=5;site=commit,attempt=8")
    with pytest.raises(Exception):
        plan.on_arrivals("commit", 10)   # fires @5, counter stops at 5
    assert plan.arrivals["commit"] == 5
    with pytest.raises(Exception):
        plan.on_arrivals("commit", 10)   # replay window (5, 15] fires @8
    assert [t[:2] for t in plan.trace] == [("commit", 5), ("commit", 8)]


def test_bulk_rollback_restores_prior_status():
    # an explicit pod with a pre-existing status rides the store, gets bulk
    # committed, and a rollback must restore the ORIGINAL status object —
    # the per-pod commit log's caller-owned-dict contract
    nodes, _ = synth_cluster(8, 0)
    ns = NodeStore()
    t = synth_node(0)
    t["metadata"] = {}
    ns.add_block(t, 8, name_fmt="node-{0:05d}", index_labels=("node-index",))
    prior_status = {"phase": "Pending"}
    special = synth_pod(7)
    special["status"] = prior_status
    ps = PodStore()
    ps.add_block(pod_template(), 10, name_fmt="pod-{0:06d}")
    ps.add_pod(special)
    sim = Simulator(ns, use_mesh=False)
    faults.install_plan(faults.FaultPlan.parse("site=fetch,attempt=1"))
    try:
        with pytest.raises(Exception):
            sim.schedule_pods(ps)
    finally:
        faults.clear_plan()
    assert special.get("status") is prior_status
    assert "nodeName" not in special.get("spec", {})
    # and a clean re-run commits it with Running like any other pod
    sim2 = Simulator(ns, use_mesh=False)
    assert not sim2.schedule_pods(ps)
    assert special["status"] == {"phase": "Running"}


def test_pods_on_node_snapshot_prunes_read_registrations():
    ns, ps = synth_cluster_store(64, 100)
    sim = Simulator(ns, use_mesh=False)
    sim.schedule_pods(ps)
    for _ in sim.pods_on_node:   # read-side full iteration registers empties
        pass
    assert len(sim.pods_on_node._lists) == 64
    snap = sim.pods_on_node.snapshot()
    # snapshot pruned the empty registrations back to touched nodes only
    assert len(sim.pods_on_node._lists) == len(snap["lists"])
    assert len(snap["lists"]) < 64 or sim.pods_on_node.total() == 100


def test_nodestore_capacity_only_resources():
    # a template advertising an extended resource only under status.capacity
    # must intern the axis exactly like the dict path (node_allocatable's
    # capacity fallback)
    t = {"apiVersion": "v1", "kind": "Node", "metadata": {}, "spec": {},
         "status": {"capacity": {"cpu": "4000m", "memory": str(8 << 30),
                                 "pods": "32", "example.com/widget": "2"}}}
    ns = NodeStore().add_block(t, 4, name_fmt="node-{0:05d}")
    sim = Simulator(ns, use_mesh=False)
    assert "example.com/widget" in sim.axis.names
    p = pod_template()
    p["spec"]["containers"][0]["resources"]["requests"][
        "example.com/widget"] = "1"
    failed = sim.schedule_pods(PodStore().add_block(p, 8,
                                                    name_fmt="pod-{0:06d}"))
    assert not failed  # 2 widgets x 4 nodes covers 8 one-widget pods
