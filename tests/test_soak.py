"""Opt-in extended differential soak: OPEN_SIMULATOR_SOAK=1 pytest tests/test_soak.py

Wide randomized waves-vs-serial sweeps beyond the CI fuzz — the harness that
validated the wave/epoch kernels during development, preserved so future
kernel work can re-run it. Each seed builds a random cluster/workload and
asserts per-(node, scheduling-signature) census and failure equality between
the batched paths and the pure serial scan.

The fault-soak half (same opt-in) drives random seeded FaultPlans through
random workloads and asserts zero state divergence: a faulted-and-rolled-back
simulator must afterwards produce placements bit-identical to a simulator
that never saw the fault.
"""

import copy
import os
import random

import pytest

from open_simulator_tpu.simulator.engine import Simulator
from open_simulator_tpu.simulator.encode import scheduling_signature

from fixtures import make_node, make_pod

pytestmark = pytest.mark.skipif(
    not os.environ.get("OPEN_SIMULATOR_SOAK"),
    reason="extended soak; set OPEN_SIMULATOR_SOAK=1",
)


def _census(sim):
    out = {}
    for i, nps in enumerate(sim.pods_on_node):
        for p in nps:
            k = (i, scheduling_signature(p))
            out[k] = out.get(k, 0) + 1
    return out


def _run(nodes, pods, waves):
    sim = Simulator(copy.deepcopy(nodes))
    sim.use_waves = waves
    failed = sim.schedule_pods(copy.deepcopy(pods))
    return _census(sim), len(failed)


@pytest.mark.parametrize("seed", range(200, 230))
def test_soak_zone_spread(seed):
    rng = random.Random(seed)
    nz = rng.choice([2, 3, 5, 8])
    nodes = []
    for i in range(rng.randint(4, 20)):
        labels = {}
        if rng.random() < 0.85:
            labels["topology.kubernetes.io/zone"] = f"z{i % nz}"
        nodes.append(make_node(f"n{i}", cpu=f"{rng.randint(1500, 6000)}m",
                               memory=str(rng.randint(3, 10) << 30),
                               pods=str(rng.randint(4, 30)), labels=labels))
    pods = []
    for b in range(rng.randint(1, 3)):
        app = f"sp{b}"
        skew = rng.choice([1, 1, 2, 3])
        for _ in range(rng.randint(8, 60)):
            p = make_pod(f"{app}-{len(pods)}", cpu=f"{rng.randint(80, 600)}m",
                         memory=str(rng.randint(64, 768) << 20),
                         labels={"app": app})
            p["spec"]["topologySpreadConstraints"] = [{
                "maxSkew": skew, "topologyKey": "topology.kubernetes.io/zone",
                "whenUnsatisfiable": "DoNotSchedule",
                "labelSelector": {"matchLabels": {"app": app}},
            }]
            pods.append(p)
    assert _run(nodes, pods, True) == _run(nodes, pods, False)


@pytest.mark.parametrize("seed", range(600, 630))
def test_fault_soak_no_state_divergence(seed):
    """Random seeded FaultPlans against random workloads: when the plan
    fires, the rollback must leave the simulator able to reproduce the
    fault-free placements bit-for-bit (and the caller's pods unmutated)."""
    from open_simulator_tpu.resilience import FaultPlan, installed

    rng = random.Random(seed)
    nodes = [make_node(f"n{i}", cpu=f"{rng.randint(1000, 6000)}m",
                       memory=str(rng.randint(2, 10) << 30),
                       pods=str(rng.randint(3, 20)))
             for i in range(rng.randint(3, 12))]
    pods = []
    for b in range(rng.randint(1, 3)):
        app = f"fs{b}"
        n_prio = rng.choice([0, 0, 100])  # some seeds arm preemption
        for _ in range(rng.randint(5, 40)):
            p = make_pod(f"{app}-{len(pods)}", cpu=f"{rng.randint(100, 900)}m",
                         memory=str(rng.randint(64, 900) << 20),
                         labels={"app": app})
            if n_prio and rng.random() < 0.5:
                p["spec"]["priority"] = n_prio
            pods.append(p)

    baseline, base_failed = _run(nodes, pods, True)

    plan = FaultPlan.seeded(
        seed, n_faults=rng.randint(1, 3), max_attempt=rng.randint(1, 6),
        sites=("encode", "to_device", "dispatch", "fetch", "commit",
               "preempt_evict"))
    sim = Simulator(copy.deepcopy(nodes))
    p2 = copy.deepcopy(pods)
    pre_pods = copy.deepcopy(p2)
    fired = False
    try:
        with installed(plan):
            sim.schedule_pods(p2)
    except Exception:
        fired = True
        assert plan.trace, "raised without a recorded injection?"
        assert _census(sim) == {}, "rollback left census residue"
        assert p2 == pre_pods, "rollback left pod-dict residue"
    # with or without a fault, the same simulator must converge to the
    # fault-free baseline exactly
    if fired:
        failed = sim.schedule_pods(p2)
        assert (_census(sim), len(failed)) == (baseline, base_failed)
    else:
        assert _census(sim) == baseline  # plan never fired: plain parity


@pytest.mark.parametrize("seed", range(700, 730))
def test_guard_containment_soak(seed):
    """simonguard soak: a random CONTAINED fault (watchdog wedge, device OOM
    at either stage) injected mid-run must reconverge bit-for-bit with the
    fault-free baseline — no exception, no divergence, and the containment
    visible on the guard's event trace whenever the plan fired."""
    from open_simulator_tpu.resilience import FaultPlan, installed
    from open_simulator_tpu.resilience import guard

    rng = random.Random(seed)
    nodes = [make_node(f"n{i}", cpu=f"{rng.randint(1000, 6000)}m",
                       memory=str(rng.randint(2, 10) << 30),
                       pods=str(rng.randint(3, 20)))
             for i in range(rng.randint(3, 12))]
    pods = []
    for b in range(rng.randint(1, 3)):
        app = f"gd{b}"
        for _ in range(rng.randint(5, 40)):
            pods.append(make_pod(f"{app}-{len(pods)}",
                                 cpu=f"{rng.randint(100, 900)}m",
                                 memory=str(rng.randint(64, 900) << 20),
                                 labels={"app": app}))

    baseline = _run(nodes, pods, True)

    guard.reset_for_tests()
    try:
        # one fault: a single contained failure per run (a second injected
        # wedge DURING the failover replay is a double-fault scenario the
        # bounded-retry path handles separately)
        plan = FaultPlan.seeded(
            seed, n_faults=1, max_attempt=rng.randint(1, 4),
            sites=("watchdog_wedge", "oom_dispatch", "oom_to_device"))
        sim = Simulator(copy.deepcopy(nodes))
        with installed(plan):
            failed = sim.schedule_pods(copy.deepcopy(pods))
        assert (_census(sim), len(failed)) == baseline
        if plan.trace:
            assert guard.events(), "containment fired but left no event trace"
    finally:
        guard.reset_for_tests()


@pytest.mark.parametrize("seed", range(400, 430))
def test_soak_epoch_wave_forced(seed, monkeypatch):
    # force the epoch wave even at low domain cardinality: the routing is a
    # performance choice, so the math must stay exact everywhere
    monkeypatch.setenv("OPEN_SIMULATOR_SPREAD_WAVE_MIN_DOMAINS", "1")
    rng = random.Random(seed)
    topo = rng.choice(["kubernetes.io/hostname", "topology.kubernetes.io/zone"])
    nz = rng.choice([2, 4, 7])
    nodes = []
    for i in range(rng.randint(64, 120)):
        labels = {}
        if rng.random() < 0.9:
            labels["topology.kubernetes.io/zone"] = f"z{i % nz}"
        nodes.append(make_node(f"n{i}", cpu=f"{rng.randint(1000, 4000)}m",
                               memory=str(rng.randint(2, 8) << 30),
                               pods=str(rng.randint(2, 12)), labels=labels))
    pods = []
    for b in range(rng.randint(1, 3)):
        app = f"hp{b}"
        skew = rng.choice([1, 1, 2, 4])
        for _ in range(rng.randint(10, 80)):
            p = make_pod(f"{app}-{len(pods)}", cpu=f"{rng.randint(50, 400)}m",
                         memory=str(rng.randint(32, 512) << 20),
                         labels={"app": app})
            p["spec"]["topologySpreadConstraints"] = [{
                "maxSkew": skew, "topologyKey": topo,
                "whenUnsatisfiable": "DoNotSchedule",
                "labelSelector": {"matchLabels": {"app": app}}}]
            pods.append(p)
    assert _run(nodes, pods, True) == _run(nodes, pods, False)
